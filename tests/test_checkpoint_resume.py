"""Preemption-aware checkpoint / auto-resume (VERDICT r2 next #5;
reference: fleet collective save/load_checkpoint,
incubate/fleet/collective/__init__.py:155-341)."""
import os
import re
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.dist

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import checkpoint as ckpt
from paddle_tpu.fluid import framework

_RUNNER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "ckpt_runner.py")


def _build_mlp(seed=5):
    x = fluid.layers.data(name="x", shape=[6], dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    h = fluid.layers.fc(input=x, size=8, act="relu")
    logits = fluid.layers.fc(input=h, size=3)
    loss = fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(logits, label))
    fluid.optimizer.AdamOptimizer(learning_rate=0.1).minimize(loss)
    return loss


def test_save_load_roundtrip_and_retention(tmp_path, rng):
    root = str(tmp_path / "ckpts")
    loss = _build_mlp()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    feed = {"x": rng.rand(8, 6).astype("float32"),
            "label": rng.randint(0, 3, (8, 1)).astype("int64")}

    losses = []
    for step in range(5):
        out = exe.run(feed=feed, fetch_list=[loss])
        losses.append(float(np.asarray(out[0]).reshape(-1)[0]))
        ckpt.save_checkpoint(exe, root,
                             ckpt.TrainStatus(epoch_no=0, step_no=step),
                             checkpoint_num=2)

    # retention: only the newest 2 numbered dirs remain
    nums = sorted(int(d.split(".")[1]) for d in os.listdir(root))
    assert nums == [3, 4]
    assert ckpt.get_last_checkpoint_no(root) == 4

    # corrupt-latest protection: a stray tmp dir is ignored
    os.makedirs(os.path.join(root, "__paddle_tpu_checkpoint__.9.tmp"))
    assert ckpt.get_last_checkpoint_no(root) == 4

    # mutate params, then restore: the next step must reproduce step 5's
    # loss trajectory
    out_drift = exe.run(feed=feed, fetch_list=[loss])
    status = ckpt.load_checkpoint(exe, root)
    assert status.step_no == 4 and status.epoch_no == 0
    out = exe.run(feed=feed, fetch_list=[loss])
    drift = float(np.asarray(out_drift[0]).reshape(-1)[0])
    restored = float(np.asarray(out[0]).reshape(-1)[0])
    assert restored == pytest.approx(drift, rel=1e-5)  # same params again


def test_load_checkpoint_falls_back_past_corrupt_latest(tmp_path, rng):
    """Crash safety: when the newest numbered dir is unreadable (disk
    fault / partial payload), load_checkpoint restores the next-newest
    intact checkpoint instead of dying, and raises only when NO dir is
    intact."""
    root = str(tmp_path / "ckpts")
    loss = _build_mlp()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    feed = {"x": rng.rand(8, 6).astype("float32"),
            "label": rng.randint(0, 3, (8, 1)).astype("int64")}
    for step in range(3):
        exe.run(feed=feed, fetch_list=[loss])
        ckpt.save_checkpoint(exe, root,
                             ckpt.TrainStatus(epoch_no=0, step_no=step),
                             checkpoint_num=3)

    latest = ckpt.latest_checkpoint_dir(root)
    with open(os.path.join(latest, "persistables.pkl"), "wb") as f:
        f.write(b"\x00truncated")
    status = ckpt.load_checkpoint(exe, root)
    assert status.step_no == 1  # newest INTACT checkpoint

    for d in os.listdir(root):
        with open(os.path.join(root, d, "persistables.pkl"), "wb") as f:
            f.write(b"\x00truncated")
    with pytest.raises(RuntimeError, match="no intact checkpoint"):
        ckpt.load_checkpoint(exe, root)


def test_load_checkpoint_empty_dir(tmp_path):
    _build_mlp()
    exe = fluid.Executor(fluid.CPUPlace())
    assert ckpt.load_checkpoint(exe, str(tmp_path / "nope")) is None
    with pytest.raises(RuntimeError, match="no checkpoint"):
        ckpt.load_checkpoint(exe, str(tmp_path / "nope"),
                             ignore_empty=False)


def _run_runner(ckpt_dir, kill_after=0):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    if kill_after:
        env["KILL_AFTER_STEP"] = str(kill_after)
    proc = subprocess.run(
        [sys.executable, _RUNNER, ckpt_dir], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        timeout=420)
    steps = {}
    for m in re.finditer(r"step (\d+): \[([-\d.e]+)\]", proc.stdout):
        steps[int(m.group(1))] = float(m.group(2))
    return proc.returncode, steps, proc.stdout


def test_kill_and_resume_matches_uninterrupted(tmp_path):
    """The VERDICT done-criterion: train, hard-kill mid-run (simulated
    preemption), restart with the same command; the resumed run's
    per-step losses must match the uninterrupted run's."""
    base_rc, base_steps, base_out = _run_runner(str(tmp_path / "a"))
    assert base_rc == 0 and len(base_steps) == 8, base_out

    dir_b = str(tmp_path / "b")
    rc1, steps1, out1 = _run_runner(dir_b, kill_after=4)
    assert rc1 == 9  # preempted
    assert ckpt.get_last_checkpoint_no(dir_b) >= 0  # something published

    rc2, steps2, out2 = _run_runner(dir_b)
    assert rc2 == 0, out2
    assert steps2, "resumed run executed no steps"
    # the resumed run must pick up AFTER the published checkpoint, not
    # from scratch
    assert min(steps2) > 1
    for step, loss_v in steps2.items():
        assert loss_v == pytest.approx(base_steps[step], rel=1e-4), (
            step, loss_v, base_steps[step], out2)


def test_async_checkpointer_snapshot_consistency(tmp_path, rng):
    """save_async snapshots the scope at CALL time (ref-grab of
    immutable jax arrays); training that continues while the worker
    writes must not leak into the checkpoint, and close() flushes."""
    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu.core.scope import Scope
    from paddle_tpu.core import scope as scope_mod
    from paddle_tpu.fluid import framework
    from paddle_tpu.fluid.checkpoint import (AsyncCheckpointer,
                                             TrainStatus,
                                             load_checkpoint)

    main, startup = framework.Program(), framework.Program()
    main.random_seed = startup.random_seed = 37
    with framework.program_guard(main, startup):
        with framework.unique_name_guard():
            x = fluid.layers.data("x", shape=[4], dtype="float32")
            y = fluid.layers.data("y", shape=[1], dtype="float32")
            pred = fluid.layers.fc(x, size=1,
                                   param_attr=fluid.ParamAttr(name="w"))
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(pred, y))
            fluid.optimizer.SGDOptimizer(0.1).minimize(loss)

            scope = Scope()
            exe = fluid.Executor(fluid.CPUPlace())
            with scope_mod.scope_guard(scope):
                exe.run(startup, scope=scope)
                xs = rng.rand(8, 4).astype("float32")
                ys = rng.rand(8, 1).astype("float32")
                ck = AsyncCheckpointer(str(tmp_path), main_program=main,
                                       scope=scope)
                for _ in range(3):
                    exe.run(main, feed={"x": xs, "y": ys},
                            fetch_list=[loss], scope=scope)
                w_at_save = np.asarray(scope.find_var("w")).copy()
                ck.save_async(TrainStatus(epoch_no=1, step_no=3))
                # keep training while the writer works
                for _ in range(3):
                    exe.run(main, feed={"x": xs, "y": ys},
                            fetch_list=[loss], scope=scope)
                w_final = np.asarray(scope.find_var("w")).copy()
                ck.close()
                assert not np.allclose(w_at_save, w_final)

            # restore into a FRESH scope: values == save-time snapshot
            scope2 = Scope()
            with scope_mod.scope_guard(scope2):
                exe2 = fluid.Executor(fluid.CPUPlace())
                exe2.run(startup, scope=scope2)
                status = load_checkpoint(exe2, str(tmp_path),
                                         main_program=main,
                                         scope=scope2)
                assert status is not None and status.epoch_no == 1
                np.testing.assert_allclose(
                    np.asarray(scope2.find_var("w")), w_at_save,
                    rtol=1e-6)
