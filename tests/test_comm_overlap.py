"""Bucketed, backward-ordered gradient collectives
(FLAGS_tpu_comm_bucket_mb) — bucket planning, parity vs the
single-buffer (cap=0) lowering across bucket-size extremes, the
sharded gradient-merge path, the optimized-HLO overlap audit, the
per-bucket census/donation attribution, and the launch supervisor's
PADDLE_CKPT_AGREE default.

References: Kumar et al., arXiv:1909.09756 (overlapping gradient
summation with backprop at MLPerf scale); Wang et al., arXiv:2011.03641
(hiding inter-core traffic behind compute). Machinery:
paddle_tpu/parallel/sharded_update.py (plan_buckets,
bucket_reduce_scatter), fluid/lowering.py (collective_overlap_audit,
_run_gradient_merge), fluid/backward.py (grad_topo).
"""
import os

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework
from paddle_tpu.utils.flags import get_flag, set_flags

O = fluid.optimizer


@pytest.fixture(autouse=True)
def _restore_flags():
    old = {k: get_flag(k) for k in ("FLAGS_tpu_sharded_weight_update",
                                    "FLAGS_tpu_comm_bucket_mb")}
    yield
    set_flags(old)


def _fresh():
    from paddle_tpu.core import scope as scope_mod

    framework.switch_main_program(framework.Program())
    framework.switch_startup_program(framework.Program())
    scope_mod._global_scope = scope_mod.Scope()


def _batch(width=32):
    r = np.random.RandomState(0)
    return (r.rand(64, width).astype("float32"),
            r.randint(0, 4, (64, 1)).astype("int64"))


def _mlp_loss(width=32, hidden=31, layers=1):
    framework.default_main_program().random_seed = 1234
    framework.default_startup_program().random_seed = 1234
    img = fluid.layers.data(name="img", shape=[width], dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    h = img
    for _ in range(layers):
        h = fluid.layers.fc(input=h, size=hidden, act="relu")
    logits = fluid.layers.fc(input=h, size=4)
    return fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(logits, label))


def _train(opt_fn, bucket_mb, ndev=8, steps=3, clip=False, width=32,
           hidden=31, layers=1, gm_k=None, sharded=True):
    """Losses over `steps` identical-feed steps; returns
    (losses, exe, prog, loss, plan)."""
    import jax

    _fresh()
    set_flags({"FLAGS_tpu_sharded_weight_update": sharded,
               "FLAGS_tpu_comm_bucket_mb": bucket_mb})
    x, y = _batch(width)
    with framework.unique_name_guard():
        loss = _mlp_loss(width, hidden, layers)
        if clip:
            fluid.clip.set_gradient_clip(
                fluid.clip.GradientClipByGlobalNorm(0.5))
        opt = opt_fn()
        if gm_k:
            opt = O.GradientMergeOptimizer(opt, k_steps=gm_k)
        opt.minimize(loss)
        fluid.clip._clip_attr.clear()
        prog = fluid.default_main_program()
        fluid.CompiledProgram(prog).with_data_parallel(
            loss_name=loss.name)
        if ndev != 8:
            from jax.sharding import Mesh

            prog._mesh = Mesh(np.array(jax.devices()[:ndev]), ("dp",))
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        losses = [exe.run(prog, feed={"img": x, "label": y},
                          fetch_list=[loss])[0].copy()
                  for _ in range(steps)]
        plan = getattr(prog, "_shard_plan", None)
    return losses, exe, prog, loss, plan


def _identical(a, b):
    return all((np.asarray(x) == np.asarray(y)).all()
               for x, y in zip(a, b))


# ---------------------------------------------------------------------------
# bucket planning (unit level: synthetic ops, no tracing)
# ---------------------------------------------------------------------------

class _FakeVar:
    def __init__(self, shape, dtype="float32"):
        self.shape = shape
        self.dtype = dtype


class _FakeBlock:
    def __init__(self, vars_):
        self._vars = vars_

    def _find_var_recursive(self, name):
        return self._vars.get(name)


class _FakeOp:
    def __init__(self, params, grads):
        self.input_names = {"Grad": grads, "Param": params}
        self.output_names = {"ParamOut": params}


def _plan(entries, ndev, grad_topo, cap_bytes):
    """entries: [(param, shape, dtype)] -> plan_buckets result."""
    from paddle_tpu.parallel.sharded_update import plan_buckets

    block = _FakeBlock({p: _FakeVar(shape, dt)
                        for p, shape, dt in entries})
    ops = [_FakeOp([p], [p + "@GRAD"]) for p, _, _ in entries]
    return plan_buckets(ops, block, ndev, grad_topo, cap_bytes)


def test_plan_buckets_backward_production_order():
    """A param used LATER in the forward (larger grad_topo) gets its
    grad EARLIER in the vjp sweep — it must land in an earlier
    bucket."""
    buckets = _plan(
        [("a", (8,), "float32"), ("b", (8,), "float32"),
         ("c", (8,), "float32")],
        ndev=4, grad_topo={"a": 0, "b": 5, "c": 9}, cap_bytes=40)
    order = [e.grad for b in buckets for e in b.entries]
    assert order == ["c@GRAD", "b@GRAD", "a@GRAD"]
    # cap 40B: two 32B entries never share; one bucket per grad here
    assert [len(b.entries) for b in buckets] == [1, 1, 1]
    assert [b.index for b in buckets] == [0, 1, 2]


def test_plan_buckets_cap_and_oversize():
    """Greedy fill up to the cap; an oversize param gets its OWN
    bucket, still padded per-entry to 1/N divisibility."""
    buckets = _plan(
        [("big", (100,), "float32"),     # 400B > cap
         ("s1", (9,), "float32"), ("s2", (9,), "float32"),
         ("s3", (9,), "float32")],
        ndev=4, grad_topo={"big": 9, "s1": 8, "s2": 7, "s3": 6},
        cap_bytes=100)
    assert [sorted(e.param for e in b.entries) for b in buckets] == \
        [["big"], ["s1", "s2"], ["s3"]]
    big = buckets[0].entries[0]
    assert big.padded == 100  # 100 % 4 == 0: no pad needed
    s1 = buckets[1].entries[0]
    assert s1.padded == 12 and s1.numel == 9  # per-entry zero padding
    assert buckets[1].nbytes == 2 * 12 * 4


def test_plan_buckets_dtype_never_mixed():
    """fp32 and bf16 grads never share a bucket even when they fit."""
    buckets = _plan(
        [("f1", (8,), "float32"), ("h1", (8,), "bfloat16"),
         ("f2", (8,), "float32")],
        ndev=4, grad_topo={"f1": 9, "h1": 8, "f2": 7},
        cap_bytes=1 << 20)
    assert [str(b.dtype) for b in buckets] == \
        ["float32", "bfloat16", "float32"]
    assert [len(b.entries) for b in buckets] == [1, 1, 1]


# ---------------------------------------------------------------------------
# parity: bucketed == single-buffer (cap=0), incl. the extremes
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name,opt_fn,ndev", [
    ("sgd_2dev", lambda: O.SGDOptimizer(learning_rate=0.1), 2),
    ("momentum_4dev",
     lambda: O.MomentumOptimizer(learning_rate=0.1, momentum=0.9), 4),
    ("adam_8dev", lambda: O.AdamOptimizer(learning_rate=0.01), 8),
])
def test_bucketed_bit_identical_to_single_buffer(name, opt_fn, ndev):
    """SGD/Momentum/Adam: bucketed runs are BIT-identical to the cap=0
    per-variable lowering at both extremes — one bucket holding every
    grad (cap huge) and one bucket per param (cap ~ 1 byte)."""
    base, *_ , p0 = _train(opt_fn, 0.0, ndev=ndev)
    assert p0 is not None and not p0.buckets
    for mb, want in ((1000.0, 1), (1e-5, None)):
        got, _, _, _, plan = _train(opt_fn, mb, ndev=ndev)
        assert plan is not None and plan.buckets
        if want is not None:
            assert len(plan.buckets) == want
        else:  # bucket-per-param extreme
            assert len(plan.buckets) == \
                sum(len(b.entries) for b in plan.buckets)
        assert _identical(base, got), (name, mb)


def test_bucketed_adam_clip_parity_and_padding_zeroed():
    """Global-norm clipping on the bucketed path: bit-identical to
    cap=0, and the sharded moment buffers' zero-padding slots stay
    exactly zero across steps (shard-space elementwise ops re-zero
    them; the uneven 31-wide params pad every flat buffer)."""
    from paddle_tpu.core.scope import global_scope

    adam = lambda: O.AdamOptimizer(learning_rate=0.01)  # noqa: E731
    base, *_ = _train(adam, 0.0, clip=True)
    got, _, _, _, plan = _train(adam, 1000.0, clip=True)
    assert plan.buckets and plan.sharded_state
    assert _identical(base, got)
    padded_any = False
    for name, info in plan.sharded_state.items():
        buf = np.asarray(global_scope().find_var(name))
        assert buf.shape == (info.padded,)
        if info.padded > info.numel:
            padded_any = True
            np.testing.assert_array_equal(
                buf[info.numel:], 0.0, err_msg=name)
    assert padded_any, "test needs at least one padded state buffer"


def test_bucketed_lamb_tolerance():
    """LAMB's trust-ratio norms psum over shards: bucketed matches
    cap=0 within fp32 reduction-order tolerance."""
    lamb = lambda: O.LambOptimizer(learning_rate=0.01)  # noqa: E731
    base, *_ = _train(lamb, 0.0, ndev=4)
    got, *_ = _train(lamb, 0.002, ndev=4)
    np.testing.assert_allclose(
        [float(np.mean(v)) for v in base],
        [float(np.mean(v)) for v in got], rtol=2e-5, atol=1e-6)


def test_oversize_param_and_census_bucket_attribution():
    """A param bigger than the cap gets its own bucket; the census
    reduce_scatter count equals the bucket count (cap=0: one per grad),
    and collective/donation reports attribute bytes by SUMMING buckets."""
    adam = lambda: O.AdamOptimizer(learning_rate=0.01)  # noqa: E731
    # fc w: 64*63*4B ~ 15.8KB >> 4KB cap -> its own bucket
    kw = dict(width=64, hidden=63, layers=2, ndev=4, steps=2)
    x, y = _batch(64)
    base, *_ = _train(adam, 0.0, **kw)
    got, exe, prog, loss, plan = _train(adam, 0.004, **kw)
    assert _identical(base, got)
    cap = int(0.004 * (1 << 20))
    n_grads = sum(len(b.entries) for b in plan.buckets)
    assert len(plan.buckets) > 1
    oversize = [b for b in plan.buckets
                if len(b.entries) == 1 and b.nbytes > cap]
    assert oversize, "the 15.8KB fc weight must sit alone in a bucket"
    e = oversize[0].entries[0]
    assert e.padded % 4 == 0 and e.padded >= e.numel

    col = exe.collective_report(prog, feed={"img": x, "label": y},
                                fetch_list=[loss])
    assert col["reduce_scatter"]["count"] == len(plan.buckets)
    # bucket_cap_mb round-trips through the integer byte cap (4194 B)
    assert col["bucket_cap_mb"] == pytest.approx(0.004, rel=1e-3)
    assert len(col["buckets"]) == len(plan.buckets)
    assert col["bucket_bytes_total"] == \
        sum(b["bytes"] for b in col["buckets"])
    don = exe.donation_report(prog, feed={"img": x, "label": y},
                              fetch_list=[loss])
    assert don["grad_bucket_count"] == len(plan.buckets)
    assert don["grad_bucket_per_replica_bytes"] * 4 == \
        don["grad_bucket_logical_bytes"]

    # cap=0 attribution: per-variable collectives, no bucket keys
    _, exe0, prog0, loss0, _ = _train(adam, 0.0, **kw)
    col0 = exe0.collective_report(prog0, feed={"img": x, "label": y},
                                  fetch_list=[loss0])
    assert "buckets" not in col0
    assert col0["reduce_scatter"]["count"] == n_grads


# ---------------------------------------------------------------------------
# sharded gradient merge (satellite: ROADMAP open item)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("opt_name,opt_fn", [
    ("sgd", lambda: O.SGDOptimizer(learning_rate=0.1)),
    ("adam", lambda: O.AdamOptimizer(learning_rate=0.01)),
])
def test_gradient_merge_sharded_parity(opt_name, opt_fn):
    """The once-per-k merged-grad sync now reduce-scatters (bucketed
    and not) inside the lax.cond apply branch: bit-identical to the
    replicated gradient-merge path, moments sharded across steps."""
    base, *_, p_off = _train(opt_fn, 0.0, gm_k=3, steps=6,
                             sharded=False)
    assert p_off is None
    for mb in (0.0, 1000.0):
        got, _, _, _, plan = _train(opt_fn, mb, gm_k=3, steps=6)
        assert plan is not None and plan.gradient_merge
        assert bool(plan.buckets) == (mb > 0)
        if opt_name == "adam":
            assert plan.sharded_state, \
                "gm must keep the ZeRO-1 sharded moments"
        assert _identical(base, got), (opt_name, mb)


def test_gradient_merge_collectives_visible_in_region_audit():
    """gm traces its bucketed merged-grad scatters inside the lax.cond
    branch (an HLO conditional region): the overlap audit must SEE
    them as region_collectives (fenced by construction) instead of
    reporting no collectives at all for the gm-sharded path."""
    sgd = lambda: O.SGDOptimizer(learning_rate=0.1)  # noqa: E731
    _, exe, prog, loss, plan = _train(sgd, 1000.0, gm_k=2, steps=2)
    assert plan is not None and plan.gradient_merge and plan.buckets
    x, y = _batch()
    rep = exe.overlap_report(prog, feed={"img": x, "label": y},
                             fetch_list=[loss])
    region = rep["region_collectives"]
    assert any(c["kind"] == "reduce-scatter" for c in region), region


# ---------------------------------------------------------------------------
# overlap audit (tentpole verification)
# ---------------------------------------------------------------------------

def _deep_mlp(bucket_mb, ndev=4):
    adam = lambda: O.AdamOptimizer(learning_rate=0.01)  # noqa: E731
    kw = dict(width=64, hidden=64, layers=4, ndev=ndev, steps=1)
    _, exe, prog, loss, plan = _train(adam, bucket_mb, **kw)
    x, y = _batch(64)
    rep = exe.overlap_report(prog, feed={"img": x, "label": y},
                             fetch_list=[loss])
    return rep, plan


def test_overlap_audit_buckets_straddle_single_buffer_fenced():
    """Tentpole verification. Bucketed: >= 2 bucket reduce-scatters
    are dataflow-ready BEFORE the final backward compute op (their
    ring transfers can overlap the remaining backward), in production
    order — earlier buckets leave MORE backward compute to hide
    behind. cap=0 (the PR-3 lowering): under the collective-combiner
    model that governs real ICI, the combined grad exchange has
    NOTHING scheduled after it — the fully exposed gap bucketing
    removes."""
    # ~16KB per fc-weight grad; 20KB cap ~ one bucket per layer
    rep, plan = _deep_mlp(0.02)
    assert rep["is_scheduled"]
    assert rep["n_buckets"] == len(plan.buckets) >= 3
    rs = [c for c in rep["collectives"] if c["kind"] == "reduce-scatter"]
    assert len(rs) == len(plan.buckets)
    assert rep["overlappable_reduce_scatters"] >= 2
    after = [c["backward_after"] for c in sorted(rs,
                                                 key=lambda c: c["pos"])]
    assert after == sorted(after, reverse=True), \
        "production order: earlier buckets hide behind more backward"
    assert after[0] > 0 and after[-1] == 0

    rep0, plan0 = _deep_mlp(0.0)
    assert plan0 is not None and not plan0.buckets
    combined = rep0["combined"]["reduce-scatter"]
    assert combined["count"] > 1  # per-var collectives...
    assert combined["backward_after"] == 0  # ...combine into a fence
    assert rep0["n_backward_compute"] > 0


def test_cap_zero_reproduces_per_var_stablehlo():
    """FLAGS_tpu_comm_bucket_mb=0 lowers through the untouched
    per-variable path: no trace-level concatenate feeds the scatter
    (one reduce_scatter per optimizer grad), no bucket census keys."""
    adam = lambda: O.AdamOptimizer(learning_rate=0.01)  # noqa: E731
    _, exe, prog, loss, plan = _train(adam, 0.0, steps=1)
    x, y = _batch()
    got = exe._cached_lowerable(prog, {"img": x, "label": y}, [loss],
                                None)
    text = got[1].as_text()
    n_grads = len(plan.grad_names)
    assert text.count("reduce_scatter") == n_grads == 4
    # bucketed: exactly one scatter per bucket
    _, exe_b, prog_b, loss_b, plan_b = _train(adam, 1000.0, steps=1)
    got_b = exe_b._cached_lowerable(prog_b, {"img": x, "label": y},
                                    [loss_b], None)
    assert got_b[1].as_text().count("reduce_scatter") == \
        len(plan_b.buckets) == 1


# ---------------------------------------------------------------------------
# explicit-sync (fleet transpiler) pending-bucket path
# ---------------------------------------------------------------------------

def test_explicit_sync_buckets_parity():
    """Programs carrying their own c_allreduce_sum ops (fleet
    transpile_collective): each bucketed grad's allreduce holds pending
    until the bucket completes, then scatters as one collective —
    bit-identical to the per-variable explicit-sync lowering."""
    from paddle_tpu import fleet

    def run(bucket_mb):
        _fresh()
        set_flags({"FLAGS_tpu_sharded_weight_update": True,
                   "FLAGS_tpu_comm_bucket_mb": bucket_mb})
        r = np.random.RandomState(0)
        x = r.rand(16, 8).astype("float32")
        y = r.rand(16, 1).astype("float32")
        with framework.unique_name_guard():
            framework.default_main_program().random_seed = 11
            framework.default_startup_program().random_seed = 11
            xv = fluid.data(name="x", shape=[-1, 8], dtype="float32")
            yv = fluid.data(name="y", shape=[-1, 1], dtype="float32")
            pred = fluid.layers.fc(input=xv, size=3)
            pred = fluid.layers.fc(input=pred, size=1)
            loss = fluid.layers.reduce_mean(
                fluid.layers.square(pred - yv))
            fleet.init()
            fleet.distributed_optimizer(
                O.SGDOptimizer(learning_rate=0.1)).minimize(loss)
            prog = fluid.default_main_program()
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(fluid.default_startup_program())
            losses = [exe.run(prog, feed={"x": x, "y": y},
                              fetch_list=[loss])[0].copy()
                      for _ in range(3)]
            plan = getattr(prog, "_shard_plan", None)
        return losses, plan

    base, p0 = run(0.0)
    assert p0 is not None and not p0.buckets
    got, plan = run(1000.0)
    assert plan is not None and plan.buckets
    if plan.explicit_sync:
        assert plan.rs_targets and plan.bucket_of
    assert _identical(base, got)


def test_gradient_merge_with_explicit_sync_now_planned():
    """ROADMAP carried-over gap, closed: a fleet-transpiled program
    (explicit c_allreduce_sum grad sync) under GradientMergeOptimizer
    now PLANS — the once-per-k merged-grad sync reduce-scatters through
    the pending-bucket path inside the lax.cond apply branch —
    bit-identical to the replicated gm+explicit path, per-var and
    bucketed."""
    from paddle_tpu import fleet

    def run(flag, bucket_mb):
        _fresh()
        set_flags({"FLAGS_tpu_sharded_weight_update": flag,
                   "FLAGS_tpu_comm_bucket_mb": bucket_mb})
        r = np.random.RandomState(0)
        x = r.rand(16, 8).astype("float32")
        y = r.rand(16, 1).astype("float32")
        with framework.unique_name_guard():
            framework.default_main_program().random_seed = 11
            framework.default_startup_program().random_seed = 11
            xv = fluid.data(name="x", shape=[-1, 8], dtype="float32")
            yv = fluid.data(name="y", shape=[-1, 1], dtype="float32")
            pred = fluid.layers.fc(input=xv, size=3)
            pred = fluid.layers.fc(input=pred, size=1)
            loss = fluid.layers.reduce_mean(
                fluid.layers.square(pred - yv))
            fleet.init()
            gm = O.GradientMergeOptimizer(
                O.AdamOptimizer(learning_rate=0.05), k_steps=2)
            fleet.distributed_optimizer(gm).minimize(loss)
            prog = fluid.default_main_program()
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(fluid.default_startup_program())
            losses = [exe.run(prog, feed={"x": x, "y": y},
                              fetch_list=[loss])[0].copy()
                      for _ in range(6)]
            plan = getattr(prog, "_shard_plan", None)
        return losses, plan

    base, p_off = run(False, 0.0)
    assert p_off is None
    for mb in (0.0, 1000.0):
        got, plan = run(True, mb)
        assert plan is not None, "gm+explicit must plan now"
        assert plan.explicit_sync and plan.gradient_merge
        assert bool(plan.buckets) == (mb > 0)
        assert plan.sharded_state, "moments must stay sharded"
        assert _identical(base, got), mb


# ---------------------------------------------------------------------------
# launch supervisor: PADDLE_CKPT_AGREE default (satellite)
# ---------------------------------------------------------------------------

def test_launcher_defaults_ckpt_agree():
    from paddle_tpu.distributed.launch import _worker_env

    eps = ["127.0.0.1:6170", "127.0.0.1:6171"]
    env = _worker_env(eps, 1, 2, base_env={"PATH": "/bin"})
    assert env["PADDLE_CKPT_AGREE"] == "1"
    assert env["PADDLE_TRAINER_ID"] == "1"
    assert env["PADDLE_TRAINERS_NUM"] == "2"
    assert env["PADDLE_CURRENT_ENDPOINT"] == eps[1]
    assert env["PADDLE_TRAINER_ENDPOINTS"] == ",".join(eps)
    assert env["PADDLE_RESTART_NUM"] == "2"
    # explicit opt-out is respected, never overridden
    env0 = _worker_env(eps, 0, 0,
                       base_env={"PADDLE_CKPT_AGREE": "0"})
    assert env0["PADDLE_CKPT_AGREE"] == "0"


# ---------------------------------------------------------------------------
# acceptance: BERT-tiny (slow leg)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_bert_tiny_bucketed_20_steps():
    """Acceptance: bucketed BERT-tiny Adam is bit-identical to the
    single-buffer path for 20 steps on the 8-dev mesh, and the audit
    shows >= 2 bucket reduce-scatters ready before the final backward
    compute op (vs a fenced combined exchange at cap=0)."""
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from __graft_entry__ import _bert_feed
    from paddle_tpu.models import bert

    cfg = bert.BertConfig.tiny()
    seq_len, batch = 32, 16

    def run(bucket_mb):
        _fresh()
        set_flags({"FLAGS_tpu_sharded_weight_update": True,
                   "FLAGS_tpu_comm_bucket_mb": bucket_mb})
        with framework.unique_name_guard():
            framework.default_main_program().random_seed = 99
            framework.default_startup_program().random_seed = 99
            total, _, _, _ = bert.bert_pretrain_loss(
                cfg, seq_len, is_test=False)
            O.AdamOptimizer(learning_rate=1e-3).minimize(total)
            prog = fluid.default_main_program()
            fluid.CompiledProgram(prog).with_data_parallel(
                loss_name=total.name)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(fluid.default_startup_program())
            feed = _bert_feed(cfg, batch, seq_len)
            out = [exe.run(prog, feed=feed,
                           fetch_list=[total])[0].copy()
                   for _ in range(20)]
            rep = exe.overlap_report(prog, feed=feed,
                                     fetch_list=[total])
        return out, rep

    base, rep0 = run(0.0)
    got, rep = run(0.25)
    assert _identical(base, got)
    assert rep["n_buckets"] >= 2
    assert rep["overlappable_reduce_scatters"] >= 2
    assert rep0["combined"]["reduce-scatter"]["backward_after"] == 0
