"""Hierarchical DCN+ICI gradient collectives on a hybrid multi-pod
mesh (FLAGS_tpu_dcn_replicas / PADDLE_NUM_PODS).

The dp axis factors into a 2-D (dcn, ici) mesh (t5x
create_hybrid_device_mesh idiom; Kumar et al. 1909.09756, Wang et al.
2011.03641): every data-parallel grad sync lowers hierarchically —
psum_scatter inside the pod over ici, cross-pod psum of the 1/ici
shards over dcn, deferred per-bucket all-gather over ici — so only
1/ici_size of the gradient bytes cross the slow DCN link.

Parity contract: the hierarchical SHARDED update is bit-identical to
the hierarchical REPLICATED reference on the same hybrid mesh
(sharding never changes the math — the ZeRO guarantee, now two-level),
for SGD/Momentum/Adam incl. global-norm clip, gradient merge and
AMP-O2 sharded masters, per-variable and bucketed. Versus the FLAT
single-axis lowering the values agree to 1 fp32 ulp: a hierarchical
reduction sums pod partials first, which is a different fp association
than the flat N-way sum — inherent to hierarchical collectives on real
hardware too, and asserted here with an explicit 2-ulp bound rather
than hidden behind allclose defaults.

Machinery: parallel/env.create_hybrid_mesh + mesh_hierarchy,
parallel/sharded_update (plan dcn axis pair, _cross_pod_sum),
fluid/lowering (_compile_dp 2-D specs, hierarchical _dp_pmean, census
ici/dcn lanes), analysis.check_hierarchical_groups,
distributed/launch._pod_shrink, observability.publish.hierarchy_block.
"""
import json
import os

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu import analysis
from paddle_tpu.fluid import framework, lowering
from paddle_tpu.parallel import env as penv
from paddle_tpu.utils.flags import get_flag, set_flags

O = fluid.optimizer


@pytest.fixture(autouse=True)
def _restore_flags():
    keys = ("FLAGS_tpu_sharded_weight_update", "FLAGS_tpu_comm_bucket_mb",
            "FLAGS_tpu_dcn_replicas")
    old = {k: get_flag(k) for k in keys}
    yield
    set_flags(old)


def _fresh():
    from paddle_tpu.core import scope as scope_mod

    framework.switch_main_program(framework.Program())
    framework.switch_startup_program(framework.Program())
    scope_mod._global_scope = scope_mod.Scope()


def _batch(width=32):
    r = np.random.RandomState(0)
    return (r.rand(64, width).astype("float32"),
            r.randint(0, 4, (64, 1)).astype("int64"))


def _set_mesh(prog, ndev, dcn):
    import jax
    from jax.sharding import Mesh

    devs = np.array(jax.devices()[:ndev])
    if dcn > 1:
        prog._mesh = Mesh(devs.reshape(dcn, ndev // dcn),
                          ("dcn", "ici"))
    else:
        prog._mesh = Mesh(devs, ("dp",))


def _train(opt_fn, ndev, dcn, sharded=True, bucket_mb=0.0, steps=3,
           clip=False, gm_k=None, amp=False):
    """Losses over `steps` identical-feed steps on an `ndev`-device
    mesh factored into `dcn` pods (dcn=1 -> the flat 1-D mesh);
    returns (losses, exe, prog, loss, plan)."""
    _fresh()
    set_flags({"FLAGS_tpu_sharded_weight_update": sharded,
               "FLAGS_tpu_comm_bucket_mb": bucket_mb,
               "FLAGS_tpu_dcn_replicas": 0})
    x, y = _batch()
    with framework.unique_name_guard():
        framework.default_main_program().random_seed = 1234
        framework.default_startup_program().random_seed = 1234
        img = fluid.layers.data(name="img", shape=[32], dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        h = fluid.layers.fc(input=img, size=31, act="relu")
        logits = fluid.layers.fc(input=h, size=4)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        if clip:
            fluid.clip.set_gradient_clip(
                fluid.clip.GradientClipByGlobalNorm(0.5))
        opt = opt_fn()
        if amp:
            from paddle_tpu.fluid.contrib import mixed_precision

            opt = mixed_precision.decorate(opt)
        if gm_k:
            opt = O.GradientMergeOptimizer(opt, k_steps=gm_k)
        opt.minimize(loss)
        fluid.clip._clip_attr.clear()
        prog = fluid.default_main_program()
        fluid.CompiledProgram(prog).with_data_parallel(
            loss_name=loss.name)
        _set_mesh(prog, ndev, dcn)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        losses = [np.asarray(exe.run(prog, feed={"img": x, "label": y},
                                     fetch_list=[loss])[0]).copy()
                  for _ in range(steps)]
        plan = getattr(prog, "_shard_plan", None)
    return losses, exe, prog, loss, plan


def _identical(a, b):
    return all((np.asarray(x) == np.asarray(y)).all()
               for x, y in zip(a, b))


def _max_ulp32(a, b):
    """Max distance in fp32 ulps between two loss sequences."""
    worst = 0
    for x, y in zip(a, b):
        xi = np.asarray(x, np.float32).view(np.int32).astype(np.int64)
        yi = np.asarray(y, np.float32).view(np.int32).astype(np.int64)
        worst = max(worst, int(np.abs(xi - yi).max()))
    return worst


# ---------------------------------------------------------------------------
# mesh construction
# ---------------------------------------------------------------------------

def test_create_hybrid_mesh_and_hierarchy():
    m = penv.create_hybrid_mesh(nranks=4, dcn=2)
    assert m is not None and m.axis_names == ("dcn", "ici")
    assert m.shape["dcn"] == 2 and m.shape["ici"] == 2
    assert penv.mesh_hierarchy(m) == ("dcn", "ici", 2, 2)
    # pods are contiguous device blocks (row-major reshape)
    import jax

    devs = jax.devices()
    assert list(np.asarray(m.devices)[0]) == devs[:2]
    # flat mesh: no hierarchy
    from jax.sharding import Mesh

    flat = Mesh(np.array(devs[:4]), ("dp",))
    assert penv.mesh_hierarchy(flat) is None
    assert penv.mesh_hierarchy(None) is None


def test_hybrid_mesh_fallbacks():
    # dcn <= 1: no hybrid mesh
    assert penv.create_hybrid_mesh(nranks=4, dcn=1) is None
    # non-divisible world: warn + flat fallback, never a wrong mesh
    with pytest.warns(UserWarning, match="not divisible"):
        assert penv.create_hybrid_mesh(nranks=6, dcn=4) is None


def test_dcn_replicas_flag_and_env(monkeypatch):
    set_flags({"FLAGS_tpu_dcn_replicas": 0})
    monkeypatch.delenv("PADDLE_NUM_PODS", raising=False)
    assert penv.dcn_replicas() == 1
    monkeypatch.setenv("PADDLE_NUM_PODS", "2")
    assert penv.dcn_replicas() == 2
    set_flags({"FLAGS_tpu_dcn_replicas": 4})  # flag wins over env
    assert penv.dcn_replicas() == 4


def test_flag_builds_hybrid_mesh_through_compile(monkeypatch):
    """FLAGS_tpu_dcn_replicas=2 alone (no hand-built mesh) lowers a DP
    program onto the hybrid mesh: compile_block constructs it and
    rewires _dp_axis/_dcn_axis."""
    _fresh()
    set_flags({"FLAGS_tpu_sharded_weight_update": True,
               "FLAGS_tpu_comm_bucket_mb": 0.0,
               "FLAGS_tpu_dcn_replicas": 2})
    x, y = _batch()
    with framework.unique_name_guard():
        img = fluid.layers.data(name="img", shape=[32], dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        logits = fluid.layers.fc(input=img, size=4)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        O.SGDOptimizer(0.1).minimize(loss)
        prog = fluid.default_main_program()
        fluid.CompiledProgram(prog).with_data_parallel(
            loss_name=loss.name)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        exe.run(prog, feed={"img": x, "label": y}, fetch_list=[loss])
    assert prog._mesh.axis_names == ("dcn", "ici")
    assert prog._dp_axis == "ici" and prog._dcn_axis == "dcn"
    assert prog._shard_plan is not None
    assert prog._shard_plan.dcn_axis == "dcn"
    assert prog._shard_plan.ndev == 4  # 8 devices / 2 pods
    assert prog._shard_plan.world == 8


# ---------------------------------------------------------------------------
# parity (acceptance criterion): hierarchical sharded == hierarchical
# replicated, bit for bit, on emulated 2x2 and 2x4 hybrid CPU meshes
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name,opt_fn,ndev,dcn", [
    ("sgd_2x2", lambda: O.SGDOptimizer(learning_rate=0.1), 4, 2),
    ("momentum_2x4",
     lambda: O.MomentumOptimizer(learning_rate=0.1, momentum=0.9), 8, 2),
    ("adam_2x2", lambda: O.AdamOptimizer(learning_rate=0.01), 4, 2),
    ("adam_4x2", lambda: O.AdamOptimizer(learning_rate=0.01), 8, 4),
])
def test_hierarchical_sharded_parity_bit_identical(name, opt_fn, ndev,
                                                   dcn):
    rep, *_ = _train(opt_fn, ndev, dcn, sharded=False)
    pv, _, _, _, plan_pv = _train(opt_fn, ndev, dcn, sharded=True)
    bk, _, _, _, plan_bk = _train(opt_fn, ndev, dcn, sharded=True,
                                  bucket_mb=0.001)
    assert plan_pv is not None and plan_pv.dcn_axis == "dcn"
    assert plan_bk.buckets, "bucketing did not engage"
    assert _identical(rep, pv), (name, rep, pv)
    assert _identical(rep, bk), (name, rep, bk)


def test_hierarchical_clip_and_gradient_merge_parity():
    adam = lambda: O.AdamOptimizer(learning_rate=0.01)  # noqa: E731
    rep, *_ = _train(adam, 4, 2, sharded=False, clip=True)
    sh, _, _, _, plan = _train(adam, 4, 2, sharded=True, clip=True,
                               bucket_mb=0.001)
    assert plan.buckets and plan.dcn_axis == "dcn"
    assert _identical(rep, sh)
    # gradient merge: the once-per-k merged-grad sync rides the same
    # hierarchical bucket path inside the lax.cond branch
    repg, *_ = _train(adam, 4, 2, sharded=False, gm_k=2, steps=4)
    shg, _, _, _, plang = _train(adam, 4, 2, sharded=True, gm_k=2,
                                 steps=4, bucket_mb=0.001)
    assert plang is not None and plang.gradient_merge
    assert _identical(repg, shg)


def test_hierarchical_amp_o2_masters_parity():
    """bf16 compute + ZeRO-sharded fp32 masters on the hybrid mesh:
    masters shard over ici (replicated across pods), grads scatter
    hierarchically in bf16, still bit-identical to the replicated
    hierarchical reference (world=4 is a power of two, so the
    bucketing gate does not engage)."""
    adam = lambda: O.AdamOptimizer(learning_rate=0.01)  # noqa: E731
    rep, *_ = _train(adam, 4, 2, sharded=False, amp=True)
    sh, _, _, _, plan = _train(adam, 4, 2, sharded=True, amp=True,
                               bucket_mb=0.001)
    assert plan.master_of and plan.buckets and plan.dcn_axis == "dcn"
    assert _identical(rep, sh)


def test_fleet_explicit_sync_hierarchical_parity():
    """The fleet transpiler's explicit c_allreduce_sum grad syncs ride
    the same hierarchical path: ring 0 spans the (dcn, ici) axis pair,
    planned grads scatter-then-cross-pod-psum per bucket, and the
    result is bit-identical to the replicated explicit-sync run on the
    same hybrid mesh."""
    from paddle_tpu.fleet import transpile_collective

    def run(sharded, bucket_mb):
        _fresh()
        set_flags({"FLAGS_tpu_sharded_weight_update": sharded,
                   "FLAGS_tpu_comm_bucket_mb": bucket_mb,
                   "FLAGS_tpu_dcn_replicas": 2})
        x, y = _batch()
        with framework.unique_name_guard():
            framework.default_main_program().random_seed = 1234
            framework.default_startup_program().random_seed = 1234
            img = fluid.layers.data(name="img", shape=[32],
                                    dtype="float32")
            label = fluid.layers.data(name="label", shape=[1],
                                      dtype="int64")
            h = fluid.layers.fc(input=img, size=31, act="relu")
            logits = fluid.layers.fc(input=h, size=4)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, label))
            O.AdamOptimizer(1e-2).minimize(loss)
            prog = fluid.default_main_program()
            transpile_collective(prog, nranks=4)
            assert prog._mesh.axis_names == ("dcn", "ici")
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(fluid.default_startup_program())
            losses = [np.asarray(
                exe.run(prog, feed={"img": x, "label": y},
                        fetch_list=[loss])[0]).copy()
                for _ in range(3)]
            plan = getattr(prog, "_shard_plan", None)
        return losses, plan

    rep, _ = run(False, 0.0)
    sh, plan = run(True, 0.001)
    assert plan is not None and plan.explicit_sync
    assert plan.dcn_axis == "dcn" and plan.buckets
    assert _identical(rep, sh), (rep, sh)


def test_sync_batch_norm_on_hybrid_mesh():
    """transpile_collective(sync_batch_norm=True) must bind the BN
    moment sync to the (dcn, ici) axis PAIR on a hybrid mesh — the
    old hardcoded "dp" was an unbound axis name inside the shard_map
    (crash found in review)."""
    from paddle_tpu.fleet import transpile_collective

    _fresh()
    set_flags({"FLAGS_tpu_sharded_weight_update": False,
               "FLAGS_tpu_comm_bucket_mb": 0.0,
               "FLAGS_tpu_dcn_replicas": 2})
    r = np.random.RandomState(0)
    x = r.rand(16, 8).astype("float32")
    y = r.rand(16, 1).astype("float32")
    with framework.unique_name_guard():
        img = fluid.layers.data(name="img", shape=[8], dtype="float32")
        lbl = fluid.layers.data(name="lbl", shape=[1], dtype="float32")
        h = fluid.layers.fc(input=img, size=6)
        h = fluid.layers.batch_norm(h)
        pred = fluid.layers.fc(input=h, size=1)
        loss = fluid.layers.reduce_mean(
            fluid.layers.square(pred - lbl))
        O.SGDOptimizer(0.1).minimize(loss)
        prog = fluid.default_main_program()
        transpile_collective(prog, nranks=4, sync_batch_norm=True)
        bn = next(op for op in prog.global_block().ops
                  if op.type == "sync_batch_norm")
        assert tuple(bn.attrs["axis_name"]) == ("dcn", "ici")
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        out = exe.run(prog, feed={"img": x, "lbl": y},
                      fetch_list=[loss])
    assert np.isfinite(np.asarray(out[0])).all()


def test_strategy_hierarchical_allreduce_knob_builds_hybrid_mesh():
    """fleet.DistributedStrategy.use_hierarchical_allreduce (accepted
    but inert since PR 1) is real now: inter_nranks becomes the
    cross-pod dcn degree and minimize() lands the program on a hybrid
    mesh."""
    from paddle_tpu import fleet as fleet_mod

    _fresh()
    set_flags({"FLAGS_tpu_dcn_replicas": 0,
               "FLAGS_tpu_sharded_weight_update": True,
               "FLAGS_tpu_comm_bucket_mb": 0.0})
    with framework.unique_name_guard():
        img = fluid.layers.data(name="img", shape=[32], dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        logits = fluid.layers.fc(input=img, size=4)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        st = fleet_mod.DistributedStrategy()
        st.use_hierarchical_allreduce = True
        st.hierarchical_allreduce_inter_nranks = 2
        fleet_mod.CollectiveOptimizer(
            O.SGDOptimizer(0.1), st).minimize(loss)
        prog = fluid.default_main_program()
    assert get_flag("FLAGS_tpu_dcn_replicas") == 2
    assert prog._mesh.axis_names == ("dcn", "ici")
    assert prog._dp_axis == "ici" and prog._dcn_axis == "dcn"


def test_hierarchical_vs_flat_within_one_ulp():
    """Hierarchy changes the REDUCTION ASSOCIATION (pod partial sums
    first) — vs the flat PR-4 lowering the losses agree to <= 2 fp32
    ulps, never bit-exactly in general. The tight bound IS the claim:
    anything larger would mean a lowering bug, not fp association."""
    adam = lambda: O.AdamOptimizer(learning_rate=0.01)  # noqa: E731
    flat, *_ = _train(adam, 4, 1, sharded=True, bucket_mb=0.001)
    hier, *_ = _train(adam, 4, 2, sharded=True, bucket_mb=0.001)
    assert _max_ulp32(flat, hier) <= 2, (flat, hier)


# ---------------------------------------------------------------------------
# census lanes (acceptance criterion: dcn bytes = flat bytes / ici per
# bucket) + flat-default invariance
# ---------------------------------------------------------------------------

def test_census_lanes_cross_pod_bytes():
    adam = lambda: O.AdamOptimizer(learning_rate=0.01)  # noqa: E731
    _, exe, prog, loss, plan = _train(adam, 8, 2, sharded=True,
                                      bucket_mb=0.001)
    x, y = _batch()
    col = exe.collective_report(prog, feed={"img": x, "label": y},
                                fetch_list=[loss])
    assert col["ici_size"] == 4 and col["dcn_size"] == 2
    lanes = col["lanes"]
    # one cross-pod psum per bucket, each carrying the bucket's 1/ici
    # shard: dcn bytes == flat-allreduce bytes / ici_size, per bucket
    dcn_ar = [c for c in lanes["dcn"]["per_collective"]
              if c["kind"] == "all_reduce"]
    assert len(dcn_ar) == len(plan.buckets) >= 2
    by_bytes = sorted(c["tensor_bytes"] for c in dcn_ar)
    want = sorted(b.nbytes // 4 for b in plan.buckets)
    assert by_bytes == want, (by_bytes, want)
    assert all(c["participants"] == 2 for c in dcn_ar)
    # the intra-pod lane carries the scatters and the deferred gathers
    kinds = {c["kind"] for c in lanes["ici"]["per_collective"]}
    assert "reduce_scatter" in kinds and "all_gather" in kinds
    assert col["dcn_bytes_total"] == lanes["dcn"]["wire_bytes"] > 0


def test_flat_default_census_unchanged():
    """FLAGS_tpu_dcn_replicas unset/1: the flat lowering — census has
    no lanes, mesh stays 1-D, and the plan carries no dcn axis."""
    adam = lambda: O.AdamOptimizer(learning_rate=0.01)  # noqa: E731
    _, exe, prog, loss, plan = _train(adam, 4, 1, sharded=True,
                                      bucket_mb=0.001)
    x, y = _batch()
    col = exe.collective_report(prog, feed={"img": x, "label": y},
                                fetch_list=[loss])
    assert "lanes" not in col and "dcn_size" not in col
    assert plan.dcn_axis is None and plan.world == plan.ndev == 4
    assert prog._mesh.axis_names == ("dp",)
    assert getattr(prog, "_dcn_axis", None) is None


def test_hierarchical_hlo_groups_lint_clean_and_seeded_defects():
    """The lowered hybrid-mesh module passes the two-level
    replica_groups audit; seeded wrong-axis / non-uniform group sets
    trip errors (the tpu-lint acceptance for this PR)."""
    adam = lambda: O.AdamOptimizer(learning_rate=0.01)  # noqa: E731
    _, exe, prog, loss, _ = _train(adam, 4, 2, sharded=True,
                                   bucket_mb=0.001)
    x, y = _batch()
    got = exe._cached_lowerable(prog, {"img": x, "label": y}, [loss],
                                None)
    assert got is not None
    hlo = got[1].as_text()
    # the real lowering: clean
    assert analysis.check_hierarchical_groups(hlo, 2) == []
    sched = analysis.hlo_collective_schedule(hlo)
    assert any(r["groups"] == ((0, 1), (2, 3)) for r in sched), \
        "expected intra-pod groups in the lowered module"
    assert any(r["groups"] == ((0, 2), (1, 3)) for r in sched), \
        "expected cross-pod groups in the lowered module"
    # seeded defects (synthetic modules)
    non_uniform = ('%0 = "stablehlo.all_reduce"(%a) {replica_groups = '
                   'dense<[[0, 1, 2], [3]]> : tensor<2x3xi64>} : '
                   '(tensor<4xf32>) -> tensor<4xf32>')
    fs = analysis.check_hierarchical_groups(non_uniform, 2)
    assert len(fs) == 1 and fs[0].severity == "error"
    assert "NON-UNIFORM" in fs[0].message
    mixed = ('%0 = "stablehlo.all_reduce"(%a) {replica_groups = '
             'dense<[[0, 1, 4, 5], [2, 3, 6, 7]]> : tensor<2x4xi64>} '
             ': (tensor<4xf32>) -> tensor<4xf32>')
    fs = analysis.check_hierarchical_groups(mixed, 2, ndev=8)
    assert len(fs) == 1 and fs[0].severity == "error"
    assert "WRONG-AXIS" in fs[0].message
    # a flat global group is legal (e.g. the AMP found_inf psum)
    flat_ok = ('%0 = "stablehlo.all_reduce"(%a) {replica_groups = '
               'dense<[[0, 1, 2, 3]]> : tensor<1x4xi64>} : '
               '(tensor<f32>) -> tensor<f32>')
    assert analysis.check_hierarchical_groups(flat_ok, 2) == []


# ---------------------------------------------------------------------------
# layout: opt state shards within the pod, replicated across pods
# ---------------------------------------------------------------------------

def test_opt_state_sharded_within_pod():
    adam = lambda: O.AdamOptimizer(learning_rate=0.01)  # noqa: E731
    _, exe, prog, loss, plan = _train(adam, 4, 2, sharded=True)
    assert plan.sharded_state, "no sharded state"
    from paddle_tpu.core.scope import global_scope

    name, info = next(iter(plan.sharded_state.items()))
    v = global_scope().find_var(name)
    assert tuple(v.shape) == (info.padded,)
    spec = v.sharding.spec
    # P("ici"): sharded over the intra-pod axis, REPLICATED across
    # pods — each pod holds a full copy of the 1/ici shards
    assert tuple(spec) == ("ici",)
    x, y = _batch()
    rep = exe.donation_report(prog, feed={"img": x, "label": y},
                              fetch_list=[loss])
    assert rep["opt_state_sharded_vars"] >= 1
    # per-replica bytes ~ padded / ici_size (ici=2), not / world (4)
    logical = rep["opt_state_logical_bytes"]
    per_rep = rep["opt_state_per_replica_bytes"]
    assert logical / 2.2 < per_rep < logical / 1.8, (logical, per_rep)


def test_feed_sharding_spans_both_axes():
    adam = lambda: O.AdamOptimizer(learning_rate=0.01)  # noqa: E731
    _, exe, prog, loss, _ = _train(adam, 4, 2, sharded=True)
    ns = exe.feed_sharding(prog)
    assert tuple(ns.spec) == (("dcn", "ici"),)


# ---------------------------------------------------------------------------
# pod-aware elastic shrink (satellite): rectangular or flat fallback,
# never a lopsided topology
# ---------------------------------------------------------------------------

def test_pod_shrink_rectangular_and_flat_fallback():
    from paddle_tpu.distributed.launch import _pod_shrink

    eps = ["127.0.0.1:%d" % (6170 + i) for i in range(4)]
    # 2x2, one rank lost in EACH pod: stays rectangular at 1/pod
    surv, npods, fields = _pod_shrink(eps, [1, 2], 2)
    assert surv == [eps[0], eps[3]] and npods == 2
    assert fields["pod_topology"] == "rectangular"
    assert fields["ranks_per_pod"] == 1
    # 2x2 losing ONE rank: pods would be lopsided (2 vs 1) -> flat
    # fallback keeping every survivor, and the event says so
    surv, npods, fields = _pod_shrink(eps, [1], 2)
    assert surv == [eps[0], eps[2], eps[3]] and npods == 1
    assert fields["pod_topology"] == "flat_fallback"
    assert fields["pod_survivor_counts"] == [1, 2]
    # a whole pod dying is NOT rectangular (a zero-rank pod cannot
    # join the dcn exchange): flat fallback
    surv, npods, fields = _pod_shrink(eps, [0, 1], 2)
    assert npods == 1 and fields["pod_topology"] == "flat_fallback"
    # flat world: no pod fields
    surv, npods, fields = _pod_shrink(eps, [1], 1)
    assert npods == 1 and fields == {}


def test_worker_env_pod_topology():
    from paddle_tpu.distributed.launch import _worker_env

    eps = ["127.0.0.1:%d" % (6170 + i) for i in range(4)]
    env = _worker_env(eps, 3, 0, base_env={}, npods=2)
    assert env["PADDLE_NUM_PODS"] == "2"
    assert env["PADDLE_POD_ID"] == "1"
    assert env["PADDLE_TRAINER_ID"] == "3"
    # flat fallback must scrub stale topology from the inherited env
    env = _worker_env(eps[:3], 0, 1,
                      base_env={"PADDLE_NUM_PODS": "2",
                                "PADDLE_POD_ID": "1"}, npods=1)
    assert "PADDLE_NUM_PODS" not in env
    assert "PADDLE_POD_ID" not in env


def test_comm_lane_classification(monkeypatch):
    from paddle_tpu.distributed.host_collectives import \
        HostCollectiveGroup

    g = object.__new__(HostCollectiveGroup)
    g.world = 4
    monkeypatch.setenv("PADDLE_NUM_PODS", "2")
    assert g._comm_lane() == "dcn"  # a 4-rank group spans both pods
    g2 = object.__new__(HostCollectiveGroup)
    g2.world = 4
    monkeypatch.delenv("PADDLE_NUM_PODS", raising=False)
    assert g2._comm_lane() is None  # no topology: no lane counters


# ---------------------------------------------------------------------------
# bench "hierarchy" block: registry-assembled + schema-valid (CI
# satellite)
# ---------------------------------------------------------------------------

def test_bench_hierarchy_block_from_registry(tmp_path):
    import paddle_tpu.observability as obs
    from paddle_tpu.observability import publish

    obs.reset_registry()
    obs.configure(telemetry_dir=str(tmp_path), rank=0)
    try:
        adam = lambda: O.AdamOptimizer(learning_rate=0.01)  # noqa: E731
        _, exe, prog, loss, plan = _train(adam, 4, 2, sharded=True,
                                          bucket_mb=0.001)
        x, y = _batch()
        blocks = publish.bench_blocks(exe, prog, {"img": x, "label": y},
                                      [loss])
        assert "hierarchy" in blocks
        hb = blocks["hierarchy"]
        # the registry is the source of truth for what bench attaches
        assert blocks == obs.registry().blocks()
        assert hb["dcn_replicas"] == 2 and hb["ici_size"] == 2
        assert hb["dcn"]["count"] == len(plan.buckets)
        assert hb["dcn_grad_sync_bytes"] * hb["ici_size"] == \
            hb["flat_allreduce_bytes"] > 0
        # the sink's records stay schema-valid with the new comm-lane
        # step fields
        schema = obs.load_schema(os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools", "telemetry_schema.json"))
        jsonl = obs.registry().jsonl_path
        lines = [json.loads(ln) for ln in open(jsonl)]
        assert lines and obs.validate_records(lines, schema) == []
        # flat program: no hierarchy block claimed
        _, exe_f, prog_f, loss_f, _ = _train(adam, 4, 1, sharded=True)
        blocks_f = publish.bench_blocks(exe_f, prog_f,
                                        {"img": x, "label": y},
                                        [loss_f])
        assert "hierarchy" not in blocks_f
    finally:
        obs.reset_registry()


# ---------------------------------------------------------------------------
# dygraph fit -> metrics registry (satellite)
# ---------------------------------------------------------------------------

def test_hapi_fit_publishes_step_records(tmp_path):
    import paddle_tpu.observability as obs
    from paddle_tpu.hapi import Model

    obs.reset_registry()
    obs.configure(telemetry_dir=str(tmp_path), rank=0)
    try:
        import paddle_tpu as paddle
        from paddle_tpu.hapi.datasets import SyntheticImages

        np.random.seed(1234)

        class FlattenLinear(paddle.nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = paddle.nn.Linear(64, 10)

            def forward(self, x):
                return self.fc(x.reshape((x.shape[0], 64)))

        model = Model(paddle.nn.Sequential(FlattenLinear()))
        model.prepare(
            optimizer=O.AdamOptimizer(learning_rate=1e-2),
            loss_function=paddle.nn.CrossEntropyLoss())
        model.fit(SyntheticImages(num_samples=48), batch_size=16,
                  epochs=1, verbose=0, log_freq=2)
        snap = obs.registry().snapshot()
        # 6 samples / batch 2 = 3 train steps, each a step record —
        # dygraph fit now shows up in --stragglers / timeline merges
        assert snap["steps"] >= 3
        recs = [json.loads(ln)
                for ln in open(obs.registry().jsonl_path)]
        steps = [rec for rec in recs if rec["kind"] == "step"]
        assert len(steps) >= 3
        assert all(rec["dispatch_ms"] > 0 for rec in steps)
    finally:
        obs.reset_registry()


# ---------------------------------------------------------------------------
# donation checker covers the dygraph-to-static path (satellite)
# ---------------------------------------------------------------------------

def test_donation_checker_covers_dygraph_to_static():
    """A `_feed_donate=False` program (the dygraph-to-static marker)
    now gets the full donation walk against its REAL feed list
    (program._feed_names): a fetch holding a param across its in-place
    optimizer rebind still trips the read-after-donate error, and a
    rebind of a caller-owned feed var warns about the eager/static
    coherence gap."""
    set_flags({"FLAGS_tpu_donate_buffers": True})
    _fresh()
    with framework.unique_name_guard():
        prog = framework.Program()
        st = framework.Program()
        with framework.program_guard(prog, st):
            x = fluid.layers.data(name="x", shape=[4], dtype="float32")
            w = fluid.layers.create_parameter([4], "float32", name="w")
            y = fluid.layers.elementwise_mul(x, w)
            loss = fluid.layers.reduce_mean(y)
            O.SGDOptimizer(0.1).minimize(loss)
        g = prog.global_block()
        from paddle_tpu.fluid.framework import Operator

        # seeded defect 1: a fetch holds the param BEFORE its sgd
        # rebind — read-after-donate under state donation
        bwd = next(i for i, op in enumerate(g.ops)
                   if op.type == "backward")
        g.ops.insert(bwd, Operator(g, "fetch", inputs={"X": ["w"]},
                                   outputs={}, attrs={}))
        # seeded defect 2: the program rebinds its caller-owned feed
        g.ops.append(Operator(g, "scale", inputs={"X": ["x"]},
                              outputs={"Out": ["x"]},
                              attrs={"scale": 2.0}))
        # the dygraph-to-static contract markers (ConcreteProgram)
        prog._feed_donate = False
        prog._feed_names = ["x"]
        fs = analysis.check_donation_safety(prog)
        errs = [f for f in fs if f.severity == "error"]
        warns = [f for f in fs if f.severity == "warning"]
        assert any(f.var == "w" and "read-after-donate" in f.message
                   for f in errs), fs
        assert any(f.var == "x" and "caller-owned" in f.message
                   for f in warns), fs
        # the same program WITHOUT the markers falls back to is_data
        # discovery (x is a data var) and must not emit the
        # caller-owned warning class
        del prog._feed_names
        prog._feed_donate = True
        fs2 = analysis.check_donation_safety(prog)
        assert not any("caller-owned" in f.message for f in fs2)
