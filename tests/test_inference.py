"""Inference predictor API tests (reference test model:
test_analysis_predictor / inference api tests)."""
import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework
from paddle_tpu import inference


def _export_model(tmp_path):
    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup):
        with framework.unique_name_guard():
            x = fluid.layers.data("x", shape=[6], dtype="float32")
            h = fluid.layers.fc(x, 8, act="relu")
            out = fluid.layers.fc(h, 3, act="softmax")
            exe = fluid.Executor()
            exe.run(startup)
            fluid.io.save_inference_model(str(tmp_path), ["x"], [out], exe,
                                          main_program=main)
            # reference result computed through the raw executor
            xs = np.random.RandomState(0).randn(4, 6).astype("float32")
            ref = exe.run(main, feed={"x": xs}, fetch_list=[out.name])[0]
    return xs, np.asarray(ref), out.name


def test_predictor_zero_copy_matches_executor(tmp_path):
    xs, ref, out_name = _export_model(tmp_path)
    config = inference.Config(str(tmp_path))
    pred = inference.create_predictor(config)
    assert pred.get_input_names() == ["x"]
    assert pred.get_output_names() == [out_name]

    inp = pred.get_input_handle("x")
    inp.copy_from_cpu(xs)
    pred.run()
    got = pred.get_output_handle(out_name).copy_to_cpu()
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(got.sum(axis=1), 1.0, rtol=1e-5)


def test_predictor_positional_run_and_shape_cache(tmp_path):
    xs, ref, out_name = _export_model(tmp_path)
    pred = inference.create_paddle_predictor(
        inference.AnalysisConfig(str(tmp_path)))
    outs = pred.run([xs])
    np.testing.assert_allclose(outs[0], ref, rtol=1e-5, atol=1e-6)
    # different batch size: recompiles under a new shape key, still correct
    outs2 = pred.run([xs[:2]])
    np.testing.assert_allclose(outs2[0], ref[:2], rtol=1e-5, atol=1e-6)


def test_two_predictors_are_isolated(tmp_path):
    xs, ref, out_name = _export_model(tmp_path / "m1")
    p1 = inference.create_predictor(inference.Config(str(tmp_path / "m1")))
    p2 = inference.create_predictor(inference.Config(str(tmp_path / "m1")))
    o1 = p1.run([xs])[0]
    o2 = p2.run([xs])[0]
    np.testing.assert_allclose(o1, o2, rtol=1e-6)


def test_analysis_pass_builder_and_report(tmp_path):
    """Analysis tier (VERDICT r2 missing #9; reference:
    analysis_predictor.cc:498 pass pipeline + AnalysisConfig)."""
    from paddle_tpu import inference

    xs, ref, _ = _export_model(tmp_path)
    cfg = inference.Config(str(tmp_path))
    pb = cfg.pass_builder()
    assert "operator_fusion_pass" in pb.all_passes()
    pb.delete_pass("operator_fusion_pass")
    assert "operator_fusion_pass" not in pb.all_passes()

    pred = inference.create_predictor(cfg)
    rep = pred.get_optimization_report()
    assert rep["num_ops"] > 0 and rep["compiler"] == "xla"
    assert rep["ir_optim"] is True
    assert "operator_fusion_pass" not in rep["passes"]

    out_opt = pred.run([xs])[0]

    # ir_optim off: same numerics through op-by-op eager dispatch
    cfg2 = inference.Config(str(tmp_path))
    cfg2.switch_ir_optim(False)
    pred2 = inference.create_predictor(cfg2)
    assert pred2.get_optimization_report()["ir_optim"] is False
    out_eager = pred2.run([xs])[0]
    np.testing.assert_allclose(out_opt, out_eager, rtol=1e-5, atol=1e-6)


def test_dygraph_zoo_model_to_predictor_roundtrip(tmp_path):
    """Deploy path for the dygraph zoo: train-mode LeNet -> jit.save
    (declarative trace + inference export) -> AnalysisPredictor run,
    matching the eager forward (eval mode: dropout-free, BN absent)."""
    import paddle_tpu as paddle
    from paddle_tpu.fluid import dygraph
    from paddle_tpu.hapi.vision.models import LeNet

    with dygraph.guard():
        net = LeNet(num_classes=10)
        net.eval()
        x = np.random.RandomState(3).rand(2, 1, 28, 28).astype("float32")
        want = None
        # trace via TracedLayer off the eager forward
        out, traced = dygraph.TracedLayer.trace(
            net, [paddle.to_tensor(x)])
        want = out.numpy()
        d = str(tmp_path / "lenet_inf")
        traced.save_inference_model(d)

    cfg = inference.Config(d)
    pred = inference.create_predictor(cfg)
    in_names = pred.get_input_names()
    h = pred.get_input_handle(in_names[0])
    h.copy_from_cpu(x)
    pred.run()
    got = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
