"""Scan programs through the inference path: save_inference_model must
serialize the scan op (sub_block + xs attrs), pruning must keep the
sub-block and stacked params, and the loaded program must reproduce the
trained model's outputs."""
import os
import tempfile

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework
from paddle_tpu.core.scope import global_scope


def test_while_model_survives_pruning():
    """Same prune bug class as scan: a While writes its results via the
    sub-block, so output_arg_names-only pruning silently dropped it."""
    H = 6
    main, st = framework.Program(), framework.Program()
    main.random_seed = st.random_seed = 2
    with framework.program_guard(main, st):
        with framework.unique_name_guard():
            x = fluid.layers.data("x", shape=[H], dtype="float32")
            h = fluid.layers.fc(x, size=H, act="tanh")
            i = fluid.layers.fill_constant([1], "int64", 0)
            n = fluid.layers.fill_constant([1], "int64", 3)
            cond = fluid.layers.less_than(i, n)
            w = fluid.layers.While(cond)
            with w.block():
                nh = fluid.layers.scale(h, scale=0.5)
                fluid.layers.assign(nh, output=h)
                fluid.layers.increment(i)
                fluid.layers.assign(
                    fluid.layers.less_than(i, n), output=cond)
            out = fluid.layers.fc(h, size=2)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(st)
    feed = {"x": np.ones((2, H), np.float32)}
    ref = np.asarray(exe.run(main, feed=feed, fetch_list=[out])[0])

    pruned = fluid.io.prune_program(main, ["x"], [out.name])
    assert any(op.type == "while" for op in pruned.global_block().ops), \
        "pruning dropped the while loop"
    got = np.asarray(exe.run(pruned, feed=feed, fetch_list=[out])[0])
    np.testing.assert_allclose(got, ref, rtol=1e-6)


def test_scan_model_inference_roundtrip():
    L, H = 4, 8
    main, st = framework.Program(), framework.Program()
    main.random_seed = st.random_seed = 5
    with framework.program_guard(main, st):
        with framework.unique_name_guard():
            x = fluid.layers.data("x", shape=[H], dtype="float32")
            w = fluid.layers.create_parameter(
                shape=[L, H, H], dtype="float32", name="inf.w",
                default_initializer=fluid.initializer.TruncatedNormal(
                    0.0, 0.2))
            h = fluid.layers.fc(x, size=H)
            scan = fluid.layers.Scan(n=L)
            with scan.block():
                wi = scan.slice_input(w)
                nh = fluid.layers.tanh(fluid.layers.matmul(h, wi))
                fluid.layers.assign(nh, output=h)
            out = fluid.layers.fc(h, size=3, act="softmax")
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(st)
    feed = {"x": np.linspace(-1, 1, 2 * H).reshape(2, H).astype(
        "float32")}
    ref = np.asarray(exe.run(main, feed=feed, fetch_list=[out])[0])

    d = tempfile.mkdtemp()
    fluid.io.save_inference_model(d, ["x"], [out], exe,
                                  main_program=main)
    # fresh scope so the load really restores the stacked param
    import paddle_tpu.core.scope as sm

    old = sm._global_scope
    sm._global_scope = sm.Scope()
    try:
        exe2 = fluid.Executor(fluid.CPUPlace())
        prog, feed_names, fetch_targets = fluid.io.load_inference_model(
            d, exe2)
        assert feed_names == ["x"]
        got = np.asarray(exe2.run(prog, feed=feed,
                                  fetch_list=fetch_targets)[0])
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
    finally:
        sm._global_scope = old
