"""slim prune + distillation tests (reference test strategy:
`contrib/slim/tests/test_*_strategy.py` run compression on a small net
and check the effect end-to-end)."""
import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework
from paddle_tpu.core.scope import global_scope
from paddle_tpu.fluid.contrib.slim.prune import (
    MagnitudePruner, StructurePruner, prune_program, sensitivity)
from paddle_tpu.fluid.contrib.slim.distillation import (
    L2Distiller, SoftLabelDistiller, FSPDistiller, merge_teacher)


def _train_mlp(steps=20, seed=0):
    r = np.random.RandomState(seed)
    feats = r.randn(64, 16).astype("float32")
    w = r.randn(16, 4).astype("float32")
    labels = feats.dot(w).argmax(1)[:, None].astype("int64")
    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup):
        with framework.unique_name_guard():
            x = fluid.layers.data("x", shape=[16], dtype="float32")
            y = fluid.layers.data("y", shape=[1], dtype="int64")
            h = fluid.layers.fc(x, 32, act="relu", name="fc1")
            logits = fluid.layers.fc(h, 4, name="fc2")
            loss = fluid.layers.mean(
                fluid.layers.loss.softmax_with_cross_entropy(logits, y))
            fluid.optimizer.SGDOptimizer(0.5).minimize(loss)
            exe = fluid.Executor()
            exe.run(startup)
            for _ in range(steps):
                out = exe.run(main, feed={"x": feats, "y": labels},
                              fetch_list=[loss])
    return main, exe, feats, labels, loss, float(np.asarray(out[0]))


def test_magnitude_pruner_sparsity():
    r = np.random.RandomState(1)
    w = r.randn(32, 32).astype("float32")
    pruned = MagnitudePruner(0.5).prune(w)
    sparsity = 1 - np.count_nonzero(pruned) / pruned.size
    assert abs(sparsity - 0.5) < 0.02
    # survivors are the largest-magnitude entries
    assert np.abs(pruned[pruned != 0]).min() >= \
        np.abs(w).ravel()[np.argsort(np.abs(w).ravel())[
            int(w.size * 0.5) - 1]]


def test_structure_pruner_axes():
    r = np.random.RandomState(2)
    w = r.randn(8, 6).astype("float32")
    p = StructurePruner({"*": 1}, {"*": "l1_norm"})
    idx = p.cal_pruned_idx("w", w, 0.5)
    assert len(idx) == 3
    scores = np.abs(w).sum(0)
    assert set(idx) == set(np.argsort(scores)[:3].tolist())
    pruned = p.prune_tensor(w, idx, 1)
    assert pruned.shape == (8, 3)
    lazy = p.prune_tensor(w, idx, 1, lazy=True)
    assert lazy.shape == w.shape and np.all(lazy[:, idx] == 0)


def test_prune_program_keeps_accuracy_reasonable():
    main, exe, feats, labels, loss, base_loss = _train_mlp()
    with framework.program_guard(main):
        stats = prune_program(main, global_scope(), {"*": 0.3})
        assert stats and all(0.2 <= s <= 0.4 for s in stats.values())
        out = exe.run(main, feed={"x": feats, "y": labels},
                      fetch_list=[loss])
    pruned_loss = float(np.asarray(out[0]))
    # 30% magnitude pruning must not destroy the model
    assert pruned_loss < base_loss * 10 + 1.0


def test_sensitivity():
    main, exe, feats, labels, loss, _ = _train_mlp(steps=10, seed=3)

    with framework.program_guard(main):
        params = [p.name for p in main.all_parameters()
                  if p.name.endswith(".w") or "w_0" in p.name or
                  p.name.startswith("fc")]
        if not params:
            params = [p.name for p in main.all_parameters()][:1]

        def ev():
            out = exe.run(main, feed={"x": feats, "y": labels},
                          fetch_list=[loss])
            return float(np.asarray(out[0]))

        sens = sensitivity(main, global_scope(), params[:1], ev,
                           ratios=(0.1, 0.9))
    (name, by_ratio), = sens.items()
    assert by_ratio[0.9] >= by_ratio[0.1] - 1e-3


def test_soft_label_distillation_trains_student():
    r = np.random.RandomState(4)
    feats = r.randn(64, 8).astype("float32")

    # teacher program: a fixed random projection
    teacher, t_startup = framework.Program(), framework.Program()
    with framework.program_guard(teacher, t_startup):
        with framework.unique_name_guard():
            x = fluid.layers.data("x", shape=[8], dtype="float32")
            t_logits = fluid.layers.fc(x, 4, name="t_fc")
            t_name = t_logits.name
        exe = fluid.Executor()
        exe.run(t_startup)

    student, s_startup = framework.Program(), framework.Program()
    with framework.program_guard(student, s_startup):
        with framework.unique_name_guard():
            x = fluid.layers.data("x", shape=[8], dtype="float32")
            s_logits = fluid.layers.fc(x, 4, name="s_fc")
            name_map = merge_teacher(teacher, student)
            dist = SoftLabelDistiller(s_logits.name, name_map[t_name],
                                      teacher_temperature=2.0,
                                      student_temperature=2.0)
            dloss = dist.distiller_loss(student)
            fluid.optimizer.AdamOptimizer(5e-2).minimize(dloss)
            exe.run(s_startup)
            losses = []
            for _ in range(25):
                out = exe.run(student, feed={"x": feats},
                              fetch_list=[dloss])
                losses.append(float(np.asarray(out[0])))
    assert losses[-1] < losses[0], (losses[0], losses[-1])


def test_l2_and_fsp_distillers_build():
    r = np.random.RandomState(5)
    feats = r.randn(8, 3, 8, 8).astype("float32")

    teacher, t_startup = framework.Program(), framework.Program()
    with framework.program_guard(teacher, t_startup):
        with framework.unique_name_guard():
            x = fluid.layers.data("x", shape=[3, 8, 8], dtype="float32")
            t1 = fluid.layers.conv2d(x, 4, 3, padding=1, name="tc1")
            t2 = fluid.layers.conv2d(t1, 4, 3, padding=1, name="tc2")
        exe = fluid.Executor()
        exe.run(t_startup)

    student, s_startup = framework.Program(), framework.Program()
    with framework.program_guard(student, s_startup):
        with framework.unique_name_guard():
            x = fluid.layers.data("x", shape=[3, 8, 8], dtype="float32")
            s1 = fluid.layers.conv2d(x, 4, 3, padding=1, name="sc1")
            s2 = fluid.layers.conv2d(s1, 4, 3, padding=1, name="sc2")
            exe.run(s_startup)
            name_map = merge_teacher(teacher, student)
            l2 = L2Distiller(s2.name, name_map[t2.name]).distiller_loss(
                student)
            fsp = FSPDistiller(
                [(s1.name, s2.name)],
                [(name_map[t1.name], name_map[t2.name])]).distiller_loss(
                student)
            out = exe.run(student, feed={"x": feats},
                          fetch_list=[l2, fsp])
    assert np.isfinite(np.asarray(out[0])).all()
    assert np.isfinite(np.asarray(out[1])).all()
