"""Serving runtime tests (paddle_tpu/serving): paged KV cache
accounting, continuous-batching correctness — token streams
BIT-IDENTICAL to sequential per-request decoding and exact against the
dense no-paging reference — block-table edge cases (page-boundary
crossing, chunked prefill), full-pool admission backpressure, cancel
eviction, AOT warmup all-hit through the persistent compile cache,
the registry-assembled bench ``serving`` block, telemetry schema
validity of serving_request/serving_step, and the tpu-lint
serving_decode exemplar's deliberate-defect twin (a fetch seeded into
the decode scan must fire the host-sync checker)."""
import json
import os
import sys

import numpy as np
import pytest

from paddle_tpu import observability as obs
from paddle_tpu import serving

_DIR = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_DIR)

MODEL_CFG = serving.TinyLMConfig(vocab=48, embed=24, layers=2, heads=2,
                                 kv_heads=2, head_dim=8, ffn=48,
                                 max_seq=48)
#: ONE model instance per run: engines over it share the jitted step,
#: so the many-engine tests don't recompile per engine
_MODEL = serving.TinyDecoderLM(MODEL_CFG)
_PARAMS = None


def _params():
    global _PARAMS
    if _PARAMS is None:
        _PARAMS = _MODEL.init_params(seed=3)
    return _PARAMS


def _engine(**over):
    cfg = dict(num_pages=96, page_size=4, max_seqs=6)
    cfg.update(over)
    return serving.Engine(_MODEL, params=_params(),
                          config=serving.EngineConfig(**cfg))


@pytest.fixture(autouse=True)
def _fresh_registry():
    obs.reset_registry()
    yield
    obs.reset_registry()


# -- paged KV cache ---------------------------------------------------------

def test_kv_cache_alloc_free_occupancy():
    cfg = serving.KVCacheConfig(num_pages=10, page_size=4,
                                pages_per_seq=5, num_layers=1,
                                num_kv_heads=1, head_dim=8)
    kv = serving.PagedKVCache(cfg)
    assert kv.pages_free == 10 and kv.occupancy == 0.0
    p0 = kv.alloc(0, 9)             # ceil(9/4) = 3 pages
    assert len(p0) == 3 and kv.pages_in_use == 3
    p1 = kv.alloc(1, 4)             # exactly one page boundary
    assert len(p1) == 1
    assert set(p0).isdisjoint(p1)
    assert kv.block_table(0) == p0
    assert kv.peak_pages_in_use == 4
    assert kv.free(0) == 3
    assert kv.pages_in_use == 1 and kv.free(0) == 0  # idempotent
    with pytest.raises(ValueError, match="already"):
        kv.alloc(1, 2)
    with pytest.raises(ValueError, match="max_context"):
        kv.alloc(2, 21)             # > pages_per_seq * page_size


def test_kv_cache_admission_backpressure():
    cfg = serving.KVCacheConfig(num_pages=4, page_size=4,
                                pages_per_seq=4, num_layers=1,
                                num_kv_heads=1, head_dim=8)
    kv = serving.PagedKVCache(cfg)
    assert kv.alloc(0, 12) is not None      # 3 of 4 pages
    assert not kv.can_admit(8)
    assert kv.alloc(1, 8) is None           # pool can't cover 2 pages
    assert kv.alloc(2, 4) is not None       # but 1 page still fits
    kv.free(0)
    assert kv.can_admit(8)


# -- engine correctness -----------------------------------------------------

def test_single_request_matches_dense_reference():
    """Engine greedy stream == dense (no paging, no engine) decode,
    including EOS stop."""
    eng = _engine()
    r = np.random.RandomState(0)
    prompt = r.randint(0, 48, size=7).astype(np.int32)
    req = eng.submit(prompt, max_new_tokens=10)
    eng.run_until_idle()
    ref = serving.dense_decode_reference(_MODEL, _params(), prompt, 10)
    assert req.output_tokens == ref
    # EOS: pick the first generated token as eos -> stream stops at 1
    eos = ref[0]
    eng2 = _engine()
    req2 = eng2.submit(prompt, max_new_tokens=10, eos_id=eos)
    eng2.run_until_idle()
    assert req2.output_tokens == [eos]
    assert req2.state == serving.RequestState.FINISHED


def test_continuous_batching_bit_identical_to_sequential():
    """THE acceptance property: staggered concurrent requests through
    the continuous-batching engine produce token streams bit-identical
    to decoding each request alone (fresh engine, same weights)."""
    r = np.random.RandomState(1)
    prompts = [r.randint(0, 48, size=n).astype(np.int32)
               for n in (5, 17, 3, 9, 21, 2, 7)]
    maxnew = [6, 9, 4, 12, 5, 8, 7]
    arrive = [0, 0, 1, 2, 2, 5, 7]

    eng = _engine()
    reqs, i, step = [], 0, 0
    while i < len(prompts) or not eng.scheduler.idle:
        while i < len(prompts) and arrive[i] <= step:
            reqs.append(eng.submit(prompts[i], max_new_tokens=maxnew[i]))
            i += 1
        eng.step()
        step += 1
    batched = [list(q.output_tokens) for q in reqs]
    assert all(len(b) == m for b, m in zip(batched, maxnew))

    sequential = []
    for p, m in zip(prompts, maxnew):
        e = _engine()
        q = e.submit(p, max_new_tokens=m)
        e.run_until_idle()
        sequential.append(list(q.output_tokens))
    assert batched == sequential


def test_page_boundary_crossing_and_chunked_prefill():
    """A prompt longer than the largest prefill bucket (16 here, after
    the max-context clamp) prefills in chunks, and decode repeatedly
    crosses page boundaries (page_size=4) — stream still exact vs the
    dense reference."""
    eng = _engine()
    assert eng.plan.max_prefill_chunk == 16
    r = np.random.RandomState(2)
    prompt = r.randint(0, 48, size=21).astype(np.int32)  # 2 chunks
    req = eng.submit(prompt, max_new_tokens=13)          # crosses pages
    eng.run_until_idle()
    ref = serving.dense_decode_reference(_MODEL, _params(), prompt, 13)
    assert req.output_tokens == ref
    assert eng.kv.pages_in_use == 0  # retired -> freed


def test_full_pool_admission_backpressure():
    """Pool sized for ~1 request: later submissions queue (depth gauge
    rises) and admit only as earlier requests retire; all finish with
    the same streams they'd produce alone."""
    eng = _engine(num_pages=6, max_seqs=6)  # 6*4 = 24 tokens of pool
    r = np.random.RandomState(3)
    prompts = [r.randint(0, 48, size=8).astype(np.int32)
               for _ in range(3)]
    reqs = [eng.submit(p, max_new_tokens=8) for p in prompts]  # 4 pages
    depth_seen = 0
    steps = 0
    while not eng.scheduler.idle and steps < 200:
        stats = eng.step()
        depth_seen = max(depth_seen, stats["queue_depth"])
        assert eng.kv.pages_in_use <= 6
        steps += 1
    assert depth_seen >= 1  # backpressure actually engaged
    assert all(q.state == serving.RequestState.FINISHED for q in reqs)
    solo = []
    for p in prompts:
        e = _engine()
        q = e.submit(p, max_new_tokens=8)
        e.run_until_idle()
        solo.append(list(q.output_tokens))
    assert [list(q.output_tokens) for q in reqs] == solo


def test_cancel_evicts_pages_mid_decode():
    eng = _engine()
    r = np.random.RandomState(4)
    keep = eng.submit(r.randint(0, 48, size=6).astype(np.int32),
                      max_new_tokens=20)
    kill = eng.submit(r.randint(0, 48, size=6).astype(np.int32),
                      max_new_tokens=20)
    for _ in range(3):
        eng.step()
    assert kill.output_tokens  # decoding underway
    in_use_before = eng.kv.pages_in_use
    eng.cancel(kill)
    eng.step()  # retire happens at the step boundary
    assert kill.state == serving.RequestState.CANCELLED
    assert eng.kv.pages_in_use < in_use_before
    got = list(kill.stream())  # stream closed, yields the partial set
    assert got == kill.output_tokens
    eng.run_until_idle()
    assert keep.state == serving.RequestState.FINISHED
    assert len(keep.output_tokens) == 20
    assert eng.kv.pages_in_use == 0
    # the cancelled request's telemetry says cancelled
    snap = obs.registry().snapshot()
    assert snap["counters"]["serving.requests_cancelled"] == 1


def test_submit_validation_and_queue_bound():
    eng = _engine(max_queue=1)
    with pytest.raises(ValueError, match="empty"):
        eng.submit(np.zeros((0,), np.int32))
    with pytest.raises(ValueError, match="max context"):
        eng.submit(np.zeros((40,), np.int32), max_new_tokens=40)
    eng.submit(np.zeros((4,), np.int32), max_new_tokens=2)
    with pytest.raises(RuntimeError, match="queue full"):
        eng.submit(np.zeros((4,), np.int32), max_new_tokens=2)
    eng.close()
    with pytest.raises(RuntimeError, match="closed"):
        eng.submit(np.zeros((4,), np.int32))


def test_over_length_request_rejected_at_model_max_seq():
    """Page rounding makes the pool bound looser than the model's
    max_seq (ceil(20/8)*8 = 24): admission must reject against the
    MODEL bound, or positions would clip and KV slots collide."""
    model = serving.TinyDecoderLM(serving.TinyLMConfig(
        vocab=32, embed=16, layers=1, heads=2, kv_heads=2, head_dim=8,
        ffn=32, max_seq=20))
    eng = serving.Engine(model, config=serving.EngineConfig(
        num_pages=16, page_size=8, max_seqs=2))
    assert eng.kv.config.max_context == 24  # pool bound, rounded up
    with pytest.raises(ValueError, match="max context"):
        eng.submit(np.zeros((15,), np.int32), max_new_tokens=7)  # 22>20
    eng.submit(np.zeros((15,), np.int32), max_new_tokens=5)      # ==20


def test_cancel_while_queued_publishes_event():
    """A request cancelled BEFORE admission still produces its
    serving_request event and the cancelled counter — submitted ==
    finished + cancelled must reconcile for the bench block."""
    eng = _engine(max_seqs=2)
    a = eng.submit(np.arange(4, dtype=np.int32), max_new_tokens=6)
    b = eng.submit(np.arange(5, dtype=np.int32), max_new_tokens=6)
    c = eng.submit(np.arange(6, dtype=np.int32), max_new_tokens=6)
    eng.step()  # a, b admitted; c queued behind max_seqs
    assert c.state == serving.RequestState.QUEUED
    eng.cancel(c)
    eng.step()
    assert c.state == serving.RequestState.CANCELLED
    eng.run_until_idle()
    reg = obs.registry()
    snap = reg.snapshot()["counters"]
    assert snap["serving.requests_submitted"] == 3
    assert snap["serving.requests_finished"] == 2
    assert snap["serving.requests_cancelled"] == 1
    assert snap["event.serving_request"] == 3
    assert a.state == b.state == serving.RequestState.FINISHED


def test_attention_impl_conflict_raises():
    model = serving.TinyDecoderLM(serving.TinyLMConfig(
        vocab=32, embed=16, layers=1, heads=2, kv_heads=2, head_dim=8,
        ffn=32, max_seq=16), attention_impl="reference")
    with pytest.raises(ValueError, match="conflicts"):
        serving.Engine(model, config=serving.EngineConfig(
            num_pages=8, page_size=4, max_seqs=2,
            attention_impl="kernel"))


def test_close_cancels_everything():
    eng = _engine()
    a = eng.submit(np.arange(5, dtype=np.int32), max_new_tokens=30)
    b = eng.submit(np.arange(4, dtype=np.int32), max_new_tokens=30)
    eng.step()
    eng.close()
    assert a.state == serving.RequestState.CANCELLED
    assert b.state == serving.RequestState.CANCELLED
    assert eng.kv.pages_in_use == 0
    assert a.result() == a.output_tokens  # streams closed, no hang


# -- AOT warmup through the persistent compile cache ------------------------

def test_warmup_all_hit_on_restart(tmp_path):
    """Cold engine warmup: every bucket a classified MISS; a second
    engine (the restarted serving process) warms ALL-HIT from the
    fingerprint index — with serving_decode/serving_prefill sources."""
    from paddle_tpu.fluid import compile_cache as cc
    from paddle_tpu.utils.flags import get_flag, set_flags

    old = get_flag("FLAGS_tpu_compile_cache_dir")
    set_flags({"FLAGS_tpu_compile_cache_dir": str(tmp_path / "cc")})
    cc._reset_for_tests()
    try:
        model = serving.TinyDecoderLM(serving.TinyLMConfig(
            vocab=32, embed=16, layers=1, heads=2, kv_heads=2,
            head_dim=8, ffn=32, max_seq=16))
        cfg = serving.EngineConfig(num_pages=16, page_size=4,
                                   max_seqs=2)
        cold = serving.Engine(model, config=cfg, seed=0).warmup()
        assert cold["misses"] == len(cold["buckets"])
        assert cold["hits"] == 0 and cold["unclassified"] == 0
        warm = serving.Engine(serving.TinyDecoderLM(model.config),
                              config=cfg, seed=0).warmup()
        assert warm["hits"] == len(warm["buckets"])
        assert warm["misses"] == 0
        reg = obs.registry()
        assert reg.counter("event.compile_cache").value >= \
            2 * len(cold["buckets"])
    finally:
        cc.disable()
        set_flags({"FLAGS_tpu_compile_cache_dir": old})
        cc._reset_for_tests()


# -- bench block + telemetry ------------------------------------------------

def test_serving_bench_block_assembled_from_registry(tmp_path):
    """Tier-1 CI leg: the synthetic multi-tenant trace runs, the
    ``serving`` block is ASSEMBLED FROM THE REGISTRY (block dict ==
    registry().blocks()["serving"]), and it carries tokens/sec +
    p50/p99 + queue depth."""
    from paddle_tpu.observability import publish

    reg = obs.configure(telemetry_dir=str(tmp_path), rank=0)
    eng = _engine(max_seqs=4)
    trace = serving.synthetic_trace(n_requests=10, n_tenants=3, seed=7,
                                    vocab=48, prompt_range=(3, 14),
                                    output_range=(3, 8))
    summary = serving.run_trace(eng, trace, warmup=False)
    assert summary["finished"] == 10
    block = publish.serving_block()
    assert block is not None
    assert reg.blocks()["serving"] == block
    assert block["tokens_per_sec"] == summary["tokens_per_sec"] > 0
    assert block["requests_finished"] == 10
    assert block["latency_ms"]["p50"] is not None
    assert block["latency_ms"]["p99"] >= block["latency_ms"]["p50"]
    assert block["queue_depth"]["max"] is not None
    assert block["tokens_generated"] == summary["tokens_generated"]


def test_serving_block_none_without_engine():
    from paddle_tpu.observability import publish

    assert publish.serving_block() is None


def test_serving_events_schema_valid(tmp_path):
    """Every record the engine writes — serving_request /
    serving_step / steps — validates against the locked telemetry
    schema, and the per-event required fields are present."""
    obs.configure(telemetry_dir=str(tmp_path), rank=0)
    eng = _engine(max_seqs=4)
    reqs = [eng.submit(np.arange(1 + i, dtype=np.int32) % 48,
                       max_new_tokens=3, tenant="t%d" % (i % 2))
            for i in range(3)]
    eng.run_until_idle()
    eng.cancel(reqs[0])  # already finished: no-op event-wise
    recs = []
    for name in os.listdir(tmp_path):
        if name.endswith(".jsonl"):
            with open(os.path.join(tmp_path, name)) as f:
                recs.extend(json.loads(ln) for ln in f if ln.strip())
    assert recs
    problems = obs.validate_records(recs, obs.load_schema(
        os.path.join(_REPO, "tools", "telemetry_schema.json")))
    assert problems == []
    kinds = {}
    for r in recs:
        if r.get("kind") == "event":
            kinds.setdefault(r["event"], []).append(r)
    assert len(kinds.get("serving_request", [])) == 3
    assert kinds["serving_step"]
    req_ev = kinds["serving_request"][0]
    assert req_ev["status"] == "finished"
    assert req_ev["output_tokens"] == 3
    st_ev = kinds["serving_step"][0]
    assert {"running", "queue_depth", "kv_blocks_in_use"} <= set(st_ev)


def test_bench_serving_leg_inprocess():
    """bench.py's --serving leg returns the registry-assembled block
    and a tokens/sec headline (run in-process, tiny trace). The leg
    arms the repo-local compile cache — restore the flag/jax config so
    later tests keep their donation behavior."""
    from paddle_tpu.fluid import compile_cache as cc
    from paddle_tpu.utils.flags import get_flag, set_flags

    sys.path.insert(0, _REPO)
    try:
        import bench
    finally:
        sys.path.pop(0)
    old = get_flag("FLAGS_tpu_compile_cache_dir")
    try:
        out = bench._bench_serving(n_requests=4, seed=1)
    finally:
        cc.disable()
        set_flags({"FLAGS_tpu_compile_cache_dir": old})
        cc._reset_for_tests()
    assert out["metric"] == "serving_tokens_per_sec"
    assert out["value"] > 0
    assert out["serving"]["requests_submitted"] == 4
    assert out["serving"] == obs.registry().blocks()["serving"]


# -- lint: the decode loop has no per-token host sync -----------------------

def _tpu_lint():
    sys.path.insert(0, os.path.join(_REPO, "tools"))
    try:
        import tpu_lint
    finally:
        sys.path.pop(0)
    return tpu_lint


def test_serving_decode_exemplar_lints_clean():
    from paddle_tpu import analysis

    tpu_lint = _tpu_lint()
    prog, _ = tpu_lint.build_serving_decode()
    findings = analysis.run_static_checks(prog)
    s = analysis.summarize(findings)
    assert s["errors"] == 0, s["findings"]
    assert s["warnings"] == 0, s["findings"]


def test_fetch_in_decode_scan_fires_host_sync_error():
    """The deliberate-defect twin: seed a fetch INTO the decode scan
    body — the PR 5 host-sync checker must fire an ERROR anchored at
    the sub-block op (a per-token host sync would serialize the whole
    decode loop)."""
    from paddle_tpu import analysis

    tpu_lint = _tpu_lint()
    prog, _ = tpu_lint.build_serving_decode()
    scan_op = next(op for op in prog.global_block().ops
                   if op.type == "scan")
    sub = prog.block(scan_op.attrs["sub_block"])
    victim = sub.ops[0].output_arg_names[0]
    sub.append_op(type="fetch", inputs={"X": [victim]}, outputs={},
                  attrs={})
    findings = analysis.run_static_checks(prog)
    errs = [f for f in findings
            if f.checker == "host-sync" and f.severity == "error"]
    assert errs, findings
    assert errs[0].op_type == "fetch"
    assert errs[0].block_idx == sub.idx  # anchored inside the loop body
    assert "every iteration" in errs[0].message
