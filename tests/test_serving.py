"""Serving runtime tests (paddle_tpu/serving): paged KV cache
accounting, continuous-batching correctness — token streams
BIT-IDENTICAL to sequential per-request decoding and exact against the
dense no-paging reference — block-table edge cases (page-boundary
crossing, chunked prefill), full-pool admission backpressure, cancel
eviction, AOT warmup all-hit through the persistent compile cache,
the registry-assembled bench ``serving`` block, telemetry schema
validity of serving_request/serving_step, and the tpu-lint
serving_decode exemplar's deliberate-defect twin (a fetch seeded into
the decode scan must fire the host-sync checker)."""
import json
import os
import sys

import numpy as np
import pytest

from paddle_tpu import observability as obs
from paddle_tpu import serving

_DIR = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_DIR)

MODEL_CFG = serving.TinyLMConfig(vocab=48, embed=24, layers=2, heads=2,
                                 kv_heads=2, head_dim=8, ffn=48,
                                 max_seq=48)
#: ONE model instance per run: engines over it share the jitted step,
#: so the many-engine tests don't recompile per engine
_MODEL = serving.TinyDecoderLM(MODEL_CFG)
_PARAMS = None


def _params():
    global _PARAMS
    if _PARAMS is None:
        _PARAMS = _MODEL.init_params(seed=3)
    return _PARAMS


def _engine(**over):
    cfg = dict(num_pages=96, page_size=4, max_seqs=6)
    cfg.update(over)
    return serving.Engine(_MODEL, params=_params(),
                          config=serving.EngineConfig(**cfg))


@pytest.fixture(autouse=True)
def _fresh_registry():
    obs.reset_registry()
    yield
    obs.reset_registry()


# -- paged KV cache ---------------------------------------------------------

def test_kv_cache_alloc_free_occupancy():
    cfg = serving.KVCacheConfig(num_pages=10, page_size=4,
                                pages_per_seq=5, num_layers=1,
                                num_kv_heads=1, head_dim=8)
    kv = serving.PagedKVCache(cfg)
    assert kv.pages_free == 10 and kv.occupancy == 0.0
    p0 = kv.alloc(0, 9)             # ceil(9/4) = 3 pages
    assert len(p0) == 3 and kv.pages_in_use == 3
    p1 = kv.alloc(1, 4)             # exactly one page boundary
    assert len(p1) == 1
    assert set(p0).isdisjoint(p1)
    assert kv.block_table(0) == p0
    assert kv.peak_pages_in_use == 4
    assert kv.free(0) == 3
    assert kv.pages_in_use == 1 and kv.free(0) == 0  # idempotent
    with pytest.raises(ValueError, match="already"):
        kv.alloc(1, 2)
    with pytest.raises(ValueError, match="max_context"):
        kv.alloc(2, 21)             # > pages_per_seq * page_size


def test_kv_cache_admission_backpressure():
    cfg = serving.KVCacheConfig(num_pages=4, page_size=4,
                                pages_per_seq=4, num_layers=1,
                                num_kv_heads=1, head_dim=8)
    kv = serving.PagedKVCache(cfg)
    assert kv.alloc(0, 12) is not None      # 3 of 4 pages
    assert not kv.can_admit(8)
    assert kv.alloc(1, 8) is None           # pool can't cover 2 pages
    assert kv.alloc(2, 4) is not None       # but 1 page still fits
    kv.free(0)
    assert kv.can_admit(8)


# -- engine correctness -----------------------------------------------------

def test_single_request_matches_dense_reference():
    """Engine greedy stream == dense (no paging, no engine) decode,
    including EOS stop."""
    eng = _engine()
    r = np.random.RandomState(0)
    prompt = r.randint(0, 48, size=7).astype(np.int32)
    req = eng.submit(prompt, max_new_tokens=10)
    eng.run_until_idle()
    ref = serving.dense_decode_reference(_MODEL, _params(), prompt, 10)
    assert req.output_tokens == ref
    # EOS: pick the first generated token as eos -> stream stops at 1
    eos = ref[0]
    eng2 = _engine()
    req2 = eng2.submit(prompt, max_new_tokens=10, eos_id=eos)
    eng2.run_until_idle()
    assert req2.output_tokens == [eos]
    assert req2.state == serving.RequestState.FINISHED


def test_continuous_batching_bit_identical_to_sequential():
    """THE acceptance property: staggered concurrent requests through
    the continuous-batching engine produce token streams bit-identical
    to decoding each request alone (fresh engine, same weights)."""
    r = np.random.RandomState(1)
    prompts = [r.randint(0, 48, size=n).astype(np.int32)
               for n in (5, 17, 3, 9, 21, 2, 7)]
    maxnew = [6, 9, 4, 12, 5, 8, 7]
    arrive = [0, 0, 1, 2, 2, 5, 7]

    eng = _engine()
    reqs, i, step = [], 0, 0
    while i < len(prompts) or not eng.scheduler.idle:
        while i < len(prompts) and arrive[i] <= step:
            reqs.append(eng.submit(prompts[i], max_new_tokens=maxnew[i]))
            i += 1
        eng.step()
        step += 1
    batched = [list(q.output_tokens) for q in reqs]
    assert all(len(b) == m for b, m in zip(batched, maxnew))

    sequential = []
    for p, m in zip(prompts, maxnew):
        e = _engine()
        q = e.submit(p, max_new_tokens=m)
        e.run_until_idle()
        sequential.append(list(q.output_tokens))
    assert batched == sequential


def test_page_boundary_crossing_and_chunked_prefill():
    """A prompt longer than the largest prefill bucket (16 here, after
    the max-context clamp) prefills in chunks, and decode repeatedly
    crosses page boundaries (page_size=4) — stream still exact vs the
    dense reference."""
    eng = _engine()
    assert eng.plan.max_prefill_chunk == 16
    r = np.random.RandomState(2)
    prompt = r.randint(0, 48, size=21).astype(np.int32)  # 2 chunks
    req = eng.submit(prompt, max_new_tokens=13)          # crosses pages
    eng.run_until_idle()
    ref = serving.dense_decode_reference(_MODEL, _params(), prompt, 13)
    assert req.output_tokens == ref
    assert eng.kv.pages_in_use == 0  # retired -> freed


def test_full_pool_admission_backpressure():
    """Pool sized for ~1 request: later submissions queue (depth gauge
    rises) and admit only as earlier requests retire; all finish with
    the same streams they'd produce alone."""
    eng = _engine(num_pages=6, max_seqs=6)  # 6*4 = 24 tokens of pool
    r = np.random.RandomState(3)
    prompts = [r.randint(0, 48, size=8).astype(np.int32)
               for _ in range(3)]
    reqs = [eng.submit(p, max_new_tokens=8) for p in prompts]  # 4 pages
    depth_seen = 0
    steps = 0
    while not eng.scheduler.idle and steps < 200:
        stats = eng.step()
        depth_seen = max(depth_seen, stats["queue_depth"])
        assert eng.kv.pages_in_use <= 6
        steps += 1
    assert depth_seen >= 1  # backpressure actually engaged
    assert all(q.state == serving.RequestState.FINISHED for q in reqs)
    solo = []
    for p in prompts:
        e = _engine()
        q = e.submit(p, max_new_tokens=8)
        e.run_until_idle()
        solo.append(list(q.output_tokens))
    assert [list(q.output_tokens) for q in reqs] == solo


def test_cancel_evicts_pages_mid_decode():
    eng = _engine()
    r = np.random.RandomState(4)
    keep = eng.submit(r.randint(0, 48, size=6).astype(np.int32),
                      max_new_tokens=20)
    kill = eng.submit(r.randint(0, 48, size=6).astype(np.int32),
                      max_new_tokens=20)
    for _ in range(3):
        eng.step()
    assert kill.output_tokens  # decoding underway
    in_use_before = eng.kv.pages_in_use
    eng.cancel(kill)
    eng.step()  # retire happens at the step boundary
    assert kill.state == serving.RequestState.CANCELLED
    assert eng.kv.pages_in_use < in_use_before
    got = list(kill.stream())  # stream closed, yields the partial set
    assert got == kill.output_tokens
    eng.run_until_idle()
    assert keep.state == serving.RequestState.FINISHED
    assert len(keep.output_tokens) == 20
    assert eng.kv.pages_in_use == 0
    # the cancelled request's telemetry says cancelled
    snap = obs.registry().snapshot()
    assert snap["counters"]["serving.requests_cancelled"] == 1


def test_submit_validation_and_queue_bound():
    eng = _engine(max_queue=1)
    with pytest.raises(ValueError, match="empty"):
        eng.submit(np.zeros((0,), np.int32))
    with pytest.raises(ValueError, match="max context"):
        eng.submit(np.zeros((40,), np.int32), max_new_tokens=40)
    eng.submit(np.zeros((4,), np.int32), max_new_tokens=2)
    with pytest.raises(RuntimeError, match="queue full"):
        eng.submit(np.zeros((4,), np.int32), max_new_tokens=2)
    eng.close()
    with pytest.raises(RuntimeError, match="closed"):
        eng.submit(np.zeros((4,), np.int32))


def test_over_length_request_rejected_at_model_max_seq():
    """Page rounding makes the pool bound looser than the model's
    max_seq (ceil(20/8)*8 = 24): admission must reject against the
    MODEL bound, or positions would clip and KV slots collide."""
    model = serving.TinyDecoderLM(serving.TinyLMConfig(
        vocab=32, embed=16, layers=1, heads=2, kv_heads=2, head_dim=8,
        ffn=32, max_seq=20))
    eng = serving.Engine(model, config=serving.EngineConfig(
        num_pages=16, page_size=8, max_seqs=2))
    assert eng.kv.config.max_context == 24  # pool bound, rounded up
    with pytest.raises(ValueError, match="max context"):
        eng.submit(np.zeros((15,), np.int32), max_new_tokens=7)  # 22>20
    eng.submit(np.zeros((15,), np.int32), max_new_tokens=5)      # ==20


def test_cancel_while_queued_publishes_event():
    """A request cancelled BEFORE admission still produces its
    serving_request event and the cancelled counter — submitted ==
    finished + cancelled must reconcile for the bench block."""
    eng = _engine(max_seqs=2)
    a = eng.submit(np.arange(4, dtype=np.int32), max_new_tokens=6)
    b = eng.submit(np.arange(5, dtype=np.int32), max_new_tokens=6)
    c = eng.submit(np.arange(6, dtype=np.int32), max_new_tokens=6)
    eng.step()  # a, b admitted; c queued behind max_seqs
    assert c.state == serving.RequestState.QUEUED
    eng.cancel(c)
    eng.step()
    assert c.state == serving.RequestState.CANCELLED
    eng.run_until_idle()
    reg = obs.registry()
    snap = reg.snapshot()["counters"]
    assert snap["serving.requests_submitted"] == 3
    assert snap["serving.requests_finished"] == 2
    assert snap["serving.requests_cancelled"] == 1
    assert snap["event.serving_request"] == 3
    assert a.state == b.state == serving.RequestState.FINISHED


def test_attention_impl_conflict_raises():
    model = serving.TinyDecoderLM(serving.TinyLMConfig(
        vocab=32, embed=16, layers=1, heads=2, kv_heads=2, head_dim=8,
        ffn=32, max_seq=16), attention_impl="reference")
    with pytest.raises(ValueError, match="conflicts"):
        serving.Engine(model, config=serving.EngineConfig(
            num_pages=8, page_size=4, max_seqs=2,
            attention_impl="kernel"))


def test_close_cancels_everything():
    eng = _engine()
    a = eng.submit(np.arange(5, dtype=np.int32), max_new_tokens=30)
    b = eng.submit(np.arange(4, dtype=np.int32), max_new_tokens=30)
    eng.step()
    eng.close()
    assert a.state == serving.RequestState.CANCELLED
    assert b.state == serving.RequestState.CANCELLED
    assert eng.kv.pages_in_use == 0
    assert a.result() == a.output_tokens  # streams closed, no hang


# -- AOT warmup through the persistent compile cache ------------------------

def test_warmup_all_hit_on_restart(tmp_path):
    """Cold engine warmup: every bucket a classified MISS; a second
    engine (the restarted serving process) warms ALL-HIT from the
    fingerprint index — with serving_decode/serving_prefill sources."""
    from paddle_tpu.fluid import compile_cache as cc
    from paddle_tpu.utils.flags import get_flag, set_flags

    old = get_flag("FLAGS_tpu_compile_cache_dir")
    set_flags({"FLAGS_tpu_compile_cache_dir": str(tmp_path / "cc")})
    cc._reset_for_tests()
    try:
        model = serving.TinyDecoderLM(serving.TinyLMConfig(
            vocab=32, embed=16, layers=1, heads=2, kv_heads=2,
            head_dim=8, ffn=32, max_seq=16))
        cfg = serving.EngineConfig(num_pages=16, page_size=4,
                                   max_seqs=2)
        cold = serving.Engine(model, config=cfg, seed=0).warmup()
        assert cold["misses"] == len(cold["buckets"])
        assert cold["hits"] == 0 and cold["unclassified"] == 0
        warm = serving.Engine(serving.TinyDecoderLM(model.config),
                              config=cfg, seed=0).warmup()
        assert warm["hits"] == len(warm["buckets"])
        assert warm["misses"] == 0
        reg = obs.registry()
        assert reg.counter("event.compile_cache").value >= \
            2 * len(cold["buckets"])
    finally:
        cc.disable()
        set_flags({"FLAGS_tpu_compile_cache_dir": old})
        cc._reset_for_tests()


# -- bench block + telemetry ------------------------------------------------

def test_serving_bench_block_assembled_from_registry(tmp_path):
    """Tier-1 CI leg: the synthetic multi-tenant trace runs, the
    ``serving`` block is ASSEMBLED FROM THE REGISTRY (block dict ==
    registry().blocks()["serving"]), and it carries tokens/sec +
    p50/p99 + queue depth."""
    from paddle_tpu.observability import publish

    reg = obs.configure(telemetry_dir=str(tmp_path), rank=0)
    eng = _engine(max_seqs=4)
    trace = serving.synthetic_trace(n_requests=10, n_tenants=3, seed=7,
                                    vocab=48, prompt_range=(3, 14),
                                    output_range=(3, 8))
    summary = serving.run_trace(eng, trace, warmup=False)
    assert summary["finished"] == 10
    block = publish.serving_block()
    assert block is not None
    assert reg.blocks()["serving"] == block
    assert block["tokens_per_sec"] == summary["tokens_per_sec"] > 0
    assert block["requests_finished"] == 10
    assert block["latency_ms"]["p50"] is not None
    assert block["latency_ms"]["p99"] >= block["latency_ms"]["p50"]
    assert block["queue_depth"]["max"] is not None
    assert block["tokens_generated"] == summary["tokens_generated"]


def test_serving_block_none_without_engine():
    from paddle_tpu.observability import publish

    assert publish.serving_block() is None


def test_serving_events_schema_valid(tmp_path):
    """Every record the engine writes — serving_request /
    serving_step / steps — validates against the locked telemetry
    schema, and the per-event required fields are present."""
    obs.configure(telemetry_dir=str(tmp_path), rank=0)
    eng = _engine(max_seqs=4)
    reqs = [eng.submit(np.arange(1 + i, dtype=np.int32) % 48,
                       max_new_tokens=3, tenant="t%d" % (i % 2))
            for i in range(3)]
    eng.run_until_idle()
    eng.cancel(reqs[0])  # already finished: no-op event-wise
    recs = []
    for name in os.listdir(tmp_path):
        if name.endswith(".jsonl"):
            with open(os.path.join(tmp_path, name)) as f:
                recs.extend(json.loads(ln) for ln in f if ln.strip())
    assert recs
    problems = obs.validate_records(recs, obs.load_schema(
        os.path.join(_REPO, "tools", "telemetry_schema.json")))
    assert problems == []
    kinds = {}
    for r in recs:
        if r.get("kind") == "event":
            kinds.setdefault(r["event"], []).append(r)
    assert len(kinds.get("serving_request", [])) == 3
    assert kinds["serving_step"]
    req_ev = kinds["serving_request"][0]
    assert req_ev["status"] == "finished"
    assert req_ev["output_tokens"] == 3
    st_ev = kinds["serving_step"][0]
    assert {"running", "queue_depth", "kv_blocks_in_use",
            "kv_page_dtype", "kv_page_bytes",
            "resident_batch"} <= set(st_ev)
    assert st_ev["kv_page_dtype"] == "float32"


def test_bench_serving_leg_inprocess():
    """bench.py's --serving leg returns the registry-assembled block
    and a tokens/sec headline (run in-process, tiny trace). The leg
    arms the repo-local compile cache — restore the flag/jax config so
    later tests keep their donation behavior."""
    from paddle_tpu.fluid import compile_cache as cc
    from paddle_tpu.utils.flags import get_flag, set_flags

    sys.path.insert(0, _REPO)
    try:
        import bench
    finally:
        sys.path.pop(0)
    old = get_flag("FLAGS_tpu_compile_cache_dir")
    try:
        out = bench._bench_serving(n_requests=4, seed=1)
    finally:
        cc.disable()
        set_flags({"FLAGS_tpu_compile_cache_dir": old})
        cc._reset_for_tests()
    assert out["metric"] == "serving_tokens_per_sec"
    assert out["value"] > 0
    assert out["serving"]["requests_submitted"] == 4
    assert out["serving"] == obs.registry().blocks()["serving"]


# -- quantization tier: int8 KV pages + PTQ weights -------------------------

def test_int8_attention_bounded_error_and_kernel_parity():
    """int8 pages with per-slot scales: the reference attention stays
    within bounded error of the float pages, and the Pallas kernel
    (interpret mode on CPU) matches the quantized reference."""
    from paddle_tpu.ops.pallas.ragged_paged_attention import (
        ragged_paged_attention, ragged_paged_attention_reference)

    r = np.random.RandomState(0)
    S, Q, Hq, Hkv, D = 3, 4, 4, 2, 16
    P, page, npp = 8, 8, 4
    q = r.standard_normal((S, Q, Hq, D)).astype(np.float32)
    kf = r.standard_normal((P, page, Hkv, D)).astype(np.float32)
    vf = r.standard_normal((P, page, Hkv, D)).astype(np.float32)
    tbl = r.randint(0, P, (S, npp)).astype(np.int32)
    ctx = np.array([page * 2, 5, page * 4], np.int32)
    ql = np.array([2, 4, 1], np.int32)
    s_k = np.maximum(np.abs(kf).max(axis=(2, 3)), 1e-9) / 127.0
    s_v = np.maximum(np.abs(vf).max(axis=(2, 3)), 1e-9) / 127.0
    kq = np.clip(np.round(kf / s_k[:, :, None, None]), -127,
                 127).astype(np.int8)
    vq = np.clip(np.round(vf / s_v[:, :, None, None]), -127,
                 127).astype(np.int8)

    o_f = ragged_paged_attention_reference(q, kf, vf, tbl, ctx, ql)
    o_q = ragged_paged_attention_reference(q, kq, vq, tbl, ctx, ql,
                                           k_scale=s_k, v_scale=s_v)
    err = float(np.max(np.abs(np.asarray(o_f) - np.asarray(o_q))))
    assert err < 0.05, err
    o_ker = ragged_paged_attention(q, kq, vq, tbl, ctx, ql,
                                   impl="kernel", k_scale=s_k,
                                   v_scale=s_v)
    d = float(np.max(np.abs(np.asarray(o_ker) - np.asarray(o_q))))
    assert d < 1e-5, d
    # scale arrays are both-or-neither
    with pytest.raises(ValueError, match="k_scale"):
        ragged_paged_attention_reference(q, kq, vq, tbl, ctx, ql,
                                         k_scale=s_k)


def test_int8_page_roundtrip_bit_exact():
    """Values of the form n * stored_scale (n integer in [-127, 127])
    survive the quantize -> dequantize page round-trip bit-exactly."""
    r = np.random.RandomState(1)
    P, page, Hkv, D = 8, 8, 2, 16
    sex = np.full((P, page), 2.0 / 127.0, np.float32)
    n = r.randint(-127, 128, (P, page, Hkv, D))
    kex = n.astype(np.float32) * sex[:, :, None, None]
    kq = np.clip(np.round(kex / sex[:, :, None, None]), -127,
                 127).astype(np.int8)
    rt = kq.astype(np.float32) * sex[:, :, None, None]
    assert np.array_equal(rt, kex)


def test_int8_page_byte_census_and_admission():
    """page_bytes: int8 pages cost elem bytes + per-slot fp32 scales —
    under a FIXED pool byte budget that admits ~2x the bf16 resident
    batch (~4x fp32). Device state: int8 layers are 4-tuples
    (k, v, k_scale, v_scale); float layers stay 2-tuples (the
    byte-identity of the unquantized path is structural)."""
    import jax.numpy as jnp

    kw = dict(num_pages=16, page_size=8, pages_per_seq=4, num_layers=2,
              num_kv_heads=2, head_dim=16)
    c32 = serving.KVCacheConfig(dtype="float32", **kw)
    c16 = serving.KVCacheConfig(dtype="bfloat16", **kw)
    c8 = serving.KVCacheConfig(dtype="int8", **kw)
    # per slot: 2 (k+v) * Hkv * D * elem_bytes (+ 2*4 scale when int8)
    assert c32.page_bytes == 2 * 8 * (2 * 2 * 16 * 4)
    assert c16.page_bytes == 2 * 8 * (2 * 2 * 16 * 2)
    assert c8.page_bytes == 2 * 8 * (2 * 2 * 16 * 1 + 2 * 4)
    budget = c32.pool_bytes
    p32, p16, p8 = (c.pages_for_budget(budget) for c in (c32, c16, c8))
    assert p16 == 2 * p32
    assert p8 >= 1.75 * p16          # ~2x minus the scale overhead
    assert c8.resident_batch == kw["num_pages"] // kw["pages_per_seq"]
    st8 = serving.PagedKVCache(c8).init_device_state()
    assert len(st8[0]) == 4
    assert st8[0][0].dtype == jnp.int8
    assert st8[0][2].shape == (16, 8)
    assert st8[0][2].dtype == jnp.float32
    st32 = serving.PagedKVCache(c32).init_device_state()
    assert len(st32[0]) == 2
    with pytest.raises(ValueError, match="dtype"):
        serving.KVCacheConfig(dtype="int4", **kw)


def test_int8_engine_batched_bit_identical_and_stats():
    """Continuous batching over int8 KV pages is bit-identical to
    sequential decoding at the same page dtype, and the engine stats /
    serving_step telemetry carry the quantization-tier fields."""
    r = np.random.RandomState(2)
    prompts = [r.randint(0, 48, size=n).astype(np.int32)
               for n in (5, 9, 3, 12)]

    def run(batched):
        eng = _engine(kv_dtype="int8")
        outs = []
        if batched:
            reqs = [eng.submit(p, max_new_tokens=6) for p in prompts]
            eng.run_until_idle()
            outs = [list(q.output_tokens) for q in reqs]
        else:
            for p in prompts:
                q = eng.submit(p, max_new_tokens=6)
                eng.run_until_idle()
                outs.append(list(q.output_tokens))
        stats = eng.stats()
        eng.close()
        return outs, stats

    batched, stats = run(True)
    sequential, _ = run(False)
    assert batched == sequential
    assert stats["kv_page_dtype"] == "int8"
    kvc = serving.KVCacheConfig(num_pages=96, page_size=4,
                                pages_per_seq=12, num_layers=2,
                                num_kv_heads=2, head_dim=8,
                                dtype="int8")
    assert stats["kv_page_bytes"] == kvc.page_bytes
    # pages_per_seq = ceil(max_seq 48 / page_size 4) = 12
    assert stats["kv_resident_batch"] == 96 // 12
    snap = obs.registry().snapshot()
    assert snap["gauges"].get("serving.kv_page_dtype") == "int8"


def test_int8_engine_step_events_schema_valid(tmp_path):
    """serving_step records from an int8 engine validate against the
    locked schema and carry kv_page_dtype='int8'."""
    obs.configure(telemetry_dir=str(tmp_path), rank=0)
    eng = _engine(kv_dtype="int8", max_seqs=4)
    eng.submit(np.arange(1, 6, dtype=np.int32), max_new_tokens=3)
    eng.run_until_idle()
    eng.close()
    recs = []
    for name in os.listdir(tmp_path):
        if name.endswith(".jsonl"):
            with open(os.path.join(tmp_path, name)) as f:
                recs.extend(json.loads(ln) for ln in f if ln.strip())
    problems = obs.validate_records(recs, obs.load_schema(
        os.path.join(_REPO, "tools", "telemetry_schema.json")))
    assert problems == []
    steps = [r for r in recs if r.get("kind") == "event"
             and r.get("event") == "serving_step"]
    assert steps and steps[0]["kv_page_dtype"] == "int8"
    assert steps[0]["kv_page_bytes"] >= 0
    assert steps[0]["resident_batch"] > 0


def test_ptq_weights_roundtrip_and_engine_golden():
    """Post-training int8 weight quantization: ~4x byte reduction over
    the quantized subset, identity on unquantized leaves, and the
    quantized-weight engine decodes bit-identically to the dense
    reference run on the SAME quantized params (batched == sequential
    included)."""
    from paddle_tpu.serving.quantize import (is_quantized,
                                             maybe_dequantize,
                                             quantize_tensor,
                                             quantize_weights_int8)

    params = _params()
    qparams = quantize_weights_int8(params)

    def census(dense, quant):
        if is_quantized(quant):
            return (int(np.asarray(dense).nbytes),
                    int(np.asarray(quant["q"]).nbytes)
                    + int(np.asarray(quant["qscale"]).nbytes))
        if isinstance(dense, dict):
            pairs = [census(dense[k], quant[k]) for k in dense]
        elif isinstance(dense, (list, tuple)):
            pairs = [census(d, q) for d, q in zip(dense, quant)]
        else:
            return (0, 0)
        return (sum(a for a, _ in pairs), sum(b for _, b in pairs))

    dense_b, quant_b = census(params, qparams)
    assert dense_b > 0
    assert quant_b * 3.5 <= dense_b
    # per-tensor: abs-max per output channel, bounded dequant error
    w = np.asarray(params["layers"][0]["wq"])
    qt = quantize_tensor(w)
    assert np.asarray(qt["q"]).dtype == np.int8
    err = np.max(np.abs(np.asarray(maybe_dequantize(qt)) - w))
    assert err <= np.abs(w).max() / 127.0 * 0.5 + 1e-7
    # identity on plain arrays: unquantized traces are unchanged
    assert maybe_dequantize(w) is w

    r = np.random.RandomState(3)
    prompts = [r.randint(0, 48, size=n).astype(np.int32)
               for n in (4, 8, 3)]

    def run(batched):
        eng = serving.Engine(_MODEL, params=_params(),
                             config=serving.EngineConfig(
                                 num_pages=96, page_size=4, max_seqs=6,
                                 kv_dtype="int8",
                                 quantize_weights=True))
        outs = []
        if batched:
            reqs = [eng.submit(p, max_new_tokens=5) for p in prompts]
            eng.run_until_idle()
            outs = [list(q.output_tokens) for q in reqs]
        else:
            for p in prompts:
                q = eng.submit(p, max_new_tokens=5)
                eng.run_until_idle()
                outs.append(list(q.output_tokens))
        eng.close()
        return outs

    batched = run(True)
    assert batched == run(False)
    golden = serving.dense_decode_reference(_MODEL, qparams,
                                            prompts[0], 5)
    assert batched[0] == golden


def test_float_kv_state_structurally_unchanged():
    """Kill-switch guarantee: at the default float page dtype the
    device state, engine stats and step records are EXACTLY the
    pre-quantization shapes — 2-tuple layers, no scale arrays."""
    eng = _engine()
    cfg = eng.kv.config
    assert cfg.dtype == "float32" and not cfg.quantized
    layers = serving.PagedKVCache(cfg).init_device_state()
    assert all(len(entry) == 2 for entry in layers)
    eng.close()


# -- lint: the decode loop has no per-token host sync -----------------------

def _tpu_lint():
    sys.path.insert(0, os.path.join(_REPO, "tools"))
    try:
        import tpu_lint
    finally:
        sys.path.pop(0)
    return tpu_lint


def test_serving_decode_exemplar_lints_clean():
    from paddle_tpu import analysis

    tpu_lint = _tpu_lint()
    prog, _ = tpu_lint.build_serving_decode()
    findings = analysis.run_static_checks(prog)
    s = analysis.summarize(findings)
    assert s["errors"] == 0, s["findings"]
    assert s["warnings"] == 0, s["findings"]


def test_fetch_in_decode_scan_fires_host_sync_error():
    """The deliberate-defect twin: seed a fetch INTO the decode scan
    body — the PR 5 host-sync checker must fire an ERROR anchored at
    the sub-block op (a per-token host sync would serialize the whole
    decode loop)."""
    from paddle_tpu import analysis

    tpu_lint = _tpu_lint()
    prog, _ = tpu_lint.build_serving_decode()
    scan_op = next(op for op in prog.global_block().ops
                   if op.type == "scan")
    sub = prog.block(scan_op.attrs["sub_block"])
    victim = sub.ops[0].output_arg_names[0]
    sub.append_op(type="fetch", inputs={"X": [victim]}, outputs={},
                  attrs={})
    findings = analysis.run_static_checks(prog)
    errs = [f for f in findings
            if f.checker == "host-sync" and f.severity == "error"]
    assert errs, findings
    assert errs[0].op_type == "fetch"
    assert errs[0].block_idx == sub.idx  # anchored inside the loop body
    assert "every iteration" in errs[0].message
