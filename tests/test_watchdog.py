"""Runtime hang watchdog (paddle_tpu/observability/watchdog.py): the
in-flight collective trace, the watchdog thread's stack + table dump,
the offline desync analyzer, the `stall` fault kind, the torn-JSONL
tolerance of --stragglers, the hang/heartbeat schema contract — and
the supervised 2-rank acceptance: rank 1 stalls inside a barrier, the
watchdog dump names rank 1 and the collective key, the supervisor
escalates through the elastic restart, the run completes rc=0."""
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu import observability as obs
from paddle_tpu.observability import capture, flight
from paddle_tpu.observability import watchdog as wd
from paddle_tpu.utils.flags import set_flags

_DIR = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_DIR)


@pytest.fixture(autouse=True)
def _fresh_observability():
    """Fresh registry/flight/capture/watchdog singletons per test (the
    in-flight trace is process state every collective writes into)."""
    obs.reset_registry()
    flight._reset_for_tests()
    capture._reset_for_tests()
    wd._reset_for_tests()
    set_flags({"FLAGS_tpu_hang_timeout_s": 0.0,
               "FLAGS_tpu_hang_capture_s": 0.0})
    yield
    obs.reset_registry()
    flight._reset_for_tests()
    capture._reset_for_tests()
    wd._reset_for_tests()
    set_flags({"FLAGS_tpu_hang_timeout_s": 0.0,
               "FLAGS_tpu_hang_capture_s": 0.0})


# ---------------------------------------------------------------------------
# in-flight trace ring
# ---------------------------------------------------------------------------

def test_inflight_trace_lifecycle_and_snapshot_json():
    tr = wd.InflightTrace(capacity=8)
    tok = tr.begin("allreduce", "allreduce#1", world=4, rank=2,
                   dtype="float32", shape=(3, 2), nbytes=24)
    assert tr.oldest_inflight_age_s() is not None
    (open_e,) = tr.inflight()
    assert open_e["state"] == "inflight" and open_e["key"] == \
        "allreduce#1"
    tok.arrived()
    assert tr.inflight()[0]["state"] == "arrived"
    tok.done(True)
    assert tr.oldest_inflight_age_s() is None
    snap = tr.snapshot()
    json.dumps(snap)  # must be JSON-encodable (embedded in dumps)
    (rec,) = snap["recent"]
    assert rec["state"] == "done"
    assert rec["ts_begin"] <= rec["ts_arrived"] <= rec["ts_end"]
    assert rec["schedule_key"] == \
        ["allreduce", "float32", [3, 2], 0, [["world", 4]], ""]


def test_inflight_trace_ring_is_bounded_and_failure_recorded():
    tr = wd.InflightTrace(capacity=4)
    for i in range(10):
        tr.begin("barrier", "barrier#%d" % i, world=2).done(i % 2 == 0)
    snap = tr.snapshot()
    assert len(snap["recent"]) == 4 and not snap["inflight"]
    assert {e["state"] for e in snap["recent"]} == {"done", "failed"}


def test_runtime_schedule_key_matches_static_grammar():
    """The runtime trace and tpu-lint's static divergence checker key
    "the same collective" identically: runtime_schedule_key on a
    host-tier barrier equals _schedule_key over the static record the
    IR pass would emit for it."""
    from paddle_tpu.analysis.collectives import (_schedule_key,
                                                 runtime_schedule_key)

    static_rec = {"kind": "barrier", "dtype": None, "shape": None,
                  "ring_id": 0, "group": (("world", 2),), "region": ""}
    assert runtime_schedule_key("barrier", world=2) == \
        _schedule_key(static_rec)
    static_rec = {"kind": "allreduce", "dtype": "float32",
                  "shape": (4,), "ring_id": 0,
                  "group": (("world", 3), ("ranks", (0, 1, 2))),
                  "region": ""}
    assert runtime_schedule_key("allreduce", dtype="float32",
                                shape=[4], world=3,
                                ranks=[0, 1, 2]) == \
        _schedule_key(static_rec)


def test_thread_stacks_names_every_live_thread():
    started = threading.Event()
    release = threading.Event()

    def parked():
        started.set()
        release.wait(10)

    t = threading.Thread(target=parked, name="parked-worker",
                         daemon=True)
    t.start()
    started.wait(5)
    try:
        stacks = wd.thread_stacks()
        assert any(k.startswith("MainThread") for k in stacks)
        parked_key = next(k for k in stacks
                          if k.startswith("parked-worker"))
        assert "release.wait" in stacks[parked_key]
    finally:
        release.set()


# ---------------------------------------------------------------------------
# watchdog thread: fire, dump, re-arm
# ---------------------------------------------------------------------------

def test_watchdog_fires_once_dumps_stacks_and_table(tmp_path):
    obs.configure(telemetry_dir=str(tmp_path), rank=0)
    w = wd.HangWatchdog(0.2, heartbeat_s=3600)
    tok = wd.trace().begin("barrier", "barrier#7", world=2, rank=0)
    assert w._tick() is None  # not stale yet
    time.sleep(0.3)
    ev = w._tick()
    assert ev is not None and ev["key"] == "barrier#7"
    assert ev["stalled_s"] >= 0.2 and ev["inflight_n"] == 1
    assert w._tick() is None, "must not re-fire while still wedged"

    dump = json.load(open(str(tmp_path / "flightrec.rank0.json")))
    assert dump["reason"] == "hang"
    assert dump["hang"]["key"] == "barrier#7"
    assert dump["inflight"]["inflight"][0]["state"] == "inflight"
    assert any(k.startswith("MainThread") for k in dump["stacks"])
    recs = [json.loads(ln) for ln in
            open(str(tmp_path / "telemetry.rank0.jsonl"))]
    hangs = [r for r in recs if r.get("event") == "hang"]
    assert len(hangs) == 1
    assert obs.validate_records(hangs) == []

    # progress re-arms; a NEW wedge fires again AND rewrites the dump
    # (a stale first-hang table must not feed a later real verdict)
    tok.done(True)
    w.note_progress()
    tok2 = wd.trace().begin("allreduce", "allreduce#8", world=2)
    time.sleep(0.3)
    ev2 = w._tick()
    assert ev2 is not None and ev2["key"] == "allreduce#8"
    dump2 = json.load(open(str(tmp_path / "flightrec.rank0.json")))
    assert dump2["hang"]["key"] == "allreduce#8"
    assert dump2["inflight"]["inflight"][0]["key"] == "allreduce#8"
    tok2.done(False)


def test_watchdog_rearms_on_collective_completion_without_step(
        tmp_path):
    """A transient first hang (the store recovered, the collective
    completed) must re-arm the watchdog even when the step epilogue
    never runs (the wedge was mid-step): a later REAL hang in the
    same step still fires with fresh forensics."""
    obs.configure(telemetry_dir=str(tmp_path), rank=0)
    w = wd.HangWatchdog(0.2, heartbeat_s=3600)
    a = wd.trace().begin("allreduce", "allreduce#1", world=2)
    time.sleep(0.3)
    assert w._tick() is not None  # transient hang fires
    a.done(True)  # store recovered; NO step epilogue in between
    assert w._tick() is None  # progress observed -> quietly re-armed
    b = wd.trace().begin("allreduce", "allreduce#2", world=2)
    time.sleep(0.3)
    ev = w._tick()
    assert ev is not None and ev["key"] == "allreduce#2"
    dump = json.load(open(str(tmp_path / "flightrec.rank0.json")))
    assert dump["hang"]["key"] == "allreduce#2"
    b.done(False)


def test_watchdog_quiet_while_other_collectives_progress(tmp_path):
    """An old open entry alone is not a hang: while OTHER collectives
    keep completing (progress), the watchdog stays quiet — the fire
    condition is in-flight age AND no progress, per the contract."""
    obs.configure(telemetry_dir=str(tmp_path), rank=0)
    w = wd.HangWatchdog(0.2, heartbeat_s=3600)
    stuck = wd.trace().begin("barrier", "barrier#1", world=2)
    time.sleep(0.3)
    wd.trace().begin("allreduce", "allreduce#2", world=2).done(True)
    assert w._tick() is None  # completion just advanced
    stuck.done(True)


def test_watchdog_install_is_flag_gated():
    assert wd.install() is None  # flag unset -> off
    assert wd.watchdog() is None
    set_flags({"FLAGS_tpu_hang_timeout_s": 30.0})
    w = wd.install()
    try:
        assert w is not None and wd.maybe_install() is w
        assert w.timeout_s == 30.0
    finally:
        wd.uninstall()


# ---------------------------------------------------------------------------
# zero overhead when off
# ---------------------------------------------------------------------------

def test_flag_off_telemetry_stream_has_no_watchdog_records(tmp_path):
    """FLAGS_tpu_hang_timeout_s unset: no watchdog thread, and the
    executor-driven telemetry stream carries exactly the record
    vocabulary it always did — no hang, no heartbeat (the
    zero-overhead-when-off acceptance regression)."""
    obs.configure(telemetry_dir=str(tmp_path), rank=0)
    from paddle_tpu.fluid import framework

    main_p, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup):
        x = fluid.data(name="x", shape=[-1, 8], dtype="float32")
        loss = fluid.layers.reduce_mean(
            fluid.layers.fc(input=x, size=4))
        fluid.optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    feed = {"x": np.ones((2, 8), np.float32)}
    for _ in range(3):
        exe.run(main_p, feed=feed, fetch_list=[loss])
    assert wd.watchdog() is None, \
        "flag unset must not arm the watchdog"
    recs = [json.loads(ln) for ln in
            open(str(tmp_path / "telemetry.rank0.jsonl"))]
    # startup dispatch + 3 train steps
    assert sum(1 for r in recs if r["kind"] == "step") == 4
    events = {r.get("event") for r in recs if r["kind"] == "event"}
    assert "hang" not in events and "heartbeat" not in events
    assert obs.validate_records(recs) == []


def test_flag_armed_watchdog_heartbeats(tmp_path):
    obs.configure(telemetry_dir=str(tmp_path), rank=0)
    w = wd.HangWatchdog(5.0, heartbeat_s=0.05)
    w._tick()
    time.sleep(0.08)
    w._tick()
    recs = [json.loads(ln) for ln in
            open(str(tmp_path / "telemetry.rank0.jsonl"))]
    beats = [r for r in recs if r.get("event") == "heartbeat"]
    assert len(beats) >= 2
    assert obs.validate_records(beats) == []
    assert all(b["up_s"] >= 0 for b in beats)


# ---------------------------------------------------------------------------
# host-collective + RPC integration: the trace records real traffic
# ---------------------------------------------------------------------------

def _free_endpoint():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return "127.0.0.1:%d" % port


@pytest.mark.dist
def test_host_collectives_record_inflight_lifecycle():
    from paddle_tpu.distributed.host_collectives import \
        HostCollectiveGroup

    ep = _free_endpoint()
    groups = [None, None]
    errs = []

    def run(r):
        try:
            g = HostCollectiveGroup(r, 2, ep)
            groups[r] = g
            g.barrier()
            out = g.all_reduce(np.ones(3, np.float64))
            assert float(out.sum()) == 6.0
            g.broadcast(np.asarray([1.0]), root=0)
        except Exception as e:  # noqa: BLE001
            errs.append((r, e))

    t0 = threading.Thread(target=run, args=(0,))
    t0.start()
    time.sleep(0.2)
    t1 = threading.Thread(target=run, args=(1,))
    t1.start()
    t0.join(30)
    t1.join(30)
    for g in groups:
        if g is not None:
            g.shutdown()
    assert not errs, errs
    snap = wd.trace().snapshot()
    assert not snap["inflight"], snap["inflight"]
    done = {(e["op"], e["key"]) for e in snap["recent"]
            if e["state"] == "done"}
    assert ("barrier", "barrier#1") in done
    assert ("allreduce", "allreduce#2") in done
    assert ("broadcast", "bcast#3") in done
    ar = next(e for e in snap["recent"] if e["op"] == "allreduce")
    assert ar["dtype"] == "float64" and ar["shape"] == [3] \
        and ar["bytes"] == 24 and ar["world"] == 2
    # both ranks passed through "arrived" before completing
    assert all("ts_arrived" in e for e in snap["recent"]
               if e["op"] != "broadcast")


# ---------------------------------------------------------------------------
# `stall` fault kind
# ---------------------------------------------------------------------------

@pytest.mark.faults
def test_stall_fault_wedges_op_until_reset():
    from paddle_tpu.distributed import faults
    from paddle_tpu.distributed.rpc import RpcClient, RpcServer, _Stop

    def handler(method, args):
        if method == "stop":
            raise _Stop()
        return [np.asarray([1])]

    srv = RpcServer("127.0.0.1", 0, handler)
    srv.start()
    cli = RpcClient("127.0.0.1:%d" % srv.port, call_retries=0)
    state = {}

    def wedged():
        try:
            cli.call("ping")
        except Exception as e:  # noqa: BLE001
            state["error"] = e

    faults.reset()
    faults.install(faults.FaultInjector(
        "stall", side="client", point="send", method="ping", at=1))
    try:
        t = threading.Thread(target=wedged, daemon=True)
        t.start()
        t.join(timeout=0.6)
        assert t.is_alive(), \
            "stall must hold the op, not bound it like delay"
        # reset() releases the parked thread with a FaultError into
        # the socket op (retries=0 -> it surfaces)
        faults.reset()
        t.join(timeout=5)
        assert not t.is_alive()
        assert isinstance(state.get("error"), Exception)
    finally:
        faults.reset()
        cli2 = RpcClient("127.0.0.1:%d" % srv.port, call_retries=0)
        try:
            cli2.call("stop")
        except Exception:  # noqa: BLE001
            pass
        cli2.close()
        cli.close()
        srv.shutdown()


def test_stall_spec_parses_from_env_syntax():
    from paddle_tpu.distributed import faults

    (inj,) = faults.parse_spec(
        "stall:side=client,point=send,method=hc_put_part,at=3")
    assert inj.kind == "stall" and inj.at == 3
    assert inj.method == "hc_put_part"


# ---------------------------------------------------------------------------
# desync analyzer
# ---------------------------------------------------------------------------

def _doc(entries, stacks=None, ts=100.0):
    return {"inflight": {
        "inflight": [e for e in entries
                     if e["state"] in ("inflight", "arrived")],
        "recent": [e for e in entries
                   if e["state"] not in ("inflight", "arrived")]},
        "stacks": stacks or {"MainThread (tid=1)":
                             "  File train.py line 10\n"},
        "ts": ts}


def _ent(key, state, world=2, op="barrier", skey=None, seq=1):
    return {"seq": seq, "op": op, "key": key, "state": state,
            "world": world, "ts_begin": 90.0,
            "schedule_key": skey
            or [op, None, None, 0, [["world", world]], ""]}


def test_analyzer_names_rank_stalled_inside_collective():
    v = wd.analyze_hang({
        0: _doc([_ent("barrier#3", "arrived")]),
        1: _doc([_ent("barrier#3", "inflight")])})
    assert v["verdict"] == "stall"
    assert v["collective"] == "barrier#3" and v["op"] == "barrier"
    assert v["guilty_ranks"] == [1] and v["waiting_ranks"] == [0]
    assert "stack_tail" in v["per_rank"][1]


def test_analyzer_names_rank_that_never_arrived():
    v = wd.analyze_hang({
        0: _doc([_ent("barrier#5", "arrived", seq=5)]),
        2: _doc([_ent("barrier#5", "arrived", seq=5)]),
        1: _doc([_ent("barrier#4", "done", seq=4)])})
    assert v["verdict"] == "desync" and v["guilty_ranks"] == [1]
    assert v["per_rank"][1]["state"] == "missing"
    assert v["per_rank"][1]["frontier_key"] == "barrier#4"
    assert sorted(v["waiting_ranks"]) == [0, 2]


def test_analyzer_open_rpc_barrier_not_masked_by_retired_calls():
    """RPC-tier keys are static per endpoint (send_barrier@host:port),
    so every call shares one key: the OPEN record (highest seq) must
    win over older retired ones — a rank wedged in its 5th PS barrier
    after 4 clean completions is a stall, not no-hang."""
    key = "send_barrier@127.0.0.1:6000"
    r1 = [_ent(key, "done", op="rpc_send_barrier", seq=s)
          for s in (1, 2, 3, 4)] \
        + [_ent(key, "inflight", op="rpc_send_barrier", seq=5)]
    r0 = [_ent(key, "done", op="rpc_send_barrier", seq=s)
          for s in (1, 2, 3, 4)] \
        + [_ent(key, "arrived", op="rpc_send_barrier", seq=5)]
    v = wd.analyze_hang({0: _doc(r0), 1: _doc(r1)})
    assert v["verdict"] == "stall", v
    assert v["guilty_ranks"] == [1] and v["collective"] == key


def test_analyzer_flags_membership_mismatch():
    v = wd.analyze_hang({
        0: _doc([_ent("barrier#2", "arrived", world=2)]),
        1: _doc([_ent("barrier#2", "arrived", world=3,
                      skey=["barrier", None, None, 0,
                            [["world", 3]], ""])])})
    assert v["verdict"] == "membership-mismatch"
    assert "0" in v["mismatched_keys"] and "1" in v["mismatched_keys"]


def test_analyzer_no_hang_and_hang_report_roundtrip(tmp_path):
    v = wd.analyze_hang({0: _doc([_ent("barrier#1", "done")])})
    assert v["verdict"] == "no-hang"

    # bundle on disk -> hang_report names the guilty rank + key
    for rank, doc in ((0, _doc([_ent("barrier#3", "arrived")])),
                      (1, _doc([_ent("barrier#3", "inflight")]))):
        with open(str(tmp_path / ("flightrec.rank%d.json" % rank)),
                  "w") as f:
            json.dump(doc, f)
    rep = wd.hang_report(str(tmp_path))
    assert rep["verdict"]["verdict"] == "stall"
    text = "\n".join(rep["lines"])
    assert "barrier#3" in text and "rank 1" in text \
        and "guilty" in text
    # unreadable dumps are skipped, not fatal
    with open(str(tmp_path / "flightrec.rank2.json"), "w") as f:
        f.write('{"torn')
    assert len(wd.load_hang_bundle(str(tmp_path))) == 2


# ---------------------------------------------------------------------------
# --stragglers torn-line tolerance (satellite)
# ---------------------------------------------------------------------------

def _write_rank_stream(path, rank, n_steps, torn_tail=False):
    with open(path, "w") as f:
        for i in range(1, n_steps + 1):
            f.write(json.dumps({
                "kind": "step", "rank": rank, "step": i,
                "ts": 100.0 + i, "feed_ms": 1.0, "dispatch_ms": 5.0,
                "comm_ms": 0.0, "sync_ms": 1.0, "host_ms": 1.0,
                "total_ms": 8.0 + rank}) + "\n")
        if torn_tail:
            # the exact artifact a killed rank leaves: a final line cut
            # mid-object, no trailing newline
            f.write('{"kind": "step", "rank": %d, "step": %d, "ts"'
                    % (rank, n_steps + 1))


def test_load_telemetry_dir_reports_torn_final_line(tmp_path):
    from paddle_tpu.observability import aggregate

    _write_rank_stream(str(tmp_path / "telemetry.rank0.jsonl"), 0, 4)
    _write_rank_stream(str(tmp_path / "telemetry.rank1.jsonl"), 1, 4,
                       torn_tail=True)
    errors = []
    by_rank = aggregate.load_telemetry_dir(str(tmp_path),
                                           errors=errors)
    assert len(by_rank[0]) == 4 and len(by_rank[1]) == 4
    (err,) = errors
    assert err["rank"] == 1 and err["final_line"] is True
    assert err["file"] == "telemetry.rank1.jsonl"


def test_stragglers_tolerates_truncated_stream(tmp_path, capsys):
    """Regression: a torn final JSONL line (killed rank) must not
    escape --stragglers with a JSON decode traceback — the report runs
    and the skip is surfaced."""
    _write_rank_stream(str(tmp_path / "telemetry.rank0.jsonl"), 0, 8)
    _write_rank_stream(str(tmp_path / "telemetry.rank1.jsonl"), 1, 8,
                       torn_tail=True)
    sys.path.insert(0, os.path.join(_REPO, "tools"))
    try:
        import perf_analysis
    finally:
        sys.path.pop(0)
    rc = perf_analysis.stragglers(str(tmp_path), window=4)
    out = capsys.readouterr().out
    assert rc == 0
    assert "skipped torn JSONL line" in out
    assert "telemetry.rank1.jsonl" in out and "final line" in out
    assert "straggler: rank 1" in out


def test_hang_watch_survives_telemetry_rotation(tmp_path):
    """The supervisor's tail must reset a file offset when the active
    JSONL rotates (os.replace to .gN + fresh file at size 0): a stale
    large offset would both hide hang events and let the silence
    fallback kill a healthy cohort."""
    from paddle_tpu.distributed.launch import _HangWatch

    watch = _HangWatch(str(tmp_path), 4.0, poll_every_s=0.0)
    p = tmp_path / "telemetry.rank0.jsonl"
    filler = json.dumps({"kind": "event", "event": "collective",
                         "rank": 0, "step": 1, "ts": 1.0,
                         "key": "barrier#1"}, sort_keys=True)
    p.write_text((filler + "\n") * 50)
    assert watch.poll() is None  # offset advances past the filler
    # rotation: active file replaced by a FRESH, smaller one whose
    # only content is the hang event
    hang = json.dumps({"kind": "event", "event": "hang", "rank": 0,
                       "step": 2, "ts": 2.0, "stalled_s": 5.0,
                       "inflight_n": 1}, sort_keys=True)
    p.write_text(hang + "\n")
    det = watch.poll()
    assert det is not None and det["via"] == "hang-event", det
    assert det["ranks"] == [0]


# ---------------------------------------------------------------------------
# schema: hang / heartbeat event contracts (satellite)
# ---------------------------------------------------------------------------

def test_schema_locks_hang_and_heartbeat_events():
    schema = obs.load_schema()
    ok_hang = {"kind": "event", "event": "hang", "rank": 0, "step": 3,
               "ts": 1.0, "stalled_s": 2.5, "inflight_n": 1,
               "op": "barrier", "key": "barrier#3"}
    assert obs.validate_record(ok_hang, schema) == []
    bad = dict(ok_hang)
    bad.pop("stalled_s")
    assert any("stalled_s" in p for p in
               obs.validate_record(bad, schema))
    ok_beat = {"kind": "event", "event": "heartbeat", "rank": 0,
               "step": 3, "ts": 1.0, "up_s": 12.0, "inflight_n": 0}
    assert obs.validate_record(ok_beat, schema) == []
    assert any("up_s" in p for p in obs.validate_record(
        {"kind": "event", "event": "heartbeat", "rank": 0, "step": 0,
         "ts": 1.0}, schema))
    # wrong type on a typed watchdog field is caught
    assert any("stalled_s" in p for p in obs.validate_record(
        dict(ok_hang, stalled_s="2.5"), schema))


# ---------------------------------------------------------------------------
# acceptance: supervised 2-rank stall -> watchdog -> elastic recovery
# ---------------------------------------------------------------------------

def _launch_env():
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("PALLAS_AXON", "AXON_", "TPU_"))}
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env.pop("PADDLE_FAULTS", None)
    env.pop("FLAGS_tpu_hang_timeout_s", None)
    return env


@pytest.mark.dist
def test_hang_timeout_without_telemetry_dir_warns(tmp_path):
    """--hang_timeout with no --log_dir / FLAGS_tpu_telemetry_dir has
    nowhere to read worker hang events from: the launch must say so
    instead of silently arming nothing supervisor-side."""
    script = tmp_path / "ok.py"
    script.write_text("print('fine')\n")
    env = _launch_env()
    env.pop("FLAGS_tpu_telemetry_dir", None)
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--hosts", "127.0.0.1:6731", "--hang_timeout", "5",
         str(script)],
        env=env, cwd=_REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout
    assert "hang ESCALATION is off" in proc.stdout, proc.stdout


@pytest.mark.slow
@pytest.mark.faults
@pytest.mark.dist
def test_supervised_stall_is_diagnosed_and_elastically_recovered(
        tmp_path):
    """End-to-end forensics acceptance: rank 1 of a supervised 2-rank
    cohort stalls (alive, heartbeating) inside its 3rd barrier; every
    rank's watchdog dumps the in-flight table + thread stacks; the
    supervisor names rank 1 + the collective via the desync verdict,
    kills the cohort, drops rank 1 through --min_ranks, and the
    1-rank attempt completes rc=0. perf_analysis --hang-report over
    the collected bundle names the same rank and key."""
    runner = os.path.join(_DIR, "hang_watchdog_runner.py")
    log_dir = str(tmp_path / "logs")
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--hosts", "127.0.0.1:6721,127.0.0.1:6722",
         "--log_dir", log_dir, "--max_restarts", "1",
         "--min_ranks", "1", "--hang_timeout", "4",
         runner, "5", "1", "3"],
        env=_launch_env(), cwd=_REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout
    assert "alive but wedged" in proc.stdout, proc.stdout
    assert "hang verdict: stall" in proc.stdout, proc.stdout
    assert "elastic shrink 2 -> 1" in proc.stdout, proc.stdout

    # every rank left a flight dump carrying the in-flight table and
    # all-thread stacks, collected into postmortem/attempt0
    att0 = os.path.join(log_dir, "postmortem", "attempt0")
    docs = {}
    for rank in (0, 1):
        path = os.path.join(att0, "flightrec.rank%d.json" % rank)
        assert os.path.exists(path), os.listdir(att0)
        docs[rank] = json.load(open(path))
        assert docs[rank]["reason"] == "hang"
        assert any(k.startswith("MainThread")
                   for k in docs[rank]["stacks"])
    key = docs[0]["hang"]["key"]
    assert key.startswith("barrier#"), docs[0]["hang"]
    # rank 0 contributed and waited; rank 1 began but never arrived
    r0 = {e["key"]: e for e in docs[0]["inflight"]["inflight"]}
    r1 = {e["key"]: e for e in docs[1]["inflight"]["inflight"]}
    assert r0[key]["state"] == "arrived"
    assert r1[key]["state"] == "inflight"

    # the analyzer (the same code the supervisor ran) blames rank 1
    v = wd.analyze_hang(docs)
    assert v["verdict"] == "stall" and v["guilty_ranks"] == [1]
    assert v["collective"] == key

    # the supervisor stream: a hang event + the elastic_transition
    # carrying the verdict
    sup = os.path.join(log_dir, "telemetry",
                       "telemetry.supervisor.jsonl")
    recs = [json.loads(ln) for ln in open(sup) if ln.strip()]
    (hang_ev,) = [r for r in recs if r["event"] == "hang"]
    assert hang_ev["via"] == "hang-event"
    assert hang_ev["stalled_s"] >= 4.0
    (trans,) = [r for r in recs
                if r["event"] == "elastic_transition"]
    assert trans["hang"] is True
    assert trans["hang_verdict"] == "stall"
    assert trans["hang_guilty_ranks"] == [1]
    assert trans["hang_collective"] == key
    assert trans["old_world"] == 2 and trans["new_world"] == 1
    assert trans["failed_ranks"] == [1]

    # attempt 1 (world 1) finished the run
    log0 = open(os.path.join(log_dir, "workerlog.0")).read()
    assert "DONE rank=0 world=1 attempt=1" in log0, log0

    # one-command offline diagnosis over the collected bundle
    rep = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools",
                                      "perf_analysis.py"),
         "--hang-report", "--log-dir", log_dir],
        env=_launch_env(), cwd=_REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True, timeout=180)
    assert rep.returncode == 0, rep.stdout
    assert "rank 1: began but NEVER CONTRIBUTED" in rep.stdout
    assert key in rep.stdout
    assert "verdict: stall" in rep.stdout
