"""End-to-end book-style model tests (reference:
`python/paddle/fluid/tests/book/` — word2vec over imikolov n-grams,
SE-block image classifier; the transformer beam-search decode round
trip lives in test_models.py): train real small models via the public API and assert the
loss drops / decode round-trips."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework


def test_fit_a_line_trains_and_infers(tmp_path):
    """reference book/test_fit_a_line.py: linear regression over the
    13-feature uci_housing rows, SGD + square_error_cost, then a
    save/load_inference_model round trip on the trained predictor."""
    rows = list(paddle.dataset.uci_housing.train()())[:128]
    xs = np.asarray([r[0] for r in rows], "float32")
    ys = np.asarray([r[1] for r in rows], "float32").reshape(-1, 1)

    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup):
        with framework.unique_name_guard():
            x = fluid.layers.data("x", shape=[13], dtype="float32")
            y = fluid.layers.data("y", shape=[1], dtype="float32")
            pred = fluid.layers.fc(input=x, size=1, act=None)
            loss = fluid.layers.mean(
                fluid.layers.loss.square_error_cost(pred, y))
            fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
            exe = fluid.Executor()
            exe.run(startup)
            losses = []
            for _ in range(30):
                out = exe.run(main, feed={"x": xs, "y": ys},
                              fetch_list=[loss])
                losses.append(float(np.asarray(out[0]).ravel()[0]))
            assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])

            d = str(tmp_path / "fit_a_line_model")
            fluid.io.save_inference_model(d, ["x"], [pred], exe,
                                          main_program=main)

    # fresh executor + program: the exported predictor must stand alone
    exe2 = fluid.Executor()
    prog, feed_names, fetch_vars = fluid.io.load_inference_model(d, exe2)
    assert feed_names == ["x"]
    got = exe2.run(prog, feed={"x": xs[:8]}, fetch_list=fetch_vars)
    pred_vals = np.asarray(got[0]).reshape(-1)
    assert pred_vals.shape == (8,)
    assert np.all(np.isfinite(pred_vals))
    # the round-tripped model predicts in the ballpark of the targets
    assert np.mean((pred_vals - ys[:8, 0]) ** 2) < losses[0], \
        (pred_vals, ys[:8, 0])


def test_word2vec_trains():
    """reference book/test_word2vec.py: n-gram embedding concat + fc."""
    n = 5
    emb_dim = 16
    vocab = 200
    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup):
        with framework.unique_name_guard():
            words = [fluid.layers.data("w%d" % i, shape=[1],
                                       dtype="int64")
                     for i in range(n)]
            embs = [fluid.layers.embedding(
                w, size=[vocab, emb_dim],
                param_attr=fluid.ParamAttr(name="shared_emb"))
                for w in words[:-1]]
            concat = fluid.layers.tensor.concat(embs, axis=1)
            hidden = fluid.layers.fc(concat, 64, act="sigmoid")
            logits = fluid.layers.fc(hidden, vocab)
            loss = fluid.layers.mean(
                fluid.layers.loss.softmax_with_cross_entropy(
                    logits, words[-1]))
            fluid.optimizer.AdamOptimizer(1e-2).minimize(loss)
            exe = fluid.Executor()
            exe.run(startup)

            grams = [g for g in paddle.dataset.imikolov.train(n=n)()
                     if max(g) < vocab][:256]
            arr = np.asarray(grams, "int64")
            feed = {("w%d" % i): arr[:, i:i + 1] for i in range(n)}
            losses = []
            for _ in range(15):
                out = exe.run(main, feed=feed, fetch_list=[loss])
                losses.append(float(np.asarray(out[0]).ravel()[0]))
    assert losses[-1] < losses[0] * 0.9, (losses[0], losses[-1])


def test_se_block_classifier_trains():
    """SE-ResNeXt-style squeeze-excitation block (reference
    book/test_image_classification + dist_se_resnext.py): conv -> SE
    gate -> fc, trained a few steps."""
    r = np.random.RandomState(1)
    feats = r.randn(8, 3, 16, 16).astype("float32")
    labels = r.randint(0, 4, (8, 1)).astype("int64")
    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup):
        with framework.unique_name_guard():
            img = fluid.layers.data("img", shape=[3, 16, 16],
                                    dtype="float32")
            y = fluid.layers.data("y", shape=[1], dtype="int64")
            conv = fluid.layers.conv2d(img, 8, 3, padding=1, act="relu")
            # squeeze-excitation: GAP -> fc(reduce) -> fc(expand) ->
            # sigmoid channel gate
            squeeze = fluid.layers.pool2d(conv, pool_size=16,
                                          pool_type="avg")
            sq = fluid.layers.fc(squeeze, 4, act="relu")
            ex = fluid.layers.fc(sq, 8, act="sigmoid")
            ex4 = fluid.layers.unsqueeze(
                fluid.layers.unsqueeze(ex, [2]), [3])
            gated = fluid.layers.elementwise_mul(conv, ex4)
            pooled = fluid.layers.pool2d(gated, pool_size=16,
                                         pool_type="avg")
            logits = fluid.layers.fc(pooled, 4)
            loss = fluid.layers.mean(
                fluid.layers.loss.softmax_with_cross_entropy(logits, y))
            fluid.optimizer.AdamOptimizer(5e-3).minimize(loss)
            exe = fluid.Executor()
            exe.run(startup)
            losses = []
            for _ in range(12):
                out = exe.run(main, feed={"img": feats, "y": labels},
                              fetch_list=[loss])
                losses.append(float(np.asarray(out[0]).ravel()[0]))
    assert losses[-1] < losses[0], (losses[0], losses[-1])


def test_public_api_surface_locked():
    """API conformance lock (reference §4.7: API.spec +
    check_api_approvals.sh): the core public surface must keep these
    names; removals break users and must be deliberate."""
    core = {
        "paddle_tpu": [
            "CPUPlace", "TPUPlace", "CUDAPlace", "Program",
            "program_guard", "Executor", "ParamAttr", "to_variable",
            "no_grad", "grad", "nn", "tensor", "optimizer",
            "distributed", "fleet", "static", "jit", "metric",
            "reader", "dataset", "batch", "manual_seed", "Model",
        ],
        "paddle_tpu.fluid": [
            "layers", "optimizer", "initializer", "regularizer", "clip",
            "io", "metrics", "dygraph", "Executor", "CompiledProgram",
            "DataFeeder", "ParamAttr", "default_main_program",
            "default_startup_program",
        ],
        "paddle_tpu.fluid.layers": [
            "fc", "conv2d", "conv3d", "batch_norm", "layer_norm",
            "embedding", "dynamic_lstm", "dynamic_gru", "warpctc",
            "linear_chain_crf", "crf_decoding", "nce", "hsigmoid",
            "prior_box", "ssd_loss", "multiclass_nms", "roi_align",
            "yolov3_loss", "interpolate", "resize_bilinear", "pool2d",
            "pool3d", "softmax_with_cross_entropy", "cross_entropy",
            "While", "while_loop", "cond", "case", "switch_case",
            "beam_search", "dynamic_decode", "py_func",
        ],
        "paddle_tpu.nn": [
            "Layer", "Linear", "Conv2D", "Conv3D", "BatchNorm",
            "LayerNorm", "Embedding", "CrossEntropyLoss", "MSELoss",
            "BCELoss", "NLLLoss", "HSigmoid", "Pad2D", "UpSample",
            "functional", "initializer", "beam_search", "gather_tree",
        ],
    }
    import importlib

    missing = []
    for mod_name, names in core.items():
        mod = importlib.import_module(mod_name)
        for n in names:
            if not hasattr(mod, n):
                missing.append("%s.%s" % (mod_name, n))
    assert not missing, missing


def test_label_semantic_roles_crf_trains_and_decodes(rng):
    """Book model: label_semantic_roles (reference:
    tests/book/test_label_semantic_roles.py) — embeddings + fc emission
    + linear_chain_crf training, crf_decoding inference, fed from the
    paddle.dataset.conll05 reader shape."""
    import paddle_tpu.dataset.conll05 as conll05

    word_dict, verb_dict, label_dict = conll05.get_dict()
    n_labels = len(label_dict)
    seq_len, batch = 12, 8

    main, startup = framework.Program(), framework.Program()
    main.random_seed = startup.random_seed = 17
    with framework.program_guard(main, startup):
        with framework.unique_name_guard():
            word = fluid.layers.data("word", shape=[seq_len],
                                     dtype="int64")
            label = fluid.layers.data("label", shape=[seq_len],
                                      dtype="int64")
            length = fluid.layers.data("length", shape=[1],
                                       dtype="int64")
            emb = fluid.layers.embedding(
                word, size=[len(word_dict), 32])
            hidden = fluid.layers.fc(emb, size=64, act="tanh",
                                     num_flatten_dims=2)
            emission = fluid.layers.fc(
                hidden, size=n_labels, num_flatten_dims=2,
                param_attr=fluid.ParamAttr(name="emission_fc.w"))
            crf_cost = fluid.layers.linear_chain_crf(
                emission, label,
                param_attr=fluid.ParamAttr(name="crfw"),
                length=length)
            loss = fluid.layers.mean(crf_cost)
            decode = fluid.layers.crf_decoding(
                emission, param_attr=fluid.ParamAttr(name="crfw"),
                length=length)
            fluid.optimizer.SGDOptimizer(1e-2).minimize(loss)

            exe = fluid.Executor()
            exe.run(startup)
            words = rng.randint(0, len(word_dict),
                                (batch, seq_len)).astype("int64")
            labels = rng.randint(0, n_labels,
                                 (batch, seq_len)).astype("int64")
            lens = np.full((batch, 1), seq_len, "int64")
            losses = []
            for _ in range(6):
                out = exe.run(main,
                              feed={"word": words, "label": labels,
                                    "length": lens},
                              fetch_list=[loss])
                losses.append(float(np.asarray(out[0]).reshape(-1)[0]))
            assert losses[-1] < losses[0], losses
            path = exe.run(main,
                           feed={"word": words, "label": labels,
                                 "length": lens},
                           fetch_list=[decode])[0]
            path = np.asarray(path)
            assert path.shape == (batch, seq_len)
            assert (path >= 0).all() and (path < n_labels).all()


def test_understand_sentiment_lstm_trains(rng):
    """Book model: understand_sentiment (reference:
    tests/book/test_understand_sentiment.py) — embedding + LSTM + pool
    + softmax classifier over the paddle.dataset.imdb vocabulary."""
    import paddle_tpu.dataset.imdb as imdb

    word_dict = imdb.word_dict()
    seq_len, batch = 16, 8

    main, startup = framework.Program(), framework.Program()
    main.random_seed = startup.random_seed = 19
    with framework.program_guard(main, startup):
        with framework.unique_name_guard():
            data = fluid.layers.data("words", shape=[seq_len],
                                     dtype="int64")
            label = fluid.layers.data("label", shape=[1], dtype="int64")
            emb = fluid.layers.embedding(
                data, size=[len(word_dict), 32])
            lstm_out, _cell = fluid.layers.dynamic_lstm(
                fluid.layers.fc(emb, size=4 * 32, num_flatten_dims=2),
                size=4 * 32)
            pooled = fluid.layers.reduce_max(lstm_out, dim=1)
            logits = fluid.layers.fc(pooled, size=2)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, label))
            fluid.optimizer.AdamOptimizer(1e-2).minimize(loss)

            exe = fluid.Executor()
            exe.run(startup)
            xs = rng.randint(0, len(word_dict),
                             (batch, seq_len)).astype("int64")
            ys = rng.randint(0, 2, (batch, 1)).astype("int64")
            losses = []
            for _ in range(8):
                out = exe.run(main, feed={"words": xs, "label": ys},
                              fetch_list=[loss])
                losses.append(float(np.asarray(out[0]).reshape(-1)[0]))
            assert losses[-1] < losses[0], losses


def test_recommender_system_trains(rng):
    """Book model: recommender_system (reference:
    tests/book/test_recommender_system.py) — user/movie embedding
    towers, cos_sim match score scaled to the 1..5 rating range,
    square loss; ids bounded by the paddle.dataset.movielens dicts."""
    import paddle_tpu.dataset.movielens as movielens

    n_users = movielens.max_user_id() + 1
    n_movies = movielens.max_movie_id() + 1
    batch = 16

    main, startup = framework.Program(), framework.Program()
    main.random_seed = startup.random_seed = 23
    with framework.program_guard(main, startup):
        with framework.unique_name_guard():
            uid = fluid.layers.data("uid", shape=[1], dtype="int64")
            mid = fluid.layers.data("mid", shape=[1], dtype="int64")
            rating = fluid.layers.data("rating", shape=[1],
                                       dtype="float32")
            u_emb = fluid.layers.embedding(uid, size=[n_users, 32])
            m_emb = fluid.layers.embedding(mid, size=[n_movies, 32])
            u_vec = fluid.layers.fc(
                fluid.layers.reshape(u_emb, [-1, 32]), size=32,
                act="tanh")
            m_vec = fluid.layers.fc(
                fluid.layers.reshape(m_emb, [-1, 32]), size=32,
                act="tanh")
            sim = fluid.layers.cos_sim(u_vec, m_vec)
            pred = fluid.layers.scale(sim, scale=5.0)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(pred, rating))
            fluid.optimizer.AdamOptimizer(5e-3).minimize(loss)

            exe = fluid.Executor()
            exe.run(startup)
            uids = rng.randint(1, n_users, (batch, 1)).astype("int64")
            mids = rng.randint(1, n_movies, (batch, 1)).astype("int64")
            ratings = rng.randint(1, 6, (batch, 1)).astype("float32")
            losses = []
            for _ in range(10):
                out = exe.run(main, feed={"uid": uids, "mid": mids,
                                          "rating": ratings},
                              fetch_list=[loss])
                losses.append(float(np.asarray(out[0]).reshape(-1)[0]))
            assert losses[-1] < losses[0], losses


def test_machine_translation_seq2seq_trains(rng):
    """Book model: machine_translation (reference:
    tests/book/test_machine_translation.py) — GRU encoder, teacher-
    forced GRU decoder with additive attention context, softmax over
    the target vocab; vocab sizes from paddle.dataset.wmt16 dicts."""
    import paddle_tpu.dataset.wmt16 as wmt16

    src_dict = wmt16.get_dict("en", 200)
    trg_dict = wmt16.get_dict("de", 200)
    src_vocab = len(src_dict)
    trg_vocab = len(trg_dict)
    seq, batch, hid = 8, 8, 32

    main, startup = framework.Program(), framework.Program()
    main.random_seed = startup.random_seed = 29
    with framework.program_guard(main, startup):
        with framework.unique_name_guard():
            src = fluid.layers.data("src", shape=[seq], dtype="int64")
            trg_in = fluid.layers.data("trg_in", shape=[seq],
                                       dtype="int64")
            trg_out = fluid.layers.data("trg_out", shape=[seq, 1],
                                        dtype="int64")
            s_emb = fluid.layers.embedding(src, size=[src_vocab, hid])
            enc_in = fluid.layers.fc(s_emb, size=3 * hid,
                                     num_flatten_dims=2)
            enc = fluid.layers.dynamic_gru(enc_in, size=hid)  # [B,S,H]
            t_emb = fluid.layers.embedding(trg_in,
                                           size=[trg_vocab, hid])
            dec_in = fluid.layers.fc(t_emb, size=3 * hid,
                                     num_flatten_dims=2)
            dec = fluid.layers.dynamic_gru(dec_in, size=hid)
            # additive attention: scores [B, St, Ss] from decoder over
            # encoder states; context concat -> vocab softmax
            scores = fluid.layers.matmul(dec, enc, transpose_y=True)
            attn = fluid.layers.softmax(scores)
            ctx = fluid.layers.matmul(attn, enc)        # [B, St, H]
            feat = fluid.layers.concat([dec, ctx], axis=2)
            logits = fluid.layers.fc(feat, size=trg_vocab,
                                     num_flatten_dims=2)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits,
                                                        trg_out))
            fluid.optimizer.AdamOptimizer(5e-3).minimize(loss)

            exe = fluid.Executor()
            exe.run(startup)
            s = rng.randint(0, src_vocab, (batch, seq)).astype("int64")
            ti = rng.randint(0, trg_vocab, (batch, seq)).astype("int64")
            to = rng.randint(0, trg_vocab,
                             (batch, seq, 1)).astype("int64")
            losses = []
            for _ in range(8):
                out = exe.run(main, feed={"src": s, "trg_in": ti,
                                          "trg_out": to},
                              fetch_list=[loss])
                losses.append(float(np.asarray(out[0]).reshape(-1)[0]))
            assert losses[-1] < losses[0], losses
