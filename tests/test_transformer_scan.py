"""Transformer (BASELINE config 4) scan-over-layers path: exact forward
parity with the unrolled encoder/decoder under shared weights, training,
and beam_search_decode reading a scan-trained scope via stacked-param
expansion (models/transformer._np_params)."""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework
from paddle_tpu.core.scope import global_scope
from paddle_tpu.models import transformer as T


def _feed(cfg, B, S):
    r = np.random.RandomState(0)
    return {
        "src_ids": r.randint(0, cfg.src_vocab, (B, S)).astype("int64"),
        "tgt_ids": r.randint(0, cfg.tgt_vocab, (B, S)).astype("int64"),
        "lbl_ids": r.randint(0, cfg.tgt_vocab, (B, S)).astype("int64"),
        "src_mask": np.ones((B, S), "float32"),
        "tgt_mask": np.ones((B, S), "float32"),
    }


def _build(cfg, S, scan, seed=21):
    main, st = framework.Program(), framework.Program()
    main.random_seed = st.random_seed = seed
    with framework.program_guard(main, st):
        with framework.unique_name_guard():
            loss, feeds = T.build_transformer_train(
                cfg, src_len=S, tgt_len=S, is_test=True,
                scan_layers=scan)
    return main, st, loss


def _run(main, st, feed, fetch):
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(st)
    return exe, lambda: np.asarray(
        exe.run(main, feed=feed, fetch_list=[fetch])[0])


def _stacked_from_unrolled(vals, cfg):
    out = {}
    for pre in ("enc", "dec"):
        for suf in T.layer_param_suffixes(pre):
            out["%s_stack%s" % (pre, suf)] = np.stack(
                [vals["%s_%d%s" % (pre, i, suf)]
                 for i in range(cfg.n_layer)])
    return out


@pytest.mark.slow
def test_transformer_scan_forward_parity():
    cfg = T.TransformerConfig.tiny()
    S, B = 12, 2
    feed = _feed(cfg, B, S)

    main_u, st_u, loss_u = _build(cfg, S, scan=False)
    _, run_u = _run(main_u, st_u, feed, loss_u)
    lu = float(run_u().ravel()[0])
    vals = {p.name: np.asarray(global_scope().find_var(p.name)).copy()
            for p in main_u.all_parameters()}

    main_s, st_s, loss_s = _build(cfg, S, scan=True)
    _, run_s = _run(main_s, st_s, feed, loss_s)
    import jax.numpy as jnp

    for name, v in {**vals, **_stacked_from_unrolled(vals, cfg)}.items():
        if global_scope().find_var(name) is not None:
            global_scope().set_var(name, jnp.asarray(v))
    ls = float(run_s().ravel()[0])
    np.testing.assert_allclose(ls, lu, rtol=2e-5, atol=2e-5)


def test_scan_stack_init_scale_matches_unrolled():
    """Xavier fan must come from the per-layer 2D slice: computing it
    from the stacked [L, d, d] shape under-scales the init ~16x."""
    cfg = T.TransformerConfig.tiny()
    S = 12
    main_u, st_u, _ = _build(cfg, S, scan=False, seed=2)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(st_u)
    w_u = np.asarray(global_scope().find_var("enc_0_selfattn_q.w"))

    main_s, st_s, _ = _build(cfg, S, scan=True, seed=2)
    exe.run(st_s)
    w_s = np.asarray(global_scope().find_var("enc_stack_selfattn_q.w"))
    ratio = w_s[0].std() / w_u.std()
    assert 0.5 < ratio < 2.0, ratio


@pytest.mark.slow
def test_transformer_scan_trains():
    cfg = T.TransformerConfig.tiny()
    S, B = 12, 4
    main, st = framework.Program(), framework.Program()
    main.random_seed = st.random_seed = 3
    with framework.program_guard(main, st):
        with framework.unique_name_guard():
            loss, feeds = T.build_transformer_train(
                cfg, src_len=S, tgt_len=S, scan_layers=True,
                scan_remat=True)
    feed = _feed(cfg, B, S)
    _, step = _run(main, st, feed, loss)
    ls = [float(step().ravel()[0]) for _ in range(8)]
    assert np.isfinite(ls).all()
    assert ls[-1] < ls[0], ls


def test_beam_decode_reads_scan_trained_scope():
    cfg = T.TransformerConfig.tiny()
    S, B = 12, 2
    main, st = framework.Program(), framework.Program()
    main.random_seed = st.random_seed = 3
    with framework.program_guard(main, st):
        with framework.unique_name_guard():
            T.build_transformer_train(cfg, src_len=S, tgt_len=S,
                                      is_test=True, scan_layers=True)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(st)
    feed = _feed(cfg, B, S)
    seqs, scores = T.beam_search_decode(
        global_scope(), feed["src_ids"], feed["src_mask"], cfg,
        beam_size=2, max_out_len=6)
    seqs, scores = np.asarray(seqs), np.asarray(scores)
    assert seqs.shape[0] == B and seqs.shape[1] == 2
    assert np.isfinite(scores).all()
