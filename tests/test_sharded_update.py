"""ZeRO-1 sharded weight update (FLAGS_tpu_sharded_weight_update) —
parity vs the replicated update on the virtual CPU mesh, per-collective
byte evidence, sharded-state donation/HBM audit, off-by-flag HLO, the
hapi evaluate/predict deferral, the map-style DataLoader device buffer,
and cross-rank checkpoint-step agreement.

Reference: "Automatic Cross-Replica Sharding of Weight Update in
Data-Parallel Training" (Xu et al., 2020); the plan/trace machinery is
paddle_tpu/parallel/sharded_update.py.
"""
import os
import threading

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework
from paddle_tpu.utils.flags import set_flags


@pytest.fixture(autouse=True)
def _restore_flag():
    from paddle_tpu.utils.flags import get_flag

    old = get_flag("FLAGS_tpu_sharded_weight_update", True)
    yield
    set_flags({"FLAGS_tpu_sharded_weight_update": old})


def _fresh():
    from paddle_tpu.core import scope as scope_mod

    framework.switch_main_program(framework.Program())
    framework.switch_startup_program(framework.Program())
    scope_mod._global_scope = scope_mod.Scope()


def _mlp_loss(uneven=True):
    framework.default_main_program().random_seed = 1234
    framework.default_startup_program().random_seed = 1234
    img = fluid.layers.data(name="img", shape=[32], dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    # size 31: not divisible by any mesh size — exercises flat-buffer
    # padding in every sharded tensor
    h = fluid.layers.fc(input=img, size=31 if uneven else 32, act="relu")
    logits = fluid.layers.fc(input=h, size=4)
    return fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(logits, label))


def _batch():
    r = np.random.RandomState(0)
    return (r.rand(64, 32).astype("float32"),
            r.randint(0, 4, (64, 1)).astype("int64"))


def _train(opt_fn, flag, ndev=8, clip=False, reg=False, fuse=False,
           steps=8, want_plan=True):
    """Losses of `steps` steps of the MLP under with_data_parallel on an
    ndev-device mesh; returns (losses, executor, program, plan)."""
    import jax

    _fresh()
    set_flags({"FLAGS_tpu_sharded_weight_update": flag})
    x, y = _batch()
    with framework.unique_name_guard():
        loss = _mlp_loss()
        if clip:
            fluid.clip.set_gradient_clip(
                fluid.clip.GradientClipByGlobalNorm(0.5))
        kwargs = {}
        if reg:
            from paddle_tpu.fluid.regularizer import L2Decay

            kwargs["regularization"] = L2Decay(1e-3)
        opt_fn(**kwargs).minimize(loss)
        fluid.clip._clip_attr.clear()
        prog = fluid.default_main_program()
        if fuse:
            from paddle_tpu.fluid.fuse_optimizer import fuse_optimizer_ops

            assert fuse_optimizer_ops(prog) > 0
        fluid.CompiledProgram(prog).with_data_parallel(
            loss_name=loss.name)
        if ndev != 8:
            from jax.sharding import Mesh

            prog._mesh = Mesh(np.array(jax.devices()[:ndev]), ("dp",))
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        losses = [float(exe.run(prog, feed={"img": x, "label": y},
                                fetch_list=[loss])[0].mean())
                  for _ in range(steps)]
        plan = getattr(prog, "_shard_plan", None)
    if flag and want_plan:
        assert plan is not None, "sharded update did not engage"
    if not flag:
        assert plan is None
    return losses, exe, prog, loss, plan


O = fluid.optimizer


@pytest.mark.parametrize("name,opt_fn,kw,exact", [
    ("adam_clip", lambda **k: O.AdamOptimizer(learning_rate=0.01, **k),
     dict(clip=True), True),
    ("adam_reg_fused",
     lambda **k: O.AdamOptimizer(learning_rate=0.01, **k),
     dict(reg=True, fuse=True), True),
    ("momentum_4dev",
     lambda **k: O.MomentumOptimizer(learning_rate=0.1, momentum=0.9,
                                     **k), dict(ndev=4), True),
    ("sgd_2dev", lambda **k: O.SGDOptimizer(learning_rate=0.1, **k),
     dict(ndev=2), True),
    ("lamb_clip_4dev",
     lambda **k: O.LambOptimizer(learning_rate=0.01, **k),
     dict(ndev=4, clip=True), False),
])
def test_sharded_vs_replicated_parity(name, opt_fn, kw, exact):
    """Sharded == replicated for Adam (+global-norm clip, +L2 reg,
    +fused groups), Momentum, SGD and LAMB (trust-ratio psum) across
    2/4/8-device meshes with an uneven (31-wide) parameter. SGD/
    Momentum/Adam are bit-identical; LAMB's psum'd norms match within
    fp32 reduction-order tolerance."""
    l_rep, *_ = _train(opt_fn, False, **kw)
    l_sh, *_ = _train(opt_fn, True, **kw)
    if exact:
        assert l_rep == l_sh, (name, l_rep, l_sh)
    else:
        np.testing.assert_allclose(l_rep, l_sh, rtol=2e-5, atol=1e-6)


def test_off_by_flag_reproduces_replicated_hlo():
    """FLAGS_tpu_sharded_weight_update=0 must lower to today's program:
    grad allreduce, NO reduce_scatter / all_gather anywhere. =1 swaps
    the grad sync to reduce_scatter + a param all_gather."""
    x, y = _batch()

    def text(flag):
        _, exe, prog, loss, _ = _train(
            lambda **k: O.AdamOptimizer(learning_rate=0.01, **k), flag,
            steps=1)
        got = exe._cached_lowerable(prog, {"img": x, "label": y},
                                    [loss], None)
        return got[1].as_text()

    t_off = text(False)
    t_on = text(True)
    assert "reduce_scatter" not in t_off and "all_gather" not in t_off
    assert "all_reduce" in t_off
    assert "reduce_scatter" in t_on and "all_gather" in t_on


def test_collective_bytes_grad_leg_halved():
    """Ring-modeled ICI bytes from the StableHLO census: the sharded
    grad exchange (reduce_scatter) costs ~half the replicated
    allreduce; the total stays ~equal (the other half moved to the
    param all_gather, off the gradient critical path)."""
    x, y = _batch()

    def census(flag):
        _, exe, prog, loss, _ = _train(
            lambda **k: O.AdamOptimizer(learning_rate=0.01, **k), flag,
            steps=1)
        return exe.collective_report(prog, feed={"img": x, "label": y},
                                     fetch_list=[loss])

    off = census(False)
    on = census(True)
    assert off["all_reduce"]["ici_bytes"] > 0
    assert "all_reduce" not in on
    rs = on["reduce_scatter"]["ici_bytes"]
    # ~half, allowing the 1/N padding overhead of uneven params
    assert rs <= 0.6 * off["all_reduce"]["ici_bytes"], (off, on)
    assert on["all_gather"]["ici_bytes"] > 0


def test_sharded_state_memory_and_donation():
    """donation_report audits the ZeRO-1 shard buffers: per-replica
    optimizer state ~1/N of the replicated footprint (within padding),
    and the sharded buffers still alias (donated) through the step."""
    x, y = _batch()
    _, exe, prog, loss, plan = _train(
        lambda **k: O.AdamOptimizer(learning_rate=0.01, **k), True,
        steps=2)
    rep = exe.donation_report(prog, feed={"img": x, "label": y},
                              fetch_list=[loss])
    assert rep is not None
    assert rep["aliases_state"], rep
    assert rep["opt_state_sharded_vars"] == len(plan.sharded_state) > 0
    logical = rep["opt_state_logical_bytes"]
    per_rep = rep["opt_state_per_replica_bytes"]
    # 8-way mesh: 1/8 plus padding (uneven 31-wide params pad each
    # flat buffer to a multiple of 8)
    assert per_rep < 0.2 * logical, rep

    # scope holds flat dp-sharded buffers between steps
    from paddle_tpu.core.scope import global_scope

    name, info = next(iter(plan.sharded_state.items()))
    v = global_scope().find_var(name)
    assert tuple(v.shape) == (info.padded,)
    assert "dp" in str(getattr(v, "sharding", ""))


def test_checkpoint_roundtrip_with_sharded_state(tmp_path):
    """save_persistables unshards optimizer state to logical shapes;
    a load + continued training matches an uninterrupted run."""
    x, y = _batch()
    adam = lambda **k: O.AdamOptimizer(learning_rate=0.01, **k)  # noqa
    # uninterrupted: 4 steps
    l_ref, *_ = _train(adam, True, steps=4)
    # interrupted: 2 steps, save, reload into a fresh scope, 2 more
    _, exe, prog, loss, plan = _train(adam, True, steps=2)
    from paddle_tpu.core.scope import global_scope

    fluid.io.save_persistables(exe, str(tmp_path), main_program=prog)
    name, info = next(iter(plan.sharded_state.items()))
    saved = np.load(os.path.join(str(tmp_path),
                                 name.replace("/", "%2F") + ".npy"))
    assert tuple(saved.shape) == info.shape, \
        "sharded state must persist at its LOGICAL shape"
    fluid.io.load_persistables(exe, str(tmp_path), main_program=prog)
    l_cont = [float(exe.run(prog, feed={"img": x, "label": y},
                            fetch_list=[loss])[0].mean())
              for _ in range(2)]
    np.testing.assert_allclose(l_ref[2:], l_cont, rtol=1e-6, atol=1e-7)


@pytest.mark.slow
def test_bert_tiny_parity_20_steps():
    """Acceptance: BERT-tiny + Adam and LAMB on the mesh, 20 steps,
    global-norm clipping — sharded losses match replicated within fp32
    tolerance."""
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from __graft_entry__ import _bert_feed
    from paddle_tpu.models import bert

    cfg = bert.BertConfig.tiny()
    seq_len, batch = 32, 16

    def run(opt_fn, flag):
        _fresh()
        set_flags({"FLAGS_tpu_sharded_weight_update": flag})
        with framework.unique_name_guard():
            framework.default_main_program().random_seed = 99
            framework.default_startup_program().random_seed = 99
            total, _, _, _ = bert.bert_pretrain_loss(
                cfg, seq_len, is_test=False)
            fluid.clip.set_gradient_clip(
                fluid.clip.GradientClipByGlobalNorm(1.0))
            opt_fn().minimize(total)
            fluid.clip._clip_attr.clear()
            prog = fluid.default_main_program()
            fluid.CompiledProgram(prog).with_data_parallel(
                loss_name=total.name)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(fluid.default_startup_program())
            feed = _bert_feed(cfg, batch, seq_len)
            out = [float(exe.run(prog, feed=feed,
                                 fetch_list=[total])[0].mean())
                   for _ in range(20)]
            assert (getattr(prog, "_shard_plan", None)
                    is not None) == flag
        return out

    for opt_fn in (lambda: O.AdamOptimizer(learning_rate=1e-3),
                   lambda: O.LambOptimizer(learning_rate=1e-3)):
        l_rep = run(opt_fn, False)
        l_sh = run(opt_fn, True)
        np.testing.assert_allclose(l_rep, l_sh, rtol=5e-5, atol=1e-5)


def test_single_element_param_stays_replica_consistent():
    """Regression: a (1,)-shaped parameter (scalar output head bias)
    must follow the SHARD layout — slot identity, not tensor size,
    decides. The size heuristic this replaces updated it on device 0
    only, silently diverging replicas (caught by test_elastic's
    resume)."""
    import jax

    _fresh()
    set_flags({"FLAGS_tpu_sharded_weight_update": True})
    from paddle_tpu import fleet
    from paddle_tpu.core.scope import global_scope

    r = np.random.RandomState(0)
    x = r.rand(16, 8).astype("float32")
    y = r.rand(16, 1).astype("float32")
    with framework.unique_name_guard():
        framework.default_main_program().random_seed = 11
        framework.default_startup_program().random_seed = 11
        xv = fluid.data(name="x", shape=[-1, 8], dtype="float32")
        yv = fluid.data(name="y", shape=[-1, 1], dtype="float32")
        pred = fluid.layers.fc(input=xv, size=1)  # (1,)-shaped bias
        loss = fluid.layers.reduce_mean(
            fluid.layers.square(pred - yv))
        fleet.init()
        fleet.distributed_optimizer(
            O.SGDOptimizer(learning_rate=0.1)).minimize(loss)
        prog = fluid.default_main_program()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        for _ in range(3):
            exe.run(prog, feed={"x": x, "y": y}, fetch_list=[loss])
        assert getattr(prog, "_shard_plan", None) is not None
        for v in prog.list_vars():
            if not v.persistable:
                continue
            val = global_scope().find_var(v.name)
            shards = [np.asarray(s.data)
                      for s in getattr(val, "addressable_shards", [])]
            for sh in shards[1:]:
                np.testing.assert_array_equal(shards[0], sh, err_msg=v.name)


def test_unsupported_program_falls_back():
    """An optimizer op the planner can't shard (dpsgd: per-element rng
    noise has no flat-shard rule) keeps the replicated update rather
    than failing. (Gradient merge — the old exemplar here — is now
    planned and sharded: tests/test_comm_overlap.py.)"""
    _fresh()
    set_flags({"FLAGS_tpu_sharded_weight_update": True})
    x, y = _batch()
    with framework.unique_name_guard():
        loss = _mlp_loss()
        opt = O.DpsgdOptimizer(learning_rate=0.1)
        opt.minimize(loss)
        prog = fluid.default_main_program()
        fluid.CompiledProgram(prog).with_data_parallel(
            loss_name=loss.name)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        out = exe.run(prog, feed={"img": x, "label": y},
                      fetch_list=[loss])[0]
        assert getattr(prog, "_shard_plan", None) is None
        assert np.isfinite(out).all()


# ---------------------------------------------------------------------------
# hapi evaluate/predict deferral (satellite)
# ---------------------------------------------------------------------------

def _hapi_model():
    from paddle_tpu.fluid.dygraph import Linear
    from paddle_tpu.hapi.model import Model
    from paddle_tpu.hapi.metrics import Accuracy

    net = Linear(16, 4)
    m = Model(net)
    m.prepare(
        fluid.optimizer.SGDOptimizer(
            learning_rate=0.1, parameter_list=net.parameters()),
        loss_function=lambda pred, label: fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(pred, label)),
        metrics=Accuracy(topk=(1,)))
    return m


class _EvalSet:
    def __init__(self, n=40):
        r = np.random.RandomState(3)
        self.x = r.rand(n, 16).astype("float32")
        self.y = r.randint(0, 4, (n, 1)).astype("int64")

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


def test_hapi_evaluate_deferred_parity_and_sync_count():
    """evaluate() defers host syncs to every log_freq steps (ROADMAP
    open item): results identical to the synchronous path, and the
    sync event fires <= ceil(steps/log_freq) + 1 times."""
    from paddle_tpu.fluid import profiler
    from paddle_tpu.utils.flags import get_flag

    data = _EvalSet(40)
    m = _hapi_model()
    set_flags({"FLAGS_tpu_deferred_fetch": False})
    r_sync = m.evaluate(data, batch_size=8, verbose=0)
    set_flags({"FLAGS_tpu_deferred_fetch": True})
    profiler.reset_profiler()
    r_defer = m.evaluate(data, batch_size=8, log_freq=2, verbose=0)
    syncs = profiler.event_count("hapi/loss_sync")
    assert 0 < syncs <= 4, syncs  # 5 steps, log_freq 2 -> <= 3 (+tail)
    assert r_sync.keys() == r_defer.keys()
    np.testing.assert_allclose(r_sync["loss"], r_defer["loss"],
                               rtol=1e-6)
    assert r_sync["acc"] == r_defer["acc"]


def test_hapi_predict_deferred_parity():
    data = _EvalSet(40)
    m = _hapi_model()
    set_flags({"FLAGS_tpu_deferred_fetch": False})
    p_sync = m.predict(data, batch_size=8, stack_outputs=True)
    set_flags({"FLAGS_tpu_deferred_fetch": True})
    p_defer = m.predict(data, batch_size=8, stack_outputs=True)
    assert len(p_sync) == len(p_defer) == 1
    np.testing.assert_array_equal(p_sync[0], p_defer[0])


def test_map_style_dataloader_device_buffer():
    """Map-style DataLoader with use_buffer_reader + an accelerator
    place yields pre-put jax arrays (reader/prefetcher.py), and the
    dygraph/hapi loops consume them without a host round-trip."""
    import jax

    from paddle_tpu.core.place import TPUPlace
    from paddle_tpu.fluid.reader import DataLoader

    data = _EvalSet(32)
    host = DataLoader(data, batch_size=8, places=None)
    dev = DataLoader(data, batch_size=8, places=[TPUPlace()])
    host_batches = list(host)
    dev_batches = list(dev)
    assert len(host_batches) == len(dev_batches) == 4
    for hb, db in zip(host_batches, dev_batches):
        for h, d in zip(hb, db):
            assert isinstance(d, jax.Array), type(d)
            np.testing.assert_array_equal(np.asarray(h), np.asarray(d))
    # off switch: host numpy contract preserved
    off = DataLoader(data, batch_size=8, places=[TPUPlace()],
                     use_buffer_reader=False)
    assert isinstance(next(iter(off))[0], np.ndarray)
    # hapi fit consumes the pre-put batches (device passthrough)
    m = _hapi_model()
    hist = m.fit(dev, batch_size=8, epochs=1, verbose=0)
    assert np.isfinite(hist[-1]["loss"])


# ---------------------------------------------------------------------------
# cross-rank checkpoint-step agreement (satellite)
# ---------------------------------------------------------------------------

def _two_rank_group():
    import socket

    from paddle_tpu.distributed.host_collectives import \
        HostCollectiveGroup

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    ep = "127.0.0.1:%d" % port
    out = {}

    def mk(rank):
        out[rank] = HostCollectiveGroup(rank, 2, ep, timeout_s=60,
                                        heartbeat_s=0)

    t = threading.Thread(target=mk, args=(1,), daemon=True)
    t.start()
    mk(0)
    t.join(timeout=30)
    return out[0], out[1]


def test_fluid_checkpoint_agreement_on_truncated_rank(tmp_path):
    """Fault injection: rank 1's NEWEST checkpoint dir is truncated.
    Without agreement each rank would pick a different step (silent
    divergence); with the allreduce-min protocol both ranks land on the
    newest step intact EVERYWHERE."""
    from paddle_tpu.core.scope import Scope
    from paddle_tpu.fluid import checkpoint as ckpt

    _fresh()
    with framework.unique_name_guard():
        loss = _mlp_loss()
        O.SGDOptimizer(learning_rate=0.1).minimize(loss)
        prog = fluid.default_main_program()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        x, y = _batch()
        roots = [str(tmp_path / "rank0"), str(tmp_path / "rank1")]
        for step in range(2):
            exe.run(prog, feed={"img": x, "label": y},
                    fetch_list=[loss])
            for root in roots:
                ckpt.save_checkpoint(
                    exe, root, ckpt.TrainStatus(epoch_no=step),
                    main_program=prog)
        # truncate rank 1's newest published dir's payload
        latest = ckpt.latest_checkpoint_dir(roots[1])
        payload = os.path.join(latest, "persistables.pkl")
        with open(payload, "wb") as f:
            f.write(b"\x00")

        g0, g1 = _two_rank_group()
        res = {}

        def load(rank, grp):
            res[rank] = ckpt.load_checkpoint(
                None, roots[rank], main_program=prog, scope=Scope(),
                group=grp)

        t = threading.Thread(target=load, args=(1, g1), daemon=True)
        t.start()
        load(0, g0)
        t.join(timeout=60)
        assert not t.is_alive()
        # both ranks agreed on the OLDER, everywhere-intact step —
        # rank 0's own newest dir was fine, yet it must not use it
        assert res[0].epoch_no == res[1].epoch_no == 0
        g1.shutdown()
        g0.shutdown()


def test_sharded_manager_agreement_on_truncated_rank(tmp_path):
    """Same protocol through ShardedCheckpointManager.restore(group=):
    one rank's newest orbax step truncated -> both agree on step 1."""
    import glob

    import jax.numpy as jnp

    from paddle_tpu.distributed import ShardedCheckpointManager

    trees = {}
    mgrs = {}
    for rank in (0, 1):
        d = str(tmp_path / ("r%d" % rank))
        mgr = ShardedCheckpointManager(d, max_to_keep=3)
        tree = {"w": jnp.arange(4.0) + rank}
        for step in (1, 2):
            mgr.save(step, dict(tree, step=jnp.int32(step)))
        trees[rank], mgrs[rank] = tree, mgr
    # truncate rank 1's step 2
    step_dir = str(tmp_path / "r1" / "2")
    files = [p for p in glob.glob(os.path.join(step_dir, "**"),
                                  recursive=True) if os.path.isfile(p)]
    assert files
    for p in files:
        open(p, "w").close()

    g0, g1 = _two_rank_group()
    res = {}

    def restore(rank, grp):
        res[rank] = mgrs[rank].restore(
            template=dict(trees[rank], step=jnp.int32(0)), group=grp)

    t = threading.Thread(target=restore, args=(1, g1), daemon=True)
    t.start()
    restore(0, g0)
    t.join(timeout=120)
    assert not t.is_alive()
    assert int(res[0]["step"]) == int(res[1]["step"]) == 1
    for mgr in mgrs.values():
        mgr.close()
    g1.shutdown()
    g0.shutdown()
