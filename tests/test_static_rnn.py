"""StaticRNN builder (reference: layers/control_flow.py StaticRNN +
recurrent_op.cc; here the step template unrolls at build time)."""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework


def test_static_rnn_matches_numpy(rng):
    T, B, D, H = 4, 3, 5, 6
    x_np = rng.rand(T, B, D).astype("float32")

    x = fluid.layers.data(name="x", shape=[B, D],
                          append_batch_size=False, dtype="float32")
    # feed provides the time-major [T, B, D] tensor
    x.shape = (T, B, D)

    rnn = fluid.layers.StaticRNN()
    with rnn.step():
        word = rnn.step_input(x)
        prev = rnn.memory(shape=[-1, H], batch_ref=word)
        hidden = fluid.layers.fc(
            input=[word, prev], size=H, act="relu",
            param_attr=fluid.ParamAttr(name="rnn_w"),
            bias_attr=fluid.ParamAttr(name="rnn_b"))
        rnn.update_memory(prev, hidden)
        rnn.step_output(hidden)
    out = rnn()
    assert tuple(out.shape) == (T, B, H)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    got = np.asarray(exe.run(feed={"x": x_np}, fetch_list=[out])[0])

    from paddle_tpu.core.scope import global_scope

    # fc over [word, prev] = two weight matrices + shared bias; the
    # second weight auto-names (a named param_attr applies to the first
    # input only, reference multiple_param_attr semantics)
    mul_ws = [op.input_names["Y"][0]
              for op in fluid.default_main_program().global_block().ops
              if op.type == "mul"][:2]
    w1 = np.asarray(global_scope().find_var(mul_ws[0]))
    w2 = np.asarray(global_scope().find_var(mul_ws[1]))
    b = np.asarray(global_scope().find_var("rnn_b"))
    assert mul_ws[0] == "rnn_w" and mul_ws[1] != "rnn_w"

    h = np.zeros((B, H), "float32")
    want = []
    for t in range(T):
        h = np.maximum(x_np[t] @ w1 + h @ w2 + b, 0.0)
        want.append(h)
    np.testing.assert_allclose(got, np.stack(want), rtol=1e-4,
                               atol=1e-5)


def test_static_rnn_trains(rng):
    T, B, D = 3, 4, 5
    x = fluid.layers.data(name="x", shape=[B, D],
                          append_batch_size=False, dtype="float32")
    x.shape = (T, B, D)
    label = fluid.layers.data(name="y", shape=[B, 1],
                              append_batch_size=False, dtype="float32")

    rnn = fluid.layers.StaticRNN()
    with rnn.step():
        w = rnn.step_input(x)
        prev = rnn.memory(shape=[-1, 8], batch_ref=w)
        h = fluid.layers.fc(input=[w, prev], size=8, act="tanh")
        rnn.update_memory(prev, h)
        rnn.step_output(h)
    seq = rnn()
    last = fluid.layers.slice(seq, axes=[0], starts=[T - 1], ends=[T])
    last = fluid.layers.reshape(last, [B, 8])
    pred = fluid.layers.fc(input=last, size=1)
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, label))
    fluid.optimizer.AdamOptimizer(0.05).minimize(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    feed = {"x": rng.rand(T, B, D).astype("float32"),
            "y": rng.rand(B, 1).astype("float32")}
    losses = [float(np.asarray(exe.run(feed=feed,
                                       fetch_list=[loss])[0]).ravel()[0])
              for _ in range(8)]
    assert losses[-1] < losses[0], losses


def test_static_rnn_memory_init_and_errors(rng):
    T, B, H = 3, 2, 4
    x = fluid.layers.data(name="x", shape=[B, H],
                          append_batch_size=False, dtype="float32")
    x.shape = (T, B, H)
    init = fluid.layers.data(name="h0", shape=[B, H],
                             append_batch_size=False, dtype="float32")

    rnn = fluid.layers.StaticRNN()
    with rnn.step():
        w = rnn.step_input(x)
        prev = rnn.memory(init=init)
        nxt = fluid.layers.elementwise_add(w, prev)
        rnn.update_memory(prev, nxt)
        rnn.step_output(nxt)
    out = rnn()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    x_np = rng.rand(T, B, H).astype("float32")
    h0 = rng.rand(B, H).astype("float32")
    got = np.asarray(exe.run(feed={"x": x_np, "h0": h0},
                             fetch_list=[out])[0])
    want = np.stack([h0 + x_np[:t + 1].sum(0) for t in range(T)])
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    rnn2 = fluid.layers.StaticRNN()
    with pytest.raises(ValueError, match="step_input"):
        rnn2.step_input(x)  # outside step()
