"""Enforce/error system with op callstack attribution (reference:
platform/enforce.h + op_call_stack.cc) and the memory facade
(memory/malloc.h + monitor.h stats)."""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.core import errors, memory
from paddle_tpu.fluid import framework


def test_enforce_taxonomy():
    with pytest.raises(errors.InvalidArgumentError):
        errors.enforce(False, "bad arg")
    with pytest.raises(errors.NotFoundError):
        errors.enforce_not_none(None, "thing")
    assert errors.UnimplementedError.code == "UNIMPLEMENTED"
    assert issubclass(errors.OutOfRangeError, errors.EnforceNotMet)


def test_op_error_carries_creation_site():
    """A failing op's error names THIS test file as the creation site
    (reference: InsertCallStackInfo in op_call_stack.cc)."""
    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup):
        with framework.unique_name_guard():
            x = fluid.layers.data(name="x", shape=[4], dtype="float32")
            # op created HERE with an impossible target shape
            y = fluid.layers.reshape(x, [3, 5])
    from paddle_tpu.core.scope import Scope

    exe = fluid.Executor(fluid.CPUPlace())
    with pytest.raises(Exception) as ei:
        exe.run(main, feed={"x": np.zeros((2, 4), "float32")},
                fetch_list=[y], scope=Scope())
    msg = str(ei.value)
    assert "op created at" in msg
    assert "test_errors_memory.py" in msg


def test_memory_facade_host_alloc():
    a = memory.Alloc(fluid.CPUPlace(), 1024)
    assert a.size == 1024 and a.ptr
    memory.Free(a)

    with pytest.raises(errors.UnavailableError):
        memory.Alloc(fluid.TPUPlace(), 1024)


def test_memory_stats_surface():
    stats = memory.memory_stats()
    assert isinstance(stats, dict)
    # CPU backends may expose no PJRT stats; the API must still answer
    assert memory.memory_allocated() >= 0
    assert memory.max_memory_allocated() >= 0
