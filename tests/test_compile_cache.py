"""Persistent compilation cache + AOT warmup (ROADMAP direction 4).

Covers: cross-process warm restart (bit-identical outputs, miss ->
hit), fingerprint invalidation on lowering-relevant flag flips and
mesh-shape changes, in-memory LRU eviction dropping AOT artifacts
while the persistent tier survives (re-admission is a HIT, not a fresh
compile), the `Executor.warmup` surface (feed-shape buckets + elastic
mesh variants, no state mutation), the registry-assembled
`compile_cache` bench block, telemetry-schema validity of the new
events, and the supervised elastic shrink's coordination/compile
recovery split.
"""
import json
import os as _os
import subprocess as _sp
import sys as _sys

import numpy as np
import pytest

_REPO = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
_RUNNER = _os.path.join(_REPO, "tests", "compile_cache_runner.py")


def _base_env(**extra):
    env = dict(_os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.update(extra)
    return env


@pytest.fixture
def cc_env(tmp_path):
    """Arm the persistent tier at a tmp dir for one test; restore the
    flag, jax config, module stats and registry afterwards."""
    from paddle_tpu import observability as obs
    from paddle_tpu.fluid import compile_cache as cc
    from paddle_tpu.utils.flags import get_flag, set_flags

    old = {k: get_flag(k) for k in ("FLAGS_tpu_compile_cache_dir",
                                    "FLAGS_tpu_compile_cache_size")}
    cdir = str(tmp_path / "cache")
    set_flags({"FLAGS_tpu_compile_cache_dir": cdir})
    cc._reset_for_tests()
    obs.reset_registry()
    from paddle_tpu.observability import flight

    flight._reset_for_tests()
    yield cdir
    cc.disable()
    cc._reset_for_tests()
    set_flags(old)
    obs.reset_registry()
    flight._reset_for_tests()


def _build(width=16):
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import framework

    main, startup = fluid.Program(), fluid.Program()
    with framework.program_guard(main, startup), \
            framework.unique_name_guard():
        main.random_seed = startup.random_seed = 7
        x = fluid.data(name="x", shape=[-1, 8], dtype="float32")
        y = fluid.data(name="y", shape=[-1, 1], dtype="float32")
        h = fluid.layers.fc(input=x, size=width, act="tanh")
        pred = fluid.layers.fc(input=h, size=1)
        loss = fluid.layers.reduce_mean(
            fluid.layers.square(pred - y))
        fluid.optimizer.SGDOptimizer(
            learning_rate=0.1).minimize(loss)
    return main, startup, loss


def _feed(batch=4):
    rng = np.random.RandomState(42)
    return {"x": rng.randn(batch, 8).astype("float32"),
            "y": rng.randn(batch, 1).astype("float32")}


def _cc_events():
    from paddle_tpu.observability import flight

    return [e for e in flight.recorder().snapshot()["events"]
            if e.get("event") == "compile_cache"]


# -- cross-process warm restart (the acceptance proof) ------------------

def test_warm_restart_second_process_hits_bit_identical(tmp_path):
    """A second process running the same program must classify every
    fresh compile as a persistent-cache HIT, record compile_cache
    events saying so, and produce bit-identical losses."""
    cache = str(tmp_path / "cache")
    results, streams = [], []
    for i in (1, 2):
        tdir = str(tmp_path / ("telemetry%d" % i))
        proc = _sp.run(
            [_sys.executable, _RUNNER, "3"],
            env=_base_env(FLAGS_tpu_compile_cache_dir=cache,
                          FLAGS_tpu_telemetry_dir=tdir),
            cwd=_REPO, stdout=_sp.PIPE, stderr=_sp.STDOUT, text=True,
            timeout=240)
        assert proc.returncode == 0, proc.stdout
        line = [ln for ln in proc.stdout.splitlines()
                if ln.startswith("RESULT ")][-1]
        results.append(json.loads(line[len("RESULT "):]))
        recs = []
        for fname in sorted(_os.listdir(tdir)):
            if fname.startswith("telemetry.rank") and \
                    fname.endswith(".jsonl"):
                with open(_os.path.join(tdir, fname)) as f:
                    recs.extend(json.loads(ln) for ln in f
                                if ln.strip())
        streams.append(recs)

    cold, warm = results
    assert cold["enabled"] and warm["enabled"]
    # bit-identical: the warm process deserialized, it did not diverge
    assert cold["losses"] == warm["losses"]
    assert cold["misses"] >= 1 and cold["hits"] == 0
    assert warm["hits"] >= 1 and warm["misses"] == 0

    cold_evs = [r for r in streams[0]
                if r.get("event") == "compile_cache"]
    warm_evs = [r for r in streams[1]
                if r.get("event") == "compile_cache"]
    assert cold_evs and all(e["status"] == "miss" for e in cold_evs)
    assert warm_evs and all(e["status"] == "hit" for e in warm_evs)
    # the hit's saved_ms is bookkept from the cold process's sentinel
    assert any(e["saved_ms"] >= 0.0 for e in warm_evs)
    # misses record the on-disk bytes they wrote
    assert any(e["bytes"] > 0 for e in cold_evs)
    # same fingerprints across processes (determinism of the key)
    assert sorted(e["key"] for e in cold_evs) == \
        sorted(e["key"] for e in warm_evs)
    # every record in both streams validates against the locked schema
    from paddle_tpu.observability import schema as tschema

    sch = tschema.load_schema()
    for recs in streams:
        assert tschema.validate_records(recs, sch) == []


# -- fingerprint semantics ----------------------------------------------

def test_fingerprint_invalidates_on_flags_and_mesh(cc_env):
    import jax
    from jax.sharding import Mesh

    from paddle_tpu.fluid import compile_cache as cc
    from paddle_tpu.utils.flags import get_flag, set_flags

    text = "module @jit_f { func @main() { return } }"
    mesh2 = Mesh(np.array(jax.devices()[:2]), ("dp",))
    mesh4 = Mesh(np.array(jax.devices()[:4]), ("dp",))
    base = cc.fingerprint(text, mesh2)
    assert base == cc.fingerprint(text, mesh2)  # deterministic
    assert base != cc.fingerprint(text, mesh4)  # mesh shape keys
    assert base != cc.fingerprint(text + " ", None)
    flips = {
        "FLAGS_tpu_comm_bucket_mb": 1.0,
        "FLAGS_tpu_amp_level": "O2",
        "FLAGS_tpu_dcn_replicas": 2,
        "FLAGS_tpu_sharded_weight_update": False,
    }
    for name, val in flips.items():
        old = get_flag(name)
        assert val != old, name
        set_flags({name: val})
        try:
            assert cc.fingerprint(text, mesh2) != base, \
                "flipping %s must invalidate the cache key" % name
        finally:
            set_flags({name: old})
    assert cc.fingerprint(text, mesh2) == base  # restored -> same key
    # loc() debug metadata is NOT part of the key (repo moves must not
    # cold-start the fleet)
    assert cc.fingerprint(
        'module @jit_f loc("/tmp/x.py":1:2) { }', mesh2) == \
        cc.fingerprint('module @jit_f loc("/elsewhere.py":9:9) { }',
                       mesh2)


def test_same_program_same_fingerprint_in_process_hit(cc_env):
    """An identical program rebuilt in the SAME process fingerprints
    identically and classifies as a hit via the index sentinel."""
    import paddle_tpu.fluid as fluid

    main1, startup1, loss1 = _build()
    exe = fluid.Executor()
    exe.run(startup1)
    exe.run(main1, feed=_feed(), fetch_list=[loss1.name])
    evs = _cc_events()
    assert evs and evs[-1]["status"] == "miss"

    main2, startup2, loss2 = _build()
    exe2 = fluid.Executor()
    exe2.run(startup2)
    exe2.run(main2, feed=_feed(), fetch_list=[loss2.name])
    evs2 = _cc_events()[len(evs):]
    by_status = [e["status"] for e in evs2]
    assert "hit" in by_status and "miss" not in by_status, evs2
    # identical structure -> identical fingerprint
    keys1 = {e["key"] for e in evs}
    keys2 = {e["key"] for e in evs2}
    assert keys2 <= keys1


# -- LRU eviction interplay ---------------------------------------------

def test_eviction_drops_aot_and_readmission_is_persistent_hit(cc_env):
    """FLAGS_tpu_compile_cache_size eviction drops entry.aot_compiled
    eagerly; the evicted program re-admitted later is a
    persistent-cache HIT, not a fresh compile."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.utils.flags import set_flags

    set_flags({"FLAGS_tpu_compile_cache_size": 1})
    main_a, startup_a, loss_a = _build(width=16)
    exe = fluid.Executor()
    exe.run(startup_a)  # evicted by the next insert (limit 1)
    exe.run(main_a, feed=_feed(), fetch_list=[loss_a.name])
    assert len(exe._cache) == 1
    entry_a = next(iter(exe._cache.values()))
    # populate the AOT artifact the report surfaces memoize
    assert exe.donation_report(main_a, feed=_feed(),
                               fetch_list=[loss_a.name]) is not None
    assert entry_a.aot_compiled is not None

    main_b, startup_b, loss_b = _build(width=24)
    exe.run(startup_b)  # evicts A's entry
    assert entry_a.aot_compiled is None, \
        "eviction must drop AOT artifacts eagerly"
    exe.run(main_b, feed=_feed(), fetch_list=[loss_b.name])

    n_before = len(_cc_events())
    exe.run(main_a, feed=_feed(), fetch_list=[loss_a.name])
    readmit = _cc_events()[n_before:]
    assert readmit and readmit[-1]["status"] == "hit", readmit


# -- warmup surface ------------------------------------------------------

def test_warmup_precompiles_without_mutating_state(cc_env):
    import paddle_tpu.fluid as fluid
    from paddle_tpu.core.scope import global_scope
    from paddle_tpu.fluid import compile_cache as cc

    main, startup, loss = _build()
    exe = fluid.Executor()
    exe.run(startup)
    params = [p.name for p in main.all_parameters()]
    before = {n: np.asarray(global_scope().find_var(n)).copy()
              for n in params}
    seed_counter = main._seed_counter

    rep = exe.warmup(main, shapes=[{"x": (4, 8), "y": (4, 1)}],
                     fetch_list=[loss.name])
    assert len(rep["compiled"]) == 1 and not rep["skipped"], rep
    assert main._seed_counter == seed_counter  # RNG stream untouched
    for n in params:
        after = np.asarray(global_scope().find_var(n))
        assert (before[n] == after).all(), \
            "warmup mutated state %s" % n
    evs = _cc_events()
    assert any(e["source"] == "warmup" for e in evs)

    # the first REAL step of the warmed shape pays zero XLA compiles
    snap = cc.jax_stats()
    out = exe.run(main, feed=_feed(), fetch_list=[loss.name])
    assert np.isfinite(np.asarray(out[0])).all()
    assert cc.stats_delta(snap)["backend_compiles"] == 0, \
        "warmed shape must not recompile on first traffic"


def test_warmup_shape_validation_and_cached_report(cc_env):
    import paddle_tpu.fluid as fluid

    main, startup, loss = _build()
    exe = fluid.Executor()
    exe.run(startup)
    rep = exe.warmup(main, shapes=[{"x": (-1, 8), "y": (4, 1)}],
                     fetch_list=[loss.name])
    assert rep["skipped"] and \
        "concrete" in rep["skipped"][0]["error"]
    exe.warmup(main, shapes=[{"x": (4, 8), "y": (4, 1)}],
               fetch_list=[loss.name])
    rep2 = exe.warmup(main, shapes=[{"x": (4, 8), "y": (4, 1)}],
                      fetch_list=[loss.name])
    assert rep2["cached"] and not rep2["compiled"]


def test_warmup_mesh_variants_populate_persistent_tier(cc_env):
    """Data-parallel program: warmup(meshes=[...]) pre-compiles OTHER
    mesh topologies into the persistent tier via a program clone —
    the live program and in-memory LRU stay untouched."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import compile_cache as cc

    main, startup, loss = _build()
    main._data_parallel = True
    exe = fluid.Executor()
    exe.run(startup)
    rep = exe.warmup(main, shapes=[{"x": (8, 8), "y": (8, 1)}],
                     meshes=[4, 2], fetch_list=[loss.name])
    n_cache = len(exe._cache)
    # base mesh + 2 variants compiled; batch 8 divides 8, 4 and 2
    assert len(rep["compiled"]) == 3, rep
    # the live program keeps ITS mesh (the full 8-device default its
    # own compile pinned); variant meshes only ever touch the clone
    import jax

    assert main._mesh is not None
    assert main._mesh.devices.size == len(jax.devices())
    # variant entries never land in the in-memory LRU (clone compiles
    # run with use_cache off): base bucket + startup only
    assert n_cache == 2, exe._cache.keys()
    st = cc.stats()
    assert st["index_entries"] >= 3
    assert st["persistent_entries"] > 0


def test_warmup_borrows_shapes_and_reports_oversized_variants(cc_env):
    """meshes= without shapes borrows the feed buckets of entries real
    traffic already compiled; an integer variant exceeding the local
    device count lands in report["skipped"], never silently drops."""
    import paddle_tpu.fluid as fluid

    main, startup, loss = _build()
    main._data_parallel = True
    exe = fluid.Executor()
    exe.run(startup)
    # no traffic yet and no shapes: nothing to borrow
    rep0 = exe.warmup(main, meshes=[4], fetch_list=[loss.name])
    assert rep0["skipped"] and "shapes" in rep0["skipped"][0]["reason"]
    exe.run(main, feed=_feed(batch=8), fetch_list=[loss.name])
    rep = exe.warmup(main, meshes=[4, 99], fetch_list=[loss.name])
    assert len(rep["compiled"]) == 1, rep  # borrowed (8, ...) bucket
    over = [s for s in rep["skipped"]
            if s.get("mesh_devices") == 99]
    assert over and "device count" in over[0]["reason"], rep


def test_warmup_enters_hbm_preflight_gate(cc_env):
    """A warmup-cached entry must not let the first real run cache-hit
    past FLAGS_tpu_hbm_budget_mb: an over-budget bucket is reported
    skipped and NOT cached."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.utils.flags import set_flags

    main, startup, loss = _build()
    exe = fluid.Executor()
    exe.run(startup)
    set_flags({"FLAGS_tpu_hbm_budget_mb": 1e-6})  # below any program
    try:
        rep = exe.warmup(main, shapes=[{"x": (4, 8), "y": (4, 1)}],
                         fetch_list=[loss.name])
    finally:
        set_flags({"FLAGS_tpu_hbm_budget_mb": 0.0})
    assert rep["skipped"] and not rep["compiled"], rep
    assert "HbmBudgetExceeded" in rep["skipped"][0]["error"] or \
        "budget" in rep["skipped"][0]["error"].lower(), rep
    # the rejected entry is NOT left in the LRU (startup's entry only)
    assert len(exe._cache) == 1


def test_elastic_mesh_variants_enumeration():
    import jax
    from jax.sharding import Mesh

    from paddle_tpu.parallel import env as penv

    devs = jax.devices()
    flat = Mesh(np.array(devs), ("dp",))
    variants = penv.elastic_mesh_variants(flat, min_ranks=5)
    assert [n for n, _ in variants] == [7, 6, 5]
    assert all(m.axis_names == ("dp",) for _, m in variants)
    # pod-aware: a (2, 4) hybrid base stays rectangular where N'
    # divides dcn=2, else falls back flat — mirroring _pod_shrink
    hybrid = Mesh(np.array(devs).reshape(2, 4), ("dcn", "ici"))
    hv = dict(penv.elastic_mesh_variants(hybrid, min_ranks=4))
    assert hv[6].axis_names == ("dcn", "ici") and \
        hv[6].shape["ici"] == 3
    assert hv[7].axis_names == ("dp",)
    assert hv[4].axis_names == ("dcn", "ici") and \
        hv[4].shape["ici"] == 2
    # mesh_for_world: hybrid when the pod count divides, else flat
    m = penv.mesh_for_world(4, dcn=2)
    assert m.axis_names == ("dcn", "ici")
    m = penv.mesh_for_world(3, dcn=2)
    assert m.axis_names == ("dp",)
    assert penv.mesh_for_world(len(devs) + 1) is None


# -- bench block + schema (CI satellite) --------------------------------

def test_compile_cache_bench_block_registry_assembled(cc_env):
    import paddle_tpu.fluid as fluid
    from paddle_tpu.observability import publish, registry
    from paddle_tpu.observability import schema as tschema

    main, startup, loss = _build()
    exe = fluid.Executor()
    exe.run(startup)
    exe.run(main, feed=_feed(), fetch_list=[loss.name])

    block = publish.compile_cache_block()
    assert block is not None
    assert block["enabled"] and block["misses"] >= 1
    assert block["dir"] == cc_env
    assert block["compile_ms_total"] > 0
    assert block["persistent_entries"] > 0
    # registry-assembled: the block must be readable back from the ONE
    # registry, exactly where bench.py's bench_blocks() reads it
    assert registry().blocks().get("compile_cache") == block
    snap = registry().snapshot()
    assert snap["counters"].get("compile_cache.miss", 0) >= 1
    assert "compile_cache.compile_ms_total" in snap["gauges"]

    # the new events validate against the locked telemetry schema,
    # which carries an explicit compile_cache contract
    sch = tschema.load_schema()
    assert "compile_cache" in sch["kinds"]["event"]["events"]
    evs = _cc_events()
    assert evs
    assert tschema.validate_records(evs, sch) == []
    # a compile_cache event missing its required fields is rejected
    bad = dict(evs[-1])
    bad.pop("status")
    assert tschema.validate_record(bad, sch) != []


def test_disabled_tier_emits_nothing():
    """FLAGS_tpu_compile_cache_dir unset (the default): no events, no
    classification, entries carry no fingerprint — byte-identical to
    the pre-cache executor."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import compile_cache as cc
    from paddle_tpu.observability import flight

    assert not cc.enabled()
    flight._reset_for_tests()
    main, startup, loss = _build()
    exe = fluid.Executor()
    exe.run(startup)
    exe.run(main, feed=_feed(), fetch_list=[loss.name])
    assert _cc_events() == []
    entry = list(exe._cache.values())[-1]
    assert entry.cc_fingerprint is None
    flight._reset_for_tests()


# -- supervised elastic shrink: warm restart + recovery split -----------

def test_supervised_elastic_shrink_warm_restart_splits_recovery(
        tmp_path):
    """2-rank cohort loses rank 1 for good; the supervisor shrinks to
    world 1 and respawns. The respawned worker compiles THROUGH the
    supervisor-exported <log_dir>/compile_cache (attempt 1 records
    HITS where attempt 0 recorded misses) and the elastic_transition
    event splits recovery into coordination_s + compile_s."""
    log_dir = str(tmp_path / "logs")
    env = _base_env()
    env.pop("FLAGS_tpu_compile_cache_dir", None)
    env.pop("FLAGS_tpu_telemetry_dir", None)
    proc = _sp.run(
        [_sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--hosts", "127.0.0.1:6921,127.0.0.1:6922",
         "--log_dir", log_dir, "--max_restarts", "1",
         "--min_ranks", "1", _RUNNER, "3", "elastic"],
        env=env, cwd=_REPO, stdout=_sp.PIPE, stderr=_sp.STDOUT,
        text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout
    assert "elastic shrink 2 -> 1" in proc.stdout, proc.stdout

    tdir = _os.path.join(log_dir, "telemetry")
    sup = _os.path.join(tdir, "telemetry.supervisor.jsonl")
    recs = [json.loads(ln) for ln in open(sup) if ln.strip()]
    evs = [r for r in recs if r.get("event") == "elastic_transition"]
    assert len(evs) == 1
    ev = evs[0]
    assert ev["old_world"] == 2 and ev["new_world"] == 1
    assert ev["coordination_s"] >= 0
    # the respawned worker's first-step compile, read from its
    # telemetry stream — reported SEPARATELY from coordination
    assert "compile_s" in ev, ev
    assert ev["compile_s"] > 0
    assert ev["recovery_s"] == pytest.approx(
        ev["coordination_s"] + ev["compile_s"], abs=1e-3)
    from paddle_tpu.observability import schema as tschema

    assert tschema.validate_record(ev, tschema.load_schema()) == []

    def _events_under(d):
        out = []
        for fname in sorted(_os.listdir(d)):
            if fname.startswith("telemetry.rank") and \
                    fname.endswith(".jsonl"):
                with open(_os.path.join(d, fname)) as f:
                    out.extend(json.loads(ln) for ln in f
                               if ln.strip())
        return [r for r in out if r.get("event") == "compile_cache"]

    # attempt 0 (collected into postmortem/) compiled cold
    pm0 = _os.path.join(log_dir, "postmortem", "attempt0")
    cold = _events_under(pm0)
    assert cold and any(e["status"] == "miss" for e in cold)
    # attempt 1 (live telemetry dir) compiled WARM from the shared dir
    warm = _events_under(tdir)
    assert warm and all(e["status"] == "hit" for e in warm), warm

    # the persistent tier itself lives beside the logs and survived
    ccdir = _os.path.join(log_dir, "compile_cache")
    assert _os.path.isdir(_os.path.join(ccdir, "index"))

    # perf_analysis --compile-cache aggregates the whole run
    _sys.path.insert(0, _os.path.join(_REPO, "tools"))
    try:
        import perf_analysis

        rc = perf_analysis.compile_cache_report(log_dir=log_dir)
    finally:
        _sys.path.pop(0)
    assert rc == 0
