"""tools/timeline.py — distributed chrome-trace merge (reference:
tools/timeline.py:32 multi-trainer profile merge)."""
import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "tools"))
import timeline  # noqa: E402


def _trace(names, pid=0):
    return {"traceEvents": [
        {"name": n, "ph": "X", "pid": pid, "tid": 1,
         "ts": 10 * i, "dur": 5, "cat": "host"}
        for i, n in enumerate(names)]}


def test_parse_profile_spec_named_and_bare():
    got = timeline.parse_profile_spec("t0=a.json,t1=b.json")
    assert got == [("t0", "a.json"), ("t1", "b.json")]
    got = timeline.parse_profile_spec("a.json,b.json")
    assert got == [("proc0", "a.json"), ("proc1", "b.json")]
    with pytest.raises(ValueError):
        timeline.parse_profile_spec("t=a.json,t=b.json")
    with pytest.raises(ValueError):
        timeline.parse_profile_spec("")


def test_merge_assigns_disjoint_labelled_lanes():
    t0, t1 = _trace(["fc", "softmax"]), _trace(["fc", "softmax"], pid=3)
    merged = timeline.merge_traces([("trainer0", t0), ("trainer1", t1)])
    evs = merged["traceEvents"]
    xs = [e for e in evs if e.get("ph") == "X"]
    # dense per-lane remap: lane 1's single pid lands at its lane base
    assert {e["pid"] for e in xs} == {0, 1000}
    names = {(e["pid"], e["args"]["name"]) for e in evs
             if e["name"] == "process_name"}
    assert (0, "trainer0") in names and (1000, "trainer1") in names
    # sort hints land on the pids that actually carry events
    sorts = {e["pid"] for e in evs if e["name"] == "process_sort_index"}
    assert sorts == {0, 1000}
    # originals untouched (merge copies events)
    assert all(e["pid"] == 3 for e in t1["traceEvents"])


def test_merge_survives_os_pids():
    """Real exporters emit OS pids (e.g. 7716): lanes must stay
    disjoint — a fixed lane*1000 offset would collide 7716 with a
    second lane's range."""
    merged = timeline.merge_traces([
        ("a", _trace(["op"], pid=7716)),
        ("b", _trace(["op"], pid=3)),
    ])
    evs = merged["traceEvents"]
    by_lane = {}
    for e in evs:
        if e["name"] == "process_name":
            by_lane.setdefault(e["args"]["name"], set()).add(e["pid"])
    assert by_lane["a"].isdisjoint(by_lane["b"]), by_lane


def test_merge_accepts_bare_array_traces():
    merged = timeline.merge_traces([
        ("a", _trace(["op"])["traceEvents"]),  # bare JSON-array form
        ("b", _trace(["op"])),
    ])
    xs = [e for e in merged["traceEvents"] if e.get("ph") == "X"]
    assert {e["pid"] for e in xs} == {0, 1000}


# -- telemetry JSONL merge (paddle_tpu/observability sink) ------------------

def _write_jsonl(path, records):
    with open(path, "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")


def _step(rank, step, ts, total_ms=10.0):
    return {"kind": "step", "rank": rank, "step": step, "ts": ts,
            "feed_ms": 1.0, "dispatch_ms": 5.0, "comm_ms": 0.0,
            "sync_ms": 2.0, "host_ms": 2.0, "total_ms": total_ms}


def _coll(rank, step, ts, key):
    return {"kind": "event", "event": "collective", "rank": rank,
            "step": step, "ts": ts, "op": "barrier", "key": key,
            "dur_ms": 1.0}


def _telemetry_dir(tmp_path, skew=5.0):
    """Two ranks, rank 1's wall clock `skew` seconds AHEAD; shared
    barrier keys anchor the correction."""
    d = tmp_path / "telemetry"
    d.mkdir()
    r0 = [_step(0, 1, 100.0), _coll(0, 1, 100.01, "barrier#1"),
          _step(0, 2, 101.0), _coll(0, 2, 101.01, "barrier#2")]
    r1 = [_step(1, 1, 100.0 + skew),
          _coll(1, 1, 100.01 + skew, "barrier#1"),
          _step(1, 2, 101.0 + skew),
          _coll(1, 2, 101.01 + skew, "barrier#2")]
    _write_jsonl(d / "telemetry.rank0.jsonl", r0)
    _write_jsonl(d / "telemetry.rank1.jsonl", r1)
    return str(d)


def test_clock_offsets_from_barrier_anchors(tmp_path):
    from paddle_tpu.observability.aggregate import load_telemetry_dir

    by_rank = load_telemetry_dir(_telemetry_dir(tmp_path, skew=5.0))
    offs = timeline.clock_offsets(by_rank)
    assert offs[0] == 0.0
    # rank 1 reads 5s ahead; the correction shifts it back
    assert abs(offs[1] - (-5.0)) < 1e-6
    # a rank sharing no keys with the reference: offset 0, not a crash
    by_rank[2] = [_coll(2, 1, 50.0, "other#1")]
    assert timeline.clock_offsets(by_rank)[2] == 0.0


def test_broadcast_events_do_not_anchor_clock_offsets(tmp_path):
    """Broadcast completion instants differ by real execution lag (the
    root returns right after its put; each non-root whenever IT
    arrives) — they must not enter the anchor pool, or a straggler's
    lag would be misread as clock skew."""
    def bcast(rank, ts, key):
        return {"kind": "event", "event": "collective", "rank": rank,
                "step": 1, "ts": ts, "op": "broadcast", "key": key,
                "dur_ms": 0.5}

    by_rank = {
        0: [_coll(0, 1, 100.0, "barrier#1"), bcast(0, 100.5, "bcast#2")],
        # rank 1's bcast completed 3s later (straggler lag, same clock)
        1: [_coll(1, 1, 100.0, "barrier#1"), bcast(1, 103.5, "bcast#2")]}
    offs = timeline.clock_offsets(by_rank)
    # only the barrier anchors: zero skew, NOT the 3s bcast lag
    assert offs[1] == 0.0, offs


def test_telemetry_lane_events_shapes():
    evs = timeline.telemetry_lane_events(
        [_step(0, 1, 100.0, total_ms=20.0),
         _coll(0, 1, 100.05, "barrier#1"),
         {"kind": "event", "event": "fault", "rank": 0, "step": 1,
          "ts": 100.06, "fault": "kill"}], offset_s=-5.0)
    step_ev = next(e for e in evs if e["name"] == "step")
    assert step_ev["ph"] == "X" and step_ev["dur"] == 20e3
    assert step_ev["ts"] == (100.0 - 5.0) * 1e6
    assert step_ev["args"]["total_ms"] == 20.0
    coll = next(e for e in evs if e["name"] == "collective/barrier")
    assert coll["ph"] == "X" and coll["dur"] == 1e3
    # the recorded ts is the COMPLETION instant: span ends there
    assert abs((coll["ts"] + coll["dur"]) - (100.05 - 5.0) * 1e6) < 1
    fault = next(e for e in evs if e["name"] == "fault")
    assert fault["ph"] == "i"  # no duration: instant marker


def test_hang_event_renders_as_wedged_window_span():
    """A watchdog `hang` event (ts = detection instant, stalled_s =
    how long the collective already sat) renders as a span COVERING
    the wedged window, ending at the event — beside the step /
    collective lanes it blocked."""
    evs = timeline.telemetry_lane_events(
        [{"kind": "event", "event": "hang", "rank": 0, "step": 3,
          "ts": 110.0, "stalled_s": 2.5, "inflight_n": 1,
          "op": "barrier", "key": "barrier#3"}], offset_s=-5.0)
    hang = next(e for e in evs if e["name"].startswith("hang"))
    assert hang["ph"] == "X" and hang["cat"] == "hang"
    assert hang["dur"] == 2.5e6
    assert abs((hang["ts"] + hang["dur"]) - (110.0 - 5.0) * 1e6) < 1
    assert hang["args"]["key"] == "barrier#3"


def test_heartbeat_gaps_synthesized_from_cadence():
    """heartbeat events tick on a fixed cadence; a gap well past the
    median interval becomes a `heartbeat-gap` span covering exactly
    the silent stretch (a stopped process — GC storm, swap, SIGSTOP),
    clock-offset-corrected like every other lane event."""
    def beat(ts):
        return {"kind": "event", "event": "heartbeat", "rank": 0,
                "step": 1, "ts": ts, "up_s": ts - 100.0}

    recs = [beat(t) for t in
            (100.0, 101.0, 102.0, 103.0, 110.0, 111.0, 112.0)]
    gaps = timeline.heartbeat_gap_events(recs, offset_s=-5.0)
    (gap,) = gaps
    assert gap["name"] == "heartbeat-gap" and gap["ph"] == "X"
    assert gap["ts"] == (103.0 - 5.0) * 1e6
    assert gap["dur"] == 7.0 * 1e6
    assert gap["args"]["gap_s"] == 7.0
    # heartbeats also still render (as instants) in the full lane,
    # and the gap rides along
    evs = timeline.telemetry_lane_events(recs)
    assert sum(1 for e in evs if e["name"] == "heartbeat") == 7
    assert sum(1 for e in evs if e["name"] == "heartbeat-gap") == 1
    # steady cadence or too few beats: no gap invented
    assert timeline.heartbeat_gap_events(
        [beat(t) for t in (100.0, 101.0, 102.0)]) == []
    assert timeline.heartbeat_gap_events([beat(100.0)]) == []


def test_cli_merges_telemetry_without_profiles(tmp_path):
    d = _telemetry_dir(tmp_path, skew=2.0)
    out = tmp_path / "merged.json"
    rc = timeline.main(["--telemetry", d,
                        "--timeline_path", str(out)])
    assert rc == 0
    data = json.load(open(out))
    lanes = {e["args"]["name"] for e in data["traceEvents"]
             if e["name"] == "process_name"}
    assert lanes == {"telemetry-rank0", "telemetry-rank1"}
    # clock correction: the two ranks' step-1 events land at the SAME
    # corrected instant despite the 2s file skew
    t0, t1 = [next(e["ts"] for e in data["traceEvents"]
                   if e.get("name") == "step"
                   and e.get("args", {}).get("rank") == r
                   and e["args"]["step"] == 1) for r in (0, 1)]
    assert abs(t0 - t1) < 1e3  # < 1ms after correcting a 2s skew
    # and both lane kinds coexist with --profile_path inputs
    prof = tmp_path / "p0.json"
    with open(prof, "w") as f:
        json.dump(_trace(["fc"]), f)
    rc = timeline.main(["--profile_path", "t0=%s" % prof,
                        "--telemetry", d,
                        "--timeline_path", str(out)])
    assert rc == 0
    data = json.load(open(out))
    lanes = {e["args"]["name"] for e in data["traceEvents"]
             if e["name"] == "process_name"}
    assert lanes == {"t0", "telemetry-rank0", "telemetry-rank1"}


def test_cli_requires_some_input(tmp_path, capsys):
    with pytest.raises(SystemExit):
        timeline.main(["--timeline_path", str(tmp_path / "o.json")])


@pytest.mark.slow  # ~14s (spins the real profiler twice); the pure
# merge logic above covers the default run
def test_cli_merges_real_profiler_output(tmp_path):
    """End to end: two profiler-written traces -> one merged file."""
    from paddle_tpu.fluid import profiler as prof

    paths = []
    for i in range(2):
        d = tmp_path / ("p%d" % i)
        with prof.profiler(state="CPU", profile_path=str(d)):
            with prof.RecordEvent("step"):
                pass
        p = d / "paddle_tpu_trace.json"
        assert p.exists()
        paths.append(str(p))

    out = tmp_path / "merged.json"
    rc = timeline.main(["--profile_path",
                        "t0=%s,t1=%s" % tuple(paths),
                        "--timeline_path", str(out)])
    assert rc == 0
    data = json.load(open(out))
    lanes = {e["args"]["name"] for e in data["traceEvents"]
             if e["name"] == "process_name"}
    assert lanes == {"t0", "t1"}
    assert any(e.get("name") == "step" for e in data["traceEvents"])
