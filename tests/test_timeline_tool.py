"""tools/timeline.py — distributed chrome-trace merge (reference:
tools/timeline.py:32 multi-trainer profile merge)."""
import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "tools"))
import timeline  # noqa: E402


def _trace(names, pid=0):
    return {"traceEvents": [
        {"name": n, "ph": "X", "pid": pid, "tid": 1,
         "ts": 10 * i, "dur": 5, "cat": "host"}
        for i, n in enumerate(names)]}


def test_parse_profile_spec_named_and_bare():
    got = timeline.parse_profile_spec("t0=a.json,t1=b.json")
    assert got == [("t0", "a.json"), ("t1", "b.json")]
    got = timeline.parse_profile_spec("a.json,b.json")
    assert got == [("proc0", "a.json"), ("proc1", "b.json")]
    with pytest.raises(ValueError):
        timeline.parse_profile_spec("t=a.json,t=b.json")
    with pytest.raises(ValueError):
        timeline.parse_profile_spec("")


def test_merge_assigns_disjoint_labelled_lanes():
    t0, t1 = _trace(["fc", "softmax"]), _trace(["fc", "softmax"], pid=3)
    merged = timeline.merge_traces([("trainer0", t0), ("trainer1", t1)])
    evs = merged["traceEvents"]
    xs = [e for e in evs if e.get("ph") == "X"]
    # dense per-lane remap: lane 1's single pid lands at its lane base
    assert {e["pid"] for e in xs} == {0, 1000}
    names = {(e["pid"], e["args"]["name"]) for e in evs
             if e["name"] == "process_name"}
    assert (0, "trainer0") in names and (1000, "trainer1") in names
    # sort hints land on the pids that actually carry events
    sorts = {e["pid"] for e in evs if e["name"] == "process_sort_index"}
    assert sorts == {0, 1000}
    # originals untouched (merge copies events)
    assert all(e["pid"] == 3 for e in t1["traceEvents"])


def test_merge_survives_os_pids():
    """Real exporters emit OS pids (e.g. 7716): lanes must stay
    disjoint — a fixed lane*1000 offset would collide 7716 with a
    second lane's range."""
    merged = timeline.merge_traces([
        ("a", _trace(["op"], pid=7716)),
        ("b", _trace(["op"], pid=3)),
    ])
    evs = merged["traceEvents"]
    by_lane = {}
    for e in evs:
        if e["name"] == "process_name":
            by_lane.setdefault(e["args"]["name"], set()).add(e["pid"])
    assert by_lane["a"].isdisjoint(by_lane["b"]), by_lane


def test_merge_accepts_bare_array_traces():
    merged = timeline.merge_traces([
        ("a", _trace(["op"])["traceEvents"]),  # bare JSON-array form
        ("b", _trace(["op"])),
    ])
    xs = [e for e in merged["traceEvents"] if e.get("ph") == "X"]
    assert {e["pid"] for e in xs} == {0, 1000}


@pytest.mark.slow  # ~14s (spins the real profiler twice); the pure
# merge logic above covers the default run
def test_cli_merges_real_profiler_output(tmp_path):
    """End to end: two profiler-written traces -> one merged file."""
    from paddle_tpu.fluid import profiler as prof

    paths = []
    for i in range(2):
        d = tmp_path / ("p%d" % i)
        with prof.profiler(state="CPU", profile_path=str(d)):
            with prof.RecordEvent("step"):
                pass
        p = d / "paddle_tpu_trace.json"
        assert p.exists()
        paths.append(str(p))

    out = tmp_path / "merged.json"
    rc = timeline.main(["--profile_path",
                        "t0=%s,t1=%s" % tuple(paths),
                        "--timeline_path", str(out)])
    assert rc == 0
    data = json.load(open(out))
    lanes = {e["args"]["name"] for e in data["traceEvents"]
             if e["name"] == "process_name"}
    assert lanes == {"t0", "t1"}
    assert any(e.get("name") == "step" for e in data["traceEvents"])
