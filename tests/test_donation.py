"""Buffer-donation audit (VERDICT r3 #2 follow-up: donation is the HBM
lever that lets batch 512 fit).

The executor's lowering donates the mutated-state argument
(`lowering.py compile_block: donate_argnums=(1,)` behind
FLAGS_tpu_donate_buffers), so XLA aliases every param/moment/BN-stat
buffer and updates it in place. This pins the contract: the aliased
byte count of a compiled train step equals the full mutated-state
footprint — a regression here silently doubles HBM for weights+opt
state."""
import numpy as np

import jax

import paddle_tpu.fluid as fluid
from paddle_tpu.core.scope import global_scope
from paddle_tpu.fluid import framework, lowering


def test_train_step_donates_all_mutated_state():
    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup):
        with framework.unique_name_guard():
            x = fluid.layers.data("x", shape=[16], dtype="float32")
            y = fluid.layers.data("y", shape=[1], dtype="float32")
            h = fluid.layers.fc(x, 32, act="relu")
            pred = fluid.layers.fc(h, 1)
            loss = fluid.layers.mean(
                fluid.layers.loss.square_error_cost(pred, y))
            fluid.optimizer.AdamOptimizer(1e-3).minimize(loss)
            exe = fluid.Executor()
            exe.run(startup)

            feed = {"x": np.zeros((4, 16), np.float32),
                    "y": np.zeros((4, 1), np.float32)}
            block = main.global_block()
            state_in, _ = lowering.analyze_block(block, list(feed),
                                                 [loss.name])
            state_specs = {n: global_scope().find_var(n)
                           for n in state_in}
            entry = lowering.compile_block(main, block, feed,
                                           [loss.name], state_specs)
            smut = {n: global_scope().find_var(n)
                    for n in entry.state_mut_names}
            sro = {n: global_scope().find_var(n)
                   for n in entry.state_ro_names}

    def aval(v):
        a = np.asarray(v)
        return jax.ShapeDtypeStruct(a.shape, a.dtype)

    comp = entry.jitted.lower(
        {k: aval(v) for k, v in feed.items()},
        {k: aval(v) for k, v in smut.items()},
        {k: aval(v) for k, v in sro.items()},
        jax.ShapeDtypeStruct((), np.uint32)).compile()
    ma = comp.memory_analysis()
    mut_bytes = sum(
        int(np.prod(np.asarray(v).shape)) * np.asarray(v).dtype.itemsize
        for v in smut.values())
    assert mut_bytes > 0
    # every mutated-state buffer must be aliased (donated): params,
    # Adam moments, beta-power accumulators, learning rate
    assert ma.alias_size_in_bytes >= mut_bytes, \
        (ma.alias_size_in_bytes, mut_bytes)


def test_donation_flag_disables_aliasing():
    from paddle_tpu.utils.flags import get_flag, set_flags

    old = get_flag("FLAGS_tpu_donate_buffers", True)
    set_flags({"FLAGS_tpu_donate_buffers": False})
    try:
        main, startup = framework.Program(), framework.Program()
        with framework.program_guard(main, startup):
            with framework.unique_name_guard():
                x = fluid.layers.data("x", shape=[8], dtype="float32")
                pred = fluid.layers.fc(x, 1)
                loss = fluid.layers.mean(pred)
                fluid.optimizer.SGD(0.1).minimize(loss)
                exe = fluid.Executor()
                exe.run(startup)
                feed = {"x": np.zeros((2, 8), np.float32)}
                block = main.global_block()
                state_in, _ = lowering.analyze_block(
                    block, list(feed), [loss.name])
                state_specs = {n: global_scope().find_var(n)
                               for n in state_in}
                entry = lowering.compile_block(main, block, feed,
                                               [loss.name], state_specs)
                smut = {n: global_scope().find_var(n)
                        for n in entry.state_mut_names}
                sro = {n: global_scope().find_var(n)
                       for n in entry.state_ro_names}

        def aval(v):
            a = np.asarray(v)
            return jax.ShapeDtypeStruct(a.shape, a.dtype)

        comp = entry.jitted.lower(
            {k: aval(v) for k, v in feed.items()},
            {k: aval(v) for k, v in smut.items()},
            {k: aval(v) for k, v in sro.items()},
            jax.ShapeDtypeStruct((), np.uint32)).compile()
        assert comp.memory_analysis().alias_size_in_bytes == 0
    finally:
        set_flags({"FLAGS_tpu_donate_buffers": old})
