"""Legacy fluid public-surface stragglers (VERDICT r4 missing #4):
fluid.unique_name, require_version, ParallelExecutor compat,
is_compiled_with_cuda, memory_optimize/release_memory no-ops,
load_op_library, ComplexVariable. The reference idioms must run
unmodified (reference: python/paddle/fluid/__init__.py:79-129,
parallel_executor.py:29, framework.py:73,151)."""
import warnings

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework


def test_unique_name_guard_idiom():
    # the multi-program idiom: counters reset inside each guard
    with fluid.unique_name.guard():
        a = fluid.unique_name.generate("fc")
    with fluid.unique_name.guard():
        b = fluid.unique_name.generate("fc")
    assert a == b == "fc_0"
    n1 = fluid.unique_name.generate("fc")
    n2 = fluid.unique_name.generate("fc")
    assert n1 != n2


def test_unique_name_prefix_and_switch():
    with fluid.unique_name.guard("pre_"):
        assert fluid.unique_name.generate("x").startswith("pre_x_")
    gen = fluid.unique_name.UniqueNameGenerator()
    old = fluid.unique_name.switch(gen)
    try:
        assert fluid.unique_name.generate("y") == "y_0"
    finally:
        fluid.unique_name.switch(old)
    assert fluid.unique_name.generate_with_ignorable_key("tmp") \
        .startswith("_generated_var_")


def test_require_version():
    fluid.require_version("0.0.1")
    fluid.require_version(min_version="0.0.1", max_version="99.0")
    with pytest.raises(Exception):
        fluid.require_version("99.0.0")
    with pytest.raises(TypeError):
        fluid.require_version(1)
    with pytest.raises(ValueError):
        fluid.require_version("not.a.version")


def test_is_compiled_with_cuda_false():
    assert fluid.is_compiled_with_cuda() is False


def test_memory_optimize_release_memory_warn_noop():
    main = framework.Program()
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        fluid.memory_optimize(main)
        fluid.release_memory(main)
    assert len(w) == 2
    assert all(issubclass(x.category, DeprecationWarning) for x in w)


def test_parallel_executor_compat_runs():
    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup):
        with fluid.unique_name.guard():
            x = fluid.layers.data(name="x", shape=[4], dtype="float32")
            y = fluid.layers.data(name="y", shape=[1], dtype="float32")
            pred = fluid.layers.fc(input=x, size=1)
            loss = fluid.layers.mean(
                fluid.layers.square(pred - y))
            fluid.optimizer.SGDOptimizer(
                learning_rate=0.01).minimize(loss)

    from paddle_tpu.core.scope import Scope, scope_guard

    with scope_guard(Scope()):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        pe = fluid.ParallelExecutor(use_cuda=False,
                                    loss_name=loss.name,
                                    main_program=main)
        r = np.random.RandomState(0)
        feed = {"x": r.rand(8, 4).astype("float32"),
                "y": r.rand(8, 1).astype("float32")}
        l0 = pe.run([loss.name], feed=feed)[0]
        # deprecated feed_dict alias + legacy positional fetch_list
        l1 = pe.run(fetch_list=[loss.name], feed_dict=feed)[0]
        assert np.isfinite(float(np.asarray(l0).reshape(-1)[0]))
        assert float(np.asarray(l1).reshape(-1)[0]) <= \
            float(np.asarray(l0).reshape(-1)[0]) + 1e-6
        pe.drop_local_exe_scopes()  # API-compat no-op
        assert pe.device_count >= 1


def test_load_op_library_loads_native_so():
    import os

    import paddle_tpu

    so = os.path.join(os.path.dirname(paddle_tpu.__file__), "core",
                      "native", "libpaddle_tpu_native.so")
    if not os.path.exists(so):
        pytest.skip("native lib not built")
    lib = fluid.load_op_library(so)
    assert lib is not None


def test_complex_variable_dygraph():
    from paddle_tpu.fluid.dygraph import base as dg

    with dg.guard():
        re = dg.to_variable(np.array([1.0, 2.0], "float32"))
        im = dg.to_variable(np.array([3.0, 4.0], "float32"))
        c = fluid.ComplexVariable(re, im)
        assert tuple(c.shape) == (2,)
        np.testing.assert_allclose(
            c.numpy(), np.array([1 + 3j, 2 + 4j]))
        assert "ComplexVariable" in repr(c)


def test_framework_unique_name_guard_prefix():
    # the framework-level guard must honor prefix like
    # fluid.unique_name.guard does (the two surfaces share state)
    with framework.unique_name_guard("fw_"):
        assert framework.unique_name("t").startswith("fw_t_")
