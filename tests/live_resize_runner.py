"""Worker script for the ZERO-DOWNTIME live-resize acceptance tests
(spawned via `python -m paddle_tpu.distributed.launch --max_restarts
--min_ranks`).

Same host-tier data-parallel trainer as elastic_world_runner.py (one
fixed GLOBAL batch per step, one allreduce-mean of loss+grads, host-side
SGD so params stay bit-identical on every rank at every world size) —
but the seam is LIVE, not a restart: the designated victim rank arms a
PADDLE_FAULTS `preempt` notice at its Nth host-collective send, every
rank's step boundary runs ElasticWorld.sync() to agree on the doomed
set, and the cohort executes ElasticWorld.resize() in place — the
doomed rank checkpoints-and-exits-0 inside its grace window while the
survivors rebuild the collective group and keep training WITHOUT a
process restart. The supervisor never sees a failure.

In degrade mode a SECOND victim arms a silent kill (exit_code=0 — a
machine reclaimed with no warning) timed to land inside the seam's
agreement barrier: the survivors' rebuild fails fast on the stale
heartbeat, raises LiveResizeError, and every survivor exits DEGRADE_RC
— the loud request for the PR 9 cohort-restart fallback (the preempt
marker written FIRST in the seam tells the shrink who actually left).

argv: <ckpt_root> <total_steps> <save_every>
      [<preempt_rank> <preempt_at> [<degrade_rank> <degrade_at>]]
Prints per completed step (rank 0): LOSS <step> <%.17g global loss>;
RESIZED/PREEMPTED lines mark the seam.
"""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PADDLE_HC_LIVENESS_S", "4")
os.environ.setdefault("PADDLE_HC_HEARTBEAT_S", "0.5")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402

GLOBAL_BATCH = 12  # divisible by 4, 3 and 2: exact mean-of-means
LR = 0.1


def build():
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import framework

    main, startup = fluid.Program(), fluid.Program()
    with framework.unique_name_guard(), \
            fluid.program_guard(main, startup):
        main.random_seed = startup.random_seed = 7
        x = fluid.data(name="x", shape=[-1, 16], dtype="float32")
        y = fluid.data(name="y", shape=[-1, 1], dtype="float32")
        h = fluid.layers.fc(input=x, size=24, act="tanh")
        pred = fluid.layers.fc(input=h, size=1, act=None)
        loss = fluid.layers.reduce_mean(fluid.layers.square(pred - y))
        pg = fluid.optimizer.SGDOptimizer(
            learning_rate=LR).backward(loss)
    names = [(p.name, g.name) for p, g in pg]
    return main, startup, loss.name, names


def data(total_steps):
    rng = np.random.RandomState(3)
    xs = rng.randn(total_steps, GLOBAL_BATCH, 16).astype(np.float32)
    w = rng.randn(16, 1).astype(np.float32)
    return xs, np.tanh(xs @ w)


def main():
    root, total, save_every = (sys.argv[1], int(sys.argv[2]),
                               int(sys.argv[3]))
    preempt_rank = int(sys.argv[4]) if len(sys.argv) > 4 else -1
    preempt_at = int(sys.argv[5]) if len(sys.argv) > 5 else 0
    degrade_rank = int(sys.argv[6]) if len(sys.argv) > 6 else -1
    degrade_at = int(sys.argv[7]) if len(sys.argv) > 7 else 0

    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    attempt = int(os.environ.get("PADDLE_RESTART_NUM", "0"))
    if attempt == 0 and rank == preempt_rank and preempt_at > 0:
        # the warned victim: a notice, not a lost machine
        os.environ["PADDLE_FAULTS"] = (
            "preempt:side=client,point=send,method=hc_put_part,at=%d"
            % preempt_at)
    if attempt == 0 and rank == degrade_rank and degrade_at > 0:
        # fault-during-recovery: a SECOND machine reclaimed silently
        # (exit 0, no marker) mid-seam — the live path must degrade to
        # the cohort restart, never hang
        os.environ["PADDLE_FAULTS"] = (
            "kill:side=client,point=send,method=hc_put_part,at=%d,"
            "exit_code=0" % degrade_at)

    import paddle_tpu.fluid as fluid
    from paddle_tpu.core.scope import Scope
    from paddle_tpu.distributed import preemption
    from paddle_tpu.distributed.host_collectives import group_from_env
    from paddle_tpu.fluid import checkpoint as ckpt
    from paddle_tpu.reader import resharding

    preemption.install_sigterm()
    group = group_from_env()
    eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")
    ew = preemption.ElasticWorld(group, eps) if group is not None \
        else None
    prog, startup, loss_name, pg_names = build()
    xs, ys = data(total)
    scope = Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)

    status = ckpt.load_checkpoint(exe, root, main_program=prog,
                                  scope=scope, group=group)
    start = status.step_no + 1 if status is not None else 0
    world = group.world if group is not None else 1
    print("RESUME %d world=%d rank=%d attempt=%d"
          % (start, world, rank, attempt), flush=True)

    fetch = [loss_name] + [g for _, g in pg_names]
    i = start
    while i < total:
        rank = group.rank if group is not None else 0
        world = group.world if group is not None else 1
        feed = resharding.shard_batch({"x": xs[i], "y": ys[i]},
                                      rank, world)
        out = exe.run(prog, feed=feed, fetch_list=fetch, scope=scope)
        vals = [np.asarray(v) for v in out]
        flat = np.concatenate([v.reshape(-1).astype(np.float64)
                               for v in vals])
        if group is not None:
            flat = group.all_reduce(flat, op="mean")
        loss_g, off = float(flat[0]), 1
        for (pname, _), v in zip(pg_names, vals[1:]):
            n = v.size
            g_mean = flat[off:off + n].reshape(v.shape)
            off += n
            w = np.asarray(scope.find_var(pname), np.float64)
            scope.set_var(pname,
                          (w - LR * g_mean).astype(np.float32))
        if rank == 0:
            print("LOSS %d %.17g" % (i, loss_g), flush=True)
            if save_every and i % save_every == save_every - 1:
                ckpt.save_checkpoint(
                    exe, root, ckpt.TrainStatus(epoch_no=0, step_no=i),
                    main_program=prog, checkpoint_num=10, scope=scope)
        if group is not None:
            group.barrier()
        # -- the step boundary IS the seam: agree, then resize live --
        if ew is not None:
            doomed = ew.sync()
            if doomed:
                step_now = i

                def snapshot(doomed_ranks):
                    # checkpoint-on-signal: the group-agreed snapshot
                    # every post-seam consumer resumes from (old rank 0
                    # holds the replicated params — host-tier DP)
                    if ew.rank == 0:
                        ckpt.save_checkpoint(
                            exe, root,
                            ckpt.TrainStatus(epoch_no=0,
                                             step_no=step_now),
                            main_program=prog, checkpoint_num=10,
                            scope=scope)

                try:
                    report = ew.resize(doomed, snapshot=snapshot,
                                       step=i)
                except preemption.LiveResizeError as e:
                    print("DEGRADE step=%d: %s" % (i, e), flush=True)
                    sys.stdout.flush()
                    os._exit(preemption.DEGRADE_RC)
                if report["role"] == "doomed":
                    print("PREEMPTED rank=%d step=%d"
                          % (report["old_rank"], i), flush=True)
                    sys.stdout.flush()
                    os._exit(0)
                group = ew.group
                print("RESIZED step=%d world=%d rank=%d "
                      "coordination_s=%.6f"
                      % (i, report["new_world"], report["new_rank"],
                         report["coordination_s"]), flush=True)
        i += 1
    if ew is not None:
        ew.shutdown()
    elif group is not None:
        group.shutdown()
    sys.stdout.flush()
    # exit WITHOUT interpreter teardown: jax's CPU runtime intermittently
    # aborts while daemon threads die at exit (see elastic_launch_runner)
    os._exit(0)


if __name__ == "__main__":
    main()
