"""OpTest fixture — the golden-test workhorse (reference:
`python/paddle/fluid/tests/unittests/op_test.py:170`): declare op_type /
inputs / attrs / expected numpy outputs; check_output builds a one-op
program and compares; check_grad compares jax.vjp analytic grads against
central-difference numeric grads (reference: get_numeric_gradient
op_test.py:57)."""
from __future__ import annotations

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework
from paddle_tpu import ops as ops_lib


def _as_list(v):
    return v if isinstance(v, (list, tuple)) else [v]


class OpTest:
    op_type: str = None
    inputs: dict = {}
    attrs: dict = {}
    outputs: dict = {}

    # -- forward -----------------------------------------------------------
    def _run_forward(self, ins_np=None):
        ins_np = ins_np if ins_np is not None else self.inputs
        import jax.numpy as jnp

        raw = {slot: [jnp.asarray(a) for a in _as_list(v)]
               for slot, v in ins_np.items()}
        return ops_lib.run_op(self.op_type, raw, self.attrs)

    def check_output(self, atol=1e-5, rtol=1e-4, no_check_set=()):
        outs = self._run_forward()
        for slot, expect in self.outputs.items():
            if slot in no_check_set:
                continue
            got = outs[slot]
            for g, e in zip(got, _as_list(expect)):
                e = np.asarray(e)
                g = np.asarray(g)
                assert g.shape == tuple(e.shape), (
                    "%s.%s shape %s != %s" % (self.op_type, slot, g.shape,
                                              e.shape))
                np.testing.assert_allclose(
                    g.astype("float64") if g.dtype.kind == "f" else g,
                    e.astype("float64") if e.dtype.kind == "f" else e,
                    atol=atol, rtol=rtol,
                    err_msg="%s output %s" % (self.op_type, slot))

    # -- gradient ----------------------------------------------------------
    @staticmethod
    def _matches_output(output_name, slot, i, n_vals):
        """ONE matching rule for every loss-summation path (analytic f,
        jitted numeric, exact host numeric): bare slot name, or the
        indexed 'Slot[i]' form for multi-value slots."""
        nm = slot if n_vals == 1 else "%s[%d]" % (slot, i)
        return output_name in (slot, nm)

    def _loss_of(self, outs, output_name):
        total = None
        for slot, vals in outs.items():
            for i, v in enumerate(vals):
                if self._matches_output(output_name, slot, i, len(vals)):
                    s = np.sum(np.asarray(v, dtype="float64"))
                    total = s if total is None else total + s
        assert total is not None, "output %r not found" % output_name
        return total

    def check_grad(self, inputs_to_check, output_name, delta=5e-3,
                   max_relative_error=5e-3):
        import jax
        import jax.numpy as jnp

        flat_slots = sorted(self.inputs)
        raw = {slot: [jnp.asarray(a) for a in _as_list(self.inputs[slot])]
               for slot in flat_slots}

        def f(check_vals):
            ins = {s: list(vs) for s, vs in raw.items()}
            for slot, v in check_vals.items():
                # replace only element 0 — multi-array slots keep their
                # remaining members, same as the numeric paths
                ins[slot] = [v] + list(raw[slot])[1:]
            outs = ops_lib.run_op(self.op_type, ins, self.attrs)
            total = None
            for slot, vals in outs.items():
                for i, v in enumerate(vals):
                    if self._matches_output(output_name, slot, i,
                                            len(vals)) and \
                            jnp.issubdtype(v.dtype, jnp.floating):
                        s = jnp.sum(v.astype(jnp.float32))
                        total = s if total is None else total + s
            return total

        check_vals = {s: raw[s][0] for s in inputs_to_check}
        analytic = jax.grad(f)(check_vals)

        for slot in inputs_to_check:
            a = np.asarray(analytic[slot], dtype="float64")
            n = rel = None
            try:
                # fast path: ONE jitted scalar loss, every perturbation
                # a cached-executable call — the eager per-element loop
                # re-dispatched recurrent ops (lstm/gru scans) from
                # python twice per element and dominated suite wall
                # clock (452s for one attention_lstm test)
                n = self._numeric_grad(slot, output_name, delta,
                                       jit=True)
                rel = self._grad_rel_err(a, n)
            except Exception:  # noqa: BLE001 - op not jittable as-is
                rel = None
            # NaN-safe gate: a NaN in the jitted-f32 rel error must
            # route to the exact fallback too (`NaN > x` is False, so
            # the positive comparison would skip it and hard-fail)
            if rel is None or not (rel.max() <= 0.5 * max_relative_error):
                # exact f64 fallback decides every non-clear case: the
                # f32 jitted sums carry cancellation noise that could
                # otherwise nudge a genuinely-failing gradient under
                # tolerance, so a fast-path PASS is only trusted with
                # 2x margin
                n = self._numeric_grad(slot, output_name, delta)
                rel = self._grad_rel_err(a, n)
            assert rel.max() <= max_relative_error, (
                "%s grad wrt %s: max rel err %.4g\nanalytic=%s\nnumeric=%s"
                % (self.op_type, slot, rel.max(), a.ravel()[:8],
                   n.ravel()[:8]))

    @staticmethod
    def _grad_rel_err(a, n):
        denom = np.maximum(np.maximum(np.abs(a), np.abs(n)), 1e-3)
        rel = np.abs(a - n) / denom
        return np.where(np.abs(a - n) < 1e-4, 0.0, rel)  # fp-noise floor

    def _numeric_grad(self, slot, output_name, delta, jit=False):
        base = {s: [np.asarray(a, dtype="float32") for a in _as_list(v)]
                for s, v in self.inputs.items()}
        x = base[slot][0]
        run = self._run_forward
        loss_of = self._loss_of
        if jit:
            import jax
            import jax.numpy as jnp

            others = {s: [jnp.asarray(a) for a in vs]
                      for s, vs in base.items()}

            @jax.jit
            def jloss(xp):
                ins = {s: list(vs) for s, vs in others.items()}
                ins[slot] = [xp] + list(others[slot])[1:]
                outs = ops_lib.run_op(self.op_type, ins, self.attrs)
                total = None
                for oslot, vals in outs.items():
                    for i, v in enumerate(vals):
                        if self._matches_output(output_name, oslot, i,
                                                len(vals)) and \
                                jnp.issubdtype(v.dtype, jnp.floating):
                            s = jnp.sum(v.astype(jnp.float32))
                            total = s if total is None else total + s
                return total

            def run(b):  # noqa: ARG001 - closure reads mutated x
                return jloss(jnp.asarray(x))

            def loss_of(out, _name):
                return float(out)
        grad = np.zeros_like(x, dtype="float64")
        it = np.nditer(x, flags=["multi_index"])
        while not it.finished:
            idx = it.multi_index
            orig = x[idx]
            x[idx] = orig + delta
            hi = loss_of(run(base), output_name)
            x[idx] = orig - delta
            lo = loss_of(run(base), output_name)
            x[idx] = orig
            grad[idx] = (hi - lo) / (2 * delta)
            it.iternext()
        return grad


class ProgramOpTest(OpTest):
    """Variant that goes through the FULL static-graph pipeline (program
    build -> Executor -> lowering), not just the registry."""

    def check_output_with_program(self, atol=1e-5, rtol=1e-4):
        from paddle_tpu.fluid.layer_helper import LayerHelper

        main, startup = framework.Program(), framework.Program()
        with framework.program_guard(main, startup):
            feed = {}
            in_vars = {}
            for slot, v in self.inputs.items():
                vars_ = []
                for i, arr in enumerate(_as_list(v)):
                    arr = np.asarray(arr)
                    name = "%s_%d" % (slot.lower(), i)
                    var = main.global_block().create_var(
                        name=name, shape=arr.shape,
                        dtype=str(arr.dtype), is_data=True,
                        stop_gradient=True)
                    vars_.append(var)
                    feed[name] = arr
                in_vars[slot] = vars_
            helper = LayerHelper(self.op_type)
            out_vars = {}
            fetch = []
            for slot, expect in self.outputs.items():
                vs = [helper.create_variable_for_type_inference()
                      for _ in _as_list(expect)]
                out_vars[slot] = vs
                fetch.extend(vs)
            main.global_block().append_op(
                type=self.op_type, inputs=in_vars, outputs=out_vars,
                attrs=self.attrs)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            results = exe.run(main, feed=feed, fetch_list=fetch)
        i = 0
        for slot, expect in self.outputs.items():
            for e in _as_list(expect):
                e = np.asarray(e)
                g = results[i]
                i += 1
                np.testing.assert_allclose(
                    np.asarray(g, dtype="float64")
                    if g.dtype.kind == "f" else g,
                    e.astype("float64") if e.dtype.kind == "f" else e,
                    atol=atol, rtol=rtol,
                    err_msg="%s output %s" % (self.op_type, slot))
