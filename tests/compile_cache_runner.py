"""Worker script for the persistent compile-cache tests.

Two uses:
  - direct subprocess (warm-restart proof): the parent sets
    FLAGS_tpu_compile_cache_dir (+ FLAGS_tpu_telemetry_dir) in the env
    and runs this twice — the second process must classify every fresh
    compile as a persistent-cache HIT and produce bit-identical
    losses;
  - under `python -m paddle_tpu.distributed.launch` (supervised
    elastic warm restart): with the "elastic" argv flag, rank 1 of
    attempt 0 exits 7 after its steps (the lost machine) and the
    survivor sleeps until the fail-fast teardown, so the supervisor
    shrinks the world and the respawned attempt-1 cohort re-compiles
    THROUGH the supervisor-exported <log_dir>/compile_cache.

argv: [<steps>] ["elastic"]. Prints one line:
RESULT {"losses": [...17-digit strs...], "hits": N, "misses": N, ...}
"""
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_xla = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _xla:
    os.environ["XLA_FLAGS"] = (
        _xla + " --xla_force_host_platform_device_count=8").strip()
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def main():
    steps = int(sys.argv[1]) if len(sys.argv) > 1 else 3
    elastic = "elastic" in sys.argv[2:]
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import framework

    main_p, startup = fluid.Program(), fluid.Program()
    with framework.program_guard(main_p, startup), \
            framework.unique_name_guard():
        # fixed seeds: the cold and warm runs must be bit-identical
        main_p.random_seed = startup.random_seed = 7
        x = fluid.data(name="x", shape=[-1, 8], dtype="float32")
        y = fluid.data(name="y", shape=[-1, 1], dtype="float32")
        h = fluid.layers.fc(input=x, size=16, act="tanh")
        pred = fluid.layers.fc(input=h, size=1)
        loss = fluid.layers.reduce_mean(
            fluid.layers.square(pred - y))
        fluid.optimizer.SGDOptimizer(
            learning_rate=0.1).minimize(loss)
    exe = fluid.Executor()
    exe.run(startup)
    rng = np.random.RandomState(42)
    feed = {"x": rng.randn(4, 8).astype("float32"),
            "y": rng.randn(4, 1).astype("float32")}
    losses = []
    for _ in range(steps):
        out = exe.run(main_p, feed=feed, fetch_list=[loss.name])
        losses.append(float(np.asarray(out[0]).reshape(-1)[0]))
    from paddle_tpu.fluid import compile_cache as cc

    st = cc.stats()
    print("RESULT " + json.dumps({
        "losses": ["%.17g" % v for v in losses],
        "hits": st["hits"], "misses": st["misses"],
        "enabled": st["enabled"], "dir": st["dir"]}), flush=True)
    if elastic:
        tid = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        attempt = int(os.environ.get("PADDLE_RESTART_NUM", "0"))
        if attempt == 0:
            if tid == 1:
                sys.exit(7)  # the lost machine
            time.sleep(60)  # survivor: await the fail-fast teardown
    sys.exit(0)


if __name__ == "__main__":
    main()
