"""Async step pipeline tests: device prefetcher (depth bound, sharding,
error propagation, drain), overlap microbenchmark (steady-state step
time ~= max(feed, compute), not the sum), LazyFetch / deferred fetches
(hapi fit syncs <= ceil(steps/log_freq) times per epoch), step-phase
counters, donation audit through the executor path, and loss parity —
prefetch + deferred fetch on vs off must match bit for bit, including
the multi-device `with_data_parallel` path (PS-mode parity rides in
test_dist_ps.py)."""
import math
import time

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework, profiler
from paddle_tpu.reader import prefetch_to_device
from paddle_tpu.reader.prefetcher import is_donatable


# ---------------------------------------------------------------------------
# prefetcher unit tests
# ---------------------------------------------------------------------------

def test_prefetch_yields_device_arrays_in_order():
    import jax

    pf = prefetch_to_device(
        ({"x": np.full((2, 2), i, np.float32)} for i in range(5)))
    got = list(pf)
    assert len(got) == 5
    for i, b in enumerate(got):
        assert isinstance(b["x"], jax.Array)
        assert float(np.asarray(b["x"])[0, 0]) == float(i)


def test_prefetch_list_and_bare_array_batches():
    import jax

    lists = list(prefetch_to_device(
        ([np.zeros(2, np.float32), np.ones(3, np.float32)]
         for _ in range(2))))
    assert all(isinstance(v, jax.Array) for b in lists for v in b)
    bare = list(prefetch_to_device(
        (np.full(4, i, np.float32) for i in range(3))))
    assert [float(np.asarray(a)[0]) for a in bare] == [0.0, 1.0, 2.0]


def test_prefetch_depth_bound():
    """The producer never runs more than `size` batches (+1 in hand)
    ahead of the consumer."""
    size = 2
    produced = []
    consumed = [0]
    max_lead = [0]

    def gen():
        for i in range(12):
            produced.append(i)
            max_lead[0] = max(max_lead[0],
                              len(produced) - consumed[0])
            yield {"x": np.zeros(4, np.float32)}

    pf = prefetch_to_device(gen(), size=size)
    for _ in pf:
        time.sleep(0.01)  # slow consumer: the producer must wait
        consumed[0] += 1
    # one batch in the producer's hand + `size` queued + the one the
    # consumer holds
    assert max_lead[0] <= size + 2, max_lead[0]


def test_prefetch_sharding():
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()), ("dp",))
    target = NamedSharding(mesh, P("dp"))
    pf = prefetch_to_device(
        ({"x": np.zeros((8, 4), np.float32)} for _ in range(2)),
        sharding=target)
    for batch in pf:
        assert batch["x"].sharding == target
    # dict sharding: named feeds shard, unknown names go to the default
    pf = prefetch_to_device(
        ({"x": np.zeros((8, 4), np.float32),
          "y": np.zeros((2,), np.float32)} for _ in range(1)),
        sharding={"x": target})
    (batch,) = list(pf)
    assert batch["x"].sharding == target


def test_prefetch_producer_error_propagates():
    def gen():
        yield {"x": np.zeros(2, np.float32)}
        yield {"x": np.zeros(2, np.float32)}
        raise ValueError("boom in producer")

    pf = prefetch_to_device(gen())
    it = iter(pf)
    next(it)
    next(it)
    # the ORIGINAL exception type surfaces (typed except clauses around
    # the consuming loop keep working)
    with pytest.raises(ValueError, match="boom in producer"):
        next(it)
    assert not pf._thread.is_alive()


def test_prefetch_drain_on_early_exit():
    """Breaking out of the loop + close() stops the producer thread and
    drains queued buffers."""
    stopped_at = [0]

    def gen():
        for i in range(1000):
            stopped_at[0] = i
            yield {"x": np.zeros(16, np.float32)}

    pf = prefetch_to_device(gen(), size=3)
    for i, _ in enumerate(pf):
        if i == 2:
            break
    pf.close()
    pf._thread.join(timeout=5.0)
    assert not pf._thread.is_alive()
    assert pf._q.qsize() == 0
    assert stopped_at[0] < 999  # producer did NOT run the whole epoch
    # context-manager form drains too
    with prefetch_to_device(gen(), size=2) as pf2:
        next(iter(pf2))
    pf2._thread.join(timeout=5.0)
    assert not pf2._thread.is_alive()


def test_prefetched_buffers_marked_donatable():
    (batch,) = list(prefetch_to_device(
        ({"x": np.zeros(4, np.float32)} for _ in range(1))))
    assert is_donatable(batch["x"])
    import jax.numpy as jnp

    assert not is_donatable(jnp.zeros(4))  # caller-owned arrays are not


def test_dataloader_double_buffer_extends_to_device():
    """DataLoader.from_generator(use_double_buffer=True) with an
    accelerator place yields batches already on device; with a CPU
    place it keeps the host-numpy contract."""
    import jax

    def reader():
        for i in range(3):
            yield [np.full((2, 4), i, np.float32)]

    x = fluid.layers.data(name="xdl", shape=[4], dtype="float32")
    dl = fluid.DataLoader.from_generator(feed_list=[x], capacity=4,
                                         use_double_buffer=True)
    dl.set_batch_generator(reader, places=fluid.TPUPlace())
    batches = list(dl)
    assert len(batches) == 3
    assert all(isinstance(b["xdl"], jax.Array) for b in batches)

    dl2 = fluid.DataLoader.from_generator(feed_list=[x], capacity=4,
                                          use_double_buffer=True)
    dl2.set_batch_generator(reader, places=fluid.CPUPlace())
    batches2 = list(dl2)
    assert all(isinstance(b["xdl"], np.ndarray) for b in batches2)


# ---------------------------------------------------------------------------
# overlap microbenchmark (acceptance: step ~= max(feed, compute))
# ---------------------------------------------------------------------------

def test_overlap_microbenchmark_speedup():
    """Synthetic sleep-based producer + compute, feed ~= compute: the
    async pipeline must approach max(feed, compute) per steady-state
    step, not feed + compute (assert >= 1.4x vs the serial loop)."""
    feed_s = compute_s = 0.04
    steps = 8

    def produce():
        for _ in range(steps):
            time.sleep(feed_s)  # host-side parse/augment/copy cost
            yield {"x": np.zeros((4, 4), np.float32)}

    def compute(batch):
        time.sleep(compute_s)  # stands in for device step time

    t0 = time.perf_counter()
    for batch in produce():
        compute(batch)
    serial = time.perf_counter() - t0

    t0 = time.perf_counter()
    pf = prefetch_to_device(produce(), size=2)
    for batch in pf:
        compute(batch)
    overlapped = time.perf_counter() - t0

    speedup = serial / overlapped
    assert speedup >= 1.4, (serial, overlapped, speedup)
    # steady state ~= max(feed, compute): allow generous CI jitter but
    # stay well under the serial sum
    assert overlapped < steps * (feed_s + compute_s) * 0.75, overlapped


# ---------------------------------------------------------------------------
# executor integration: LazyFetch, phases, donation audit, parity
# ---------------------------------------------------------------------------

def _build_mlp(seed):
    framework.default_main_program().random_seed = seed
    framework.default_startup_program().random_seed = seed
    x = fluid.layers.data(name="x", shape=[16], dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    h = fluid.layers.fc(input=x, size=32, act="relu")
    logits = fluid.layers.fc(input=h, size=4)
    loss = fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(logits, label))
    return loss


def _batches(n=6, batch=16):
    r = np.random.RandomState(3)
    for _ in range(n):
        yield {"x": r.rand(batch, 16).astype("float32"),
               "label": r.randint(0, 4, (batch, 1)).astype("int64")}


def _fresh_world():
    from paddle_tpu.core import scope as scope_mod

    framework.switch_main_program(framework.Program())
    framework.switch_startup_program(framework.Program())
    scope_mod._global_scope = scope_mod.Scope()


def test_lazy_fetch_handle():
    from paddle_tpu.fluid.executor import LazyFetch

    loss = _build_mlp(5)
    fluid.optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(framework.default_startup_program())
    feed = next(_batches(1))
    (h,) = exe.run(feed=feed, fetch_list=[loss], return_numpy=False)
    assert isinstance(h, LazyFetch)
    assert h.shape == () or h.shape == (1,)
    import jax

    assert isinstance(h.value, jax.Array)
    a = np.asarray(h)  # __array__ materializes
    assert a.dtype == np.float32
    assert float(h) == float(np.ravel(a)[0])
    assert h.block_until_ready() is h


def test_step_phases_recorded():
    loss = _build_mlp(6)
    fluid.optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(framework.default_startup_program())
    profiler.reset_step_phases()
    for feed in _batches(3):
        exe.run(feed=feed, fetch_list=[loss])
    s = profiler.step_phase_summary()
    assert s["steps"] == 3
    for k in ("feed_ms", "dispatch_ms", "sync_ms", "host_ms",
              "total_ms"):
        assert k in s and s[k] >= 0.0
    assert s["dispatch_ms"] > 0.0
    line = profiler.step_phase_line()
    assert "feed" in line and "dispatch" in line
    # phase events reach the chrome-trace buffer when tracing is live
    profiler.reset_profiler()
    profiler._trace_enabled = True
    try:
        profiler.record_step_phase("feed", 0.001, time.perf_counter())
    finally:
        profiler._trace_enabled = False
    assert any(n == "phase/feed" for n, *_ in profiler._trace_events)


def test_donation_audit_executor_path():
    """FLAGS_tpu_donate_buffers must actually alias params/opt-state in
    the executor path (compiled-memory analysis of the CACHED entry)."""
    loss = _build_mlp(7)
    fluid.optimizer.AdamOptimizer(1e-3).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(framework.default_startup_program())
    feed = next(_batches(1))
    exe.run(feed=feed, fetch_list=[loss])
    rep = exe.donation_report(feed=feed, fetch_list=[loss])
    assert rep is not None
    assert rep["mut_bytes"] > 0
    assert rep["aliases_state"], rep
    assert rep["feed_donate"] is True


def test_parity_prefetch_and_lazy_vs_sync():
    """MNIST-style loop: prefetch + deferred fetch on == synchronous
    path, loss for loss (same seed)."""
    loss = _build_mlp(1234)
    fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(framework.default_startup_program())
    sync = [float(exe.run(feed=f, fetch_list=[loss])[0][0])
            for f in _batches()]

    _fresh_world()
    with framework.unique_name_guard():
        loss2 = _build_mlp(1234)
        fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss2)
        exe2 = fluid.Executor(fluid.CPUPlace())
        exe2.run(framework.default_startup_program())
        handles = []
        pf = prefetch_to_device(_batches(), size=2)
        for f in pf:
            handles.append(exe2.run(feed=f, fetch_list=[loss2],
                                    return_numpy=False)[0])
        # ONE deferred sync at the end materializes every step's loss
        async_losses = [float(h) for h in handles]
    assert sync == async_losses, (sync, async_losses)


def test_parity_with_data_parallel():
    """Multi-device path: with_data_parallel + prefetched pre-sharded
    feeds == the same compiled program fed from host numpy."""
    loss = _build_mlp(77)
    fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
    cp = fluid.CompiledProgram(
        framework.default_main_program()).with_data_parallel(
            loss_name=loss.name)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(framework.default_startup_program())
    base = [float(exe.run(cp, feed=f, fetch_list=[loss])[0].mean())
            for f in _batches(5, batch=16)]

    _fresh_world()
    with framework.unique_name_guard():
        loss2 = _build_mlp(77)
        fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss2)
        cp2 = fluid.CompiledProgram(
            framework.default_main_program()).with_data_parallel(
                loss_name=loss2.name)
        exe2 = fluid.Executor(fluid.CPUPlace())
        exe2.run(framework.default_startup_program())
        shard = exe2.feed_sharding(cp2)
        assert shard is not None  # 8-device mesh -> dp sharding
        pf = prefetch_to_device(_batches(5, batch=16), size=2,
                                sharding=shard)
        pre = []
        for f in pf:
            out = exe2.run(cp2, feed=f, fetch_list=[loss2],
                           return_numpy=False)[0]
            pre.append(float(np.asarray(out).mean()))
    assert base == pre, (base, pre)


def test_prefetch_uneven_tail_batch_falls_back_unsharded():
    """A tail batch whose rows don't divide the mesh must not crash in
    the producer: it lands unsharded and the executor's tail bucketing
    replicates it to the cached divisible batch (host-path parity)."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()), ("dp",))
    target = NamedSharding(mesh, P("dp"))

    def gen():
        yield {"x": np.zeros((16, 4), np.float32)}  # divisible by 8
        yield {"x": np.zeros((6, 4), np.float32)}   # uneven tail

    got = list(prefetch_to_device(gen(), sharding=target))
    assert got[0]["x"].sharding == target
    assert got[1]["x"].shape == (6, 4)  # landed, just unsharded


def test_trainer_prefetch_parity():
    """train_from_dataset (device-prefetching feeder) == a plain
    synchronous exe.run loop over the same dataset."""
    from paddle_tpu.fluid.dataset import InMemoryDataset

    r = np.random.RandomState(9)
    xs = r.rand(64, 16).astype("float32")
    ys = r.randint(0, 4, (64, 1)).astype("int64")

    loss = _build_mlp(55)
    fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(framework.default_startup_program())
    sync_losses = []
    for i in range(0, 64, 16):
        out = exe.run(feed={"x": xs[i:i + 16], "label": ys[i:i + 16]},
                      fetch_list=[loss])
        sync_losses.append(float(np.asarray(out[0]).reshape(-1)[0]))

    _fresh_world()
    with framework.unique_name_guard():
        loss2 = _build_mlp(55)
        fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss2)
        exe2 = fluid.Executor(fluid.CPUPlace())
        exe2.run(framework.default_startup_program())

        class _DS:
            def _iter_batches(self):
                for i in range(0, 64, 16):
                    yield {"x": xs[i:i + 16],
                           "label": ys[i:i + 16]}

        final = exe2.train_from_dataset(
            program=framework.default_main_program(), dataset=_DS(),
            fetch_list=[loss2], print_period=0)
    assert float(np.ravel(final[0])[0]) == sync_losses[-1], \
        (final, sync_losses)


# ---------------------------------------------------------------------------
# hapi deferred fetches
# ---------------------------------------------------------------------------

def _hapi_model():
    import paddle_tpu as paddle
    from paddle_tpu.hapi import Model

    class FlattenLinear(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = paddle.nn.Linear(64, 10)

        def forward(self, x):
            return self.fc(x.reshape((x.shape[0], 64)))

    m = Model(paddle.nn.Sequential(FlattenLinear()))
    m.prepare(
        optimizer=paddle.fluid.optimizer.AdamOptimizer(
            learning_rate=1e-2),
        loss_function=paddle.nn.CrossEntropyLoss())
    return m


def test_hapi_fit_sync_count_bounded():
    """Deferred fetches: fit performs <= ceil(steps/log_freq) host
    syncs per epoch (counted at the profiler's hapi/loss_sync event)."""
    from paddle_tpu.hapi.datasets import SyntheticImages

    np.random.seed(1234)
    m = _hapi_model()
    data = SyntheticImages(num_samples=96)
    batch_size, log_freq = 16, 4
    steps = 96 // batch_size
    profiler.reset_profiler()
    m.fit(data, batch_size=batch_size, epochs=1, verbose=0,
          shuffle=False, log_freq=log_freq)
    syncs = profiler.event_count("hapi/loss_sync")
    assert 0 < syncs <= math.ceil(steps / log_freq), \
        (syncs, steps, log_freq)


def test_hapi_fit_deferred_parity():
    """Same seed, deferred fetches on vs off: losses bit-identical."""
    from paddle_tpu.hapi.datasets import SyntheticImages
    from paddle_tpu.utils.flags import get_flag, set_flags

    def run():
        np.random.seed(99)
        m = _hapi_model()
        data = SyntheticImages(num_samples=64)
        return m.fit(data, batch_size=16, epochs=2, verbose=0,
                     shuffle=False, log_freq=3)

    old = get_flag("FLAGS_tpu_deferred_fetch", True)
    try:
        set_flags({"FLAGS_tpu_deferred_fetch": True})
        on = run()
        set_flags({"FLAGS_tpu_deferred_fetch": False})
        off = run()
    finally:
        set_flags({"FLAGS_tpu_deferred_fetch": old})
    assert [h["loss"] for h in on] == [h["loss"] for h in off]


def test_hapi_deferred_logs_fresh_for_callbacks():
    """A third-party callback reading logs['loss'] EVERY step must see
    fresh per-step values under deferral (reading forces the sync); it
    pays per-step syncs, the default callbacks keep the deferred
    cadence."""
    from paddle_tpu.hapi.callbacks import Callback
    from paddle_tpu.hapi.datasets import SyntheticImages

    seen = []

    class Greedy(Callback):
        def on_train_batch_end(self, step, logs=None):
            seen.append(float(logs["loss"]))

    np.random.seed(5)
    m = _hapi_model()
    data = SyntheticImages(num_samples=64)
    hist = m.fit(data, batch_size=16, epochs=1, verbose=0,
                 shuffle=False, log_freq=3, callbacks=[Greedy()])
    assert len(seen) == 4  # one fresh loss per step
    assert len(set(seen)) > 1  # values actually change step to step
    assert seen[-1] == hist[-1]["loss"]


def test_hapi_fit_with_metrics_deferred():
    """Metrics still accumulate over EVERY step under deferral."""
    from paddle_tpu.hapi import Accuracy
    from paddle_tpu.hapi.datasets import SyntheticImages

    np.random.seed(7)
    m = _hapi_model()
    m._metrics = [Accuracy()]
    data = SyntheticImages(num_samples=64)
    hist = m.fit(data, batch_size=16, epochs=1, verbose=0,
                 shuffle=False, log_freq=3)
    assert "acc" in hist[-1]
    assert m._metrics[0].count == 64  # every sample counted
