"""Golden/behavioral tests for the specialty ops without coverage yet:
tree_conv, var_conv_2d, pyramid_hash, attention_lstm,
fused_embedding_fc_lstm, fusion_seqexpand_concat_fc, similarity_focus,
add_position_encoding, roi_perspective_transform,
deformable_psroi_pooling, sampled softmax, polygon_box_transform."""
import numpy as np

from op_test import OpTest
from paddle_tpu import ops as ops_lib


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


class TestTreeConv(OpTest):
    def test(self):
        r = np.random.RandomState(0)
        n, max_n, feat, out_c, k = 1, 4, 3, 2, 2
        nodes = r.randn(n, max_n, feat).astype("float32")
        # tree: 1 -> 2, 1 -> 3 (node 0 unused root placeholder)
        edges = np.array([[[1, 2], [1, 3], [0, 0]]], "int32")
        filt = r.randn(feat, 3, out_c, k).astype("float32")
        self.op_type = "tree_conv"
        self.inputs = {"NodesVector": nodes, "EdgeSet": edges,
                       "Filter": filt}
        out = np.asarray(self._run_forward()["Out"][0])
        assert out.shape == (n, max_n, out_c * k)
        assert np.all(np.isfinite(out))
        # node with no children: only the self (top) term contributes
        w_self = filt[:, 0] + 0.5 * filt[:, 1] + 0.5 * filt[:, 2]
        e2 = np.tanh(np.einsum("f,fok->ok", nodes[0, 2], w_self))
        np.testing.assert_allclose(out[0, 2], e2.reshape(-1), rtol=1e-4)


class TestVarConv2d(OpTest):
    def test(self):
        r = np.random.RandomState(1)
        x = r.randn(2, 6, 8).astype("float32")
        w = r.randn(4, 9).astype("float32")
        self.op_type = "var_conv_2d"
        self.inputs = {"X": x, "W": w}
        self.attrs = {"kernel_h": 3, "kernel_w": 3}
        out = np.asarray(self._run_forward()["Out"][0])
        assert out.shape == (2, 4, 6, 8)
        # center pixel of a same-padded 3x3 conv over row 0
        xp = np.pad(x[0], ((1, 1), (1, 1)))
        patch = xp[3:6, 4:7].reshape(-1)
        np.testing.assert_allclose(out[0, 1, 3, 4].item(),
                                   float(w[1] @ patch), rtol=1e-4)


class TestPyramidHash(OpTest):
    def test(self):
        r = np.random.RandomState(2)
        x = r.randint(1, 1000, (3, 6)).astype("int64")
        w = r.randn(128, 8).astype("float32")
        self.op_type = "pyramid_hash"
        self.inputs = {"X": x, "W": w}
        self.attrs = {"num_emb": 8, "pyramid_layer": 2}
        out = np.asarray(self._run_forward()["Out"][0])
        assert out.shape == (3, 8)
        out2 = np.asarray(self._run_forward()["Out"][0])
        np.testing.assert_array_equal(out, out2)  # deterministic hash


class TestAttentionLstm(OpTest):
    def test(self):
        r = np.random.RandomState(3)
        b, t, m, d = 2, 4, 5, 3
        x = r.randn(b, t, m).astype("float32")
        aw = (r.randn(m + d, 1) * 0.3).astype("float32")
        lw = (r.randn(m + d, 4 * d) * 0.3).astype("float32")
        lb = np.zeros((4 * d,), "float32")
        self.op_type = "attention_lstm"
        self.inputs = {"X": x, "AttentionWeight": aw, "LSTMWeight": lw,
                       "LSTMBias": lb}
        outs = self._run_forward()
        hid = np.asarray(outs["Hidden"][0])
        assert hid.shape == (b, t, d)
        assert np.all(np.isfinite(hid))
        # padded rows must not receive attention mass
        self.inputs["Length"] = np.array([4, 2], "int64")
        hid2 = np.asarray(self._run_forward()["Hidden"][0])
        assert np.all(np.isfinite(hid2))
        self.check_grad(["X", "LSTMWeight"], "Hidden",
                        max_relative_error=0.05)


class TestFusedEmbeddingFcLstm(OpTest):
    def test(self):
        r = np.random.RandomState(4)
        b, t, v, d = 2, 3, 20, 4
        ids = r.randint(0, v, (b, t)).astype("int64")
        emb = (r.randn(v, 4 * d) * 0.2).astype("float32")
        wh = (r.randn(d, 4 * d) * 0.2).astype("float32")
        bias = np.zeros((1, 4 * d), "float32")
        self.op_type = "fused_embedding_fc_lstm"
        self.inputs = {"Ids": ids, "Embeddings": emb, "WeightH": wh,
                       "Bias": bias}
        outs = self._run_forward()
        hid = np.asarray(outs["Hidden"][0])
        assert hid.shape == (b, t, d)
        # golden: manual cand/i/f/o recurrence over the embedded gates
        xx = emb[ids] + bias.reshape(-1)
        h = np.zeros((b, d))
        c = np.zeros((b, d))
        for step in range(t):
            proj = xx[:, step] + h @ wh
            cand = np.tanh(proj[:, :d])
            i = _sigmoid(proj[:, d:2 * d])
            f = _sigmoid(proj[:, 2 * d:3 * d])
            o = _sigmoid(proj[:, 3 * d:])
            c = f * c + i * cand
            h = o * np.tanh(c)
        np.testing.assert_allclose(hid[:, -1], h, rtol=1e-4, atol=1e-5)


class TestFusionSeqexpandConcatFc(OpTest):
    def test(self):
        r = np.random.RandomState(5)
        b, t, d0, d1 = 2, 3, 4, 2
        seq = r.randn(b, t, d0).astype("float32")
        vec = r.randn(b, d1).astype("float32")
        w = r.randn(d0 + d1, 5).astype("float32")
        self.op_type = "fusion_seqexpand_concat_fc"
        self.inputs = {"X": [seq, vec], "FCWeight": w}
        self.attrs = {"fc_activation": "relu"}
        out = np.asarray(self._run_forward()["Out"][0])
        cat = np.concatenate(
            [seq, np.tile(vec[:, None, :], (1, t, 1))], -1)
        np.testing.assert_allclose(out, np.maximum(cat @ w, 0),
                                   rtol=1e-4, atol=1e-5)


class TestSimilarityFocus(OpTest):
    def test(self):
        """Reference (similarity_focus_op.cc): greedy largest-value picks
        with each row/col used at most once, broadcast over the selected
        axis."""
        r = np.random.RandomState(6)
        x = r.randn(1, 3, 4, 4).astype("float32")
        self.op_type = "similarity_focus"
        self.inputs = {"X": x}
        self.attrs = {"axis": 1, "indexes": [1]}
        out = np.asarray(self._run_forward()["Out"][0])
        plane = x[0, 1]
        expect = np.zeros((4, 4), "float32")
        used_r, used_c = set(), set()
        for pos in np.argsort(-plane, axis=None):
            rr, cc = divmod(int(pos), 4)
            if rr in used_r or cc in used_c:
                continue
            expect[rr, cc] = 1
            used_r.add(rr)
            used_c.add(cc)
        for ch in range(3):
            np.testing.assert_array_equal(out[0, ch], expect)


class TestAddPositionEncoding(OpTest):
    def test(self):
        r = np.random.RandomState(7)
        x = r.randn(2, 6, 8).astype("float32")
        self.op_type = "add_position_encoding"
        self.inputs = {"X": x}
        self.attrs = {"alpha": 0.5, "beta": 2.0}
        out = np.asarray(self._run_forward()["Out"][0])
        pos = np.arange(6, dtype="float64")[:, None]
        freq = np.power(10000.0, -np.arange(4, dtype="float64") / 4)
        ang = pos * freq[None, :]
        enc = np.concatenate([np.sin(ang), np.cos(ang)], 1)
        np.testing.assert_allclose(out, 0.5 * x + 2.0 * enc[None],
                                   rtol=1e-4, atol=1e-5)
        self.check_grad(["X"], "Out")


class TestRoiPerspectiveTransform(OpTest):
    def test(self):
        """An axis-aligned quad must behave like a crop+resize: constant
        regions map to the constant."""
        x = np.full((1, 2, 12, 12), 2.5, "float32")
        quad = np.array([[2., 2., 9., 2., 9., 9., 2., 9.]], "float32")
        self.op_type = "roi_perspective_transform"
        self.inputs = {"X": x, "ROIs": quad}
        self.attrs = {"transformed_height": 4, "transformed_width": 4,
                      "spatial_scale": 1.0}
        out = np.asarray(self._run_forward()["Out"][0])
        np.testing.assert_allclose(out, 2.5, rtol=1e-4)


class TestDeformablePsroiPooling(OpTest):
    def test(self):
        """Zero offsets on a constant map: every bin equals the
        constant."""
        oc, ph, pw = 2, 2, 2
        x = np.full((1, oc * ph * pw, 8, 8), 1.5, "float32")
        rois = np.array([[0., 0., 8., 8.]], "float32")
        self.op_type = "deformable_psroi_pooling"
        self.inputs = {"Input": x, "ROIs": rois}
        self.attrs = {"pooled_height": ph, "pooled_width": pw,
                      "output_dim": oc, "spatial_scale": 1.0,
                      "sample_per_part": 4}
        out = np.asarray(self._run_forward()["Output"][0])
        assert out.shape == (1, oc, ph, pw)
        np.testing.assert_allclose(out, 1.5, rtol=1e-3)


class TestPolygonBoxTransform(OpTest):
    def test(self):
        r = np.random.RandomState(8)
        x = r.randn(1, 8, 2, 3).astype("float32")
        self.op_type = "polygon_box_transform"
        self.inputs = {"Input": x}
        out = np.asarray(self._run_forward()["Output"][0])
        gx = np.arange(3)[None, None, None, :]
        gy = np.arange(2)[None, None, :, None]
        is_x = (np.arange(8) % 2 == 0)[None, :, None, None]
        base = np.where(is_x, 4.0 * gx, 4.0 * gy)
        np.testing.assert_allclose(out, base - x, rtol=1e-5)


class TestShardIndex(OpTest):
    def test(self):
        ids = np.array([[1], [5], [9], [3]], "int64")
        self.op_type = "shard_index"
        self.inputs = {"X": ids}
        self.attrs = {"index_num": 12, "nshards": 3, "shard_id": 1,
                      "ignore_value": -1}
        out = np.asarray(self._run_forward()["Out"][0])
        # shard 1 owns ids [4, 8): 5 -> 1; others -> ignore
        e = np.array([[-1], [1], [-1], [-1]], "int64")
        np.testing.assert_array_equal(out, e)
