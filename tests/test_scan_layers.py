"""layers.Scan — lax.scan-backed fixed-trip loop over stacked [n, ...]
parameters (the TPU-native deep-stack builder; no direct reference
counterpart: the reference's recurrent_op (operators/recurrent_op.cc)
steps a sub-block via scope mutation, here the loop is functional so
grads are ordinary jax.vjp through lax.scan). Covers: training through
the scan, remat, per-iteration dropout keys, and EXACT forward parity
of the scan BERT encoder against the unrolled one under shared
parameter values."""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework
from paddle_tpu.core.scope import global_scope
from paddle_tpu.models import bert
from __graft_entry__ import _bert_feed


def _run(main, st, feed, fetch):
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(st)
    return exe, lambda: np.asarray(
        exe.run(main, feed=feed, fetch_list=[fetch])[0])


def test_scan_trains_through_stacked_params():
    L, H = 3, 8
    main, st = framework.Program(), framework.Program()
    main.random_seed = st.random_seed = 5
    with framework.program_guard(main, st):
        with framework.unique_name_guard():
            x = fluid.layers.data("x", shape=[H], dtype="float32")
            w = fluid.layers.create_parameter(
                shape=[L, H, H], dtype="float32", name="stk.w",
                default_initializer=fluid.initializer.TruncatedNormal(
                    0.0, 0.2))
            h = fluid.layers.fc(x, size=H)
            scan = fluid.layers.Scan(n=L)
            with scan.block():
                wi = scan.slice_input(w)
                nh = fluid.layers.relu(fluid.layers.matmul(h, wi))
                fluid.layers.assign(nh, output=h)
            loss = fluid.layers.mean(h)
            fluid.optimizer.SGDOptimizer(0.05).minimize(loss)
    exe, step = _run(main, st, {"x": np.ones((2, H), np.float32)}, loss)
    w0 = np.asarray(global_scope().find_var("stk.w")).copy()
    ls = [float(step().ravel()[0]) for _ in range(4)]
    w1 = np.asarray(global_scope().find_var("stk.w"))
    assert np.isfinite(ls).all()
    assert ls[-1] != ls[0], "loss did not move"
    # grads reached EVERY slice of the stacked param
    per_layer_delta = np.abs(w1 - w0).reshape(L, -1).max(axis=1)
    assert (per_layer_delta > 0).all(), per_layer_delta


def test_scan_without_carry_rebind_raises():
    """A body that never rebinds a pre-existing var would discard every
    iteration's results — the lowering refuses it (mirrors the while
    cond-rebind check)."""
    H = 4
    main, st = framework.Program(), framework.Program()
    with framework.program_guard(main, st):
        with framework.unique_name_guard():
            x = fluid.layers.data("x", shape=[H], dtype="float32")
            w = fluid.layers.create_parameter(
                shape=[2, H, H], dtype="float32", name="nc.w")
            h = fluid.layers.fc(x, size=H)
            scan = fluid.layers.Scan(n=2)
            with scan.block():
                wi = scan.slice_input(w)
                fluid.layers.matmul(h, wi)  # result dropped: no assign
            loss = fluid.layers.mean(h)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(st)
    with pytest.raises(Exception, match="never rebinds"):
        exe.run(main, feed={"x": np.ones((2, H), np.float32)},
                fetch_list=[loss])


def test_scan_slice_leading_dim_mismatch_raises():
    main, st = framework.Program(), framework.Program()
    with framework.program_guard(main, st):
        with framework.unique_name_guard():
            w = fluid.layers.create_parameter(
                shape=[4, 3], dtype="float32", name="w")
            scan = fluid.layers.Scan(n=3)
            with pytest.raises(ValueError, match="leading dim"):
                with scan.block():
                    scan.slice_input(w)
                    # unreachable; block exits via the raise
                    raise AssertionError


def _snapshot_params(prog):
    return {p.name: np.asarray(global_scope().find_var(p.name)).copy()
            for p in prog.all_parameters()}


def _stack_unrolled_into_scan(vals, cfg):
    """Assemble the scan path's stacked [L, ...] params from the
    unrolled per-layer values (q|k|v fused on the output axis)."""
    L = cfg.num_hidden_layers
    out = {}
    out["enc_qkv.w"] = np.stack([np.concatenate(
        [vals["layer_%d_attn_q.w" % i], vals["layer_%d_attn_k.w" % i],
         vals["layer_%d_attn_v.w" % i]], axis=1) for i in range(L)])
    out["enc_qkv.b"] = np.stack([np.concatenate(
        [vals["layer_%d_attn_q.b" % i], vals["layer_%d_attn_k.b" % i],
         vals["layer_%d_attn_v.b" % i]]) for i in range(L)])
    for scan_name, unroll_fmt in [
            ("enc_attn_out.w", "layer_%d_attn_out.w"),
            ("enc_attn_out.b", "layer_%d_attn_out.b"),
            ("enc_post_att_ln.scale", "layer_%d_post_att_ln.scale"),
            ("enc_post_att_ln.bias", "layer_%d_post_att_ln.bias"),
            ("enc_ffn0.w", "layer_%d_ffn0.w"),
            ("enc_ffn0.b", "layer_%d_ffn0.b"),
            ("enc_ffn1.w", "layer_%d_ffn1.w"),
            ("enc_ffn1.b", "layer_%d_ffn1.b"),
            ("enc_post_ffn_ln.scale", "layer_%d_post_ffn_ln.scale"),
            ("enc_post_ffn_ln.bias", "layer_%d_post_ffn_ln.bias")]:
        out[scan_name] = np.stack(
            [vals[unroll_fmt % i] for i in range(L)])
    return out


@pytest.mark.parametrize("remat", [False, True])
@pytest.mark.slow
def test_scan_bert_forward_parity_with_unrolled(remat):
    """Same parameter values => identical loss (is_test kills dropout).
    Also proves remat does not change the math."""
    cfg = bert.BertConfig.tiny()
    SEQ, B = 32, 2
    feed = _bert_feed(cfg, B, SEQ, max_pred=int(SEQ * 0.15))

    main_u, st_u = framework.Program(), framework.Program()
    main_u.random_seed = st_u.random_seed = 7
    with framework.program_guard(main_u, st_u):
        with framework.unique_name_guard():
            tot_u, _, _, _ = bert.bert_pretrain_loss(cfg, SEQ,
                                                     is_test=True)
    _, run_u = _run(main_u, st_u, feed, tot_u)
    loss_u = float(run_u().ravel()[0])
    vals = _snapshot_params(main_u)

    main_s, st_s = framework.Program(), framework.Program()
    main_s.random_seed = st_s.random_seed = 7
    with framework.program_guard(main_s, st_s):
        with framework.unique_name_guard():
            tot_s, _, _, _ = bert.bert_pretrain_loss(
                cfg, SEQ, is_test=True, scan_layers=True,
                scan_remat=remat)
    exe_s, run_s = _run(main_s, st_s, feed, tot_s)
    # overwrite shared params (embeddings/heads: same names) and
    # assemble the stacked encoder params from the unrolled values
    import jax.numpy as jnp

    stacked = _stack_unrolled_into_scan(vals, cfg)
    for name, v in {**vals, **stacked}.items():
        if global_scope().find_var(name) is not None \
                or name in stacked:
            global_scope().set_var(name, jnp.asarray(v))
    loss_s = float(run_s().ravel()[0])
    np.testing.assert_allclose(loss_s, loss_u, rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_scan_bert_train_decreases_and_per_layer_dropout_differs():
    cfg = bert.BertConfig.tiny()
    SEQ, B = 32, 4
    main, st = framework.Program(), framework.Program()
    main.random_seed = st.random_seed = 9
    with framework.program_guard(main, st):
        with framework.unique_name_guard():
            total, _, _, _ = bert.bert_pretrain_loss(
                cfg, SEQ, is_test=False, scan_layers=True,
                scan_remat=True)
            fluid.optimizer.AdamOptimizer(1e-3).minimize(total)
    feed = _bert_feed(cfg, B, SEQ, max_pred=int(SEQ * 0.15))
    _, step = _run(main, st, feed, total)
    ls = [float(step().ravel()[0]) for _ in range(6)]
    assert np.isfinite(ls).all()
    assert ls[-1] < ls[0], ls
