"""paddle.nn 2.0 surface tests: export-list parity with the reference
`python/paddle/nn/__init__.py` and eager behavior of the new layer
classes."""
import numpy as np
import pytest

import paddle_tpu
from paddle_tpu import nn
import paddle_tpu.fluid as fluid

# every name the reference nn/__init__.py DEFINE_ALIASes (minus module
# re-exports)
REFERENCE_NN_EXPORTS = """BCELoss BatchNorm BilinearTensorProduct Conv2D
Conv2DTranspose Conv3D Conv3DTranspose CrossEntropyLoss Embedding
GradientClipByGlobalNorm GradientClipByNorm GradientClipByValue GroupNorm
HSigmoid InstanceNorm L1Loss Layer LayerList LayerNorm LeakyReLU Linear
LogSoftmax MSELoss NLLLoss Pad2D Pool2D ReLU RowConv Sigmoid SpectralNorm
UpSample beam_search beam_search_decode case clip clip_by_norm cond data
gather_tree switch_case while_loop""".split()


def test_export_parity():
    missing = [n for n in REFERENCE_NN_EXPORTS if not hasattr(nn, n)]
    assert not missing, missing


def test_functional_parity():
    from paddle_tpu.nn import functional as F
    want = """conv2d conv2d_transpose conv3d conv3d_transpose interpolate
    image_resize pool2d pool3d adaptive_pool2d adaptive_pool3d relu gelu
    sigmoid softmax log_softmax dropout one_hot pad pad2d warpctc hsigmoid
    ssd_loss prior_box multiclass_nms roi_align yolo_box yolov3_loss
    grid_sampler affine_grid pixel_shuffle maxout selu cross_entropy
    softmax_with_cross_entropy mse_loss kldiv_loss log_loss npair_loss
    dice_loss noam_decay cosine_decay l2_normalize label_smooth""".split()
    missing = [n for n in want if not hasattr(F, n)]
    assert not missing, missing


def test_new_losses_eager():
    from paddle_tpu.fluid import dygraph

    r = np.random.RandomState(0)
    with dygraph.guard():
        p = dygraph.to_variable(
            r.uniform(0.1, 0.9, (4, 3)).astype("float32"))
        y = dygraph.to_variable(
            r.randint(0, 2, (4, 3)).astype("float32"))
        bce = nn.BCELoss()(p, y)
        e = -(np.asarray(y.numpy()) * np.log(p.numpy())
              + (1 - y.numpy()) * np.log(1 - p.numpy())).mean()
        np.testing.assert_allclose(float(bce.numpy()), e, rtol=1e-4)

        logp = dygraph.to_variable(np.log(
            r.dirichlet(np.ones(5), 6)).astype("float32"))
        lbl = dygraph.to_variable(r.randint(0, 5, (6,)).astype("int64"))
        nll = nn.NLLLoss()(logp, lbl)
        e = -logp.numpy()[np.arange(6), lbl.numpy()].mean()
        np.testing.assert_allclose(float(nll.numpy()), e, rtol=1e-4)


def test_new_layers_eager():
    from paddle_tpu.fluid import dygraph

    r = np.random.RandomState(1)
    with dygraph.guard():
        x = dygraph.to_variable(r.randn(2, 3, 4, 4).astype("float32"))
        pad = nn.Pad2D(paddings=1)(x)
        assert pad.shape == (2, 3, 6, 6)
        up = nn.UpSample(out_shape=[8, 8])(x)
        assert up.shape == (2, 3, 8, 8)
        inorm = nn.InstanceNorm(3)(x)
        assert inorm.shape == x.shape
        ls = nn.LogSoftmax()(x)
        np.testing.assert_allclose(
            np.exp(ls.numpy()).sum(-1), np.ones((2, 3, 4)), rtol=1e-4)

        x3 = dygraph.to_variable(r.randn(1, 2, 4, 4, 4).astype("float32"))
        c3 = nn.Conv3D(2, 4, 3, padding=1)(x3)
        assert c3.shape == (1, 4, 4, 4, 4)

        b = nn.BilinearTensorProduct(3, 4, 5)
        out = b(dygraph.to_variable(r.randn(6, 3).astype("float32")),
                dygraph.to_variable(r.randn(6, 4).astype("float32")))
        assert out.shape == (6, 5)

        hs = nn.HSigmoid(8, 10)
        cost = hs(dygraph.to_variable(r.randn(4, 8).astype("float32")),
                  dygraph.to_variable(r.randint(0, 10, (4, 1))
                                      .astype("int64")))
        assert np.all(cost.numpy() > 0)


def test_nn_initializer_namespace():
    assert hasattr(nn.initializer, "ConstantInitializer")
    assert hasattr(nn.initializer, "XavierInitializer")
