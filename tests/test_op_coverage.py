"""Zero genuine op absentees vs the reference's REGISTER_OPERATOR scan
(tools/op_coverage.py) — round-3's VERDICT found ~20 this way; this
test keeps the gap closed. Skips when the reference tree is absent."""
import os

import pytest


def test_no_genuine_op_absentees():
    if not os.path.isdir("/root/reference/paddle/fluid/operators"):
        pytest.skip("reference tree not available")
    import sys

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools"))
    import op_coverage

    missing, n_ref, n_have = op_coverage.missing_ops()
    assert not missing, (
        "op absentees reopened vs reference scan: %s" % missing)
    assert n_ref > 250 and n_have > 500  # scan sanity
