"""Cold tier: PS-backed row cache (paddle_tpu/embedding/cold.py) —
fault-in/eviction mechanics, admission-by-touch-frequency, the
capped==uncapped training contract, exactly-once across a pserver
kill/restart, and schema-valid telemetry events."""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.distributed.rpc import RpcClient, RpcServer
from paddle_tpu.fluid import framework
from paddle_tpu.utils.flags import get_flag, set_flags

VOCAB, DIM = 64, 8


@pytest.fixture(autouse=True)
def _flags():
    old = {k: get_flag(k) for k in
           ("FLAGS_tpu_sparse_embedding", "FLAGS_tpu_comm_bucket_mb")}
    yield
    set_flags(old)


def _fresh():
    from paddle_tpu.core import scope as scope_mod

    framework.switch_main_program(framework.Program())
    framework.switch_startup_program(framework.Program())
    scope_mod._global_scope = scope_mod.Scope()


def _scope():
    from paddle_tpu.core import scope as scope_mod

    return scope_mod._global_scope


def _ps(tmp_path=None, trainers=1):
    from paddle_tpu.distributed.ps import ParameterServer
    from paddle_tpu.fluid import framework as fw

    ps = ParameterServer(fw.Program(), None, trainers=trainers,
                         mode="async",
                         ckpt_dir=(str(tmp_path) if tmp_path else None),
                         ckpt_every=1)
    srv = RpcServer("127.0.0.1", 0, ps.handle)
    srv.start()
    return ps, srv, RpcClient("127.0.0.1:%d" % srv.port)


class _HostScope:
    """Dict-backed scope stand-in for cache unit tests."""

    def __init__(self, **vars_):
        self._v = dict(vars_)

    def find_var(self, n):
        return self._v.get(n)

    def set_var(self, n, v):
        self._v[n] = v


def _cache(client, capacity, scope=None, admit_after=2):
    from paddle_tpu.embedding import RowCache

    scope = scope or _HostScope(
        emb=np.zeros((capacity, DIM), np.float32),
        emb_m=np.zeros((capacity, DIM), np.float32))
    c = RowCache(client, "emb", VOCAB, DIM, capacity, scope=scope,
                 var_name="emb", moment_vars={"emb_m": "Moment"},
                 admit_after=admit_after)
    return c, scope


def test_fault_in_eviction_and_roundtrip(tmp_path):
    ps, srv, cli = _ps()
    try:
        c, scope = _cache(cli, capacity=8)
        full = np.arange(VOCAB * DIM, dtype=np.float32).reshape(
            VOCAB, DIM)
        c.seed_ps(full)
        slots = c.translate(np.array([3, 5, 3, 9]))
        assert slots.shape == (4,)
        assert slots[0] == slots[2]  # duplicate id, same slot
        # out-of-range ids map PAST the slot table (the sharded lookup
        # masks them to zeros) — never onto another row's slot
        oov = c.translate(np.array([3, -1, VOCAB + 7]))
        assert oov[0] == slots[0]
        assert oov[1] == c.capacity and oov[2] == c.capacity
        # faulted rows carry the authoritative values
        dev = np.asarray(scope.find_var("emb"))
        np.testing.assert_array_equal(dev[slots[1]], full[5])
        assert c.resident_rows == 3 and c._misses == 3
        # second touch: all hits
        c.translate(np.array([3, 5, 9]))
        assert c._misses == 3 and c._hits >= 3
        # capacity pressure: 8 resident max, evictions demote EXACT
        # device values (here: mutate a device row first)
        dev = np.asarray(scope.find_var("emb")).copy()
        dev[slots[0]] = 42.0
        scope.set_var("emb", dev)
        c.translate(np.arange(10, 16))  # 6 new ids > free slots
        assert c.resident_rows <= 8
        assert c._evicted > 0
        c.flush()
        got = c.ps_table()
        np.testing.assert_array_equal(got[3], np.full((DIM,), 42.0))
        # untouched rows keep their seed values
        np.testing.assert_array_equal(got[60], full[60])
    finally:
        srv.shutdown()
        ps.heartbeat.stop()


def test_admission_by_touch_frequency():
    ps, srv, cli = _ps()
    try:
        c, _ = _cache(cli, capacity=4, admit_after=2)
        c.seed_ps(np.zeros((VOCAB, DIM), np.float32))
        c.translate(np.array([1, 2]))
        c.translate(np.array([1, 2]))  # rows 1,2 admitted (2 touches)
        c.translate(np.array([3, 4]))  # one-hit wonders
        # 1 free slot short: the never-admitted rows evict FIRST
        c.translate(np.array([5, 6, 7]))
        resident = set(c._slot_of)
        # both one-hit wonders went first; the LRU admitted row (1)
        # paid the third slot — 2 (equally admitted, same recency
        # class) survives
        assert 3 not in resident and 4 not in resident
        assert 2 in resident, resident
        assert c._evicted >= 3
    finally:
        srv.shutdown()
        ps.heartbeat.stop()


def test_prefetch_overlaps_and_matches_sync(tmp_path):
    ps, srv, cli = _ps()
    try:
        c, scope = _cache(cli, capacity=16)
        full = np.random.RandomState(0).rand(
            VOCAB, DIM).astype(np.float32)
        c.seed_ps(full)
        ids = np.array([7, 11, 13])
        c.prefetch(ids)
        slots = c.translate(ids)  # joins the background fault-in
        dev = np.asarray(scope.find_var("emb"))
        for i, s in zip(ids, slots):
            np.testing.assert_array_equal(dev[s], full[i])
    finally:
        srv.shutdown()
        ps.heartbeat.stop()


def test_telemetry_events_schema_valid():
    from paddle_tpu.observability import flight, schema
    from paddle_tpu.observability.registry import registry

    ps, srv, cli = _ps()
    try:
        reg = registry()
        c, _ = _cache(cli, capacity=4)
        c.seed_ps(np.zeros((VOCAB, DIM), np.float32))
        c.translate(np.array([1, 2, 3]))
        c.translate(np.array([9, 10, 11]))  # forces evictions
        # events fan out through the flight recorder ring (and the
        # JSONL sink when FLAGS_tpu_telemetry_dir is set)
        recs = [r for r in flight.recorder().snapshot()["events"]
                if r.get("event") in ("embedding_fetch",
                                      "embedding_evict")]
        fetches = [r for r in recs if r["event"] == "embedding_fetch"]
        evicts = [r for r in recs if r["event"] == "embedding_evict"]
        assert fetches and evicts
        problems = schema.validate_records(recs)
        assert not problems, problems
        assert sum(r["rows_fetched"] for r in fetches) >= 6
        assert sum(r["rows_evicted"] for r in evicts) >= 2
        assert reg.gauge("embedding.resident_rows").value <= 4
    finally:
        srv.shutdown()
        ps.heartbeat.stop()


# -- the acceptance leg: capped table trains to the SAME loss ---------------

def _ctr_step_fn(cap_vocab):
    """One-table CTR-ish model whose embedding var holds `cap_vocab`
    rows (the device slot table for capped runs)."""
    framework.default_main_program().random_seed = 11
    framework.default_startup_program().random_seed = 11
    ids = fluid.layers.data(name="ids", shape=[1], dtype="int64")
    dense = fluid.layers.data(name="dense", shape=[4], dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    emb = fluid.layers.embedding(
        ids, size=[cap_vocab, DIM], is_sparse=True, padding_idx=0,
        param_attr=fluid.ParamAttr(name="ctr_emb"))
    h = fluid.layers.concat([emb, dense], axis=1)
    h = fluid.layers.fc(input=h, size=16, act="relu")
    logits = fluid.layers.fc(input=h, size=2)
    loss = fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(logits, label))
    fluid.optimizer.AdagradOptimizer(learning_rate=0.1).minimize(loss)
    return loss


def _batches(steps, batch=32, seed=5):
    r = np.random.RandomState(seed)
    out = []
    for _ in range(steps):
        ids = r.randint(0, VOCAB, (batch, 1))
        ids[:3] = 0  # padding positions in every batch
        out.append({
            "ids": ids.astype("int64"),
            "dense": r.rand(batch, 4).astype("float32"),
            "label": r.randint(0, 2, (batch, 1)).astype("int64")})
    return out


def _moment_name(prog):
    return next(n for n in (v.name for v in
                            prog.global_block().vars.values())
                if "ctr_emb" in n and "moment" in n)


def test_capped_trains_to_same_loss_as_uncapped():
    """A table capped below its full size (40 of 64 rows resident)
    trains BIT-IDENTICALLY to the uncapped run: rows fault in on
    demand with their moments, evictions demote exact values, and the
    slot-table update math is slot-index-independent."""
    import jax

    from paddle_tpu.embedding import RowCache

    steps = 6
    batches = _batches(steps)
    ndev = 4

    # uncapped reference (vocab-sized table, raw ids)
    _fresh()
    set_flags({"FLAGS_tpu_sparse_embedding": True,
               "FLAGS_tpu_comm_bucket_mb": 0.0})
    with framework.unique_name_guard():
        loss = _ctr_step_fn(VOCAB)
        prog = fluid.default_main_program()
        fluid.CompiledProgram(prog).with_data_parallel(
            loss_name=loss.name)
        prog._mesh = jax.sharding.Mesh(
            np.array(jax.devices()[:ndev]), ("dp",))
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        init_full = np.asarray(_scope().find_var("ctr_emb")).copy()
        ref_losses = [float(exe.run(prog, feed=b,
                                    fetch_list=[loss])[0].mean())
                      for b in batches]
        from paddle_tpu.parallel.sharded_update import \
            unshard_scope_value

        ref_table = np.asarray(unshard_scope_value(
            prog, "ctr_emb", _scope().find_var("ctr_emb"))).copy()

    # capped run: 40-slot device table, authoritative rows on the PS
    cap = 40
    ps, srv, cli = _ps()
    try:
        _fresh()
        with framework.unique_name_guard():
            loss = _ctr_step_fn(cap)
            prog = fluid.default_main_program()
            fluid.CompiledProgram(prog).with_data_parallel(
                loss_name=loss.name)
            prog._mesh = jax.sharding.Mesh(
                np.array(jax.devices()[:ndev]), ("dp",))
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(fluid.default_startup_program())
            mname = _moment_name(prog)
            cache = RowCache(cli, "ctr_emb", VOCAB, DIM, cap,
                             scope=_scope(), var_name="ctr_emb",
                             moment_vars={mname: "Moment"},
                             padding_idx=0)
            # authoritative init = the SAME initial table the uncapped
            # run drew (its first `cap` rows seeded the device table)
            cache.seed_ps(init_full)
            _scope().set_var("ctr_emb",
                             np.zeros((cap, DIM), np.float32))
            cap_losses = []
            for i, b in enumerate(batches):
                feed = dict(b)
                feed["ids"] = cache.translate(b["ids"])
                if i + 1 < len(batches):
                    # overlap the NEXT batch's PS round-trip with this
                    # step's compute (the reader-prefetcher idiom)
                    cache.prefetch(batches[i + 1]["ids"])
                cap_losses.append(float(exe.run(
                    prog, feed=feed, fetch_list=[loss])[0].mean()))
            assert cache._evicted > 0, "capacity never pressured"
            cache.flush()
            cap_table = cache.ps_table()
    finally:
        srv.shutdown()
        ps.heartbeat.stop()

    assert cap_losses == ref_losses
    np.testing.assert_array_equal(cap_table, ref_table)


# -- exactly-once across a pserver kill/restart ------------------------------

def test_cold_rows_survive_pserver_kill_and_dedup(tmp_path):
    """A demotion applied-and-persisted before the server dies is
    answered from the restored dedup marker on retry (never
    re-applied... write_rows is an exact write, but the marker proves
    the envelope short-circuits), and the reborn server serves the
    demoted rows — the cache keeps working across the restart."""
    import socket

    from paddle_tpu.distributed.rpc import (_ENVELOPE, read_msg,
                                            write_msg)

    ps1, srv1, cli = _ps(tmp_path)
    full = np.random.RandomState(1).rand(VOCAB, DIM).astype(np.float32)
    try:
        c, scope = _cache(cli, capacity=4)
        c.seed_ps(full)
        c.translate(np.array([1, 2, 3, 4]))
        dev = np.asarray(scope.find_var("emb")).copy()
        dev[:] = 7.5
        scope.set_var("emb", dev)
        c.translate(np.array([9, 10, 11, 12]))  # demotes rows 1..4
        assert c._evicted >= 4
        # flush so the LAST rpc is a marked write_rows (lookup_rows is
        # read-only and records no dedup marker) — retry_seq below
        # must name an APPLIED mutation
        c.flush()
        retry_seq = cli._seq
        rows_after = np.asarray(ps1.scope.find_var("emb")).copy()
        np.testing.assert_array_equal(rows_after[1],
                                      np.full((DIM,), 7.5))
    finally:
        srv1.shutdown()
        ps1.heartbeat.stop()

    # reborn server: tables + dedup markers restore from disk
    from paddle_tpu.distributed.ps import ParameterServer
    from paddle_tpu.fluid import framework as fw

    ps2 = ParameterServer(fw.Program(), None, trainers=1, mode="async",
                          ckpt_dir=str(tmp_path), ckpt_every=1)
    dedup = ps2.restore_from_checkpoint()
    assert dedup and cli._cid in dedup
    np.testing.assert_array_equal(
        np.asarray(ps2.scope.find_var("emb")), rows_after)
    srv2 = RpcServer("127.0.0.1", 0, ps2.handle)
    srv2.dedup_restore(dedup)
    srv2.start()
    try:
        # the lost-response retry of the LAST demotion short-circuits
        # at the marker
        s = socket.create_connection(("127.0.0.1", srv2.port))
        try:
            write_msg(s, [_ENVELOPE, cli._cid, retry_seq, "write_rows",
                          "emb", np.asarray([1], np.int64),
                          np.zeros((1, DIM), np.float32), 0])
            resp = read_msg(s)
            assert resp and resp[0] == "ok", resp
            # NOT re-applied: row 1 keeps its demoted 7.5s, not zeros
            np.testing.assert_array_equal(
                np.asarray(ps2.scope.find_var("emb"))[1],
                np.full((DIM,), 7.5))
        finally:
            s.close()
        # a fresh cache against the reborn server reads demoted rows
        cli2 = RpcClient("127.0.0.1:%d" % srv2.port)
        c2, scope2 = _cache(cli2, capacity=8)
        slots = c2.translate(np.array([1, 9]))
        dev2 = np.asarray(scope2.find_var("emb"))
        np.testing.assert_array_equal(dev2[slots[0]],
                                      np.full((DIM,), 7.5))
        np.testing.assert_array_equal(dev2[slots[1]], full[9])
    finally:
        srv2.shutdown()
        ps2.heartbeat.stop()
