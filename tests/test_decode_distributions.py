"""layers.dynamic_decode + BeamSearchDecoder (reference: layers/rnn.py)
and layers.distributions (reference: layers/distributions.py)."""
import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework, dygraph


def test_dynamic_decode_beam_search():
    """Deterministic cell: logits independent of state, so the best beam
    must repeat the argmax token until max steps."""
    from paddle_tpu.fluid.layers.rnn_decode import (
        BeamSearchDecoder, dynamic_decode, RNNCell)

    with dygraph.guard():
        vocab = 6
        logits_row = np.log(np.array(
            [0.01, 0.02, 0.6, 0.17, 0.1, 0.1], "float32"))

        class FixedCell(RNNCell):
            def call(self, inputs, states):
                b = inputs.shape[0]
                out = dygraph.to_variable(
                    np.tile(logits_row, (b, 1)))
                return out, states

        dec = BeamSearchDecoder(FixedCell(), start_token=1, end_token=0,
                                beam_size=2,
                                embedding_fn=lambda ids:
                                fluid.layers.one_hot(
                                    fluid.layers.unsqueeze(ids, [1]),
                                    depth=vocab),
                                output_fn=None)
        init = dygraph.to_variable(np.zeros((2, vocab), "float32"))
        outs, scores = dynamic_decode(dec, inits=init, max_step_num=4)
        ids = np.asarray(outs._val if hasattr(outs, "_val") else outs)
        assert ids.shape == (2, 4, 2)
        # best beam = token 2 at every step for every batch row
        np.testing.assert_array_equal(ids[:, :, 0], 2)


def test_distributions_normal_categorical():
    from paddle_tpu.fluid.layers.distributions import (
        Normal, Uniform, Categorical)

    with dygraph.guard():
        n1 = Normal(0.0, 1.0)
        n2 = Normal(1.0, 2.0)
        ent = np.asarray(n1.entropy()._val)
        np.testing.assert_allclose(
            ent, 0.5 + 0.5 * np.log(2 * np.pi), rtol=1e-5)
        kl = np.asarray(n1.kl_divergence(n2)._val)
        expect = np.log(2.0) + (0.25 + 0.25 * 1.0) - 0.5
        # KL(N(0,1)||N(1,2)) = log(2) + (1+1)/(2*4) - 1/2
        np.testing.assert_allclose(kl, np.log(2.0) + 2.0 / 8.0 - 0.5,
                                   rtol=1e-5)
        lp = np.asarray(n1.log_prob(
            dygraph.to_variable(np.array([0.0], "float32")))._val)
        np.testing.assert_allclose(lp, -0.5 * np.log(2 * np.pi),
                                   rtol=1e-5)

        u = Uniform(0.0, 2.0)
        np.testing.assert_allclose(np.asarray(u.entropy()._val),
                                   np.log(2.0), rtol=1e-5)

        logits = np.log(np.array([[0.5, 0.25, 0.25]], "float32"))
        c = Categorical(dygraph.to_variable(logits))
        ent = np.asarray(c.entropy()._val)
        expect = -(0.5 * np.log(0.5) + 2 * 0.25 * np.log(0.25))
        np.testing.assert_allclose(ent, [expect], rtol=1e-4)

        c2 = Categorical(dygraph.to_variable(
            np.log(np.array([[1 / 3, 1 / 3, 1 / 3]], "float32"))))
        kl = np.asarray(c.kl_divergence(c2)._val)
        assert kl[0] > 0


def test_distributions_sample_static():
    """Sampling works in the static graph via the seeded RNG ops."""
    from paddle_tpu.fluid.layers.distributions import Normal

    main, startup = framework.Program(), framework.Program()
    main.random_seed = startup.random_seed = 3
    with framework.program_guard(main, startup):
        with framework.unique_name_guard():
            d = Normal(0.0, 1.0)
            s = d.sample([1000])
    exe = fluid.Executor(fluid.CPUPlace())
    from paddle_tpu.core.scope import Scope

    scope = Scope()
    exe.run(startup, scope=scope)
    out = exe.run(main, feed={}, fetch_list=[s], scope=scope)
    arr = np.asarray(out[0])
    assert arr.shape == (1000,)
    assert abs(arr.mean()) < 0.2 and 0.8 < arr.std() < 1.2


def test_dynamic_decode_return_length():
    """return_length counts tokens through the first end token
    (reference dynamic_decode sequence_lengths semantics)."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import framework
    from paddle_tpu.fluid.layers import rnn_decode

    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup):
        with framework.unique_name_guard():
            cell = rnn_decode.GRUCell(hidden_size=8)
            decoder = rnn_decode.BeamSearchDecoder(
                cell, start_token=0, end_token=1, beam_size=2,
                embedding_fn=lambda ids: fluid.layers.one_hot(
                    fluid.layers.unsqueeze(ids, [1]), depth=16),
                output_fn=lambda h: fluid.layers.fc(
                    h, 16, name="len_out_fc"))
            init = fluid.layers.data("h0", shape=[2, 8],
                                     dtype="float32",
                                     append_batch_size=False)
            outs = rnn_decode.dynamic_decode(
                decoder, inits=init, max_step_num=5, return_length=True)
            assert len(outs) == 3
            ids, scores, lengths = outs
            exe = fluid.Executor()
            exe.run(startup)
            got = exe.run(main,
                          feed={"h0": np.zeros((2, 8), "float32")},
                          fetch_list=[ids, lengths])
    ids_v, len_v = np.asarray(got[0]), np.asarray(got[1])
    b, t, beam = ids_v.shape
    assert len_v.shape == (b, beam)
    for bi in range(b):
        for k in range(beam):
            seq = ids_v[bi, :, k]
            ends = np.nonzero(seq == 1)[0]
            expect = (ends[0] + 1) if len(ends) else t
            assert len_v[bi, k] == expect, (seq, len_v[bi, k])
