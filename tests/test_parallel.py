"""Distributed/parallel tests on the 8-device virtual CPU mesh
(reference strategy: SURVEY.md §4.3/4.4 — loss parity between distributed
and single-process runs; collective ops vs numpy expectation)."""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework


def _build_mlp(seed):
    framework.default_main_program().random_seed = seed
    framework.default_startup_program().random_seed = seed
    img = fluid.layers.data(name="img", shape=[32], dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    h = fluid.layers.fc(input=img, size=32, act="relu")
    logits = fluid.layers.fc(input=h, size=4)
    loss = fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(logits, label))
    return loss


def _batch(rng, n=64):
    x = rng.rand(n, 32).astype("float32")
    y = rng.randint(0, 4, (n, 1)).astype("int64")
    return x, y


def test_fleet_dp_loss_parity(rng):
    """Fleet collective DP over 8 chips == single-chip run, same global
    batch (reference: TestDistBase compares per-step losses)."""
    from paddle_tpu import fleet
    from paddle_tpu.core import scope as scope_mod

    x, y = _batch(rng)

    # single-device baseline
    loss = _build_mlp(seed=1234)
    fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    base_losses = [float(exe.run(feed={"img": x, "label": y},
                                 fetch_list=[loss])[0][0])
                   for _ in range(5)]

    # fleet DP run in a fresh program/scope
    framework.switch_main_program(framework.Program())
    framework.switch_startup_program(framework.Program())
    scope_mod._global_scope = scope_mod.Scope()
    with framework.unique_name_guard():
        loss2 = _build_mlp(seed=1234)
        fleet.init(is_collective=True)
        opt = fleet.distributed_optimizer(
            fluid.optimizer.SGDOptimizer(learning_rate=0.1))
        opt.minimize(loss2)
        exe2 = fluid.Executor(fluid.CPUPlace())
        exe2.run(fluid.default_startup_program())
        dp_losses = []
        for _ in range(5):
            out = exe2.run(feed={"img": x, "label": y},
                           fetch_list=[loss2])[0]
            assert out.shape == (8,)  # per-shard losses concat'd
            dp_losses.append(float(out.mean()))

    np.testing.assert_allclose(base_losses, dp_losses, rtol=2e-4,
                               atol=1e-5)


def test_compiled_program_data_parallel(rng):
    """CompiledProgram.with_data_parallel drives the same SPMD path."""
    loss = _build_mlp(seed=7)
    fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
    compiled = fluid.CompiledProgram(
        fluid.default_main_program()).with_data_parallel(
        loss_name=loss.name)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    x, y = _batch(rng)
    l0 = exe.run(compiled, feed={"img": x, "label": y},
                 fetch_list=[loss])[0]
    for _ in range(10):
        exe.run(compiled, feed={"img": x, "label": y}, fetch_list=[loss])
    l1 = exe.run(compiled, feed={"img": x, "label": y},
                 fetch_list=[loss])[0]
    assert float(l1.mean()) < float(l0.mean())


def test_eager_collectives():
    import jax

    import paddle_tpu.distributed as dist
    import paddle_tpu as paddle

    dist.init_parallel_env()
    x = np.arange(16, dtype="float32").reshape(8, 2)
    t = paddle.to_tensor(x)
    out = dist.all_reduce(t)
    got = np.asarray(out._value())
    # each dp shard of rows is replaced by the sum over shards
    expect = np.tile(x.reshape(8, 1, 2).sum(0), (8, 1))
    np.testing.assert_allclose(got, expect)


def test_collective_ops_in_shard_map():
    """c_* kernels under a live mesh (reference: test_collective_base
    check_with_place vs numpy)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from paddle_tpu import ops as ops_lib
    from paddle_tpu.parallel import env as penv

    mesh = Mesh(np.array(jax.devices()), ("dp",))
    penv.register_ring(0, "dp", 8)
    x = np.arange(32, dtype="float32").reshape(8, 4)

    def run(op, **attrs):
        def inner(v):
            with penv.collective_scope({"dp": 8}):
                return ops_lib.run_op(op, {"X": [v]},
                                      dict(attrs, ring_id=0))["Out"][0]

        from paddle_tpu.parallel.env import shard_map_compat

        f = jax.jit(shard_map_compat(inner, mesh=mesh, in_specs=P("dp"),
                                     out_specs=P("dp"),
                                     check_vma=False))
        return np.asarray(f(x))

    np.testing.assert_allclose(
        run("c_allreduce_sum"), np.tile(x.reshape(8, 1, 4).sum(0), (8, 1)))
    np.testing.assert_allclose(
        run("c_allreduce_max"), np.tile(x.max(0), (8, 1)))
    np.testing.assert_allclose(
        run("c_broadcast", root=2), np.tile(x[2], (8, 1)))

    # allgather: per-shard [1,4] -> [8,4] on every shard -> global [64,4]
    got = run("c_allgather", nranks=8)
    assert got.shape == (64, 4)
    np.testing.assert_allclose(got[:8], x)
    np.testing.assert_allclose(got[8:16], x)

    # reducescatter: per-shard [8,4] scatters to [1,4]; device i holds
    # the sum over devices of their i-th local row
    x2 = np.arange(256, dtype="float32").reshape(64, 4)

    def run2(op, **attrs):
        def inner(v):
            with penv.collective_scope({"dp": 8}):
                return ops_lib.run_op(op, {"X": [v]},
                                      dict(attrs, ring_id=0))["Out"][0]

        from paddle_tpu.parallel.env import shard_map_compat

        f = jax.jit(shard_map_compat(inner, mesh=mesh, in_specs=P("dp"),
                                     out_specs=P("dp"),
                                     check_vma=False))
        return np.asarray(f(x2))

    got = run2("c_reducescatter", nranks=8)
    assert got.shape == (8, 4)
    blocks = x2.reshape(8, 8, 4)
    np.testing.assert_allclose(got, blocks.sum(0))

    # prod with negatives AND zeros: the reference kRedProd
    # (c_allreduce_op.h:58, ncclProd) covers all reals — the former
    # exp(psum(log)) lowering NaN'd here (VERDICT r3 weak #2)
    xs = np.linspace(-2.0, 2.0, 32).astype("float32").reshape(8, 4)
    xs[3, 1] = 0.0  # exact zero on one shard
    def run_prod(v):
        def inner(s):
            with penv.collective_scope({"dp": 8}):
                return ops_lib.run_op("c_allreduce_prod", {"X": [s]},
                                      {"ring_id": 0})["Out"][0]

        from paddle_tpu.parallel.env import shard_map_compat

        f = jax.jit(shard_map_compat(inner, mesh=mesh, in_specs=P("dp"),
                                     out_specs=P("dp"),
                                     check_vma=False))
        return np.asarray(f(v))

    np.testing.assert_allclose(
        run_prod(xs), np.tile(np.prod(xs.reshape(8, 1, 4), axis=0),
                              (8, 1)), rtol=1e-6)


def test_spmd_transformer_parity():
    """dp2 x pp2 x tp2 == single-device, same params + batch."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.parallel.transformer import (
        SPMDConfig, init_params, init_opt_state, make_train_step,
        shard_params, demo_batch)

    kw = dict(vocab=64, d_model=32, n_heads=4, d_ff=64, seq_len=16,
              n_layers=4, n_micro=4, dtype="float32", remat=False)
    cfg1 = SPMDConfig(dp=1, pp=1, tp=1, **kw)
    cfg8 = SPMDConfig(dp=2, pp=2, tp=2, **kw)

    losses = {}
    for name, cfg in (("single", cfg1), ("spmd", cfg8)):
        mesh = cfg.mesh()
        params = shard_params(init_params(cfg, seed=5), cfg, mesh)
        opt = init_opt_state(params)
        step = make_train_step(cfg, mesh)
        tokens, labels = demo_batch(cfg, 8, seed=5)
        ls = []
        p, o = params, opt
        for i in range(3):
            p, o, loss = step(p, o, tokens, labels, jnp.int32(i))
            ls.append(float(loss))
        losses[name] = ls

    np.testing.assert_allclose(losses["single"], losses["spmd"],
                               rtol=2e-4, atol=1e-5)
    assert losses["spmd"][-1] < losses["spmd"][0]


def test_spmd_transformer_grad_parity():
    """Gradient VALUES match between dp2xpp2xtp2 and single device —
    pins the cotangent scaling of the loss collectives (a psum inside
    the differentiated function would inflate grads by tp*pp, which
    Adam hides but SGD/weight-decay would not)."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.parallel.transformer import (
        SPMDConfig, init_params, init_opt_state, make_train_step,
        shard_params, demo_batch)

    kw = dict(vocab=64, d_model=32, n_heads=4, d_ff=64, seq_len=16,
              n_layers=4, n_micro=4, dtype="float32", remat=False)
    grads = {}
    for name, cfg in (("single", SPMDConfig(dp=1, pp=1, tp=1, **kw)),
                      ("spmd", SPMDConfig(dp=2, pp=2, tp=2, **kw))):
        mesh = cfg.mesh()
        params = shard_params(init_params(cfg, seed=5), cfg, mesh)
        opt = init_opt_state(params)
        step = make_train_step(cfg, mesh, with_grads=True)
        tokens, labels = demo_batch(cfg, 8, seed=5)
        _, _, _, g = step(params, opt, tokens, labels, jnp.int32(0))
        grads[name] = jax.tree.map(np.asarray, g)

    def flat_layers(leaf):
        # (pp, layers_per_stage, ...) -> (n_layers, ...)
        return leaf.reshape((-1,) + leaf.shape[2:])

    for key in grads["single"]["layers"]:
        np.testing.assert_allclose(
            flat_layers(grads["spmd"]["layers"][key]),
            flat_layers(grads["single"]["layers"][key]),
            rtol=5e-4, atol=1e-6, err_msg=key)
    np.testing.assert_allclose(grads["spmd"]["embed"],
                               grads["single"]["embed"],
                               rtol=5e-4, atol=1e-6)
