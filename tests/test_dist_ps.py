"""Parameter-server mode end-to-end: REAL subprocesses on localhost
(reference pattern: test_dist_base.py:506 TestDistBase — 2 pservers +
2 trainers vs single-process, per-step loss comparison)."""
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.dist

_DIR = os.path.dirname(os.path.abspath(__file__))
_RUNNER = os.path.join(_DIR, "dist_ps_runner.py")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _env():
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("PALLAS_AXON", "AXON_", "TPU_"))}
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    return env


def _spawn(args, extra_env=None):
    env = _env()
    if extra_env:
        env.update(extra_env)
    return subprocess.Popen([sys.executable, _RUNNER] + args,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True,
                            env=env, cwd=_DIR)


def _losses(out):
    return [float(line.split()[1]) for line in out.splitlines()
            if line.startswith("LOSS")]


@pytest.mark.parametrize("mode", [
    "sync", "async",
    # geo / half_async exercise alternate push schedules over the same
    # PS wire protocol; ~20s each, so they ride in the slow lane to
    # keep the default run inside the tier-1 budget (sync + async stay)
    pytest.param("geo", marks=pytest.mark.slow),
    pytest.param("half_async", marks=pytest.mark.slow),
])
def test_ps_2x2_localhost(mode):
    eps = "127.0.0.1:%d,127.0.0.1:%d" % (_free_port(), _free_port())
    ep_list = eps.split(",")
    n_trainers = 2

    single = _spawn(["single"])
    sout, _ = single.communicate(timeout=240)
    assert single.returncode == 0, sout
    base = _losses(sout)
    assert len(base) == 5

    servers = [_spawn(["pserver", ep, eps, str(n_trainers), mode])
               for ep in ep_list]
    trainers = [_spawn(["trainer", str(i), eps, str(n_trainers), mode])
                for i in range(n_trainers)]
    touts = []
    try:
        for t in trainers:
            out, _ = t.communicate(timeout=240)
            assert t.returncode == 0, out
            touts.append(out)
        for s in servers:
            out, _ = s.communicate(timeout=60)
            assert s.returncode == 0, out
    finally:
        for p in servers + trainers:
            if p.poll() is None:
                p.kill()

    all_ls = [_losses(out) for out in touts]
    for ls in all_ls:
        assert len(ls) >= 5, touts
        assert np.isfinite(ls).all()
        assert ls[-1] < ls[0], ls
    if mode == "sync":
        # sync PS == single-process SGD on the same global batch: the
        # pserver applies mean-of-half-batch grads == full-batch grad,
        # and each trainer's loss is the mean over its half, so the
        # AVERAGE of the two trainers' losses equals the single-process
        # full-batch loss at every step (fp tolerance only).
        avg = np.mean(all_ls, axis=0)
        np.testing.assert_allclose(avg, base, rtol=1e-4, atol=1e-4)


def test_ps_sync_prefetch_parity():
    """Async input pipeline in PS mode: trainers feeding prefetched
    on-device batches + LazyFetch results produce EXACTLY the same
    per-step losses as the plain synchronous trainers (the PS push
    path keeps its required per-step grad sync either way)."""
    n_trainers = 2

    def cohort(extra_env=None):
        eps = "127.0.0.1:%d" % _free_port()
        servers = [_spawn(["pserver", ep, eps, str(n_trainers), "sync"])
                   for ep in eps.split(",")]
        trainers = [
            _spawn(["trainer", str(i), eps, str(n_trainers), "sync"],
                   extra_env=extra_env)
            for i in range(n_trainers)]
        touts = []
        try:
            for t in trainers:
                out, _ = t.communicate(timeout=240)
                assert t.returncode == 0, out
                touts.append(out)
            for s in servers:
                out, _ = s.communicate(timeout=60)
                assert s.returncode == 0, out
        finally:
            for p in servers + trainers:
                if p.poll() is None:
                    p.kill()
        return [_losses(out) for out in touts]

    plain = cohort()
    prefetched = cohort({"PADDLE_PS_TEST_PREFETCH": "1"})
    assert plain == prefetched, (plain, prefetched)


def test_ps_distributed_lookup_table_sync():
    """distributed_lookup_table: sparse embedding hosted on pservers,
    row prefetch before each step, SelectedRows-style sparse grad push
    (reference: distributed_lookup_table_op.cc +
    parameter_prefetch.cc). Sync 2x2 == single-process."""
    eps = "127.0.0.1:%d,127.0.0.1:%d" % (_free_port(), _free_port())
    ep_list = eps.split(",")
    n_trainers = 2

    single = _spawn(["single_emb"])
    sout, _ = single.communicate(timeout=240)
    assert single.returncode == 0, sout
    base = _losses(sout)

    servers = [_spawn(["pserver_emb", ep, eps, str(n_trainers), "sync"])
               for ep in ep_list]
    trainers = [_spawn(["trainer_emb", str(i), eps, str(n_trainers),
                        "sync"]) for i in range(n_trainers)]
    touts = []
    try:
        for t in trainers:
            out, _ = t.communicate(timeout=240)
            assert t.returncode == 0, out
            touts.append(out)
        for s in servers:
            out, _ = s.communicate(timeout=60)
            assert s.returncode == 0, out
    finally:
        for p in servers + trainers:
            if p.poll() is None:
                p.kill()

    all_ls = [_losses(out) for out in touts]
    avg = np.mean(all_ls, axis=0)
    np.testing.assert_allclose(avg, base, rtol=1e-4, atol=1e-4)


def test_heartbeat_monitor_detects_lost_worker():
    """Reference: heart_beat_monitor.cc LostWorkerMonitor — a worker
    whose beats stop past the timeout is flagged."""
    from paddle_tpu.distributed.ps import HeartBeatMonitor

    lost = []
    m = HeartBeatMonitor(trainers=2, timeout_s=5.0,
                         on_lost=lost.append)
    t = [0.0]
    m._clock = lambda: t[0]
    m.beat(0)
    m.beat(1)
    t[0] = 3.0
    m.beat(1)  # worker 1 keeps beating
    assert m.lost_workers() == []
    t[0] = 7.0  # worker 0 silent for 7s > 5s; worker 1 only 4s
    assert m.lost_workers() == [0]
    assert lost == [0]
    m.beat(0)  # recovery clears the flag
    t[0] = 8.0
    assert m.lost_workers() == []


_FLEET_RUNNER = os.path.join(_DIR, "dist_fleet_ps_runner.py")


def _spawn_fleet(args):
    return subprocess.Popen([sys.executable, _FLEET_RUNNER] + args,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True,
                            env=_env(), cwd=_DIR)


def test_fleet_a_sync_ps_2x2_localhost():
    """strategy.a_sync through the PUBLIC fleet API (role makers +
    init_server/run_server/init_worker) — reference: fleet 2.0
    parameter_server mode. 2 pservers + 2 trainers; every trainer's
    loss must decrease on the learnable batch."""
    eps = "127.0.0.1:%d,127.0.0.1:%d" % (_free_port(), _free_port())
    n_trainers = 2

    servers = [_spawn_fleet(["pserver", str(i), eps, str(n_trainers)])
               for i in range(2)]
    trainers = [_spawn_fleet(["trainer", str(i), eps, str(n_trainers)])
                for i in range(n_trainers)]
    touts = []
    try:
        for t in trainers:
            out, _ = t.communicate(timeout=240)
            assert t.returncode == 0, out
            touts.append(out)
        for s in servers:
            out, _ = s.communicate(timeout=60)
            assert s.returncode == 0, out
            assert "SERVED" in out
    finally:
        for p in servers + trainers:
            if p.poll() is None:
                p.kill()

    for out in touts:
        ls = _losses(out)
        assert len(ls) == 5, out
        assert ls[-1] < ls[0], (ls, out)


def test_fleet_ps_via_launch_ps(tmp_path):
    """The COMPLETE reference user workflow: one role-agnostic script
    (PaddleCloudRoleMaker from env) for 2 servers + 2 trainers, spawned
    by `paddle_tpu.distributed.launch_ps` — reference quickstart:
    launch_ps.py + fleet parameter_server mode."""
    from paddle_tpu.distributed import launch_ps

    script = os.path.join(_DIR, "fleet_ps_env_runner.py")
    logs = str(tmp_path / "logs")
    servers = "127.0.0.1:%d,127.0.0.1:%d" % (_free_port(), _free_port())
    env_backup = dict(os.environ)
    clean = _env()  # snapshot BEFORE clear: keep PATH/HOME/... intact
    try:
        # full swap: update() without clear() would leave the
        # accelerator-plugin vars in place and the spawned roles would
        # hang on the tunnel
        os.environ.clear()
        os.environ.update(clean)
        rc = launch_ps.launch([
            "--servers", servers, "--worker_num", "2",
            "--log_dir", logs, script])
    finally:
        os.environ.clear()
        os.environ.update(env_backup)
    assert rc == 0
    for i in range(2):
        with open(os.path.join(logs, "workerlog.%d.log" % i)) as f:
            ls = _losses(f.read())
        assert len(ls) == 5
        assert ls[-1] < ls[0], ls
