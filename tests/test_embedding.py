"""Vocab-sharded embedding engine (paddle_tpu/embedding) — plan
engagement, bit-parity vs the replicated dense reference, 1/N HBM
layout, touched-rows collective bytes, padding_idx/OOV semantics, and
the elastic N' checkpoint round-trip.

Numerics reference: the dense path at PER-VARIABLE collectives
(FLAGS_tpu_comm_bucket_mb=0 — PR-3's lowering, the documented CPU
ground truth; the dense path's own bucketed lowering can drift 1 ulp
on tiny programs at small worlds, the PR-4 CPU-fusion caveat, which
is independent of this engine). The engine itself keeps the bucket
contract: sparse-bucketed == sparse-per-var is asserted below.
"""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework
from paddle_tpu.utils.flags import get_flag, set_flags

VOCAB, DIM = 37, 8


@pytest.fixture(autouse=True)
def _flags():
    old = {k: get_flag(k) for k in
           ("FLAGS_tpu_sparse_embedding", "FLAGS_tpu_comm_bucket_mb",
            "FLAGS_tpu_static_checks")}
    yield
    set_flags(old)


def _fresh():
    from paddle_tpu.core import scope as scope_mod

    framework.switch_main_program(framework.Program())
    framework.switch_startup_program(framework.Program())
    scope_mod._global_scope = scope_mod.Scope()


def _scope():
    from paddle_tpu.core import scope as scope_mod

    return scope_mod._global_scope


def _build(opt="adagrad", two_sites=False, padding_idx=0, infer=False):
    framework.default_main_program().random_seed = 7
    framework.default_startup_program().random_seed = 7
    ids = fluid.layers.data(name="ids", shape=[1], dtype="int64")
    dense = fluid.layers.data(name="dense", shape=[4], dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    emb = fluid.layers.embedding(
        ids, size=[VOCAB, DIM], is_sparse=True, padding_idx=padding_idx,
        param_attr=fluid.ParamAttr(name="emb_w"))
    parts = [emb, dense]
    if two_sites:
        ids2 = fluid.layers.data(name="ids2", shape=[1], dtype="int64")
        emb2 = fluid.layers.embedding(
            ids2, size=[VOCAB, DIM], is_sparse=True,
            padding_idx=padding_idx,
            param_attr=fluid.ParamAttr(name="emb_w"))
        parts.append(emb2)
    h = fluid.layers.concat(parts, axis=1)
    h = fluid.layers.fc(input=h, size=16, act="relu")
    logits = fluid.layers.fc(input=h, size=2)
    if infer:
        return fluid.layers.softmax(logits), emb
    loss = fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(logits, label))
    O = fluid.optimizer
    {"sgd": lambda: O.SGDOptimizer(learning_rate=0.1),
     "momentum": lambda: O.MomentumOptimizer(learning_rate=0.1,
                                             momentum=0.9),
     "adagrad": lambda: O.AdagradOptimizer(learning_rate=0.1),
     "adam": lambda: O.AdamOptimizer(learning_rate=0.05),
     }[opt]().minimize(loss)
    return loss, emb


def _mesh(prog, ndev, hybrid=False):
    import jax
    from jax.sharding import Mesh

    if hybrid:
        prog._mesh = Mesh(np.array(jax.devices()[:ndev]).reshape(
            ndev // 2, 2), ("dcn", "ici"))
    elif ndev != 8:
        prog._mesh = Mesh(np.array(jax.devices()[:ndev]), ("dp",))


def _batch(seed=0, full_cover=False, batch=48):
    # 48 divides every mesh size used here (2, 3, 4, 8) and covers
    # the 37-row vocab when full_cover asks for it
    r = np.random.RandomState(seed)
    if full_cover:
        # every row touched (incl. padding 0, whose grads mask out):
        # adam's dense update moves momentum-tail rows even at zero
        # grad, so exactness vs dense needs full coverage (the lazy
        # contract, documented in embedding/README.md)
        base = np.arange(VOCAB)
        extra = r.randint(0, VOCAB, (batch - VOCAB,))
        ids = np.concatenate([base, extra])
        r.shuffle(ids)
    else:
        ids = r.randint(0, VOCAB, (batch,))
    return {"ids": ids.reshape(batch, 1).astype("int64"),
            "ids2": r.randint(0, VOCAB, (batch, 1)).astype("int64"),
            "dense": r.rand(batch, 4).astype("float32"),
            "label": r.randint(0, 2, (batch, 1)).astype("int64")}


def _state_snapshot(prog):
    from paddle_tpu.parallel.sharded_update import unshard_scope_value

    out = {}
    for n in sorted(_scope().local_var_names()):
        v = _scope().find_var(n)
        if v is None:
            continue
        out[n] = np.asarray(unshard_scope_value(prog, n, v)).copy()
    return out


def _train(sparse, opt="adagrad", ndev=4, hybrid=False, steps=4,
           bucket_mb=0.0, two_sites=False, full_cover=None,
           feed=None, seed_state=None, want_plan=True):
    _fresh()
    set_flags({"FLAGS_tpu_sparse_embedding": sparse,
               "FLAGS_tpu_comm_bucket_mb": bucket_mb})
    if full_cover is None:
        full_cover = opt in ("adam", "momentum")
    feed = feed or _batch(full_cover=full_cover)
    if not two_sites:
        feed = {k: v for k, v in feed.items() if k != "ids2"}
    with framework.unique_name_guard():
        loss, emb = _build(opt, two_sites=two_sites)
        prog = fluid.default_main_program()
        fluid.CompiledProgram(prog).with_data_parallel(
            loss_name=loss.name)
        _mesh(prog, ndev, hybrid)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        if seed_state:
            for n, v in seed_state.items():
                if _scope().find_var(n) is not None:
                    _scope().set_var(n, v.copy())
        losses = [float(exe.run(prog, feed=feed,
                                fetch_list=[loss])[0].mean())
                  for _ in range(steps)]
        plan = getattr(prog, "_sparse_plan", None)
        snap = _state_snapshot(prog)
    if sparse and want_plan:
        assert plan is not None, \
            getattr(prog, "_sparse_embedding_fallback", None)
        assert "emb_w" in plan.tables
    if not sparse:
        assert plan is None
    return losses, snap, plan, exe, prog


def _assert_state_equal(a, b):
    keys = sorted(set(a) & set(b))
    assert keys
    for n in keys:
        assert np.array_equal(a[n], b[n]), \
            "state %r differs (max delta %g)" % (
                n, float(np.abs(a[n].astype(np.float64)
                                - b[n].astype(np.float64)).max()))


# -- plan engagement ---------------------------------------------------------

def test_plan_engagement_and_flag_off():
    _, _, plan, _, prog = _train(True, "adagrad", ndev=4)
    t = plan.tables["emb_w"]
    assert t.opt_type == "adagrad"
    assert list(t.row_state) == ["Moment"]
    assert plan.state_vars[t.row_state["Moment"]].shape == (VOCAB, DIM)
    # padded to a multiple of the shard count
    assert t.info.padded_rows == 40 and t.info.rows_local == 10
    _train(False, "adagrad", ndev=4)  # asserts plan is None


def test_declines_are_recorded_not_fatal():
    # global-norm clip reads every grad -> the table degrades to the
    # dense path with a structured reason, and training still runs
    _fresh()
    set_flags({"FLAGS_tpu_sparse_embedding": True})
    feed = _batch()
    feed.pop("ids2")
    with framework.unique_name_guard():
        framework.default_main_program().random_seed = 7
        framework.default_startup_program().random_seed = 7
        ids = fluid.layers.data(name="ids", shape=[1], dtype="int64")
        dense = fluid.layers.data(name="dense", shape=[4],
                                  dtype="float32")
        label = fluid.layers.data(name="label", shape=[1],
                                  dtype="int64")
        emb = fluid.layers.embedding(ids, size=[VOCAB, DIM],
                                     is_sparse=True)
        h = fluid.layers.concat([emb, dense], axis=1)
        logits = fluid.layers.fc(input=h, size=2)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        fluid.clip.set_gradient_clip(
            fluid.clip.GradientClipByGlobalNorm(0.5))
        fluid.optimizer.AdagradOptimizer(
            learning_rate=0.1).minimize(loss)
        fluid.clip._clip_attr.clear()
        prog = fluid.default_main_program()
        fluid.CompiledProgram(prog).with_data_parallel(
            loss_name=loss.name)
        _mesh(prog, 4)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        exe.run(prog, feed=feed, fetch_list=[loss])
        assert getattr(prog, "_sparse_plan", None) is None
        reasons = [f["reason"] for f in
                   prog._sparse_embedding_fallback]
        assert any("touched outside" in r for r in reasons), reasons


# -- bit-parity vs the replicated dense reference ----------------------------

@pytest.mark.parametrize("opt,ndev,hybrid", [
    ("sgd", 2, False),
    ("adagrad", 4, False),
    ("adam", 8, False),
    ("adagrad", 4, True),   # hybrid 2x2: table replicated over dcn
])
def test_parity_vs_dense(opt, ndev, hybrid):
    ls, ss, _, _, _ = _train(True, opt, ndev=ndev, hybrid=hybrid)
    ld, sd, _, _, _ = _train(False, opt, ndev=ndev, hybrid=hybrid)
    assert ls == ld
    _assert_state_equal(ss, sd)


@pytest.mark.slow
@pytest.mark.parametrize("opt", ["sgd", "momentum", "adagrad", "adam"])
@pytest.mark.parametrize("ndev,hybrid", [(2, False), (4, False),
                                         (8, False), (4, True),
                                         (8, True)])
def test_parity_matrix_full(opt, ndev, hybrid):
    ls, ss, _, _, _ = _train(True, opt, ndev=ndev, hybrid=hybrid)
    ld, sd, _, _, _ = _train(False, opt, ndev=ndev, hybrid=hybrid)
    assert ls == ld
    _assert_state_equal(ss, sd)


def test_sparse_keeps_bucket_contract():
    # the engine composes with PR-4 bucketed collectives for the DENSE
    # params without breaking their bit-identity to per-var. ndev=4:
    # at ndev=2 this tiny program's DENSE fc-bias bucket drifts 1 ulp
    # off per-var on XLA:CPU with or without the sparse engine (the
    # PR-4 CPU-fusion caveat) — not an engine property
    lb, sb, _, _, _ = _train(True, "adagrad", ndev=4, bucket_mb=25.0)
    lp, sp, _, _, _ = _train(True, "adagrad", ndev=4, bucket_mb=0.0)
    assert lb == lp
    _assert_state_equal(sb, sp)


def _ulp_dist(a, b):
    """Max elementwise distance in float32 ulps (int32 lexicographic
    view, monotone across the sign bit; both zeros map to 0)."""
    a = np.asarray(a, np.float32).ravel()
    b = np.asarray(b, np.float32).ravel()
    ai = a.view(np.int32).astype(np.int64)
    bi = b.view(np.int32).astype(np.int64)
    ai = np.where(ai < 0, np.int64(-0x80000000) - ai, ai)
    bi = np.where(bi < 0, np.int64(-0x80000000) - bi, bi)
    return int(np.abs(ai - bi).max()) if a.size else 0


def test_dense_fc_bucket_cpu_drift_is_ulp_bounded_at_ndev2():
    """Regression pin for the PR 15 observation (see ROADMAP): at
    ndev=2 this tiny program's DENSE fc-bias bucket can drift off the
    per-var lowering on XLA:CPU by at most ONE float32 ulp (the PR-4
    CPU-fusion caveat — /N + cast regrouping past the optimization
    barriers). This pins the drift BOUNDED, per state var, per step:
    a >1-ulp delta means the bucketed dense lowering regressed, not
    the known fusion artifact. The sparse table and its moments stay
    bit-exact regardless — the caveat is not an engine property."""
    lb, sb, _, _, _ = _train(True, "adagrad", ndev=2, bucket_mb=25.0,
                             steps=1)
    lp, sp, _, _, _ = _train(True, "adagrad", ndev=2, bucket_mb=0.0,
                             steps=1)
    for n in sb:
        if n.startswith("emb_w"):
            assert np.array_equal(sb[n], sp[n]), \
                "sparse engine state must stay bit-exact: %s" % n
    worst = {n: _ulp_dist(sb[n], sp[n]) for n in sorted(sb)}
    assert max(worst.values()) <= 1, worst
    assert _ulp_dist(np.float32(lb), np.float32(lp)) <= 1, (lb, lp)


def test_two_sites_one_table_parity():
    ls, ss, plan, _, _ = _train(True, "adagrad", ndev=4,
                                two_sites=True)
    assert len(plan.tables["emb_w"].sites) == 2
    ld, sd, _, _, _ = _train(False, "adagrad", ndev=4, two_sites=True)
    assert ls == ld
    _assert_state_equal(ss, sd)


# -- layout: 1/N HBM, touched-rows collective bytes --------------------------

def test_table_and_moment_hbm_is_one_over_n():
    _, _, plan, _, prog = _train(True, "adagrad", ndev=4)
    import jax

    for name, info in plan.state_vars.items():
        v = _scope().find_var(name)
        assert isinstance(v, jax.Array)
        assert tuple(v.shape) == (40, DIM)
        shards = v.addressable_shards
        per_dev = {s.device.id: s.data.shape for s in shards}
        on_mesh = [d.id for d in prog._mesh.devices.reshape(-1)]
        for did in on_mesh:
            assert per_dev[did] == (10, DIM), (name, per_dev)
        # replicated devices (off-mesh) hold nothing extra: the mesh
        # spans 4 of 8 devices here
    # save path: logical shape round-trips
    from paddle_tpu.parallel.sharded_update import unshard_scope_value

    w = unshard_scope_value(prog, "emb_w", _scope().find_var("emb_w"))
    assert w.shape == (VOCAB, DIM)


def test_collective_bytes_scale_with_batch_not_vocab():
    _fresh()
    set_flags({"FLAGS_tpu_sparse_embedding": True,
               "FLAGS_tpu_comm_bucket_mb": 0.0})
    feed = _batch()
    feed.pop("ids2")
    with framework.unique_name_guard():
        loss, _ = _build("adagrad")
        prog = fluid.default_main_program()
        fluid.CompiledProgram(prog).with_data_parallel(
            loss_name=loss.name)
        _mesh(prog, 4)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        exe.run(prog, feed=feed, fetch_list=[loss])
        col = exe.collective_report(prog, feed=feed,
                                    fetch_list=[loss])
    assert col["total_ici_bytes"] > 0
    # the dense path syncs a (VOCAB, DIM) fp32 grad per step: any
    # single collective that big would be vocab-proportional
    dense_grad_bytes = VOCAB * DIM * 4
    biggest = max(
        v["tensor_bytes"] / max(v["count"], 1)
        for k, v in col.items()
        if isinstance(v, dict) and "tensor_bytes" in v)
    assert biggest < dense_grad_bytes
    # the sparse schedule's signature collectives are present: ids/tap
    # all_gathers and the lookup psum_scatter
    assert col.get("all_gather", {}).get("count", 0) >= 2
    assert col.get("reduce_scatter", {}).get("count", 0) >= 1


# -- padding_idx / OOV semantics --------------------------------------------

def test_padding_idx_rows_zero_and_frozen():
    _fresh()
    set_flags({"FLAGS_tpu_sparse_embedding": True,
               "FLAGS_tpu_comm_bucket_mb": 0.0})
    feed = _batch()
    feed.pop("ids2")
    feed["ids"][:8] = 0  # padding id
    with framework.unique_name_guard():
        loss, emb = _build("adagrad")
        prog = fluid.default_main_program()
        fluid.CompiledProgram(prog).with_data_parallel(
            loss_name=loss.name)
        _mesh(prog, 4)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        w0 = np.asarray(_scope().find_var("emb_w"))[0].copy()
        for _ in range(3):
            out = exe.run(prog, feed=feed, fetch_list=[loss, emb])
        emb_out = np.asarray(out[1])
        # padding positions look up exact zeros
        assert np.array_equal(emb_out[:8], np.zeros((8, DIM), "f"))
        # the padding row never trains (reference contract)
        from paddle_tpu.parallel.sharded_update import \
            unshard_scope_value

        w = unshard_scope_value(prog, "emb_w",
                                _scope().find_var("emb_w"))
        assert np.array_equal(np.asarray(w)[0], w0)


def test_oov_raises_under_static_checks():
    _fresh()
    set_flags({"FLAGS_tpu_sparse_embedding": True,
               "FLAGS_tpu_static_checks": "error"})
    feed = _batch()
    feed.pop("ids2")
    with framework.unique_name_guard():
        loss, _ = _build("sgd")
        prog = fluid.default_main_program()
        fluid.CompiledProgram(prog).with_data_parallel(
            loss_name=loss.name)
        _mesh(prog, 4)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        exe.run(prog, feed=feed, fetch_list=[loss])  # in-range: fine
        bad = dict(feed)
        bad["ids"] = feed["ids"].copy()
        bad["ids"][3] = VOCAB + 5
        with pytest.raises(ValueError, match="out-of-range"):
            exe.run(prog, feed=bad, fetch_list=[loss])
        # warn mode: non-fatal, like every other checker on the flag
        set_flags({"FLAGS_tpu_static_checks": "warn"})
        with pytest.warns(UserWarning, match="out-of-range"):
            exe.run(prog, feed=bad, fetch_list=[loss])
        # flag off: silent (sharded lookup yields a zero row)
        set_flags({"FLAGS_tpu_static_checks": "off"})
        exe.run(prog, feed=bad, fetch_list=[loss])


# -- elastic checkpoint round-trip (N' != N) --------------------------------

@pytest.mark.parametrize("new_ndev", [2, 3])
def test_checkpoint_reshard_roundtrip(new_ndev):
    # train at 4 devs, snapshot LOGICAL state, resume at N' devs ==
    # dense replicated resumed from the same snapshot, bit-identical
    # (incl. genuinely different row padding: vocab 37 -> 40 at 4,
    # 38 at 2, 39 at 3)
    _, snap, _, _, _ = _train(True, "adagrad", ndev=4, steps=3)
    ls, ss, plan, _, _ = _train(True, "adagrad", ndev=new_ndev,
                                steps=3, seed_state=snap)
    assert plan.tables["emb_w"].info.padded_rows == \
        -(-VOCAB // new_ndev) * new_ndev
    ld, sd, _, _, _ = _train(False, "adagrad", ndev=new_ndev, steps=3,
                             seed_state=snap)
    assert ls == ld
    _assert_state_equal(ss, sd)


def test_stale_world_padding_strips_on_restore():
    # a scope value arriving as the OLD world's padded (40, D) buffer
    # restores bit-identically at ndev=3 (padded 39)
    _, snap, _, _, _ = _train(True, "adagrad", ndev=4, steps=2)
    padded = {n: v for n, v in snap.items()}
    padded["emb_w"] = np.pad(snap["emb_w"], ((0, 3), (0, 0)))  # (40,D)
    ls, ss, _, _, _ = _train(True, "adagrad", ndev=3, steps=2,
                             seed_state=padded)
    lref, sref, _, _, _ = _train(True, "adagrad", ndev=3, steps=2,
                                 seed_state=snap)
    assert ls == lref
    _assert_state_equal(ss, sref)


# -- forward-only programs ---------------------------------------------------

def test_forward_only_table_stays_sharded():
    _fresh()
    set_flags({"FLAGS_tpu_sparse_embedding": True})
    feed = _batch()
    with framework.unique_name_guard():
        prob, emb = _build(infer=True)
        prog = fluid.default_main_program()
        fluid.CompiledProgram(prog).with_data_parallel()
        _mesh(prog, 4)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        out_s = np.asarray(exe.run(
            prog, feed={"ids": feed["ids"], "dense": feed["dense"]},
            fetch_list=[emb])[0])
        assert getattr(prog, "_sparse_plan", None) is not None
        import jax

        w = _scope().find_var("emb_w")
        assert isinstance(w, jax.Array) and tuple(w.shape) == (40, DIM)
    _fresh()
    set_flags({"FLAGS_tpu_sparse_embedding": False})
    with framework.unique_name_guard():
        prob, emb = _build(infer=True)
        prog = fluid.default_main_program()
        fluid.CompiledProgram(prog).with_data_parallel()
        _mesh(prog, 4)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        out_d = np.asarray(exe.run(
            prog, feed={"ids": feed["ids"], "dense": feed["dense"]},
            fetch_list=[emb])[0])
    assert np.array_equal(out_s, out_d)


# -- bench block: registry-assembled + schema-valid telemetry ---------------

def test_embedding_block_is_registry_assembled(tmp_path):
    import json
    import os

    from paddle_tpu import observability as obs
    from paddle_tpu.observability import publish, schema

    _fresh()
    set_flags({"FLAGS_tpu_sparse_embedding": True})
    obs.configure(telemetry_dir=str(tmp_path), rank=0)
    feed = _batch()
    feed.pop("ids2")
    try:
        with framework.unique_name_guard():
            loss, _ = _build("adagrad")
            prog = fluid.default_main_program()
            fluid.CompiledProgram(prog).with_data_parallel(
                loss_name=loss.name)
            _mesh(prog, 4)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(fluid.default_startup_program())
            exe.run(prog, feed=feed, fetch_list=[loss])
            blocks = publish.bench_blocks(exe, prog, feed, [loss])
            # the registry is the source of truth: what bench attaches
            # IS what the registry holds
            assert blocks == obs.registry().blocks()
            emb = blocks["embedding"]
            assert "emb_w" in emb["tables"]
            t = emb["tables"]["emb_w"]
            assert t["vocab"] == VOCAB and t["rows_per_replica"] == 10
            assert emb["shards"] == 4
            # per-replica state is the 1/N shard of table + moment
            assert emb["state_per_replica_bytes"] == 2 * 10 * DIM * 4
            # dense reference: one vocab-sized grad allreduce — scales
            # with VOCAB; the sparse schedule scales with touched rows
            # (the < crossover needs real vocab sizes: bench.py
            # --embedding at vocab 20k shows 0.28MB vs 9.9MB)
            assert emb["modeled_dense_sync_bytes_per_step"] == \
                2 * VOCAB * DIM * 4
            assert emb["touched_rows_per_step"] == 48
            # the JSONL stream stays schema-valid with the new events
            jsonl = blocks["telemetry"]["jsonl"]
            assert jsonl and os.path.exists(jsonl)
            lines = [json.loads(ln) for ln in open(jsonl)]
            assert schema.validate_records(lines) == []
    finally:
        obs.reset_registry()


@pytest.mark.slow
def test_perf_analysis_embedding_cli(tmp_path):
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable,
         os.path.join(repo, "tools", "perf_analysis.py"),
         "--embedding"],
        capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    diff = json.load(open(os.path.join(repo, "artifacts",
                                       "embedding_diff.json")))
    assert diff["tables_sharded"] == 4
    assert diff["state_bytes"]["per_replica"] * diff["ndev"] == \
        diff["state_bytes"]["logical"]
    assert diff["largest_sharded_collective_bytes"] < \
        diff["smallest_vocab_grad_bytes"]
    assert diff["row_cache"]["evicted_rows"] > 0


@pytest.mark.slow
def test_bench_embedding_cli():
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py"),
         "--embedding", "4"],
        capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    line = next(ln for ln in r.stdout.splitlines()
                if ln.startswith("BENCH_RESULT_JSON:"))
    res = json.loads(line.split(":", 1)[1])
    assert res["tables_sharded"] == 8
    emb = res["embedding"]
    assert emb["state_per_replica_bytes"] * emb["shards"] == \
        pytest.approx(emb["state_logical_bytes"], rel=0.01)
    assert emb["modeled_sparse_sync_bytes_per_step"] < \
        emb["modeled_dense_sync_bytes_per_step"]


# -- engine units ------------------------------------------------------------

def test_fetching_sparse_grad_densifies():
    # debug fetch of a planned table's gradient: the SelectedRows grad
    # stays bound past its optimizer op and densifies to the logical
    # (vocab, dim) mean gradient at fn exit (the checker warns, the
    # run must not crash)
    _fresh()
    set_flags({"FLAGS_tpu_sparse_embedding": True,
               "FLAGS_tpu_comm_bucket_mb": 0.0})
    feed = _batch()
    feed.pop("ids2")
    with framework.unique_name_guard():
        loss, _ = _build("sgd")
        prog = fluid.default_main_program()
        fluid.CompiledProgram(prog).with_data_parallel(
            loss_name=loss.name)
        _mesh(prog, 4)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        out = exe.run(prog, feed=feed,
                      fetch_list=[loss, "emb_w@GRAD"])
        g = np.asarray(out[1])
        assert g.shape == (VOCAB, DIM)
        touched = np.unique(feed["ids"].reshape(-1))
        untouched = np.setdiff1d(np.arange(VOCAB), touched)
        assert np.abs(g[touched]).sum() > 0
        assert np.array_equal(g[untouched],
                              np.zeros((len(untouched), DIM), "f"))


def test_aggregate_rows_matches_dense_association():
    # duplicate ids across replicas: per-replica partials folded in
    # replica order, then /world — the pmean association, exactly
    import jax

    from paddle_tpu.embedding.engine import _aggregate_rows
    from paddle_tpu.embedding.planner import SparseTablePlan

    plan = SparseTablePlan.__new__(SparseTablePlan)
    plan.ndev = 2
    plan.dcn_size = 2  # world 4, hybrid fold (pods of 2)
    ids = np.array([3, 5, 3, 7, 5, 3, 9, 3], np.int32)  # 4 slices of 2
    vals = np.linspace(0.1, 1.7, 16).reshape(8, 2).astype("f")
    rows, grads = jax.jit(
        lambda i, v: _aggregate_rows(i, v, plan))(ids, vals)
    rows, grads = np.asarray(rows), np.asarray(grads)
    ref = {}
    for d in range(2):  # dense association: pod partials, then pods
        for r in range(2):
            part = {}
            for k in range(2):
                pos = (d * 2 + r) * 2 + k
                part[ids[pos]] = part.get(
                    ids[pos], np.zeros(2, "f")) + vals[pos]
            for i, v in part.items():
                ref[i] = ref.get(i, np.zeros(2, "f")) + v
    for i, v in ref.items():
        slot = list(rows).index(i)
        assert np.array_equal(grads[slot], v / 4.0), (i, grads[slot],
                                                      v / 4.0)


def test_foreign_op_on_engine_value_raises():
    # runtime twin of the sparse-update lint error: an op consuming a
    # TableShard/SparseRowGrad without a rule fails loudly at trace
    from paddle_tpu.embedding import engine as eng
    from paddle_tpu.embedding.planner import (RowShardInfo,
                                              SparseTablePlan)

    plan = SparseTablePlan(axis="dp", ndev=2, dcn_axis=None,
                           dcn_size=1, tables={})
    info = RowShardInfo("w", (8, 2), "float32", 2)

    class FakeOp:
        type = "elementwise_pow"
        input_names = {"X": ["w"]}
        output_names = {"Out": ["o"]}
        attrs = {}

    tok = eng._ACTIVE.set(plan)
    try:
        with pytest.raises(RuntimeError, match="sparse-aware rule"):
            eng.maybe_exec(FakeOp(), {"w": eng.TableShard(
                np.zeros((4, 2), "f"), info)})
    finally:
        eng._ACTIVE.reset(tok)
