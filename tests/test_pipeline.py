"""Fluid pipeline parallelism: PipelineOptimizer cuts the program into
stages run by the GPipe engine (parallel/pipeline.py: shard_map over a
'pp' mesh axis + lax.scan fill-drain + ppermute boundary handoff).
Reference: optimizer.py:3634 PipelineOptimizer + section_worker.cc:82."""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework


def _build(pipeline, n_micro=4, lr=0.2):
    main, startup = framework.Program(), framework.Program()
    main.random_seed = startup.random_seed = 5
    with framework.program_guard(main, startup):
        with framework.unique_name_guard():
            x = fluid.layers.data(name="x", shape=[32], dtype="float32")
            label = fluid.layers.data(name="label", shape=[1],
                                      dtype="int64")
            h1 = fluid.layers.fc(input=x, size=64, act="relu")
            h2 = fluid.layers.fc(input=h1, size=64, act="relu")
            logits = fluid.layers.fc(input=h2, size=10)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, label))
            opt = fluid.optimizer.SGDOptimizer(learning_rate=lr)
            if pipeline:
                opt = fluid.optimizer.PipelineOptimizer(
                    opt, cut_list=[[h1]], num_microbatches=n_micro)
            opt.minimize(loss)
    return main, startup, loss


def _run(pipeline, steps=6, n_micro=4):
    from paddle_tpu.core.scope import Scope

    main, startup, loss = _build(pipeline, n_micro=n_micro)
    scope = Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    r = np.random.RandomState(3)
    x = r.rand(32, 32).astype("float32")
    y = r.randint(0, 10, (32, 1)).astype("int64")
    losses = []
    for _ in range(steps):
        out = exe.run(main, feed={"x": x, "label": y},
                      fetch_list=[loss], scope=scope)
        losses.append(float(np.asarray(out[0]).reshape(-1)[0]))
    return losses


def test_pipeline_matches_nonpipelined():
    """GPipe microbatching is exact: per-step losses match the plain
    single-computation program (same seeded init, no dropout)."""
    base = _run(pipeline=False)
    pp = _run(pipeline=True)
    np.testing.assert_allclose(pp, base, rtol=2e-5, atol=2e-5)
    assert pp[-1] < pp[0]


def test_pipeline_single_stage_grad_accumulation():
    """No cut_list -> one stage: the engine degrades to exact microbatch
    gradient accumulation."""
    from paddle_tpu.core.scope import Scope

    main, startup, loss = _build(pipeline=False)
    # rebuild with pipeline but no cuts
    main2, startup2 = framework.Program(), framework.Program()
    main2.random_seed = startup2.random_seed = 5
    with framework.program_guard(main2, startup2):
        with framework.unique_name_guard():
            x = fluid.layers.data(name="x", shape=[32], dtype="float32")
            label = fluid.layers.data(name="label", shape=[1],
                                      dtype="int64")
            h1 = fluid.layers.fc(input=x, size=64, act="relu")
            h2 = fluid.layers.fc(input=h1, size=64, act="relu")
            logits = fluid.layers.fc(input=h2, size=10)
            loss2 = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, label))
            opt = fluid.optimizer.PipelineOptimizer(
                fluid.optimizer.SGDOptimizer(learning_rate=0.2),
                num_microbatches=2)
            opt.minimize(loss2)

    r = np.random.RandomState(3)
    x_ = r.rand(32, 32).astype("float32")
    y_ = r.randint(0, 10, (32, 1)).astype("int64")

    scope = Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup2, scope=scope)
    out = exe.run(main2, feed={"x": x_, "label": y_},
                  fetch_list=[loss2], scope=scope)
    assert np.isfinite(float(np.asarray(out[0]).reshape(-1)[0]))


def test_pipeline_rejects_bn_state_updates():
    """v1 restriction is loud: in-forward state updates raise."""
    from paddle_tpu.core.scope import Scope

    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup):
        with framework.unique_name_guard():
            x = fluid.layers.data(name="x", shape=[16], dtype="float32")
            label = fluid.layers.data(name="label", shape=[1],
                                      dtype="int64")
            h = fluid.layers.fc(input=x, size=16)
            h = fluid.layers.batch_norm(input=h)
            cut = fluid.layers.fc(input=h, size=16, act="relu")
            logits = fluid.layers.fc(input=cut, size=4)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, label))
            opt = fluid.optimizer.PipelineOptimizer(
                fluid.optimizer.SGDOptimizer(learning_rate=0.1),
                cut_list=[[cut]], num_microbatches=2)
            opt.minimize(loss)
    scope = Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    with pytest.raises(NotImplementedError, match="state update"):
        exe.run(main,
                feed={"x": np.zeros((8, 16), "float32"),
                      "label": np.zeros((8, 1), "int64")},
                fetch_list=[loss], scope=scope)
