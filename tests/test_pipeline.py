"""Fluid pipeline parallelism: PipelineOptimizer cuts the program into
stages run by the GPipe engine (parallel/pipeline.py: shard_map over a
'pp' mesh axis + lax.scan fill-drain + ppermute boundary handoff).
Reference: optimizer.py:3634 PipelineOptimizer + section_worker.cc:82."""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework


def _build(pipeline, n_micro=4, lr=0.2):
    main, startup = framework.Program(), framework.Program()
    main.random_seed = startup.random_seed = 5
    with framework.program_guard(main, startup):
        with framework.unique_name_guard():
            x = fluid.layers.data(name="x", shape=[32], dtype="float32")
            label = fluid.layers.data(name="label", shape=[1],
                                      dtype="int64")
            h1 = fluid.layers.fc(input=x, size=64, act="relu")
            h2 = fluid.layers.fc(input=h1, size=64, act="relu")
            logits = fluid.layers.fc(input=h2, size=10)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, label))
            opt = fluid.optimizer.SGDOptimizer(learning_rate=lr)
            if pipeline:
                opt = fluid.optimizer.PipelineOptimizer(
                    opt, cut_list=[[h1]], num_microbatches=n_micro)
            opt.minimize(loss)
    return main, startup, loss


def _run(pipeline, steps=6, n_micro=4):
    from paddle_tpu.core.scope import Scope

    main, startup, loss = _build(pipeline, n_micro=n_micro)
    scope = Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    r = np.random.RandomState(3)
    x = r.rand(32, 32).astype("float32")
    y = r.randint(0, 10, (32, 1)).astype("int64")
    losses = []
    for _ in range(steps):
        out = exe.run(main, feed={"x": x, "label": y},
                      fetch_list=[loss], scope=scope)
        losses.append(float(np.asarray(out[0]).reshape(-1)[0]))
    return losses


def test_pipeline_matches_nonpipelined():
    """GPipe microbatching is exact: per-step losses match the plain
    single-computation program (same seeded init, no dropout)."""
    base = _run(pipeline=False)
    pp = _run(pipeline=True)
    np.testing.assert_allclose(pp, base, rtol=2e-5, atol=2e-5)
    assert pp[-1] < pp[0]


def test_pipeline_single_stage_grad_accumulation():
    """No cut_list -> one stage: the engine degrades to exact microbatch
    gradient accumulation."""
    from paddle_tpu.core.scope import Scope

    main, startup, loss = _build(pipeline=False)
    # rebuild with pipeline but no cuts
    main2, startup2 = framework.Program(), framework.Program()
    main2.random_seed = startup2.random_seed = 5
    with framework.program_guard(main2, startup2):
        with framework.unique_name_guard():
            x = fluid.layers.data(name="x", shape=[32], dtype="float32")
            label = fluid.layers.data(name="label", shape=[1],
                                      dtype="int64")
            h1 = fluid.layers.fc(input=x, size=64, act="relu")
            h2 = fluid.layers.fc(input=h1, size=64, act="relu")
            logits = fluid.layers.fc(input=h2, size=10)
            loss2 = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, label))
            opt = fluid.optimizer.PipelineOptimizer(
                fluid.optimizer.SGDOptimizer(learning_rate=0.2),
                num_microbatches=2)
            opt.minimize(loss2)

    r = np.random.RandomState(3)
    x_ = r.rand(32, 32).astype("float32")
    y_ = r.randint(0, 10, (32, 1)).astype("int64")

    scope = Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup2, scope=scope)
    out = exe.run(main2, feed={"x": x_, "label": y_},
                  fetch_list=[loss2], scope=scope)
    assert np.isfinite(float(np.asarray(out[0]).reshape(-1)[0]))


def test_fleet_dp_pipeline_matches_nonpipelined():
    """Fleet DP x PipelineOptimizer (2 stages x 4 replicas on the 8-dev
    mesh) matches the plain single-computation program: GPipe microbatch
    accumulation is exact and the dp pmean reproduces the global-batch
    mean (VERDICT r2 next #3)."""
    from paddle_tpu import fleet
    from paddle_tpu.core.scope import Scope

    base = _run(pipeline=False, steps=5)

    main, startup = framework.Program(), framework.Program()
    main.random_seed = startup.random_seed = 5
    with framework.program_guard(main, startup):
        with framework.unique_name_guard():
            x = fluid.layers.data(name="x", shape=[32], dtype="float32")
            label = fluid.layers.data(name="label", shape=[1],
                                      dtype="int64")
            h1 = fluid.layers.fc(input=x, size=64, act="relu")
            h2 = fluid.layers.fc(input=h1, size=64, act="relu")
            logits = fluid.layers.fc(input=h2, size=10)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, label))
            fleet.init(is_collective=True)
            opt = fleet.distributed_optimizer(
                fluid.optimizer.PipelineOptimizer(
                    fluid.optimizer.SGDOptimizer(learning_rate=0.2),
                    cut_list=[[h1]], num_microbatches=4))
            opt.minimize(loss)
    assert main._pipeline_cfg["dp"] == 4  # 8 devices / 2 stages

    scope = Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    r = np.random.RandomState(3)
    x_ = r.rand(32, 32).astype("float32")
    y_ = r.randint(0, 10, (32, 1)).astype("int64")
    losses = []
    for _ in range(5):
        out = exe.run(main, feed={"x": x_, "label": y_},
                      fetch_list=[loss], scope=scope)
        losses.append(float(np.asarray(out[0]).reshape(-1)[0]))
    np.testing.assert_allclose(losses, base, rtol=2e-5, atol=2e-5)


def _build_bn_net(cut, n_micro=2, lr=0.1):
    """conv+BN ResNet-stem-style net; BN lives on stage 0 when cut."""
    main, startup = framework.Program(), framework.Program()
    main.random_seed = startup.random_seed = 11
    with framework.program_guard(main, startup):
        with framework.unique_name_guard():
            x = fluid.layers.data(name="x", shape=[4, 8, 8],
                                  dtype="float32")
            label = fluid.layers.data(name="label", shape=[1],
                                      dtype="int64")
            h = fluid.layers.batch_norm(input=x, momentum=0.9)
            h = fluid.layers.conv2d(h, num_filters=8, filter_size=3,
                                    padding=1, act="relu")
            cut_var = fluid.layers.pool2d(h, pool_size=2, pool_stride=2,
                                          pool_type="avg")
            flat = fluid.layers.reshape(cut_var, [-1, 8 * 4 * 4])
            logits = fluid.layers.fc(input=flat, size=4)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, label))
            opt = fluid.optimizer.PipelineOptimizer(
                fluid.optimizer.SGDOptimizer(learning_rate=lr),
                cut_list=[[cut_var]] if cut else [],
                num_microbatches=n_micro)
            opt.minimize(loss)
    return main, startup, loss


def _run_bn(cut, steps=4, n_micro=2):
    from paddle_tpu.core.scope import Scope

    main, startup, loss = _build_bn_net(cut, n_micro=n_micro)
    scope = Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    r = np.random.RandomState(9)
    x = (r.rand(8, 4, 8, 8) * 2).astype("float32")
    y = r.randint(0, 4, (8, 1)).astype("int64")
    losses = []
    for _ in range(steps):
        out = exe.run(main, feed={"x": x, "label": y},
                      fetch_list=[loss], scope=scope)
        losses.append(float(np.asarray(out[0]).reshape(-1)[0]))
    # fetch BN running stats from the scope
    bn_mean = bn_var = None
    for name in scope.local_var_names():
        if "batch_norm" in name and ".mean" in name:
            bn_mean = np.asarray(scope.find_var(name))
        if "batch_norm" in name and ".var" in name:
            bn_var = np.asarray(scope.find_var(name))
    return losses, bn_mean, bn_var, x


def test_pipeline_bn_stats_v2():
    """v2: BN running-stat updates inside pipeline stages are carried
    through the scan and written back (VERDICT r2 next #4). Cut vs
    no-cut pipelines are bit-equivalent (stage splitting never changes
    math; both microbatch identically), and the running mean after one
    step equals the numpy sequential per-microbatch update."""
    base_losses, base_mean, base_var, _ = _run_bn(cut=False, steps=4)
    pp_losses, pp_mean, pp_var, x = _run_bn(cut=True, steps=4)
    np.testing.assert_allclose(pp_losses, base_losses, rtol=2e-5,
                               atol=2e-5)
    np.testing.assert_allclose(pp_mean, base_mean, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(pp_var, base_var, rtol=1e-5, atol=1e-6)
    assert pp_losses[-1] < pp_losses[0]

    # one-step numpy check of the sequential microbatch update:
    # mb_k = rows [k*4:(k+1)*4]; mean <- 0.9*mean + 0.1*mu_k, twice
    _, mean1, _, _ = _run_bn(cut=True, steps=1)
    m = np.zeros(4)
    for k in range(2):
        mu = x[k * 4:(k + 1) * 4].mean(axis=(0, 2, 3))
        m = 0.9 * m + 0.1 * mu
    np.testing.assert_allclose(mean1, m, rtol=1e-4, atol=1e-5)


def test_pipeline_typed_int_boundary():
    """v2: non-float boundary values cross the cut in the i32 lane of
    the dtype-tagged ring buffer (v1 raised on them)."""
    from paddle_tpu.core.scope import Scope

    main, startup = framework.Program(), framework.Program()
    main.random_seed = startup.random_seed = 13
    with framework.program_guard(main, startup):
        with framework.unique_name_guard():
            x = fluid.layers.data(name="x", shape=[16], dtype="float32")
            label = fluid.layers.data(name="label", shape=[1],
                                      dtype="int64")
            h = fluid.layers.fc(input=x, size=12)
            ids = fluid.layers.argmax(h, axis=1)  # int64 boundary
            emb = fluid.layers.embedding(ids, size=[12, 8])
            logits = fluid.layers.fc(input=emb, size=4)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, label))
            opt = fluid.optimizer.PipelineOptimizer(
                fluid.optimizer.SGDOptimizer(learning_rate=0.1),
                cut_list=[[ids]], num_microbatches=2)
            opt.minimize(loss)
    scope = Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    r = np.random.RandomState(3)
    feed = {"x": r.rand(8, 16).astype("float32"),
            "label": r.randint(0, 4, (8, 1)).astype("int64")}
    losses = []
    for _ in range(6):
        out = exe.run(main, feed=feed, fetch_list=[loss], scope=scope)
        losses.append(float(np.asarray(out[0]).reshape(-1)[0]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]  # embedding/fc on stage 1 still learn


def test_pipeline_bypass_records_structured_decline():
    """The unified planner (sparse/TP/ZeRO-1) never runs for
    _pipeline_cfg programs — the pipeline engine owns the partition.
    That bypass must be a STRUCTURED decline on the program's fallback
    trail (kind="pipeline_bypassed", surfaced by perf_analysis
    --sharded-diff), recorded exactly once even across recompiles."""
    from paddle_tpu.core.scope import Scope
    from paddle_tpu.utils.flags import get_flag, set_flags

    old = get_flag("FLAGS_tpu_sharded_weight_update")
    set_flags({"FLAGS_tpu_sharded_weight_update": True})
    try:
        main, startup, loss = _build(pipeline=True, n_micro=2)
        scope = Scope()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup, scope=scope)
        r = np.random.RandomState(3)
        feed = {"x": r.rand(32, 32).astype("float32"),
                "label": r.randint(0, 10, (32, 1)).astype("int64")}
        for _ in range(2):
            exe.run(main, feed=feed, fetch_list=[loss], scope=scope)
        # force a second compile of the same program (fresh fetch set)
        exe.run(main, feed=feed, fetch_list=[], scope=scope)
        trail = [e for e in (getattr(main, "_sharded_update_fallback",
                                     None) or [])
                 if e.get("kind") == "pipeline_bypassed"]
        assert len(trail) == 1, trail
        assert "plan_parallel" in trail[0]["reason"]
        # the plain (non-pipeline) program records no such decline
        main2, startup2, loss2 = _build(pipeline=False)
        scope2 = Scope()
        exe.run(startup2, scope=scope2)
        exe.run(main2, feed=feed, fetch_list=[loss2], scope=scope2)
        assert not [e for e in (getattr(
            main2, "_sharded_update_fallback", None) or [])
            if e.get("kind") == "pipeline_bypassed"]
    finally:
        set_flags({"FLAGS_tpu_sharded_weight_update": old})
