"""Data-parallel (shard_map over the 8-device CPU mesh) x layers.Scan:
the scan-over-layers program must compile and match the single-device
run's losses exactly on the same global batch — pins the lax.scan
lowering inside the DP shard_map path the bench's multi-chip story
depends on."""
import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework
from paddle_tpu.core import scope as scope_mod


L, H, CLASSES = 3, 16, 4


def _build(seed):
    main = framework.default_main_program()
    st = framework.default_startup_program()
    main.random_seed = st.random_seed = seed
    x = fluid.layers.data("x", shape=[H], dtype="float32")
    y = fluid.layers.data("y", shape=[1], dtype="int64")
    w = fluid.layers.create_parameter(
        shape=[L, H, H], dtype="float32", name="dp_stack.w",
        default_initializer=fluid.initializer.TruncatedNormal(0.0, 0.2))
    h = fluid.layers.fc(x, size=H)
    scan = fluid.layers.Scan(n=L)
    with scan.block():
        wi = scan.slice_input(w)
        nh = fluid.layers.elementwise_add(
            h, fluid.layers.tanh(fluid.layers.matmul(h, wi)))
        fluid.layers.assign(nh, output=h)
    logits = fluid.layers.fc(h, size=CLASSES)
    loss = fluid.layers.mean(
        fluid.layers.loss.softmax_with_cross_entropy(logits, y))
    return loss


def test_gradient_merge_under_implicit_dp():
    """gradient_merge x with_data_parallel: the merged-grad sync happens
    at the k-step boundary inside lax.cond under shard_map (counter
    predicate is shard-uniform, so every shard takes the branch
    together); losses must match the single-device gradient-merge run."""
    from paddle_tpu.fluid.optimizer import (GradientMergeOptimizer,
                                            SGDOptimizer)

    r = np.random.RandomState(3)
    xs = r.randn(32, H).astype("float32")
    ys = r.randint(0, CLASSES, (32, 1)).astype("int64")
    K, STEPS = 2, 6

    loss = _build(seed=5)
    GradientMergeOptimizer(SGDOptimizer(0.1), k_steps=K).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(framework.default_startup_program())
    base = [float(np.asarray(exe.run(
        feed={"x": xs, "y": ys}, fetch_list=[loss])[0]).ravel()[0])
        for _ in range(STEPS)]

    framework.switch_main_program(framework.Program())
    framework.switch_startup_program(framework.Program())
    scope_mod._global_scope = scope_mod.Scope()
    with framework.unique_name_guard():
        loss2 = _build(seed=5)
        GradientMergeOptimizer(SGDOptimizer(0.1),
                               k_steps=K).minimize(loss2)
        compiled = fluid.CompiledProgram(
            framework.default_main_program()).with_data_parallel(
                loss_name=loss2.name)
        exe2 = fluid.Executor(fluid.CPUPlace())
        exe2.run(framework.default_startup_program())
        dp = [float(np.asarray(exe2.run(
            compiled, feed={"x": xs, "y": ys},
            fetch_list=[loss2])[0]).mean()) for _ in range(STEPS)]

    np.testing.assert_allclose(base, dp, rtol=2e-4, atol=1e-5)


def test_scan_under_data_parallel_matches_single():
    r = np.random.RandomState(0)
    xs = r.randn(32, H).astype("float32")
    ys = r.randint(0, CLASSES, (32, 1)).astype("int64")

    loss = _build(seed=77)
    fluid.optimizer.SGDOptimizer(0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(framework.default_startup_program())
    base = [float(np.asarray(exe.run(
        feed={"x": xs, "y": ys}, fetch_list=[loss])[0]).ravel()[0])
        for _ in range(4)]

    framework.switch_main_program(framework.Program())
    framework.switch_startup_program(framework.Program())
    scope_mod._global_scope = scope_mod.Scope()
    with framework.unique_name_guard():
        loss2 = _build(seed=77)
        fluid.optimizer.SGDOptimizer(0.1).minimize(loss2)
        compiled = fluid.CompiledProgram(
            framework.default_main_program()).with_data_parallel(
                loss_name=loss2.name)
        exe2 = fluid.Executor(fluid.CPUPlace())
        exe2.run(framework.default_startup_program())
        dp = []
        for _ in range(4):
            out = np.asarray(exe2.run(
                compiled, feed={"x": xs, "y": ys},
                fetch_list=[loss2])[0])
            dp.append(float(out.mean()))

    np.testing.assert_allclose(base, dp, rtol=2e-4, atol=1e-5)
    assert base[-1] < base[0]
