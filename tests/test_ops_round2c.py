"""Golden + behavioral tests for the round-2c ops batch: framework/IO
ops, CTR/specialty ops, candidate-sampling losses, CRF/CTC, yolov3_loss,
conditional_block lowering, and PS op registrations."""
import numpy as np
import pytest

from op_test import OpTest
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework
from paddle_tpu import ops as ops_lib


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


# -- IO ops -----------------------------------------------------------------

class TestSaveLoad(OpTest):
    def test(self, tmp_path=None):
        import tempfile
        import os
        d = tempfile.mkdtemp()
        path = os.path.join(d, "ckpt", "w0")
        r = np.random.RandomState(0)
        x = r.randn(4, 6).astype("float32")
        import jax.numpy as jnp
        ops_lib.run_op("save", {"X": [jnp.asarray(x)]},
                       {"file_path": path})
        out = ops_lib.run_op("load", {}, {"file_path": path})["Out"][0]
        np.testing.assert_array_equal(np.asarray(out), x)

        ys = [r.randn(3).astype("float32"),
              r.randint(0, 9, (2, 2)).astype("int64")]
        ops_lib.run_op("save_combine",
                       {"X": [jnp.asarray(y) for y in ys]},
                       {"file_path": path + "_c",
                        "var_names": ["a", "b"]})
        outs = ops_lib.run_op("load_combine", {},
                              {"file_path": path + "_c"})["Out"]
        for got, e in zip(outs, ys):
            np.testing.assert_array_equal(np.asarray(got), e)


class TestPrintPyFunc(OpTest):
    def test(self, capsys=None):
        import jax.numpy as jnp
        x = np.arange(6).astype("float32")
        out = ops_lib.run_op("print", {"In": [jnp.asarray(x)]},
                             {"message": "dbg"})["Out"][0]
        np.testing.assert_array_equal(np.asarray(out), x)

        from paddle_tpu.ops.framework_ops import register_py_func
        fid = register_py_func(lambda a, b: a * 2 + b)
        got = ops_lib.run_op(
            "py_func",
            {"X": [jnp.asarray(x), jnp.asarray(np.ones_like(x))]},
            {"func_id": fid})["Out"][0]
        np.testing.assert_allclose(np.asarray(got), x * 2 + 1)


# -- routing ----------------------------------------------------------------

class TestMultiplex(OpTest):
    def test(self):
        r = np.random.RandomState(1)
        xs = [r.randn(5, 3).astype("float32") for _ in range(3)]
        ids = r.randint(0, 3, (5, 1)).astype("int32")
        self.op_type = "multiplex"
        self.inputs = {"X": xs, "Ids": ids}
        e = np.stack([xs[ids[i, 0]][i] for i in range(5)])
        self.outputs = {"Out": e}
        self.check_output()


class TestSplitMergeLod(OpTest):
    def test(self):
        import jax.numpy as jnp
        r = np.random.RandomState(2)
        x = r.randn(6, 4).astype("float32")
        mask = np.array([1, 0, 1, 1, 0, 1], "int32")
        outs = ops_lib.run_op("split_lod_tensor",
                              {"X": [jnp.asarray(x)],
                               "Mask": [jnp.asarray(mask)]}, {})
        t, f = np.asarray(outs["OutTrue"][0]), np.asarray(outs["OutFalse"][0])
        np.testing.assert_array_equal(t, x[mask.astype(bool)])
        merged = ops_lib.run_op(
            "merge_lod_tensor",
            {"InTrue": [jnp.asarray(t)], "InFalse": [jnp.asarray(f)],
             "Mask": [jnp.asarray(mask)]}, {})["Out"][0]
        np.testing.assert_array_equal(np.asarray(merged), x)


class TestCoalesceShuffle(OpTest):
    def test(self):
        import jax.numpy as jnp
        r = np.random.RandomState(3)
        xs = [r.randn(2, 3).astype("float32"),
              r.randn(4).astype("float32")]
        outs = ops_lib.run_op("coalesce_tensor",
                              {"Input": [jnp.asarray(v) for v in xs]},
                              {})
        fused = np.asarray(outs["FusedOutput"][0])
        np.testing.assert_allclose(
            fused, np.concatenate([v.ravel() for v in xs]))

        x = np.arange(20).reshape(10, 2).astype("float32")
        out = np.asarray(ops_lib.run_op(
            "shuffle_batch", {"X": [jnp.asarray(x)]}, {})["Out"][0])
        assert sorted(out[:, 0].tolist()) == x[:, 0].tolist()


# -- specialty --------------------------------------------------------------

class TestCvm(OpTest):
    def test(self):
        r = np.random.RandomState(4)
        x = np.abs(r.randn(5, 6)).astype("float32")
        self.op_type = "cvm"
        self.inputs = {"X": x}
        self.attrs = {"use_cvm": True}
        show = np.log(x[:, :1] + 1)
        click = np.log(x[:, 1:2] + 1) - show
        self.outputs = {"Y": np.concatenate([show, click, x[:, 2:]], 1)}
        self.check_output()
        self.attrs = {"use_cvm": False}
        self.outputs = {"Y": x[:, 2:]}
        self.check_output()


class TestBatchFc(OpTest):
    def test(self):
        r = np.random.RandomState(5)
        x = r.randn(3, 4, 5).astype("float32")
        w = r.randn(3, 5, 2).astype("float32")
        b = r.randn(3, 2).astype("float32")
        self.op_type = "batch_fc"
        self.inputs = {"Input": x, "W": w, "Bias": b}
        self.outputs = {"Out": np.einsum("sni,sio->sno", x, w)
                        + b[:, None, :]}
        self.check_output(atol=1e-4)
        self.check_grad(["Input", "W", "Bias"], "Out")


class TestHash(OpTest):
    def test(self):
        x = np.array([[1, 2], [1, 2], [3, 4]], "int64")
        self.op_type = "hash"
        self.inputs = {"X": x}
        self.attrs = {"num_hash": 2, "mod_by": 1000}
        out = np.asarray(self._run_forward()["Out"][0])
        assert out.shape == (3, 2, 1)
        # deterministic: identical rows hash identically
        np.testing.assert_array_equal(out[0], out[1])
        assert not np.array_equal(out[0], out[2])
        assert out.min() >= 0 and out.max() < 1000


class TestNce(OpTest):
    def test(self):
        r = np.random.RandomState(6)
        n, d, c = 4, 8, 20
        x = r.randn(n, d).astype("float32")
        w = r.randn(c, d).astype("float32")
        label = r.randint(0, c, (n, 1)).astype("int64")
        import jax
        self.op_type = "nce"
        self.inputs = {"Input": x, "Weight": w, "Label": label}
        # pin the sampling key so analytic and numeric grads see the
        # same negatives
        self.attrs = {"num_neg_samples": 5, "sampler": 1,
                      "_rng_key": jax.random.PRNGKey(0)}
        outs = self._run_forward()
        cost = np.asarray(outs["Cost"][0])
        assert cost.shape == (n, 1)
        assert np.all(cost > 0)
        self.check_grad(["Input", "Weight"], "Cost",
                        max_relative_error=0.05)


class TestSampleLogits(OpTest):
    def test(self):
        r = np.random.RandomState(7)
        n, c = 4, 30
        logits = r.randn(n, c).astype("float32")
        labels = r.randint(0, c, (n, 1)).astype("int64")
        self.op_type = "sample_logits"
        self.inputs = {"Logits": logits, "Labels": labels}
        self.attrs = {"num_samples": 8}
        outs = self._run_forward()
        sl = np.asarray(outs["SampledLogits"][0])
        samples = np.asarray(outs["Samples"][0])
        assert sl.shape == (n, 9)
        # col 0 is the true class
        np.testing.assert_array_equal(samples[:, 0], labels[:, 0])


def _np_ctc_loss(logp, labels, blank):
    """Brute-force CTC via dynamic programming in prob space."""
    t, c = logp.shape
    ext = [blank]
    for l in labels:
        ext += [l, blank]
    s = len(ext)
    alpha = np.zeros((t, s))
    alpha[0, 0] = np.exp(logp[0, ext[0]])
    if s > 1:
        alpha[0, 1] = np.exp(logp[0, ext[1]])
    for ti in range(1, t):
        for si in range(s):
            a = alpha[ti - 1, si]
            if si >= 1:
                a += alpha[ti - 1, si - 1]
            if si >= 2 and ext[si] != blank and ext[si] != ext[si - 2]:
                a += alpha[ti - 1, si - 2]
            alpha[ti, si] = a * np.exp(logp[ti, ext[si]])
    return -np.log(alpha[t - 1, s - 1] + alpha[t - 1, s - 2])


@pytest.mark.slow
class TestWarpCtc(OpTest):
    def test(self):
        r = np.random.RandomState(8)
        b, t, c, l = 2, 6, 5, 2
        logits = r.randn(b, t, c).astype("float32")
        label = r.randint(1, c, (b, l)).astype("int32")
        self.op_type = "warpctc"
        self.inputs = {"Logits": logits, "Label": label}
        self.attrs = {"blank": 0}
        logp = logits - np.log(
            np.exp(logits).sum(-1, keepdims=True))
        e = np.stack([
            [_np_ctc_loss(logp[i], label[i].tolist(), 0)]
            for i in range(b)])
        self.outputs = {"Loss": e.astype("float32")}
        self.check_output(atol=1e-4)
        self.check_grad(["Logits"], "Loss", max_relative_error=0.01)


def _np_crf_nll(em, trans_full, labels):
    k = em.shape[1]
    start, stop, trans = trans_full[0], trans_full[1], trans_full[2:]
    # logZ
    alpha = start + em[0]
    for t in range(1, em.shape[0]):
        alpha = np.log(np.exp(
            alpha[:, None] + trans).sum(0)) + em[t]
    logz = np.log(np.exp(alpha + stop).sum())
    score = start[labels[0]] + em[0, labels[0]]
    for t in range(1, em.shape[0]):
        score += trans[labels[t - 1], labels[t]] + em[t, labels[t]]
    score += stop[labels[-1]]
    return logz - score


@pytest.mark.slow
class TestLinearChainCrf(OpTest):
    def test(self):
        r = np.random.RandomState(9)
        b, t, k = 2, 5, 4
        em = r.randn(b, t, k).astype("float32")
        trans = (r.randn(k + 2, k) * 0.3).astype("float32")
        label = r.randint(0, k, (b, t)).astype("int64")
        self.op_type = "linear_chain_crf"
        self.inputs = {"Emission": em, "Transition": trans,
                       "Label": label}
        e = np.stack([[_np_crf_nll(em[i].astype("float64"),
                                   trans.astype("float64"), label[i])]
                      for i in range(b)])
        self.outputs = {"LogLikelihood": e.astype("float32")}
        self.check_output(
            atol=1e-4,
            no_check_set=("Alpha", "EmissionExps", "TransitionExps"))
        self.check_grad(["Emission", "Transition"], "LogLikelihood",
                        max_relative_error=0.01)


class TestCrfDecoding(OpTest):
    def test(self):
        r = np.random.RandomState(10)
        b, t, k = 2, 5, 3
        em = r.randn(b, t, k).astype("float32")
        trans = (r.randn(k + 2, k) * 0.3).astype("float32")
        self.op_type = "crf_decoding"
        self.inputs = {"Emission": em, "Transition": trans}
        path = np.asarray(self._run_forward()["ViterbiPath"][0])
        # brute force viterbi
        start, stop, tr = trans[0], trans[1], trans[2:]
        import itertools
        for i in range(b):
            best, best_s = None, -1e30
            for cand in itertools.product(range(k), repeat=t):
                s = start[cand[0]] + em[i, 0, cand[0]]
                for j in range(1, t):
                    s += tr[cand[j - 1], cand[j]] + em[i, j, cand[j]]
                s += stop[cand[-1]]
                if s > best_s:
                    best, best_s = cand, s
            np.testing.assert_array_equal(path[i], np.array(best))


@pytest.mark.slow
class TestYolov3Loss(OpTest):
    def test(self):
        r = np.random.RandomState(11)
        n, h, w = 1, 4, 4
        anchors = [10, 13, 16, 30, 33, 23]
        mask = [0, 1, 2]
        cnum = 3
        x = (r.randn(n, 3 * (5 + cnum), h, w) * 0.2).astype("float32")
        gtbox = np.array([[[0.4, 0.4, 0.3, 0.3],
                           [0, 0, 0, 0]]], "float32")
        gtlabel = np.array([[1, 0]], "int32")
        self.op_type = "yolov3_loss"
        self.inputs = {"X": x, "GTBox": gtbox, "GTLabel": gtlabel}
        self.attrs = {"anchors": anchors, "anchor_mask": mask,
                      "class_num": cnum, "ignore_thresh": 0.7,
                      "downsample_ratio": 32,
                      "use_label_smooth": False}
        outs = self._run_forward()
        loss = np.asarray(outs["Loss"][0])
        gmm = np.asarray(outs["GTMatchMask"][0])
        assert loss.shape == (n,)
        assert np.isfinite(loss).all() and loss[0] > 0
        assert gmm[0, 1] == -1  # invalid gt
        assert 0 <= gmm[0, 0] < 3
        self.check_grad(["X"], "Loss", max_relative_error=0.05)


class TestFusionSquaredMatSub(OpTest):
    def test(self):
        r = np.random.RandomState(12)
        x = r.randn(3, 4).astype("float32")
        y = r.randn(4, 5).astype("float32")
        self.op_type = "fusion_squared_mat_sub"
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"scalar": 0.5}
        e = (np.square(x @ y) - np.square(x) @ np.square(y)) * 0.5
        self.outputs = {"Out": e}
        self.check_output(
            atol=1e-4, no_check_set=("SquaredX", "SquaredY", "SquaredXY"))


class TestFusionRepeatedFcRelu(OpTest):
    def test(self):
        r = np.random.RandomState(13)
        x = r.randn(4, 6).astype("float32")
        ws = [r.randn(6, 5).astype("float32"),
              r.randn(5, 3).astype("float32")]
        bs = [r.randn(5).astype("float32"), r.randn(3).astype("float32")]
        self.op_type = "fusion_repeated_fc_relu"
        self.inputs = {"X": x, "W": ws, "Bias": bs}
        e = x
        for wi, bi in zip(ws, bs):
            e = np.maximum(e @ wi + bi, 0)
        self.outputs = {"Out": e}
        self.check_output(atol=1e-4)


class TestRankAttention(OpTest):
    def test(self):
        r = np.random.RandomState(14)
        n, d, p, mr = 3, 4, 2, 3
        x = r.randn(n, d).astype("float32")
        param = r.randn(mr * mr * d, p).astype("float32")
        # instance 0: rank 1 with one pair (rank 2); instance 1: rank 2
        # with two pairs; instance 2: invalid
        ro = np.array([[1, 2, 0, 0, 0, 0, 0],
                       [2, 1, 1, 3, 2, 0, 0],
                       [0, 0, 0, 0, 0, 0, 0]], "int32")
        self.op_type = "rank_attention"
        self.inputs = {"X": x, "RankOffset": ro, "RankParam": param}
        self.attrs = {"MaxRank": mr}
        out = np.asarray(self._run_forward()["Out"][0])
        assert out.shape == (n, p)
        blocks = param.reshape(mr * mr, d, p)
        e0 = x[0] @ blocks[(1 - 1) * mr + (2 - 1)]
        np.testing.assert_allclose(out[0], e0, rtol=1e-4)
        np.testing.assert_allclose(out[2], 0.0, atol=1e-6)


class TestInplaceAbn(OpTest):
    def test(self):
        r = np.random.RandomState(15)
        x = r.randn(2, 3, 4, 4).astype("float32")
        scale = np.ones(3, "float32")
        bias = np.zeros(3, "float32")
        mean = np.zeros(3, "float32")
        var = np.ones(3, "float32")
        self.op_type = "inplace_abn"
        self.inputs = {"X": x, "Scale": scale, "Bias": bias,
                       "Mean": mean, "Variance": var}
        self.attrs = {"activation": "leaky_relu", "alpha": 0.1,
                      "is_test": True}
        bn = x  # mean 0 var 1 identity
        e = np.where(bn >= 0, bn, 0.1 * bn)
        outs = self._run_forward()
        np.testing.assert_allclose(np.asarray(outs["Y"][0]), e, atol=1e-4)


# -- conditional_block lowering --------------------------------------------

class TestConditionalBlockLowering:
    def test(self):
        from paddle_tpu.fluid.layers import tensor as T

        main, startup = framework.Program(), framework.Program()
        with framework.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[4], dtype="float32")
            acc = T.fill_constant([4], "float32", 0.0)
            flag = fluid.layers.data("flag", shape=[1], dtype="bool")
            prog = framework.default_main_program()
            parent = prog.current_block()
            sub = prog._create_block()
            doubled = fluid.layers.elementwise_add(x, x)
            T.assign(doubled, output=acc)
            prog._rollback()
            parent.append_op(
                type="conditional_block",
                inputs={"Cond": [flag]}, outputs={},
                attrs={"sub_block": sub.idx})
            exe = fluid.Executor()
            exe.run(startup)
            xv = np.arange(4).astype("float32")
            on = exe.run(main, feed={
                "x": xv, "flag": np.array([True])},
                fetch_list=[acc])
            off = exe.run(main, feed={
                "x": xv, "flag": np.array([False])},
                fetch_list=[acc])
        np.testing.assert_allclose(np.asarray(on[0]), 2 * xv)
        np.testing.assert_allclose(np.asarray(off[0]), 0.0)


# -- PS op registration smoke ----------------------------------------------

class TestPsOpsRegistered:
    def test(self):
        from paddle_tpu.ops.registry import has_op
        for op in ("listen_and_serv", "distributed_lookup_table",
                   "recv_save", "pull_sparse", "push_sparse",
                   "pull_box_sparse", "split_byref", "c_gen_nccl_id",
                   "c_comm_init", "c_comm_init_all", "run_program"):
            assert has_op(op), op

    def test_lookup_local_fallback(self):
        import jax.numpy as jnp
        r = np.random.RandomState(16)
        w = r.randn(10, 3).astype("float32")
        ids = np.array([[1], [7], [1]], "int64")
        out = ops_lib.run_op(
            "distributed_lookup_table",
            {"Ids": [jnp.asarray(ids)], "W": [jnp.asarray(w)]},
            {"table_name": ""})["Outputs"][0]
        np.testing.assert_allclose(np.asarray(out), w[[1, 7, 1]])

    def test_split_byref(self):
        import jax.numpy as jnp
        x = np.arange(12).reshape(6, 2).astype("float32")
        outs = ops_lib.run_op("split_byref", {"X": [jnp.asarray(x)]},
                              {"height_sections": [2, 4]})["Out"]
        np.testing.assert_array_equal(np.asarray(outs[0]), x[:2])
        np.testing.assert_array_equal(np.asarray(outs[1]), x[2:])

    def test_comm_bootstrap_noop(self):
        ops_lib.run_op("c_gen_nccl_id", {}, {"ring_id": 3})
        ops_lib.run_op("c_comm_init", {}, {"ring_id": 3})


class TestCudnnLstmSequenceLength(OpTest):
    def test(self):
        """A padded row must produce the same outputs as the same row in
        an unpadded shorter batch."""
        r = np.random.RandomState(17)
        t, b, d, h = 6, 2, 3, 4
        x = r.randn(t, b, d).astype("float32")
        lens = np.array([6, 4], "int32")
        x[4:, 1] = 0.0
        sz = 2 * (4 * h * d + 4 * h * h + 8 * h)
        w = (r.randn(sz) * 0.2).astype("float32")
        self.op_type = "cudnn_lstm"
        self.inputs = {"Input": x, "W": w,
                       "SequenceLength": lens}
        self.attrs = {"hidden_size": h, "num_layers": 1,
                      "is_bidirec": True}
        out = np.asarray(self._run_forward()["Out"][0])
        # row 1 alone, truncated to its true length
        self.inputs = {"Input": x[:4, 1:2], "W": w}
        out1 = np.asarray(self._run_forward()["Out"][0])
        np.testing.assert_allclose(out[:4, 1], out1[:, 0], atol=1e-5)
