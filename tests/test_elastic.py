"""DistributedStrategy.elastic — preemption checkpoint + auto-resume.

Reference: `framework/distributed_strategy.proto:301` reserves `elastic`
(unimplemented there). Here it wires `fluid/checkpoint.py` into every
step of the marked program: async numbered checkpoints every
`save_steps`, and transparent restore from the latest checkpoint before
the first step after a restart."""
import pytest

pytestmark = pytest.mark.dist

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu import fleet
from paddle_tpu.core.scope import Scope
from paddle_tpu.fluid import checkpoint as ckpt


def _build_and_minimize(seed, elastic, root):
    """One simulated process: fresh name counters (a restarted process
    rebuilds fc_0/fc_1..., matching the checkpointed names), build,
    optionally wrap with the elastic strategy, minimize."""
    from paddle_tpu.fluid import framework

    main, startup = fluid.Program(), fluid.Program()
    with framework.unique_name_guard(), \
            fluid.program_guard(main, startup):
        main.random_seed = startup.random_seed = seed
        x = fluid.data(name="x", shape=[-1, 16], dtype="float32")
        y = fluid.data(name="y", shape=[-1, 1], dtype="float32")
        h = fluid.layers.fc(input=x, size=24, act="tanh")
        pred = fluid.layers.fc(input=h, size=1, act=None)
        loss = fluid.layers.reduce_mean(fluid.layers.square(pred - y))
        opt = fluid.optimizer.SGD(learning_rate=0.1)
        st = fleet.DistributedStrategy()
        if elastic:
            st.elastic = True
            st.elastic_configs = {"checkpoint_dir": root,
                                  "save_steps": 2,
                                  "max_checkpoints": 2}
        fleet.init()
        opt = fleet.distributed_optimizer(opt, st)
        opt.minimize(loss)
    return main, startup, loss.name


def _data(steps, batch=8):
    rng = np.random.RandomState(3)
    xs = rng.randn(steps, batch, 16).astype(np.float32)
    w = rng.randn(16, 1).astype(np.float32)
    return xs, np.tanh(xs @ w)


def test_elastic_checkpoints_and_resumes(tmp_path):
    root = str(tmp_path / "elastic")
    xs, ys = _data(8)

    def make(elastic):
        return _build_and_minimize(seed=5, elastic=elastic, root=root)

    def run(main, startup, loss_name, scope, lo, hi):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup, scope=scope)
        out = []
        for i in range(lo, hi):
            v, = exe.run(main, feed={"x": xs[i], "y": ys[i]},
                         fetch_list=[loss_name], scope=scope)
            out.append(float(np.asarray(v).reshape(-1)[0]))
        return out

    # uninterrupted reference trajectory (no elastic)
    m0, s0, ln0 = make(elastic=False)
    ref = run(m0, s0, ln0, Scope(), 0, 8)

    # run 1: elastic on, 4 steps -> checkpoints at steps 1 and 3
    m1, s1, ln1 = make(elastic=True)
    got1 = run(m1, s1, ln1, Scope(), 0, 4)
    cp = m1._elastic_cfg.get("_ckpt")
    assert cp is not None, "save_steps=2 over 4 steps must checkpoint"
    cp.close()  # flush the async writer before the simulated preemption
    status = ckpt.read_status(ckpt.latest_checkpoint_dir(root))
    assert status.step_no == 3

    # run 2: fresh program + scope (params re-initialized by startup),
    # elastic auto-resumes from step 3's checkpoint before step 4
    m2, s2, ln2 = make(elastic=True)
    got2 = run(m2, s2, ln2, Scope(), 4, 8)
    assert m2._elastic_cfg["_step"] >= 8 - 4

    np.testing.assert_allclose(got1, ref[:4], rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(got2, ref[4:], rtol=1e-4, atol=1e-5)


def test_elastic_off_leaves_program_unmarked(tmp_path):
    main, _, _ = _build_and_minimize(seed=9, elastic=False,
                                     root=str(tmp_path))
    assert getattr(main, "_elastic_cfg", None) is None
