"""DistributedStrategy.elastic — preemption checkpoint + auto-resume.

Reference: `framework/distributed_strategy.proto:301` reserves `elastic`
(unimplemented there). Here it wires `fluid/checkpoint.py` into every
step of the marked program: async numbered checkpoints every
`save_steps`, and transparent restore from the latest checkpoint before
the first step after a restart."""
import pytest

pytestmark = pytest.mark.dist

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu import fleet
from paddle_tpu.core.scope import Scope
from paddle_tpu.fluid import checkpoint as ckpt


def _build_and_minimize(seed, elastic, root):
    """One simulated process: fresh name counters (a restarted process
    rebuilds fc_0/fc_1..., matching the checkpointed names), build,
    optionally wrap with the elastic strategy, minimize."""
    from paddle_tpu.fluid import framework

    main, startup = fluid.Program(), fluid.Program()
    with framework.unique_name_guard(), \
            fluid.program_guard(main, startup):
        main.random_seed = startup.random_seed = seed
        x = fluid.data(name="x", shape=[-1, 16], dtype="float32")
        y = fluid.data(name="y", shape=[-1, 1], dtype="float32")
        h = fluid.layers.fc(input=x, size=24, act="tanh")
        pred = fluid.layers.fc(input=h, size=1, act=None)
        loss = fluid.layers.reduce_mean(fluid.layers.square(pred - y))
        opt = fluid.optimizer.SGD(learning_rate=0.1)
        st = fleet.DistributedStrategy()
        if elastic:
            st.elastic = True
            st.elastic_configs = {"checkpoint_dir": root,
                                  "save_steps": 2,
                                  "max_checkpoints": 2}
        fleet.init()
        opt = fleet.distributed_optimizer(opt, st)
        opt.minimize(loss)
    return main, startup, loss.name


def _data(steps, batch=8):
    rng = np.random.RandomState(3)
    xs = rng.randn(steps, batch, 16).astype(np.float32)
    w = rng.randn(16, 1).astype(np.float32)
    return xs, np.tanh(xs @ w)


def test_elastic_checkpoints_and_resumes(tmp_path):
    root = str(tmp_path / "elastic")
    xs, ys = _data(8)

    def make(elastic):
        return _build_and_minimize(seed=5, elastic=elastic, root=root)

    def run(main, startup, loss_name, scope, lo, hi):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup, scope=scope)
        out = []
        for i in range(lo, hi):
            v, = exe.run(main, feed={"x": xs[i], "y": ys[i]},
                         fetch_list=[loss_name], scope=scope)
            out.append(float(np.asarray(v).reshape(-1)[0]))
        return out

    # uninterrupted reference trajectory (no elastic)
    m0, s0, ln0 = make(elastic=False)
    ref = run(m0, s0, ln0, Scope(), 0, 8)

    # run 1: elastic on, 4 steps -> checkpoints at steps 1 and 3
    m1, s1, ln1 = make(elastic=True)
    got1 = run(m1, s1, ln1, Scope(), 0, 4)
    cp = m1._elastic_cfg.get("_ckpt")
    assert cp is not None, "save_steps=2 over 4 steps must checkpoint"
    cp.close()  # flush the async writer before the simulated preemption
    status = ckpt.read_status(ckpt.latest_checkpoint_dir(root))
    assert status.step_no == 3

    # run 2: fresh program + scope (params re-initialized by startup),
    # elastic auto-resumes from step 3's checkpoint before step 4
    m2, s2, ln2 = make(elastic=True)
    got2 = run(m2, s2, ln2, Scope(), 4, 8)
    assert m2._elastic_cfg["_step"] >= 8 - 4

    np.testing.assert_allclose(got1, ref[:4], rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(got2, ref[4:], rtol=1e-4, atol=1e-5)


def test_elastic_off_leaves_program_unmarked(tmp_path):
    main, _, _ = _build_and_minimize(seed=9, elastic=False,
                                     root=str(tmp_path))
    assert getattr(main, "_elastic_cfg", None) is None


# -- supervised launch: fail-fast + restart-with-resume ---------------------

import os as _os
import subprocess as _sp
import sys as _sys

_DIR = _os.path.dirname(_os.path.abspath(__file__))
_REPO = _os.path.dirname(_DIR)


def _launch_env():
    env = {k: v for k, v in _os.environ.items()
           if not k.startswith(("PALLAS_AXON", "AXON_", "TPU_"))}
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env.pop("PADDLE_FAULTS", None)
    return env


def _loss_lines(text):
    return [ln for ln in text.splitlines() if ln.startswith("LOSS")]


def test_launch_fail_fast_exits_with_first_nonzero_rc(tmp_path):
    """First worker failure terminates the rest of the cohort and the
    launcher exits with THAT code — not the last seen, and not after the
    healthy worker's full (long) runtime."""
    script = tmp_path / "worker.py"
    script.write_text(
        "import os, sys, time\n"
        "tid = int(os.environ['PADDLE_TRAINER_ID'])\n"
        "if tid == 1:\n"
        "    sys.exit(7)\n"
        "time.sleep(120)\n")
    import time

    t0 = time.monotonic()
    proc = _sp.run(
        [_sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--hosts", "127.0.0.1:6701,127.0.0.1:6702",
         "--log_dir", str(tmp_path / "logs"), str(script)],
        env=_launch_env(), cwd=_REPO, stdout=_sp.PIPE,
        stderr=_sp.STDOUT, text=True, timeout=90)
    dt = time.monotonic() - t0
    assert proc.returncode == 7, proc.stdout
    assert dt < 60, "fail-fast took %.0fs (healthy worker sleeps 120s)" \
        % dt
    assert "worker 1 exited with 7" in proc.stdout


def test_supervised_restart_resumes_from_elastic_checkpoint(tmp_path):
    """--max_restarts composes with the elastic checkpoint-resume path:
    attempt 0 is killed hard after step 4 (last published checkpoint:
    step 3), the restarted attempt resumes at step 4 and the combined
    trajectory matches an uninterrupted run."""
    runner = _os.path.join(_DIR, "elastic_launch_runner.py")
    ref_root = str(tmp_path / "ref_ckpt")
    ref = _sp.run([_sys.executable, runner, ref_root],
                  env=_launch_env(), cwd=_REPO, stdout=_sp.PIPE,
                  stderr=_sp.STDOUT, text=True, timeout=240)
    assert ref.returncode == 0, ref.stdout
    ref_losses = _loss_lines(ref.stdout)
    assert len(ref_losses) == 8

    root = str(tmp_path / "crash_ckpt")
    log_dir = str(tmp_path / "logs")
    proc = _sp.run(
        [_sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--hosts", "127.0.0.1:6703", "--log_dir", log_dir,
         "--max_restarts", "1", runner, root, "crash"],
        env=_launch_env(), cwd=_REPO, stdout=_sp.PIPE,
        stderr=_sp.STDOUT, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout
    assert "restart 1/1" in proc.stdout, proc.stdout

    log = open(_os.path.join(log_dir, "workerlog.0")).read()
    got = _loss_lines(log)
    # attempt 0 printed steps 0..4 then died; attempt 1 resumed from the
    # step-3 checkpoint and reran 4..7 (log is append mode)
    assert [ln.split()[1] for ln in got] == \
        ["0", "1", "2", "3", "4", "4", "5", "6", "7"], log
    # last occurrence per step: attempt 1's rerun of step 4 onwards
    resumed = {ln.split()[1]: float(ln.split()[2]) for ln in got}
    expected = {ln.split()[1]: float(ln.split()[2])
                for ln in ref_losses}
    for step in ("4", "5", "6", "7"):
        np.testing.assert_allclose(resumed[step], expected[step],
                                   rtol=1e-4, atol=1e-5)


# -- supervisor-collected flight-recorder postmortem ------------------------

def test_supervisor_collects_flight_dump_of_fault_killed_rank(tmp_path):
    """Acceptance (observability): a PADDLE_FAULTS kill on ONE rank of
    a supervised 2-worker cohort leaves a flight-recorder dump that the
    launch supervisor collects into <log_dir>/postmortem/attempt0/
    BEFORE the --max_restarts cohort restart; the dump parses, names
    the fatal fault event, and carries the rank's last step records
    intact. The restarted cohort completes clean (rc=0)."""
    import json as _json

    script = tmp_path / "worker.py"
    script.write_text(
        "import os, sys\n"
        "sys.path.insert(0, %r)\n"
        "tid = int(os.environ['PADDLE_TRAINER_ID'])\n"
        "attempt = int(os.environ.get('PADDLE_RESTART_NUM', '0'))\n"
        "if tid == 1 and attempt == 0:\n"
        "    # the designated victim: die at its 3rd collective send\n"
        "    os.environ['PADDLE_FAULTS'] = \\\n"
        "        'kill:side=client,point=send,method=hc_put_part,at=3'\n"
        "import numpy as np\n"
        "import paddle_tpu.fluid as fluid\n"
        "from paddle_tpu.fluid import framework\n"
        "from paddle_tpu.distributed.host_collectives import \\\n"
        "    group_from_env\n"
        "os.environ.setdefault('PADDLE_HC_LIVENESS_S', '4')\n"
        "os.environ.setdefault('PADDLE_HC_HEARTBEAT_S', '0.5')\n"
        "g = group_from_env()\n"
        "main, startup = fluid.Program(), fluid.Program()\n"
        "with framework.program_guard(main, startup):\n"
        "    x = fluid.data(name='x', shape=[-1, 8], dtype='float32')\n"
        "    loss = fluid.layers.reduce_mean(\n"
        "        fluid.layers.fc(input=x, size=4))\n"
        "    fluid.optimizer.SGD(0.1).minimize(loss)\n"
        "exe = fluid.Executor(fluid.CPUPlace())\n"
        "exe.run(startup)\n"
        "feed = {'x': np.ones((2, 8), 'float32')}\n"
        "for i in range(6):\n"
        "    exe.run(main, feed=feed, fetch_list=[loss])\n"
        "    g.barrier()\n"
        "g.shutdown()\n"
        "sys.stdout.flush()\n"
        "os._exit(0)\n" % _REPO)
    log_dir = str(tmp_path / "logs")
    proc = _sp.run(
        [_sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--hosts", "127.0.0.1:6711,127.0.0.1:6712",
         "--log_dir", log_dir, "--max_restarts", "1", str(script)],
        env=_launch_env(), cwd=_REPO, stdout=_sp.PIPE,
        stderr=_sp.STDOUT, text=True, timeout=240)
    assert proc.returncode == 0, proc.stdout
    assert "restart 1/1" in proc.stdout, proc.stdout
    assert "collected" in proc.stdout and "flight-recorder" \
        in proc.stdout, proc.stdout

    # the victim's dump was secured under postmortem/attempt0 before
    # the restart (the restarted cohort overwrites the telemetry dir)
    dump_path = _os.path.join(log_dir, "postmortem", "attempt0",
                              "flightrec.rank1.json")
    assert _os.path.exists(dump_path), proc.stdout
    doc = _json.load(open(dump_path))
    assert doc["reason"] == "fault-kill"
    assert doc["fatal_event"]["event"] == "fault"
    assert doc["fatal_event"]["fault"] == "kill"
    assert doc["rank"] == 1
    # rank 1 died at its 3rd barrier: startup + 3 train steps recorded,
    # in order, with the step-phase split intact
    steps = [s["step"] for s in doc["steps"]]
    assert doc["n_steps"] >= 3 and steps == sorted(steps)
    assert all("total_ms" in s for s in doc["steps"])
    # the collective events before death rode along in the ring
    assert any(e.get("event") == "collective" for e in doc["events"])
    # the JSONL streams moved with the dumps, so attempt 1 started a
    # FRESH stream (no silent cross-attempt append with a reset step
    # counter) and attempt 0's records stay analyzable per-attempt
    att0 = _os.path.join(log_dir, "postmortem", "attempt0")
    assert _os.path.exists(_os.path.join(
        att0, "telemetry.rank1.jsonl")), _os.listdir(att0)
    tdir = _os.path.join(log_dir, "telemetry")
    assert _os.path.isdir(tdir)
    fresh = [f for f in _os.listdir(tdir) if f.endswith(".jsonl")]
    assert fresh, "restarted cohort must write its own stream"
    for f in fresh:
        recs = [_json.loads(ln) for ln in
                open(_os.path.join(tdir, f)) if ln.strip()]
        steps = [r["step"] for r in recs if r["kind"] == "step"]
        # a fresh stream restarts at step 1 — proof attempt 1 did not
        # append into attempt 0's file
        assert steps and steps[0] == 1, (f, steps[:3])
