"""DistributedStrategy.elastic — preemption checkpoint + auto-resume.

Reference: `framework/distributed_strategy.proto:301` reserves `elastic`
(unimplemented there). Here it wires `fluid/checkpoint.py` into every
step of the marked program: async numbered checkpoints every
`save_steps`, and transparent restore from the latest checkpoint before
the first step after a restart."""
import pytest

pytestmark = pytest.mark.dist

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu import fleet
from paddle_tpu.core.scope import Scope
from paddle_tpu.fluid import checkpoint as ckpt


def _build_and_minimize(seed, elastic, root):
    """One simulated process: fresh name counters (a restarted process
    rebuilds fc_0/fc_1..., matching the checkpointed names), build,
    optionally wrap with the elastic strategy, minimize."""
    from paddle_tpu.fluid import framework

    main, startup = fluid.Program(), fluid.Program()
    with framework.unique_name_guard(), \
            fluid.program_guard(main, startup):
        main.random_seed = startup.random_seed = seed
        x = fluid.data(name="x", shape=[-1, 16], dtype="float32")
        y = fluid.data(name="y", shape=[-1, 1], dtype="float32")
        h = fluid.layers.fc(input=x, size=24, act="tanh")
        pred = fluid.layers.fc(input=h, size=1, act=None)
        loss = fluid.layers.reduce_mean(fluid.layers.square(pred - y))
        opt = fluid.optimizer.SGD(learning_rate=0.1)
        st = fleet.DistributedStrategy()
        if elastic:
            st.elastic = True
            st.elastic_configs = {"checkpoint_dir": root,
                                  "save_steps": 2,
                                  "max_checkpoints": 2}
        fleet.init()
        opt = fleet.distributed_optimizer(opt, st)
        opt.minimize(loss)
    return main, startup, loss.name


def _data(steps, batch=8):
    rng = np.random.RandomState(3)
    xs = rng.randn(steps, batch, 16).astype(np.float32)
    w = rng.randn(16, 1).astype(np.float32)
    return xs, np.tanh(xs @ w)


def test_elastic_checkpoints_and_resumes(tmp_path):
    root = str(tmp_path / "elastic")
    xs, ys = _data(8)

    def make(elastic):
        return _build_and_minimize(seed=5, elastic=elastic, root=root)

    def run(main, startup, loss_name, scope, lo, hi):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup, scope=scope)
        out = []
        for i in range(lo, hi):
            v, = exe.run(main, feed={"x": xs[i], "y": ys[i]},
                         fetch_list=[loss_name], scope=scope)
            out.append(float(np.asarray(v).reshape(-1)[0]))
        return out

    # uninterrupted reference trajectory (no elastic)
    m0, s0, ln0 = make(elastic=False)
    ref = run(m0, s0, ln0, Scope(), 0, 8)

    # run 1: elastic on, 4 steps -> checkpoints at steps 1 and 3
    m1, s1, ln1 = make(elastic=True)
    got1 = run(m1, s1, ln1, Scope(), 0, 4)
    cp = m1._elastic_cfg.get("_ckpt")
    assert cp is not None, "save_steps=2 over 4 steps must checkpoint"
    cp.close()  # flush the async writer before the simulated preemption
    status = ckpt.read_status(ckpt.latest_checkpoint_dir(root))
    assert status.step_no == 3

    # run 2: fresh program + scope (params re-initialized by startup),
    # elastic auto-resumes from step 3's checkpoint before step 4
    m2, s2, ln2 = make(elastic=True)
    got2 = run(m2, s2, ln2, Scope(), 4, 8)
    assert m2._elastic_cfg["_step"] >= 8 - 4

    np.testing.assert_allclose(got1, ref[:4], rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(got2, ref[4:], rtol=1e-4, atol=1e-5)


def test_elastic_off_leaves_program_unmarked(tmp_path):
    main, _, _ = _build_and_minimize(seed=9, elastic=False,
                                     root=str(tmp_path))
    assert getattr(main, "_elastic_cfg", None) is None


# -- supervised launch: fail-fast + restart-with-resume ---------------------

import os as _os
import subprocess as _sp
import sys as _sys

_DIR = _os.path.dirname(_os.path.abspath(__file__))
_REPO = _os.path.dirname(_DIR)


def _launch_env():
    env = {k: v for k, v in _os.environ.items()
           if not k.startswith(("PALLAS_AXON", "AXON_", "TPU_"))}
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env.pop("PADDLE_FAULTS", None)
    return env


def _loss_lines(text):
    return [ln for ln in text.splitlines() if ln.startswith("LOSS")]


def test_launch_fail_fast_exits_with_first_nonzero_rc(tmp_path):
    """First worker failure terminates the rest of the cohort and the
    launcher exits with THAT code — not the last seen, and not after the
    healthy worker's full (long) runtime."""
    script = tmp_path / "worker.py"
    script.write_text(
        "import os, sys, time\n"
        "tid = int(os.environ['PADDLE_TRAINER_ID'])\n"
        "if tid == 1:\n"
        "    sys.exit(7)\n"
        "time.sleep(120)\n")
    import time

    t0 = time.monotonic()
    proc = _sp.run(
        [_sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--hosts", "127.0.0.1:6701,127.0.0.1:6702",
         "--log_dir", str(tmp_path / "logs"), str(script)],
        env=_launch_env(), cwd=_REPO, stdout=_sp.PIPE,
        stderr=_sp.STDOUT, text=True, timeout=90)
    dt = time.monotonic() - t0
    assert proc.returncode == 7, proc.stdout
    assert dt < 60, "fail-fast took %.0fs (healthy worker sleeps 120s)" \
        % dt
    assert "worker 1 exited with 7" in proc.stdout


def test_supervised_restart_resumes_from_elastic_checkpoint(tmp_path):
    """--max_restarts composes with the elastic checkpoint-resume path:
    attempt 0 is killed hard after step 4 (last published checkpoint:
    step 3), the restarted attempt resumes at step 4 and the combined
    trajectory matches an uninterrupted run."""
    runner = _os.path.join(_DIR, "elastic_launch_runner.py")
    ref_root = str(tmp_path / "ref_ckpt")
    ref = _sp.run([_sys.executable, runner, ref_root],
                  env=_launch_env(), cwd=_REPO, stdout=_sp.PIPE,
                  stderr=_sp.STDOUT, text=True, timeout=240)
    assert ref.returncode == 0, ref.stdout
    ref_losses = _loss_lines(ref.stdout)
    assert len(ref_losses) == 8

    root = str(tmp_path / "crash_ckpt")
    log_dir = str(tmp_path / "logs")
    proc = _sp.run(
        [_sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--hosts", "127.0.0.1:6703", "--log_dir", log_dir,
         "--max_restarts", "1", runner, root, "crash"],
        env=_launch_env(), cwd=_REPO, stdout=_sp.PIPE,
        stderr=_sp.STDOUT, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout
    assert "restart 1/1" in proc.stdout, proc.stdout

    log = open(_os.path.join(log_dir, "workerlog.0")).read()
    got = _loss_lines(log)
    # attempt 0 printed steps 0..4 then died; attempt 1 resumed from the
    # step-3 checkpoint and reran 4..7 (log is append mode)
    assert [ln.split()[1] for ln in got] == \
        ["0", "1", "2", "3", "4", "4", "5", "6", "7"], log
    # last occurrence per step: attempt 1's rerun of step 4 onwards
    resumed = {ln.split()[1]: float(ln.split()[2]) for ln in got}
    expected = {ln.split()[1]: float(ln.split()[2])
                for ln in ref_losses}
    for step in ("4", "5", "6", "7"):
        np.testing.assert_allclose(resumed[step], expected[step],
                                   rtol=1e-4, atol=1e-5)


# -- supervisor-collected flight-recorder postmortem ------------------------

def test_supervisor_collects_flight_dump_of_fault_killed_rank(tmp_path):
    """Acceptance (observability): a PADDLE_FAULTS kill on ONE rank of
    a supervised 2-worker cohort leaves a flight-recorder dump that the
    launch supervisor collects into <log_dir>/postmortem/attempt0/
    BEFORE the --max_restarts cohort restart; the dump parses, names
    the fatal fault event, and carries the rank's last step records
    intact. The restarted cohort completes clean (rc=0)."""
    import json as _json

    script = tmp_path / "worker.py"
    script.write_text(
        "import os, sys\n"
        "sys.path.insert(0, %r)\n"
        "tid = int(os.environ['PADDLE_TRAINER_ID'])\n"
        "attempt = int(os.environ.get('PADDLE_RESTART_NUM', '0'))\n"
        "if tid == 1 and attempt == 0:\n"
        "    # the designated victim: die at its 3rd collective send\n"
        "    os.environ['PADDLE_FAULTS'] = \\\n"
        "        'kill:side=client,point=send,method=hc_put_part,at=3'\n"
        "import numpy as np\n"
        "import paddle_tpu.fluid as fluid\n"
        "from paddle_tpu.fluid import framework\n"
        "from paddle_tpu.distributed.host_collectives import \\\n"
        "    group_from_env\n"
        "os.environ.setdefault('PADDLE_HC_LIVENESS_S', '4')\n"
        "os.environ.setdefault('PADDLE_HC_HEARTBEAT_S', '0.5')\n"
        "g = group_from_env()\n"
        "main, startup = fluid.Program(), fluid.Program()\n"
        "with framework.program_guard(main, startup):\n"
        "    x = fluid.data(name='x', shape=[-1, 8], dtype='float32')\n"
        "    loss = fluid.layers.reduce_mean(\n"
        "        fluid.layers.fc(input=x, size=4))\n"
        "    fluid.optimizer.SGD(0.1).minimize(loss)\n"
        "exe = fluid.Executor(fluid.CPUPlace())\n"
        "exe.run(startup)\n"
        "feed = {'x': np.ones((2, 8), 'float32')}\n"
        "for i in range(6):\n"
        "    exe.run(main, feed=feed, fetch_list=[loss])\n"
        "    g.barrier()\n"
        "g.shutdown()\n"
        "sys.stdout.flush()\n"
        "os._exit(0)\n" % _REPO)
    log_dir = str(tmp_path / "logs")
    proc = _sp.run(
        [_sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--hosts", "127.0.0.1:6711,127.0.0.1:6712",
         "--log_dir", log_dir, "--max_restarts", "1", str(script)],
        env=_launch_env(), cwd=_REPO, stdout=_sp.PIPE,
        stderr=_sp.STDOUT, text=True, timeout=240)
    assert proc.returncode == 0, proc.stdout
    assert "restart 1/1" in proc.stdout, proc.stdout
    assert "collected" in proc.stdout and "flight-recorder" \
        in proc.stdout, proc.stdout

    # the victim's dump was secured under postmortem/attempt0 before
    # the restart (the restarted cohort overwrites the telemetry dir)
    dump_path = _os.path.join(log_dir, "postmortem", "attempt0",
                              "flightrec.rank1.json")
    assert _os.path.exists(dump_path), proc.stdout
    doc = _json.load(open(dump_path))
    assert doc["reason"] == "fault-kill"
    assert doc["fatal_event"]["event"] == "fault"
    assert doc["fatal_event"]["fault"] == "kill"
    assert doc["rank"] == 1
    # rank 1 died at its 3rd barrier: startup + 3 train steps recorded,
    # in order, with the step-phase split intact
    steps = [s["step"] for s in doc["steps"]]
    assert doc["n_steps"] >= 3 and steps == sorted(steps)
    assert all("total_ms" in s for s in doc["steps"])
    # the collective events before death rode along in the ring
    assert any(e.get("event") == "collective" for e in doc["events"])
    # the JSONL streams moved with the dumps, so attempt 1 started a
    # FRESH stream (no silent cross-attempt append with a reset step
    # counter) and attempt 0's records stay analyzable per-attempt
    att0 = _os.path.join(log_dir, "postmortem", "attempt0")
    assert _os.path.exists(_os.path.join(
        att0, "telemetry.rank1.jsonl")), _os.listdir(att0)
    tdir = _os.path.join(log_dir, "telemetry")
    assert _os.path.isdir(tdir)
    fresh = [f for f in _os.listdir(tdir) if f.endswith(".jsonl")]
    assert fresh, "restarted cohort must write its own stream"
    for f in fresh:
        recs = [_json.loads(ln) for ln in
                open(_os.path.join(tdir, f)) if ln.strip()]
        steps = [r["step"] for r in recs if r["kind"] == "step"]
        # a fresh stream restarts at step 1 — proof attempt 1 did not
        # append into attempt 0's file
        assert steps and steps[0] == 1, (f, steps[:3])
    # satellite: the run-wide postmortem index aggregates the dump
    index_path = _os.path.join(log_dir, "postmortem", "index.json")
    assert _os.path.exists(index_path), _os.listdir(
        _os.path.join(log_dir, "postmortem"))
    idx = _json.load(open(index_path))
    entries = [d for d in idx["dumps"]
               if d["attempt"] == 0 and d["rank"] == 1]
    assert entries and entries[0]["reason"] == "fault-kill"
    assert entries[0]["fatal_event"]["fault"] == "kill"
    assert entries[0]["n_steps"] >= 3


# -- elastic data re-sharding (reader.resharding) ---------------------------

def test_rank_slice_partitions_every_sample_exactly_once():
    from paddle_tpu.reader import resharding as rs

    for n in (0, 1, 5, 12, 24, 31):
        for world in (1, 2, 3, 4, 7):
            spans = [rs.rank_slice(n, r, world) for r in range(world)]
            # contiguous cover, no gap, no overlap, balanced
            assert spans[0][0] == 0 and spans[-1][1] == n
            for (a, b), (c, d) in zip(spans, spans[1:]):
                assert b == c
            sizes = [hi - lo for lo, hi in spans]
            assert max(sizes) - min(sizes) <= 1
    with pytest.raises(ValueError):
        rs.rank_slice(8, 2, 2)
    with pytest.raises(ValueError):
        rs.rank_slice(8, 0, 0)


def test_shard_batch_reshards_consistently_across_world_sizes():
    from paddle_tpu.reader import resharding as rs

    batch = {"x": np.arange(24).reshape(12, 2),
             "y": np.arange(12).reshape(12, 1)}
    for world in (1, 2, 3, 4):
        got = np.concatenate([rs.shard_batch(batch, r, world)["x"]
                              for r in range(world)])
        np.testing.assert_array_equal(got, batch["x"])
    tup = rs.shard_batch((batch["x"], batch["y"]), 1, 3)
    np.testing.assert_array_equal(tup[0], batch["x"][4:8])
    with pytest.raises(ValueError, match="disagree"):
        rs.shard_batch({"x": np.zeros((4, 1)), "y": np.zeros((5, 1))},
                       0, 2)


def test_resume_offset_and_skip_are_world_size_independent():
    from paddle_tpu.reader import resharding as rs

    # any world consumes global_batch samples per step: a checkpoint
    # taken at N resumes at the same sample cursor at N'
    assert rs.resume_sample_offset(5, 12) == 60
    assert rs.resume_sample_offset(-1, 12) == 0
    batches = [{"x": np.full((6, 1), i)} for i in range(5)]
    rest = list(rs.skip_steps(batches, 2))
    assert [int(b["x"][0, 0]) for b in rest] == [2, 3, 4]
    sharded = list(rs.shard_batches(rest, rank=1, world=2))
    assert all(b["x"].shape[0] == 3 for b in sharded)


# -- in-process elastic shrink: ZeRO-1 / AMP state re-shards at N' ----------
#
# The fast tier-1 elastic leg: a checkpoint written by an N-device
# sharded run restores into an N'-device program (N' != N), the
# executor re-pads/re-shards moments (and AMP masters) for the new
# mesh, and the post-restore trajectory is BIT-IDENTICAL to the
# replicated update restored from the same checkpoint — the invariant
# that makes an elastic world-size restart exact.

from paddle_tpu.utils.flags import get_flag, set_flags  # noqa: E402


@pytest.fixture
def _restore_shard_flags():
    old = {k: get_flag(k) for k in
           ("FLAGS_tpu_sharded_weight_update", "FLAGS_tpu_comm_bucket_mb")}
    yield
    set_flags(old)


def _shrink_batch():
    r = np.random.RandomState(0)
    # batch 24: divisible by every mesh size used below (4, 3, 2, 1)
    return (r.rand(24, 16).astype("float32"),
            r.randint(0, 4, (24, 1)).astype("int64"))


def _build_dp(ndev, zero1, amp=False, bucket_mb=0.0):
    """DP MLP (uneven fc size 31 -> flat-buffer padding differs between
    mesh sizes: 31 pads to 32 on 4/2 devs but 33 on 3) compiled for an
    ndev CPU sub-mesh."""
    import jax
    from jax.sharding import Mesh

    from paddle_tpu.fluid import framework

    set_flags({"FLAGS_tpu_sharded_weight_update": zero1,
               "FLAGS_tpu_comm_bucket_mb": bucket_mb})
    main, startup = fluid.Program(), fluid.Program()
    with framework.unique_name_guard(), \
            fluid.program_guard(main, startup):
        main.random_seed = startup.random_seed = 77
        img = fluid.layers.data(name="img", shape=[16],
                                dtype="float32")
        label = fluid.layers.data(name="label", shape=[1],
                                  dtype="int64")
        h = fluid.layers.fc(input=img, size=31, act="relu")
        logits = fluid.layers.fc(input=h, size=4)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        opt = fluid.optimizer.AdamOptimizer(learning_rate=0.01)
        if amp:
            from paddle_tpu.fluid.contrib import mixed_precision

            opt = mixed_precision.decorate(opt)
        opt.minimize(loss)
        fluid.CompiledProgram(main).with_data_parallel(
            loss_name=loss.name)
        main._mesh = Mesh(np.array(jax.devices()[:ndev]), ("dp",))
    return main, startup, loss.name


def _run_dp(prog, startup, loss_name, steps, scope=None, restore=None):
    x, y = _shrink_batch()
    scope = scope or Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    if restore:
        status = ckpt.load_checkpoint(exe, restore, main_program=prog,
                                      scope=scope)
        assert status is not None
    losses = [float(np.asarray(exe.run(
        prog, feed={"img": x, "label": y}, fetch_list=[loss_name],
        scope=scope)[0]).mean()) for _ in range(steps)]
    return losses, exe, scope


@pytest.mark.parametrize("amp", [False, True], ids=["zero1", "amp_o2"])
def test_elastic_shrink_restores_bit_identical_at_new_world(
        tmp_path, _restore_shard_flags, amp):
    """Tier-1 elastic leg: train sharded on 4 devices, checkpoint
    (logical shapes), then continue at N' in {3, 2, 1}: the sharded
    continuation must be BIT-IDENTICAL to the replicated continuation
    restored from the same checkpoint — proving the ZeRO-1 moments
    (and at amp_o2 the fp32 masters) re-pad/re-shard exactly for the
    new mesh. N'=3 exercises genuinely different padding (31 -> 33).

    The amp leg runs the per-variable lowering (bucket cap 0): on the
    CPU backend the AMP x BUCKETED combination drifts one bf16 ulp off
    replicated at world sizes where /N rounds in bf16 (ndev=3) — a
    pre-existing instance of PR 4's optimization_barrier-does-not-pin-
    CPU-fusions caveat, invisible at the power-of-two worlds PR 6
    tested; recorded in ROADMAP."""
    bucket_mb = 0.0 if amp else 0.25
    root = str(tmp_path / "shrink")
    prog4, st4, ln = _build_dp(4, True, amp=amp, bucket_mb=bucket_mb)
    _, exe4, sc4 = _run_dp(prog4, st4, ln, steps=2)
    plan4 = prog4._shard_plan
    assert plan4 is not None and plan4.ndev == 4
    ckpt.save_checkpoint(exe4, root,
                         ckpt.TrainStatus(epoch_no=0, step_no=1),
                         main_program=prog4, scope=sc4)

    for ndev in (3, 2, 1):
        p_s, st_s, ln_s = _build_dp(ndev, True, amp=amp,
                                    bucket_mb=bucket_mb)
        sharded, _, _ = _run_dp(p_s, st_s, ln_s, steps=3, restore=root)
        p_r, st_r, ln_r = _build_dp(ndev, False, amp=amp)
        replicated, _, _ = _run_dp(p_r, st_r, ln_r, steps=3,
                                   restore=root)
        np.testing.assert_array_equal(
            np.asarray(sharded), np.asarray(replicated),
            err_msg="shrink 4->%d not bit-identical" % ndev)
        plan = getattr(p_s, "_shard_plan", None)
        if ndev > 1:
            # the plan (and its bucket layout) re-planned for N'
            assert plan is not None and plan.ndev == ndev
            if bucket_mb:
                assert plan.buckets, "bucket plan must re-plan for N'"
                assert all(e.padded % ndev == 0
                           for b in plan.buckets for e in b.entries)
            padded = sorted({info.padded
                             for info in plan.sharded_state.values()})
            assert all(p % ndev == 0 for p in padded), padded
            if ndev == 3:
                # 31-element tensors: padding genuinely changed vs N=4
                assert any(info.numel == 31 and info.padded == 33
                           for info in plan.sharded_state.values())


# -- elastic supervisor: shrink-to-survivors policy -------------------------

def test_launch_elastic_shrink_drops_dead_rank_and_reassigns(tmp_path):
    """--min_ranks: rank 1 of a 3-worker cohort dies for good; the
    restart relaunches the TWO survivors with contiguous ranks and a
    rebuilt endpoint list, and the supervisor publishes an
    elastic_transition event with the reassignment map + recovery wall
    time into its own telemetry stream."""
    import json as _json

    script = tmp_path / "worker.py"
    script.write_text(
        "import os, sys, time\n"
        "tid = int(os.environ['PADDLE_TRAINER_ID'])\n"
        "attempt = int(os.environ.get('PADDLE_RESTART_NUM', '0'))\n"
        "print('WORLD', os.environ['PADDLE_TRAINERS_NUM'],\n"
        "      'RANK', tid, 'ATTEMPT', attempt,\n"
        "      'EPS', os.environ['PADDLE_TRAINER_ENDPOINTS'],\n"
        "      flush=True)\n"
        "if attempt == 0:\n"
        "    if tid == 1:\n"
        "        sys.exit(7)  # the lost machine\n"
        "    time.sleep(30)\n")
    log_dir = str(tmp_path / "logs")
    proc = _sp.run(
        [_sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--hosts", "127.0.0.1:6721,127.0.0.1:6722,127.0.0.1:6723",
         "--log_dir", log_dir, "--max_restarts", "1",
         "--min_ranks", "2", str(script)],
        env=_launch_env(), cwd=_REPO, stdout=_sp.PIPE,
        stderr=_sp.STDOUT, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout
    assert "elastic shrink 3 -> 2" in proc.stdout, proc.stdout
    assert "restart 1/1" in proc.stdout

    # attempt 1 ran at world 2 with contiguous ranks over the survivors
    log0 = open(_os.path.join(log_dir, "workerlog.0")).read()
    log1 = open(_os.path.join(log_dir, "workerlog.1")).read()
    assert "WORLD 3 RANK 0 ATTEMPT 0" in log0
    assert "WORLD 2 RANK 0 ATTEMPT 1" in log0
    assert "WORLD 2 RANK 1 ATTEMPT 1" in log1
    a1 = [ln for ln in log1.splitlines() if "ATTEMPT 1" in ln][0]
    eps = a1.split("EPS")[1].strip()
    assert eps == "127.0.0.1:6721,127.0.0.1:6723", a1  # 6722 dropped

    # the supervisor's own telemetry stream carries the seam event,
    # schema-valid against the locked telemetry contract
    sup = _os.path.join(log_dir, "telemetry",
                        "telemetry.supervisor.jsonl")
    assert _os.path.exists(sup), _os.listdir(log_dir)
    recs = [_json.loads(ln) for ln in open(sup) if ln.strip()]
    evs = [r for r in recs if r.get("event") == "elastic_transition"]
    assert len(evs) == 1
    ev = evs[0]
    assert ev["old_world"] == 3 and ev["new_world"] == 2
    assert ev["failed_ranks"] == [1]
    assert ev["reassignment"] == {"0": 0, "2": 1}
    assert ev["recovery_s"] >= 0
    from paddle_tpu.observability import schema as tschema

    assert tschema.validate_record(ev, tschema.load_schema()) == []


def test_launch_pod_aware_shrink_flat_fallback_and_rectangular(
        tmp_path):
    """Pod-aware elastic shrink (hybrid multi-pod topology): a 2x2
    cohort (--num_pods 2) losing ONE rank cannot stay rectangular
    (pods 1 vs 2) — the restart falls back to a FLAT 3-rank world,
    the elastic_transition event names the fallback
    (pod_topology=flat_fallback), and the shrunk workers see NO stale
    PADDLE_NUM_PODS/PADDLE_POD_ID. Losing one rank in EACH pod
    re-forms as a legal 1-per-pod 2-pod world. Never a wedged
    rendezvous either way."""
    import json as _json

    def run(kill_tids, ports):
        script = tmp_path / ("worker_%s.py" % "_".join(
            str(t) for t in kill_tids))
        script.write_text(
            "import os, sys, time\n"
            "tid = int(os.environ['PADDLE_TRAINER_ID'])\n"
            "attempt = int(os.environ.get('PADDLE_RESTART_NUM', '0'))\n"
            "print('WORLD', os.environ['PADDLE_TRAINERS_NUM'],\n"
            "      'RANK', tid, 'ATTEMPT', attempt,\n"
            "      'PODS', os.environ.get('PADDLE_NUM_PODS', '-'),\n"
            "      'POD', os.environ.get('PADDLE_POD_ID', '-'),\n"
            "      flush=True)\n"
            "if attempt == 0:\n"
            "    if tid in (%s,):\n"
            "        sys.exit(7)\n"
            "    time.sleep(30)\n"
            % ",".join(str(t) for t in kill_tids))
        log_dir = str(tmp_path / ("logs_%s" % "_".join(
            str(t) for t in kill_tids)))
        proc = _sp.run(
            [_sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--hosts", ",".join("127.0.0.1:%d" % p for p in ports),
             "--log_dir", log_dir, "--max_restarts", "1",
             "--min_ranks", "2", "--num_pods", "2", str(script)],
            env=_launch_env(), cwd=_REPO, stdout=_sp.PIPE,
            stderr=_sp.STDOUT, text=True, timeout=120)
        assert proc.returncode == 0, proc.stdout
        sup = _os.path.join(log_dir, "telemetry",
                            "telemetry.supervisor.jsonl")
        recs = [_json.loads(ln) for ln in open(sup) if ln.strip()]
        evs = [r for r in recs
               if r.get("event") == "elastic_transition"]
        assert len(evs) == 1
        logs = {tid: open(_os.path.join(
            log_dir, "workerlog.%d" % tid)).read()
            for tid in range(len(ports))
            if _os.path.exists(_os.path.join(log_dir,
                                             "workerlog.%d" % tid))}
        return proc.stdout, evs[0], logs

    # attempt 0 runs 2 pods x 2 ranks (contiguous blocks)
    out, ev, logs = run([1], [6731, 6732, 6733, 6734])
    assert "WORLD 4 RANK 0 ATTEMPT 0 PODS 2 POD 0" in logs[0]
    assert "WORLD 4 RANK 3 ATTEMPT 0 PODS 2 POD 1" in logs[3]
    # lopsided survivors (1 vs 2): flat fallback keeping all three
    assert ev["old_world"] == 4 and ev["new_world"] == 3
    assert ev["pod_topology"] == "flat_fallback"
    assert ev["pods_old"] == 2 and ev["pods_new"] == 1
    assert ev["pod_survivor_counts"] == [1, 2]
    assert "pods 2 -> 1 (flat_fallback)" in out
    assert "WORLD 3 RANK 0 ATTEMPT 1 PODS - POD -" in logs[0]

    # one rank lost in EACH pod: re-forms rectangular at 1 rank/pod
    out, ev, logs = run([1, 2], [6741, 6742, 6743, 6744])
    assert ev["new_world"] == 2
    assert ev["pod_topology"] == "rectangular"
    assert ev["pods_old"] == ev["pods_new"] == 2
    assert ev["ranks_per_pod"] == 1
    assert "WORLD 2 RANK 0 ATTEMPT 1 PODS 2 POD 0" in logs[0]
    # the restarted cohort logs under its NEW contiguous rank ids
    assert "WORLD 2 RANK 1 ATTEMPT 1 PODS 2 POD 1" in logs[1]
    from paddle_tpu.observability import schema as tschema

    assert tschema.validate_record(ev, tschema.load_schema()) == []


def test_launch_elastic_gives_up_below_min_ranks(tmp_path):
    """Survivor count below --min_ranks must NOT relaunch a too-small
    cohort: the launcher exits with the failure rc."""
    script = tmp_path / "worker.py"
    script.write_text(
        "import os, sys, time\n"
        "tid = int(os.environ['PADDLE_TRAINER_ID'])\n"
        "if tid == 0:\n"
        "    time.sleep(30)\n"
        "sys.exit(9)\n")
    proc = _sp.run(
        [_sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--hosts", "127.0.0.1:6725,127.0.0.1:6726",
         "--max_restarts", "3", "--min_ranks", "2", str(script)],
        env=_launch_env(), cwd=_REPO, stdout=_sp.PIPE,
        stderr=_sp.STDOUT, text=True, timeout=90)
    assert proc.returncode == 9, proc.stdout
    assert "below --min_ranks 2; giving up" in proc.stdout
    # no relaunch happened after the give-up line
    assert "restart 1/3" not in proc.stdout


def test_write_postmortem_index_summarizes_all_attempts(tmp_path):
    """postmortem/index.json (carried-over ROADMAP item): every
    attempt's per-rank dumps summarized in one file — attempt, rank,
    reason, fatal event, last recorded step; unreadable dumps get an
    error entry instead of poisoning the index."""
    import json as _json

    from paddle_tpu.distributed import launch as launch_mod

    pm = tmp_path / "postmortem"
    (pm / "attempt0").mkdir(parents=True)
    (pm / "attempt1").mkdir()
    (pm / "attempt0" / "flightrec.rank1.json").write_text(_json.dumps({
        "reason": "fault-kill",
        "fatal_event": {"event": "fault", "fault": "kill"},
        "n_steps": 4,
        "steps": [{"step": 3}, {"step": 4}], "events": []}))
    (pm / "attempt1" / "flightrec.rank0.json").write_text(_json.dumps({
        "reason": "signal", "fatal_event": {"event": "signal"},
        "n_steps": 2, "steps": [{"step": 9}], "events": []}))
    (pm / "attempt1" / "flightrec.rank2.json").write_text("{torn")
    path = launch_mod._write_postmortem_index(str(pm))
    idx = _json.load(open(path))
    assert idx["attempts"] == 2
    assert len(idx["dumps"]) == 3
    # newest attempt first
    assert [d["attempt"] for d in idx["dumps"]] == [1, 1, 0]
    by = {(d["attempt"], d["rank"]): d for d in idx["dumps"]}
    assert by[(0, 1)]["reason"] == "fault-kill"
    assert by[(0, 1)]["last_step"] == 4
    assert by[(1, 0)]["fatal_event"]["event"] == "signal"
    assert "error" in by[(1, 2)]


# -- supervised elastic acceptance: 4 -> 3 kill/shrink ----------------------

@pytest.mark.slow
@pytest.mark.faults
def test_supervised_elastic_4_to_3_shrink_resumes_bit_identical(
        tmp_path):
    """Acceptance: a supervised 4-rank CPU run killed mid-run (rank 1
    via PADDLE_FAULTS) restarts as a 3-rank cohort (reassigned ranks,
    rebuilt rendezvous), resumes from the last intact checkpoint with
    re-sharded per-rank data, and its post-resume losses are
    BIT-IDENTICAL to an uninterrupted 3-rank run restored from the same
    checkpoint."""
    import json as _json
    import shutil as _shutil

    runner = _os.path.join(_DIR, "elastic_world_runner.py")
    root = str(tmp_path / "ckpt")
    log_dir = str(tmp_path / "logs")
    hosts = ",".join("127.0.0.1:%d" % p
                     for p in (6731, 6733, 6735, 6737))
    # rank 1 dies at its step-5 allreduce contribution (events: 1
    # startup agreement put + 2 per completed step): last published
    # checkpoint is step 3, so the 3-rank cohort resumes at step 4
    proc = _sp.run(
        [_sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--hosts", hosts, "--log_dir", log_dir,
         "--max_restarts", "1", "--min_ranks", "3",
         runner, root, "8", "2", "1", "12"],
        env=_launch_env(), cwd=_REPO, stdout=_sp.PIPE,
        stderr=_sp.STDOUT, text=True, timeout=600)
    assert proc.returncode == 0, proc.stdout
    assert "elastic shrink 4 -> 3" in proc.stdout, proc.stdout

    log0 = open(_os.path.join(log_dir, "workerlog.0")).read()
    got = {}
    for ln in _loss_lines(log0):
        got[int(ln.split()[1])] = float(ln.split()[2])  # last wins
    assert sorted(got) == list(range(8)), log0
    resumes = [ln for ln in log0.splitlines()
               if ln.startswith("RESUME")]
    assert "RESUME 0 world=4 rank=0 attempt=0" in resumes[0]
    assert "RESUME 4 world=3 rank=0 attempt=1" in resumes[-1], resumes

    # uninterrupted 3-rank reference from the SAME checkpoint: copy
    # only the checkpoints the crashed attempt could have restored
    # (step_no <= 3 — the resumed attempt appended newer ones)
    ref_root = str(tmp_path / "ref_ckpt")
    _os.makedirs(ref_root)
    from paddle_tpu.fluid import checkpoint as _ck

    for name in _os.listdir(root):
        d = _os.path.join(root, name)
        if not _os.path.isdir(d):
            continue
        try:
            if _ck.read_status(d).step_no <= 3:
                _shutil.copytree(d, _os.path.join(ref_root, name))
        except OSError:
            continue
    ref_logs = str(tmp_path / "ref_logs")
    ref_hosts = ",".join("127.0.0.1:%d" % p for p in (6741, 6743, 6745))
    ref = _sp.run(
        [_sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--hosts", ref_hosts, "--log_dir", ref_logs,
         runner, ref_root, "8", "2"],
        env=_launch_env(), cwd=_REPO, stdout=_sp.PIPE,
        stderr=_sp.STDOUT, text=True, timeout=600)
    assert ref.returncode == 0, ref.stdout
    ref_log0 = open(_os.path.join(ref_logs, "workerlog.0")).read()
    assert "RESUME 4 world=3 rank=0 attempt=0" in ref_log0, ref_log0
    ref_losses = {int(ln.split()[1]): float(ln.split()[2])
                  for ln in _loss_lines(ref_log0)}
    assert sorted(ref_losses) == [4, 5, 6, 7], ref_log0
    for step in (4, 5, 6, 7):
        assert got[step] == ref_losses[step], (
            "step %d not bit-identical: elastic %.17g vs 3-rank ref "
            "%.17g" % (step, got[step], ref_losses[step]))

    # the seam is observable: transition event + recovery wall time
    sup = _os.path.join(log_dir, "telemetry",
                        "telemetry.supervisor.jsonl")
    evs = [_json.loads(ln) for ln in open(sup) if ln.strip()]
    evs = [r for r in evs if r.get("event") == "elastic_transition"]
    assert len(evs) == 1 and evs[0]["old_world"] == 4 \
        and evs[0]["new_world"] == 3 and evs[0]["recovery_s"] > 0
    # ... and tools/perf_analysis.py --elastic reports it
    pa = _sp.run(
        [_sys.executable, _os.path.join(_REPO, "tools",
                                        "perf_analysis.py"),
         "--elastic", "--log-dir", log_dir],
        env=_launch_env(), cwd=_REPO, stdout=_sp.PIPE,
        stderr=_sp.STDOUT, text=True, timeout=240)
    assert pa.returncode == 0, pa.stdout
    assert "world 4 -> 3" in pa.stdout, pa.stdout
