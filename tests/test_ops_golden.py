"""Op-level golden tests vs numpy references (reference test strategy:
SURVEY.md §4.1, op_test.py fixture)."""
import numpy as np
import pytest

from op_test import OpTest, ProgramOpTest


def rngf(*shape, seed=7, scale=1.0):
    r = np.random.RandomState(seed)
    return (r.rand(*shape).astype("float32") - 0.5) * 2 * scale


class TestMatmul(OpTest):
    op_type = "matmul"

    def test(self):
        x, y = rngf(3, 4, 5), rngf(3, 5, 6, seed=8)
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"transpose_X": False, "transpose_Y": False,
                      "alpha": 1.0}
        self.outputs = {"Out": np.matmul(x, y)}
        self.check_output()

    def test_transpose(self):
        x, y = rngf(4, 3), rngf(4, 6, seed=9)
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"transpose_X": True, "transpose_Y": False,
                      "alpha": 2.0}
        self.outputs = {"Out": 2.0 * (x.T @ y)}
        self.check_output()

    def test_grad(self):
        x, y = rngf(3, 4), rngf(4, 5, seed=8)
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"transpose_X": False, "transpose_Y": False,
                      "alpha": 1.0}
        self.check_grad(["X", "Y"], "Out")


class TestMul(OpTest):
    op_type = "mul"

    def test(self):
        x, y = rngf(2, 3, 4), rngf(12, 5, seed=8)
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"x_num_col_dims": 1, "y_num_col_dims": 1}
        self.outputs = {"Out": x.reshape(2, 12) @ y}
        self.check_output()


class TestElementwiseAxis(OpTest):
    op_type = "elementwise_add"

    def test_axis_broadcast(self):
        x, y = rngf(2, 3, 4, 5), rngf(3, 4, seed=8)
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"axis": 1}
        self.outputs = {"Out": x + y.reshape(1, 3, 4, 1)}
        self.check_output()

    def test_same_shape(self):
        x, y = rngf(4, 5), rngf(4, 5, seed=8)
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"axis": -1}
        self.outputs = {"Out": x + y}
        self.check_output()


class TestSoftmax(OpTest):
    op_type = "softmax"

    def test(self):
        x = rngf(4, 10)
        e = np.exp(x - x.max(-1, keepdims=True))
        self.inputs = {"X": x}
        self.attrs = {"axis": -1}
        self.outputs = {"Out": e / e.sum(-1, keepdims=True)}
        self.check_output()

    def test_grad(self):
        self.inputs = {"X": rngf(3, 6)}
        self.attrs = {"axis": -1}
        self.check_grad(["X"], "Out")


class TestSoftmaxCE(OpTest):
    op_type = "softmax_with_cross_entropy"

    def test(self):
        logits = rngf(5, 7)
        label = np.array([[0], [3], [6], [2], [1]], dtype="int64")
        e = np.exp(logits - logits.max(-1, keepdims=True))
        sm = e / e.sum(-1, keepdims=True)
        loss = -np.log(sm[np.arange(5), label[:, 0]])[:, None]
        self.inputs = {"Logits": logits, "Label": label}
        self.attrs = {"soft_label": False, "axis": -1}
        self.outputs = {"Softmax": sm, "Loss": loss}
        self.check_output()

    def test_grad(self):
        self.inputs = {"Logits": rngf(4, 5),
                       "Label": np.array([[0], [1], [4], [2]], "int64")}
        self.attrs = {"soft_label": False, "axis": -1}
        self.check_grad(["Logits"], "Loss")


class TestLayerNorm(OpTest):
    op_type = "layer_norm"

    def test(self):
        x = rngf(4, 6)
        scale = rngf(6, seed=8) + 1.0
        bias = rngf(6, seed=9)
        mu = x.mean(-1, keepdims=True)
        var = x.var(-1, keepdims=True)
        y = (x - mu) / np.sqrt(var + 1e-5) * scale + bias
        self.inputs = {"X": x, "Scale": scale, "Bias": bias}
        self.attrs = {"begin_norm_axis": 1, "epsilon": 1e-5}
        self.outputs = {"Y": y, "Mean": mu.reshape(4),
                        "Variance": var.reshape(4)}
        self.check_output(atol=1e-4)

    def test_grad(self):
        self.inputs = {"X": rngf(3, 5), "Scale": rngf(5, seed=8) + 1.0,
                       "Bias": rngf(5, seed=9)}
        self.attrs = {"begin_norm_axis": 1, "epsilon": 1e-5}
        self.check_grad(["X", "Scale", "Bias"], "Y",
                        max_relative_error=1e-2)


class TestBatchNormTrain(OpTest):
    op_type = "batch_norm"

    def test(self):
        x = rngf(4, 3, 2, 2)
        scale = np.ones(3, "float32") * 1.5
        bias = np.zeros(3, "float32")
        mean = np.zeros(3, "float32")
        var = np.ones(3, "float32")
        bm = x.mean(axis=(0, 2, 3))
        bv = x.var(axis=(0, 2, 3))
        y = ((x - bm.reshape(1, 3, 1, 1))
             / np.sqrt(bv.reshape(1, 3, 1, 1) + 1e-5)) * 1.5
        self.inputs = {"X": x, "Scale": scale, "Bias": bias,
                       "Mean": mean, "Variance": var}
        self.attrs = {"momentum": 0.9, "epsilon": 1e-5, "is_test": False,
                      "data_layout": "NCHW"}
        self.outputs = {
            "Y": y,
            "MeanOut": mean * 0.9 + bm * 0.1,
            "VarianceOut": var * 0.9 + bv * 0.1,
            "SavedMean": bm,
            "SavedVariance": 1.0 / np.sqrt(bv + 1e-5),
        }
        self.check_output(atol=1e-4)


class TestConv2D(OpTest):
    op_type = "conv2d"

    @staticmethod
    def _ref_conv(x, w, stride, pad):
        n, c, h, wd = x.shape
        oc, ic, kh, kw = w.shape
        xp = np.pad(x, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
        oh = (h + 2 * pad - kh) // stride + 1
        ow = (wd + 2 * pad - kw) // stride + 1
        out = np.zeros((n, oc, oh, ow), "float32")
        for i in range(oh):
            for j in range(ow):
                patch = xp[:, :, i * stride:i * stride + kh,
                           j * stride:j * stride + kw]
                out[:, :, i, j] = np.tensordot(
                    patch, w, axes=([1, 2, 3], [1, 2, 3]))
        return out

    def test(self):
        x = rngf(2, 3, 5, 5)
        w = rngf(4, 3, 3, 3, seed=8)
        self.inputs = {"Input": x, "Filter": w}
        self.attrs = {"strides": [1, 1], "paddings": [1, 1],
                      "dilations": [1, 1], "groups": 1}
        self.outputs = {"Output": self._ref_conv(x, w, 1, 1)}
        self.check_output(atol=1e-4)

    def test_stride2(self):
        x = rngf(1, 2, 6, 6)
        w = rngf(3, 2, 3, 3, seed=8)
        self.inputs = {"Input": x, "Filter": w}
        self.attrs = {"strides": [2, 2], "paddings": [0, 0],
                      "dilations": [1, 1], "groups": 1}
        self.outputs = {"Output": self._ref_conv(x, w, 2, 0)}
        self.check_output(atol=1e-4)


class TestPool2D(OpTest):
    op_type = "pool2d"

    def test_max(self):
        x = rngf(2, 3, 4, 4)
        ref = x.reshape(2, 3, 2, 2, 2, 2).max(axis=(3, 5))
        self.inputs = {"X": x}
        self.attrs = {"pooling_type": "max", "ksize": [2, 2],
                      "strides": [2, 2], "paddings": [0, 0]}
        self.outputs = {"Out": ref}
        self.check_output()

    def test_avg(self):
        x = rngf(2, 3, 4, 4)
        ref = x.reshape(2, 3, 2, 2, 2, 2).mean(axis=(3, 5))
        self.inputs = {"X": x}
        self.attrs = {"pooling_type": "avg", "ksize": [2, 2],
                      "strides": [2, 2], "paddings": [0, 0],
                      "exclusive": True}
        self.outputs = {"Out": ref}
        self.check_output()

    def test_global(self):
        x = rngf(2, 3, 4, 4)
        self.inputs = {"X": x}
        self.attrs = {"pooling_type": "avg", "ksize": [1, 1],
                      "global_pooling": True}
        self.outputs = {"Out": x.mean(axis=(2, 3), keepdims=True)}
        self.check_output()


class TestReduce(OpTest):
    op_type = "reduce_sum"

    def test_dim(self):
        x = rngf(3, 4, 5)
        self.inputs = {"X": x}
        self.attrs = {"dim": [1], "keep_dim": False, "reduce_all": False}
        self.outputs = {"Out": x.sum(1)}
        self.check_output()

    def test_all(self):
        x = rngf(3, 4)
        self.inputs = {"X": x}
        self.attrs = {"reduce_all": True, "dim": [0], "keep_dim": False}
        self.outputs = {"Out": np.asarray([x.sum()], "float32").reshape(())}
        # reduce_all produces shape (1,)
        self.outputs = {"Out": x.sum().reshape(1)}
        self.check_output()


class TestLookupTable(OpTest):
    op_type = "lookup_table_v2"

    def test(self):
        w = rngf(10, 4)
        ids = np.array([[1, 2], [3, 0]], "int64")
        self.inputs = {"W": w, "Ids": ids}
        self.attrs = {"padding_idx": -1}
        self.outputs = {"Out": w[ids]}
        self.check_output()

    def test_padding(self):
        w = rngf(10, 4)
        ids = np.array([[1, 2], [3, 2]], "int64")
        ref = w[ids].copy()
        ref[ids == 2] = 0.0
        self.inputs = {"W": w, "Ids": ids}
        self.attrs = {"padding_idx": 2}
        self.outputs = {"Out": ref}
        self.check_output()


class TestTopK(OpTest):
    op_type = "top_k"

    def test(self):
        x = np.array([[1.0, 3.0, 2.0], [5.0, 4.0, 6.0]], "float32")
        self.inputs = {"X": x}
        self.attrs = {"k": 2}
        self.outputs = {"Out": np.array([[3.0, 2.0], [6.0, 5.0]],
                                        "float32"),
                        "Indices": np.array([[1, 2], [2, 0]], "int64")}
        self.check_output()


class TestAccuracy(OpTest):
    op_type = "accuracy"

    def test(self):
        indices = np.array([[0, 1], [2, 3], [4, 5]], "int64")
        label = np.array([[1], [0], [4]], "int64")
        self.inputs = {"Out": rngf(3, 2), "Indices": indices,
                       "Label": label}
        self.outputs = {"Accuracy": np.array([2.0 / 3], "float32"),
                        "Correct": np.array([2], "int32"),
                        "Total": np.array([3], "int32")}
        self.check_output()


class TestCast(OpTest):
    op_type = "cast"

    def test(self):
        x = rngf(3, 4)
        self.inputs = {"X": x}
        self.attrs = {"out_dtype": "int32"}
        self.outputs = {"Out": x.astype("int32")}
        self.check_output()


class TestOneHot(OpTest):
    op_type = "one_hot_v2"

    def test(self):
        x = np.array([1, 0, 3], "int64")
        ref = np.zeros((3, 4), "float32")
        ref[np.arange(3), x] = 1.0
        self.inputs = {"X": x}
        self.attrs = {"depth": 4}
        self.outputs = {"Out": ref}
        self.check_output()


class TestDropoutInfer(OpTest):
    op_type = "dropout"

    def test_is_test(self):
        x = rngf(4, 5)
        self.inputs = {"X": x}
        self.attrs = {"dropout_prob": 0.3, "is_test": True,
                      "dropout_implementation": "downgrade_in_infer"}
        self.outputs = {"Out": x * 0.7}
        self.check_output(no_check_set=("Mask",))

    def test_upscale_infer(self):
        x = rngf(4, 5)
        self.inputs = {"X": x}
        self.attrs = {"dropout_prob": 0.3, "is_test": True,
                      "dropout_implementation": "upscale_in_train"}
        self.outputs = {"Out": x}
        self.check_output(no_check_set=("Mask",))

    def test_train_stats(self):
        # statistical check: ~p zeros, upscale preserves mean
        from paddle_tpu import ops as ops_lib
        import jax.numpy as jnp
        import jax

        x = np.ones((100, 100), "float32")
        out = ops_lib.run_op(
            "dropout", {"X": [jnp.asarray(x)]},
            {"dropout_prob": 0.4, "is_test": False,
             "dropout_implementation": "upscale_in_train",
             "_rng_key": jax.random.PRNGKey(0)})
        o = np.asarray(out["Out"][0])
        frac_zero = (o == 0).mean()
        assert abs(frac_zero - 0.4) < 0.03
        assert abs(o.mean() - 1.0) < 0.05


class TestGather(OpTest):
    op_type = "gather"

    def test(self):
        x = rngf(5, 3)
        idx = np.array([0, 2, 4], "int64")
        self.inputs = {"X": x, "Index": idx}
        self.attrs = {"axis": 0}
        self.outputs = {"Out": x[idx]}
        self.check_output()


class TestConcatSplit(OpTest):
    op_type = "concat"

    def test_concat(self):
        xs = [rngf(2, 3), rngf(2, 4, seed=8), rngf(2, 1, seed=9)]
        self.inputs = {"X": xs}
        self.attrs = {"axis": 1}
        self.outputs = {"Out": np.concatenate(xs, 1)}
        self.check_output()


class TestSliceOp(OpTest):
    op_type = "slice"

    def test(self):
        x = rngf(4, 5, 6)
        self.inputs = {"Input": x}
        self.attrs = {"axes": [0, 2], "starts": [1, 2], "ends": [3, 5],
                      "decrease_axis": []}
        self.outputs = {"Out": x[1:3, :, 2:5]}
        self.check_output()


class TestActivationGrads(OpTest):
    op_type = "tanh"

    @pytest.mark.parametrize("op", ["relu", "sigmoid", "tanh", "gelu",
                                    "softplus", "square", "exp"])
    def test_grads(self, op):
        self.op_type = op
        self.inputs = {"X": rngf(3, 4) + 0.1}
        self.attrs = {}
        self.check_grad(["X"], "Out", max_relative_error=1e-2)


class TestProgramPath(ProgramOpTest):
    """One op through the whole program->Executor->XLA pipeline."""

    op_type = "elementwise_mul"

    def test(self):
        x, y = rngf(3, 4), rngf(3, 4, seed=8)
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"axis": -1}
        self.outputs = {"Out": x * y}
        self.check_output_with_program()
