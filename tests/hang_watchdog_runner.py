"""Worker script for the hang-watchdog acceptance test (spawned via
`python -m paddle_tpu.distributed.launch --hang_timeout --min_ranks
--max_restarts`).

A tiny supervised train loop over the HOST collective tier: every rank
runs `total_steps` executor steps with a cohort barrier after each. In
stall mode the designated victim rank of attempt 0 arms a
PADDLE_FAULTS `stall` at its Nth host-collective contribution
(`hc_put_part` client send) — an alive-but-wedged machine: the process
keeps running and heartbeating, but its barrier part never leaves, so
the whole cohort blocks inside the barrier with no error and no crash.

The launcher's --hang_timeout exports FLAGS_tpu_hang_timeout_s, so
every rank's in-process watchdog dumps all-thread stacks + the
in-flight collective table and publishes a `hang` event; the
supervisor escalates (dumps into postmortem/, cohort killed, guilty
rank dropped through the --min_ranks elastic restart) and the
surviving attempt completes rc=0.

argv: <total_steps> [<stall_rank> <stall_at>]
Prints one `DONE rank=R world=W attempt=K` line on completion.
"""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PADDLE_HC_HEARTBEAT_S", "0.5")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def main():
    total = int(sys.argv[1])
    stall_rank = int(sys.argv[2]) if len(sys.argv) > 2 else -1
    stall_at = int(sys.argv[3]) if len(sys.argv) > 3 else 0

    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    world = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    attempt = int(os.environ.get("PADDLE_RESTART_NUM", "0"))
    if attempt == 0 and rank == stall_rank and stall_at > 0:
        # the designated victim: wedge (not die) inside its Nth
        # barrier contribution — the send never happens, the process
        # stays alive and heartbeating
        os.environ["PADDLE_FAULTS"] = (
            "stall:side=client,point=send,method=hc_put_part,at=%d"
            % stall_at)

    import paddle_tpu.fluid as fluid
    from paddle_tpu.distributed.host_collectives import group_from_env
    from paddle_tpu.fluid import framework

    group = group_from_env()
    main_p, startup = fluid.Program(), fluid.Program()
    with framework.unique_name_guard(), \
            fluid.program_guard(main_p, startup):
        main_p.random_seed = startup.random_seed = 7
        x = fluid.data(name="x", shape=[-1, 8], dtype="float32")
        loss = fluid.layers.reduce_mean(
            fluid.layers.fc(input=x, size=4, act="tanh"))
        fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    feed = {"x": np.ones((2, 8), np.float32)}
    for i in range(total):
        exe.run(main_p, feed=feed, fetch_list=[loss])
        if group is not None:
            # the stall's injection point: the victim wedges inside
            # barrier contribution #stall_at and never returns
            group.barrier()
    print("DONE rank=%d world=%d attempt=%d" % (rank, world, attempt),
          flush=True)
    if group is not None:
        group.shutdown()
    sys.stdout.flush()
    # exit WITHOUT interpreter teardown: jax's CPU runtime
    # intermittently aborts while daemon threads die at exit (see
    # elastic_launch_runner)
    os._exit(0)


if __name__ == "__main__":
    main()
