"""DistributedStrategy.sync_batch_norm — BN moments pmean'd over the
dp axis (reference: sync_batch_norm_op.cu via ncclAllReduce; TPU-native:
the sync_batch_norm op's lax.pmean inside the DP shard_map, with the
synchronized backward falling out of jax.vjp through pmean)."""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu import fleet
from paddle_tpu.core.scope import Scope
from paddle_tpu.fluid import framework


def _train(sync_bn, dp, steps=4, batch=16, seed=3):
    """Conv+BN classifier under fleet DP (or single-device when
    dp=False); returns the per-step losses."""
    rng = np.random.RandomState(0)
    xs = rng.randn(batch, 4, 6, 6).astype(np.float32)
    ys = rng.randint(0, 3, (batch, 1)).astype(np.int64)

    main, startup = fluid.Program(), fluid.Program()
    with framework.unique_name_guard(), \
            fluid.program_guard(main, startup):
        main.random_seed = startup.random_seed = seed
        x = fluid.layers.data(name="x", shape=[4, 6, 6],
                              dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="int64")
        h = fluid.layers.conv2d(x, num_filters=8, filter_size=3,
                                padding=1)
        h = fluid.layers.batch_norm(h)
        h = fluid.layers.relu(h)
        logits = fluid.layers.fc(h, 3)
        loss = fluid.layers.mean(
            fluid.layers.loss.softmax_with_cross_entropy(logits, y))
        opt = fluid.optimizer.SGD(learning_rate=0.1)
        if dp:
            st = fleet.DistributedStrategy()
            st.sync_batch_norm = sync_bn
            fleet.init()
            opt = fleet.distributed_optimizer(opt, st)
        opt.minimize(loss)

    if dp and sync_bn:
        assert any(op.type == "sync_batch_norm"
                   for op in main.global_block().ops)

    scope = Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    losses = []
    for _ in range(steps):
        out, = exe.run(main, feed={"x": xs, "y": ys},
                       fetch_list=[loss.name], scope=scope)
        # DP fetch of a non-persistable var returns the per-device
        # shard values; their mean is the global batch loss (each
        # device averaged an equal 2-row shard)
        losses.append(float(np.asarray(out).reshape(-1).mean()))
    return losses


def test_sync_bn_matches_full_batch_single_device():
    """With synchronized moments, the 8-way DP run (2 rows/device) must
    reproduce the single-device full-batch trajectory; per-replica BN
    (sync off) must NOT — that divergence is exactly what the knob
    fixes."""
    ref = _train(sync_bn=False, dp=False)
    synced = _train(sync_bn=True, dp=True)
    unsynced = _train(sync_bn=False, dp=True)
    np.testing.assert_allclose(synced, ref, rtol=2e-4, atol=2e-5)
    assert not np.allclose(unsynced, ref, rtol=2e-4, atol=2e-5), (
        "per-replica BN over 2-row shards cannot match full-batch "
        "stats; if it does, the sync path is not being exercised")


def test_sync_bn_off_leaves_ops_untouched():
    main, startup = fluid.Program(), fluid.Program()
    with framework.unique_name_guard(), \
            fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4, 6, 6],
                              dtype="float32")
        h = fluid.layers.batch_norm(fluid.layers.conv2d(
            x, num_filters=4, filter_size=3))
        loss = fluid.layers.mean(h)
        st = fleet.DistributedStrategy()
        fleet.init()
        opt = fleet.distributed_optimizer(
            fluid.optimizer.SGD(learning_rate=0.1), st)
        opt.minimize(loss)
    types = [op.type for op in main.global_block().ops]
    assert "batch_norm" in types and "sync_batch_norm" not in types
