"""Golden tests for the round-3 de-descoped op corners (VERDICT r2
weak #4/next #6): grouped conv2d_transpose, chunk_eval IOBES,
similarity_focus greedy selection + axes."""
import numpy as np
import pytest

import jax.numpy as jnp

from paddle_tpu.ops.registry import get_op


@pytest.mark.slow
def test_conv2d_transpose_groups_matches_torch():
    import torch

    r = np.random.RandomState(0)
    x = r.randn(2, 6, 7, 7).astype("float32")
    for groups, stride, pad, dil in [(2, 1, 0, 1), (2, 2, 1, 1),
                                     (3, 1, 1, 2), (6, 2, 0, 1)]:
        # paddle filter layout: (in, out/groups, kh, kw); out = 12
        w_use = r.randn(6, 12 // groups, 3, 3).astype("float32")
        out = get_op("conv2d_transpose").compute(
            {"Input": [jnp.asarray(x)], "Filter": [jnp.asarray(w_use)]},
            {"strides": [stride, stride], "paddings": [pad, pad],
             "dilations": [dil, dil], "groups": groups})["Output"]
        ref = torch.conv_transpose2d(
            torch.from_numpy(x), torch.from_numpy(w_use), stride=stride,
            padding=pad, dilation=dil, groups=groups).numpy()
        np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4,
                                   atol=2e-4), (groups, stride, pad, dil)


def _chunk_f1(inference, label, num_chunk_types, scheme):
    out = get_op("chunk_eval").compute(
        {"Inference": [jnp.asarray(inference)],
         "Label": [jnp.asarray(label)]},
        {"num_chunk_types": num_chunk_types, "chunk_scheme": scheme})
    return (float(out["Precision"][0]), float(out["Recall"][0]),
            int(out["NumInferChunks"][0]), int(out["NumLabelChunks"][0]))


def test_chunk_eval_iobes():
    """IOBES: tag = label % 4 in (B=0, I=1, E=2, S=3), chunk type =
    label // 4; Outside = num_chunk_types*4 (reference chunk_eval_op.h
    tag table). Sequence: B-0 E-0 | O | S-1 | B-0 I-0 E-0."""
    label = np.array([0, 2, 8, 7, 0, 1, 2], "int64")  # 3 gold chunks
    # prediction gets the first and last chunk right, misses S-1
    pred = np.array([0, 2, 8, 8, 0, 1, 2], "int64")
    prec, rec, n_pred, n_gold = _chunk_f1(pred, label, 2, "IOBES")
    assert n_gold == 3 and n_pred == 2
    assert prec == pytest.approx(1.0) and rec == pytest.approx(2 / 3)


def test_chunk_eval_iobes_single_splits_chunks():
    """S tags are complete single-token chunks: S-0 S-0 is two chunks,
    not one merged span."""
    label = np.array([3, 3], "int64")
    _, _, n_pred, n_gold = _chunk_f1(label, label, 1, "IOBES")
    assert n_gold == 2 and n_pred == 2


def test_chunk_eval_plain_merges_contiguous_runs():
    """plain scheme is IO semantics (reference chunk_eval_op.h:142-147,
    all tag ids -1): contiguous same-type tokens form ONE chunk, they do
    not each open their own (ADVICE r3: a begin tag of 0 made every
    token its own chunk because label % 1 == 0 always)."""
    # types: 0 0 0 | O | 1 1  (num_chunk_types=2, Outside id = 2)
    label = np.array([0, 0, 0, 2, 1, 1], "int64")
    prec, rec, n_pred, n_gold = _chunk_f1(label, label, 2, "plain")
    assert n_gold == 2 and n_pred == 2
    assert prec == pytest.approx(1.0) and rec == pytest.approx(1.0)
    # a type switch without an Outside gap also splits: 0 0 1 = 2 chunks
    label2 = np.array([0, 0, 1], "int64")
    _, _, n_pred2, n_gold2 = _chunk_f1(label2, label2, 2, "plain")
    assert n_gold2 == 2 and n_pred2 == 2


def test_chunk_eval_invalid_scheme():
    with pytest.raises(ValueError, match="chunk_scheme"):
        _chunk_f1(np.array([0], "int64"), np.array([0], "int64"), 1,
                  "BILOU")


def test_similarity_focus_greedy_unique_rows_cols():
    """Reference semantics (similarity_focus_op.cc): greedy largest-value
    selection with each row/col used at most once — NOT row-max OR
    col-max."""
    x = np.zeros((1, 1, 2, 2), "float32")
    x[0, 0] = [[5.0, 4.0], [3.0, 1.0]]
    out = np.asarray(get_op("similarity_focus").compute(
        {"X": [jnp.asarray(x)]}, {"axis": 1, "indexes": [0]})["Out"])
    # greedy: pick 5 at (0,0); 4 and 3 share its row/col; then 1 at (1,1)
    np.testing.assert_array_equal(out[0, 0],
                                  [[1.0, 0.0], [0.0, 1.0]])


def test_similarity_focus_axis_2():
    r = np.random.RandomState(2)
    x = r.rand(2, 3, 2, 4).astype("float32")
    out = np.asarray(get_op("similarity_focus").compute(
        {"X": [jnp.asarray(x)]}, {"axis": 2, "indexes": [1]})["Out"])
    assert out.shape == x.shape
    # mask is constant along the selected axis (2), and the greedy
    # selection makes min(3, 4) = 3 picks in each [3, 4] plane
    np.testing.assert_array_equal(out[:, :, 0], out[:, :, 1])
    assert out[0, :, 0].sum() == 3
    with pytest.raises(ValueError, match="axis"):
        get_op("similarity_focus").compute(
            {"X": [jnp.asarray(x)]}, {"axis": 0, "indexes": [0]})


def test_sequence_pool_invalid_type_is_construction_time():
    import paddle_tpu.fluid as fluid

    x = fluid.layers.data(name="sp_x", shape=[4, 8], dtype="float32")
    with pytest.raises(ValueError, match="pool_type"):
        fluid.layers.sequence_pool(x, "median")
