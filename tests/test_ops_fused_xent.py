"""Golden + grad tests for the fused_linear_softmax_xent op (the
memory-fused large-vocab classifier head; see ops/fused_ops.py) and its
integration in the BERT masked-LM head."""
import numpy as np
import pytest

from op_test import OpTest


def _ref_loss(x, w, b, label):
    logits = x @ w + (b if b is not None else 0.0)
    m = logits.max(-1, keepdims=True)
    lse = m[:, 0] + np.log(np.exp(logits - m).sum(-1))
    picked = logits[np.arange(x.shape[0]), label]
    return (lse - picked)[:, None]


class TestFusedLinearSoftmaxXent(OpTest):
    op_type = "fused_linear_softmax_xent"

    def _mk(self, n=6, h=5, v=13, seed=3):
        r = np.random.RandomState(seed)
        x = (r.rand(n, h).astype("float32") - 0.5) * 2
        w = (r.rand(h, v).astype("float32") - 0.5) * 2
        b = (r.rand(v).astype("float32") - 0.5)
        label = r.randint(0, v, (n,)).astype("int64")
        return x, w, b, label

    def test_single_chunk(self):
        x, w, b, label = self._mk()
        self.inputs = {"X": x, "W": w, "Bias": b, "Label": label}
        self.attrs = {"chunk_size": 16}
        self.outputs = {"Loss": _ref_loss(x, w, b, label)}
        self.check_output()

    def test_multi_chunk_with_padding(self):
        # v=13, chunk=4 -> 4 chunks, padded to 16: exercises the online
        # logsumexp across chunks AND the -1e30 padded-column masking
        x, w, b, label = self._mk()
        self.inputs = {"X": x, "W": w, "Bias": b, "Label": label}
        self.attrs = {"chunk_size": 4}
        self.outputs = {"Loss": _ref_loss(x, w, b, label)}
        self.check_output()

    def test_no_bias(self):
        x, w, _, label = self._mk()
        self.inputs = {"X": x, "W": w, "Label": label}
        self.attrs = {"chunk_size": 5}
        self.outputs = {"Loss": _ref_loss(x, w, None, label)}
        self.check_output()

    def test_label_2d_and_leading_dims(self):
        # x [B, P, H] with label [B, P, 1] must give loss [B, P, 1]
        r = np.random.RandomState(5)
        x = (r.rand(2, 3, 4).astype("float32") - 0.5)
        w = (r.rand(4, 9).astype("float32") - 0.5)
        b = np.zeros(9, "float32")
        label = r.randint(0, 9, (2, 3, 1)).astype("int64")
        ref = _ref_loss(x.reshape(-1, 4), w, b,
                        label.reshape(-1)).reshape(2, 3, 1)
        self.inputs = {"X": x, "W": w, "Bias": b, "Label": label}
        self.attrs = {"chunk_size": 4}
        self.outputs = {"Loss": ref}
        self.check_output()

    @pytest.mark.slow
    def test_grad_multi_chunk(self):
        x, w, b, label = self._mk(n=4, h=3, v=11)
        self.inputs = {"X": x, "W": w, "Bias": b, "Label": label}
        self.attrs = {"chunk_size": 4}
        self.check_grad(["X", "W", "Bias"], "Loss")

    def test_matches_unfused_composite(self):
        # parity with the unfused mul + softmax_with_cross_entropy chain
        from paddle_tpu.ops.registry import get_op

        x, w, b, label = self._mk(n=8, h=6, v=17, seed=11)
        import jax.numpy as jnp

        fused = get_op("fused_linear_softmax_xent").compute(
            {"X": [jnp.asarray(x)], "W": [jnp.asarray(w)],
             "Bias": [jnp.asarray(b)], "Label": [jnp.asarray(label)]},
            {"chunk_size": 4})["Loss"]
        logits = jnp.asarray(x @ w + b)
        unfused = get_op("softmax_with_cross_entropy").compute(
            {"Logits": [logits], "Label": [jnp.asarray(label[:, None])]},
            {})["Loss"]
        np.testing.assert_allclose(np.asarray(fused), np.asarray(unfused),
                                   rtol=2e-5, atol=2e-5)
