"""paddle.nn MultiHeadAttention / TransformerEncoder (reference 2.0
nn.layer.transformer surface) running in dygraph with autograd."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.fluid import dygraph
from paddle_tpu import nn


def test_multihead_attention_shapes_and_grads():
    with dygraph.guard():
        mha = nn.MultiHeadAttention(embed_dim=16, num_heads=4)
        x = dygraph.to_variable(
            np.random.RandomState(0).randn(2, 5, 16).astype("float32"))
        out = mha(x)
        assert tuple(out._val.shape) == (2, 5, 16)
        loss = paddle.fluid.layers.mean(out)
        loss.backward()
        g = mha.q_proj.weight._grad
        assert g is not None and np.isfinite(np.asarray(g)).all()


def test_transformer_encoder_trains():
    with dygraph.guard():
        layer = nn.TransformerEncoderLayer(
            d_model=16, nhead=4, dim_feedforward=32, dropout=0.0)
        enc = nn.TransformerEncoder(layer, num_layers=2)
        opt = paddle.fluid.optimizer.AdamOptimizer(
            1e-2, parameter_list=enc.parameters())
        r = np.random.RandomState(1)
        x = r.randn(4, 6, 16).astype("float32")
        tgt = r.randn(4, 6, 16).astype("float32")
        losses = []
        for _ in range(8):
            out = enc(dygraph.to_variable(x))
            diff = out - dygraph.to_variable(tgt)
            loss = paddle.fluid.layers.mean(diff * diff)
            opt.minimize(loss, parameter_list=enc.parameters())
            enc.clear_gradients()
            losses.append(float(np.asarray(loss._val).reshape(-1)[0]))
        assert losses[-1] < losses[0], losses
        assert len(enc.parameters()) > 10
