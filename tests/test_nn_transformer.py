"""paddle.nn MultiHeadAttention / TransformerEncoder (reference 2.0
nn.layer.transformer surface) running in dygraph with autograd."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.fluid import dygraph
from paddle_tpu import nn


def test_multihead_attention_shapes_and_grads():
    with dygraph.guard():
        mha = nn.MultiHeadAttention(embed_dim=16, num_heads=4)
        x = dygraph.to_variable(
            np.random.RandomState(0).randn(2, 5, 16).astype("float32"))
        out = mha(x)
        assert tuple(out._val.shape) == (2, 5, 16)
        loss = paddle.fluid.layers.mean(out)
        loss.backward()
        g = mha.q_proj.weight._grad
        assert g is not None and np.isfinite(np.asarray(g)).all()


def test_transformer_encoder_trains():
    with dygraph.guard():
        layer = nn.TransformerEncoderLayer(
            d_model=16, nhead=4, dim_feedforward=32, dropout=0.0)
        enc = nn.TransformerEncoder(layer, num_layers=2)
        opt = paddle.fluid.optimizer.AdamOptimizer(
            1e-2, parameter_list=enc.parameters())
        r = np.random.RandomState(1)
        x = r.randn(4, 6, 16).astype("float32")
        tgt = r.randn(4, 6, 16).astype("float32")
        losses = []
        for _ in range(8):
            out = enc(dygraph.to_variable(x))
            diff = out - dygraph.to_variable(tgt)
            loss = paddle.fluid.layers.mean(diff * diff)
            opt.minimize(loss, parameter_list=enc.parameters())
            enc.clear_gradients()
            losses.append(float(np.asarray(loss._val).reshape(-1)[0]))
        assert losses[-1] < losses[0], losses
        assert len(enc.parameters()) > 10


def test_multihead_attention_need_weights():
    """need_weights=True returns (out, probs) via the unfused path
    (paddle 2.0 transformer.py contract); probs rows sum to 1."""
    with dygraph.guard():
        mha = nn.MultiHeadAttention(embed_dim=16, num_heads=4,
                                    need_weights=True)
        x = dygraph.to_variable(
            np.random.RandomState(0).randn(2, 5, 16).astype("float32"))
        out, w = mha(x)
        assert tuple(out._val.shape) == (2, 5, 16)
        assert tuple(w._val.shape) == (2, 4, 5, 5)
        np.testing.assert_allclose(np.asarray(w._val).sum(-1),
                                   np.ones((2, 4, 5)), rtol=1e-5)
        # unfused path must agree with the fused one (no dropout)
        mha.need_weights = False
        fused = mha(x)
        np.testing.assert_allclose(np.asarray(fused._val),
                                   np.asarray(out._val), rtol=2e-5,
                                   atol=2e-5)


def test_multihead_attention_cache_decode():
    """Incremental decoding with Cache: step-by-step causal decode must
    equal the full-sequence causal pass (paddle 2.0 gen_cache/Cache)."""
    r = np.random.RandomState(3)
    seq = r.randn(1, 4, 16).astype("float32")
    with dygraph.guard():
        mha = nn.MultiHeadAttention(embed_dim=16, num_heads=4)
        mha.eval()
        # full causal pass: additive [Sq, Sk] lower-triangular mask
        causal = np.triu(np.full((4, 4), -1e9, "float32"), k=1)
        full = mha(dygraph.to_variable(seq),
                   attn_mask=dygraph.to_variable(causal))
        full_np = np.asarray(full._val)

        cache = mha.gen_cache(dygraph.to_variable(seq[:, :1]))
        steps = []
        for t in range(4):
            tok = dygraph.to_variable(seq[:, t:t + 1])
            out, cache = mha(tok, tok, tok, cache=cache)
            steps.append(np.asarray(out._val)[:, 0])
        dec = np.stack(steps, axis=1)
    np.testing.assert_allclose(dec, full_np, rtol=2e-4, atol=2e-4)


def test_multihead_attention_static_cache():
    """StaticCache: encoder K/V projected once for cross-attention."""
    r = np.random.RandomState(5)
    with dygraph.guard():
        mha = nn.MultiHeadAttention(embed_dim=16, num_heads=4)
        mha.eval()
        enc = dygraph.to_variable(r.randn(2, 6, 16).astype("float32"))
        q = dygraph.to_variable(r.randn(2, 3, 16).astype("float32"))
        cache = mha.gen_cache(enc, enc,
                              type=nn.MultiHeadAttention.StaticCache)
        out, cache2 = mha(q, cache=cache)
        ref = mha(q, enc, enc)
        np.testing.assert_allclose(np.asarray(out._val),
                                   np.asarray(ref._val), rtol=2e-5,
                                   atol=2e-5)
        assert cache2 is cache


def test_sdpa_full_mask():
    """functional.scaled_dot_product_attention accepts a broadcastable
    [Sq, Sk] / [B, H, Sq, Sk] additive mask (unfused XLA path)."""
    import paddle_tpu.nn.functional as F

    r = np.random.RandomState(7)
    with dygraph.guard():
        q = dygraph.to_variable(r.randn(2, 4, 5, 8).astype("float32"))
        k = dygraph.to_variable(r.randn(2, 4, 5, 8).astype("float32"))
        v = dygraph.to_variable(r.randn(2, 4, 5, 8).astype("float32"))
        causal = np.triu(np.full((5, 5), -1e9, "float32"), k=1)
        masked = F.scaled_dot_product_attention(
            q, k, v, attn_mask=dygraph.to_variable(causal),
            training=False)
        ref = F.scaled_dot_product_attention(q, k, v, is_causal=True,
                                             training=False)
        np.testing.assert_allclose(np.asarray(masked._val),
                                   np.asarray(ref._val), rtol=2e-5,
                                   atol=2e-5)


def test_sdpa_batched_3d_mask_broadcast():
    """[B, Sq, Sk] masks insert the head axis at dim 1 (code-review r3:
    prepending would misalign batch with heads)."""
    import paddle_tpu.nn.functional as F

    r = np.random.RandomState(9)
    B, H, S, D = 2, 4, 5, 8
    with dygraph.guard():
        q = dygraph.to_variable(r.randn(B, H, S, D).astype("float32"))
        k = dygraph.to_variable(r.randn(B, H, S, D).astype("float32"))
        v = dygraph.to_variable(r.randn(B, H, S, D).astype("float32"))
        # per-batch masks: batch 0 causal, batch 1 unmasked
        m3 = np.zeros((B, S, S), "float32")
        m3[0] = np.triu(np.full((S, S), -1e9, "float32"), k=1)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=dygraph.to_variable(m3), training=False)
        causal_all = F.scaled_dot_product_attention(
            q, k, v, is_causal=True, training=False)
        plain = F.scaled_dot_product_attention(q, k, v, training=False)
        np.testing.assert_allclose(np.asarray(out._val)[0],
                                   np.asarray(causal_all._val)[0],
                                   rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(np.asarray(out._val)[1],
                                   np.asarray(plain._val)[1],
                                   rtol=2e-5, atol=2e-5)
