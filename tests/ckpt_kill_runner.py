"""Subprocess runner for the preemption-mid-save tests: dies via
PADDLE_FAULTS kill at the ckpt/write injection point DURING its second
checkpoint save, leaving a genuinely half-written newest step on disk
(fluid: the un-published .tmp payload dir; sharded: orbax's uncommitted
*.orbax-checkpoint-tmp-* step). The parent test then asserts the
newest-intact restore fallback never surfaces the half-written step.

argv: <fluid|sharded> <root>
Arms its own PADDLE_FAULTS (kill at the 2nd ckpt/write event: save #1
publishes cleanly, save #2 dies mid-write) unless the env already set
one. Prints SAVED0 after the first (intact) save.
"""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault(
    "PADDLE_FAULTS", "kill:side=ckpt,point=write,at=2,exit_code=9")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def run_fluid(root):
    import paddle_tpu.fluid as fluid
    from paddle_tpu.core.scope import Scope
    from paddle_tpu.fluid import checkpoint as ckpt
    from paddle_tpu.fluid import framework

    main, startup = fluid.Program(), fluid.Program()
    with framework.unique_name_guard(), \
            fluid.program_guard(main, startup):
        main.random_seed = startup.random_seed = 3
        x = fluid.data(name="x", shape=[-1, 4], dtype="float32")
        loss = fluid.layers.reduce_mean(
            fluid.layers.fc(input=x, size=2))
        fluid.optimizer.SGD(0.1).minimize(loss)
    scope = Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    ckpt.save_checkpoint(exe, root,
                         ckpt.TrainStatus(epoch_no=0, step_no=0),
                         main_program=main, scope=scope)
    print("SAVED0", flush=True)
    # train one step so the second snapshot differs, then die mid-save
    exe.run(main, feed={"x": np.ones((2, 4), "float32")},
            fetch_list=[loss], scope=scope)
    ckpt.save_checkpoint(exe, root,
                         ckpt.TrainStatus(epoch_no=0, step_no=1),
                         main_program=main, scope=scope)
    print("UNREACHABLE", flush=True)


def run_sharded(root):
    from paddle_tpu.distributed.sharded_checkpoint import \
        ShardedCheckpointManager

    mgr = ShardedCheckpointManager(root, max_to_keep=3)
    # ~4MB payload: orbax's async commit comfortably outlives the
    # os._exit fired at the ckpt/write hook right after save() returns
    tree = {"w": np.full((1 << 20,), 1.0, np.float32),
            "step": np.asarray([0], np.int64)}
    mgr.save(0, tree, wait=True)
    print("SAVED0", flush=True)
    tree2 = {"w": np.full((1 << 20,), 2.0, np.float32),
             "step": np.asarray([1], np.int64)}
    mgr.save(1, tree2, wait=True)
    print("UNREACHABLE", flush=True)


if __name__ == "__main__":
    mode, root = sys.argv[1], sys.argv[2]
    (run_fluid if mode == "fluid" else run_sharded)(root)
    sys.exit(3)  # the kill must have fired during the second save
