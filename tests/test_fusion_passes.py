"""fuse_elewise_add_act / fuse_bn_act BuildStrategy knobs as real
rewrites (reference: ir/fuse_elewise_add_act_pass.cc,
ir/fuse_bn_act_pass.cc). Training parity must be exact: the rewrites
run before lowering, so jax.vjp differentiates the fused forward like
the composition."""
import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework
from paddle_tpu.core import scope as scope_mod


def _fresh():
    framework.switch_main_program(framework.Program())
    framework.switch_startup_program(framework.Program())
    scope_mod._global_scope = scope_mod.Scope()


def _build_residual_conv(seed=9):
    main = framework.default_main_program()
    st = framework.default_startup_program()
    main.random_seed = st.random_seed = seed
    img = fluid.layers.data("image", shape=[3, 8, 8], dtype="float32")
    y = fluid.layers.data("y", shape=[1], dtype="int64")
    h = fluid.layers.conv2d(img, 4, 3, padding=1, bias_attr=False)
    h = fluid.layers.batch_norm(h)
    h = fluid.layers.relu(h)          # bn -> relu pair
    res = fluid.layers.conv2d(h, 4, 3, padding=1, bias_attr=False)
    h = fluid.layers.relu(fluid.layers.elementwise_add(h, res))  # add->relu
    h = fluid.layers.pool2d(h, pool_type="avg", global_pooling=True)
    logits = fluid.layers.fc(h, size=3)
    loss = fluid.layers.mean(
        fluid.layers.loss.softmax_with_cross_entropy(logits, y))
    fluid.optimizer.MomentumOptimizer(0.02, momentum=0.9).minimize(loss)
    return loss


def _steps(loss, compiled=None, n=4):
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(framework.default_startup_program())
    r = np.random.RandomState(0)
    feed = {"image": r.randn(8, 3, 8, 8).astype("float32"),
            "y": r.randint(0, 3, (8, 1)).astype("int64")}
    tgt = compiled if compiled is not None else \
        framework.default_main_program()
    return [float(np.asarray(exe.run(tgt, feed=feed,
                                     fetch_list=[loss])[0]).ravel()[0])
            for _ in range(n)]


def test_fusion_passes_training_parity():
    _fresh()
    with framework.unique_name_guard():
        loss = _build_residual_conv()
        base = _steps(loss)

    _fresh()
    with framework.unique_name_guard():
        loss2 = _build_residual_conv()
        prog = framework.default_main_program()
        from paddle_tpu.fluid.fusion_passes import (fuse_bn_act,
                                                    fuse_elewise_add_act)

        n_ew = fuse_elewise_add_act(prog)
        n_bn = fuse_bn_act(prog)
        assert n_ew >= 1 and n_bn >= 1, (n_ew, n_bn)
        types = [op.type for op in prog.global_block().ops]
        assert "fused_elemwise_activation" in types
        assert any(op.type == "batch_norm" and op.attrs.get("fused_act")
                   for op in prog.global_block().ops)
        got = _steps(loss2)
    np.testing.assert_allclose(got, base, rtol=1e-5, atol=1e-6)


def test_fetched_intermediate_blocks_fusion():
    """Fetching the BN pre-activation (or the add's intermediate) must
    keep those vars producible — the pass skips such pairs."""
    _fresh()
    with framework.unique_name_guard():
        main = framework.default_main_program()
        st = framework.default_startup_program()
        main.random_seed = st.random_seed = 9
        img = fluid.layers.data("image", shape=[3, 8, 8],
                                dtype="float32")
        h = fluid.layers.conv2d(img, 4, 3, padding=1, bias_attr=False)
        pre_act = fluid.layers.batch_norm(h)
        out = fluid.layers.relu(pre_act)
        loss = fluid.layers.mean(out)
        bs = fluid.BuildStrategy()
        bs.fuse_bn_act_ops = True
        compiled = fluid.CompiledProgram(main, build_strategy=bs)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(st)
        r = np.random.RandomState(0)
        feed = {"image": r.randn(2, 3, 8, 8).astype("float32")}
        # fetching the pre-activation: fusion must be skipped and BOTH
        # fetches must come back
        pre, got = exe.run(compiled, feed=feed,
                           fetch_list=[pre_act, loss])
        assert np.isfinite(np.asarray(pre)).all()
        assert np.isfinite(np.asarray(got)).all()
        assert not any(op.attrs.get("fused_act")
                       for op in main.global_block().ops
                       if op.type == "batch_norm")


def test_conv_bn_fuse_skips_relu_fused_bn():
    """inference conv_bn_fuse must not fold a BN carrying a fused relu
    (the fold would drop the activation)."""
    from paddle_tpu.fluid.fusion_passes import fuse_bn_act
    from paddle_tpu.inference.passes import conv_bn_fuse
    from paddle_tpu.core.scope import global_scope

    _fresh()
    with framework.unique_name_guard():
        main = framework.default_main_program()
        st = framework.default_startup_program()
        img = fluid.layers.data("image", shape=[3, 8, 8],
                                dtype="float32")
        h = fluid.layers.conv2d(img, 4, 3, padding=1, bias_attr=False)
        h = fluid.layers.batch_norm(h, is_test=True)
        fluid.layers.relu(h)
        assert fuse_bn_act(main) == 1
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(st)
        assert conv_bn_fuse(main, global_scope()) == 0


def test_build_strategy_knobs_drive_fusion():
    _fresh()
    with framework.unique_name_guard():
        loss = _build_residual_conv()
        prog = framework.default_main_program()
        bs = fluid.BuildStrategy()
        bs.fuse_elewise_add_act_ops = True
        bs.fuse_bn_act_ops = True
        compiled = fluid.CompiledProgram(prog, build_strategy=bs)
        ls = _steps(loss, compiled=compiled)
        assert np.isfinite(ls).all()
        types = [op.type for op in prog.global_block().ops]
        assert "fused_elemwise_activation" in types
        assert any(op.type == "batch_norm" and op.attrs.get("fused_act")
                   for op in prog.global_block().ops)


def test_fetch_after_fusion_names_the_knob():
    """A later run fetching a fuse_bn_act-removed intermediate must get
    an error naming BuildStrategy.fuse_bn_act_ops, not lowering's
    generic 'never computed' (ADVICE r4)."""
    import pytest

    _fresh()
    main = framework.default_main_program()
    st = framework.default_startup_program()
    main.random_seed = st.random_seed = 3
    img = fluid.layers.data("image", shape=[3, 8, 8], dtype="float32")
    h = fluid.layers.conv2d(img, 4, 3, padding=1, bias_attr=False)
    bn = fluid.layers.batch_norm(h)
    out = fluid.layers.relu(bn)
    total = fluid.layers.reduce_sum(out)

    bs = fluid.BuildStrategy()
    bs.fuse_bn_act_ops = True
    cp = fluid.CompiledProgram(main, build_strategy=bs)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(st)
    feed = {"image": np.zeros((2, 3, 8, 8), "float32")}
    exe.run(cp, feed=feed, fetch_list=[total])  # first run fuses
    with pytest.raises(RuntimeError, match="fuse_bn_act_ops"):
        exe.run(cp, feed=feed, fetch_list=[bn])
