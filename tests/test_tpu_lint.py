"""tpu-lint: the static SPMD verifier (paddle_tpu/analysis).

Seeded-defect fixtures — each checker must trip with the expected
severity AND op/var location (the checkers themselves are the
regression surface): a rank-divergent collective schedule (checker 1),
a read-after-donate (checker 2), a fetch inside a scan body (checker
3), a non-zeroed padding slot / tampered shard layout (checker 4), a
drifted dtype contract + silent fp64 promotion (checker 5). Plus: the
`FLAGS_tpu_static_checks` Executor compile-time hook (error raises
BEFORE dispatch, warn warns, clean programs pass under =error), the
`collective_byte_census` region coverage for switch_case /
conditional_block collectives, the `_block_host_op_kinds` any-depth
recursion contract, and the exemplar lint-regression harness
(tools/tpu_lint.py: BERT-tiny DP step — plain and bf16 AMP + ZeRO-2
bucketed masters — resnet scan, 2-rank sync-PS — zero errors,
standing). Checker 6 (zero2-lifetimes) seeded defects: a full-grad
read after scatter, a fetch of a scattered grad, an early-flushed
pending bucket; dtype-contract gains redundant-cast round-trip
fixtures and the AMP-policy suppressions.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu import analysis
from paddle_tpu.fluid import framework, lowering
from paddle_tpu.fluid.framework import Operator
from paddle_tpu.utils.flags import get_flag, set_flags

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _restore_flags():
    keys = ("FLAGS_tpu_donate_buffers", "FLAGS_tpu_donate_feed_buffers",
            "FLAGS_tpu_static_checks", "FLAGS_tpu_sharded_weight_update",
            "FLAGS_tpu_comm_bucket_mb")
    old = {k: get_flag(k) for k in keys}
    yield
    set_flags(old)


def _mlp_loss(width=8, classes=4):
    img = fluid.layers.data(name="img", shape=[width], dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    h = fluid.layers.fc(input=img, size=8, act="relu")
    logits = fluid.layers.fc(input=h, size=classes)
    return fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(logits, label))


def _batch(width=8, n=16):
    r = np.random.RandomState(0)
    return {"img": r.rand(n, width).astype("float32"),
            "label": r.randint(0, 4, (n, 1)).astype("int64")}


def _bwd_idx(block):
    return next(i for i, op in enumerate(block.ops)
                if op.type == "backward")


# ---------------------------------------------------------------------------
# checker 1 — collective divergence
# ---------------------------------------------------------------------------

def _transpiled_program(extra_allreduce=False):
    from paddle_tpu.fleet import transpile_collective

    p, st = framework.Program(), framework.Program()
    with framework.program_guard(p, st):
        loss = _mlp_loss()
        fluid.optimizer.SGDOptimizer(0.1).minimize(loss)
    transpile_collective(p, nranks=2)
    if extra_allreduce:
        # the classic rank-conditional bug: one rank emits an extra
        # collective the others never post — a deadlock on real ICI
        g = p.global_block()
        g.ops.append(Operator(
            g, "c_allreduce_sum", inputs={"X": [loss.name]},
            outputs={"Out": [loss.name]}, attrs={"ring_id": 0}))
    return p, loss


def test_collective_schedule_records_transpiled_allreduces():
    prog, _ = _transpiled_program()
    sched = analysis.collective_schedule(prog)
    grads = [op for op in prog.global_block().ops
             if op.type == "c_allreduce_sum"]
    assert len(sched) == len(grads) >= 2
    assert all(r["kind"] == "c_allreduce_sum" and r["ring_id"] == 0
               for r in sched)
    # records carry the op location the finding would anchor to
    assert all(r["block_idx"] == 0 and r["op_idx"] >= 0 for r in sched)


def test_cross_rank_divergence_trips_with_location():
    p0, _ = _transpiled_program()
    p1, _ = _transpiled_program(extra_allreduce=True)
    fs = analysis.check_collective_divergence([p0, p1],
                                              labels=["r0", "r1"])
    assert len(fs) == 1
    f = fs[0]
    assert f.severity == "error" and f.checker == "collective-divergence"
    assert f.rank == "r1" and f.op_type == "c_allreduce_sum"
    assert "diverges" in f.message
    # identical ranks: clean
    assert not analysis.check_collective_divergence([p0, p0])
    # strict-prefix direction (r1 is MISSING the extra collective):
    # the finding still names the diverging rank, not the reference
    fs = analysis.check_collective_divergence([p1, p0],
                                              labels=["r0", "r1"])
    assert len(fs) == 1 and fs[0].rank == "r1"
    assert "<end of schedule>" in fs[0].message


def _program_with_barrier(group_world, group_ranks, nranks=None):
    """A transpiled DP program plus one host-tier barrier whose
    HostCollectiveGroup membership lives in op attrs."""
    p, loss = _transpiled_program()
    g = p.global_block()
    attrs = {"ring_id": 0, "group_world": group_world,
             "group_ranks": list(group_ranks)}
    if nranks is not None:
        attrs["nranks"] = nranks
    g.ops.append(Operator(g, "barrier", inputs={"X": [loss.name]},
                          outputs={}, attrs=attrs))
    return p


def test_divergent_host_group_membership_trips():
    """Seeded defect: two ranks agree on every opcode/dtype/shape AND
    ring_id, but the HostCollectiveGroup behind the barrier spans 2
    ranks on one and 3 on the other — rank 0 waits forever on the
    phantom member. ring_id-only comparison called this clean (the
    carried-over false negative); membership modeling must trip it."""
    p0 = _program_with_barrier(2, [0, 1])
    p1 = _program_with_barrier(3, [0, 1, 2])
    fs = analysis.check_collective_divergence([p0, p1],
                                              labels=["r0", "r1"])
    assert len(fs) == 1
    f = fs[0]
    assert f.severity == "error" and f.checker == "collective-divergence"
    assert f.rank == "r1" and f.op_type == "barrier"
    # identical membership: clean
    assert not analysis.check_collective_divergence(
        [p0, _program_with_barrier(2, [0, 1])])
    # membership signature is part of the schedule record itself
    rec = analysis.collective_schedule(p0)[-1]
    assert rec["kind"] == "barrier"
    assert ("world", 2) in rec["group"] and \
        ("ranks", (0, 1)) in rec["group"]


def test_divergent_nranks_membership_trips():
    """Same ring_id, different `nranks` on a sized device collective
    (a c_allgather transpiled against different world sizes) must
    diverge too; ops without any membership attrs keep the
    pre-existing ring_id-only behavior (group=None)."""
    p0 = _program_with_barrier(2, [0, 1], nranks=2)
    p1 = _program_with_barrier(2, [0, 1], nranks=4)
    fs = analysis.check_collective_divergence([p0, p1])
    assert len(fs) == 1 and fs[0].severity == "error"
    plain, _ = _transpiled_program()
    assert all(r["group"] is None
               for r in analysis.collective_schedule(plain))


def test_branch_collective_divergence():
    from paddle_tpu.fluid.layers.collective import _c_allreduce

    x = fluid.layers.data(name="x", shape=[4], dtype="float32")
    pred = fluid.layers.reduce_mean(x) > 0.0
    fluid.layers.cond(pred,
                      lambda: _c_allreduce(x, reduce_type="sum"),
                      lambda: x)
    prog = fluid.default_main_program()
    fs = analysis.check_branch_uniformity(prog)
    assert len(fs) == 1 and fs[0].severity == "error"
    assert fs[0].op_type == "cond" and fs[0].block_idx == 0


def test_branch_collective_nesting_divergence():
    """A collective inside a while body in one branch repeats per
    iteration; a bare one in the other branch fires once — flattening
    the loop away would compare them equal (deadlock-class false
    negative), so the branch keys must keep the region nesting."""
    x = fluid.layers.data(name="x", shape=[4], dtype="float32")
    prog = fluid.default_main_program()
    blk = prog.global_block()
    t_blk = prog._create_block()
    w_body = prog._create_block()
    w_body.append_op(type="c_allreduce_sum", inputs={"X": [x.name]},
                     outputs={"Out": [x.name]}, attrs={"ring_id": 0})
    prog._rollback()
    t_blk.append_op(type="while", inputs={}, outputs={},
                    attrs={"sub_block": w_body.idx})
    prog._rollback()
    f_blk = prog._create_block()
    f_blk.append_op(type="c_allreduce_sum", inputs={"X": [x.name]},
                    outputs={"Out": [x.name]}, attrs={"ring_id": 0})
    prog._rollback()
    blk.append_op(type="cond", inputs={}, outputs={},
                  attrs={"sub_block_t": t_blk.idx,
                         "sub_block_f": f_blk.idx})
    fs = analysis.check_branch_uniformity(prog)
    assert len(fs) == 1 and fs[0].severity == "error"
    assert fs[0].op_type == "cond"


def test_branch_identical_schedules_clean():
    from paddle_tpu.fluid.layers.collective import _c_allreduce

    x = fluid.layers.data(name="x", shape=[4], dtype="float32")
    pred = fluid.layers.reduce_mean(x) > 0.0
    fluid.layers.cond(pred,
                      lambda: _c_allreduce(x, reduce_type="sum"),
                      lambda: _c_allreduce(x * 2.0, reduce_type="sum"))
    assert not analysis.check_branch_uniformity(
        fluid.default_main_program())


_HLO_A = """\
module {
  %0 = "stablehlo.all_reduce"(%arg0) ({
    ^bb0(%a: tensor<f32>, %b: tensor<f32>):
    "stablehlo.return"(%a) : (tensor<f32>) -> ()
  }) {replica_groups = dense<[[0, 1]]>} : (tensor<8xf32>) -> tensor<8xf32>
  %1 = "stablehlo.all_gather"(%0) {replica_groups = dense<[[0, 1]]>} : (tensor<4xf32>) -> tensor<8xf32>
}
"""


def test_hlo_schedule_and_cross_rank_divergence():
    sched = analysis.hlo_collective_schedule(_HLO_A)
    assert [r["kind"] for r in sched] == ["all_reduce", "all_gather"]
    assert sched[0]["type"] == "8xf32"
    assert "0, 1" in sched[0]["replica_groups"]
    assert not analysis.check_hlo_divergence([_HLO_A, _HLO_A])
    # rank 1 lowered to a different schedule (missing the gather)
    hlo_b = _HLO_A.replace("all_gather", "all_reduce")
    fs = analysis.check_hlo_divergence([_HLO_A, hlo_b],
                                       labels=["r0", "r1"])
    assert len(fs) == 1 and fs[0].severity == "error"


# ---------------------------------------------------------------------------
# checker 2 — donation use-after-donate
# ---------------------------------------------------------------------------

def _seeded_read_after_donate():
    """A fetch op holds the param's buffer BEFORE its in-place sgd
    rebind: under state-buffer donation the fetched array observes the
    updated bytes."""
    loss = _mlp_loss()
    fluid.optimizer.SGDOptimizer(0.1).minimize(loss)
    prog = fluid.default_main_program()
    blk = prog.global_block()
    w = prog.all_parameters()[0].name
    blk.ops.insert(_bwd_idx(blk) + 1, Operator(
        blk, "fetch", inputs={"X": [w]}, outputs={}, attrs={}))
    return prog, loss, w


def test_read_after_donate_trips_at_the_rebinding_op():
    prog, _, w = _seeded_read_after_donate()
    fs = analysis.check_donation_safety(prog)
    errs = [f for f in fs if f.severity == "error"]
    assert len(errs) == 1
    f = errs[0]
    assert f.checker == "donation-safety" and f.var == w
    assert f.op_type == "sgd"  # located at the donated (in-place) use
    assert "read-after-donate" in f.message
    # donation off: the buffer is never aliased — no hazard
    set_flags({"FLAGS_tpu_donate_buffers": False})
    assert not analysis.check_donation_safety(prog)


def test_read_after_donate_inside_loop_body():
    """A fetch buried in a scan body holding a donated param that the
    body rebinds per iteration: iteration i's held buffer is clobbered
    by iteration i+1's in-place update — the walk must descend into
    sub-blocks (and replay loop bodies) to see it."""
    H = 4
    x = fluid.layers.data(name="x", shape=[H], dtype="float32")
    w = fluid.layers.create_parameter(shape=[H, H], dtype="float32",
                                      name="loop.w")
    h = fluid.layers.fc(x, size=H)
    scan = fluid.layers.Scan(n=2)
    with scan.block():
        sub = fluid.default_main_program().current_block()
        sub.append_op(type="fetch", inputs={"X": [w]}, outputs={},
                      attrs={})
        nh = fluid.layers.relu(fluid.layers.matmul(h, w))
        sub.append_op(type="scale", inputs={"X": [w]},
                      outputs={"Out": [w]}, attrs={"scale": 0.5})
        fluid.layers.assign(nh, output=h)
    fluid.layers.mean(h)
    prog = fluid.default_main_program()
    fs = analysis.check_donation_safety(prog)
    errs = [f for f in fs if f.severity == "error"]
    assert len(errs) == 1 and errs[0].var == "loop.w"
    assert errs[0].op_type == "scale"  # the rebinding actor, in-loop
    # the location names the sub-block op, not the enclosing scan
    sub_idx = errs[0].block_idx
    assert sub_idx >= 1
    assert prog.block(sub_idx).ops[errs[0].op_idx].type == "scale"


def test_executor_hook_error_does_not_cache_the_bad_entry():
    """A caught-and-retried run must re-check, not cache-hit past the
    lint and dispatch the known-bad program."""
    prog, loss, _ = _seeded_read_after_donate()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    set_flags({"FLAGS_tpu_static_checks": "error"})
    for _ in range(2):  # the second run is the regression
        with pytest.raises(RuntimeError, match="read-after-donate"):
            exe.run(prog, feed=_batch(), fetch_list=[loss])


def test_feed_overwrite_warning():
    x = fluid.layers.data(name="x", shape=[4], dtype="float32")
    prog = fluid.default_main_program()
    blk = prog.global_block()
    blk.append_op(type="scale", inputs={"X": [x]}, outputs={"Out": [x]},
                  attrs={"scale": 2.0})
    fs = analysis.check_donation_safety(prog, feed_names=["x"])
    assert [f.severity for f in fs] == ["warning"]
    assert fs[0].var == "x" and "overwrites feed var" in fs[0].message


def test_cross_check_donation_report():
    report = {"mut_bytes": 1024, "alias_bytes": 0,
              "aliases_state": False}
    fs = analysis.cross_check_donation_report([], report)
    assert len(fs) == 1 and fs[0].severity == "warning"
    assert "disengaged" in fs[0].message
    ok = {"mut_bytes": 1024, "alias_bytes": 1024, "aliases_state": True}
    assert not analysis.cross_check_donation_report([], ok)
    assert not analysis.cross_check_donation_report([], None)


def test_cross_check_against_live_donation_report():
    """The dynamic side of the cross-check: a clean program's compiled
    executable really does alias its donated state."""
    loss = _mlp_loss()
    fluid.optimizer.SGDOptimizer(0.1).minimize(loss)
    prog = fluid.default_main_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    feed = _batch()
    exe.run(prog, feed=feed, fetch_list=[loss])
    rep = exe.donation_report(prog, feed=feed, fetch_list=[loss])
    assert rep is not None and rep["aliases_state"]
    fs = analysis.check_donation_safety(prog, feed_names=list(feed),
                                        fetch_names=[loss.name])
    assert not fs
    assert not analysis.cross_check_donation_report(fs, rep)


# ---------------------------------------------------------------------------
# checker 3 — host sync in hot loops
# ---------------------------------------------------------------------------

def _seeded_fetch_in_scan():
    H = 4
    x = fluid.layers.data(name="x", shape=[H], dtype="float32")
    w = fluid.layers.create_parameter(shape=[2, H, H], dtype="float32",
                                      name="lint.w")
    h = fluid.layers.fc(x, size=H)
    scan = fluid.layers.Scan(n=2)
    with scan.block():
        wi = scan.slice_input(w)
        nh = fluid.layers.relu(fluid.layers.matmul(h, wi))
        sub = fluid.default_main_program().current_block()
        sub.append_op(type="fetch", inputs={"X": [nh]}, outputs={},
                      attrs={})
        fluid.layers.Print(nh)
        fluid.layers.assign(nh, output=h)
    return fluid.default_main_program(), h


def test_fetch_in_scan_body_is_an_error_print_a_warning():
    prog, _ = _seeded_fetch_in_scan()
    fs = analysis.check_host_sync(prog)
    fetch = [f for f in fs if f.op_type == "fetch"]
    assert len(fetch) == 1 and fetch[0].severity == "error"
    assert fetch[0].block_idx == 1  # inside the scan sub-block
    assert "every iteration" in fetch[0].message
    prints = [f for f in fs if f.op_type == "print"]
    assert len(prints) == 1 and prints[0].severity == "warning"
    assert "pure_callback" in prints[0].message


def test_rpc_marker_in_while_body_is_an_error():
    one = fluid.layers.fill_constant([1], "int64", 1)
    i = fluid.layers.fill_constant([1], "int64", 0)
    n = fluid.layers.fill_constant([1], "int64", 3)
    c = fluid.layers.less_than(i, n)
    w = fluid.layers.While(c)
    with w.block():
        sub = fluid.default_main_program().current_block()
        sub.append_op(type="send", inputs={"X": [i]}, outputs={},
                      attrs={"endpoints": ["127.0.0.1:6174"]})
        fluid.layers.assign(i + one, output=i)
        fluid.layers.less_than(i, n, cond=c)
    fs = analysis.check_host_sync(fluid.default_main_program())
    send = [f for f in fs if f.op_type == "send"]
    assert len(send) == 1 and send[0].severity == "error"


def test_dynamic_shape_op_severity_by_loop_depth():
    prog = fluid.default_main_program()
    blk = prog.global_block()
    x = fluid.layers.data(name="x", shape=[4, 6], dtype="float32")
    blk.append_op(type="multiclass_nms",
                  inputs={"BBoxes": [x], "Scores": [x]},
                  outputs={"Out": [blk.create_var(
                      name="nms.out", shape=(-1, 6),
                      dtype="float32")]},
                  attrs={})
    fs = analysis.check_host_sync(prog)
    assert [f.severity for f in fs] == ["warning"]
    assert "unjitted" in fs[0].message
    # the same op inside a scan body: the whole block goes eager
    # EVERY step — error
    sub = prog._create_block()
    sub.append_op(type="multiclass_nms",
                  inputs={"BBoxes": [x], "Scores": [x]},
                  outputs={"Out": [sub.create_var(
                      name="nms.out2", shape=(-1, 6),
                      dtype="float32")]},
                  attrs={})
    prog._rollback()
    blk.append_op(type="scan", inputs={}, outputs={},
                  attrs={"sub_block": sub.idx, "n": 2})
    fs = analysis.check_host_sync(prog)
    assert sorted(f.severity for f in fs) == ["error", "warning"]


def test_block_host_op_kinds_recurses_to_any_depth():
    """Satellite audit of lowering._block_host_op_kinds: a host op
    buried inside a cond inside a while must still be found (checker 3
    and the jit/eager lowering split both depend on it)."""
    one = fluid.layers.fill_constant([1], "int64", 1)
    i = fluid.layers.fill_constant([1], "int64", 0)
    n = fluid.layers.fill_constant([1], "int64", 3)
    c = fluid.layers.less_than(i, n)
    w = fluid.layers.While(c)
    with w.block():
        pred = fluid.layers.less_than(i, one)
        fluid.layers.cond(pred,
                          lambda: fluid.layers.Print(i),
                          lambda: i)
        fluid.layers.assign(i + one, output=i)
        fluid.layers.less_than(i, n, cond=c)
    block = fluid.default_main_program().global_block()
    host, dynamic = lowering._block_host_op_kinds(block)
    assert host and not dynamic
    # and the checker locates it at depth 2 (while -> cond branch)
    fs = analysis.check_host_sync(fluid.default_main_program())
    prints = [f for f in fs if f.op_type == "print"]
    assert prints and prints[0].severity == "warning"
    assert prints[0].block_idx >= 2


# ---------------------------------------------------------------------------
# checker 4 — ZeRO-1 planner invariants
# ---------------------------------------------------------------------------

def _planned_dp_program():
    from paddle_tpu.parallel import sharded_update as su

    loss = _mlp_loss()
    fluid.optimizer.AdamOptimizer(1e-3).minimize(loss)
    prog = fluid.default_main_program()
    fluid.CompiledProgram(prog).with_data_parallel(loss_name=loss.name)
    plan = su.plan_sharded_update(prog, prog.global_block(), 8, "dp")
    assert plan is not None
    prog._shard_plan = plan
    return prog, plan


def test_valid_plan_is_clean():
    prog, _ = _planned_dp_program()
    assert not analysis.check_shard_plan(prog)


def test_non_zeroed_padding_slot_trips():
    """An op without a shard-aware re-zeroing rule inserted AFTER
    planning: its output can carry nonzero values in the flat-buffer
    padding slots straight into the optimizer."""
    prog, plan = _planned_dp_program()
    blk = prog.global_block()
    g = sorted(plan.grad_names)[0]
    idx = _bwd_idx(blk) + 1
    blk.ops.insert(idx, Operator(
        blk, "elementwise_pow", inputs={"X": [g], "Y": [g]},
        outputs={"Out": [g]}, attrs={}))
    fs = analysis.check_shard_plan(prog)
    assert len(fs) == 1
    f = fs[0]
    assert f.severity == "error" and f.op_type == "elementwise_pow"
    assert f.op_idx == idx and f.var == g
    assert "not provably zeroed" in f.message


def test_broadcasting_elementwise_after_planning_trips():
    """The planner DECLINES programs whose elementwise binary ops
    broadcast mismatched non-scalar operands over a sharded grad (no
    flat-shard analogue); the checker must mirror that rule, or a
    program mutated this way after planning lints clean and then
    mis-broadcasts at shard-space trace time."""
    prog, plan = _planned_dp_program()
    blk = prog.global_block()
    g = next(n for n in sorted(plan.grad_names)
             if int(np.prod(blk._find_var_recursive(n).shape)) > 8)
    vec = blk.create_var(name="lint.bcast.vec", shape=(8,),
                         dtype="float32")
    idx = _bwd_idx(blk) + 1
    blk.ops.insert(idx, Operator(
        blk, "elementwise_mul", inputs={"X": [g], "Y": [vec.name]},
        outputs={"Out": [g]}, attrs={"axis": 0}))
    fs = analysis.check_shard_plan(prog)
    assert len(fs) == 1
    f = fs[0]
    assert f.severity == "error" and f.op_type == "elementwise_mul"
    assert f.op_idx == idx and f.var == g
    assert "no flat-shard analogue" in f.message
    # and the planner really does decline the mutated program
    from paddle_tpu.parallel import sharded_update as su
    assert su.plan_sharded_update(prog, blk, 8, "dp") is None


def test_tampered_shard_layout_trips():
    prog, plan = _planned_dp_program()
    name, info = sorted(plan.sharded_state.items())[0]
    info.shape = tuple(d + 1 for d in info.shape)
    fs = analysis.check_shard_plan(prog)
    assert any(f.severity == "error" and f.var == name
               and "save" in f.message.lower() for f in fs)


def test_mixed_dtype_bucket_trips():
    from paddle_tpu.parallel.sharded_update import (BucketEntry,
                                                    GradBucket)

    prog, plan = _planned_dp_program()
    e32 = BucketEntry("g32", "p32", "p32", (8,), "float32", 8, 0)
    e16 = BucketEntry("g16", "p16", "p16", (8,), "bfloat16", 8, 1)
    plan.buckets = (GradBucket(0, [e32, e16]),)
    fs = analysis.check_shard_plan(prog)
    assert any(f.severity == "error" and "mixes dtypes" in f.message
               for f in fs)


def test_misaligned_bucket_padding_trips():
    from paddle_tpu.parallel.sharded_update import (BucketEntry,
                                                    GradBucket)

    prog, plan = _planned_dp_program()
    e = BucketEntry("g", "p", "p", (9,), "float32", 8, 0)
    e.padded = 9  # not a multiple of ndev=8
    plan.buckets = (GradBucket(0, [e]),)
    fs = analysis.check_shard_plan(prog)
    assert any(f.severity == "error" and "misalign" in f.message
               for f in fs)


# ---------------------------------------------------------------------------
# checker 4 extension — model-sharded (tensor-parallel) vocabulary
# ---------------------------------------------------------------------------

def _planned_tp_program():
    """MLP Adam step planned by the ONE parallel planner on a
    (1, 4, 2) (dcn, ici, model) mesh: both fc weights column-parallel
    over `model`, ZeRO state over the replica axis."""
    import jax
    from jax.sharding import Mesh

    from paddle_tpu.parallel import env as penv
    from paddle_tpu.parallel import planner

    loss = _mlp_loss()
    fluid.optimizer.AdamOptimizer(1e-3).minimize(loss)
    prog = fluid.default_main_program()
    fluid.CompiledProgram(prog).with_data_parallel(loss_name=loss.name)
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(1, 4, 2),
                ("dcn", "ici", "model"))
    pplan = planner.plan_parallel(prog, prog.global_block(), mesh,
                                  penv.ICI_AXIS)
    prog._mesh = mesh
    prog._tp_plan = pplan.tp_plan
    prog._shard_plan = pplan.shard_plan
    assert pplan.tp_plan is not None and pplan.tp_plan.params
    return prog, pplan.tp_plan


def test_model_sharded_plan_is_clean():
    prog, _ = _planned_tp_program()
    assert not analysis.check_shard_plan(prog)


def test_model_sharded_norm_reader_trips():
    """A global-norm reader over a model-sharded grad inserted after
    planning: each model member holds a DISTINCT shard, so the norm
    would mix partial sums without a model-axis psum."""
    prog, tpp = _planned_tp_program()
    blk = prog.global_block()
    g = sorted(tpp.params)[0] + "@GRAD"
    out = blk.create_var(name="lint.tp.norm", shape=(1,),
                         dtype="float32")
    idx = _bwd_idx(blk) + 1
    blk.ops.insert(idx, Operator(
        blk, "squared_l2_norm", inputs={"X": [g]},
        outputs={"Out": [out.name]}, attrs={}))
    fs = analysis.check_shard_plan(prog)
    errs = [f for f in fs if f.severity == "error"]
    assert len(errs) == 1
    f = errs[0]
    assert f.checker == "zero1-invariants"
    assert f.op_type == "squared_l2_norm" and f.op_idx == idx
    assert f.var == g and "model-sharded" in f.message


def test_model_sharded_collective_trips():
    """A raw allreduce over a model-sharded grad would average
    DISTINCT shards together — grad sync belongs on (dcn, replica)."""
    prog, tpp = _planned_tp_program()
    blk = prog.global_block()
    g = sorted(tpp.params)[0] + "@GRAD"
    idx = _bwd_idx(blk) + 1
    blk.ops.insert(idx, Operator(
        blk, "c_allreduce_sum", inputs={"X": [g]},
        outputs={"Out": [g]}, attrs={"ring_id": 0}))
    fs = analysis.check_shard_plan(prog)
    errs = [f for f in fs if f.severity == "error"]
    # the classic ZeRO padding walk flags the same op (no re-zeroing
    # rule) — BOTH findings must land, on the same op
    assert errs and all(f.op_type == "c_allreduce_sum" for f in errs)
    assert any("DISTINCT shards" in f.message for f in errs)


def test_model_sharded_unknown_op_trips():
    """Any op outside the shard-space vocabulary touching a TP'd var
    post-backward: inside shard_map the value is one member's LOCAL
    block, not the logical tensor."""
    prog, tpp = _planned_tp_program()
    blk = prog.global_block()
    p = sorted(tpp.params)[0]
    out = blk.create_var(name="lint.tp.mm", shape=(8, 8),
                         dtype="float32")
    idx = _bwd_idx(blk) + 1
    blk.ops.insert(idx, Operator(
        blk, "matmul", inputs={"X": [p], "Y": [p]},
        outputs={"Out": [out.name]}, attrs={}))
    fs = analysis.check_shard_plan(prog)
    errs = [f for f in fs if f.severity == "error"]
    assert len(errs) == 1
    assert errs[0].op_type == "matmul"
    assert "without a shard-space rule" in errs[0].message


def test_model_sharded_tp_local_layout_tamper_trips():
    """A TP'd ShardInfo whose local shape no longer derives from
    (logical_shape, tp_dim, mp) would make the model-major flat
    restore reassemble a wrong tensor."""
    prog, tpp = _planned_tp_program()
    plan = prog._shard_plan
    name, info = next((n, i) for n, i in plan.sharded_state.items()
                      if getattr(i, "tp_dim", None) is not None)
    info.tp_dim = len(info.logical_shape)  # out of range
    fs = analysis.check_shard_plan(prog)
    assert any(f.severity == "error" and f.var == name
               and "reassemble" in f.message for f in fs)


def test_hierarchical_groups_model_axis_grammar():
    """check_hierarchical_groups on a model-parallel mesh (ici=2,
    mp=2, pod=4): within-pod groups must be one model block, one
    member per model block, or the full pod — a partial span would
    average DISTINCT TP shards."""
    tmpl = ('%%0 = "stablehlo.all_reduce"(%%a) {replica_groups = '
            'dense<%s> : tensor<%s>} : '
            '(tensor<4xf32>) -> tensor<4xf32>')
    legal = [
        ("[[0, 1], [2, 3]]", "2x2xi64"),    # model blocks
        ("[[0, 2], [1, 3]]", "2x2xi64"),    # replica axis
        ("[[0, 1, 2, 3]]", "1x4xi64"),      # full pod
    ]
    for groups, shape in legal:
        hlo = tmpl % (groups, shape)
        assert analysis.check_hierarchical_groups(
            hlo, 2, ndev=8, mp_size=2) == [], groups
    mixed = tmpl % ("[[0, 1, 2], [1, 2, 3]]", "2x3xi64")
    fs = analysis.check_hierarchical_groups(mixed, 2, ndev=8,
                                            mp_size=2)
    assert len(fs) == 1 and fs[0].severity == "error"
    assert "MODEL/REPLICA-mixed" in fs[0].message
    # mp grammar applies on the single-pod (dcn=1) TP mesh too: the
    # world is exactly one pod, no cross-pod tier to hide behind
    fs1 = analysis.check_hierarchical_groups(mixed, 2, ndev=4,
                                             mp_size=2)
    assert any("MODEL/REPLICA-mixed" in f.message for f in fs1)


# ---------------------------------------------------------------------------
# checker 6 — ZeRO-2 gradient lifetimes
# ---------------------------------------------------------------------------

def test_zero2_valid_plan_is_clean():
    prog, _ = _planned_dp_program()
    assert not analysis.check_zero2_lifetimes(prog)


def test_zero2_full_grad_read_after_scatter_trips():
    """An op without a shard-space rule reading a scattered gradient
    (inserted after planning) would all_gather the full buffer back —
    the ZeRO-2 lifetime violation, located at the offending op."""
    prog, plan = _planned_dp_program()
    blk = prog.global_block()
    g = sorted(plan.grad_names)[0]
    out = blk.create_var(name="lint.zero2.out", shape=(1,),
                         dtype="float32")
    idx = _bwd_idx(blk) + 1
    blk.ops.insert(idx, Operator(
        blk, "elementwise_pow", inputs={"X": [g], "Y": [g]},
        outputs={"Out": [out.name]}, attrs={}))
    fs = [f for f in analysis.check_zero2_lifetimes(prog)]
    assert len(fs) == 1
    f = fs[0]
    assert f.severity == "error" and f.op_type == "elementwise_pow"
    assert f.op_idx == idx and f.var == g
    assert "all_gather the full gradient" in f.message


def test_zero2_broadcasting_elementwise_after_planning_trips():
    """The elementwise vocabulary is shard-safe only for same-shape /
    scalar operands — a post-planning broadcast over a scattered grad
    must trip here too (mirrors the planner's and checker 4's decline),
    or a standalone zero2 run would bless a program whose shard-space
    lowering mis-broadcasts."""
    prog, plan = _planned_dp_program()
    blk = prog.global_block()
    g = next(n for n in sorted(plan.grad_names)
             if int(np.prod(blk._find_var_recursive(n).shape)) > 8)
    vec = blk.create_var(name="lint.zero2.bcast", shape=(8,),
                         dtype="float32")
    idx = _bwd_idx(blk) + 1
    blk.ops.insert(idx, Operator(
        blk, "elementwise_mul", inputs={"X": [g], "Y": [vec.name]},
        outputs={"Out": [g]}, attrs={"axis": 0}))
    fs = analysis.check_zero2_lifetimes(prog)
    assert len(fs) == 1
    f = fs[0]
    assert f.severity == "error" and f.op_type == "elementwise_mul"
    assert f.op_idx == idx and f.var == g
    assert "no flat-shard analogue" in f.message


def test_zero2_fetch_of_scattered_grad_warns():
    prog, plan = _planned_dp_program()
    g = sorted(plan.grad_names)[0]
    fs = analysis.check_zero2_lifetimes(prog, fetch_names=[g])
    assert len(fs) == 1
    assert fs[0].severity == "warning" and fs[0].var == g
    assert "gathers the FULL buffer" in fs[0].message


def test_zero2_pending_bucket_early_flush_warns():
    """Explicit-sync bucketed programs: an op reading a grad whose
    bucket is still pending forces a partial early flush — the bucket's
    full grads die in pieces."""
    from paddle_tpu import fleet
    from paddle_tpu.parallel import sharded_update as su

    loss = _mlp_loss()
    fluid.optimizer.SGDOptimizer(0.1).minimize(loss)
    prog = fluid.default_main_program()
    fleet.transpile_collective(prog, nranks=8)
    blk = prog.global_block()
    set_flags({"FLAGS_tpu_comm_bucket_mb": 1000.0,
               "FLAGS_tpu_sharded_weight_update": True})
    plan = su.plan_sharded_update(prog, blk, 8, "dp")
    assert plan is not None and plan.explicit_sync and plan.buckets
    prog._shard_plan = plan
    assert not analysis.check_zero2_lifetimes(prog)  # contiguous: clean
    # wedge a reader of the FIRST allreduced grad between the pending
    # c_allreduce_sum ops
    ar_idx = [i for i, op in enumerate(blk.ops)
              if op.type == "c_allreduce_sum"]
    assert len(ar_idx) >= 2
    first_g = blk.ops[ar_idx[0]].input_names["X"][0]
    out = blk.create_var(name="lint.zero2.flush", shape=(1,),
                         dtype="float32")
    blk.ops.insert(ar_idx[0] + 1, Operator(
        blk, "squared_l2_norm", inputs={"X": [first_g]},
        outputs={"Out": [out.name]}, attrs={}))
    fs = analysis.check_zero2_lifetimes(prog)
    wedge = [f for f in fs if f.op_idx == ar_idx[0] + 1]
    assert wedge and wedge[0].severity == "warning"
    assert wedge[0].var == first_g
    assert "reduce-scatters early" in wedge[0].message
    # the remaining grads then flush partially at the optimizer's own
    # read — the checker mirrors the runtime and flags that too
    assert all(f.severity == "warning" for f in fs)


# ---------------------------------------------------------------------------
# checker 5 — dtype/shape contracts
# ---------------------------------------------------------------------------

def _planned_sparse_program(opt="adagrad"):
    from paddle_tpu.embedding import plan_sparse_tables

    ids = fluid.layers.data(name="ids", shape=[1], dtype="int64")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    emb = fluid.layers.embedding(
        ids, size=[37, 8], is_sparse=True,
        param_attr=fluid.ParamAttr(name="lint_emb"))
    logits = fluid.layers.fc(input=emb, size=4)
    loss = fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(logits, label))
    O = fluid.optimizer
    {"adagrad": lambda: O.AdagradOptimizer(0.1),
     "sgd": lambda: O.SGDOptimizer(0.1)}[opt]().minimize(loss)
    prog = fluid.default_main_program()
    fluid.CompiledProgram(prog).with_data_parallel(loss_name=loss.name)
    plan = plan_sparse_tables(prog, prog.global_block(), 8, "dp",
                              feed_names=["ids", "label"])
    assert plan is not None and "lint_emb" in plan.tables, \
        getattr(prog, "_sparse_embedding_fallback", None)
    prog._sparse_plan = plan
    return prog, plan


def test_sparse_update_valid_plan_is_clean():
    prog, _ = _planned_sparse_program()
    assert not analysis.check_sparse_update(prog)


def test_sparse_grad_consumed_by_foreign_op_trips():
    """A non-shard-aware op reading the table's SelectedRows gradient
    (inserted after planning) = error, located at the offending op —
    the static twin of the engine's trace-time raise."""
    prog, plan = _planned_sparse_program()
    blk = prog.global_block()
    g = sorted(plan.grad_of)[0]
    out = blk.create_var(name="lint.sparse.out", shape=(37, 8),
                         dtype="float32")
    idx = _bwd_idx(blk) + 1
    blk.ops.insert(idx, Operator(
        blk, "elementwise_mul", inputs={"X": [g], "Y": [g]},
        outputs={"Out": [out.name]}, attrs={}))
    fs = analysis.check_sparse_update(prog)
    assert len(fs) == 1
    f = fs[0]
    assert f.severity == "error" and f.op_type == "elementwise_mul"
    assert f.op_idx == idx and f.var == g
    assert "no sparse-aware rule" in f.message


def test_sparse_table_touched_outside_engine_trips():
    prog, plan = _planned_sparse_program()
    blk = prog.global_block()
    out = blk.create_var(name="lint.sparse.scale", shape=(37, 8),
                         dtype="float32")
    blk.ops.insert(0, Operator(
        blk, "scale", inputs={"X": ["lint_emb"]},
        outputs={"Out": [out.name]}, attrs={"scale": 2.0}))
    fs = analysis.check_sparse_update(prog)
    assert any(f.severity == "error" and f.var == "lint_emb"
               and f.op_type == "scale" for f in fs)


def test_sparse_tampered_row_layout_trips():
    prog, plan = _planned_sparse_program()
    info = plan.tables["lint_emb"].info
    info.padded_rows = info.padded_rows + 1  # no longer ndev-aligned
    fs = analysis.check_sparse_update(prog)
    assert any(f.severity == "error" and f.var == "lint_emb"
               and "misalign" in f.message for f in fs)


def test_sparse_fetch_of_selectedrows_grad_warns():
    prog, plan = _planned_sparse_program()
    g = sorted(plan.grad_of)[0]
    fs = analysis.check_sparse_update(prog, fetch_names=[g])
    assert len(fs) == 1
    assert fs[0].severity == "warning" and fs[0].var == g
    assert "densifies" in fs[0].message


def test_rank_divergent_table_shard_schedule_trips():
    """Rank 0 shards the table (sparse plan), rank 1 does not (e.g. a
    per-rank flag skew): their collective schedules diverge at the
    lookup — the deadlock class the divergence checker exists for."""
    prog0, _ = _planned_sparse_program()
    framework.switch_main_program(framework.Program())
    framework.switch_startup_program(framework.Program())
    with framework.unique_name_guard():
        prog1, _ = _planned_sparse_program()
    prog1._sparse_plan = None  # rank 1 "planned" nothing
    recs = analysis.collective_schedule(prog0)
    assert any(r["kind"] == "sparse_lookup" and r["var"] == "lint_emb"
               for r in recs)
    fs = analysis.check_collective_divergence([prog0, prog1])
    assert any(f.severity == "error" for f in fs), fs


def test_zero1_skips_engine_owned_optimizer_ops():
    """The sparse table's optimizer op consumes a SelectedRows grad
    with its OWN schedule — the zero1 checker must not flag it as
    'never reduce-scattered' (the taint-vocabulary extension)."""
    from paddle_tpu.parallel import sharded_update as su

    prog, _ = _planned_sparse_program()
    prog._shard_plan = su.plan_sharded_update(
        prog, prog.global_block(), 8, "dp")
    assert prog._shard_plan is not None  # fc params still plan dense
    assert not analysis.check_shard_plan(prog)
    assert not analysis.check_zero2_lifetimes(prog)


def test_dtype_contract_drift_and_fp64_promotion():
    x = fluid.layers.data(name="x", shape=[4], dtype="float32")
    y = fluid.layers.scale(x, scale=2.0)
    prog = fluid.default_main_program()
    assert not analysis.check_dtype_shape_contracts(prog)
    # drift the declaration after the op was appended
    prog.global_block()._find_var_recursive(y.name).dtype = "float16"
    fs = analysis.check_dtype_shape_contracts(prog)
    assert [f.severity for f in fs] == ["warning"]
    assert fs[0].var == y.name and "drifted" in fs[0].message
    prog.global_block()._find_var_recursive(y.name).dtype = "float32"
    # fp64 computed from non-fp64 inputs: flagged even when declared
    fluid.layers.cast(y, "float64")
    fs = analysis.check_dtype_shape_contracts(prog)
    assert any("fp64 promotion" in f.message and f.op_type == "cast"
               for f in fs)


def test_shape_contract_drift():
    x = fluid.layers.data(name="x", shape=[4], dtype="float32")
    y = fluid.layers.scale(x, scale=2.0)
    prog = fluid.default_main_program()
    v = prog.global_block()._find_var_recursive(y.name)
    v.shape = (-1, 5)
    fs = analysis.check_dtype_shape_contracts(prog)
    assert any(f.var == y.name and "shape" in f.message for f in fs)


def _mark_amp(prog, dtype="bfloat16"):
    from paddle_tpu.fluid.contrib.mixed_precision import \
        AutoMixedPrecisionLists

    prog._amp = True
    prog._amp_lists = AutoMixedPrecisionLists()
    prog._amp_dtype = dtype
    return prog


def test_redundant_cast_round_trip_warns():
    """cast(cast(x bf16 -> f32) -> bf16) with a single-use intermediate
    is an identity round-trip the AMP pass should have elided."""
    x = fluid.layers.data(name="x", shape=[4], dtype="float32")
    a = fluid.layers.cast(x, "bfloat16")
    b = fluid.layers.cast(a, "float32")
    c = fluid.layers.cast(b, "bfloat16")
    prog = fluid.default_main_program()
    fs = [f for f in analysis.check_dtype_shape_contracts(prog)
          if "redundant-cast" in f.message]
    assert len(fs) == 1
    f = fs[0]
    assert f.severity == "warning" and f.var == c.name
    assert "identity" in f.message
    # a consumer of the fp32 intermediate legitimizes the chain
    fluid.layers.scale(b, scale=2.0)
    fs = [f for f in analysis.check_dtype_shape_contracts(prog)
          if "redundant-cast" in f.message and f.var == c.name]
    assert not fs


def test_redundant_upcast_into_white_list_warns_under_amp():
    """AMP: an explicit bf16 -> fp32 cast whose every reader is a
    white-list op round-trips by construction (the policy casts those
    inputs straight back down)."""
    x = fluid.layers.data(name="x", shape=[4, 4], dtype="bfloat16")
    y = fluid.layers.cast(x, "float32")
    fluid.layers.mul(y, y)
    prog = _mark_amp(fluid.default_main_program())
    fs = [f for f in analysis.check_dtype_shape_contracts(prog)
          if "redundant-cast" in f.message]
    assert len(fs) == 1 and fs[0].var == y.name
    assert "white-list" in fs[0].message
    # without the AMP marking there is no policy to re-cast: clean
    prog._amp = False
    assert not [f for f in analysis.check_dtype_shape_contracts(prog)
                if "redundant-cast" in f.message]


def test_amp_policy_suppresses_mixed_dtype_drift_and_fp64_flag():
    """The trace-time AMP casts make a f32<->bf16 declaration
    disagreement legitimate (suppressed under _amp, a warning without
    it); the fp64-promotion check never fires on white-listed ops."""
    x = fluid.layers.data(name="x", shape=[4], dtype="float32")
    y = fluid.layers.scale(x, scale=2.0)
    prog = fluid.default_main_program()
    prog.global_block()._find_var_recursive(y.name).dtype = "bfloat16"
    fs = analysis.check_dtype_shape_contracts(prog)
    assert any(f.var == y.name and "drifted" in f.message for f in fs)
    _mark_amp(prog)
    assert not analysis.check_dtype_shape_contracts(prog)
    # white-listed op requesting f64 via attrs: mis-flag without the
    # policy, clean with it (the op runs in bf16 under AMP)
    from paddle_tpu.fluid.framework import Operator

    blk = prog.global_block()
    out = blk.create_var(name="amp.f64.out", shape=(4,),
                         dtype="float32")
    blk.ops.append(Operator(
        blk, "mul", inputs={"X": [x.name], "Y": [x.name]},
        outputs={"Out": [out.name]}, attrs={"dtype": "float64"}))
    assert not [f for f in analysis.check_dtype_shape_contracts(prog)
                if "fp64" in f.message]
    prog._amp = False
    assert [f for f in analysis.check_dtype_shape_contracts(prog)
            if "fp64" in f.message and f.op_type == "mul"]


# ---------------------------------------------------------------------------
# orchestrator + Executor hook
# ---------------------------------------------------------------------------

def test_run_static_checks_rejects_unknown_checker():
    with pytest.raises(ValueError, match="unknown checker"):
        analysis.run_static_checks(fluid.default_main_program(),
                                   checkers=["bogus"])


def test_run_static_checks_labels_cover_prepended_program():
    """A caller labeling only rank_programs must still get a Finding
    (naming the diverging rank), not an IndexError, when the LAST rank
    diverges from the prepended reference program."""
    p0, _ = _transpiled_program()
    p1, _ = _transpiled_program()
    p2, _ = _transpiled_program(extra_allreduce=True)
    fs = analysis.run_static_checks(
        p0, checkers=["collective-divergence"],
        rank_programs=[p1, p2], rank_labels=["rank1", "rank2"])
    errs = [f for f in fs if f.severity == "error"]
    assert len(errs) == 1 and errs[0].rank == "rank2"


def test_executor_hook_error_raises_before_dispatch():
    prog, loss, _ = _seeded_read_after_donate()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    set_flags({"FLAGS_tpu_static_checks": "error"})
    with pytest.raises(RuntimeError, match="read-after-donate"):
        exe.run(prog, feed=_batch(), fetch_list=[loss])


def test_executor_hook_error_raises_before_the_xla_compile(monkeypatch):
    """IR-only findings must reject the program BEFORE the (potentially
    tens of seconds) compile_block call, not after it."""
    from paddle_tpu.fluid import lowering as lowering_mod

    prog, loss, _ = _seeded_read_after_donate()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    set_flags({"FLAGS_tpu_static_checks": "error"})

    def boom(*a, **k):
        raise AssertionError("compile_block ran before the lint")

    monkeypatch.setattr(lowering_mod, "compile_block", boom)
    with pytest.raises(RuntimeError, match="read-after-donate"):
        exe.run(prog, feed=_batch(), fetch_list=[loss])


def test_executor_hook_warn_mode_warns_and_runs():
    prog, loss, _ = _seeded_read_after_donate()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    set_flags({"FLAGS_tpu_static_checks": "warn"})
    with pytest.warns(UserWarning, match="tpu-lint"):
        out = exe.run(prog, feed=_batch(), fetch_list=[loss])
    assert np.isfinite(np.asarray(out[0])).all()


def test_executor_hook_clean_program_passes_under_error():
    """The acceptance contract: ordinary tier-1 programs lint clean
    under FLAGS_tpu_static_checks=error — the flag costs nothing."""
    set_flags({"FLAGS_tpu_static_checks": "error"})
    loss = _mlp_loss()
    fluid.optimizer.AdamOptimizer(1e-3).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    out = exe.run(fluid.default_main_program(), feed=_batch(),
                  fetch_list=[loss])
    assert np.isfinite(np.asarray(out[0])).all()


# ---------------------------------------------------------------------------
# collective_byte_census region coverage (switch_case/conditional_block)
# ---------------------------------------------------------------------------

def _dp_mark(prog, nranks=8):
    import jax
    from jax.sharding import Mesh

    from paddle_tpu.parallel import env as penv

    mesh = Mesh(np.array(jax.devices()[:nranks]), ("dp",))
    prog._data_parallel = True
    prog._mesh = mesh
    penv.set_global_mesh(mesh)
    penv.register_ring(0, "dp", nranks)


def test_census_counts_switch_case_region_collectives():
    """lax.switch branches live in non-entry StableHLO regions; the
    census must count their all_reduces (previously only the gm
    lax.cond path was regression-tested)."""
    from paddle_tpu.fluid.layers.collective import _c_allreduce

    x = fluid.layers.data(name="x", shape=[4], dtype="float32")
    idx = fluid.layers.data(name="idx", shape=[1], dtype="int32")
    out = fluid.layers.switch_case(
        idx,
        [lambda: _c_allreduce(x, reduce_type="sum"),
         lambda: _c_allreduce(x * 2.0, reduce_type="sum")],
        default=lambda: _c_allreduce(x * 3.0, reduce_type="sum"))
    loss = fluid.layers.mean(out)
    prog = fluid.default_main_program()
    _dp_mark(prog)
    exe = fluid.Executor(fluid.TPUPlace())
    feed = {"x": np.ones((8, 4), np.float32),
            "idx": np.zeros((8, 1), np.int32)}
    exe.run(prog, feed=feed, fetch_list=[loss])
    col = exe.collective_report(prog, feed=feed, fetch_list=[loss])
    assert col is not None
    # one psum per traced branch (2 keyed + default), each inside its
    # switch region
    assert col["all_reduce"]["count"] == 3
    assert col["all_reduce"]["tensor_bytes"] > 0


def test_census_counts_conditional_block_region_collectives():
    x = fluid.layers.data(name="x", shape=[4], dtype="float32")
    y = fluid.layers.scale(x, scale=2.0)
    c = fluid.layers.reduce_mean(x) > 0.0
    prog = fluid.default_main_program()
    blk = prog.global_block()
    sub = prog._create_block()
    sub.append_op(type="c_allreduce_sum", inputs={"X": [y]},
                  outputs={"Out": [y]}, attrs={"ring_id": 0})
    prog._rollback()
    blk.append_op(type="conditional_block", inputs={"Cond": [c]},
                  outputs={}, attrs={"sub_block": sub.idx})
    loss = fluid.layers.mean(y)
    _dp_mark(prog)
    exe = fluid.Executor(fluid.TPUPlace())
    feed = {"x": np.ones((8, 4), np.float32)}
    exe.run(prog, feed=feed, fetch_list=[loss])
    col = exe.collective_report(prog, feed=feed, fetch_list=[loss])
    assert col is not None
    assert col.get("all_reduce", {}).get("count", 0) >= 1


# ---------------------------------------------------------------------------
# exemplar lint-regression harness (tools/tpu_lint.py)
# ---------------------------------------------------------------------------

def _import_tpu_lint():
    sys.path.insert(0, os.path.join(_REPO, "tools"))
    try:
        import tpu_lint
    finally:
        sys.path.pop(0)
    return tpu_lint


def test_exemplar_programs_lint_clean(tmp_path):
    """The standing tier-1 CI leg: tools/tpu_lint.py over the FULL
    exemplar corpus — BERT-tiny DP step (plain, bf16 AMP + ZeRO-2
    bucketed masters, AND the fp8 delayed-scaling tier), resnet scan,
    the serving decode loop, and the 2-rank fleet-transpiled sync-PS
    programs — through main() with --fail-on error, so the exit code
    and artifact are exactly what CI sees."""
    tpu_lint = _import_tpu_lint()
    out = tmp_path / "static_checks.json"
    rc = tpu_lint.main(["--fail-on", "error", "--out", str(out)])
    report = json.loads(out.read_text())
    assert set(report["programs"]) == {
        "bert_tiny", "bert_tiny_amp", "bert_tiny_fp8", "bert_tiny_tp",
        "mlp_hier", "embedding_ctr", "resnet_scan", "serving_decode",
        "serving_decode_sampled", "fleet_ps_2rank"}
    assert rc == 0 and report["ok"] and report["total_errors"] == 0, \
        report


@pytest.mark.slow
def test_cli_end_to_end(tmp_path):
    out = tmp_path / "static_checks.json"
    r = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "tpu_lint.py"),
         "--fail-on", "error", "--out", str(out)],
        capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    report = json.loads(out.read_text())
    assert report["ok"] and report["total_errors"] == 0
    assert set(report["programs"]) == {"bert_tiny", "bert_tiny_amp",
                                       "bert_tiny_fp8", "bert_tiny_tp",
                                       "mlp_hier", "embedding_ctr",
                                       "resnet_scan", "serving_decode",
                                       "serving_decode_sampled",
                                       "fleet_ps_2rank"}
    assert "tpu-lint:" in r.stdout


@pytest.mark.slow
def test_perf_analysis_lint_alias(tmp_path):
    out = tmp_path / "static_checks.json"
    r = subprocess.run(
        [sys.executable,
         os.path.join(_REPO, "tools", "perf_analysis.py"),
         "--lint", "--out", str(out), "--json"],
        capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    assert json.loads(out.read_text())["ok"]


# ---------------------------------------------------------------------------
# checker — quantization-tier contracts (fp8 scale-state ownership,
# fp8 site wiring, calibrated quantizer scales)
# ---------------------------------------------------------------------------

def _fp8_program():
    from paddle_tpu.fluid.contrib import mixed_precision

    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup):
        with framework.unique_name_guard():
            loss = _mlp_loss()
            mixed_precision.decorate(
                fluid.optimizer.AdamOptimizer(1e-3),
                amp_dtype="float8_e4m3").minimize(loss)
    assert getattr(main, "_amp_fp8", None)
    return main


def test_fp8_decorated_program_lints_clean():
    assert not analysis.check_quantization_contracts(_fp8_program())


def test_fp8_foreign_scale_state_write_trips():
    """A pass inserting an op that WRITES an @FP8_SCALE var outside
    the backward op's Fp8ScaleState slots corrupts the amax window —
    deliberate-defect twin of the clean exemplar."""
    prog = _fp8_program()
    blk = prog.global_block()
    sname = next(iter(prog._amp_fp8["inputs"].values()))["scale"]
    idx = _bwd_idx(blk) + 1
    blk.ops.insert(idx, Operator(
        blk, "scale", inputs={"X": [sname]}, outputs={"Out": [sname]},
        attrs={"scale": 2.0}))
    fs = analysis.check_quantization_contracts(prog)
    assert len(fs) == 1
    f = fs[0]
    assert f.severity == "error" and f.var == sname
    assert f.op_idx == idx and f.op_type == "scale"
    assert "outside the sanctioned set" in f.message
    assert "writes" in f.message


def test_fp8_foreign_hist_read_trips():
    """A mere READ of the amax history mid-program observes the scale
    mid-update — still an error, reported with the read verb."""
    prog = _fp8_program()
    blk = prog.global_block()
    hname = next(iter(prog._amp_fp8["inputs"].values()))["hist"]
    peek = blk.create_var(name="lint.fp8.peek", shape=(1,),
                          dtype="float32")
    idx = _bwd_idx(blk)  # before backward: a forward-section consumer
    blk.ops.insert(idx, Operator(
        blk, "reduce_max", inputs={"X": [hname]},
        outputs={"Out": [peek.name]}, attrs={}))
    fs = analysis.check_quantization_contracts(prog)
    assert len(fs) == 1
    f = fs[0]
    assert f.severity == "error" and f.var == hname
    assert f.op_type == "reduce_max" and "reads" in f.message


def test_fp8_cast_without_scale_trips():
    """Dropping one input's delayed-scaling state from the recipe (a
    rewrite pass that forgot to re-wire) leaves an fp8-white-list op
    quantizing at an uncalibrated scale — every orphaned site trips."""
    prog = _fp8_program()
    cfg = prog._amp_fp8
    victim = sorted(cfg["inputs"])[0]
    del cfg["inputs"][victim]
    fs = analysis.check_quantization_contracts(prog)
    assert fs and all(f.severity == "error" for f in fs)
    assert any(f.var == victim and
               "fp8 cast without scale" in f.message for f in fs)


def test_quantizer_missing_scale_slot_trips():
    """A slim/PTQ dequantize op with an empty Scale slot would
    (de)quantize with no scale at all."""
    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup):
        blk = main.global_block()
        x = blk.create_var(name="q.x", shape=(4, 4), dtype="float32")
        out = blk.create_var(name="q.out", shape=(4, 4),
                             dtype="float32")
        blk.ops.append(Operator(
            blk, "fake_dequantize_max_abs", inputs={"X": [x.name]},
            outputs={"Out": [out.name]}, attrs={"max_range": 127.0}))
    fs = analysis.check_quantization_contracts(main)
    assert len(fs) == 1
    f = fs[0]
    assert f.severity == "error"
    assert f.op_type == "fake_dequantize_max_abs"
    assert "missing its calibrated scale input" in f.message
