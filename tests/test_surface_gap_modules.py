"""Round-4 surface-gap modules (module-tree sweep vs the reference):
fluid.input (one_hot/embedding), fluid.average, fluid.DataFeedDesc,
fluid.communicator, fluid.evaluator, fluid.debugger, fleet.util,
paddle.utils.plot."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu import fleet
from paddle_tpu.fluid import framework


def test_fluid_one_hot_and_embedding_train():
    """reference input.py:24,130 — the 2.0-era input helpers build and
    run."""
    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup):
        with framework.unique_name_guard():
            ids = fluid.layers.data("ids", shape=[4], dtype="int64")
            oh = fluid.one_hot(ids, depth=7)
            emb = fluid.embedding(ids, size=[7, 5])
            s = fluid.layers.reduce_sum(oh) + fluid.layers.reduce_sum(
                emb)
            exe = fluid.Executor()
            exe.run(startup)
            out = exe.run(main,
                          feed={"ids": np.array([[1, 2, 3, 6]],
                                                "int64")},
                          fetch_list=[oh, emb, s])
    oh_v, emb_v = np.asarray(out[0]), np.asarray(out[1])
    assert oh_v.shape[-1] == 7
    assert oh_v.sum() == 4  # one hot per id
    assert emb_v.shape[-2:] == (4, 5)


def test_fluid_embedding_keeps_trailing_ids_axis():
    """The v2 contract: ids [N, 1] -> out [N, 1, emb] (the v1
    layers.embedding squeezes to [N, emb])."""
    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup):
        with framework.unique_name_guard():
            ids = fluid.layers.data("ids", shape=[1], dtype="int64")
            v2 = fluid.embedding(ids, size=[9, 6])
            v1 = fluid.layers.embedding(ids, size=[9, 6])
            exe = fluid.Executor()
            exe.run(startup)
            out = exe.run(main,
                          feed={"ids": np.array([[1], [2], [3]],
                                                "int64")},
                          fetch_list=[v2, v1])
    assert np.asarray(out[0]).shape == (3, 1, 6)
    assert np.asarray(out[1]).shape == (3, 6)


def test_weighted_average():
    with pytest.warns(Warning, match="deprecated"):
        avg = fluid.average.WeightedAverage()
    avg.add(value=2.0, weight=1)
    avg.add(value=4.0, weight=2)
    np.testing.assert_allclose(avg.eval(), 10.0 / 3.0)
    avg.reset()
    with pytest.raises(ValueError):
        avg.eval()
    with pytest.raises(ValueError):
        avg.add(value="nope", weight=1)


def test_data_feed_desc_roundtrip(tmp_path):
    proto = tmp_path / "data.proto"
    proto.write_text(
        'name: "MultiSlotDataFeed"\n'
        "batch_size: 2\n"
        "multi_slot_desc {\n"
        "    slots {\n"
        '         name: "words"\n'
        '         type: "uint64"\n'
        "         is_dense: false\n"
        "         is_used: false\n"
        "     }\n"
        "     slots {\n"
        '         name: "label"\n'
        '         type: "uint64"\n'
        "         is_dense: false\n"
        "         is_used: false\n"
        "    }\n"
        "}\n")
    d = fluid.DataFeedDesc(str(proto))
    assert d.slot_names() == ["words", "label"]
    d.set_batch_size(128)
    d.set_dense_slots(["words"])
    d.set_use_slots(["words", "label"])
    text = d.desc()
    assert "batch_size: 128" in text
    assert "is_dense: true" in text
    # the printed text parses back identically
    proto2 = tmp_path / "data2.proto"
    proto2.write_text(text)
    d2 = fluid.DataFeedDesc(str(proto2))
    assert d2.batch_size == 128
    assert d2._slot_by_name["words"].is_dense
    assert d2._slot_by_name["label"].is_used
    with pytest.raises(ValueError):
        d.set_use_slots(["nope"])


def test_communicator_requires_ps_program():
    main = framework.Program()
    with pytest.raises(ValueError, match="transpiled"):
        fluid.communicator.Communicator(main)


def test_evaluator_chunk_and_edit_distance():
    with pytest.warns(Warning, match="deprecated"):
        ce = fluid.evaluator.ChunkEvaluator()
    ce.update(10, 8, 6)
    p, r, f1 = ce.eval()
    np.testing.assert_allclose([p, r], [0.6, 0.75])
    with pytest.warns(Warning, match="deprecated"):
        ed = fluid.evaluator.EditDistance()
    ed.update(np.array([1.0, 0.0, 3.0]), 3)
    dist, err = ed.eval()
    np.testing.assert_allclose([dist, err], [4.0 / 3.0, 2.0 / 3.0])


def test_evaluator_detection_map_accumulates():
    with pytest.warns(Warning, match="deprecated"):
        m = fluid.evaluator.DetectionMAP(class_num=2)
    det = [[1, 0.9, 0.1, 0.1, 0.4, 0.4],
           [1, 0.8, 0.5, 0.5, 0.9, 0.9],
           [1, 0.7, 0.0, 0.0, 0.05, 0.05]]
    lab = [[1, 0.1, 0.1, 0.4, 0.4], [1, 0.5, 0.5, 0.9, 0.9]]
    m.update(det, [0, 1, 3], lab, [0, 1, 2])
    np.testing.assert_allclose(m.eval(), 1.0, atol=1e-6)
    # a second batch (one FP det, one missed gt): the ACCUMULATED
    # ranking is FP(.95), TP(.9), TP(.8), FP(.7) over 3 gts ->
    # integral AP = (1/3)*(1/2) + (1/3)*(2/3) = 0.38888 — a
    # last-batch-only evaluation would report 0.0 instead
    m.update([[1, 0.95, 0, 0, 0.05, 0.05]], [0, 1],
             [[1, 0.5, 0.5, 0.9, 0.9]], [0, 1])
    np.testing.assert_allclose(m.eval(), 0.388888, atol=1e-4)


def test_debugger_pprint_and_dot(tmp_path):
    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup):
        with framework.unique_name_guard():
            x = fluid.layers.data("x", shape=[4], dtype="float32")
            y = fluid.layers.fc(x, 3)
            fluid.layers.mean(y)
    code = fluid.debugger.pprint_program_codes(main)
    assert "fc" in code or "mul" in code
    assert "var x" in code
    dot = tmp_path / "g.dot"
    fluid.debugger.draw_block_graphviz(main.global_block(),
                                       highlights=["x"],
                                       path=str(dot))
    text = dot.read_text()
    assert text.startswith("digraph G {") and '"v_x"' in text
    assert "fillcolor=\"red\"" in text  # highlight applied


def test_fleet_util_single_process_identities():
    u = fleet.util
    a = np.arange(4.0)
    np.testing.assert_array_equal(u.all_reduce(a), a)
    assert [g.tolist() for g in u.all_gather(a)] == [a.tolist()]
    u.barrier()  # no-op without a group
    files = ["f%d" % i for i in range(7)]
    assert u.get_file_shard(files) == files  # 1 worker -> all files


def test_fleet_util_file_shard_split():
    u = fleet.util

    class RM:
        def worker_num(self):
            return 3

        def worker_index(self):
            return self._i

    rm = RM()
    u._set_role_maker(rm)
    try:
        files = ["f%d" % i for i in range(7)]
        shards = []
        for i in range(3):
            rm._i = i
            shards.append(u.get_file_shard(files))
        assert [len(s) for s in shards] == [3, 2, 2]
        assert sum(shards, []) == files
        with pytest.raises(TypeError):
            u.get_file_shard("not-a-list")
    finally:
        u._set_role_maker(None)


def test_utils_plot_collects_without_matplotlib(monkeypatch):
    monkeypatch.setenv("DISABLE_PLOT", "True")
    p = paddle.utils.plot.Ploter("train", "test")
    p.append("train", 0, 1.0)
    p.append("train", 1, 0.5)
    assert p.__plot_data__["train"].value == [1.0, 0.5]
    p.plot()  # disabled: must be a no-op, not a crash
    p.reset()
    assert p.__plot_data__["train"].value == []


def test_op_freq_statistic():
    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup):
        with framework.unique_name_guard():
            x = fluid.layers.data("x", shape=[4], dtype="float32")
            h = fluid.layers.fc(x, 3, act="relu")
            fluid.layers.mean(h)
    uni, adj = fluid.contrib.op_freq_statistic(main)
    assert uni.get("relu", 0) >= 1
    assert any(k.endswith("->mean") for k in adj), adj
    with pytest.raises(TypeError):
        fluid.contrib.op_freq_statistic("not a program")
    # reference-name alias for the model-stat module
    assert fluid.contrib.model_stat is fluid.contrib.model_stats


def test_dataset_folder_and_image_folder(tmp_path):
    """reference hapi/datasets/folder.py:60,197 — filesystem-backed
    datasets; .npy samples keep the test image-codec-free."""
    from paddle_tpu.hapi import datasets

    for cls in ("cat", "dog"):
        d = tmp_path / "train" / cls
        d.mkdir(parents=True)
        for i in range(3):
            np.save(str(d / ("%d.npy" % i)),
                    np.full((4, 4), ord(cls[0]) + i, "float32"))
    ds = datasets.DatasetFolder(str(tmp_path / "train"))
    assert ds.classes == ["cat", "dog"]
    assert len(ds) == 6
    sample, target = ds[0]
    assert sample.shape == (4, 4) and target == 0
    assert ds.targets.count(1) == 3

    flat = datasets.ImageFolder(str(tmp_path / "train"))
    assert len(flat) == 6
    (s0,) = flat[0]
    assert s0.shape == (4, 4)

    seen = datasets.DatasetFolder(
        str(tmp_path / "train"),
        transform=lambda a: a * 0 + 7)
    np.testing.assert_array_equal(seen[2][0], np.full((4, 4), 7.0))

    empty = tmp_path / "empty"
    empty.mkdir()
    with pytest.raises(RuntimeError):
        datasets.DatasetFolder(str(empty))  # no class subfolders


@pytest.mark.slow
def test_communicator_lifecycle(tmp_path):
    """start/stop lifecycle semantics: stop() completes the instance
    (dead - the executor must never step it again), mode mismatch is
    rejected, restart builds a fresh communicator."""
    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup):
        with framework.unique_name_guard():
            x = fluid.layers.data("x", shape=[4], dtype="float32")
            y = fluid.layers.fc(x, 2)
            loss = fluid.layers.mean(y)
            fluid.optimizer.SGDOptimizer(0.1).minimize(loss)
    t = fluid.DistributeTranspiler()
    t.transpile(0, program=main, pservers="127.0.0.1:1,127.0.0.1:2",
                trainers=2, sync_mode=False, startup_program=startup)

    with pytest.raises(ValueError, match="does not match"):
        fluid.communicator.Communicator(main, mode="geo")

    c = fluid.communicator.Communicator(main)
    assert not c.is_running()
    c.start()
    assert c.is_running()
    first = main._ps_comm
    assert first is not None
    c.stop()
    assert not c.is_running()
    assert main._ps_comm is None
    assert getattr(first, "_completed", False) is True

    c2 = fluid.communicator.Communicator(main)
    c2.start()
    assert main._ps_comm is not first  # fresh instance after restart
    c2.stop()


def test_distributed_batch_sampler_partitions_dataset(monkeypatch):
    """reference hapi/distributed.py:36 — ranks see disjoint subsets
    covering the (padded) dataset; same-epoch shuffles agree across
    ranks."""
    from paddle_tpu.hapi.distributed import DistributedBatchSampler
    from paddle_tpu.parallel import env as penv

    class DS:
        def __len__(self):
            return 10

    monkeypatch.setattr(penv, "trainer_num", lambda: 4)
    rank_batches = {}
    for rank in range(4):
        monkeypatch.setattr(penv, "trainer_id", lambda r=rank: r)
        s = DistributedBatchSampler(DS(), batch_size=2)
        rank_batches[rank] = [i for b in s for i in b]
        assert len(s) == 2  # ceil(ceil(10/4)/2)
    all_idx = sum(rank_batches.values(), [])
    # 12 padded slots (10 + 2 wrap-around), each rank 3
    assert len(all_idx) == 12
    assert set(all_idx) == set(range(10))
    assert all(len(v) == 3 for v in rank_batches.values())
    # disjoint before padding: the two wrapped indices are 0 and 1
    from collections import Counter

    c = Counter(all_idx)
    assert c[0] == 2 and c[1] == 2
    assert all(c[i] == 1 for i in range(2, 10))

    # same epoch -> identical permutation on every rank
    monkeypatch.setattr(penv, "trainer_id", lambda: 0)
    a = DistributedBatchSampler(DS(), batch_size=2, shuffle=True)
    a.set_epoch(5)
    seq_a = [i for b in a for i in b]
    b_ = DistributedBatchSampler(DS(), batch_size=2, shuffle=True)
    b_.set_epoch(5)
    assert seq_a == [i for bb in b_ for i in bb]
