"""proto-check: the explicit-state interleaving checker for the host
protocol tier (paddle_tpu/analysis/protocol.py + proto_models.py).

Two regression surfaces:

1. the SHIPPED protocols explore clean — every registered model
   (proto_models.PROTOCOLS) runs the tier-1 budget with ZERO errors:
   retried RPC envelopes are exactly-once, PS apply survives
   kill/restart, the elastic seam agrees, drain/adopt conserves every
   request+token, the paged-KV ledger conserves every page. This is
   the standing claim `tools/tpu_lint.py --protocol` gates CI on.
2. seeded-defect MUTANTS — one per invariant class — must each be
   CAUGHT, and the finding's compact trace must reproduce the
   violation DETERMINISTICALLY when replayed alone on a fresh model
   (protocol.replay). A checker that can't catch the defect it was
   built for, or whose repro doesn't replay, is the regression.

Plus: engine mechanics on inline toy models (deadlock detection,
fingerprint pruning, sleep-set reduction, budget truncation), the
findings location contract (actor/step/trace — satellite of the
op/var contract the IR checkers assert), the --protocol CLI leg, and
the protocol_check telemetry schema lock.
"""
import json
import os
import subprocess
import sys

import pytest

from paddle_tpu import analysis
from paddle_tpu.analysis import proto_models, protocol

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: mutant name -> the invariant class its finding must carry. One per
#: invariant family the tier claims to check (ISSUE: each mutant is
#: caught by exactly the checker built for its class).
MUTANT_INVARIANT = {
    "rpc_envelope__no_retry": "deadlock",
    "ps_apply__non_atomic_persist": "exactly-once",
    "elastic_seam__local_decision": "seam-agreement",
    "serving_drain__skip_prefill": "drain-conservation",
    "kv_pages__evict_leaves_index": "kv-conservation",
}

#: tier-1 exploration budget: the acceptance floor is >= 1k
#: interleavings per model (models whose full space is smaller finish
#: un-truncated below it; kv_pages truncates at the budget).
TIER1_BUDGET = 1000


# ---------------------------------------------------------------------------
# engine mechanics (inline toy models — no real protocol objects)
# ---------------------------------------------------------------------------

class _Toy(protocol.ProtocolModel):
    """Two actors each take 2 steps; optional seeded defects."""

    name = "toy"
    deadlock_at = None  # (a_steps, b_steps) where both actors block
    violate_at = None   # state where invariants() reports a violation

    def reset(self):
        self.a = 0
        self.b = 0

    def actions(self):
        if (self.a, self.b) == self.deadlock_at:
            return []
        acts = []
        if self.a < 2:
            acts.append(("A", "step"))
        if self.b < 2:
            acts.append(("B", "step"))
        return acts

    def step(self, action):
        if action[0] == "A":
            self.a += 1
        else:
            self.b += 1

    def invariants(self):
        if (self.a, self.b) == self.violate_at:
            return [("toy-invariant", "hit the seeded state %r"
                     % ((self.a, self.b),))]
        return []

    def done(self):
        return self.a == 2 and self.b == 2

    def fingerprint(self):
        return (self.a, self.b)


def test_explore_clean_toy_visits_all_states():
    res = protocol.explore(_Toy)
    assert res.errors == 0 and not res.truncated
    # 3x3 grid of (a, b) states; `states` counts VISITS (a revisited
    # fingerprint is observed, then pruned), so >= the 9 distinct
    assert res.states >= 9
    assert res.deepest == 4


def test_explore_finds_seeded_violation_with_trace():
    class Bad(_Toy):
        violate_at = (2, 1)

    res = protocol.explore(Bad)
    assert res.errors >= 1
    f = res.findings[0]
    assert f.checker == "protocol" and f.severity == "error"
    assert "toy-invariant" in f.message
    rep = protocol.replay(Bad, f.trace)
    assert rep["reproduced"] and rep["violations"]
    assert rep["violations"][0][0] == "toy-invariant"


def test_explore_flags_deadlock():
    class Stuck(_Toy):
        deadlock_at = (1, 1)

    res = protocol.explore(Stuck)
    assert res.errors >= 1
    f = res.findings[0]
    assert "deadlock" in f.message
    rep = protocol.replay(Stuck, f.trace)
    assert rep["deadlock"] and rep["reproduced"]


def test_explore_budget_truncates_without_error():
    res = protocol.explore(_Toy, max_schedules=2)
    assert res.truncated and res.errors == 0
    assert res.schedules == 2


def test_sleep_set_reduction_prunes_commuting_interleavings():
    class Comm(_Toy):
        def independent(self, x, y):
            return x[0] != y[0]  # A and B steps always commute

    full = protocol.explore(_Toy, dedupe_states=False)
    reduced = protocol.explore(Comm, dedupe_states=False)
    assert reduced.errors == 0
    # one maximal schedule suffices when everything commutes
    assert reduced.schedules < full.schedules


def test_trace_round_trip():
    trace = [("client", "send"), ("net", "deliver", 1),
             ("rank-2", "resize", -1)]
    enc = protocol.format_trace(trace)
    assert protocol.parse_trace(enc) == trace
    assert protocol.parse_trace("") == []


# ---------------------------------------------------------------------------
# the shipped protocols explore CLEAN at the tier-1 budget
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name", list(proto_models.PROTOCOLS))
def test_shipped_protocol_explores_clean(name):
    res = protocol.explore(proto_models.PROTOCOLS[name],
                           max_schedules=TIER1_BUDGET)
    assert res.errors == 0, \
        "%s: %s" % (name, [analysis.format_finding(f)
                           for f in res.findings])
    assert res.schedules >= min(TIER1_BUDGET, res.schedules)
    # un-truncated models covered their FULL space under the budget
    if not res.truncated:
        assert res.schedules < TIER1_BUDGET


def test_run_protocol_checks_report_shape():
    findings, report = analysis.run_protocol_checks(budget=200)
    assert report["ok"] and report["errors"] == 0 and not findings
    assert set(report["models"]) == set(proto_models.PROTOCOLS)
    for m in report["models"].values():
        assert m["schedules"] > 0 and m["states"] >= m["schedules"] // 2
    with pytest.raises(ValueError):
        analysis.run_protocol_checks(models=["nope"])


# ---------------------------------------------------------------------------
# seeded-defect mutants: every invariant class catches its defect,
# and the finding's trace replays deterministically
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name", list(proto_models.MUTANTS))
def test_mutant_caught_with_replayable_trace(name):
    cls = proto_models.MUTANTS[name]
    res = protocol.explore(cls, max_schedules=TIER1_BUDGET)
    errs = [f for f in res.findings if f.severity == "error"]
    assert errs, "mutant %s was not caught" % name
    inv = MUTANT_INVARIANT[name]
    hits = [f for f in errs if ": %s: " % inv in f.message]
    assert hits, "mutant %s caught, but not by the %r invariant: %s" \
        % (name, inv, [f.message for f in errs])
    f = hits[0]
    # determinism: the compact trace alone reproduces the violation on
    # a fresh model — twice, to rule out cross-replay state leaks
    for _ in range(2):
        rep = protocol.replay(cls, f.trace)
        assert rep["reproduced"], \
            "%s: trace %r did not reproduce" % (name, f.trace)
        if inv == "deadlock":
            assert rep["deadlock"]
        else:
            assert any(v[0] == inv for v in rep["violations"]), \
                rep["violations"]


def test_mutant_traces_are_minimal_enough_to_read():
    """The whole point of compact traces: a repro a human can eyeball.
    Every mutant's first finding stays within the depth budget and
    parses back to the action tuples the model executed."""
    for name, cls in proto_models.MUTANTS.items():
        res = protocol.explore(cls, max_schedules=TIER1_BUDGET)
        f = next(x for x in res.findings if x.severity == "error")
        acts = protocol.parse_trace(f.trace)
        assert 0 < len(acts) <= 96
        assert all(isinstance(a[0], str) and isinstance(a[1], str)
                   for a in acts), acts


# ---------------------------------------------------------------------------
# findings location contract on protocol findings (satellite: the
# trace IS the location — seed + actor + step index)
# ---------------------------------------------------------------------------

def _one_mutant_finding():
    res = protocol.explore(
        proto_models.MUTANTS["ps_apply__non_atomic_persist"],
        max_schedules=TIER1_BUDGET)
    return next(f for f in res.findings if f.severity == "error")


def test_protocol_finding_location_contract():
    f = _one_mutant_finding()
    acts = protocol.parse_trace(f.trace)
    last = acts[-1]
    assert f.checker == "protocol" and f.severity == "error"
    assert f.var == str(last[0])          # acting actor
    assert f.op_idx == len(acts) - 1      # step index into the trace
    assert f.op_type == str(last[1])      # action label
    assert f.block_idx is None and f.rank is None
    loc = f.location
    assert "actor %r" % f.var in loc
    assert "step %d (%s)" % (f.op_idx, f.op_type) in loc
    assert "trace %r" % f.trace in loc
    assert f.message.startswith("ps_apply__non_atomic_persist: ")


def test_protocol_finding_to_dict_carries_trace():
    f = _one_mutant_finding()
    d = f.to_dict()
    assert d["trace"] == f.trace and d["checker"] == "protocol"
    # IR findings don't grow a trace key — the artifact shape of the
    # six static checkers is unchanged
    ir = analysis.Finding("host-sync", "error", "x", block_idx=1,
                          op_idx=2, op_type="fetch")
    assert "trace" not in ir.to_dict()
    assert "block 1 op 2 (fetch)" in ir.location


def test_protocol_finding_sorts_with_ir_findings():
    f = _one_mutant_finding()
    warn = analysis.Finding("host-sync", "warning", "w", block_idx=0,
                            op_idx=0)
    ordered = analysis.sort_findings([warn, f])
    assert ordered[0] is f  # error outranks warning, trace or not


# ---------------------------------------------------------------------------
# surfaces: CLI leg, artifact, telemetry schema
# ---------------------------------------------------------------------------

def _import_tpu_lint():
    sys.path.insert(0, os.path.join(_REPO, "tools"))
    try:
        import tpu_lint
    finally:
        sys.path.pop(0)
    return tpu_lint


def test_cli_protocol_leg_in_process(tmp_path):
    tpu_lint = _import_tpu_lint()
    out = tmp_path / "protocol_checks.json"
    rc = tpu_lint.main(["--protocol", "--fail-on", "error",
                        "--protocol-budget", str(TIER1_BUDGET),
                        "--out", str(out)])
    report = json.loads(out.read_text())
    assert rc == 0 and report["ok"], report
    assert set(report["models"]) == set(proto_models.PROTOCOLS)
    assert report["total_errors"] == 0 and report["findings"] == []
    assert report["budget"] == TIER1_BUDGET


def test_cli_protocol_model_filter(tmp_path):
    tpu_lint = _import_tpu_lint()
    out = tmp_path / "p.json"
    rc = tpu_lint.main(["--protocol", "--protocol-model", "ps_apply",
                        "--protocol-budget", "100",
                        "--out", str(out)])
    report = json.loads(out.read_text())
    assert rc == 0 and list(report["models"]) == ["ps_apply"]
    with pytest.raises(SystemExit):
        tpu_lint.main(["--protocol", "--protocol-model", "bogus",
                       "--out", str(out)])


def test_protocol_check_telemetry_matches_schema(tmp_path):
    from paddle_tpu.observability import schema
    from paddle_tpu.observability.registry import (configure,
                                                   reset_registry)

    configure(telemetry_dir=str(tmp_path), rank=0)
    try:
        analysis.run_protocol_checks(budget=50, models=["ps_apply"])
    finally:
        reset_registry()
    recs = []
    for fn in os.listdir(str(tmp_path)):
        with open(os.path.join(str(tmp_path), fn)) as fh:
            recs += [json.loads(x) for x in fh if x.strip()]
    pc = [r for r in recs if r.get("event") == "protocol_check"]
    assert pc and pc[0]["model"] == "ps_apply"
    assert pc[0]["schedules"] > 0 and pc[0]["errors"] == 0
    assert schema.validate_records(pc) == []


@pytest.mark.slow
def test_cli_protocol_end_to_end_full_budget(tmp_path):
    out = tmp_path / "protocol_checks.json"
    r = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "tpu_lint.py"),
         "--protocol", "--protocol-budget", "5000",
         "--fail-on", "warning", "--out", str(out)],
        capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    report = json.loads(out.read_text())
    assert report["ok"] and report["errors"] == 0
    assert "tpu-lint --protocol:" in r.stdout
