"""bench.py's warm/measure protocol serializes the traced train step
with jax.export and re-jits the deserialized module. With
FLAGS_prng_impl=rbg (what `auto` resolves to on TPU — core/rng.py) the
lowered program contains stablehlo rng_bit_generator custom ops; this
guards that the export round-trip still works, BEFORE a live tunnel
window spends its warm budget discovering it doesn't."""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework, lowering
from paddle_tpu.core.scope import global_scope
from paddle_tpu.utils.flags import get_flag, set_flags


@pytest.fixture
def _impl_flag():
    old = get_flag("FLAGS_prng_impl")
    yield
    set_flags({"FLAGS_prng_impl": old})


@pytest.mark.parametrize("impl", ["threefry2x32", "rbg"])
def test_export_roundtrip_with_dropout(_impl_flag, impl):
    import jax
    import jax.export  # noqa: F401 - 0.4.x needs the explicit submodule import

    set_flags({"FLAGS_prng_impl": impl})
    main, startup = framework.Program(), framework.Program()
    main.random_seed = startup.random_seed = 3
    with framework.program_guard(main, startup):
        with framework.unique_name_guard():
            x = fluid.layers.data("x", shape=[16], dtype="float32")
            h = fluid.layers.fc(x, size=16)
            h = fluid.layers.dropout(h, dropout_prob=0.2)
            loss = fluid.layers.mean(h)
            fluid.optimizer.SGDOptimizer(0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    feed = {"x": np.ones((4, 16), np.float32)}

    block = main.global_block()
    state_in, _ = lowering.analyze_block(block, list(feed), [loss.name])
    state_specs = {n: global_scope().find_var(n) for n in state_in}
    entry = lowering.compile_block(main, block, feed, [loss.name],
                                   state_specs)

    def aval(v):
        a = np.asarray(v)
        return jax.ShapeDtypeStruct(a.shape, a.dtype)

    favals = {k: aval(v) for k, v in feed.items()}
    smut = {n: aval(global_scope().find_var(n))
            for n in entry.state_mut_names}
    sro = {n: aval(global_scope().find_var(n))
           for n in entry.state_ro_names}
    exp = jax.export.export(entry.jitted)(
        favals, smut, sro, jax.ShapeDtypeStruct((), np.uint32))
    blob = exp.serialize()
    assert len(blob) > 0

    re_exp = jax.export.deserialize(bytearray(blob))
    rejit = jax.jit(re_exp.call, donate_argnums=(1,))
    smut_vals = {n: np.asarray(global_scope().find_var(n))
                 for n in entry.state_mut_names}
    sro_vals = {n: np.asarray(global_scope().find_var(n))
                for n in entry.state_ro_names}
    out = rejit(feed, smut_vals, sro_vals, np.uint32(11))
    fetched, new_state = out
    flat = np.asarray(jax.tree_util.tree_leaves(fetched)[0])
    assert np.isfinite(flat).all()

    # direct call of the original entry with the same seed must agree
    out2 = entry.jitted(feed, smut_vals, sro_vals, np.uint32(11))
    flat2 = np.asarray(jax.tree_util.tree_leaves(out2[0])[0])
    np.testing.assert_allclose(flat, flat2, rtol=1e-6)
