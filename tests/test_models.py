"""End-to-end model tests on tiny shapes (reference strategy: SURVEY.md
§4.2 program-level integration tests asserting loss decrease)."""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.models import resnet, bert, transformer, mnist


def _train(feeds_fn, loss_var, feeds, steps=8):
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    losses = []
    for s in range(steps):
        out = exe.run(feed=feeds_fn(s), fetch_list=[loss_var])
        losses.append(float(np.asarray(out[0]).reshape(-1)[0]))
    return losses


def test_mnist_conv_trains(rng):
    loss, acc, _ = mnist.build_mnist_train(arch="conv", lr=0.01)
    x = rng.rand(16, 1, 28, 28).astype("float32")
    y = rng.randint(0, 10, (16, 1)).astype("int64")
    losses = _train(lambda s: {"img": x, "label": y}, loss, None, steps=10)
    assert losses[-1] < losses[0]


@pytest.mark.slow
def test_resnet18_trains(rng):
    loss, acc, _ = resnet.build_resnet_train(
        image_shape=(3, 32, 32), class_dim=10, depth=18, lr=0.05)
    x = rng.rand(8, 3, 32, 32).astype("float32")
    y = rng.randint(0, 10, (8, 1)).astype("int64")
    losses = _train(lambda s: {"image": x, "label": y}, loss, None,
                    steps=8)
    assert losses[-1] < losses[0], losses


@pytest.mark.slow
def test_resnet50_builds_and_steps(rng):
    loss, acc, _ = resnet.build_resnet_train(
        image_shape=(3, 64, 64), class_dim=10, depth=50, lr=0.01)
    x = rng.rand(2, 3, 64, 64).astype("float32")
    y = rng.randint(0, 10, (2, 1)).astype("int64")
    losses = _train(lambda s: {"image": x, "label": y}, loss, None,
                    steps=2)
    assert np.isfinite(losses).all()


def _bert_batch(rng, cfg, bsz, seq, max_pred):
    src = rng.randint(0, cfg.vocab_size, (bsz, seq)).astype("int64")
    pos = np.tile(np.arange(seq), (bsz, 1)).astype("int64")
    sent = np.zeros((bsz, seq), "int64")
    mask = np.ones((bsz, seq), "float32")
    mask_pos = np.stack([rng.choice(seq, max_pred, replace=False)
                         for _ in range(bsz)]).astype("int64")
    mask_label = rng.randint(0, cfg.vocab_size,
                             (bsz, max_pred)).astype("int64")
    mask_weight = np.ones((bsz, max_pred), "float32")
    nsp = rng.randint(0, 2, (bsz, 1)).astype("int64")
    return {"src_ids": src, "pos_ids": pos, "sent_ids": sent,
            "input_mask": mask, "mask_pos": mask_pos,
            "mask_label": mask_label, "mask_weight": mask_weight,
            "nsp_label": nsp}


@pytest.mark.slow
def test_bert_tiny_trains(rng):
    cfg = bert.BertConfig.tiny()
    total, mlm, nsp, feeds = bert.build_bert_pretrain(
        cfg, seq_len=16, lr=1e-3)
    batch = _bert_batch(rng, cfg, 4, 16, 4)
    losses = _train(lambda s: batch, total, None, steps=10)
    assert losses[-1] < losses[0], losses


@pytest.mark.slow
def test_transformer_tiny_trains_and_decodes(rng):
    cfg = transformer.TransformerConfig.tiny()
    loss, feeds = transformer.build_transformer_train(
        cfg, src_len=8, tgt_len=8, lr=1e-2, warmup=10,
        label_smooth_eps=0.0)
    bsz = 4
    batch = {
        "src_ids": rng.randint(2, cfg.src_vocab, (bsz, 8)).astype("int64"),
        "tgt_ids": rng.randint(2, cfg.tgt_vocab, (bsz, 8)).astype("int64"),
        "lbl_ids": rng.randint(2, cfg.tgt_vocab, (bsz, 8)).astype("int64"),
        "src_mask": np.ones((bsz, 8), "float32"),
        "tgt_mask": np.ones((bsz, 8), "float32"),
    }
    losses = _train(lambda s: batch, loss, None, steps=10)
    assert losses[-1] < losses[0], losses

    # beam-search decode (jittable while_loop) off the trained scope
    from paddle_tpu.core.scope import global_scope

    seqs, scores = transformer.beam_search_decode(
        global_scope(), batch["src_ids"][:2], batch["src_mask"][:2], cfg,
        beam_size=3, max_out_len=6, bos_id=0, eos_id=1)
    seqs = np.asarray(seqs)
    scores = np.asarray(scores)
    assert seqs.shape == (2, 3, 7)
    assert scores.shape == (2, 3)
    # beams sorted by score
    assert (np.diff(scores, axis=1) <= 1e-5).all()
    assert (seqs[:, :, 0] == 0).all()
