"""End-to-end: MNIST-style MLP trains in the fluid static-graph mode
(BASELINE.json config 1; reference test:
`python/paddle/fluid/tests/book/test_recognize_digits.py:65`)."""
import numpy as np

import paddle_tpu.fluid as fluid


def _synthetic_mnist(rng, n=512):
    # separable synthetic data so loss must drop fast
    x = rng.rand(n, 784).astype("float32") * 0.1
    y = rng.randint(0, 10, size=(n, 1)).astype("int64")
    for i in range(n):
        x[i, y[i, 0] * 78:(y[i, 0] + 1) * 78] += 1.0
    return x, y


def build_mlp():
    img = fluid.layers.data(name="img", shape=[784], dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    h = fluid.layers.fc(input=img, size=128, act="relu")
    h = fluid.layers.fc(input=h, size=64, act="relu")
    logits = fluid.layers.fc(input=h, size=10, act=None)
    loss = fluid.layers.softmax_with_cross_entropy(logits, label)
    avg_loss = fluid.layers.mean(loss)
    acc = fluid.layers.accuracy(input=fluid.layers.softmax(logits),
                                label=label)
    return avg_loss, acc


def test_mnist_mlp_trains(rng):
    avg_loss, acc = build_mlp()
    opt = fluid.optimizer.SGDOptimizer(learning_rate=0.5)
    opt.minimize(avg_loss)

    place = fluid.CPUPlace()
    exe = fluid.Executor(place)
    exe.run(fluid.default_startup_program())

    x, y = _synthetic_mnist(rng)
    losses = []
    for step in range(30):
        i = (step * 64) % 448
        out = exe.run(fluid.default_main_program(),
                      feed={"img": x[i:i + 64], "label": y[i:i + 64]},
                      fetch_list=[avg_loss, acc])
        losses.append(float(out[0]))
    assert losses[-1] < losses[0] * 0.5, losses
    assert losses[-1] < 0.7, losses


def test_mnist_eval_and_fetch_params(rng):
    avg_loss, acc = build_mlp()
    opt = fluid.optimizer.AdamOptimizer(learning_rate=1e-3)
    opt.minimize(avg_loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    x, y = _synthetic_mnist(rng, 64)
    l0 = exe.run(feed={"img": x, "label": y}, fetch_list=[avg_loss])[0]
    for _ in range(20):
        exe.run(feed={"img": x, "label": y}, fetch_list=[avg_loss])
    l1 = exe.run(feed={"img": x, "label": y}, fetch_list=[avg_loss])[0]
    assert float(l1) < float(l0)
