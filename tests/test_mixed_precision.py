"""Mixed precision at scale: bf16 compute + ZeRO-sharded fp32 master
weights, fp16 dynamic loss scaling, and ZeRO-2 sharded gradient
lifetimes.

Machinery: fluid/contrib/mixed_precision (decorate, master rewrite,
loss-scale wiring), fluid/lowering (_apply_amp_casts,
_run_loss_scaled_post), parallel/sharded_update (master planning,
16-bit bucketed grads + deferred 16-bit param gathers), executor
donation_report param_*/grad_peak_* fields. Reference: Xu et al.
arXiv:2004.13336 (cross-replica weight-update sharding), Wang et al.
arXiv:2011.03641 (HBM headroom as the binding constraint).
"""
import os

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework
from paddle_tpu.fluid.contrib import mixed_precision
from paddle_tpu.utils.flags import get_flag, set_flags

O = fluid.optimizer


@pytest.fixture(autouse=True)
def _restore_flags():
    old = {k: get_flag(k) for k in ("FLAGS_tpu_sharded_weight_update",
                                    "FLAGS_tpu_comm_bucket_mb",
                                    "FLAGS_tpu_amp_level",
                                    "FLAGS_tpu_amp_dtype",
                                    "FLAGS_tpu_model_parallel")}
    yield
    set_flags(old)


def _fresh():
    from paddle_tpu.core import scope as scope_mod

    framework.switch_main_program(framework.Program())
    framework.switch_startup_program(framework.Program())
    scope_mod._global_scope = scope_mod.Scope()


def _batch(n=64):
    r = np.random.RandomState(0)
    return (r.rand(n, 32).astype("float32"),
            r.randint(0, 4, (n, 1)).astype("int64"))


def _mlp_loss(hidden=31):
    framework.default_main_program().random_seed = 1234
    framework.default_startup_program().random_seed = 1234
    img = fluid.layers.data(name="img", shape=[32], dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    # 31-wide: not divisible by any mesh size — every master/moment
    # flat buffer is padded
    h = fluid.layers.fc(input=img, size=hidden, act="relu")
    logits = fluid.layers.fc(input=h, size=4)
    return fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(logits, label))


def _train(opt_fn, flag, ndev=8, bucket_mb=0.0, steps=4, clip=False,
           decorate_kw=None, batch_n=64):
    """Losses of `steps` identical-feed steps of the AMP-decorated MLP;
    returns (losses, exe, prog, loss, plan, opt)."""
    import jax

    _fresh()
    set_flags({"FLAGS_tpu_sharded_weight_update": flag,
               "FLAGS_tpu_comm_bucket_mb": bucket_mb})
    x, y = _batch(batch_n)
    with framework.unique_name_guard():
        loss = _mlp_loss()
        if clip:
            fluid.clip.set_gradient_clip(
                fluid.clip.GradientClipByGlobalNorm(0.5))
        opt = mixed_precision.decorate(opt_fn(), **(decorate_kw or {}))
        opt.minimize(loss)
        fluid.clip._clip_attr.clear()
        prog = fluid.default_main_program()
        fluid.CompiledProgram(prog).with_data_parallel(
            loss_name=loss.name)
        if ndev != 8:
            from jax.sharding import Mesh

            prog._mesh = Mesh(np.array(jax.devices()[:ndev]), ("dp",))
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        losses = [float(exe.run(prog, feed={"img": x, "label": y},
                                fetch_list=[loss])[0].mean())
                  for _ in range(steps)]
        plan = getattr(prog, "_shard_plan", None)
    return losses, exe, prog, loss, plan, opt


# ---------------------------------------------------------------------------
# master-weight parity (acceptance criterion)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name,opt_fn,ndev", [
    ("sgd_2dev", lambda: O.SGDOptimizer(learning_rate=0.1), 2),
    ("momentum_4dev",
     lambda: O.MomentumOptimizer(learning_rate=0.1, momentum=0.9), 4),
    ("adam_8dev", lambda: O.AdamOptimizer(learning_rate=0.01), 8),
])
def test_sharded_master_parity_bit_identical(name, opt_fn, ndev):
    """bf16 compute + fp32 masters: the ZeRO-sharded master update is
    bit-identical to the unsharded (replicated) fp32-master reference
    given the same bf16 grads, on 2/4/8-device meshes."""
    l_rep, *_ = _train(opt_fn, False, ndev=ndev)
    l_sh, _, _, _, plan, _ = _train(opt_fn, True, ndev=ndev)
    assert plan is not None and plan.master_of, \
        "masters did not shard: %s" % (plan,)
    assert l_rep == l_sh, (name, l_rep, l_sh)


def test_amp_bucketing_gated_off_at_non_power_of_two_world():
    """ROADMAP carried numerics item (found by PR 9's elastic-shrink
    tests): AMP x BUCKETED grad collectives drift one bf16 ulp off the
    per-variable lowering on the CPU backend at world sizes where the
    /N mean rounds in bf16 (ndev=3) — the batched scatter's /N + cast
    fusion regroups one FMA contraction past the optimization barriers
    (the PR-4 CPU-fusion caveat, invisible at power-of-two worlds
    where /N is exact). The planner now gates bucketing OFF for AMP
    programs at non-power-of-two worlds on the CPU backend, records a
    structured `buckets_disabled` fallback reason, and the per-var
    lowering it degrades to is bit-identical at every N. Power-of-two
    worlds keep their buckets."""
    adam = lambda: O.AdamOptimizer(learning_rate=0.01)  # noqa: E731
    l_rep, *_ = _train(adam, False, ndev=3, batch_n=48)
    l_sh, _, prog, _, plan, _ = _train(adam, True, ndev=3,
                                       bucket_mb=1000.0, batch_n=48)
    assert plan is not None and not plan.buckets, \
        "bucketing engaged at ndev=3 under AMP on CPU"
    fb = [f for f in (getattr(prog, "_sharded_update_fallback", None)
                      or []) if f["kind"] == "buckets_disabled"]
    assert fb and "bf16 ulp" in fb[0]["reason"], fb
    assert l_rep == l_sh, (l_rep, l_sh)
    # power-of-two world: the gate stays out of the way
    _, _, prog4, _, plan4, _ = _train(adam, True, ndev=4,
                                      bucket_mb=1000.0, batch_n=48)
    assert plan4 is not None and plan4.buckets
    assert not [f for f in (getattr(prog4, "_sharded_update_fallback",
                                    None) or [])
                if f["kind"] == "buckets_disabled"]


def test_sharded_master_parity_with_clip_and_buckets():
    """Global-norm clip runs on the 16-bit grad shards (psum'd
    partials) and bucketed scatters stay bit-identical to per-var."""
    adam = lambda: O.AdamOptimizer(learning_rate=0.01)  # noqa: E731
    l_rep, *_ = _train(adam, False, clip=True)
    l_pv, *_ = _train(adam, True, clip=True)
    l_bk, _, _, _, plan, _ = _train(adam, True, clip=True,
                                    bucket_mb=1000.0)
    assert plan.buckets and plan.master_of
    assert l_rep == l_pv == l_bk


# ---------------------------------------------------------------------------
# layout + HBM evidence (acceptance criterion)
# ---------------------------------------------------------------------------

def test_params_live_bf16_with_sharded_masters():
    """Scope params are bf16; fp32 masters live as dp-sharded flat
    buffers; donation_report shows per-replica param bytes ~halved
    (2 + 4/N bytes/elem vs fp32 DP's 4) and the 16-bit all-gather."""
    import jax.numpy as jnp

    from paddle_tpu.core.scope import global_scope

    x, y = _batch()
    adam = lambda: O.AdamOptimizer(learning_rate=0.01)  # noqa: E731
    # ~0.001 MB cap: the MLP's grads split into several buckets, so the
    # ZeRO-2 peak model (max bucket + shards) beats all-grads-at-once
    _, exe, prog, loss, plan, _ = _train(adam, True, bucket_mb=0.001)
    for p in prog.all_parameters():
        v = global_scope().find_var(p.name)
        assert v.dtype == jnp.bfloat16, (p.name, v.dtype)
    # masters are sharded state: flat (padded,) buffers, P(dp)
    assert plan.master_of
    for pname, m in plan.master_of.items():
        info = plan.sharded_state[m]
        v = global_scope().find_var(m)
        assert tuple(v.shape) == (info.padded,)
        assert "dp" in str(getattr(v, "sharding", ""))
        assert info.dtype == np.dtype("float32")
    rep = exe.donation_report(prog, feed={"img": x, "label": y},
                              fetch_list=[loss])
    assert rep["param_masters_sharded"] == len(plan.master_of)
    per_replica = rep["param_bf16_bytes"] + rep["param_master_bytes"]
    # 8-way mesh: 2 + 4/8 = 2.5 bytes/elem vs 4 -> ~0.63x (+ padding)
    assert per_replica < 0.75 * rep["param_fp32_replicated_bytes"], rep
    assert rep["aliases_state"], rep
    # ZeRO-2 grad-lifetime model: peak grad HBM ~ max bucket + shards
    # — strictly below every-full-grad-at-once when grads split into
    # multiple buckets (full buffers die bucket-by-bucket)
    assert len(plan.buckets) >= 2
    assert rep["grad_peak_per_replica_bytes"] == \
        max(b.nbytes for b in plan.buckets) + \
        rep["grad_bucket_per_replica_bytes"]
    assert rep["grad_peak_per_replica_bytes"] < \
        rep["grad_replicated_peak_bytes"] + \
        rep["grad_bucket_per_replica_bytes"]


def test_collective_bytes_halve_vs_fp32():
    """The 16-bit grads/params halve BOTH collective legs' ICI bytes
    relative to the fp32 ZeRO run of the same model."""
    x, y = _batch()
    adam = lambda: O.AdamOptimizer(learning_rate=0.01)  # noqa: E731

    def census(amp):
        _fresh()
        set_flags({"FLAGS_tpu_sharded_weight_update": True,
                   "FLAGS_tpu_comm_bucket_mb": 0.0})
        with framework.unique_name_guard():
            loss = _mlp_loss()
            opt = mixed_precision.decorate(adam()) if amp else adam()
            opt.minimize(loss)
            prog = fluid.default_main_program()
            fluid.CompiledProgram(prog).with_data_parallel(
                loss_name=loss.name)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(fluid.default_startup_program())
            exe.run(prog, feed={"img": x, "label": y},
                    fetch_list=[loss])
            return exe.collective_report(
                prog, feed={"img": x, "label": y}, fetch_list=[loss])

    c32 = census(False)
    c16 = census(True)
    assert c16["reduce_scatter"]["ici_bytes"] * 2 == \
        c32["reduce_scatter"]["ici_bytes"]
    assert c16["all_gather"]["ici_bytes"] * 2 == \
        c32["all_gather"]["ici_bytes"]


def test_amp_off_is_untouched_and_kill_switch():
    """Undecorated fp32 programs lower with zero bf16 anywhere; the
    FLAGS_tpu_amp_level=O0 kill switch makes a decorated program lower
    identically to the undecorated one (byte-for-byte HLO)."""
    x, y = _batch()
    adam = lambda: O.AdamOptimizer(learning_rate=0.01)  # noqa: E731

    def text(decorated, level=""):
        _fresh()
        set_flags({"FLAGS_tpu_sharded_weight_update": True,
                   "FLAGS_tpu_comm_bucket_mb": 0.0,
                   "FLAGS_tpu_amp_level": level})
        with framework.unique_name_guard():
            loss = _mlp_loss()
            opt = mixed_precision.decorate(adam()) if decorated \
                else adam()
            opt.minimize(loss)
            prog = fluid.default_main_program()
            fluid.CompiledProgram(prog).with_data_parallel(
                loss_name=loss.name)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(fluid.default_startup_program())
            exe.run(prog, feed={"img": x, "label": y},
                    fetch_list=[loss])
            got = exe._cached_lowerable(prog, {"img": x, "label": y},
                                        [loss], None)
            return got[1].as_text(), prog

    t_plain, prog_plain = text(False)
    assert "bf16" not in t_plain
    assert not getattr(prog_plain, "_amp", False)
    t_killed, prog_killed = text(True, level="O0")
    assert t_killed == t_plain, "O0 kill switch must reproduce the " \
        "undecorated HLO byte-for-byte"
    assert not getattr(prog_killed, "_amp_master_of", None)
    t_amp, _ = text(True)
    assert "bf16" in t_amp


# ---------------------------------------------------------------------------
# checkpoint save/restore (tentpole d)
# ---------------------------------------------------------------------------

def test_checkpoint_roundtrip_unshards_masters(tmp_path):
    """Masters save at their LOGICAL fp32 shapes (unshard_scope_value,
    same path as the moments); params save bf16; a reload + continued
    training matches an uninterrupted run bit-for-bit."""
    import ml_dtypes

    adam = lambda: O.AdamOptimizer(learning_rate=0.01)  # noqa: E731
    x, y = _batch()
    l_ref, *_ = _train(adam, True, steps=4)
    _, exe, prog, loss, plan, _ = _train(adam, True, steps=2)
    fluid.io.save_persistables(exe, str(tmp_path), main_program=prog)
    pname, m = next(iter(plan.master_of.items()))
    saved_m = np.load(os.path.join(str(tmp_path),
                                   m.replace("/", "%2F") + ".npy"))
    info = plan.sharded_state[m]
    assert tuple(saved_m.shape) == info.shape, \
        "master must persist at its LOGICAL fp32 shape"
    assert saved_m.dtype == np.float32
    # bf16 params persist with their true dtype (npy descr degrades
    # ml_dtypes to raw void; io writes a .dtype sidecar)
    saved_p = fluid.io._load_dict(str(tmp_path), [pname])[pname]
    assert saved_p.dtype == ml_dtypes.bfloat16
    fluid.io.load_persistables(exe, str(tmp_path), main_program=prog)
    l_cont = [float(exe.run(prog, feed={"img": x, "label": y},
                            fetch_list=[loss])[0].mean())
              for _ in range(2)]
    assert l_ref[2:] == l_cont


# ---------------------------------------------------------------------------
# fp16 dynamic loss scaling (satellite: state-machine tests)
# ---------------------------------------------------------------------------

def _fp16_setup(init_scaling, incr_every=2, decr_every=1, steps=0,
                ndev=8):
    from paddle_tpu.core.scope import global_scope

    _fresh()
    set_flags({"FLAGS_tpu_sharded_weight_update": True,
               "FLAGS_tpu_comm_bucket_mb": 0.0})
    r = np.random.RandomState(0)
    x = r.rand(64, 32).astype("float32")
    y = r.randint(0, 4, (64, 1)).astype("int64")
    with framework.unique_name_guard():
        loss = _mlp_loss(hidden=16)
        opt = mixed_precision.decorate(
            O.SGDOptimizer(learning_rate=0.1), amp_dtype="float16",
            init_loss_scaling=init_scaling,
            incr_every_n_steps=incr_every,
            decr_every_n_nan_or_inf=decr_every, incr_ratio=2.0,
            decr_ratio=0.5)
        opt.minimize(loss)
        prog = fluid.default_main_program()
        fluid.CompiledProgram(prog).with_data_parallel(
            loss_name=loss.name)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        dls = opt._scale_state
        assert dls is not None

        def step():
            exe.run(prog, feed={"img": x, "label": y},
                    fetch_list=[loss])

        def read(name):
            return float(np.asarray(
                global_scope().find_var(name)).reshape(-1)[0])

        def master():
            # layout-agnostic read: logical before the first compile,
            # flat dp-sharded (padded) after
            m = sorted(opt.get_master_weights().values())[0]
            mv = prog.global_block()._find_var_recursive(m)
            numel = int(np.prod(mv.shape))
            v = np.asarray(global_scope().find_var(m))
            return v.reshape(-1)[:numel].copy()

        for _ in range(steps):
            step()
    return step, read, master, dls, opt, exe, prog


def test_fp16_overflow_skips_update_and_decays_scale():
    """A scale large enough to overflow fp16 grads: the whole weight
    update (master included) is SKIPPED under the lax.cond, the bad
    counter trips and the scale decays by decr_ratio; once the scale
    has decayed into range, updates apply again."""
    step, read, master, dls, opt, _, _ = _fp16_setup(2.**20)
    p0 = master()
    s0 = read(dls["scale"])
    step()
    assert read(dls["scale"]) == s0 * 0.5, "overflow must decay"
    np.testing.assert_array_equal(p0, master())  # update skipped
    # keep stepping until the scale is in range: update applies
    for _ in range(8):
        step()
        if not np.array_equal(p0, master()):
            break
    assert not np.array_equal(p0, master()), \
        "update never resumed after the scale decayed into range"
    assert opt.get_loss_scaling() < 2.**20


def test_fp16_scale_growth_every_n_clean_steps():
    """incr_every_n_steps=2 clean steps double the scale; the good
    counter resets after each growth."""
    step, read, master, dls, *_ = _fp16_setup(2.**4, incr_every=2)
    s0 = read(dls["scale"])
    p0 = master()
    step()
    assert read(dls["scale"]) == s0
    assert read(dls["good"]) == 1
    assert not np.array_equal(p0, master()), "clean step must update"
    step()
    assert read(dls["scale"]) == s0 * 2
    assert read(dls["good"]) == 0
    step()
    assert read(dls["scale"]) == s0 * 2
    assert read(dls["good"]) == 1


def test_fp16_scale_state_survives_checkpoint(tmp_path):
    """The scale/good/bad state persists through save_persistables +
    load_persistables like any optimizer state: a restored run resumes
    the state machine exactly where it left off."""
    step, read, _, dls, _, exe, prog = _fp16_setup(2.**4, incr_every=3,
                                                   steps=2)
    want = {k: read(dls[k]) for k in ("scale", "good", "bad")}
    assert want["good"] == 2
    fluid.io.save_persistables(exe, str(tmp_path), main_program=prog)
    step()  # mutate past the snapshot
    assert read(dls["good"]) != want["good"]
    fluid.io.load_persistables(exe, str(tmp_path), main_program=prog)
    got = {k: read(dls[k]) for k in ("scale", "good", "bad")}
    assert got == want
    step()  # third clean step after restore -> growth fires
    assert read(dls["scale"]) == want["scale"] * 2
    assert read(dls["good"]) == 0


def test_fp16_dynamic_scaling_sharded_parity():
    """With an in-range scale, fp16 dynamic-loss-scaled training is
    bit-identical between the sharded and replicated master update."""
    kw = dict(decorate_kw=dict(amp_dtype="float16",
                               init_loss_scaling=2.**8,
                               incr_every_n_steps=3))
    sgd = lambda: O.SGDOptimizer(learning_rate=0.1)  # noqa: E731
    l_rep, *_ = _train(sgd, False, **kw)
    l_sh, _, _, _, plan, _ = _train(sgd, True, **kw)
    assert plan is not None and plan.master_of
    assert l_rep == l_sh


def test_fp16_dls_with_global_norm_clip_and_aux_fetch():
    """Two cond-typing regressions: (a) global-norm clip promotes the
    rebound fp16 grads to fp32 inside the apply branch — the branch
    exit must re-align dtypes with the skip side or lax.cond rejects
    the mismatched pytrees; (b) a post-section-CREATED var (the global
    grad norm) must ride the cond outputs to stay fetchable — zeros on
    a skipped step, the real value on an applied one."""
    from paddle_tpu.fluid.framework import grad_var_name

    _fresh()
    set_flags({"FLAGS_tpu_sharded_weight_update": True,
               "FLAGS_tpu_comm_bucket_mb": 0.0})
    x, y = _batch()
    with framework.unique_name_guard():
        loss = _mlp_loss(hidden=16)
        fluid.clip.set_gradient_clip(
            fluid.clip.GradientClipByGlobalNorm(1.0))
        opt = mixed_precision.decorate(
            O.SGDOptimizer(learning_rate=0.1), amp_dtype="float16",
            init_loss_scaling=2.**8, incr_every_n_steps=100)
        opt.minimize(loss)
        fluid.clip._clip_attr.clear()
        prog = fluid.default_main_program()
        fluid.CompiledProgram(prog).with_data_parallel(
            loss_name=loss.name)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        # a clipped (rebound, dtype-promoted inside the branch) grad
        # var fetches fine, as does a post-CREATED intermediate
        gname = grad_var_name(prog.all_parameters()[0].name)
        post = prog.global_block().ops
        bwd = next(i for i, op in enumerate(post)
                   if op.type == "backward")
        created = next(
            n for op in post[bwd + 1:]
            for n in op.output_arg_names
            if prog.global_block()._find_var_recursive(n) is not None
            and "sqrt" in op.type)
        outs = [exe.run(prog, feed={"img": x, "label": y},
                        fetch_list=[loss, gname, created])
                for _ in range(3)]
        for o in outs:
            assert np.isfinite(np.asarray(o[0])).all()
            # the global norm: one live positive value (replicated
            # per-shard by the non-persistable fetch spec)
            norm = np.unique(np.asarray(o[2]))
            assert norm.size == 1 and norm[0] > 0, norm


def test_fp16_dls_disabled_under_explicit_sync_with_warning():
    """Explicit-sync (fleet) programs sum grads inside the post
    section: the finite check would see pre-sum values and the unscale
    would run pre-sum — mis-protection. The lowering must disable dls
    LOUDLY and pass the scale state through unchanged."""
    import warnings as _w

    from paddle_tpu import fleet
    from paddle_tpu.core.scope import global_scope

    _fresh()
    set_flags({"FLAGS_tpu_sharded_weight_update": True,
               "FLAGS_tpu_comm_bucket_mb": 0.0})
    x, y = _batch()
    with framework.unique_name_guard():
        loss = _mlp_loss(hidden=16)
        opt = mixed_precision.decorate(
            O.SGDOptimizer(learning_rate=0.1), amp_dtype="float16",
            init_loss_scaling=2.**10)
        opt.minimize(loss)
        prog = fluid.default_main_program()
        fleet.transpile_collective(prog)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        with _w.catch_warnings(record=True) as rec:
            _w.simplefilter("always")
            exe.run(prog, feed={"img": x, "label": y},
                    fetch_list=[loss])
        assert any("explicit-sync" in str(w.message) for w in rec), \
            [str(w.message) for w in rec]
        dls = opt._scale_state
        exe.run(prog, feed={"img": x, "label": y}, fetch_list=[loss])
        scale = float(np.asarray(
            global_scope().find_var(dls["scale"])).reshape(-1)[0])
        assert scale == 2.**10, "scale state must pass through unchanged"


# ---------------------------------------------------------------------------
# planner fallback reasons (satellite: ZeRO-1 gap surfacing)
# ---------------------------------------------------------------------------

def test_fallback_reasons_are_structured_not_silent():
    """An unplannable program (dpsgd has no flat-shard rule) records a
    structured per-var reason on program._sharded_update_fallback
    instead of falling back silently."""
    _fresh()
    set_flags({"FLAGS_tpu_sharded_weight_update": True})
    x, y = _batch()
    with framework.unique_name_guard():
        loss = _mlp_loss()
        O.DpsgdOptimizer(learning_rate=0.1).minimize(loss)
        prog = fluid.default_main_program()
        fluid.CompiledProgram(prog).with_data_parallel(
            loss_name=loss.name)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        exe.run(prog, feed={"img": x, "label": y}, fetch_list=[loss])
        assert getattr(prog, "_shard_plan", None) is None
        fb = getattr(prog, "_sharded_update_fallback", None)
        assert fb, "decline must be recorded"
        assert fb[0]["kind"] == "declined"
        assert fb[0]["op"] == "dpsgd"
        assert "shard-aware" in fb[0]["reason"]


# ---------------------------------------------------------------------------
# hapi dygraph surface (Model.prepare(amp_level=...))
# ---------------------------------------------------------------------------

def test_hapi_amp_level_o2_masters():
    """prepare(amp_level='O2'): network params live bf16, the eager
    wrapper keeps fp32 masters, and training converges on a toy fit."""
    import jax.numpy as jnp

    from paddle_tpu.fluid.dygraph import Linear
    from paddle_tpu.hapi.model import Model

    r = np.random.RandomState(3)
    x = r.rand(64, 16).astype("float32")
    y = r.randint(0, 4, (64, 1)).astype("int64")
    net = Linear(16, 4)
    m = Model(net)
    m.prepare(
        O.SGDOptimizer(learning_rate=0.5,
                       parameter_list=net.parameters()),
        loss_function=lambda pred, label: fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(pred, label)),
        amp_level="O2")
    from paddle_tpu.fluid.contrib.mixed_precision import \
        EagerMasterWeightOptimizer

    assert isinstance(m._optimizer, EagerMasterWeightOptimizer)
    for p in net.parameters():
        assert p._value().dtype == jnp.bfloat16, p.name
    losses = [m.train_batch([x], [y])[0][0] for _ in range(12)]
    assert losses[-1] < losses[0]
    for p in net.parameters():
        assert p._value().dtype == jnp.bfloat16  # live stays bf16
        master = m._optimizer._masters[p.name]
        assert master.dtype == jnp.float32
        np.testing.assert_array_equal(
            np.asarray(p._value()),
            np.asarray(master.astype(jnp.bfloat16)))


def test_hapi_amp_master_invalidated_on_external_reassignment():
    """Regression: after Model.load (or any external _assign_raw) the
    eager wrapper must re-seed its fp32 master from the NEW live value
    — a stale cached master would silently overwrite the loaded
    weights on the next step."""
    import jax.numpy as jnp

    from paddle_tpu.fluid.dygraph import Linear
    from paddle_tpu.hapi.model import Model

    r = np.random.RandomState(3)
    x = r.rand(32, 8).astype("float32")
    y = r.randint(0, 2, (32, 1)).astype("int64")
    net = Linear(8, 2)
    m = Model(net)
    m.prepare(
        O.SGDOptimizer(learning_rate=0.1,
                       parameter_list=net.parameters()),
        loss_function=lambda pred, label: fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(pred, label)),
        amp_level="O2")
    for _ in range(3):
        m.train_batch([x], [y])  # masters cached
    # external same-shape reassignment (what Model.load does)
    loaded = jnp.asarray(
        r.rand(*net.parameters()[0].shape).astype("float32")
    ).astype(jnp.bfloat16)
    net.parameters()[0]._assign_raw(loaded)
    m.train_batch([x], [y])
    new_master = m._optimizer._masters[net.parameters()[0].name]
    # one SGD step from the LOADED value, not from the stale master:
    # the loaded weights moved by at most lr*|grad|, not back to the
    # pre-load trajectory
    drift = np.abs(np.asarray(new_master, np.float32)
                   - np.asarray(loaded, np.float32))
    assert float(drift.max()) < 0.2, \
        "master was not re-seeded from the externally assigned value"


def test_hapi_amp_skips_bn_stats_and_survives_load(tmp_path):
    """Regression pair: (a) BatchNorm running mean/variance
    (non-trainable) stay fp32 under amp_level — their momentum update
    accumulates and bf16 resolution would degrade eval statistics;
    (b) Model.load re-applies the compute-dtype cast (set_dict restores
    the checkpoint's fp32 dtypes, which would silently turn AMP and
    the master wrapper off)."""
    import jax.numpy as jnp

    from paddle_tpu.fluid.dygraph import BatchNorm, Linear, Sequential
    from paddle_tpu.hapi.model import Model

    r = np.random.RandomState(3)
    x = r.rand(32, 8).astype("float32")
    y = r.randint(0, 2, (32, 1)).astype("int64")

    def build():
        net = Sequential(Linear(8, 8), BatchNorm(8), Linear(8, 2))
        m = Model(net)
        m.prepare(
            O.SGDOptimizer(learning_rate=0.1,
                           parameter_list=net.parameters()),
            loss_function=lambda p, l: fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(p, l)),
            amp_level="O2")
        return m, net

    m, net = build()
    stats = [p for p in net.parameters()
             if not getattr(p, "trainable", True)]
    assert stats, "BatchNorm must expose running stats"
    for p in stats:
        assert p._value().dtype == jnp.float32, p.name
    m.train_batch([x], [y])
    path = str(tmp_path / "ckpt")
    m.save(path)
    m2, net2 = build()
    m2.load(path)
    for p in net2.parameters():
        want = jnp.bfloat16 if getattr(p, "trainable", True) \
            else jnp.float32
        assert p._value().dtype == want, (p.name, p._value().dtype)
    m2.train_batch([x], [y])
    assert m2._optimizer._masters, "masters must re-engage after load"


def test_hapi_amp_level_validation():
    from paddle_tpu.fluid.dygraph import Linear
    from paddle_tpu.hapi.model import Model

    with pytest.raises(ValueError):
        Model(Linear(4, 2)).prepare(amp_level="O3")


# ---------------------------------------------------------------------------
# fp8 tier (amp_dtype="float8_e4m3"): delayed-scaling qdq on the bf16
# carrier — parity, kill switch, state slots, checkpoint + elastic
# survival, eager-master coexistence
# ---------------------------------------------------------------------------

def _fp8_kw():
    return {"amp_dtype": "float8_e4m3"}


def _scope():
    from paddle_tpu.core import scope as scope_mod

    return scope_mod._global_scope


def _fp8_state_names(prog):
    cfg = prog._amp_fp8
    return sorted(s[k] for group in (cfg["inputs"], cfg["grads"])
                  for s in group.values() for k in ("hist", "scale"))


def _fp8_state_values(prog):
    return {n: np.asarray(_scope().find_var(n), np.float32).copy()
            for n in _fp8_state_names(prog)}


@pytest.mark.parametrize("ndev,bucket_mb", [(2, 0.0), (8, 0.25)])
def test_fp8_zero1_bit_identical_and_close_to_bf16(ndev, bucket_mb):
    """The fp8 qdq sites live in COMPUTE, before the grad collectives:
    ZeRO-1 sharded fp8 training is bit-identical to replicated fp8
    (same composition theorem as bf16), and the qdq perturbation keeps
    losses close to — but measurably distinct from — the plain bf16
    trajectory."""
    adam = lambda: O.AdamOptimizer(learning_rate=0.01)  # noqa: E731
    l_rep, _, prog, *_ = _train(adam, False, ndev=ndev,
                                decorate_kw=_fp8_kw())
    assert prog._amp_fp8["inputs"] and prog._amp_fp8["grads"]
    assert str(prog._amp_dtype) == "bfloat16", \
        "fp8 programs keep the bf16 carrier dtype"
    l_sh, _, _, _, plan, _ = _train(adam, True, ndev=ndev,
                                    bucket_mb=bucket_mb,
                                    decorate_kw=_fp8_kw())
    assert plan is not None and plan.master_of
    assert l_rep == l_sh, (l_rep, l_sh)
    assert all(np.isfinite(l_sh)) and l_sh[-1] < l_sh[0]
    l_bf, *_ = _train(adam, True, ndev=ndev, bucket_mb=bucket_mb)
    assert l_sh != l_bf, "qdq must actually be in the graph"
    assert np.allclose(l_sh, l_bf, rtol=0.2, atol=0.05), (l_sh, l_bf)


def test_fp8_kill_switch_hlo_and_state_slots():
    """FLAGS_tpu_amp_dtype="bfloat16" lowers an fp8-decorated program
    byte-identically to the plain bf16 one; without the switch the HLO
    carries e4m3 forward casts and e5m2 grad casts, and the delayed-
    scaling state rides the backward op's Fp8ScaleState slots."""
    x, y = _batch()
    adam = lambda: O.AdamOptimizer(learning_rate=0.01)  # noqa: E731

    def text(kw, flag_dtype=""):
        _fresh()
        set_flags({"FLAGS_tpu_sharded_weight_update": True,
                   "FLAGS_tpu_comm_bucket_mb": 0.0,
                   "FLAGS_tpu_amp_dtype": flag_dtype})
        with framework.unique_name_guard():
            loss = _mlp_loss()
            mixed_precision.decorate(adam(), **kw).minimize(loss)
            prog = fluid.default_main_program()
            fluid.CompiledProgram(prog).with_data_parallel(
                loss_name=loss.name)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(fluid.default_startup_program())
            exe.run(prog, feed={"img": x, "label": y},
                    fetch_list=[loss])
            got = exe._cached_lowerable(prog, {"img": x, "label": y},
                                        [loss], None)
            return got[1].as_text(), prog

    t_bf, _ = text({})
    t_f8, prog8 = text(_fp8_kw())
    low = t_f8.lower()
    assert "f8e4m3" in low, "forward qdq must cast through e4m3"
    assert "f8e5m2" in low, "grad qdq must cast through e5m2"
    bop = next(op for op in prog8.global_block().ops
               if op.type == "backward")
    slots = bop.input_names.get("Fp8ScaleState")
    assert slots and slots == bop.output_names.get("Fp8ScaleState")
    assert set(slots) == set(_fp8_state_names(prog8))
    assert bop.attrs["fp8_delayed_scaling"] is prog8._amp_fp8
    t_killed, progk = text(_fp8_kw(), flag_dtype="bfloat16")
    assert t_killed == t_bf, "fp8 kill switch must reproduce the " \
        "plain bf16 HLO byte-for-byte"
    assert getattr(progk, "_amp_fp8", None) is None


def test_fp8_composes_with_tensor_parallel():
    """fp8 qdq + TP on the (dcn, ici, model) mesh: the scale update
    pmax's over every live axis so the delayed-scaling state stays
    replica-uniform, and losses track the bf16 TP trajectory."""
    import jax
    from jax.sharding import Mesh

    adam = lambda: O.AdamOptimizer(learning_rate=0.01)  # noqa: E731
    x, y = _batch()

    def run(kw):
        _fresh()
        set_flags({"FLAGS_tpu_sharded_weight_update": True,
                   "FLAGS_tpu_comm_bucket_mb": 0.0})
        with framework.unique_name_guard():
            loss = _mlp_loss()
            mixed_precision.decorate(adam(), **kw).minimize(loss)
            prog = fluid.default_main_program()
            fluid.CompiledProgram(prog).with_data_parallel(
                loss_name=loss.name)
            prog._mesh = Mesh(
                np.array(jax.devices()).reshape(1, 4, 2),
                ("dcn", "ici", "model"))
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(fluid.default_startup_program())
            losses = [float(exe.run(prog, feed={"img": x, "label": y},
                                    fetch_list=[loss])[0].mean())
                      for _ in range(4)]
        return losses, prog

    l_bf, prog_bf = run({})
    l_f8, prog = run(_fp8_kw())
    tpp = getattr(prog, "_tp_plan", None)
    assert tpp is not None and tpp.params, \
        getattr(prog, "_sharded_update_fallback", None)
    assert prog._amp_fp8["inputs"]
    assert all(np.isfinite(l_f8)) and l_f8[-1] < l_f8[0]
    assert np.allclose(l_f8, l_bf, rtol=0.2, atol=0.05), (l_f8, l_bf)
    # state is replica-uniform: every scale/hist is a plain replicated
    # scope value, never TP- or ZeRO-sharded
    plan = getattr(prog, "_shard_plan", None)
    for n in _fp8_state_names(prog):
        assert n not in tpp.params
        assert plan is None or n not in plan.sharded_state


def test_fp8_scale_state_advances_and_checkpoints(tmp_path):
    """Satellite 5a: the @FP8_SCALE / @FP8_AMAX_HIST vars behave like
    optimizer state — they advance each step, persist through
    save_persistables / load_persistables, and a reload + continued
    run reproduces the uninterrupted trajectory bit-for-bit."""
    adam = lambda: O.AdamOptimizer(learning_rate=0.01)  # noqa: E731
    x, y = _batch()
    l_ref, *_ = _train(adam, True, steps=4, decorate_kw=_fp8_kw())
    _, exe, prog, loss, _, _ = _train(adam, True, steps=2,
                                      decorate_kw=_fp8_kw())
    cfg = prog._amp_fp8
    state = _fp8_state_values(prog)
    some_in = next(iter(cfg["inputs"].values()))
    assert float(state[some_in["hist"]].max()) > 0.0, \
        "amax history must observe live abs-max values"
    assert float(state[some_in["scale"]][0]) != 1.0, \
        "scale must leave its init once the window is non-empty"
    fluid.io.save_persistables(exe, str(tmp_path), main_program=prog)
    for n, want in state.items():
        saved = np.load(os.path.join(str(tmp_path),
                                     n.replace("/", "%2F") + ".npy"))
        assert np.array_equal(saved, want), n
    fluid.io.load_persistables(exe, str(tmp_path), main_program=prog)
    l_cont = [float(exe.run(prog, feed={"img": x, "label": y},
                            fetch_list=[loss])[0].mean())
              for _ in range(2)]
    assert l_ref[2:] == l_cont, (l_ref, l_cont)


def test_fp8_state_survives_elastic_reshard(tmp_path):
    """Satellite 5b: fp8 state vars are replicated [H]/[1] scalars —
    an N=8 checkpoint restores verbatim into an N'=4 world (no
    re-shard math applies to them) and training continues finite,
    rolling the history forward."""
    import jax
    from jax.sharding import Mesh

    adam = lambda: O.AdamOptimizer(learning_rate=0.01)  # noqa: E731
    x, y = _batch()

    def build(ndev):
        _fresh()
        set_flags({"FLAGS_tpu_sharded_weight_update": True,
                   "FLAGS_tpu_comm_bucket_mb": 0.0})
        with framework.unique_name_guard():
            loss = _mlp_loss()
            mixed_precision.decorate(
                adam(), **_fp8_kw()).minimize(loss)
            prog = fluid.default_main_program()
            fluid.CompiledProgram(prog).with_data_parallel(
                loss_name=loss.name)
            if ndev != 8:
                prog._mesh = Mesh(np.array(jax.devices()[:ndev]),
                                  ("dp",))
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(fluid.default_startup_program())
        return exe, prog, loss

    exe, prog, loss = build(8)
    for _ in range(2):
        exe.run(prog, feed={"img": x, "label": y}, fetch_list=[loss])
    saved = _fp8_state_values(prog)
    fluid.io.save_persistables(exe, str(tmp_path), main_program=prog)

    exe4, prog4, loss4 = build(4)
    assert _fp8_state_names(prog4) == sorted(saved), \
        "same program, same state var names across world sizes"
    fluid.io.load_persistables(exe4, str(tmp_path),
                               main_program=prog4)
    for n, want in saved.items():
        assert np.array_equal(
            np.asarray(_scope().find_var(n), np.float32), want), n
    l = float(exe4.run(prog4, feed={"img": x, "label": y},
                       fetch_list=[loss4])[0].mean())
    assert np.isfinite(l)
    after = _fp8_state_values(prog4)
    rolled = [n for n in saved
              if not np.array_equal(saved[n], after[n])]
    assert rolled, "history must keep rolling after the re-shard"


def test_fp8_state_unmoved_by_eager_master_rebind():
    """Satellite 5c: the dygraph EagerMasterWeightOptimizer rebind
    path (external _assign_raw -> master re-seed) runs in object
    space and must not touch the graph program's fp8 scope state; the
    graph keeps stepping afterwards and its state keeps advancing."""
    import jax.numpy as jnp

    from paddle_tpu.fluid.dygraph import Linear
    from paddle_tpu.hapi.model import Model

    adam = lambda: O.AdamOptimizer(learning_rate=0.01)  # noqa: E731
    x, y = _batch()
    _, exe, prog, loss, _, _ = _train(adam, True, steps=1,
                                      decorate_kw=_fp8_kw())
    before = _fp8_state_values(prog)

    r = np.random.RandomState(3)
    dx = r.rand(32, 8).astype("float32")
    dy = r.randint(0, 2, (32, 1)).astype("int64")
    net = Linear(8, 2)
    m = Model(net)
    m.prepare(
        O.SGDOptimizer(learning_rate=0.1,
                       parameter_list=net.parameters()),
        loss_function=lambda pred, label: fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(pred, label)),
        amp_level="O2")
    m.train_batch([dx], [dy])
    loaded = jnp.asarray(
        r.rand(*net.parameters()[0].shape).astype("float32")
    ).astype(jnp.bfloat16)
    net.parameters()[0]._assign_raw(loaded)
    m.train_batch([dx], [dy])
    assert m._optimizer._masters, "rebind path must have engaged"

    mid = _fp8_state_values(prog)
    for n, want in before.items():
        assert np.array_equal(mid[n], want), \
            "eager rebind must not touch graph fp8 state: %s" % n
    exe.run(prog, feed={"img": x, "label": y}, fetch_list=[loss])
    after = _fp8_state_values(prog)
    assert any(not np.array_equal(after[n], before[n])
               for n in before), "graph fp8 state must keep advancing"
