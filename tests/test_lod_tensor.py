"""Multi-level LoD (VERDICT r2 missing #8; reference:
framework/lod_tensor.h:52 nested offset LoD +
python/paddle/fluid/lod_tensor.py create_lod_tensor)."""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid


def test_single_level_create_and_offsets():
    # reference doc example: two sentences of 2 and 3 words
    t = fluid.create_lod_tensor(np.arange(5).reshape(5, 1), [[2, 3]],
                                fluid.CPUPlace())
    assert t.lod() == [[0, 2, 5]]
    assert t.recursive_sequence_lengths() == [[2, 3]]
    assert t.has_valid_recursive_sequence_lengths()
    assert t.shape() == [5, 1]


def test_two_level_paragraphs_sentences_words():
    # 2 paragraphs: first has 2 sentences (3 + 1 words), second has 1
    # sentence (2 words) -> 6 word rows total
    data = np.arange(12, dtype="float32").reshape(6, 2)
    t = fluid.create_lod_tensor(data, [[2, 1], [3, 1, 2]],
                                fluid.CPUPlace())
    assert t.lod() == [[0, 2, 3], [0, 3, 4, 6]]
    assert t.lod_level() == 2
    assert t.has_valid_recursive_sequence_lengths()
    # offsets of level 0 partition level 1's sequences; level 1's
    # offsets partition the payload rows
    assert t.lod()[0][-1] == len(t.lod()[1]) - 1
    assert t.lod()[1][-1] == data.shape[0]


def test_invalid_lod_rejected():
    with pytest.raises(AssertionError):
        fluid.create_lod_tensor(np.zeros((5, 1)), [[2, 2]],
                                fluid.CPUPlace())  # sums to 4, not 5
    t = fluid.LoDTensor(np.zeros((4, 1)), [[0, 2, 5]])
    assert not t.has_valid_recursive_sequence_lengths()
    t2 = fluid.LoDTensor(np.zeros((5, 1)), [[0, 3, 2]])  # decreasing
    assert not t2.has_valid_recursive_sequence_lengths()


def test_nested_list_data():
    # reference: list data converted row-wise with top-level check
    t = fluid.create_lod_tensor([[1, 2], [3, 4, 5]], [[2, 3]],
                                fluid.CPUPlace())
    assert t.shape()[0] == 5
    np.testing.assert_array_equal(np.asarray(t).ravel(),
                                  [1, 2, 3, 4, 5])


def test_padded_bridge_roundtrip():
    data = np.arange(12, dtype="float32").reshape(6, 2)
    t = fluid.create_lod_tensor(data, [[2, 1], [3, 1, 2]],
                                fluid.CPUPlace())
    padded, lens = t.to_padded(pad_value=-1.0)
    assert padded.shape == (3, 3, 2)  # 3 sentences, max 3 words
    np.testing.assert_array_equal(lens, [3, 1, 2])
    assert padded[1, 1, 0] == -1.0  # padding
    back = fluid.LoDTensor.from_padded(padded, lens, outer_lens=[2, 1])
    np.testing.assert_array_equal(back.numpy(), data)
    assert back.lod() == t.lod()


def test_padded_feeds_sequence_op():
    """The bridge layout drives the device-side sequence ops: pool the
    WORDS of each sentence of a 2-level LoD batch."""
    data = np.arange(12, dtype="float32").reshape(6, 2)
    t = fluid.create_lod_tensor(data, [[2, 1], [3, 1, 2]],
                                fluid.CPUPlace())
    padded, lens = t.to_padded()

    x = fluid.layers.data(name="lod_x", shape=[3, 2], dtype="float32")
    length = fluid.layers.data(name="lod_len", shape=[1], dtype="int64")
    pooled = fluid.layers.sequence_pool(x, "sum", length=length)
    exe = fluid.Executor(fluid.CPUPlace())
    out = exe.run(feed={"lod_x": padded,
                        "lod_len": lens.reshape(-1, 1)},
                  fetch_list=[pooled])
    want = np.stack([data[0:3].sum(0), data[3:4].sum(0),
                     data[4:6].sum(0)])
    np.testing.assert_allclose(np.asarray(out[0]), want, rtol=1e-6)


def test_create_random_int_lodtensor():
    t = fluid.create_random_int_lodtensor([[2, 3]], [1],
                                          fluid.CPUPlace(), 0, 9,
                                          seed=0)
    assert t.shape() == [5, 1]
    assert t.recursive_sequence_lengths() == [[2, 3]]
    a = np.asarray(t)
    assert a.min() >= 0 and a.max() <= 9
