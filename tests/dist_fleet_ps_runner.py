"""Fleet 2.0 parameter-server runner (strategy.a_sync through the
public fleet API; reference: fleet parameter_server mode over the
DistributeTranspiler — role makers, init_server/run_server,
init_worker). Spawned as subprocesses by test_dist_ps.py.

argv: pserver <server_idx> <pserver_eps> <n_trainers>
      trainer <trainer_id> <pserver_eps> <n_trainers>
Prints LOSS <v> per trainer step / SERVED when a pserver drains."""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import paddle_tpu.fluid as fluid  # noqa: E402
from paddle_tpu import fleet  # noqa: E402
from paddle_tpu.fleet.role_maker import (  # noqa: E402
    Role, UserDefinedRoleMaker)
from paddle_tpu.fluid import framework  # noqa: E402

# ONE model + dataset for the whole PS test family (the data() comment
# about learnable labels is load-bearing — VERDICT r3 weak #1b)
from dist_ps_runner import BATCH, build_net as build, data  # noqa: E402

LR = 0.5
STEPS = 5


def _minimize(role, current_id, eps, n_trainers):
    main, startup, loss = build()
    rm = UserDefinedRoleMaker(current_id=current_id, role=role,
                              worker_num=n_trainers,
                              server_endpoints=eps.split(","))
    fleet.init(rm, is_collective=False)
    st = fleet.DistributedStrategy()
    st.a_sync = True
    opt = fleet.distributed_optimizer(
        fluid.optimizer.SGDOptimizer(learning_rate=LR), st)
    with framework.program_guard(main, startup):
        opt.minimize(loss, startup_program=startup)
    return main, startup, loss


def run_pserver(idx, eps, n_trainers):
    _minimize(Role.SERVER, idx, eps, n_trainers)
    assert fleet.fleet.is_server()
    fleet.fleet.init_server()
    print("SERVING", flush=True)
    fleet.fleet.run_server()
    print("SERVED", flush=True)


def run_trainer(tid, eps, n_trainers):
    from paddle_tpu.core.scope import Scope

    main, startup, loss = _minimize(Role.WORKER, tid, eps, n_trainers)
    assert fleet.fleet.is_worker()
    fleet.fleet.init_worker()  # waits for pserver ports
    scope = Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    x, y = data()
    half = BATCH // n_trainers
    xs = x[tid * half:(tid + 1) * half]
    ys = y[tid * half:(tid + 1) * half]
    for _ in range(STEPS):
        out = exe.run(main, feed={"x": xs, "label": ys},
                      fetch_list=[loss], scope=scope)
        print("LOSS %.6f" % float(np.asarray(out[0]).reshape(-1)[0]),
              flush=True)
    exe.close()  # complete() so the pservers drain and exit


if __name__ == "__main__":
    kind = sys.argv[1]
    if kind == "pserver":
        run_pserver(int(sys.argv[2]), sys.argv[3], int(sys.argv[4]))
    elif kind == "trainer":
        run_trainer(int(sys.argv[2]), sys.argv[3], int(sys.argv[4]))
    else:
        raise SystemExit("unknown role %r" % kind)
