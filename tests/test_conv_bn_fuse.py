"""conv_bn_fuse inference pass (reference: ir/conv_bn_fuse_pass.cc) —
a frozen batch_norm folds into the preceding conv's weights + one
channel bias at model load. XLA cannot do this (params are runtime
inputs), so it is a real load-time pass with scope values. Output
parity within fp tolerance; BN ops gone from the predictor program."""
import tempfile

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework


def _save_convbn_model(d):
    main, st = framework.Program(), framework.Program()
    main.random_seed = st.random_seed = 4
    with framework.program_guard(main, st):
        with framework.unique_name_guard():
            img = fluid.layers.data("image", shape=[3, 16, 16],
                                    dtype="float32")
            h = fluid.layers.conv2d(img, 8, 3, padding=1,
                                    bias_attr=False)
            h = fluid.layers.batch_norm(h, act="relu", is_test=True)
            h = fluid.layers.conv2d(h, 8, 3, padding=1, bias_attr=False)
            h = fluid.layers.batch_norm(h, is_test=True)
            h = fluid.layers.pool2d(h, pool_type="avg",
                                    global_pooling=True)
            out = fluid.layers.fc(h, size=5, act="softmax")
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(st)
    # make every BN stat/affine non-trivial so the parity assertion can
    # catch fold-math bugs (sign of the mean term, wrong scale axis):
    # perturb moving mean/var AND gamma/beta of the batch_norm layers
    import jax.numpy as jnp

    from paddle_tpu.core.scope import global_scope

    r = np.random.RandomState(0)
    perturbed = 0
    for name in list(global_scope().local_var_names()):
        if not name.startswith("batch_norm"):
            continue
        v = global_scope().find_var(name)
        if v is None or not hasattr(v, "shape"):
            continue
        if ".mean" in name:
            new = r.randn(*v.shape).astype("float32") * 0.3
        elif ".var" in name:
            new = (np.abs(r.randn(*v.shape)) + 0.5).astype("float32")
        elif ".w_" in name:  # gamma
            new = (1.0 + 0.3 * r.randn(*v.shape)).astype("float32")
        elif ".b_" in name:  # beta
            new = (0.2 * r.randn(*v.shape)).astype("float32")
        else:
            continue
        global_scope().set_var(name, jnp.asarray(new))
        perturbed += 1
    assert perturbed >= 8, perturbed  # 2 BN layers x 4 vars each
    fluid.io.save_inference_model(d, ["image"], [out], exe,
                                  main_program=main)


def test_conv_bn_fold_output_parity_and_removal():
    from paddle_tpu import inference

    d = tempfile.mkdtemp()
    _save_convbn_model(d)
    x = np.random.RandomState(1).randn(2, 3, 16, 16).astype("float32")

    def predict(ir_optim):
        cfg = inference.Config(d)
        cfg.switch_ir_optim(ir_optim)
        pred = inference.create_predictor(cfg)
        inp = pred.get_input_handle(pred.get_input_names()[0])
        inp.copy_from_cpu(x)
        pred.run()
        out = pred.get_output_handle(pred.get_output_names()[0])
        return pred, out.copy_to_cpu()

    pred_ref, ref = predict(ir_optim=False)
    assert pred_ref.get_optimization_report()["conv_bn_fused"] == 0

    pred_opt, got = predict(ir_optim=True)
    rep = pred_opt.get_optimization_report()
    assert rep["conv_bn_fused"] == 2, rep
    assert rep["op_types"].get("batch_norm", 0) == 0, rep["op_types"]
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_weight_tied_filter_blocks_fold():
    """Two convs sharing one filter var: folding would rescale the
    tied weights for BOTH convs — the pass must skip the pair."""
    from paddle_tpu.inference.passes import conv_bn_fuse
    from paddle_tpu.core.scope import global_scope

    main, st = framework.Program(), framework.Program()
    main.random_seed = st.random_seed = 4
    with framework.program_guard(main, st):
        with framework.unique_name_guard():
            img = fluid.layers.data("image", shape=[3, 8, 8],
                                    dtype="float32")
            w = fluid.layers.create_parameter(
                shape=[3, 3, 3, 3], dtype="float32", name="tied.w")
            a = fluid.layers.conv2d(img, 3, 3, padding=1, param_attr=w,
                                    bias_attr=False)
            a = fluid.layers.batch_norm(a, is_test=True)
            b = fluid.layers.conv2d(img, 3, 3, padding=1, param_attr=w,
                                    bias_attr=False)
            out = fluid.layers.elementwise_add(a, b)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(st)
    assert conv_bn_fuse(main, global_scope()) == 0
    assert any(op.type == "batch_norm"
               for op in main.global_block().ops)


def test_deleting_the_pass_disables_folding():
    from paddle_tpu import inference

    d = tempfile.mkdtemp()
    _save_convbn_model(d)
    cfg = inference.Config(d)
    cfg.pass_builder().delete_pass("conv_bn_fuse_pass")
    pred = inference.create_predictor(cfg)
    rep = pred.get_optimization_report()
    assert rep["conv_bn_fused"] == 0
    assert rep["op_types"].get("batch_norm", 0) == 2
