"""Golden + grad tests for the round-2 ops sprint (sequence, loss,
linalg, detection, beam search, manipulation, activations) — OpTest
pattern per SURVEY.md §4.1."""
import numpy as np
import pytest

from op_test import OpTest


# -- losses -----------------------------------------------------------------

class TestHingeLoss(OpTest):
    def setup(self):
        r = np.random.RandomState(0)
        self.op_type = "hinge_loss"
        logits = r.randn(8, 1).astype("float32")
        labels = r.randint(0, 2, (8, 1)).astype("float32")
        self.inputs = {"Logits": logits, "Labels": labels}
        self.outputs = {"Loss": np.maximum(
            0.0, 1.0 - (2 * labels - 1) * logits)}

    def test(self):
        self.setup()
        self.check_output()
        self.check_grad(["Logits"], "Loss")


class TestRankLoss(OpTest):
    def test(self):
        r = np.random.RandomState(1)
        self.op_type = "rank_loss"
        label = r.randint(0, 2, (6, 1)).astype("float32")
        left = r.randn(6, 1).astype("float32")
        right = r.randn(6, 1).astype("float32")
        self.inputs = {"Label": label, "Left": left, "Right": right}
        d = left - right
        self.outputs = {"Out": np.log1p(np.exp(d)) - label * d}
        self.check_output()
        self.check_grad(["Left", "Right"], "Out")


class TestLogLoss(OpTest):
    def test(self):
        r = np.random.RandomState(2)
        self.op_type = "log_loss"
        p = r.uniform(0.1, 0.9, (8, 1)).astype("float32")
        label = r.randint(0, 2, (8, 1)).astype("float32")
        self.inputs = {"Predicted": p, "Labels": label}
        eps = 1e-4
        self.outputs = {"Loss": -label * np.log(p + eps)
                        - (1 - label) * np.log(1 - p + eps)}
        self.check_output()
        self.check_grad(["Predicted"], "Loss")


class TestCosSim(OpTest):
    def test(self):
        r = np.random.RandomState(3)
        self.op_type = "cos_sim"
        x = r.randn(4, 8).astype("float32")
        y = r.randn(4, 8).astype("float32")
        self.inputs = {"X": x, "Y": y}
        xn = np.linalg.norm(x, axis=1, keepdims=True)
        yn = np.linalg.norm(y, axis=1, keepdims=True)
        self.outputs = {"Out": np.sum(x * y, 1, keepdims=True)
                        / (xn * yn), "XNorm": xn, "YNorm": yn}
        self.check_output()
        self.check_grad(["X", "Y"], "Out")


class TestDiceLoss(OpTest):
    def test(self):
        r = np.random.RandomState(4)
        self.op_type = "dice_loss"
        x = r.uniform(0.1, 0.9, (4, 10)).astype("float32")
        label = (r.rand(4, 10) > 0.5).astype("float32")
        self.inputs = {"X": x, "Label": label}
        eps = 1e-5
        inter = 2 * np.sum(x * label, 1)
        union = np.sum(x, 1) + np.sum(label, 1)
        self.outputs = {"Out": 1.0 - (inter + eps) / (union + eps)}
        self.check_output()
        self.check_grad(["X"], "Out")


# -- linalg -----------------------------------------------------------------

class TestBmm(OpTest):
    def test(self):
        r = np.random.RandomState(5)
        self.op_type = "bmm"
        x = r.randn(3, 4, 5).astype("float32")
        y = r.randn(3, 5, 6).astype("float32")
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": x @ y}
        self.check_output()
        self.check_grad(["X", "Y"], "Out")


class TestKron(OpTest):
    def test(self):
        r = np.random.RandomState(6)
        self.op_type = "kron"
        x = r.randn(2, 3).astype("float32")
        y = r.randn(3, 2).astype("float32")
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": np.kron(x, y)}
        self.check_output()
        self.check_grad(["X", "Y"], "Out")


class TestTrace(OpTest):
    def test(self):
        r = np.random.RandomState(7)
        self.op_type = "trace"
        x = r.randn(4, 4).astype("float32")
        self.inputs = {"Input": x}
        self.outputs = {"Out": np.trace(x)}
        self.check_output()
        self.check_grad(["Input"], "Out")


class TestCholeskyInverse(OpTest):
    def test(self):
        r = np.random.RandomState(8)
        a = r.randn(4, 4).astype("float32")
        spd = a @ a.T + 4 * np.eye(4, dtype="float32")
        self.op_type = "cholesky"
        self.inputs = {"X": spd}
        self.outputs = {"Out": np.linalg.cholesky(spd)}
        self.check_output(atol=1e-4)

        self.op_type = "inverse"
        self.inputs = {"Input": spd}
        self.outputs = {"Output": np.linalg.inv(spd)}
        self.check_output(atol=1e-4)


class TestAddmmLogsumexp(OpTest):
    def test(self):
        r = np.random.RandomState(9)
        self.op_type = "addmm"
        inp = r.randn(3, 5).astype("float32")
        x = r.randn(3, 4).astype("float32")
        y = r.randn(4, 5).astype("float32")
        self.inputs = {"Input": inp, "X": x, "Y": y}
        self.attrs = {"Alpha": 2.0, "Beta": 0.5}
        self.outputs = {"Out": 0.5 * inp + 2.0 * (x @ y)}
        self.check_output()
        self.check_grad(["X", "Y", "Input"], "Out")

        self.op_type = "logsumexp"
        x = r.randn(4, 6).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"axis": [1], "keepdim": False}
        self.outputs = {"Out": np.log(np.sum(np.exp(x), axis=1))}
        self.check_output()
        self.check_grad(["X"], "Out")


class TestBilinearTensorProduct(OpTest):
    def test(self):
        r = np.random.RandomState(10)
        self.op_type = "bilinear_tensor_product"
        x = r.randn(3, 4).astype("float32")
        y = r.randn(3, 5).astype("float32")
        w = r.randn(6, 4, 5).astype("float32")
        b = r.randn(1, 6).astype("float32")
        self.inputs = {"X": x, "Y": y, "Weight": w, "Bias": b}
        self.outputs = {"Out": np.einsum("bi,kij,bj->bk", x, w, y) + b}
        self.check_output(atol=1e-4)
        self.check_grad(["X", "Y", "Weight"], "Out")


# -- sequence ---------------------------------------------------------------

class TestSequencePadUnpad(OpTest):
    def test(self):
        r = np.random.RandomState(11)
        x = r.randn(3, 5, 2).astype("float32")
        length = np.array([2, 5, 3], "int64")
        self.op_type = "sequence_pad"
        self.inputs = {"X": x, "Length": length,
                       "PadValue": np.array([9.0], "float32")}
        expect = x.copy()
        for i, l in enumerate(length):
            expect[i, l:] = 9.0
        self.outputs = {"Out": expect, "Length": length}
        self.check_output()

        self.op_type = "sequence_unpad"
        self.inputs = {"X": x, "Length": length}
        expect = x.copy()
        for i, l in enumerate(length):
            expect[i, l:] = 0.0
        self.outputs = {"Out": expect}
        self.check_output()


class TestSequenceErase(OpTest):
    def test(self):
        self.op_type = "sequence_erase"
        x = np.array([[1, 2, 3, 2, 5]], "int64")
        self.inputs = {"X": x}
        self.attrs = {"tokens": [2]}
        self.outputs = {"Out": np.array([[1, 3, 5, 0, 0]], "int64"),
                        "Length": np.array([3], "int64")}
        self.check_output()


class TestSequenceConv(OpTest):
    def test(self):
        r = np.random.RandomState(12)
        self.op_type = "sequence_conv"
        x = r.randn(2, 6, 4).astype("float32")
        filt = r.randn(12, 8).astype("float32")
        self.inputs = {"X": x, "Filter": filt}
        self.attrs = {"contextLength": 3, "contextStart": -1}
        # golden: shifted concat then matmul
        cols = []
        for off in (-1, 0, 1):
            s = np.zeros_like(x)
            if off < 0:
                s[:, -off:] = x[:, :off]
            elif off > 0:
                s[:, :-off] = x[:, off:]
            else:
                s = x
            cols.append(s)
        ctx = np.concatenate(cols, -1)
        self.outputs = {"Out": ctx @ filt}
        self.check_output(atol=1e-5)
        self.check_grad(["X", "Filter"], "Out")


# -- detection --------------------------------------------------------------

class TestIouSimilarity(OpTest):
    def test(self):
        self.op_type = "iou_similarity"
        x = np.array([[0, 0, 10, 10], [5, 5, 15, 15]], "float32")
        y = np.array([[0, 0, 10, 10]], "float32")
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": np.array([[1.0], [25.0 / 175.0]],
                                        "float32")}
        self.check_output()


class TestBoxCoderRoundTrip(OpTest):
    def test(self):
        import paddle_tpu.ops as ops_lib
        import jax.numpy as jnp

        prior = np.array([[0, 0, 10, 10], [10, 10, 30, 30]], "float32")
        target = np.array([[1, 1, 9, 9], [12, 8, 28, 32]], "float32")
        enc = ops_lib.run_op(
            "box_coder",
            {"PriorBox": [jnp.asarray(prior)],
             "TargetBox": [jnp.asarray(target)]},
            {"code_type": "encode_center_size",
             "box_normalized": True})["OutputBox"][0]
        # decode expects [n, p, 4] deltas aligned per prior
        deltas = np.stack([np.asarray(enc)[i, i] for i in range(2)])
        dec = ops_lib.run_op(
            "box_coder",
            {"PriorBox": [jnp.asarray(prior)],
             "TargetBox": [jnp.asarray(deltas[:, None, :].repeat(
                 2, axis=1))]},
            {"code_type": "decode_center_size",
             "box_normalized": True})["OutputBox"][0]
        got = np.stack([np.asarray(dec)[i, i] for i in range(2)])
        np.testing.assert_allclose(got, target, rtol=1e-5, atol=1e-4)


class TestYoloBoxShapes(OpTest):
    def test(self):
        r = np.random.RandomState(13)
        self.op_type = "yolo_box"
        x = r.randn(1, 3 * 7, 4, 4).astype("float32")
        img = np.array([[128, 128]], "int32")
        self.inputs = {"X": x, "ImgSize": img}
        self.attrs = {"anchors": [10, 13, 16, 30, 33, 23],
                      "class_num": 2, "conf_thresh": 0.0,
                      "downsample_ratio": 32}
        outs = self._run_forward()
        assert np.asarray(outs["Boxes"][0]).shape == (1, 48, 4)
        assert np.asarray(outs["Scores"][0]).shape == (1, 48, 2)


class TestRoiAlign(OpTest):
    def test(self):
        self.op_type = "roi_align"
        x = np.arange(16, dtype="float32").reshape(1, 1, 4, 4)
        rois = np.array([[0, 0, 3, 3]], "float32")
        self.inputs = {"X": x, "ROIs": rois}
        self.attrs = {"pooled_height": 2, "pooled_width": 2,
                      "spatial_scale": 1.0, "sampling_ratio": 2}
        outs = self._run_forward()
        got = np.asarray(outs["Out"][0])
        assert got.shape == (1, 1, 2, 2)
        # average over the ROI quadrants of a linear ramp
        assert got[0, 0, 0, 0] < got[0, 0, 0, 1] < got[0, 0, 1, 1]


class TestMulticlassNMS(OpTest):
    def test(self):
        self.op_type = "multiclass_nms"
        boxes = np.array([[[0, 0, 10, 10], [0.5, 0.5, 10, 10],
                           [20, 20, 30, 30]]], "float32")
        scores = np.array([[[0.0, 0.9, 0.8],
                            [0.0, 0.05, 0.9]]], "float32").transpose(
                                0, 1, 2)
        # scores layout [N, class, M]
        scores = np.array([[[0.9, 0.85, 0.1],
                            [0.1, 0.05, 0.9]]], "float32")
        self.inputs = {"BBoxes": boxes, "Scores": scores}
        self.attrs = {"score_threshold": 0.3, "nms_threshold": 0.5,
                      "background_label": -1, "keep_top_k": 10}
        outs = self._run_forward()
        got = np.asarray(outs["Out"][0])
        # cls0: boxes 0,1 overlap (IoU 0.9) -> box1 suppressed, box2
        # under threshold; cls1: box2 kept -> 2 detections total
        assert got.shape == (2, 6), got
        assert got[0][1] >= got[1][1]  # sorted by score desc


# -- beam search ------------------------------------------------------------

class TestBeamSearchStep(OpTest):
    def test(self):
        import jax.numpy as jnp
        import paddle_tpu.ops as ops_lib

        pre_ids = np.array([[1, 2]], "int64")
        pre_scores = np.array([[0.0, -1.0]], "float32")
        # beam 0 candidates better than beam 1
        scores = np.log(np.array(
            [[[0.7, 0.2, 0.1], [0.1, 0.1, 0.8]]], "float32"))
        outs = ops_lib.run_op(
            "beam_search",
            {"pre_ids": [jnp.asarray(pre_ids)],
             "pre_scores": [jnp.asarray(pre_scores)],
             "scores": [jnp.asarray(scores)]},
            {"beam_size": 2, "end_id": 0})
        sel = np.asarray(outs["selected_ids"][0])
        par = np.asarray(outs["parent_idx"][0])
        # best: beam0 token0 (0.0 + log .7); second: beam1 token2
        assert sel.shape == (1, 2)
        assert par[0, 0] == 0 and sel[0, 0] == 0
        assert par[0, 1] in (0, 1)


class TestGatherTree(OpTest):
    def test(self):
        self.op_type = "gather_tree"
        ids = np.array([[[2, 3]], [[4, 5]], [[6, 7]]], "int64")
        parents = np.array([[[0, 0]], [[1, 0]], [[0, 1]]], "int64")
        self.inputs = {"Ids": ids, "Parents": parents}
        # backtrack: t2 beam0 <- parent 0 at t2 -> t1 beam0's parent=1
        out = self._run_forward()
        got = np.asarray(out["Out"][0])
        assert got.shape == (3, 1, 2)
        np.testing.assert_array_equal(got[2], [[6, 7]])
        np.testing.assert_array_equal(got[1], [[4, 5]])
        np.testing.assert_array_equal(got[0], [[3, 2]])


# -- manipulation / activations --------------------------------------------

class TestManipulationOps(OpTest):
    def test_shard_index(self):
        self.op_type = "shard_index"
        x = np.array([[1], [6], [12], [19]], "int64")
        self.inputs = {"X": x}
        self.attrs = {"index_num": 20, "nshards": 2, "shard_id": 0,
                      "ignore_value": -1}
        self.outputs = {"Out": np.array([[1], [6], [-1], [-1]], "int64")}
        self.check_output()

    def test_index_sample(self):
        r = np.random.RandomState(14)
        self.op_type = "index_sample"
        x = r.randn(3, 5).astype("float32")
        idx = np.array([[0, 2], [1, 3], [4, 4]], "int32")
        self.inputs = {"X": x, "Index": idx}
        self.outputs = {"Out": np.take_along_axis(x, idx, 1)}
        self.attrs = {}
        self.check_output()
        self.check_grad(["X"], "Out")

    def test_pixel_shuffle(self):
        r = np.random.RandomState(15)
        self.op_type = "pixel_shuffle"
        x = r.randn(1, 8, 2, 2).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"upscale_factor": 2}
        out = self._run_forward()
        assert np.asarray(out["Out"][0]).shape == (1, 2, 4, 4)

    def test_unfold(self):
        self.op_type = "unfold"
        x = np.arange(16, dtype="float32").reshape(1, 1, 4, 4)
        self.inputs = {"X": x}
        self.attrs = {"kernel_sizes": [2, 2], "strides": [2, 2],
                      "paddings": [0, 0], "dilations": [1, 1]}
        out = np.asarray(self._run_forward()["Y"][0])
        assert out.shape == (1, 4, 4)
        np.testing.assert_array_equal(out[0, :, 0], [0, 1, 4, 5])

    def test_maxout(self):
        r = np.random.RandomState(16)
        self.op_type = "maxout"
        x = r.randn(2, 6, 3, 3).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"groups": 2, "axis": 1}
        expect = x.reshape(2, 3, 2, 3, 3).max(axis=2)
        self.outputs = {"Out": expect}
        self.check_output()
        self.check_grad(["X"], "Out")

    def test_selu_grad(self):
        r = np.random.RandomState(17)
        self.op_type = "selu"
        x = r.randn(4, 5).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {}
        scale, alpha = 1.0507009873554805, 1.6732632423543772
        self.outputs = {"Out": scale * np.where(
            x > 0, x, alpha * (np.exp(x) - 1))}
        self.check_output()
        self.check_grad(["X"], "Out")

    def test_lrn(self):
        r = np.random.RandomState(18)
        self.op_type = "lrn"
        x = r.rand(2, 8, 3, 3).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"n": 5, "k": 2.0, "alpha": 1e-4, "beta": 0.75}
        out = self._run_forward()
        sq = np.square(x)
        pad = np.pad(sq, ((0, 0), (2, 2), (0, 0), (0, 0)))
        acc = sum(pad[:, i:i + 8] for i in range(5))
        expect = x / np.power(2.0 + 1e-4 * acc, 0.75)
        np.testing.assert_allclose(np.asarray(out["Out"][0]), expect,
                                   rtol=1e-5)

    def test_put_along_axis(self):
        self.op_type = "put_along_axis"
        x = np.zeros((2, 3), "float32")
        idx = np.array([[1], [2]], "int64")
        v = np.array([[5.0], [7.0]], "float32")
        self.inputs = {"Input": x, "Index": idx, "Value": v}
        self.attrs = {"Axis": 1, "Reduce": "assign"}
        out = np.asarray(self._run_forward()["Result"][0])
        np.testing.assert_array_equal(
            out, [[0, 5, 0], [0, 0, 7]])


class TestPrecisionRecall(OpTest):
    def test(self):
        self.op_type = "precision_recall"
        preds = np.array([0, 1, 1, 2, 2, 0], "int32").reshape(-1, 1)
        labels = np.array([0, 1, 0, 2, 1, 0], "int32").reshape(-1, 1)
        self.inputs = {"Indices": preds, "Labels": labels}
        self.attrs = {"class_number": 3}
        outs = self._run_forward()
        batch = np.asarray(outs["BatchMetrics"][0])
        # micro precision == accuracy == 4/6
        np.testing.assert_allclose(batch[3], 4.0 / 6.0, rtol=1e-5)


class TestProximalOps(OpTest):
    def test(self):
        r = np.random.RandomState(19)
        self.op_type = "proximal_gd"
        p = r.randn(5).astype("float32")
        g = r.randn(5).astype("float32")
        lr = np.array([0.1], "float32")
        self.inputs = {"Param": p, "Grad": g, "LearningRate": lr}
        self.attrs = {"l1": 0.01, "l2": 0.01}
        prox = p - 0.1 * g
        expect = np.sign(prox) * np.maximum(
            np.abs(prox) - 0.1 * 0.01, 0) / (1 + 0.1 * 0.01)
        self.outputs = {"ParamOut": expect}
        self.check_output()


class TestFusedOps(OpTest):
    def test_fused_elemwise_activation(self):
        r = np.random.RandomState(20)
        self.op_type = "fused_elemwise_activation"
        x = r.randn(4, 6).astype("float32")
        y = r.randn(6).astype("float32")
        self.inputs = {"X": x, "Y": y}
        # reference semantics: FIRST functor is OUTER —
        # ["elementwise_add","relu"] = x + relu(y)
        self.attrs = {"functor_list": ["elementwise_add", "relu"],
                      "axis": -1}
        outs = self._run_forward()
        np.testing.assert_allclose(np.asarray(outs["Out"][0]),
                                   x + np.maximum(y, 0), rtol=1e-6)
        self.attrs = {"functor_list": ["scale", "elementwise_add"],
                      "scale": 0.5, "axis": -1}
        outs = self._run_forward()
        np.testing.assert_allclose(np.asarray(outs["Out"][0]),
                                   0.5 * (x + y), rtol=1e-6)

    def test_multihead_matmul(self):
        r = np.random.RandomState(21)
        self.op_type = "multihead_matmul"
        b, s, d, h = 2, 5, 8, 2
        x = r.randn(b, s, d).astype("float32")
        w = r.randn(d, 3, h, d // h).astype("float32")
        bias = np.zeros((3, h, d // h), "float32")
        self.inputs = {"Input": x, "W": w, "Bias": bias}
        self.attrs = {"head_number": h}
        out = np.asarray(self._run_forward()["Out"][0])
        assert out.shape == (b, s, d)
        assert np.isfinite(out).all()

    def test_fused_gemm_epilogue(self):
        r = np.random.RandomState(22)
        self.op_type = "fused_gemm_epilogue"
        x = r.randn(3, 4).astype("float32")
        y = r.randn(4, 5).astype("float32")
        bias = r.randn(5).astype("float32")
        self.inputs = {"X": x, "Y": y, "Bias": bias}
        self.attrs = {"activation": "relu"}
        self.outputs = {"Out": np.maximum(x @ y + bias, 0)}
        self.check_output()


class TestArrayOps(OpTest):
    def test_write_read_roundtrip(self):
        import jax.numpy as jnp
        import paddle_tpu.ops as ops_lib

        arr = None
        vals = [np.full((2, 3), float(i), "float32") for i in range(3)]
        length = None
        for i, v in enumerate(vals):
            ins = {"X": [jnp.asarray(v)],
                   "I": [jnp.asarray([i], jnp.int32)]}
            if arr is not None:
                ins["Array"] = [arr]
            if length is not None:
                ins["Len"] = [length]
            outs = ops_lib.run_op("array_write", ins, {"max_len": 4})
            arr = outs["Out"][0]
            length = outs["OutLen"][0]
        for i, v in enumerate(vals):
            got = ops_lib.run_op(
                "array_read",
                {"Array": [arr], "I": [jnp.asarray([i], jnp.int32)]},
                {})["Out"][0]
            np.testing.assert_allclose(np.asarray(got), v)
        # reference semantics: number WRITTEN (3), not capacity (4)
        ln = ops_lib.run_op("lod_array_length",
                            {"X": [arr], "Len": [length]}, {})
        assert int(np.asarray(ln["Out"][0])[0]) == 3
        # concrete out-of-range write raises
        import pytest

        with pytest.raises(IndexError):
            ops_lib.run_op("array_write",
                           {"Array": [arr], "X": [jnp.ones((2, 3))],
                            "I": [jnp.asarray([9], jnp.int32)]}, {})

    def test_lod_rank_table(self):
        import jax.numpy as jnp
        import paddle_tpu.ops as ops_lib

        out = ops_lib.run_op(
            "lod_rank_table",
            {"X": [jnp.zeros((3, 5))],
             "Length": [jnp.asarray([2, 5, 3])]}, {})
        np.testing.assert_array_equal(np.asarray(out["Out"][0]),
                                      [1, 2, 0])


class TestFusionRNNSignatures(OpTest):
    def test_fusion_gru_reference_layout(self):
        import jax.numpy as jnp
        import paddle_tpu.ops as ops_lib

        r = np.random.RandomState(23)
        b, t, d, h = 2, 4, 3, 5
        x = r.randn(b, t, d).astype("float32")
        wx = r.randn(d, 3 * h).astype("float32")   # reference (D, 3H)
        wh = r.randn(h, 3 * h).astype("float32")   # reference (H, 3H)
        bias = r.randn(1, 3 * h).astype("float32")
        out = ops_lib.run_op(
            "fusion_gru",
            {"X": [jnp.asarray(x)], "WeightX": [jnp.asarray(wx)],
             "WeightH": [jnp.asarray(wh)], "Bias": [jnp.asarray(bias)]},
            {})
        hid = np.asarray(out["Hidden"][0])
        assert hid.shape == (b, t, h)
        xx = np.asarray(out["XX"][0])
        assert xx.shape == (b, t, 3 * h)
        # golden: paddle GRU recurrence [u, r | c],
        # c = tanh(x_c + (r*h) Wc), h = u*c + (1-u)*h
        # (jit/refer/refer.h GRUHtPart2: out = zt*ht~ + (1-zt)*ht_1)
        def sigmoid(v):
            return 1.0 / (1.0 + np.exp(-v))

        hh = np.zeros((b, h), "float32")
        xproj = x @ wx + bias.reshape(-1)
        for ti in range(t):
            g = sigmoid(xproj[:, ti, :2 * h] + hh @ wh[:, :2 * h])
            u, r = g[:, :h], g[:, h:]
            c = np.tanh(xproj[:, ti, 2 * h:] + (r * hh) @ wh[:, 2 * h:])
            hh = u * c + (1 - u) * hh
            np.testing.assert_allclose(hid[:, ti], hh, rtol=2e-5,
                                       atol=1e-5)

    def test_fusion_lstm_reference_layout(self):
        import jax.numpy as jnp
        import paddle_tpu.ops as ops_lib

        r = np.random.RandomState(24)
        b, t, d, h = 2, 4, 3, 5
        out = ops_lib.run_op(
            "fusion_lstm",
            {"X": [jnp.asarray(r.randn(b, t, d).astype("float32"))],
             "WeightX": [jnp.asarray(
                 r.randn(d, 4 * h).astype("float32"))],
             "WeightH": [jnp.asarray(
                 r.randn(h, 4 * h).astype("float32"))],
             "Bias": [jnp.asarray(
                 r.randn(1, 4 * h).astype("float32"))]},
            {})
        hid = np.asarray(out["Hidden"][0])
        cell = np.asarray(out["Cell"][0])
        assert hid.shape == cell.shape == (b, t, h)
        assert np.isfinite(hid).all()
        assert not np.allclose(hid, cell)  # cell is the c-sequence


class TestEditDistanceChunkEvalCtc(OpTest):
    def test_edit_distance(self):
        self.op_type = "edit_distance"
        hyp = np.array([[1, 2, 3, 0]], "int64")
        ref = np.array([[1, 3, 3, 4]], "int64")
        self.inputs = {"Hyps": hyp, "Refs": ref,
                       "HypsLength": np.array([3], "int64"),
                       "RefsLength": np.array([4], "int64")}
        outs = self._run_forward()
        # "123" vs "1334": sub 2->3, insert 4 => distance 2
        assert float(np.asarray(outs["Out"][0])[0, 0]) == 2.0

    def test_chunk_eval(self):
        self.op_type = "chunk_eval"
        # IOB with 2 types: B0=0 I0=1 B1=2 I1=3 O=4
        label = np.array([0, 1, 4, 2, 3, 4], "int64")
        pred = np.array([0, 1, 4, 2, 4, 4], "int64")
        self.inputs = {"Inference": pred, "Label": label}
        self.attrs = {"num_chunk_types": 2}
        outs = self._run_forward()
        # gold: (0,2,t0),(3,5,t1); pred: (0,2,t0),(3,4,t1) -> 1 correct
        np.testing.assert_allclose(
            np.asarray(outs["Precision"][0]), [0.5])
        np.testing.assert_allclose(np.asarray(outs["Recall"][0]), [0.5])

    def test_chunk_eval_batched_seqlength(self):
        self.op_type = "chunk_eval"
        # two rows; row0 valid len 2, row1 valid len 2: a chunk must NOT
        # span the row boundary and padding must not be scored
        pred = np.array([[0, 1, 0, 0], [1, 4, 0, 0]], "int64")
        label = np.array([[0, 1, 4, 4], [0, 4, 0, 0]], "int64")
        self.inputs = {"Inference": pred, "Label": label,
                       "SeqLength": np.array([2, 2], "int64")}
        self.attrs = {"num_chunk_types": 2}
        outs = self._run_forward()
        # row0: gold {(0,2,t0)} pred {(0,2,t0)} correct;
        # row1: gold {(0,1,t0)} pred {(0,1,t0)} (I at start opens chunk)
        np.testing.assert_allclose(
            np.asarray(outs["Precision"][0]), [1.0])
        assert int(np.asarray(outs["NumInferChunks"][0])[0]) == 2

    def test_ctc_align(self):
        self.op_type = "ctc_align"
        x = np.array([[0, 1, 1, 0, 2, 2, 0, 3]], "int32")
        self.inputs = {"Input": x}
        self.attrs = {"blank": 0, "merge_repeated": True}
        outs = self._run_forward()
        got = np.asarray(outs["Output"][0])[0]
        np.testing.assert_array_equal(got[:3], [1, 2, 3])
        assert int(np.asarray(outs["OutputLength"][0])[0, 0]) == 3
        # InputLength bounds decoding; padding_value fills the tail
        self.inputs = {"Input": x,
                       "InputLength": np.array([4], "int64")}
        self.attrs = {"blank": 0, "merge_repeated": True,
                      "padding_value": -1}
        outs = self._run_forward()
        got = np.asarray(outs["Output"][0])[0]
        assert int(np.asarray(outs["OutputLength"][0])[0, 0]) == 1
        np.testing.assert_array_equal(got[:2], [1, -1])
