"""Program-level tests for the round-2 layer-builder tranche
(fluid.layers.nn_extra + fluid.layers.detection): each builds a static
program via the public API, runs it through Executor, and checks
numerics/shapes — the reference exercises the same surface through
tests/unittests/test_layers.py."""
import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework


def _run(build, feeds):
    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup):
        with framework.unique_name_guard():
            fetch = build()
            exe = fluid.Executor()
            exe.run(startup)
            outs = exe.run(main, feed=feeds,
                           fetch_list=list(fetch) if isinstance(
                               fetch, (list, tuple)) else [fetch])
    return [np.asarray(o) for o in outs]


def test_interpolate_and_resizes():
    x = np.random.RandomState(0).randn(2, 3, 8, 8).astype("float32")

    def build():
        inp = fluid.layers.data("x", shape=[3, 8, 8], dtype="float32")
        a = fluid.layers.interpolate(inp, out_shape=[16, 12])
        b = fluid.layers.resize_bilinear(inp, out_shape=[4, 4],
                                         align_corners=False,
                                         align_mode=0)
        return a, b

    a, b = _run(build, {"x": x})
    assert a.shape == (2, 3, 16, 12)
    assert b.shape == (2, 3, 4, 4)


def test_conv3d_pool3d():
    x = np.random.RandomState(1).randn(1, 2, 4, 6, 6).astype("float32")

    def build():
        inp = fluid.layers.data("x", shape=[2, 4, 6, 6], dtype="float32")
        c = fluid.layers.conv3d(inp, num_filters=3, filter_size=3,
                                padding=1, act="relu")
        p = fluid.layers.pool3d(c, pool_size=2, pool_stride=2)
        return p

    (p,) = _run(build, {"x": x})
    assert p.shape == (1, 3, 2, 3, 3)
    assert np.all(p >= 0)


def test_dynamic_lstm_gru_program():
    r = np.random.RandomState(2)
    xl = r.randn(2, 5, 16).astype("float32")
    xg = r.randn(2, 5, 12).astype("float32")

    def build():
        il = fluid.layers.data("xl", shape=[5, 16], dtype="float32")
        ig = fluid.layers.data("xg", shape=[5, 12], dtype="float32")
        h, c = fluid.layers.dynamic_lstm(il, size=16)
        g = fluid.layers.dynamic_gru(ig, size=4)
        return h, c, g

    h, c, g = _run(build, {"xl": xl, "xg": xg})
    assert h.shape == (2, 5, 4) and c.shape == (2, 5, 4)
    assert g.shape == (2, 5, 4)
    assert np.all(np.isfinite(h)) and np.all(np.isfinite(g))


def test_nce_hsigmoid_train():
    """Both large-vocab losses must produce finite positive costs and
    train end-to-end."""
    r = np.random.RandomState(3)
    feats = r.randn(8, 16).astype("float32")
    labels = r.randint(0, 50, (8, 1)).astype("int64")

    def build():
        x = fluid.layers.data("x", shape=[16], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="int64")
        cost_nce = fluid.layers.nce(x, y, num_total_classes=50,
                                    num_neg_samples=8,
                                    sampler="log_uniform")
        cost_hs = fluid.layers.hsigmoid(x, y, num_classes=50)
        loss = fluid.layers.mean(cost_nce) + fluid.layers.mean(cost_hs)
        fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
        return loss

    (loss,) = _run(build, {"x": feats, "y": labels})
    assert np.isfinite(loss).all() and loss > 0


def test_warpctc_crf_program():
    r = np.random.RandomState(4)
    logits = r.randn(2, 8, 6).astype("float32")
    label = r.randint(1, 6, (2, 3)).astype("int32")
    em = r.randn(2, 8, 5).astype("float32")
    tags = r.randint(0, 5, (2, 8)).astype("int64")

    def build():
        lg = fluid.layers.data("lg", shape=[8, 6], dtype="float32")
        lb = fluid.layers.data("lb", shape=[3], dtype="int32")
        e = fluid.layers.data("e", shape=[8, 5], dtype="float32")
        t = fluid.layers.data("t", shape=[8], dtype="int64")
        ctc = fluid.layers.warpctc(lg, lb)
        crf = fluid.layers.linear_chain_crf(
            e, t, param_attr=fluid.ParamAttr(name="crf_w"))
        dec = fluid.layers.crf_decoding(
            e, param_attr=fluid.ParamAttr(name="crf_w"))
        return ctc, crf, dec

    ctc, crf, dec = _run(build, {"lg": logits, "lb": label,
                                 "e": em, "t": tags})
    assert ctc.shape == (2, 1) and np.all(ctc > 0)
    assert crf.shape == (2, 1)
    assert dec.shape == (2, 8) and np.issubdtype(dec.dtype, np.integer)


def test_detection_pipeline():
    """prior_box shape contract, then the full ssd_loss composition
    (iou → bipartite_match via host callback → target_assign → huber +
    softmax conf) through the jitted Executor path."""
    r = np.random.RandomState(5)
    feat = r.randn(1, 8, 4, 4).astype("float32")
    img = r.randn(1, 3, 32, 32).astype("float32")

    def build():
        f = fluid.layers.data("f", shape=[8, 4, 4], dtype="float32")
        im = fluid.layers.data("im", shape=[3, 32, 32], dtype="float32")
        boxes, variances = fluid.layers.prior_box(
            f, im, min_sizes=[4.0], aspect_ratios=[1.0])
        return boxes, variances

    boxes, variances = _run(build, {"f": feat, "im": img})
    assert boxes.shape[-1] == 4 and variances.shape == boxes.shape

    n_priors, n_gt, n_cls = 6, 2, 3
    loc = r.randn(1, n_priors, 4).astype("float32")
    conf = r.randn(1, n_priors, n_cls).astype("float32")
    gtb = np.array([[[0.1, 0.1, 0.4, 0.4], [0.5, 0.5, 0.9, 0.9]]],
                   "float32")[0]
    gtl = np.array([[1], [2]], "int64")
    priors = np.array([[0.1, 0.1, 0.4, 0.4], [0.5, 0.5, 0.9, 0.9],
                       [0.0, 0.0, 0.2, 0.2], [0.3, 0.3, 0.6, 0.6],
                       [0.7, 0.1, 0.9, 0.3], [0.2, 0.6, 0.5, 0.9]],
                      "float32")

    def build_loss():
        lv = fluid.layers.data("loc", shape=[n_priors, 4],
                               dtype="float32")
        cv = fluid.layers.data("conf", shape=[n_priors, n_cls],
                               dtype="float32")
        gb = fluid.layers.data("gtb", shape=[n_gt, 4], dtype="float32",
                               append_batch_size=False)
        gl = fluid.layers.data("gtl", shape=[n_gt, 1], dtype="int64",
                               append_batch_size=False)
        pb = fluid.layers.data("pb", shape=[n_priors, 4],
                               dtype="float32", append_batch_size=False)
        return fluid.layers.ssd_loss(lv, cv, gb, gl, pb)

    (loss,) = _run(build_loss, {"loc": loc, "conf": conf, "gtb": gtb,
                                "gtl": gtl, "pb": priors})
    assert np.isfinite(loss).all()


def test_zero_gt_target_assign_ops():
    import jax.numpy as jnp
    from paddle_tpu import ops as ops_lib

    anchors = np.array([[0., 0., 10., 10.], [5., 5., 20., 20.]],
                       "float32")
    empty = np.zeros((0, 4), "float32")
    out = ops_lib.run_op("rpn_target_assign",
                         {"Anchor": [jnp.asarray(anchors)],
                          "GtBoxes": [jnp.asarray(empty)]}, {})
    assert np.asarray(out["LocationIndex"][0]).size == 0
    out = ops_lib.run_op("retinanet_target_assign",
                         {"Anchor": [jnp.asarray(anchors)],
                          "GtBoxes": [jnp.asarray(empty)],
                          "GtLabels": [jnp.asarray(
                              np.zeros((0,), "int32"))]}, {})
    assert np.all(np.asarray(out["TargetLabel"][0]) == 0)


def test_box_decoder_and_assign_op():
    import jax.numpy as jnp
    from paddle_tpu import ops as ops_lib

    prior = np.array([[0., 0., 10., 10.]], "float32")
    pvar = np.array([[1., 1., 1., 1.]], "float32")
    tb = np.zeros((1, 3 * 4), "float32")     # zero deltas: decode = prior
    score = np.array([[0.1, 0.2, 0.7]], "float32")
    out = ops_lib.run_op("box_decoder_and_assign",
                         {"PriorBox": [jnp.asarray(prior)],
                          "PriorBoxVar": [jnp.asarray(pvar)],
                          "TargetBox": [jnp.asarray(tb)],
                          "BoxScore": [jnp.asarray(score)]}, {})
    assigned = np.asarray(out["OutputAssignBox"][0])
    np.testing.assert_allclose(assigned[0], [0, 0, 10, 10], atol=1e-5)


def test_affine_channel_defaults():
    r = np.random.RandomState(9)
    x = r.randn(1, 3, 4, 4).astype("float32")

    def build():
        inp = fluid.layers.data("x", shape=[3, 4, 4], dtype="float32")
        return fluid.layers.affine_channel(inp)

    (out,) = _run(build, {"x": x})
    np.testing.assert_allclose(out, x, rtol=1e-5)


def test_misc_wrappers():
    r = np.random.RandomState(6)
    x = r.randn(2, 4, 6, 6).astype("float32")

    def build():
        inp = fluid.layers.data("x", shape=[4, 6, 6], dtype="float32")
        a = fluid.layers.maxout(inp, groups=2)
        b = fluid.layers.shuffle_channel(inp, group=2)
        c = fluid.layers.space_to_depth(inp, blocksize=2)
        d = fluid.layers.pixel_shuffle(inp, upscale_factor=2)
        e = fluid.layers.lrn(inp)
        return a, b, c, d, e

    a, b, c, d, e = _run(build, {"x": x})
    assert a.shape == (2, 2, 6, 6)
    assert b.shape == (2, 4, 6, 6)
    assert c.shape == (2, 16, 3, 3)
    assert d.shape == (2, 1, 12, 12)
    assert e.shape == (2, 4, 6, 6)


def test_small_losses():
    r = np.random.RandomState(7)
    a = r.rand(6, 1).astype("float32") * 0.8 + 0.1
    lbl = r.randint(0, 2, (6, 1)).astype("float32")

    def build():
        p = fluid.layers.data("p", shape=[1], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="float32")
        ll = fluid.layers.log_loss(p, y)
        rk = fluid.layers.rank_loss(y, p, p)
        return ll, rk

    ll, rk = _run(build, {"p": a, "y": lbl})
    eps = 1e-4
    e = -lbl * np.log(a + eps) - (1 - lbl) * np.log(1 - a + eps)
    np.testing.assert_allclose(ll, e, rtol=1e-4)


def test_lstm_cudnn_builder():
    r = np.random.RandomState(8)
    x = r.randn(6, 2, 8).astype("float32")
    h0 = np.zeros((2, 2, 4), "float32")
    c0 = np.zeros((2, 2, 4), "float32")

    def build():
        inp = fluid.layers.data("x", shape=[6, 2, 8], dtype="float32",
                                append_batch_size=False)
        ih = fluid.layers.data("h0", shape=[2, 2, 4], dtype="float32",
                               append_batch_size=False)
        ic = fluid.layers.data("c0", shape=[2, 2, 4], dtype="float32",
                               append_batch_size=False)
        out, lh, lc = fluid.layers.lstm(inp, ih, ic, max_len=6,
                                        hidden_size=4, num_layers=1,
                                        is_bidirec=True)
        return out, lh

    out, lh = _run(build, {"x": x, "h0": h0, "c0": c0})
    assert out.shape == (6, 2, 8)
    assert lh.shape == (2, 2, 4)
