"""hapi.vision models/transforms + hapi.text building blocks
(reference: incubate/hapi/vision + text test patterns: run a tiny batch
through each model and check shapes/finite outputs)."""
import numpy as np

from paddle_tpu.fluid import dygraph
from paddle_tpu.hapi.vision import models, transforms
from paddle_tpu.hapi import text as htext


def test_lenet_forward():
    r = np.random.RandomState(0)
    with dygraph.guard():
        net = models.LeNet(num_classes=10)
        x = dygraph.to_variable(r.randn(2, 1, 28, 28).astype("float32"))
        out = net(x)
        assert out.shape == (2, 10)
        assert np.all(np.isfinite(out.numpy()))


def test_mobilenet_v2_forward():
    r = np.random.RandomState(1)
    with dygraph.guard():
        net = models.MobileNetV2(num_classes=7, scale=0.35)
        x = dygraph.to_variable(r.randn(1, 3, 96, 96).astype("float32"))
        out = net(x)
        assert out.shape == (1, 7)
        assert np.all(np.isfinite(out.numpy()))


def test_mobilenet_v1_forward():
    r = np.random.RandomState(2)
    with dygraph.guard():
        net = models.MobileNetV1(num_classes=5, scale=0.25)
        x = dygraph.to_variable(r.randn(1, 3, 64, 64).astype("float32"))
        out = net(x)
        assert out.shape == (1, 5)


def test_transforms_pipeline():
    r = np.random.RandomState(3)
    img = (r.rand(40, 60, 3) * 255).astype("uint8")
    pipe = transforms.Compose([
        transforms.Resize(32),
        transforms.CenterCrop(28),
        transforms.RandomHorizontalFlip(1.0),
        transforms.ColorJitter(0.1, 0.1, 0.1, 0.05),
        transforms.Normalize(mean=127.5, std=127.5),
        transforms.Permute(),
    ])
    out = pipe(img)
    assert out.shape == (3, 28, 28)
    assert out.dtype == np.float32
    assert -2 < out.min() and out.max() < 2

    rrc = transforms.RandomResizedCrop(16)
    assert rrc(img).shape[:2] == (16, 16)


def test_text_cells_and_encoder():
    r = np.random.RandomState(4)
    with dygraph.guard():
        # TextCNN encoder over [B, C, T]
        enc = htext.CNNEncoder(num_channels=8, num_filters=6,
                               filter_size=[2, 3], act="relu")
        x = dygraph.to_variable(r.randn(2, 8, 12).astype("float32"))
        out = enc(x)
        assert out.shape == (2, 12)  # 6 filters x 2 branches

        # BasicLSTMCell driven by the hapi RNN wrapper
        cell = htext.BasicLSTMCell(input_size=5, hidden_size=4)
        rnn = htext.RNN(cell)
        seq = dygraph.to_variable(r.randn(2, 3, 5).astype("float32"))
        h0 = dygraph.to_variable(np.zeros((2, 4), "float32"))
        c0 = dygraph.to_variable(np.zeros((2, 4), "float32"))
        outs, (h, c) = rnn(seq, (h0, c0))
        assert outs.shape == (2, 3, 4)
        assert h.shape == (2, 4) and c.shape == (2, 4)

        # bidirectional wrappers delegate to nn.rnn
        bi = htext.BidirectionalGRU(input_size=5, hidden_size=4)
        out2 = bi(seq)
        got = out2[0] if isinstance(out2, tuple) else out2
        assert got.shape[-1] == 8
