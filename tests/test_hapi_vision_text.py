"""hapi.vision models/transforms + hapi.text building blocks
(reference: incubate/hapi/vision + text test patterns: run a tiny batch
through each model and check shapes/finite outputs)."""
import numpy as np
import pytest

from paddle_tpu.fluid import dygraph
from paddle_tpu.hapi.vision import models, transforms
from paddle_tpu.hapi import text as htext


def test_lenet_forward():
    r = np.random.RandomState(0)
    with dygraph.guard():
        net = models.LeNet(num_classes=10)
        x = dygraph.to_variable(r.randn(2, 1, 28, 28).astype("float32"))
        out = net(x)
        assert out.shape == (2, 10)
        assert np.all(np.isfinite(out.numpy()))


def test_mobilenet_v2_forward():
    r = np.random.RandomState(1)
    with dygraph.guard():
        net = models.MobileNetV2(num_classes=7, scale=0.35)
        x = dygraph.to_variable(r.randn(1, 3, 96, 96).astype("float32"))
        out = net(x)
        assert out.shape == (1, 7)
        assert np.all(np.isfinite(out.numpy()))


def test_mobilenet_v1_forward():
    r = np.random.RandomState(2)
    with dygraph.guard():
        net = models.MobileNetV1(num_classes=5, scale=0.25)
        x = dygraph.to_variable(r.randn(1, 3, 64, 64).astype("float32"))
        out = net(x)
        assert out.shape == (1, 5)


def test_transforms_pipeline():
    r = np.random.RandomState(3)
    img = (r.rand(40, 60, 3) * 255).astype("uint8")
    pipe = transforms.Compose([
        transforms.Resize(32),
        transforms.CenterCrop(28),
        transforms.RandomHorizontalFlip(1.0),
        transforms.ColorJitter(0.1, 0.1, 0.1, 0.05),
        transforms.Normalize(mean=127.5, std=127.5),
        transforms.Permute(),
    ])
    out = pipe(img)
    assert out.shape == (3, 28, 28)
    assert out.dtype == np.float32
    assert -2 < out.min() and out.max() < 2

    rrc = transforms.RandomResizedCrop(16)
    assert rrc(img).shape[:2] == (16, 16)


def test_text_cells_and_encoder():
    r = np.random.RandomState(4)
    with dygraph.guard():
        # TextCNN encoder over [B, C, T]
        enc = htext.CNNEncoder(num_channels=8, num_filters=6,
                               filter_size=[2, 3], act="relu")
        x = dygraph.to_variable(r.randn(2, 8, 12).astype("float32"))
        out = enc(x)
        assert out.shape == (2, 12)  # 6 filters x 2 branches

        # BasicLSTMCell driven by the hapi RNN wrapper
        cell = htext.BasicLSTMCell(input_size=5, hidden_size=4)
        rnn = htext.RNN(cell)
        seq = dygraph.to_variable(r.randn(2, 3, 5).astype("float32"))
        h0 = dygraph.to_variable(np.zeros((2, 4), "float32"))
        c0 = dygraph.to_variable(np.zeros((2, 4), "float32"))
        outs, (h, c) = rnn(seq, (h0, c0))
        assert outs.shape == (2, 3, 4)
        assert h.shape == (2, 4) and c.shape == (2, 4)

        # bidirectional wrappers delegate to nn.rnn
        bi = htext.BidirectionalGRU(input_size=5, hidden_size=4)
        out2 = bi(seq)
        got = out2[0] if isinstance(out2, tuple) else out2
        assert got.shape[-1] == 8


def test_layer_setattr_none_then_sublayer_not_shadowed():
    """`self.x = None; self.x = Layer(...)` must resolve to the layer
    (a plain None in __dict__ used to shadow _sub_layers forever), and
    re-assigning None removes the sublayer again."""
    from paddle_tpu.fluid.dygraph import nn as dnn
    from paddle_tpu.fluid.dygraph.layers import Layer

    class M(Layer):
        def __init__(self):
            super().__init__()
            self.short = None
            self.short = dnn.Linear(4, 4)

    m = M()
    assert m.short is not None and isinstance(m.short, Layer)
    assert "short" in m._sub_layers
    m.short = None
    assert m.short is None and "short" not in m._sub_layers


@pytest.mark.slow
def test_hapi_resnet_vgg_variants_forward_backward(rng):
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import dygraph
    from paddle_tpu.hapi.vision.models import (resnet18, resnet34,
                                               resnet50, vgg11)

    with dygraph.guard():
        x = paddle.to_tensor(rng.rand(2, 3, 32, 32).astype("float32"))
        for ctor in (resnet18, resnet34, resnet50):
            m = ctor(num_classes=5)
            y = m(x)
            assert tuple(y.shape) == (2, 5)
        loss = fluid.layers.mean(y)
        loss.backward()
        g = np.asarray(m.fc.weight.gradient())
        assert g.shape == (2048, 5) and np.isfinite(g).all()
    # vgg variants build (full 224 fc sizing; forward at 224 is slow on
    # CPU, construction + param shapes suffice here)
    m = vgg11(num_classes=3)
    assert m.classifier[-1].weight.shape[-1] == 3


def test_layer_setattr_cross_kind_rebinding():
    """Re-binding an attribute across kinds (param <-> sublayer <->
    plain) must fully replace, never shadow (code-review r4)."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.fluid import dygraph
    from paddle_tpu.fluid.dygraph import nn as dnn
    from paddle_tpu.fluid.dygraph.layers import Layer

    with dygraph.guard():
        lin = dnn.Linear(3, 3)
        m = Layer()
        # None -> param
        m.w = None
        m.w = lin.weight
        assert m.w is not None and "w" in m._parameters
        # param -> sublayer
        m.w = dnn.Linear(2, 2)
        assert isinstance(m.w, Layer)
        assert "w" not in m._parameters and "w" in m._sub_layers
        # sublayer -> plain string: dead weights must leave parameters()
        m.w = "plain"
        assert m.w == "plain" and "w" not in m._sub_layers
        assert all("w." not in k for k in m.state_dict())


def test_vgg_batch_norm_variant():
    from paddle_tpu.fluid.dygraph import nn as dnn
    from paddle_tpu.hapi.vision.models import vgg11

    m = vgg11(batch_norm=True, num_classes=4)
    kinds = [type(l).__name__ for l in m.features]
    assert "BatchNorm" in kinds
    # one BN per conv
    assert kinds.count("BatchNorm") == kinds.count("Conv2D")
    m2 = vgg11(batch_norm=False, num_classes=4)
    assert "BatchNorm" not in [type(l).__name__ for l in m2.features]
