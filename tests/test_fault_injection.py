"""Fault-tolerant distributed runtime: deterministic fault injection
(distributed/faults.py) exercising RPC reconnect + idempotent retry,
rank liveness fast-fail, store blob release, and the RpcServer shutdown
race — all on CPU, no accelerator involved."""
import os
import socket
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

pytestmark = pytest.mark.faults

from paddle_tpu.distributed import faults
from paddle_tpu.distributed.rpc import (RpcClient, RpcRemoteError,
                                        RpcServer)

_DIR = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_DIR)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _env(extra):
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("PALLAS_AXON", "AXON_", "TPU_"))}
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env.pop("PADDLE_FAULTS", None)
    env.update(extra)
    return env


# -- injector unit behavior -------------------------------------------------

def test_parse_spec_roundtrip():
    injs = faults.parse_spec(
        "drop:side=client,point=recv,every=3;"
        "kill:at=40,exit_code=9;delay:every=2,delay_ms=1")
    assert [i.kind for i in injs] == ["drop", "kill", "delay"]
    assert injs[0].every == 3 and injs[0].side == "client"
    assert injs[1].at == 40 and injs[1].exit_code == 9
    assert injs[2].delay_ms == 1.0
    with pytest.raises(ValueError):
        faults.parse_spec("drop:every=1,at=2")  # both triggers
    with pytest.raises(ValueError):
        faults.parse_spec("explode:every=1")  # unknown kind


def test_injector_counts_only_matching_events():
    inj = faults.FaultInjector("drop", side="client", point="recv",
                               method="put", every=2)
    inj.fire("server", "recv", "put", None)   # wrong side: no count
    inj.fire("client", "send", "put", None)   # wrong point: no count
    inj.fire("client", "recv", "get", None)   # wrong method: no count
    inj.fire("client", "recv", "put", None)   # 1st match: no fire
    with pytest.raises(ConnectionError):
        inj.fire("client", "recv", "put", None)  # 2nd match: fires


def test_wire_format_roundtrips_large_batches():
    """u16 field count: a batched send_grads_batch for a model with
    hundreds of params per pserver must fit in one message (the u8
    count capped it at ~125 params and overflowed with a bare
    ValueError)."""
    from paddle_tpu.distributed.rpc import decode, encode

    fields = ["send_grads_batch", 7, 150]
    for i in range(150):
        fields += ["param_%d" % i, np.full((3,), i, np.float32)]
    body = encode(fields)[8:]  # strip the u64 length prefix
    out = decode(body)
    assert out[0] == "send_grads_batch" and out[2] == 150
    assert len(out) == len(fields)
    np.testing.assert_array_equal(out[-1], fields[-1])
    with pytest.raises(ValueError, match="max 65535"):
        encode(list(range(70000)))


# -- RPC reconnect + exactly-once retry -------------------------------------

def _counting_server():
    seen = []

    def handler(method, args):
        if method == "incr":
            seen.append(int(args[0]))
            return [len(seen)]
        if method == "boom":
            raise KeyError("table row missing")
        return list(args)

    srv = RpcServer("127.0.0.1", 0, handler)
    srv.start()
    return srv, seen


def test_client_reconnects_and_handler_runs_exactly_once():
    """Drop the connection on every 3rd response read: the request was
    already APPLIED server-side, so the blind-retry failure mode is a
    double-apply. The envelope dedup must keep the handler at exactly
    one invocation per call."""
    srv, seen = _counting_server()
    cli = RpcClient("127.0.0.1:%d" % srv.port)
    try:
        with faults.inject("drop", side="client", point="recv", every=3):
            for i in range(20):
                (n,) = cli.call("incr", i)
                assert n == i + 1  # replayed response, not re-applied
        assert seen == list(range(20))
    finally:
        cli.close()
        srv.shutdown()


def test_close_evicts_server_dedup_entry():
    """A clean client close must release the server-side dedup entry
    (it pins the client's last response blob otherwise)."""
    srv, _ = _counting_server()
    cli = RpcClient("127.0.0.1:%d" % srv.port)
    try:
        cli.call("incr", 0)
        assert cli._cid in srv._dedup
        cli.close()
        deadline = time.monotonic() + 5
        while cli._cid in srv._dedup and time.monotonic() < deadline:
            time.sleep(0.02)
        assert cli._cid not in srv._dedup
    finally:
        srv.shutdown()


def test_ack_last_releases_retained_blob_but_keeps_dedup():
    """Acked-release (ROADMAP carried-over item): after the client acks
    the applied seq, the server frees the retained response blob (a
    params-sized get_params_batch reply pinned per trainer between
    steps otherwise) while the seq marker stays for dedup — and later
    calls still dedup/replay correctly."""
    srv, seen = _counting_server()
    cli = RpcClient("127.0.0.1:%d" % srv.port)
    try:
        big = b"x" * (1 << 20)
        (echo,) = cli.call("echo", big)
        assert bytes(np.asarray(echo).tobytes()) == big

        def blob_bytes(resp):
            return sum(int(getattr(f, "nbytes", 0)) for f in resp)

        ent = srv._dedup[cli._cid]
        acked_seq = ent["seq"]
        assert blob_bytes(ent["resp"]) >= len(big), "blob retained pre-ack"
        cli.ack_last()
        ent = srv._dedup[cli._cid]
        assert ent["seq"] == acked_seq, "seq marker must survive the ack"
        assert blob_bytes(ent["resp"]) < len(big), "blob must be freed"
        # exactly-once semantics are untouched for later calls
        with faults.inject("drop", side="client", point="recv", every=2):
            for i in range(6):
                (n,) = cli.call("incr", i)
                assert n == i + 1
        assert seen == list(range(6))
    finally:
        cli.close()
        srv.shutdown()


def test_ack_of_stale_seq_is_a_noop():
    """An ack for anything but the newest completed seq (a late or
    confused client) must not disturb the dedup entry."""
    srv, _ = _counting_server()
    cli = RpcClient("127.0.0.1:%d" % srv.port)
    try:
        cli.call("echo", b"first")
        cli.call("echo", b"payload")  # newest completed seq is 2
        ent = srv._dedup[cli._cid]
        resp_before = ent["resp"]
        # hand-roll an ack for the STALE seq 1
        from paddle_tpu.distributed.rpc import (_ENVELOPE, read_msg,
                                                write_msg)

        with cli._lock:
            cli._seq += 1
            write_msg(cli._sock, [_ENVELOPE, cli._cid, cli._seq,
                                  "__rpc_ack__", 1])
            read_msg(cli._sock)
        assert srv._dedup[cli._cid]["resp"] is resp_before
    finally:
        cli.close()
        srv.shutdown()


def test_client_retries_send_side_drops_too():
    srv, seen = _counting_server()
    cli = RpcClient("127.0.0.1:%d" % srv.port)
    try:
        with faults.inject("drop", side="client", point="send", every=4):
            for i in range(12):
                cli.call("incr", i)
        assert seen == list(range(12))
    finally:
        cli.close()
        srv.shutdown()


def test_retry_budget_exhaustion_raises_connection_error(monkeypatch):
    monkeypatch.setenv("PADDLE_RPC_RETRIES", "2")
    monkeypatch.setenv("PADDLE_RPC_BACKOFF_S", "0.01")
    srv, _ = _counting_server()
    cli = RpcClient("127.0.0.1:%d" % srv.port)
    try:
        with faults.inject("drop", side="client", point="send", every=1):
            with pytest.raises(ConnectionError, match="after 2 retries"):
                cli.call("incr", 0)
    finally:
        cli.close()
        srv.shutdown()


def test_remote_errors_carry_type_and_traceback():
    srv, _ = _counting_server()
    cli = RpcClient("127.0.0.1:%d" % srv.port)
    try:
        with pytest.raises(RpcRemoteError) as ei:
            cli.call("boom")
        e = ei.value
        assert e.remote_type == "KeyError"
        assert "table row missing" in e.remote_msg
        assert "KeyError" in e.remote_traceback
        assert "remote traceback" in str(e)
        # the connection survives an application error (no retry storm)
        assert cli.call("echo", 7) == [7]
    finally:
        cli.close()
        srv.shutdown()


# -- RpcServer shutdown race (satellite regression) -------------------------

def test_server_shutdown_idempotent_and_safe_from_handler_thread():
    done = threading.Event()

    def handler(method, args):
        if method == "die":
            srv.shutdown()  # from THIS server's own handler thread
            done.set()
            return []
        return []

    srv = RpcServer("127.0.0.1", 0, handler)
    srv.start()
    cli = RpcClient("127.0.0.1:%d" % srv.port)
    cli.call("die")
    assert done.wait(timeout=10), "handler-thread shutdown deadlocked"
    # idempotent: repeated + concurrent shutdowns are no-ops
    srv.shutdown()
    srv.shutdown()
    cli.close()


def test_ps_sync_barrier_breaks_with_missing_ranks_and_recovers(
        monkeypatch):
    """A sync barrier stuck on a dead trainer must (a) time out naming
    the ranks that never arrived — heartbeat ages can't attribute it,
    every blocked waiter looks stale — and (b) reset so a later round
    with all trainers present still synchronizes."""
    monkeypatch.setenv("PADDLE_PS_BARRIER_TIMEOUT_S", "1")
    from paddle_tpu.distributed.ps import ParameterServer
    from paddle_tpu.fluid import framework as fw

    ps = ParameterServer(fw.Program(), None, trainers=2, mode="sync")
    try:
        with pytest.raises(RuntimeError, match=r"trainers \[1\] never "
                                               r"arrived"):
            ps.handle("send_barrier", [0])
        # recovery: both trainers arrive -> the reset barrier releases
        results = []
        ts = [threading.Thread(
            target=lambda t=t: results.append(
                ps.handle("send_barrier", [t]))) for t in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
        assert results == [[], []]
    finally:
        ps.heartbeat.stop()


# -- host-collective liveness + blob release --------------------------------

def test_store_liveness_fast_fail_names_missing_ranks(monkeypatch):
    """A barrier blocked on a dead rank must fail in ~liveness_s with
    the missing rank ids + heartbeat age, not hang to the full
    PADDLE_HC_TIMEOUT_S."""
    monkeypatch.setenv("PADDLE_HC_HEARTBEAT_S", "0.2")
    monkeypatch.setenv("PADDLE_HC_LIVENESS_S", "1.0")
    # rank 1 never connects, so it is judged by the JOIN window (which
    # defaults to minutes to tolerate cold starts) — shrink it
    monkeypatch.setenv("PADDLE_HC_JOIN_S", "1.0")
    monkeypatch.setenv("PADDLE_HC_TIMEOUT_S", "120")
    from paddle_tpu.distributed.host_collectives import \
        HostCollectiveGroup

    g0 = HostCollectiveGroup(0, 2, "127.0.0.1:0")  # rank 1 never joins
    try:
        t0 = time.monotonic()
        with pytest.raises(RpcRemoteError) as ei:
            g0.barrier()
        dt = time.monotonic() - t0
        assert dt < 30, "fast-fail took %.0fs (liveness window 1s)" % dt
        assert "waiting on ranks {1}" in ei.value.remote_msg
        assert "last heartbeat" in ei.value.remote_msg
    finally:
        g0.shutdown()


def test_store_releases_blobs_after_each_collective(monkeypatch):
    """Seed leaked every contributed blob for the life of the run:
    _kv/_counts must drain once all ranks fetched (memory stays bounded
    across per-step barriers/allreduces)."""
    monkeypatch.setenv("PADDLE_HC_HEARTBEAT_S", "0.2")
    from paddle_tpu.distributed.host_collectives import \
        HostCollectiveGroup

    g0 = HostCollectiveGroup(0, 2, "127.0.0.1:0")
    ep = "127.0.0.1:%d" % g0._server.port
    g1 = HostCollectiveGroup(1, 2, ep)
    out = {}

    def run(g, r):
        for _ in range(5):
            g.barrier()
            out[(r, "sum")] = g.all_reduce(np.asarray([1.0 + r]))[0]
            out[(r, "b")] = int(g.broadcast(np.asarray([9 + r]),
                                            root=0)[0])

    ts = [threading.Thread(target=run, args=(g, r))
          for r, g in ((0, g0), (1, g1))]
    try:
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=120)
        assert not any(t.is_alive() for t in ts)
        assert out[(0, "sum")] == out[(1, "sum")] == 3.0
        assert out[(0, "b")] == out[(1, "b")] == 9
        assert g0.store_stats() == (0, 0, 0), \
            "store still holds blobs: kv/counts/fetched=%s" \
            % (g0.store_stats(),)
    finally:
        g1.shutdown()
        g0.shutdown()


# -- end-to-end: collectives + PS train loop under injected drops -----------

@pytest.mark.dist
def test_two_rank_collectives_identical_under_injected_drops():
    """Acceptance: with fault injection dropping the store connection
    every N messages, a 2-rank host-collective run completes with
    results identical to the no-fault run."""
    script = textwrap.dedent("""
        import sys, numpy as np
        sys.path.insert(0, %r)
        from paddle_tpu.distributed.host_collectives import \\
            HostCollectiveGroup
        rank = int(sys.argv[1])
        g = HostCollectiveGroup(rank, 2, "127.0.0.1:" + sys.argv[2])
        for i in range(6):
            g.barrier()
            s = g.all_reduce(np.asarray([1.0 + rank, float(i)]))
            print("SUM", i, s.tolist(), flush=True)
        g.barrier()
        g.shutdown()
    """ % _REPO)

    def run(fault_spec):
        port = str(_free_port())
        extra = {"PADDLE_FAULTS": fault_spec} if fault_spec else {}
        procs = [subprocess.Popen(
            [sys.executable, "-c", script, str(r), port],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=_env(extra)) for r in range(2)]
        outs = []
        for p in procs:
            out, _ = p.communicate(timeout=180)
            assert p.returncode == 0, out
            outs.append(sorted(ln for ln in out.splitlines()
                               if ln.startswith("SUM")))
        return outs

    clean = run(None)
    faulty = run("drop:side=client,point=recv,method=hc_gather,every=4")
    assert clean == faulty
    assert len(clean[0]) == 6


@pytest.mark.dist
def test_ps_sync_train_loop_identical_under_injected_drops():
    """Acceptance: a REAL sync PS train loop (fluid Executor +
    transpiled programs, dist_ps_runner) with the trainer connection
    dropped every N messages produces bit-identical losses to the
    no-fault run — retried grad pushes are never double-applied."""
    runner = os.path.join(_DIR, "dist_ps_runner.py")

    def run(fault_spec):
        eps = "127.0.0.1:%d" % _free_port()
        extra = {"PADDLE_FAULTS": fault_spec} if fault_spec else {}
        server = subprocess.Popen(
            [sys.executable, runner, "pserver", eps, eps, "1", "sync"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=_env({}), cwd=_DIR)
        trainer = subprocess.Popen(
            [sys.executable, runner, "trainer", "0", eps, "1", "sync"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=_env(extra), cwd=_DIR)
        try:
            tout, _ = trainer.communicate(timeout=240)
            assert trainer.returncode == 0, tout
            sout, _ = server.communicate(timeout=60)
            assert server.returncode == 0, sout
        finally:
            for p in (server, trainer):
                if p.poll() is None:
                    p.kill()
        return [ln for ln in tout.splitlines() if ln.startswith("LOSS")]

    clean = run(None)
    faulty = run("drop:side=client,point=recv,every=5")
    assert len(clean) == 5
    assert clean == faulty


# -- reconnect backoff jitter (elastic satellite) ---------------------------

def test_backoff_jitter_spreads_retry_sleeps(monkeypatch):
    """Pure exponential backoff synchronizes the cohort's retry clocks
    after a pserver restart (thundering herd); each sleep must jitter
    within [1-j, 1+j] of the capped exponential base, and j=0 must stay
    exactly deterministic."""
    monkeypatch.setenv("PADDLE_RPC_BACKOFF_S", "0.1")
    monkeypatch.setenv("PADDLE_RPC_BACKOFF_MAX_S", "0.8")
    monkeypatch.setenv("PADDLE_RPC_BACKOFF_JITTER", "0.5")
    srv, _ = _counting_server()
    try:
        cli = RpcClient("127.0.0.1:%d" % srv.port)
        base2 = 0.2   # 0.1 * 2^(2-1)
        draws = {cli._backoff_sleep_s(2) for _ in range(64)}
        assert all(0.1 - 1e-9 <= d <= 0.3 + 1e-9 for d in draws), draws
        assert len(draws) > 1, "jitter must actually vary the sleeps"
        assert any(abs(d - base2) > 0.01 for d in draws)
        # the exponential stays capped under jitter's upper bound
        assert all(d <= 0.8 * 1.5 + 1e-9
                   for d in (cli._backoff_sleep_s(30)
                             for _ in range(16)))
        cli.close()
        monkeypatch.setenv("PADDLE_RPC_BACKOFF_JITTER", "0")
        cli2 = RpcClient("127.0.0.1:%d" % srv.port)
        assert cli2._backoff_sleep_s(2) == base2
        assert cli2._backoff_sleep_s(30) == 0.8
        cli2.close()
    finally:
        srv.shutdown()


# -- preemption DURING a checkpoint save (elastic satellite) ----------------

def _run_ckpt_kill(mode, root):
    # cwd = the checkpoint parent: the fault-kill's flight dump lands
    # there instead of polluting the repo root
    proc = subprocess.run(
        [sys.executable, os.path.join(_DIR, "ckpt_kill_runner.py"),
         mode, root],
        env=_env({}), cwd=os.path.dirname(root), stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True, timeout=240)
    assert proc.returncode == 9, proc.stdout  # the injected kill's rc
    assert "SAVED0" in proc.stdout
    assert "UNREACHABLE" not in proc.stdout
    return proc.stdout


def test_fluid_restore_never_sees_half_written_step_dir(tmp_path):
    """PADDLE_FAULTS kill DURING the second fluid checkpoint save
    (payload written, publication pending): the .tmp dir is left on
    disk, and the newest-intact fallback restores checkpoint 0 without
    ever surfacing the half-written step."""
    root = str(tmp_path / "ck")
    _run_ckpt_kill("fluid", root)
    from paddle_tpu.fluid import checkpoint as ckpt

    leftovers = sorted(os.listdir(root))
    assert any(n.endswith(".tmp") for n in leftovers), leftovers
    assert ckpt.get_last_checkpoint_no(root) == 0
    latest = ckpt.latest_checkpoint_dir(root)
    assert latest and not latest.endswith(".tmp")
    assert ckpt.read_status(latest).step_no == 0


def test_sharded_restore_never_sees_half_written_step_dir(tmp_path):
    """Same for the orbax-backed manager: the kill fires after save()
    issued the async write (step dir uncommitted on disk);
    all_steps()/restore() must surface only step 0."""
    root = str(tmp_path / "sck")
    _run_ckpt_kill("sharded", root)
    leftovers = sorted(os.listdir(root))
    assert any("tmp" in n for n in leftovers), \
        "the kill must leave an uncommitted step: %s" % leftovers
    from paddle_tpu.distributed.sharded_checkpoint import \
        ShardedCheckpointManager

    mgr = ShardedCheckpointManager(root)
    try:
        assert mgr.all_steps() == [0]
        got = mgr.restore(template={
            "w": np.zeros((1 << 20,), np.float32),
            "step": np.zeros((1,), np.int64)})
        assert float(np.asarray(got["w"])[0]) == 1.0
        assert int(np.asarray(got["step"])[0]) == 0
    finally:
        mgr.close()


# -- pserver checkpoint/restore: exactly-once across a server death ---------

def test_pserver_checkpoint_restores_tables_and_dedup(tmp_path):
    """The server role's elastic story (ROADMAP carried-over item): a
    server that dies after applying-and-persisting a request comes back
    with its tables AND per-client applied-seq markers; the client's
    RETRY of that request is answered from the restored marker — never
    re-applied — while a genuinely new request executes normally."""
    from paddle_tpu.distributed.ps import ParameterServer
    from paddle_tpu.fluid import framework as fw

    ckpt_dir = str(tmp_path / "ps_ckpt")
    ps1 = ParameterServer(fw.Program(), None, trainers=1, mode="async",
                          ckpt_dir=ckpt_dir, ckpt_every=1)
    srv1 = RpcServer("127.0.0.1", 0, ps1.handle)
    srv1.start()
    cli = RpcClient("127.0.0.1:%d" % srv1.port)
    try:
        table0 = np.arange(12, dtype=np.float32).reshape(4, 3)
        cli.call("init_param", "w", table0)
        rows = np.asarray([1, 3], np.int64)
        vals = np.ones((2, 3), np.float32)
        cli.call("sparse_grad_sgd", "w", rows, vals, 0.5)
        applied = np.asarray(ps1.scope.find_var("w")).copy()
        assert not np.array_equal(applied, table0)
        retry_seq = cli._seq  # the request whose response could be lost
    finally:
        srv1.shutdown()
        ps1.heartbeat.stop()

    # the reborn server restores tables + dedup markers from disk
    ps2 = ParameterServer(fw.Program(), None, trainers=1, mode="async",
                          ckpt_dir=ckpt_dir, ckpt_every=1)
    dedup = ps2.restore_from_checkpoint()
    assert dedup and cli._cid in dedup
    np.testing.assert_array_equal(
        np.asarray(ps2.scope.find_var("w")), applied)
    srv2 = RpcServer("127.0.0.1", 0, ps2.handle)
    srv2.dedup_restore(dedup)
    srv2.start()
    try:
        from paddle_tpu.distributed.rpc import (_ENVELOPE, read_msg,
                                                write_msg)

        # the client never got its response: re-send the SAME envelope
        s = socket.create_connection(("127.0.0.1", srv2.port))
        try:
            write_msg(s, [_ENVELOPE, cli._cid, retry_seq,
                          "sparse_grad_sgd", "w", rows, vals, 0.5])
            resp = read_msg(s)
            assert resp and resp[0] == "ok", resp
            # the retry was answered from the marker, NOT re-applied
            np.testing.assert_array_equal(
                np.asarray(ps2.scope.find_var("w")), applied)
            # a NEW request still executes normally
            write_msg(s, [_ENVELOPE, cli._cid, retry_seq + 1,
                          "sparse_grad_sgd", "w", rows, vals, 0.5])
            resp2 = read_msg(s)
            assert resp2 and resp2[0] == "ok", resp2
            assert not np.array_equal(
                np.asarray(ps2.scope.find_var("w")), applied)
        finally:
            s.close()
    finally:
        srv2.shutdown()
        ps2.heartbeat.stop()
        cli.close()


def test_pserver_restored_complete_marker_still_stops_the_server(
        tmp_path):
    """A server killed between applying the LAST trainer's `complete`
    and answering it must not serve forever after restart: the
    restored marker carries the stop bit, so the trainer's retried
    `complete` is answered from dedup AND stops the reborn server —
    and a restore whose completed-set is already full releases
    wait_stopped immediately."""
    from paddle_tpu.distributed.ps import ParameterServer
    from paddle_tpu.fluid import framework as fw
    from paddle_tpu.distributed.rpc import (_ENVELOPE, read_msg,
                                            write_msg)

    ckpt_dir = str(tmp_path / "ps_ckpt")
    ps1 = ParameterServer(fw.Program(), None, trainers=1, mode="async",
                          ckpt_dir=ckpt_dir, ckpt_every=1)
    srv1 = RpcServer("127.0.0.1", 0, ps1.handle)
    srv1.start()
    cli = RpcClient("127.0.0.1:%d" % srv1.port)
    try:
        cli.call("complete", 0)  # applied + persisted (stop marker)
        last_seq = cli._seq
    finally:
        srv1.shutdown()
        ps1.heartbeat.stop()

    ps2 = ParameterServer(fw.Program(), None, trainers=1, mode="async",
                          ckpt_dir=ckpt_dir, ckpt_every=1)
    dedup = ps2.restore_from_checkpoint()
    try:
        assert ps2._completed == {0}
        assert dedup[cli._cid][2] is True, "stop bit must persist"
        srv2 = RpcServer("127.0.0.1", 0, ps2.handle)
        srv2.dedup_restore(dedup)
        srv2.start()
        # the retried complete replays from the marker AND stops the
        # reborn server (the hang the review caught)
        s = socket.create_connection(("127.0.0.1", srv2.port))
        try:
            write_msg(s, [_ENVELOPE, cli._cid, last_seq,
                          "complete", 0])
            assert read_msg(s)[0] == "ok"
        finally:
            s.close()
        srv2._stop_evt.wait(timeout=10)
        assert srv2._stop_evt.is_set()
        srv2.shutdown()
    finally:
        ps2.heartbeat.stop()
        cli.close()


def test_pserver_restore_falls_back_past_corrupt_snapshot(tmp_path):
    """Newest-intact semantics for the server snapshots too: a torn
    newest file (disk fault) falls back to the previous one."""
    from paddle_tpu.distributed.ps import ParameterServer
    from paddle_tpu.fluid import framework as fw

    ckpt_dir = str(tmp_path / "ps_ckpt")
    ps1 = ParameterServer(fw.Program(), None, trainers=1, mode="async",
                          ckpt_dir=ckpt_dir, ckpt_every=1)
    srv1 = RpcServer("127.0.0.1", 0, ps1.handle)
    srv1.start()
    cli = RpcClient("127.0.0.1:%d" % srv1.port)
    try:
        cli.call("init_param", "w", np.zeros((2, 2), np.float32))
        cli.call("sparse_grad_sgd", "w",
                 np.asarray([0], np.int64),
                 np.ones((1, 2), np.float32), 1.0)
        good = np.asarray(ps1.scope.find_var("w")).copy()
    finally:
        cli.close()
        srv1.shutdown()
        ps1.heartbeat.stop()
    snaps = sorted(os.listdir(ckpt_dir))
    assert len(snaps) == 2, snaps
    with open(os.path.join(ckpt_dir, snaps[-1]), "wb") as f:
        f.write(b"torn write")
    ps2 = ParameterServer(fw.Program(), None, trainers=1, mode="async",
                          ckpt_dir=ckpt_dir, ckpt_every=1)
    try:
        assert ps2.restore_from_checkpoint() is not None
        # the corrupt newest snapshot fell back to snapshot 0 (the
        # state right after init_param: zeros)
        np.testing.assert_array_equal(
            np.asarray(ps2.scope.find_var("w")),
            np.zeros((2, 2), np.float32))
        assert not np.array_equal(
            np.asarray(ps2.scope.find_var("w")), good)
    finally:
        ps2.heartbeat.stop()


# -- acceptance: pserver killed mid-run, restarted by the supervisor --------

@pytest.mark.dist
@pytest.mark.slow
def test_ps_sync_pserver_killed_and_restarted_identical(tmp_path):
    """Acceptance (server-role elastic): a sync-PS cohort whose ONE
    pserver is PADDLE_FAULTS-killed mid-run and restarted by the
    launch_ps supervisor — restoring tables + dedup markers from its
    snapshots — completes with per-step losses IDENTICAL to the
    no-fault run (extends PR 1's exactly-once acceptance to the server
    role)."""
    script = tmp_path / "role.py"
    script.write_text(
        "import os, sys\n"
        "sys.path.insert(0, %r)\n"
        "sys.path.insert(0, %r)\n"
        "import dist_ps_runner as R\n"
        "role = os.environ['TRAINING_ROLE']\n"
        "eps = os.environ['PADDLE_PSERVERS_IP_PORT_LIST']\n"
        "n = int(os.environ['PADDLE_TRAINERS_NUM'])\n"
        "if role == 'PSERVER':\n"
        "    if int(os.environ.get('PADDLE_RESTART_NUM', '0')) > 0:\n"
        "        os.environ.pop('PADDLE_FAULTS', None)\n"
        "    R.run_pserver(os.environ['PADDLE_CURRENT_ENDPOINT'],\n"
        "                  eps, n, 'sync')\n"
        "else:\n"
        "    os.environ.pop('PADDLE_FAULTS', None)\n"
        "    R.run_trainer(int(os.environ['PADDLE_TRAINER_ID']),\n"
        "                  eps, n, 'sync')\n"
        % (_DIR, _REPO))

    from paddle_tpu.distributed import launch_ps

    def run(tag, fault_spec, max_restarts):
        logs = str(tmp_path / ("logs_" + tag))
        server_ep = "127.0.0.1:%d" % _free_port()
        env_backup = dict(os.environ)
        clean = _env({})
        clean["PADDLE_RPC_RETRIES"] = "60"  # ride out the jax restart
        # the killed server's flight dump must land here, not in CWD
        clean["FLAGS_tpu_telemetry_dir"] = str(
            tmp_path / ("telemetry_" + tag))
        if fault_spec:
            clean["PADDLE_FAULTS"] = fault_spec
        argv = ["--servers", server_ep, "--worker_num", "2",
                "--log_dir", logs,
                "--ps_ckpt_dir", str(tmp_path / ("ps_state_" + tag)),
                str(script)]
        if max_restarts:
            argv = ["--max_restarts", str(max_restarts)] + argv
        try:
            os.environ.clear()
            os.environ.update(clean)
            rc = launch_ps.launch(argv)
        finally:
            os.environ.clear()
            os.environ.update(env_backup)
        assert rc == 0, open(
            os.path.join(logs, "workerlog.0.log")).read()
        out = []
        for i in range(2):
            with open(os.path.join(logs,
                                   "workerlog.%d.log" % i)) as f:
                out.append([ln for ln in f.read().splitlines()
                            if ln.startswith("LOSS")])
        return out, logs

    clean_losses, _ = run("clean", None, 0)
    # the kill lands mid-run on the server's Nth socket recv event
    faulty_losses, logs = run(
        "kill", "kill:side=server,point=recv,at=25", 2)
    with open(os.path.join(logs, "serverlog.0.log")) as f:
        slog = f.read()
    assert slog.count("SERVING") >= 2, \
        "server was not restarted by the supervisor:\n" + slog
    assert all(len(ls) == 5 for ls in clean_losses), clean_losses
    assert clean_losses == faulty_losses, (clean_losses, faulty_losses)
