"""Tensor parallelism on the hybrid mesh (FLAGS_tpu_model_parallel /
PADDLE_MP_DEGREE): the intra-pod ici tier factors into
(replica, model) and ONE planner (parallel/planner.plan_parallel)
assigns every axis — weight out-dims / vocab rows shard over `model`
via the logical-axis rules (parallel/axis_rules), ZeRO-1 moments, AMP
fp32 masters and grad buckets shard over the replica axis at TP-LOCAL
shapes, grad sync stays confined to the (dcn, replica) pair.

Numerics contract (parallel/README.md "Tensor parallelism"): the TP
forward is bit-identical to single-device — column-parallel partials
are assembled by all_gather (a reordering-free concat) and
vocab-parallel lookups psum DISJOINT row blocks. Only the activation
gradient's model-axis psum reassociates a sum, so losses match the
single-device trajectory within a small fp32 relative bound (~1e-7
per step observed; asserted at rtol 2e-5 over multi-step training).
At mp=1 the factorization short-circuits everywhere: the lowered HLO
is byte-for-byte the pre-TP module.

Machinery under test: parallel/env.create_hybrid_mesh 3-D mesh +
mesh_hierarchy, parallel/tensor_parallel (plan + shard_map
primitives), parallel/planner, parallel/sharded_update TP-local
layout, fluid/lowering (_compile_dp four-group state split, census
"mp" lane), fluid/checkpoint save-logical/restore-sharded,
observability/publish.model_parallel_block.
"""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu import analysis
from paddle_tpu.core.scope import Scope
from paddle_tpu.fluid import checkpoint as ckpt
from paddle_tpu.fluid import framework
from paddle_tpu.parallel import env as penv
from paddle_tpu.utils.flags import get_flag, set_flags

O = fluid.optimizer


@pytest.fixture(autouse=True)
def _restore_flags():
    keys = ("FLAGS_tpu_sharded_weight_update", "FLAGS_tpu_comm_bucket_mb",
            "FLAGS_tpu_dcn_replicas", "FLAGS_tpu_model_parallel")
    old = {k: get_flag(k) for k in keys}
    yield
    set_flags(old)


def _fresh():
    from paddle_tpu.core import scope as scope_mod

    framework.switch_main_program(framework.Program())
    framework.switch_startup_program(framework.Program())
    scope_mod._global_scope = scope_mod.Scope()


def _batch():
    r = np.random.RandomState(3)
    # batch 16: divisible by every data world used here (4, 2, 1)
    return (r.randint(0, 64, size=(16, 8)).astype("int64"),
            r.randint(0, 4, (16, 1)).astype("int64"))


def _set_mesh(prog, ndev, dcn, mp):
    import jax
    from jax.sharding import Mesh

    devs = np.array(jax.devices()[:ndev])
    if mp > 1:
        # model INNERMOST: a model group is a contiguous fastest-hop
        # block, and the dcn axis is kept even at dcn == 1 (one mesh
        # shape for every consumer) — mirrors create_hybrid_mesh
        prog._mesh = Mesh(devs.reshape(dcn, ndev // (dcn * mp), mp),
                          ("dcn", "ici", "model"))
    elif dcn > 1:
        prog._mesh = Mesh(devs.reshape(dcn, ndev // dcn),
                          ("dcn", "ici"))
    else:
        prog._mesh = Mesh(devs, ("dp",))


def _train(ndev, dcn=1, mp=1, zero1=False, amp=False, bucket_mb=0.0,
           steps=4, fc1=16):
    """Embedding (vocab-parallel) + 2 fc (column-parallel) classifier
    trained `steps` identical-feed Adam steps on an `ndev`-device mesh
    factored (dcn, ici, model). Returns (losses, exe, prog, loss)."""
    _fresh()
    set_flags({"FLAGS_tpu_sharded_weight_update": zero1,
               "FLAGS_tpu_comm_bucket_mb": bucket_mb,
               "FLAGS_tpu_dcn_replicas": 0,
               "FLAGS_tpu_model_parallel": 0})
    ids_np, y = _batch()
    with framework.unique_name_guard():
        framework.default_main_program().random_seed = 1234
        framework.default_startup_program().random_seed = 1234
        ids = fluid.data(name="ids", shape=[-1, 8], dtype="int64")
        label = fluid.data(name="label", shape=[-1, 1], dtype="int64")
        emb = fluid.embedding(ids, size=(64, 16),
                              param_attr=fluid.ParamAttr(name="tp.emb"))
        pooled = fluid.layers.reduce_mean(emb, dim=1)
        h = fluid.layers.fc(input=pooled, size=fc1, act="relu")
        logits = fluid.layers.fc(input=h, size=4)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        opt = O.AdamOptimizer(learning_rate=0.01)
        if amp:
            from paddle_tpu.fluid.contrib import mixed_precision

            opt = mixed_precision.decorate(opt)
        opt.minimize(loss)
        prog = fluid.default_main_program()
        fluid.CompiledProgram(prog).with_data_parallel(
            loss_name=loss.name)
        _set_mesh(prog, ndev, dcn, mp)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        losses = [float(np.mean(np.asarray(exe.run(
            prog, feed={"ids": ids_np, "label": y},
            fetch_list=[loss])[0]))) for _ in range(steps)]
    return losses, exe, prog, loss


# ---------------------------------------------------------------------------
# parity matrix: mp=2 / mp=4 / dp x mp / dcn x dp x mp vs single-device
# ---------------------------------------------------------------------------

def test_tp_parity_matrix_vs_single_device():
    """The acceptance matrix: every TP factorization tracks the
    single-device trajectory within the documented bound (only the
    activation-grad psum reassociates; everything else is
    bit-preserving concat/disjoint-psum)."""
    base, *_ = _train(1)
    matrix = [(8, 1, 2), (8, 1, 4), (4, 1, 2), (8, 2, 2)]
    for ndev, dcn, mp in matrix:
        got, _, prog, _ = _train(ndev, dcn, mp)
        np.testing.assert_allclose(
            got, base, rtol=2e-5, atol=0,
            err_msg="ndev=%d dcn=%d mp=%d diverged from single-device"
            % (ndev, dcn, mp))
        tpp = prog._tp_plan
        assert tpp is not None and tpp.mp == mp
        # all three weights shard: the embedding table vocab-parallel
        # (dim 0), both fc weights column-parallel (dim 1)
        dims = {n: p.tp_dim for n, p in tpp.params.items()}
        assert dims.pop("tp.emb") == 0
        assert len(dims) == 2 and set(dims.values()) == {1}
        hier = penv.mesh_hierarchy(prog._mesh)
        assert hier.model_axis == "model" and hier.mp_size == mp


def test_tp_zero1_sharded_matches_replicated_bit_identical():
    """The ZeRO guarantee survives TP: on the SAME (dcn, ici, model)
    mesh the replica-sharded update (moments + buckets at TP-LOCAL
    shapes) is bit-identical to the replicated update — sharding never
    changes the math, now three-axis."""
    rep, *_ = _train(8, 1, 2, zero1=False)
    sh, _, prog, _ = _train(8, 1, 2, zero1=True, bucket_mb=0.001)
    assert rep == sh, (rep, sh)
    plan = prog._shard_plan
    assert plan is not None and plan.sharded_state and plan.buckets
    # TP'd vars ride the flat ZeRO layout at their LOCAL block shapes
    tp_infos = {n: i for n, i in plan.sharded_state.items()
                if getattr(i, "tp_dim", None) is not None}
    assert tp_infos, "no TP-local sharded state in the ZeRO plan"
    for n, info in tp_infos.items():
        logical = list(info.logical_shape)
        logical[info.tp_dim] //= info.mp
        assert tuple(logical) == tuple(info.shape), (n, info)


def test_tp_amp_o2_masters_plan_and_parity():
    """ZeRO-1 + AMP-O2 + bucketed overlap all PLAN on a TP'd program
    (fp32 masters shard over the replica axis at TP-local shapes) and
    the sharded run stays bit-identical to replicated on the same
    mesh."""
    rep, *_ = _train(8, 1, 2, zero1=False, amp=True)
    sh, _, prog, _ = _train(8, 1, 2, zero1=True, amp=True,
                            bucket_mb=0.25)
    assert rep == sh, (rep, sh)
    plan = prog._shard_plan
    assert plan is not None and plan.master_of and plan.buckets
    assert prog._tp_plan is not None and prog._tp_plan.params
    trail = getattr(prog, "_sharded_update_fallback", []) or []
    unexplained = [e for e in trail
                   if e.get("kind") not in ("tp_declined",)]
    assert not unexplained, unexplained


# ---------------------------------------------------------------------------
# mp=1 byte-for-byte + structured declines
# ---------------------------------------------------------------------------

def test_mp1_hlo_byte_identical():
    """FLAGS_tpu_model_parallel=1 short-circuits everywhere: the
    lowered module is byte-for-byte the flag-unset module."""
    ids_np, y = _batch()

    def lowered(mp_flag):
        losses, exe, prog, loss = _train(4)
        set_flags({"FLAGS_tpu_model_parallel": mp_flag})
        got = exe._cached_lowerable(
            prog, {"ids": ids_np, "label": y}, [loss], None)
        assert got is not None
        return losses, got[1].as_text()

    l0, hlo0 = lowered(0)
    l1, hlo1 = lowered(1)
    assert hlo0 == hlo1
    assert l0 == l1


def test_tp_structured_decline_records_reason():
    """A weight whose sharded dim does not divide by mp is DECLINED
    with a structured reason on the fallback trail (kind=tp_declined)
    — and the program still trains, tracking single-device."""
    base, *_ = _train(1, fc1=15)
    got, _, prog, _ = _train(8, 1, 2, fc1=15)
    np.testing.assert_allclose(got, base, rtol=2e-5, atol=0)
    tpp = prog._tp_plan
    assert tpp is not None and "tp.emb" in tpp.params
    declined = [e for e in getattr(prog, "_sharded_update_fallback", [])
                if e.get("kind") == "tp_declined"]
    assert declined, "decline must be recorded on the trail"
    assert any("divisible" in e.get("reason", "") for e in declined)
    assert all(e["var"] not in tpp.params for e in declined
               if e.get("var"))


# ---------------------------------------------------------------------------
# census: per-chip param bytes ∝ 1/mp, grad sync confined to data axes
# ---------------------------------------------------------------------------

def test_census_mp_lane_and_param_bytes():
    _, exe, prog, loss = _train(8, 1, 2, zero1=True, bucket_mb=0.001)
    ids_np, y = _batch()
    col = exe.collective_report(prog, feed={"ids": ids_np, "label": y},
                                fetch_list=[loss])
    assert col["mp_size"] == 2 and col["ici_size"] == 4
    assert col["mp_bytes_total"] == \
        col["lanes"]["mp"]["wire_bytes"] > 0
    # TP collectives (forward gathers + backward psums) ride the mp
    # lane; grad sync stays on the data lanes
    kinds = {c["kind"] for c in col["lanes"]["mp"]["per_collective"]}
    assert kinds & {"all_gather", "all_reduce"}
    assert all(c["participants"] == 2
               for c in col["lanes"]["mp"]["per_collective"])
    # per-chip param storage halves for every sharded var
    tpp = prog._tp_plan
    for n, p in tpp.params.items():
        assert int(np.prod(p.local_shape)) * 2 == \
            int(np.prod(p.logical_shape)), (n, p)
    # the lowered module passes the model-axis replica_groups grammar
    got = exe._cached_lowerable(prog, {"ids": ids_np, "label": y},
                                [loss], None)
    hlo = got[1].as_text()
    assert analysis.check_hierarchical_groups(
        hlo, 4, ndev=8, mp_size=2) == []


def test_bench_model_parallel_block():
    from paddle_tpu.observability import publish

    _, exe, prog, loss = _train(8, 1, 2)
    ids_np, y = _batch()
    feed = {"ids": ids_np, "label": y}
    block = publish.model_parallel_block(exe, prog, feed, [loss])
    assert block is not None and block["mp_degree"] == 2
    assert block["model_axis"] == "model"
    assert "tp.emb" in block["sharded_params"]
    assert block["sharded_params"]["tp.emb"]["tp_dim"] == 0
    assert block["local_param_elems"] * 2 == \
        block["logical_param_elems"]
    assert block.get("mp_bytes_total", 0) > 0
    # registry-assembled: the bench harness picks the block up
    blocks = publish.bench_blocks(exe, prog, feed, [loss])
    assert "model_parallel" in blocks and \
        blocks["model_parallel"]["mp_degree"] == 2
    # and at mp=1 the block is absent, not zero-filled
    _, exe1, prog1, loss1 = _train(4)
    assert publish.model_parallel_block(
        exe1, prog1, feed, [loss1]) is None


# ---------------------------------------------------------------------------
# elastic: checkpoint restores into a DIFFERENT world, TP re-planned
# ---------------------------------------------------------------------------

def test_elastic_restore_replans_tp_layout(tmp_path):
    """Checkpoints save model-sharded state at LOGICAL shapes, so an
    N=8 (mp=2) run restores into an N'=4 (mp=2) world: the planner
    re-plans the TP layout for the new mesh and the sharded
    continuation is bit-identical to the replicated continuation
    restored from the same checkpoint."""
    root = str(tmp_path / "tp_ckpt")
    _, exe8, prog8, _ = _train(8, 1, 2, zero1=True, bucket_mb=0.001,
                               steps=2)
    assert prog8._tp_plan is not None
    ckpt.save_checkpoint(exe8, root,
                         ckpt.TrainStatus(epoch_no=0, step_no=1),
                         main_program=prog8)

    def _continue(ndev, mp, zero1):
        losses, exe, prog, loss = _train(ndev, 1, mp, zero1=zero1,
                                         bucket_mb=0.001, steps=0)
        scope = Scope()
        exe.run(framework.default_startup_program(), scope=scope)
        status = ckpt.load_checkpoint(exe, root, main_program=prog,
                                      scope=scope)
        assert status is not None
        ids_np, y = _batch()
        out = [float(np.mean(np.asarray(exe.run(
            prog, feed={"ids": ids_np, "label": y}, fetch_list=[loss],
            scope=scope)[0]))) for _ in range(3)]
        return out, prog

    sharded, p_s = _continue(4, 2, True)
    replicated, _ = _continue(4, 2, False)
    assert sharded == replicated, (sharded, replicated)
    tpp = p_s._tp_plan
    assert tpp is not None and tpp.mp == 2 and "tp.emb" in tpp.params
    plan = p_s._shard_plan
    assert plan is not None and plan.ndev == 2
    assert all(i.padded % plan.ndev == 0
               for i in plan.sharded_state.values())


# ---------------------------------------------------------------------------
# flag / env / launch wiring
# ---------------------------------------------------------------------------

def test_flag_builds_tp_mesh_through_compile(monkeypatch):
    """FLAGS_tpu_model_parallel=2 alone (no hand-built mesh) factors
    the 8-device world into the (1, 4, 2) mesh — the flag/env contract
    the compile path reads through create_hybrid_mesh."""
    monkeypatch.delenv("PADDLE_MP_DEGREE", raising=False)
    set_flags({"FLAGS_tpu_model_parallel": 2,
               "FLAGS_tpu_dcn_replicas": 0})
    mesh = penv.create_hybrid_mesh()
    assert mesh is not None and mesh.axis_names == \
        ("dcn", "ici", "model")
    assert dict(mesh.shape) == {"dcn": 1, "ici": 4, "model": 2}
    hier = penv.mesh_hierarchy(mesh)
    assert hier.mp_size == 2 and hier.model_axis == "model"
    assert hier[0] == "dcn" and hier[1] == "ici"
    # 2 pods x mp=2: replica axis halves, model group survives
    set_flags({"FLAGS_tpu_dcn_replicas": 2})
    mesh2 = penv.create_hybrid_mesh()
    assert dict(mesh2.shape) == {"dcn": 2, "ici": 2, "model": 2}
    # a world the factorization cannot tile falls back to flat (None)
    assert penv.create_hybrid_mesh(nranks=6, dcn=1, mp=4) is None


def test_model_parallel_degree_flag_and_env(monkeypatch):
    set_flags({"FLAGS_tpu_model_parallel": 0})
    monkeypatch.setenv("PADDLE_MP_DEGREE", "4")
    assert penv.model_parallel_degree() == 4
    set_flags({"FLAGS_tpu_model_parallel": 2})  # flag wins over env
    assert penv.model_parallel_degree() == 2
    monkeypatch.delenv("PADDLE_MP_DEGREE")
    set_flags({"FLAGS_tpu_model_parallel": 0})
    assert penv.model_parallel_degree() == 1


def test_launch_worker_env_exports_mp_degree():
    from paddle_tpu.distributed import launch

    eps = ["h:1", "h:2", "h:3", "h:4"]
    env = launch._worker_env(eps, 0, 0, base_env={}, mp_degree=2)
    assert env["PADDLE_MP_DEGREE"] == "2"
    env1 = launch._worker_env(eps, 0, 0,
                              base_env={"PADDLE_MP_DEGREE": "8"})
    assert "PADDLE_MP_DEGREE" not in env1


def test_elastic_mesh_variants_keep_tp_group_indivisible():
    """An elastic shrink of a (dcn, ici, model) base keeps BOTH the
    pod count and the model degree fixed: N' must divide by dcn*mp,
    else that N' falls back to the flat world."""
    import jax
    from jax.sharding import Mesh

    devs = np.array(jax.devices()[:8]).reshape(1, 4, 2)
    base = Mesh(devs, ("dcn", "ici", "model"))
    variants = dict(penv.elastic_mesh_variants(base, min_ranks=4))
    assert set(variants) == {7, 6, 5, 4}
    assert variants[6].axis_names == ("dcn", "ici", "model")
    assert dict(variants[6].shape) == {"dcn": 1, "ici": 3, "model": 2}
    assert variants[4].axis_names == ("dcn", "ici", "model")
    assert dict(variants[4].shape) == {"dcn": 1, "ici": 2, "model": 2}
    # odd worlds cannot hold a 2-way TP group: flat fallback
    assert variants[7].axis_names == ("dp",)
    assert variants[5].axis_names == ("dp",)
