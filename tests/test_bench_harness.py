"""Unit tests for bench.py's resilience logic (jax-free: monkeypatched
children) — the round-2 failure mode was a tunnel outage erasing the
round's perf evidence (VERDICT round 2, missing #1); round 4 added the
probe-gated warm/measure staging after a live 03:17Z window was burned
by three long attempts on a by-then-dead tunnel."""
import json
import os
import sys

import pytest

pytestmark = pytest.mark.dist

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import bench  # noqa: E402

# Derived from the real schedule, not hardcoded: round 3 shipped with
# these tests pinned to a stale attempt count, so the stale path went
# untested (VERDICT r3 weak #1a).
_WARM_KEYS = {bench._stage_key(s) for s in bench._STAGES
              if s["kind"] == "warm"}
# TPU calls when every stage fails: each warm runs (and fails, skipping
# its key's measure); measures without a warm sibling run cold.
N_TPU_ALL_FAIL = sum(
    1 for s in bench._STAGES
    if s["kind"] == "warm" or bench._stage_key(s) not in _WARM_KEYS)


@pytest.fixture(autouse=True)
def _no_backoff(monkeypatch):
    # inter-stage backoffs are real-tunnel behavior; with monkeypatched
    # children they are pure sleep per test
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)


@pytest.fixture(autouse=True)
def _tunnel_up(monkeypatch):
    # default: the liveness probe passes; individual tests override
    monkeypatch.setattr(bench, "_tunnel_alive", lambda errors: True)


@pytest.fixture(autouse=True)
def _warm_isolation(tmp_path, monkeypatch):
    # warm markers persist across invocations by design — isolate them
    # per test, with a non-empty fake compile cache so markers validate
    monkeypatch.setattr(bench, "_WARM_MARKER",
                        str(tmp_path / "warm.json"))
    cache = tmp_path / "jax_cache"
    cache.mkdir()
    (cache / "executable").write_text("x")
    monkeypatch.setattr(bench, "_COMPILE_CACHE", str(cache))
    monkeypatch.delenv("BENCH_ASSUME_LIVE", raising=False)


@pytest.fixture
def lastgood(tmp_path, monkeypatch):
    path = str(tmp_path / "last_good.json")
    monkeypatch.setattr(bench, "_LAST_GOOD", path)
    return path


def _fake_attempts(results):
    """results: list of dict-or-None per _run_attempt call, in order."""
    calls = []

    def fake(platform, budget, batch, steps, warmup, idx, errors,
             model="bert"):
        calls.append((platform, batch, steps, model))
        r = results[len(calls) - 1]
        if r is None:
            errors.append("%s attempt %d: timeout" % (platform, idx))
        return None if r is None else dict(r)

    return fake, calls


def _tpu_result(v=83000.0):
    return {"metric": "bert_base_pretrain_throughput", "value": v,
            "unit": "tokens/sec/chip", "vs_baseline": round(v / 25000, 3),
            "platform": "tpu", "mfu_pct": 34.0}


def _warm_result(batch):
    return {"warm": True, "platform": "tpu", "batch": batch,
            "compile_time_s": 88.0}


def _resnet_result(v=1500.0):
    return {"metric": "resnet50_train_throughput", "value": v,
            "unit": "images/sec/chip", "vs_baseline": round(v / 900, 3),
            "platform": "tpu", "mfu_pct": 9.4}


def _longctx_result(v=50000.0):
    return {"metric": "bert_longctx4096_pretrain_throughput", "value": v,
            "unit": "tokens/sec/chip", "platform": "tpu",
            "seq_len": 4096, "mfu_pct": 33.0}


def _cpu_stub(v=44.0):
    return {"metric": "bert_base_pretrain_throughput", "value": v,
            "unit": "tokens/sec/chip", "vs_baseline": round(v / 25000, 3),
            "platform": "cpu"}


def _results_only_model(model, result_fn):
    """Per-stage fake results where only `model`'s stages succeed:
    other models' warms fail (so their measures are skipped) and their
    warm-less measures run cold and fail — the failed-warm-skips-measure
    call-ordering contract in bench.main()."""
    results = []
    for s in bench._STAGES:
        if s["model"] == model:
            results.append(_warm_result(s["batch"])
                           if s["kind"] == "warm" else result_fn())
        elif s["kind"] == "warm" or bench._stage_key(s) not in _WARM_KEYS:
            results.append(None)
    return results


def test_warm_then_measure_writes_last_good(lastgood, monkeypatch,
                                            capsys):
    first = bench._STAGES[0]
    fake, calls = _fake_attempts([_warm_result(first["batch"]),
                                  _tpu_result(),
                                  _warm_result(128),
                                  _resnet_result(),
                                  _warm_result(bench.LONGCTX_BATCH),
                                  _longctx_result()])
    monkeypatch.setattr(bench, "_run_attempt", fake)
    assert bench.main() == 0
    out = json.loads(capsys.readouterr().out.strip())
    assert out["platform"] == "tpu" and "stale" not in out
    assert "warm" not in out  # the warm tag must never be the headline
    # ALL configs land: BERT headline + ResNet + longctx sub-objects
    assert out["resnet50"]["value"] == 1500.0
    assert out["longctx"]["seq_len"] == 4096
    saved = json.load(open(lastgood))
    assert saved["result"]["value"] == 83000.0 and saved["ts"] > 0
    assert saved["result"]["resnet50"]["value"] == 1500.0
    assert saved["result"]["longctx"]["value"] == 50000.0
    # warm ran steps=0, measure ran real steps
    assert calls[0][2] == 0 and calls[1][2] > 0
    assert calls[2][3] == "resnet" and calls[3][3] == "resnet"
    assert calls[4][3] == "longctx" and calls[5][3] == "longctx"


def test_fresh_resnet_rides_stale_bert(lastgood, monkeypatch, capsys):
    """BERT stages fail but the ResNet pair lands: the stale-BERT
    emission must carry the fresh on-chip ResNet number (config 2 has
    never been measured; a window that lands it must not be wasted)."""
    with open(lastgood, "w") as f:
        json.dump({"ts": 1000.0, "iso": "2026-07-30T07:50:00Z",
                   "result": _tpu_result()}, f)
    results = _results_only_model("resnet", _resnet_result)
    results.append(None)  # cpu fallback
    fake, calls = _fake_attempts(results)
    monkeypatch.setattr(bench, "_run_attempt", fake)
    assert bench.main() == 0
    out = json.loads(capsys.readouterr().out.strip())
    assert out["stale"] is True and out["value"] == 83000.0
    assert out["resnet50"]["value"] == 1500.0
    # and last-good now carries the resnet number for future stales
    saved = json.load(open(lastgood))
    assert saved["result"]["resnet50"]["value"] == 1500.0


def test_failed_warm_skips_its_measure_stage(lastgood, monkeypatch,
                                             capsys):
    """A warm that can't land its compile must not let the measure
    stage recompile cold in a short window — the batch is skipped."""
    cpu = {"metric": "bert_base_pretrain_throughput", "value": 44.0,
           "unit": "tokens/sec/chip", "vs_baseline": 0.002,
           "platform": "cpu"}
    fake, calls = _fake_attempts([None] * N_TPU_ALL_FAIL + [cpu])
    monkeypatch.setattr(bench, "_run_attempt", fake)
    assert bench.main() == 0
    tpu_calls = [c for c in calls if c[0] == "tpu"]
    assert len(tpu_calls) == N_TPU_ALL_FAIL
    measured_keys = {bench._stage_key(c[3], c[1])
                     for c in tpu_calls if c[2] > 0}
    assert not (measured_keys & _WARM_KEYS), tpu_calls


def test_dead_tunnel_skips_all_stages_and_emits_stale(lastgood,
                                                      monkeypatch,
                                                      capsys):
    with open(lastgood, "w") as f:
        json.dump({"ts": 1000.0, "iso": "2026-07-30T07:50:00Z",
                   "result": _tpu_result()}, f)
    cpu = {"metric": "bert_base_pretrain_throughput", "value": 44.0,
           "unit": "tokens/sec/chip", "vs_baseline": 0.002,
           "platform": "cpu", "loss": 9.4, "steps_per_sec": 0.1}

    def dead(errors):
        errors.append("probe: tunnel dead (timeout 75s)")
        return False

    monkeypatch.setattr(bench, "_tunnel_alive", dead)
    fake, calls = _fake_attempts([cpu])
    monkeypatch.setattr(bench, "_run_attempt", fake)
    assert bench.main() == 0
    out = json.loads(capsys.readouterr().out.strip())
    # headline is the last-good TPU number, stale-marked, with the CPU
    # probe attached and the outage recorded
    assert out["platform"] == "tpu" and out["value"] == 83000.0
    assert out["stale"] is True
    assert out["stale_since"] == "2026-07-30T07:50:00Z"
    assert out["stale_age_h"] > 0
    assert out["cpu_fallback"]["value"] == 44.0
    assert "tunnel dead" in out["error"]
    # zero TPU stage budgets burned: only the CPU fallback ran
    assert [c[0] for c in calls] == ["cpu"]


def test_total_outage_no_last_good_falls_back_to_cpu(lastgood,
                                                     monkeypatch, capsys):
    cpu = {"metric": "bert_base_pretrain_throughput", "value": 44.0,
           "unit": "tokens/sec/chip", "vs_baseline": 0.002,
           "platform": "cpu"}
    fake, _ = _fake_attempts([None] * N_TPU_ALL_FAIL + [cpu])
    monkeypatch.setattr(bench, "_run_attempt", fake)
    assert bench.main() == 0
    out = json.loads(capsys.readouterr().out.strip())
    assert out["platform"] == "cpu" and "stale" not in out


def test_everything_fails_still_emits_json(lastgood, monkeypatch, capsys):
    fake, _ = _fake_attempts([None] * (N_TPU_ALL_FAIL + 1))
    monkeypatch.setattr(bench, "_run_attempt", fake)
    assert bench.main() == 0
    out = json.loads(capsys.readouterr().out.strip())
    assert out["value"] == 0.0 and "error" in out


def test_timeout_salvages_tagged_result(monkeypatch):
    # child printed the BERT result, then the optional ResNet pass blew
    # the wall budget: the parent must keep the tagged line (ADVICE r3)
    import subprocess

    bert = _tpu_result()
    out = ("startup noise\n" + bench._RESULT_TAG + json.dumps(bert)
           + "\nresnet compile...\n")

    def fake_run(*a, **kw):
        raise subprocess.TimeoutExpired(cmd="bench", timeout=560,
                                        output=out)

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    errors = []
    got = bench._run_attempt("tpu", 560, 512, 10, 3, 0, errors)
    assert got is not None and got["value"] == bert["value"]
    assert any("salvaged" in e for e in errors)


def test_timeout_without_tagged_line_returns_none(monkeypatch):
    import subprocess

    def fake_run(*a, **kw):
        raise subprocess.TimeoutExpired(cmd="bench", timeout=560,
                                        output=b"compiling...\n")

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    errors = []
    assert bench._run_attempt("tpu", 560, 512, 10, 3, 0, errors) is None
    assert any("timeout" in e for e in errors)


def test_child_env_enables_compile_cache():
    env = bench._child_env("cpu")
    assert env["JAX_COMPILATION_CACHE_DIR"] == bench._COMPILE_CACHE
    assert env["JAX_PLATFORMS"] == "cpu"
    assert not any(k.startswith(("TPU_", "AXON_", "PALLAS_AXON"))
                   for k in env)


def test_warm_marker_persists_across_invocations(lastgood, monkeypatch,
                                                 capsys):
    """Run 1 lands the warm compile then the window dies; run 2 (a new
    bench invocation in a later short window) must skip the warm stage
    and go straight to measuring — the round-4 failure mode was
    re-paying the warm child in every window."""
    first = bench._STAGES[0]
    # run 1: warm ok, then every remaining stage fails
    fake, calls1 = _fake_attempts(
        [_warm_result(first["batch"])] + [None] * (len(bench._STAGES))
        + [None])  # generous None tail incl. cpu fallback
    monkeypatch.setattr(bench, "_run_attempt", fake)
    assert bench.main() == 0
    capsys.readouterr()
    assert bench._load_warm_batches() == {bench._stage_key(first)}

    # run 2: measure succeeds immediately; the warm stage must NOT run
    fake2, calls2 = _fake_attempts([_tpu_result()] +
                                   [None] * len(bench._STAGES))
    monkeypatch.setattr(bench, "_run_attempt", fake2)
    assert bench.main() == 0
    out = json.loads(capsys.readouterr().out.strip())
    assert out["platform"] == "tpu" and "stale" not in out
    assert calls2[0][2] > 0, "first call of run 2 must be a measure"


def test_failed_measure_on_warm_batch_drops_marker(lastgood, monkeypatch,
                                                   capsys):
    """A lying warm marker (cache evicted / lowering changed outside the
    fingerprint) must be dropped after a failed measure so the next
    window re-warms instead of repeating a doomed 180s cold measure."""
    first = bench._STAGES[0]
    bench._mark_warm(first["model"], first["batch"])
    fake, calls = _fake_attempts([None] * (len(bench._STAGES) + 1))
    monkeypatch.setattr(bench, "_run_attempt", fake)
    assert bench.main() == 0
    capsys.readouterr()
    assert bench._stage_key(first) not in bench._load_warm_batches()
    # and the warm stage itself was skipped this run (marker trusted
    # until the measure disproved it)
    assert calls[0][2] > 0


def test_warm_marker_invalidated_by_fingerprint(monkeypatch, tmp_path):
    bench._mark_warm("bert", 256)
    assert "bert:256" in bench._load_warm_batches()
    monkeypatch.setattr(bench, "_bench_fingerprint", lambda: "changed")
    assert bench._load_warm_batches() == set()


def test_warm_marker_invalidated_by_empty_cache(monkeypatch, tmp_path):
    bench._mark_warm("bert", 256)
    empty = tmp_path / "empty_cache"
    empty.mkdir()
    monkeypatch.setattr(bench, "_COMPILE_CACHE", str(empty))
    assert bench._load_warm_batches() == set()


def test_probe_skipped_after_successful_stage(lastgood, monkeypatch,
                                              capsys):
    """A TPU child that just succeeded proves liveness — the next stage
    must not burn window time on another probe; a failed stage requires
    a fresh probe."""
    probes = []

    def probe(errors):
        probes.append(True)
        return True

    monkeypatch.setattr(bench, "_tunnel_alive", probe)
    first = bench._STAGES[0]
    fake, calls = _fake_attempts([_warm_result(first["batch"]),
                                  _tpu_result(),
                                  _warm_result(128),
                                  _resnet_result(),
                                  _warm_result(bench.LONGCTX_BATCH),
                                  _longctx_result()])
    monkeypatch.setattr(bench, "_run_attempt", fake)
    assert bench.main() == 0
    capsys.readouterr()
    # exactly one probe: before stage 0; every later stage rides the
    # previous success's liveness proof
    assert len(probes) == 1


def test_assume_live_env_skips_first_probe(lastgood, monkeypatch,
                                           capsys):
    probes = []

    def probe(errors):
        probes.append(True)
        return True

    monkeypatch.setattr(bench, "_tunnel_alive", probe)
    monkeypatch.setenv("BENCH_ASSUME_LIVE", "1")
    first = bench._STAGES[0]
    fake, _ = _fake_attempts([_warm_result(first["batch"]),
                              _tpu_result(),
                              _warm_result(128),
                              _resnet_result(),
                              _warm_result(bench.LONGCTX_BATCH),
                              _longctx_result()])
    monkeypatch.setattr(bench, "_run_attempt", fake)
    assert bench.main() == 0
    capsys.readouterr()
    assert probes == []  # the caller vouched; successes carry it on


def test_stage_schedule_shape():
    """Every warm stage precedes a measure stage of the same batch, and
    warm stages request zero steps."""
    seen_measure = set()
    for s in bench._STAGES:
        if s["kind"] == "measure":
            seen_measure.add(bench._stage_key(s))
        else:
            assert s["steps"] == 0
            assert bench._stage_key(s) not in seen_measure, \
                "warm after its measure is useless"
    assert any(s["kind"] == "measure" for s in bench._STAGES)
    assert any(s["model"] == "resnet" for s in bench._STAGES), \
        "BASELINE config 2 must be scheduled"


@pytest.mark.slow
def test_bench_resnet_path_runs_on_cpu():
    """The ResNet bench path has never executed on chip (VERDICT r3
    missing #2): smoke-run it end-to-end at toy scale so a silent
    breakage can't waste a live tunnel window."""
    res = bench._bench_resnet(batch=2, steps=1, warmup=0,
                              platform="cpu", depth=18, img=32,
                              class_dim=10)
    assert res["metric"] == "resnet50_train_throughput"
    assert res["value"] > 0 and "mfu_pct" not in res
    assert res["batch"] == 2
    import numpy as np

    assert np.isfinite(res["loss"])


def test_fresh_longctx_rides_stale_bert(lastgood, monkeypatch, capsys):
    """BERT (and ResNet) stages fail but the longctx pair lands: the
    stale-BERT emission must carry the fresh on-chip longctx number and
    persist it into last-good (same contract as the ResNet leg)."""
    with open(lastgood, "w") as f:
        json.dump({"ts": 1000.0, "iso": "2026-07-30T07:50:00Z",
                   "result": _tpu_result()}, f)
    results = _results_only_model("longctx", _longctx_result)
    results.append(None)  # cpu fallback
    fake, calls = _fake_attempts(results)
    monkeypatch.setattr(bench, "_run_attempt", fake)
    assert bench.main() == 0
    out = json.loads(capsys.readouterr().out.strip())
    assert out["stale"] is True and out["value"] == 83000.0
    assert out["longctx"]["value"] == 50000.0
    saved = json.load(open(lastgood))
    assert saved["result"]["longctx"]["value"] == 50000.0


def test_fresh_longctx_rides_cpu_fallback_without_last_good(
        lastgood, monkeypatch, capsys):
    """No last-good exists and only longctx lands: the CPU-fallback
    emission must still carry the scarce on-chip longctx number."""
    results = _results_only_model("longctx", _longctx_result)
    results.append(_cpu_stub())
    fake, _ = _fake_attempts(results)
    monkeypatch.setattr(bench, "_run_attempt", fake)
    assert bench.main() == 0
    out = json.loads(capsys.readouterr().out.strip())
    assert out["platform"] == "cpu"
    assert out["longctx"]["value"] == 50000.0


def test_bench_lock_serializes_and_proceeds_on_timeout(tmp_path,
                                                       monkeypatch):
    """The driver's end-of-round bench and the capture loop's
    opportunistic bench share one flock; a crashed holder must never
    wedge the round artifact — the waiter proceeds after max_wait_s."""
    import time as _time

    monkeypatch.setattr(bench, "_LOCK_PATH", str(tmp_path / "lock"))
    holder = bench._acquire_bench_lock(max_wait_s=1.0)
    t0 = _time.perf_counter()
    waiter = bench._acquire_bench_lock(max_wait_s=0.3)
    elapsed = _time.perf_counter() - t0
    # lower bound: it actually waited; upper bound: the prompt-timeout
    # contract (depends on _no_backoff no-op'ing bench's 10s sleep)
    assert 0.3 <= elapsed < 2.0
    assert waiter is not None
    holder.close()
    waiter.close()
    # free lock: immediate acquire
    t0 = _time.perf_counter()
    again = bench._acquire_bench_lock(max_wait_s=5.0)
    assert _time.perf_counter() - t0 < 1.0
    again.close()
