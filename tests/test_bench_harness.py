"""Unit tests for bench.py's resilience logic (jax-free: monkeypatched
children) — the round-2 failure mode was a tunnel outage erasing the
round's perf evidence (VERDICT round 2, missing #1)."""
import json
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import bench  # noqa: E402

# number of TPU rows in the attempt ladder — derived, not hardcoded:
# round 3 shipped with these tests pinned to 2 while bench gained a
# third attempt, so the stale path went untested (VERDICT r3 weak #1a)
N_TPU = len(bench._ATTEMPTS)


@pytest.fixture(autouse=True)
def _no_backoff(monkeypatch):
    # main()'s 15s/30s inter-attempt backoffs are real-tunnel behavior;
    # with monkeypatched children they were 45s of pure sleep per test
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)


@pytest.fixture
def lastgood(tmp_path, monkeypatch):
    path = str(tmp_path / "last_good.json")
    monkeypatch.setattr(bench, "_LAST_GOOD", path)
    return path


def _fake_attempts(results):
    """results: list of dict-or-None per (platform) attempt call."""
    calls = []

    def fake(platform, budget, batch, steps, warmup, idx, errors):
        calls.append(platform)
        r = results[len(calls) - 1]
        if r is None:
            errors.append("%s attempt %d: timeout" % (platform, idx))
        return None if r is None else dict(r)

    return fake, calls


def _tpu_result(v=83000.0):
    return {"metric": "bert_base_pretrain_throughput", "value": v,
            "unit": "tokens/sec/chip", "vs_baseline": round(v / 25000, 3),
            "platform": "tpu", "mfu_pct": 34.0}


def test_tpu_success_writes_last_good(lastgood, monkeypatch, capsys):
    fake, calls = _fake_attempts([_tpu_result()])
    monkeypatch.setattr(bench, "_run_attempt", fake)
    assert bench.main() == 0
    out = json.loads(capsys.readouterr().out.strip())
    assert out["platform"] == "tpu" and "stale" not in out
    saved = json.load(open(lastgood))
    assert saved["result"]["value"] == 83000.0 and saved["ts"] > 0


def test_tunnel_outage_emits_stale_last_good(lastgood, monkeypatch,
                                             capsys):
    with open(lastgood, "w") as f:
        json.dump({"ts": 1000.0, "iso": "2026-07-30T07:50:00Z",
                   "result": _tpu_result()}, f)
    cpu = {"metric": "bert_base_pretrain_throughput", "value": 44.0,
           "unit": "tokens/sec/chip", "vs_baseline": 0.002,
           "platform": "cpu", "loss": 9.4, "steps_per_sec": 0.1}
    fake, calls = _fake_attempts([None] * N_TPU + [cpu])
    monkeypatch.setattr(bench, "_run_attempt", fake)
    assert bench.main() == 0
    out = json.loads(capsys.readouterr().out.strip())
    # headline is the last-good TPU number, stale-marked, with the CPU
    # probe attached and the outage recorded
    assert out["platform"] == "tpu" and out["value"] == 83000.0
    assert out["stale"] is True
    assert out["stale_since"] == "2026-07-30T07:50:00Z"
    assert out["stale_age_h"] > 0
    assert out["cpu_fallback"]["value"] == 44.0
    assert "timeout" in out["error"]
    assert calls == ["tpu"] * N_TPU + ["cpu"]


def test_total_outage_no_last_good_falls_back_to_cpu(lastgood,
                                                     monkeypatch, capsys):
    cpu = {"metric": "bert_base_pretrain_throughput", "value": 44.0,
           "unit": "tokens/sec/chip", "vs_baseline": 0.002,
           "platform": "cpu"}
    fake, _ = _fake_attempts([None] * N_TPU + [cpu])
    monkeypatch.setattr(bench, "_run_attempt", fake)
    assert bench.main() == 0
    out = json.loads(capsys.readouterr().out.strip())
    assert out["platform"] == "cpu" and "stale" not in out


def test_everything_fails_still_emits_json(lastgood, monkeypatch, capsys):
    fake, _ = _fake_attempts([None] * (N_TPU + 1))
    monkeypatch.setattr(bench, "_run_attempt", fake)
    assert bench.main() == 0
    out = json.loads(capsys.readouterr().out.strip())
    assert out["value"] == 0.0 and "error" in out


def test_timeout_salvages_tagged_result(monkeypatch):
    # child printed the BERT result, then the optional ResNet pass blew
    # the wall budget: the parent must keep the tagged line (ADVICE r3)
    import subprocess

    bert = _tpu_result()
    out = ("startup noise\n" + bench._RESULT_TAG + json.dumps(bert)
           + "\nresnet compile...\n")

    def fake_run(*a, **kw):
        raise subprocess.TimeoutExpired(cmd="bench", timeout=560,
                                        output=out)

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    errors = []
    got = bench._run_attempt("tpu", 560, 512, 10, 3, 0, errors)
    assert got is not None and got["value"] == bert["value"]
    assert any("salvaged" in e for e in errors)


def test_timeout_without_tagged_line_returns_none(monkeypatch):
    import subprocess

    def fake_run(*a, **kw):
        raise subprocess.TimeoutExpired(cmd="bench", timeout=560,
                                        output=b"compiling...\n")

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    errors = []
    assert bench._run_attempt("tpu", 560, 512, 10, 3, 0, errors) is None
    assert any("timeout" in e for e in errors)


def test_child_env_enables_compile_cache():
    env = bench._child_env("cpu")
    assert env["JAX_COMPILATION_CACHE_DIR"] == bench._COMPILE_CACHE
    assert env["JAX_PLATFORMS"] == "cpu"
    assert not any(k.startswith(("TPU_", "AXON_", "PALLAS_AXON"))
                   for k in env)


def test_bench_resnet_path_runs_on_cpu():
    """The ResNet bench path has never executed on chip (VERDICT r3
    missing #2): smoke-run it end-to-end at toy scale so a silent
    breakage can't waste a live tunnel window."""
    res = bench._bench_resnet(batch=2, steps=1, warmup=0,
                              platform="cpu", depth=18, img=32,
                              class_dim=10)
    assert res["metric"] == "resnet50_train_throughput"
    assert res["value"] > 0 and "mfu_pct" not in res
    assert res["batch"] == 2
    import numpy as np

    assert np.isfinite(res["loss"])
