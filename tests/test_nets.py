"""fluid.nets composite builders (reference: python/paddle/fluid/nets.py)."""
import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework


def test_simple_img_conv_pool_and_glu(rng):
    from paddle_tpu.core.scope import Scope

    main, startup = framework.Program(), framework.Program()
    main.random_seed = startup.random_seed = 2
    with framework.program_guard(main, startup):
        with framework.unique_name_guard():
            img = fluid.layers.data(name="img", shape=[1, 8, 8],
                                    dtype="float32",)
            conv_pool = fluid.nets.simple_img_conv_pool(
                input=img, num_filters=4, filter_size=3, pool_size=2,
                pool_stride=2, act="relu")
            g = fluid.nets.glu(fluid.layers.reshape(conv_pool, [-1, 36]))
    scope = Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    out = exe.run(main, feed={"img": rng.rand(2, 1, 8, 8).astype(
        "float32")}, fetch_list=[conv_pool, g], scope=scope)
    assert np.asarray(out[0]).shape == (2, 4, 3, 3)
    assert np.asarray(out[1]).shape == (2, 18)
    assert np.isfinite(np.asarray(out[1])).all()


def test_nets_attention_and_seq_conv_pool(rng):
    from paddle_tpu.core.scope import Scope

    main, startup = framework.Program(), framework.Program()
    main.random_seed = startup.random_seed = 2
    with framework.program_guard(main, startup):
        with framework.unique_name_guard():
            x = fluid.layers.data(name="x", shape=[6, 16],
                                  dtype="float32")
            att = fluid.nets.scaled_dot_product_attention(
                x, x, x, num_heads=4)
            scp = fluid.nets.sequence_conv_pool(
                input=x, num_filters=8, filter_size=3, act="sigmoid",
                pool_type="max")
    scope = Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    out = exe.run(main, feed={"x": rng.rand(2, 6, 16).astype("float32")},
                  fetch_list=[att, scp], scope=scope)
    assert np.asarray(out[0]).shape == (2, 6, 16)
    assert np.asarray(out[1]).shape == (2, 8)


def test_jit_static_namespaces_and_install_check(capsys):
    import paddle_tpu.jit as jit
    import paddle_tpu.static as static

    assert callable(jit.to_static) and callable(jit.declarative)
    assert static.Program is not None and callable(static.data)

    from paddle_tpu.fluid import install_check

    install_check.run_check()
    out = capsys.readouterr().out
    assert "install_check passed" in out


def test_model_stats_summary_and_memory(rng):
    from paddle_tpu.fluid.contrib import model_stats

    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup):
        with framework.unique_name_guard():
            x = fluid.layers.data(name="x", shape=[16], dtype="float32")
            h = fluid.layers.fc(input=x, size=32)
            y = fluid.layers.fc(input=h, size=8)
    st = model_stats.summary(main, batch_size=4)
    # params: 16*32 + 32 + 32*8 + 8
    assert st["total_params"] == 16 * 32 + 32 + 32 * 8 + 8
    assert st["total_flops"] > 0
    mem = model_stats.memory_usage(main, batch_size=4)
    assert mem["persistable_bytes"] >= st["total_params"] * 4
    assert mem["total_bytes"] > mem["persistable_bytes"]
