"""slim quantization: QAT pass (fake quant ops w/ STE grads) and PTQ
calibration (reference: contrib/slim/quantization/)."""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework


def _mlp():
    x = fluid.layers.data(name="x", shape=[16], dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    h = fluid.layers.fc(input=x, size=32, act="relu")
    logits = fluid.layers.fc(input=h, size=4)
    loss = fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(logits, label))
    return loss


def _data():
    r = np.random.RandomState(8)
    return (r.rand(32, 16).astype("float32"),
            r.randint(0, 4, (32, 1)).astype("int64"))


def test_fake_quant_ops_golden():
    import jax.numpy as jnp
    import paddle_tpu.ops as ops_lib

    x = np.array([[-1.0, 0.5, 0.25, 1.0]], "float32")
    out = ops_lib.run_op("fake_quantize_abs_max",
                         {"X": [jnp.asarray(x)]}, {"bit_length": 8})
    got = np.asarray(out["Out"][0])
    scale = float(np.asarray(out["OutScale"][0])[0])
    assert scale == 1.0
    np.testing.assert_allclose(
        got, np.round(x * 127) / 127, atol=1e-6)

    # STE: gradient of sum(qdq(x)) wrt x is all-ones
    import jax

    g = jax.grad(lambda v: jnp.sum(ops_lib.run_op(
        "fake_quantize_abs_max", {"X": [v]},
        {"bit_length": 8})["Out"][0]))(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(g), np.ones_like(x))


def test_qat_trains():
    from paddle_tpu.fluid.contrib.slim.quantization import (
        QuantizationTransformPass)
    from paddle_tpu.core.scope import Scope

    main, startup = framework.Program(), framework.Program()
    main.random_seed = startup.random_seed = 4
    with framework.program_guard(main, startup):
        with framework.unique_name_guard():
            loss = _mlp()
            QuantizationTransformPass().apply(main, startup)
            fluid.optimizer.SGDOptimizer(0.5).minimize(loss)
    qops = [op.type for op in main.global_block().ops
            if op.type.startswith("fake_quantize")]
    assert len(qops) >= 4, qops  # 2 weights + 2 activations

    scope = Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    x, y = _data()
    losses = []
    for _ in range(15):
        out = exe.run(main, feed={"x": x, "label": y},
                      fetch_list=[loss], scope=scope)
        losses.append(float(np.asarray(out[0]).reshape(-1)[0]))
    assert losses[-1] < losses[0], losses


def test_ptq_calibration():
    from paddle_tpu.fluid.contrib.slim.quantization import (
        PostTrainingQuantization)
    from paddle_tpu.core.scope import Scope

    main, startup = framework.Program(), framework.Program()
    main.random_seed = startup.random_seed = 4
    with framework.program_guard(main, startup):
        with framework.unique_name_guard():
            loss = _mlp()
    scope = Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    x, y = _data()

    def samples():
        for _ in range(3):
            yield {"x": x, "label": y}

    ptq = PostTrainingQuantization(
        exe, main, feed_list=["x", "label"], fetch_list=[loss],
        sample_generator=samples, batch_nums=3, scope=scope)
    qprog = ptq.quantize()
    assert ptq.scales, "no calibration scales collected"
    assert abs(list(ptq.scales.values())[0]
               - float(np.abs(x).max())) < 1e-5
    qops = [op for op in qprog.global_block().ops
            if op.type.startswith("fake_quantize")]
    assert qops
    # calibrated static scales are BOUND into the activation quant ops
    bound = [op.attrs.get("static_scale") for op in qops
             if op.type == "fake_quantize_abs_max"
             and op.input_names["X"][0] in ptq.scales]
    assert bound and all(b is not None for b in bound), qops
    # quantized program still runs (on different data: static scales)
    x2 = x * 0.5
    out = exe.run(qprog, feed={"x": x2, "label": y},
                  fetch_list=[loss], scope=scope)
    assert np.isfinite(float(np.asarray(out[0]).reshape(-1)[0]))
