"""slim quantization: QAT pass (fake quant ops w/ STE grads) and PTQ
calibration (reference: contrib/slim/quantization/)."""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework


def _mlp():
    x = fluid.layers.data(name="x", shape=[16], dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    h = fluid.layers.fc(input=x, size=32, act="relu")
    logits = fluid.layers.fc(input=h, size=4)
    loss = fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(logits, label))
    return loss


def _data():
    r = np.random.RandomState(8)
    return (r.rand(32, 16).astype("float32"),
            r.randint(0, 4, (32, 1)).astype("int64"))


def test_fake_quant_ops_golden():
    import jax.numpy as jnp
    import paddle_tpu.ops as ops_lib

    x = np.array([[-1.0, 0.5, 0.25, 1.0]], "float32")
    out = ops_lib.run_op("fake_quantize_abs_max",
                         {"X": [jnp.asarray(x)]}, {"bit_length": 8})
    got = np.asarray(out["Out"][0])
    scale = float(np.asarray(out["OutScale"][0])[0])
    assert scale == 1.0
    np.testing.assert_allclose(
        got, np.round(x * 127) / 127, atol=1e-6)

    # STE: gradient of sum(qdq(x)) wrt x is all-ones
    import jax

    g = jax.grad(lambda v: jnp.sum(ops_lib.run_op(
        "fake_quantize_abs_max", {"X": [v]},
        {"bit_length": 8})["Out"][0]))(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(g), np.ones_like(x))


def test_qat_trains():
    from paddle_tpu.fluid.contrib.slim.quantization import (
        QuantizationTransformPass)
    from paddle_tpu.core.scope import Scope

    main, startup = framework.Program(), framework.Program()
    main.random_seed = startup.random_seed = 4
    with framework.program_guard(main, startup):
        with framework.unique_name_guard():
            loss = _mlp()
            QuantizationTransformPass().apply(main, startup)
            fluid.optimizer.SGDOptimizer(0.5).minimize(loss)
    qops = [op.type for op in main.global_block().ops
            if op.type.startswith("fake_quantize")]
    assert len(qops) >= 4, qops  # 2 weights + 2 activations

    scope = Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    x, y = _data()
    losses = []
    for _ in range(15):
        out = exe.run(main, feed={"x": x, "label": y},
                      fetch_list=[loss], scope=scope)
        losses.append(float(np.asarray(out[0]).reshape(-1)[0]))
    assert losses[-1] < losses[0], losses


def test_ptq_calibration():
    from paddle_tpu.fluid.contrib.slim.quantization import (
        PostTrainingQuantization)
    from paddle_tpu.core.scope import Scope

    main, startup = framework.Program(), framework.Program()
    main.random_seed = startup.random_seed = 4
    with framework.program_guard(main, startup):
        with framework.unique_name_guard():
            loss = _mlp()
    scope = Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    x, y = _data()

    def samples():
        for _ in range(3):
            yield {"x": x, "label": y}

    ptq = PostTrainingQuantization(
        exe, main, feed_list=["x", "label"], fetch_list=[loss],
        sample_generator=samples, batch_nums=3, scope=scope)
    qprog = ptq.quantize()
    assert ptq.scales, "no calibration scales collected"
    assert abs(list(ptq.scales.values())[0]
               - float(np.abs(x).max())) < 1e-5
    qops = [op for op in qprog.global_block().ops
            if op.type.startswith("fake_quantize")]
    assert qops
    # calibrated static scales are BOUND into the activation quant ops
    bound = [op.attrs.get("static_scale") for op in qops
             if op.type == "fake_quantize_abs_max"
             and op.input_names["X"][0] in ptq.scales]
    assert bound and all(b is not None for b in bound), qops
    # quantized program still runs (on different data: static scales)
    x2 = x * 0.5
    out = exe.run(qprog, feed={"x": x2, "label": y},
                  fetch_list=[loss], scope=scope)
    assert np.isfinite(float(np.asarray(out[0]).reshape(-1)[0]))


def _conv_fc_net():
    """conv (per-channel quantizable) + fc classifier on 8x8 images."""
    img = fluid.layers.data(name="img", shape=[1, 8, 8],
                            dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    conv = fluid.layers.conv2d(img, num_filters=4, filter_size=3,
                               padding=1, act="relu")
    pool = fluid.layers.pool2d(conv, pool_size=2, pool_stride=2,
                               pool_type="max")
    logits = fluid.layers.fc(input=pool, size=4)
    loss = fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(logits, label))
    return img, label, logits, loss


def _img_data(n=64):
    r = np.random.RandomState(3)
    x = r.rand(n, 1, 8, 8).astype("float32")
    y = (x.mean(axis=(1, 2, 3), keepdims=False) * 4).astype(
        "int64").clip(0, 3).reshape(n, 1)
    return x, y


def test_qat_freeze_export_roundtrip(tmp_path):
    """VERDICT r4 #5: per-channel QAT -> OutScale tracking -> freeze
    (int8-grid weights in scope, out_threshold attrs) ->
    save_inference_model -> load -> int8-simulated accuracy within
    tolerance of fp32. Reference:
    contrib/slim/quantization/quantization_pass.py:119 (Transform),
    :700 (Freeze)."""
    from paddle_tpu.core.scope import Scope, scope_guard
    from paddle_tpu.fluid.contrib.slim.quantization import (
        OutScaleForInferencePass, OutScaleForTrainingPass,
        QuantizationFreezePass, QuantizationTransformPass)

    main, startup = framework.Program(), framework.Program()
    main.random_seed = startup.random_seed = 11
    with framework.program_guard(main, startup):
        with framework.unique_name_guard():
            img, label, logits, loss = _conv_fc_net()
            QuantizationTransformPass(
                weight_quantize_type="channel_wise_abs_max",
                activation_quantize_type="moving_average_abs_max",
            ).apply(main, startup)
            OutScaleForTrainingPass().apply(main, startup)
            test_prog = main.clone(for_test=True)
            fluid.optimizer.AdamOptimizer(0.01).minimize(loss)

    # per-channel transform: the conv weight quantizer is channel-wise
    cw = [op for op in main.global_block().ops
          if op.type == "fake_channel_wise_quantize_abs_max"]
    assert cw and cw[0].attrs["quant_axis"] == 0
    scale_var = main.global_block()._find_var_recursive(
        cw[0].output_names["OutScale"][0])
    assert tuple(scale_var.shape) == (4,)  # one scale per out channel

    scope = Scope()
    x, y = _img_data()
    with scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup, scope=scope)
        for _ in range(30):
            exe.run(main, feed={"img": x, "label": y},
                    fetch_list=[loss], scope=scope)

        fp32_logits = np.asarray(exe.run(
            test_prog, feed={"img": x, "label": y},
            fetch_list=[logits], scope=scope)[0])

        # freeze: weights snap to the int8 grid in scope; trackers
        # become out_threshold attrs
        QuantizationFreezePass(
            scope=scope,
            weight_quantize_type="channel_wise_abs_max",
        ).apply(test_prog, scope=scope)
        OutScaleForInferencePass().apply(test_prog, scope=scope)

        frozen_ops = test_prog.global_block().ops
        assert not any(o.type == "fake_channel_wise_quantize_abs_max"
                       for o in frozen_ops)  # weight q-ops removed
        conv_ops = [o for o in frozen_ops if o.type == "conv2d"]
        assert conv_ops[0].attrs["quantization_type"] == \
            "qat_with_weight_quantize"
        assert len(conv_ops[0].attrs["weight_quant_scale"]) == 4
        assert any("out_threshold" in o.attrs for o in frozen_ops)
        # scale propagation: max-pool inherits its input's threshold
        pools = [o for o in frozen_ops if o.type == "pool2d"]
        assert pools and "out_threshold" in pools[0].attrs

        # conv weights in scope now sit ON the int8 grid per channel
        wname = conv_ops[0].input_names["Filter"][0]
        w = np.asarray(scope.find_var(wname))
        s = np.array(conv_ops[0].attrs["weight_quant_scale"]).reshape(
            4, 1, 1, 1)
        steps = w * 127.0 / np.maximum(s, 1e-8)
        np.testing.assert_allclose(steps, np.round(steps), atol=1e-4)

        q_logits = np.asarray(exe.run(
            test_prog, feed={"img": x, "label": y},
            fetch_list=[logits], scope=scope)[0])
        fp32_acc = float((fp32_logits.argmax(1) ==
                          y.reshape(-1)).mean())
        q_acc = float((q_logits.argmax(1) == y.reshape(-1)).mean())
        assert q_acc >= fp32_acc - 0.05, (fp32_acc, q_acc)

        # round trip through save/load_inference_model
        d = str(tmp_path / "qmodel")
        fluid.io.save_inference_model(d, ["img"], [logits], exe,
                                      main_program=test_prog)
        prog2, feed_names, fetch_targets = \
            fluid.io.load_inference_model(d, exe)
        out2 = np.asarray(exe.run(
            prog2, feed={"img": x}, fetch_list=fetch_targets,
            scope=scope)[0])
        np.testing.assert_allclose(out2, q_logits, atol=1e-5,
                                   rtol=1e-5)
        # the frozen attrs survive serialization
        ops2 = prog2.global_block().ops
        assert any(o.attrs.get("quantization_type") ==
                   "qat_with_weight_quantize" for o in ops2)
        assert any("out_threshold" in o.attrs for o in ops2)


def test_out_scale_inference_requires_scope():
    from paddle_tpu.fluid.contrib.slim.quantization import (
        OutScaleForInferencePass)

    with pytest.raises(ValueError, match="scope"):
        OutScaleForInferencePass().apply(framework.Program())


def test_out_scale_tracker_frozen_in_test_clone():
    """clone(for_test=True) must stop the moving-average trackers from
    mutating calibration state: eval batches with different magnitudes
    may not drift the out_threshold the freeze will bake."""
    import jax.numpy as jnp
    import paddle_tpu.ops as ops_lib

    # op level: is_test returns InScale untouched
    out = ops_lib.run_op(
        "moving_average_abs_max_scale",
        {"X": [jnp.asarray(np.full((4,), 100.0, "float32"))],
         "InScale": [jnp.asarray([2.0], "float32")]},
        {"is_test": True})
    assert float(np.asarray(out["OutScale"][0])[0]) == 2.0

    # program level: the tracker op is in _IS_TEST_OPS so the clone
    # carries is_test=True
    from paddle_tpu.fluid.contrib.slim.quantization import (
        OutScaleForTrainingPass)

    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup):
        with framework.unique_name_guard():
            x = fluid.layers.data(name="x", shape=[4], dtype="float32")
            h = fluid.layers.fc(input=x, size=4, act="relu")
            OutScaleForTrainingPass().apply(main, startup)
    test_prog = main.clone(for_test=True)
    trackers = [op for op in test_prog.global_block().ops
                if op.type == "moving_average_abs_max_scale"]
    assert trackers
    assert all(op.attrs.get("is_test") for op in trackers)


def test_freeze_bakes_static_scale_for_abs_max_activations():
    """abs_max activation quantizers have no state input; freeze must
    bake the last calibrated OutScale from scope as static_scale, or
    'frozen' inference silently keeps dynamic per-batch scales."""
    from paddle_tpu.core.scope import Scope, scope_guard
    from paddle_tpu.fluid.contrib.slim.quantization import (
        QuantizationFreezePass, QuantizationTransformPass)

    main, startup = framework.Program(), framework.Program()
    main.random_seed = startup.random_seed = 2
    with framework.program_guard(main, startup):
        with framework.unique_name_guard():
            x = fluid.layers.data(name="x", shape=[8], dtype="float32")
            y = fluid.layers.data(name="y", shape=[1], dtype="float32")
            h = fluid.layers.fc(input=x, size=1)
            loss = fluid.layers.mean(fluid.layers.square(h - y))
            QuantizationTransformPass(
                activation_quantize_type="abs_max").apply(main, startup)
            fluid.optimizer.SGDOptimizer(0.01).minimize(loss)
    scope = Scope()
    with scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup, scope=scope)
        r = np.random.RandomState(0)
        xv = r.rand(4, 8).astype("float32")
        exe.run(main, feed={"x": xv, "y": r.rand(4, 1).astype(
            "float32")}, fetch_list=[loss], scope=scope)
        QuantizationFreezePass(scope=scope).apply(main)
        acts = [op for op in main.global_block().ops
                if op.type == "fake_quantize_abs_max"]
        assert acts
        for op in acts:
            assert op.attrs.get("is_test") is True
            assert op.attrs.get("static_scale", 0.0) > 0.0
        # the input quantizer's baked scale is the batch abs-max of x
        in_ops = [op for op in acts
                  if op.input_names["X"][0] == "x"]
        assert in_ops and abs(in_ops[0].attrs["static_scale"]
                              - float(np.abs(xv).max())) < 1e-5
