"""Batch-tail bucketing in the executor (SURVEY §7 hard part (d);
VERDICT r3 missing #7): an epoch-end partial batch whose size divides a
cached bucket runs through the CACHED executable via exact row
replication — one compile for the whole ragged epoch, loss identical to
the unbucketed run (reference contract: executor.cc:184 runs any batch
size without recompiling)."""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework, lowering


def _build(with_bn=False):
    framework.default_main_program().random_seed = 7
    framework.default_startup_program().random_seed = 7
    x = fluid.layers.data(name="x", shape=[6], dtype="float32")
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")
    h = fluid.layers.fc(input=x, size=8, act="relu",
                        param_attr=fluid.ParamAttr(name="w1"))
    if with_bn:
        h = fluid.layers.batch_norm(h)
    pred = fluid.layers.fc(input=h, size=1,
                           param_attr=fluid.ParamAttr(name="w2"))
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    fluid.optimizer.SGDOptimizer(0.1).minimize(loss)
    return x, y, pred, loss


def _data(rng, n):
    return (rng.rand(n, 6).astype("float32"),
            rng.rand(n, 1).astype("float32"))


def _count_compiles(monkeypatch):
    calls = []
    orig = lowering.compile_block

    def counted(*a, **kw):
        calls.append(1)
        return orig(*a, **kw)

    monkeypatch.setattr(lowering, "compile_block", counted)
    return calls


def _run_epoch(exe, scope, loss, pred, xs, ys, batch):
    """Feed batches of `batch` plus the ragged tail; returns losses and
    the final tail prediction rows."""
    losses, tail_pred = [], None
    from paddle_tpu.core import scope as scope_mod

    with scope_mod.scope_guard(scope):
        exe.run(fluid.default_startup_program(), scope=scope)
        for lo in range(0, len(xs), batch):
            fx, fy = xs[lo:lo + batch], ys[lo:lo + batch]
            out = exe.run(feed={"x": fx, "y": fy},
                          fetch_list=[loss, pred], scope=scope)
            losses.append(float(np.asarray(out[0]).reshape(-1)[0]))
            tail_pred = np.asarray(out[1])
    return losses, tail_pred


@pytest.mark.parametrize("with_bn", [False, True])
def test_divisible_tail_one_compile_exact_loss(rng, monkeypatch,
                                               with_bn):
    from paddle_tpu.core.scope import Scope

    xs, ys = _data(rng, 20)  # batches of 8: 8, 8, tail 4 (divides 8)
    calls = _count_compiles(monkeypatch)

    _x, _y, pred, loss = _build(with_bn)
    exe = fluid.Executor(fluid.CPUPlace())
    main_losses, tail_pred = _run_epoch(exe, Scope(), loss, pred,
                                        xs, ys, 8)
    # startup program + ONE training-shape compile, tail reused the
    # bucket via replication
    n_compiles = len(calls)
    assert n_compiles == 2, n_compiles
    # tail fetch of the batch-majored prediction is un-replicated
    assert tail_pred.shape == (4, 1)

    # unbucketed reference: same program rebuilt, bucketing disabled
    fluid.set_flags({"FLAGS_batch_tail_bucketing": False})
    try:
        framework.switch_main_program(framework.Program())
        framework.switch_startup_program(framework.Program())
        with framework.unique_name_guard():
            _x, _y, pred2, loss2 = _build(with_bn)
            exe2 = fluid.Executor(fluid.CPUPlace())
            ref_losses, ref_tail = _run_epoch(exe2, Scope(), loss2,
                                              pred2, xs, ys, 8)
    finally:
        fluid.set_flags({"FLAGS_batch_tail_bucketing": True})
    np.testing.assert_allclose(main_losses, ref_losses, rtol=1e-5,
                               atol=1e-7)
    np.testing.assert_allclose(tail_pred, ref_tail, rtol=1e-5,
                               atol=1e-6)


def test_non_divisible_tail_compiles_once_then_caches(rng, monkeypatch):
    from paddle_tpu.core.scope import Scope

    xs, ys = _data(rng, 19)  # batches of 8: 8, 8, tail 3 (no divide)
    calls = _count_compiles(monkeypatch)
    _x, _y, pred, loss = _build()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = Scope()
    _run_epoch(exe, scope, loss, pred, xs, ys, 8)
    # startup + batch-8 + tail-3 compile
    assert len(calls) == 3
    # epoch 2 re-feeds the same shapes: zero new compiles
    from paddle_tpu.core import scope as scope_mod

    with scope_mod.scope_guard(scope):
        for lo in range(0, len(xs), 8):
            exe.run(feed={"x": xs[lo:lo + 8], "y": ys[lo:lo + 8]},
                    fetch_list=[loss, pred], scope=scope)
    assert len(calls) == 3


def test_constant_side_input_not_replicated(rng, monkeypatch):
    """A feed whose shape does not carry the batch axis (same shape in
    bucket and tail) passes through unreplicated."""
    from paddle_tpu.core.scope import Scope

    x = fluid.layers.data(name="x", shape=[4], dtype="float32")
    t = fluid.layers.data(name="t", shape=[4], dtype="float32",
                          append_batch_size=False)
    out = fluid.layers.reduce_sum(fluid.layers.elementwise_add(x, t),
                                  dim=1)
    exe = fluid.Executor(fluid.CPUPlace())
    calls = _count_compiles(monkeypatch)
    scope = Scope()
    tvec = np.arange(4, dtype="float32")
    xs8 = rng.rand(8, 4).astype("float32")
    xs4 = rng.rand(4, 4).astype("float32")
    o8 = exe.run(feed={"x": xs8, "t": tvec}, fetch_list=[out],
                 scope=scope)
    o4 = exe.run(feed={"x": xs4, "t": tvec}, fetch_list=[out],
                 scope=scope)
    assert len(calls) == 1  # tail reused the batch-8 executable
    np.testing.assert_allclose(np.asarray(o4[0]),
                               (xs4 + tvec).sum(1), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(o8[0]),
                               (xs8 + tvec).sum(1), rtol=1e-6)


def test_sum_loss_program_never_buckets(rng, monkeypatch):
    """Replication scales a batch-SUM loss by m, so such programs must
    compile their tail shape instead of bucketing (code-review r4)."""
    from paddle_tpu.core.scope import Scope

    framework.default_main_program().random_seed = 7
    framework.default_startup_program().random_seed = 7
    x = fluid.layers.data(name="x", shape=[6], dtype="float32")
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")
    pred = fluid.layers.fc(input=x, size=1)
    loss = fluid.layers.reduce_sum(
        fluid.layers.square_error_cost(pred, y))
    fluid.optimizer.SGDOptimizer(0.01).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    calls = _count_compiles(monkeypatch)
    scope = Scope()
    from paddle_tpu.core import scope as scope_mod

    xs, ys = _data(rng, 12)
    with scope_mod.scope_guard(scope):
        exe.run(fluid.default_startup_program(), scope=scope)
        l8 = exe.run(feed={"x": xs[:8], "y": ys[:8]},
                     fetch_list=[loss], scope=scope)
        l4 = exe.run(feed={"x": xs[8:], "y": ys[8:]},
                     fetch_list=[loss], scope=scope)
    # startup + batch-8 + tail-4: the tail COMPILED (no bucket reuse)
    assert len(calls) == 3
    # and the sum-loss value is the true 4-row sum, not 2x it
    w = np.asarray(scope.find_var("fc_0.w_0"))
    assert np.isfinite(np.asarray(l4[0])).all()


def test_streaming_metric_program_never_buckets(rng, monkeypatch):
    """Programs with streaming/counting metric ops (auc histograms,
    accuracy Correct/Total) must not bucket: replicated tail rows would
    inflate counts m-fold (code-review r4 high)."""
    from paddle_tpu.core.scope import Scope
    from paddle_tpu.core import scope as scope_mod

    x = fluid.layers.data(name="x", shape=[4], dtype="float32")
    lbl = fluid.layers.data(name="lbl", shape=[1], dtype="int64")
    logits = fluid.layers.fc(x, size=2)
    probs = fluid.layers.softmax(logits)
    topv, topi = fluid.layers.topk(probs, k=1)
    acc = fluid.layers.accuracy(input=probs, label=lbl, k=1)
    exe = fluid.Executor(fluid.CPUPlace())
    calls = _count_compiles(monkeypatch)
    scope = Scope()
    with scope_mod.scope_guard(scope):
        exe.run(fluid.default_startup_program(), scope=scope)
        xs = rng.rand(12, 4).astype("float32")
        ys = rng.randint(0, 2, (12, 1)).astype("int64")
        a8 = exe.run(feed={"x": xs[:8], "lbl": ys[:8]},
                     fetch_list=[acc], scope=scope)
        a4 = exe.run(feed={"x": xs[8:], "lbl": ys[8:]},
                     fetch_list=[acc], scope=scope)
    # startup + batch-8 + tail-4: metric program COMPILED its tail
    assert len(calls) == 3
    # and the tail accuracy is over 4 rows (a fraction with denom 4)
    assert abs(float(np.asarray(a4[0]).reshape(-1)[0]) * 4
               - round(float(np.asarray(a4[0]).reshape(-1)[0]) * 4)) < 1e-5
