"""Test config: force an 8-device virtual CPU mesh BEFORE jax import so
sharding/collective tests run without TPU hardware (SURVEY.md §4.4:
CI runs on CPU with xla_force_host_platform_device_count)."""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

# jaxtyping's pytest plugin imports jax before this conftest runs, which can
# initialize the accelerator backend (axon/TPU). Reset so the env above
# (cpu + 8 virtual devices) takes effect for all tests.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.clear_caches()
    jax.extend.backend.clear_backends()
except Exception:
    pass
assert jax.default_backend() == "cpu", jax.devices()
assert len(jax.devices()) == 8, jax.devices()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _fresh_programs():
    """Each test gets fresh default programs + scope + unique names."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import framework
    from paddle_tpu.core import scope as scope_mod

    old_main = framework.switch_main_program(framework.Program())
    old_startup = framework.switch_startup_program(framework.Program())
    old_scope = scope_mod._global_scope
    scope_mod._global_scope = scope_mod.Scope()
    with framework.unique_name_guard():
        yield
    framework.switch_main_program(old_main)
    framework.switch_startup_program(old_startup)
    scope_mod._global_scope = old_scope


@pytest.fixture
def rng():
    return np.random.RandomState(42)
