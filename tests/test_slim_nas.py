"""slim NAS: SAController + SANAS end-to-end width/prune-ratio search
(VERDICT r2 next #8; reference: slim/searcher/controller.py SAController
+ slim/nas/ LightNAS)."""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework
from paddle_tpu.fluid.contrib.slim.nas import SANAS, SearchSpace
from paddle_tpu.fluid.contrib.slim.searcher import SAController


def test_sa_controller_accepts_better_tracks_best():
    c = SAController(seed=0, init_temperature=1.0, reduce_rate=0.5)
    c.reset([4, 4], [0, 0])
    c.update([0, 0], 0.1)
    c.update([1, 0], 0.5)   # better: always accepted
    assert c.best_tokens == [1, 0] and c.max_reward == 0.5
    for _ in range(20):
        t = c.next_tokens()
        assert len(t) == 2 and 0 <= t[0] < 4 and 0 <= t[1] < 4
    # constraint is honored
    c.reset([4, 4], [0, 0], constrain_func=lambda t: t[0] != 3)
    for _ in range(20):
        assert c.next_tokens()[0] != 3


class _WidthSpace(SearchSpace):
    """Prune-ratio search: tokens pick each hidden layer's kept width
    from a ladder — the structured-prune search the reference's
    LightNAS ran over flops-constrained nets."""

    WIDTHS = [4, 8, 16]

    def init_tokens(self):
        return [0, 0]

    def range_table(self):
        return [len(self.WIDTHS), len(self.WIDTHS)]

    def create_net(self, tokens):
        return [self.WIDTHS[t] for t in tokens]


def _train_reward(widths, steps=6):
    """Train a tiny MLP of the candidate widths; reward = -final loss -
    flops penalty (so the search must trade capacity vs size)."""
    r = np.random.RandomState(0)
    x = r.rand(64, 8).astype("float32")
    y = ((x.sum(1) > 4.0).astype("int64")[:, None])

    main, startup = framework.Program(), framework.Program()
    main.random_seed = startup.random_seed = 7
    with framework.program_guard(main, startup):
        with framework.unique_name_guard():
            xv = fluid.layers.data(name="x", shape=[8], dtype="float32")
            yv = fluid.layers.data(name="y", shape=[1], dtype="int64")
            h = xv
            for w in widths:
                h = fluid.layers.fc(input=h, size=w, act="relu")
            logits = fluid.layers.fc(input=h, size=2)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, yv))
            fluid.optimizer.AdamOptimizer(0.05).minimize(loss)
            from paddle_tpu.core.scope import Scope

            scope = Scope()
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup, scope=scope)
            for _ in range(steps):
                out = exe.run(main, feed={"x": x, "y": y},
                              fetch_list=[loss], scope=scope)
    final = float(np.asarray(out[0]).reshape(-1)[0])
    flops = 8 * widths[0] + widths[0] * widths[1] + widths[1] * 2
    return -final - 1e-4 * flops


@pytest.mark.slow
def test_sanas_width_search_improves():
    space = _WidthSpace()
    nas = SANAS(space, lambda net, tokens: _train_reward(net),
                seed=3, init_temperature=0.5)
    best_tokens, best_reward = nas.search(max_iterations=6)
    assert len(nas.history) == 7
    assert best_tokens is not None and len(best_tokens) == 2
    assert all(0 <= t < 3 for t in best_tokens)
    first_reward = nas.history[0][1]
    assert best_reward >= first_reward
    # the returned best really is the argmax of everything evaluated
    assert best_reward == max(r for _, r in nas.history)
