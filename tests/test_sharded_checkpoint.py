"""ShardedCheckpointManager (orbax-backed, SURVEY §5 TPU mapping for
checkpoint/resume): mesh-sharded SPMD trainer state round-trips with
shardings preserved, retention prunes old steps, and resumed training
continues bit-identically."""
import tempfile

import numpy as np
import pytest


def _mesh_and_params():
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = np.array(jax.devices()).reshape(4, 2)
    mesh = Mesh(devs, ("dp", "tp"))
    r = np.random.RandomState(0)
    w = jnp.asarray(r.randn(8, 16).astype("float32"))
    b = jnp.asarray(r.randn(16).astype("float32"))
    w = jax.device_put(w, NamedSharding(mesh, P(None, "tp")))
    b = jax.device_put(b, NamedSharding(mesh, P("tp")))
    step = jax.device_put(jnp.int32(3), NamedSharding(mesh, P()))
    return mesh, {"w": w, "b": b, "step": step}


def test_sharded_roundtrip_preserves_sharding():
    import jax

    from paddle_tpu.distributed import ShardedCheckpointManager

    mesh, tree = _mesh_and_params()
    d = tempfile.mkdtemp()
    mgr = ShardedCheckpointManager(d, max_to_keep=2)
    mgr.save(0, tree)
    assert mgr.latest_step() == 0

    restored = mgr.restore(template=tree)
    np.testing.assert_array_equal(np.asarray(restored["w"]),
                                  np.asarray(tree["w"]))
    np.testing.assert_array_equal(np.asarray(restored["b"]),
                                  np.asarray(tree["b"]))
    assert int(restored["step"]) == 3
    # layout landed back on the live mesh, not gathered to one device
    assert restored["w"].sharding == tree["w"].sharding
    assert restored["b"].sharding == tree["b"].sharding
    mgr.close()


def test_restore_relays_out_on_a_different_world_size():
    """Elastic restart (N' != N): a checkpoint written by a 4-device dp
    mesh restores DIRECTLY into a template laid out on a 2-device mesh
    (and vice versa back to 4) — orbax re-lays shards out against the
    template's shardings, values exactly preserved. This is the
    SPMD-trainer half of the world-size-change story (the ZeRO flat
    buffers re-shard via the executor's scope conversion; see
    tests/test_elastic.py)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from paddle_tpu.distributed import ShardedCheckpointManager

    def tree_on(ndev):
        mesh = Mesh(np.array(jax.devices()[:ndev]), ("dp",))
        r = np.random.RandomState(7)
        w = jnp.asarray(r.randn(8, 16).astype("float32"))
        b = jnp.asarray(r.randn(16).astype("float32"))
        return {
            "w": jax.device_put(w, NamedSharding(mesh, P("dp"))),
            "b": jax.device_put(b, NamedSharding(mesh, P())),
        }

    d = tempfile.mkdtemp()
    mgr = ShardedCheckpointManager(d, max_to_keep=2)
    four = tree_on(4)
    mgr.save(0, four)

    two = tree_on(2)
    restored = mgr.restore(template=two)
    np.testing.assert_array_equal(np.asarray(restored["w"]),
                                  np.asarray(four["w"]))
    assert restored["w"].sharding == two["w"].sharding
    assert len(restored["w"].sharding.device_set) == 2

    # shrink persists: a checkpoint SAVED at 2 grows back to 4
    mgr.save(1, restored)
    regrown = mgr.restore(template=four)
    np.testing.assert_array_equal(np.asarray(regrown["w"]),
                                  np.asarray(four["w"]))
    assert len(regrown["w"].sharding.device_set) == 4
    mgr.close()


def test_scalar_leaves_roundtrip():
    """Plain python scalars in the state tree (lr, epoch) must survive
    the save -> restore(template) round trip."""
    from paddle_tpu.distributed import ShardedCheckpointManager

    _, tree = _mesh_and_params()
    tree = dict(tree, lr=0.05, epoch=2)
    d = tempfile.mkdtemp()
    mgr = ShardedCheckpointManager(d)
    mgr.save(0, tree)
    restored = mgr.restore(template=tree)
    assert float(restored["lr"]) == 0.05
    assert int(restored["epoch"]) == 2
    mgr.close()


def test_retention_prunes_old_steps():
    from paddle_tpu.distributed import ShardedCheckpointManager

    _, tree = _mesh_and_params()
    d = tempfile.mkdtemp()
    mgr = ShardedCheckpointManager(d, max_to_keep=2)
    for s in (1, 2, 3):
        mgr.save(s, tree)
    assert mgr.latest_step() == 3
    assert set(mgr.all_steps()) == {2, 3}
    mgr.close()


def test_restore_falls_back_past_corrupt_latest_step(tmp_path):
    """A mid-save kill can leave a partial/truncated latest step dir:
    default restore must validate it and fall back to the newest INTACT
    step instead of dying (or training from scratch). An explicitly
    requested step still raises."""
    import glob
    import os

    import jax.numpy as jnp

    from paddle_tpu.distributed import ShardedCheckpointManager

    _, tree = _mesh_and_params()
    d = str(tmp_path)
    mgr = ShardedCheckpointManager(d, max_to_keep=3)
    mgr.save(1, dict(tree, step=jnp.int32(1)))
    mgr.save(2, dict(tree, step=jnp.int32(2)))
    # simulate the truncation a kill mid-save leaves behind
    step_dir = os.path.join(d, "2")
    files = [p for p in glob.glob(os.path.join(step_dir, "**"),
                                  recursive=True) if os.path.isfile(p)]
    assert files, "expected orbax files under %s" % step_dir
    for p in files:
        open(p, "w").close()

    restored = mgr.restore(template=tree)
    assert int(restored["step"]) == 1
    np.testing.assert_array_equal(np.asarray(restored["w"]),
                                  np.asarray(tree["w"]))
    with pytest.raises(Exception):
        mgr.restore(step=2, template=tree)  # explicit step: no fallback
    mgr.close()


def test_restore_raises_when_no_step_is_intact(tmp_path):
    import glob
    import os

    from paddle_tpu.distributed import ShardedCheckpointManager

    _, tree = _mesh_and_params()
    d = str(tmp_path)
    mgr = ShardedCheckpointManager(d)
    mgr.save(1, tree)
    for p in glob.glob(os.path.join(d, "1", "**"), recursive=True):
        if os.path.isfile(p):
            open(p, "w").close()
    with pytest.raises(RuntimeError, match="no intact checkpoint"):
        mgr.restore(template=tree)
    mgr.close()


def test_resume_training_continues_identically():
    """Save mid-run, keep training; reload and retrain from the
    checkpoint: the loss tails must match exactly."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.distributed import ShardedCheckpointManager

    mesh, tree = _mesh_and_params()
    x = jnp.asarray(np.random.RandomState(1).randn(4, 8)
                    .astype("float32"))

    @jax.jit
    def step(params):
        def loss_fn(p):
            return jnp.mean((x @ p["w"] + p["b"]) ** 2)

        l, g = jax.value_and_grad(loss_fn)(
            {"w": params["w"], "b": params["b"]})
        return l, {"w": params["w"] - 0.05 * g["w"],
                   "b": params["b"] - 0.05 * g["b"],
                   "step": params["step"] + 1}

    d = tempfile.mkdtemp()
    mgr = ShardedCheckpointManager(d)
    p = tree
    for _ in range(3):
        _, p = step(p)
    mgr.save(int(p["step"]), p)
    tail_a = []
    q = p
    for _ in range(3):
        l, q = step(q)
        tail_a.append(float(l))

    restored = mgr.restore(template=tree)
    tail_b = []
    q2 = restored
    for _ in range(3):
        l, q2 = step(q2)
        tail_b.append(float(l))
    np.testing.assert_array_equal(tail_a, tail_b)
    mgr.close()
