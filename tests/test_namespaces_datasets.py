"""Top-level 2.0/classic namespace parity (reference:
`python/paddle/__init__.py` module list) + classic reader/dataset
behavior."""
import numpy as np

import paddle_tpu as paddle


def test_top_level_namespaces():
    for ns in ["reader", "dataset", "distributed", "tensor", "nn",
               "fleet", "framework", "imperative", "optimizer", "metric",
               "complex", "compat", "sysconfig", "static", "jit",
               "incubate", "hapi"]:
        assert hasattr(paddle, ns), ns
    assert callable(paddle.batch)
    assert callable(paddle.manual_seed)


def test_reader_decorators():
    base = lambda: iter(range(10))  # noqa: E731
    assert list(paddle.reader.firstn(base, 3)()) == [0, 1, 2]
    assert sorted(paddle.reader.shuffle(base, 5)()) == list(range(10))
    assert list(paddle.reader.map_readers(
        lambda a, b: a + b, base, base)()) == [2 * i for i in range(10)]
    assert list(paddle.reader.chain(base, base)()) == \
        list(range(10)) * 2
    assert list(paddle.reader.buffered(base, 4)()) == list(range(10))
    cached = paddle.reader.cache(base)
    assert list(cached()) == list(range(10)) == list(cached())
    mapped = paddle.reader.xmap_readers(lambda x: x * 3, base, 2, 4,
                                        order=True)
    assert list(mapped()) == [3 * i for i in range(10)]


def test_batch():
    batches = list(paddle.batch(lambda: iter(range(7)), 3)())
    assert batches == [[0, 1, 2], [3, 4, 5], [6]]
    batches = list(paddle.batch(lambda: iter(range(7)), 3,
                                drop_last=True)())
    assert batches == [[0, 1, 2], [3, 4, 5]]


def test_dataset_mnist_contract():
    r = paddle.dataset.mnist.train()
    img, lbl = next(iter(r()))
    assert img.shape == (784,) and img.dtype == np.float32
    assert -1.0 <= img.min() and img.max() <= 1.0
    assert 0 <= lbl < 10
    # deterministic across instantiations
    img2, lbl2 = next(iter(paddle.dataset.mnist.train()()))
    np.testing.assert_array_equal(img, img2)
    assert lbl == lbl2


def test_dataset_uci_and_imdb():
    x, y = next(iter(paddle.dataset.uci_housing.train()()))
    assert x.shape == (13,) and y.shape == (1,)
    ids, label = next(iter(paddle.dataset.imdb.train()()))
    assert isinstance(ids, list) and label in (0, 1)
    wd = paddle.dataset.imdb.word_dict()
    assert len(wd) > 1000
    grams = list(paddle.dataset.imikolov.train(n=5)())
    assert all(len(g) == 5 for g in grams[:10])


def test_uci_housing_trains():
    """End-to-end: classic reader+batch feeding a static regression."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import framework

    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup):
        with framework.unique_name_guard():
            x = fluid.layers.data("x", shape=[13], dtype="float32")
            y = fluid.layers.data("y", shape=[1], dtype="float32")
            pred = fluid.layers.fc(x, 1)
            loss = fluid.layers.mean(
                fluid.layers.loss.square_error_cost(pred, y))
            fluid.optimizer.SGDOptimizer(0.01).minimize(loss)
            exe = fluid.Executor()
            exe.run(startup)
            reader = paddle.batch(paddle.dataset.uci_housing.train(), 64)
            losses = []
            for epoch in range(3):
                for batch in reader():
                    xs = np.stack([b[0] for b in batch])
                    ys = np.stack([b[1] for b in batch])
                    out = exe.run(main, feed={"x": xs, "y": ys},
                                  fetch_list=[loss])
                losses.append(float(np.asarray(out[0]).ravel()[0]))
    assert losses[-1] < losses[0]


def test_metric_namespace():
    m = paddle.metric.Accuracy()
    assert hasattr(m, "update") or hasattr(m, "eval")
    assert paddle.metric.Auc is not None


def test_io_state_helpers(tmp_path):
    """fluid.io get_parameter_value / load_program_state /
    set_program_state round trip (reference io.py surface)."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import framework, io as fio

    r = np.random.RandomState(0)
    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup):
        with framework.unique_name_guard():
            x = fluid.layers.data("x", shape=[6], dtype="float32")
            y = fluid.layers.fc(x, 3, name="iofc")
            loss = fluid.layers.mean(y)
            fluid.optimizer.AdamOptimizer(1e-3).minimize(loss)
            exe = fluid.Executor()
            exe.run(startup)

            params = fio.get_program_parameter(main)
            assert any(p.name == "iofc.w_0" for p in params)
            w = fio.get_parameter_value_by_name("iofc.w_0",
                                                program=main)
            assert w.shape == (6, 3)

            opt_state = [v for v in main.list_vars()
                         if fio.is_belong_to_optimizer(v)]
            assert opt_state, "adam moments should be flagged"
            assert not fio.is_belong_to_optimizer(params[0])

            path = str(tmp_path / "model")
            fio.save(main, path)
            state = fio.load_program_state(path)
            assert "iofc.w_0" in state
            # optimizer state merges in too (reference load_program_state)
            assert any("Optimizer_" in k for k in state)
            only_w = fio.load_program_state(path, var_list=[params[0]])
            assert set(only_w) == {params[0].name}
            # a user param named 'linear' must not be misflagged
            class _V:
                name = "linear.w_0"
                persistable = True
            assert not fio.is_belong_to_optimizer(_V())
            # perturb then restore
            from paddle_tpu.core.scope import global_scope
            import jax.numpy as jnp
            global_scope().set_var("iofc.w_0",
                                   jnp.zeros((6, 3), jnp.float32))
            left = fio.set_program_state(main, state)
            w2 = fio.get_parameter_value_by_name("iofc.w_0",
                                                 program=main)
            np.testing.assert_allclose(w2, w)
            assert "iofc.w_0" not in left


# -- round-3 dataset long tail (VERDICT r2 next #9) ---------------------


def test_dataset_module_inventory_matches_reference():
    import paddle_tpu.dataset as ds

    for m in ("mnist", "cifar", "uci_housing", "imdb", "imikolov",
              "conll05", "movielens", "mq2007", "sentiment", "flowers",
              "voc2012", "wmt14", "wmt16", "image", "common"):
        assert hasattr(ds, m), m


def test_conll05_contract():
    from paddle_tpu.dataset import conll05

    w, v, l = conll05.get_dict()
    emb = conll05.get_embedding()
    assert emb.shape[0] == len(w)
    sample = next(iter(conll05.test()()))
    assert len(sample) == 9
    length = len(sample[0])
    assert all(len(s) == length for s in sample)
    assert all(t < len(l) for t in sample[8])
    assert sum(sample[7]) == 1  # exactly one predicate mark


def test_movielens_contract():
    from paddle_tpu.dataset import movielens

    s = next(iter(movielens.train()()))
    # [user_id, gender, age, job, movie_id, categories, title, [score]]
    assert len(s) == 8
    assert 1 <= s[0] <= movielens.max_user_id()
    assert 1 <= s[4] <= movielens.max_movie_id()
    assert isinstance(s[5], list) and isinstance(s[6], list)
    assert 1.0 <= s[7][0] <= 5.0
    assert movielens.max_job_id() == 20
    info = movielens.movie_info()[1]
    assert "MovieInfo" in repr(info)


def test_mq2007_formats():
    from paddle_tpu.dataset import mq2007

    hi_lbl, hi, lo = next(iter(mq2007.train(format="pairwise")))
    assert len(hi) == 46 and len(lo) == 46
    lbl, feat = next(iter(mq2007.train(format="pointwise")))
    assert isinstance(lbl, float) and len(feat) == 46
    labels, feats = next(iter(mq2007.train(format="listwise")))
    assert len(labels) == len(feats)


def test_sentiment_flowers_voc_contract():
    from paddle_tpu.dataset import sentiment, flowers, voc2012

    ids, label = next(iter(sentiment.train()()))
    assert label in (0, 1) and max(ids) < len(sentiment.get_word_dict())
    img, lbl = next(iter(flowers.train()()))
    assert img.shape[0] == 3 and 0 <= lbl < 102
    img, mask = next(iter(voc2012.train()()))
    assert img.shape[1:] == mask.shape and mask.max() < 21


def test_wmt_contract():
    from paddle_tpu.dataset import wmt14, wmt16

    src, trg_in, trg_next = next(iter(wmt14.train(1000)()))
    assert trg_in[0] == wmt14.START_ID
    assert trg_next[-1] == wmt14.END_ID
    assert trg_in[1:] == trg_next[:-1]
    sd, td = wmt14.get_dict(1000, reverse=True)
    assert sd[0] == "<s>" and len(sd) == 1000

    src, trg_in, trg_next = next(iter(wmt16.train(800, 900, "en")()))
    assert max(src) < 800 and max(trg_in) < 900
    d = wmt16.get_dict("de", 900)
    assert d["<s>"] == 0 and len(d) == 900


def test_image_transforms():
    import numpy as np

    from paddle_tpu.dataset import image as img_mod

    im = np.arange(40 * 60 * 3, dtype="float32").reshape(40, 60, 3)
    r = img_mod.resize_short(im, 20)
    assert min(r.shape[:2]) == 20 and r.shape[1] == 30
    c = img_mod.center_crop(r, 16)
    assert c.shape[:2] == (16, 16)
    chw = img_mod.to_chw(c)
    assert chw.shape == (3, 16, 16)
    f = img_mod.left_right_flip(c)
    assert np.array_equal(f[:, 0], c[:, -1])
    out = img_mod.simple_transform(im, 24, 16, is_train=True,
                                   mean=[1.0, 2.0, 3.0])
    assert out.shape == (3, 16, 16) and out.dtype == np.float32


def test_fleet_utils_fs():
    """LocalFS surface (reference: incubate/fleet/utils/fs.py) +
    HDFSClient's loud no-hadoop failure."""
    import tempfile

    import pytest as _pytest

    from paddle_tpu.fleet.utils import (LocalFS, HDFSClient,
                                        ExecuteError,
                                        FSFileExistsError)

    fs = LocalFS()
    d = tempfile.mkdtemp()
    fs.mkdirs(d + "/a/b")
    assert fs.is_dir(d + "/a") and not fs.need_upload_download()
    fs.touch(d + "/a/x.txt")
    assert fs.is_file(d + "/a/x.txt")
    assert fs.list_dirs(d) == ["a"]
    assert sorted(fs.ls_dir(d + "/a")) == ["b", "x.txt"]
    fs.mv(d + "/a/x.txt", d + "/a/y.txt")
    with _pytest.raises(FSFileExistsError):
        fs.mv(d + "/a/y.txt", d + "/a/b")
    fs.delete(d + "/a")
    assert not fs.is_exist(d + "/a")

    import shutil as _sh

    if _sh.which("hadoop") is None:
        with _pytest.raises(ExecuteError, match="hadoop"):
            HDFSClient()


def test_launch_ps_env_contract(tmp_path):
    """launch_ps spawns pserver+trainer procs with the reference PS env
    (reference: distributed/launch_ps.py), readable by
    PaddleCloudRoleMaker(is_collective=False)."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "probe.py"
    script.write_text(
        "import os, sys\n"
        "sys.path.insert(0, %r)\n"
        "os.environ.setdefault('JAX_PLATFORMS', 'cpu')\n"
        "from paddle_tpu.fleet.role_maker import PaddleCloudRoleMaker\n"
        "rm = PaddleCloudRoleMaker(is_collective=False)\n"
        "print('ROLE', 'S' if rm.is_server() else 'W',\n"
        "      rm.server_index() if rm.is_server() else rm.worker_index(),\n"
        "      rm.server_num(), rm.worker_num())\n"
        % repo)
    logs = tmp_path / "logs"
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch_ps",
         "--server_num", "2", "--worker_num", "2",
         "--log_dir", str(logs), str(script)],
        cwd=repo, env={**os.environ,
                                        "JAX_PLATFORMS": "cpu"},
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        timeout=240)
    assert proc.returncode == 0, proc.stdout
    roles = []
    for f in sorted(logs.iterdir()):
        roles.append(f.read_text().strip())
    assert sorted(roles) == ["ROLE S 0 2 2", "ROLE S 1 2 2",
                             "ROLE W 0 2 2", "ROLE W 1 2 2"], roles


def test_classic_reader_datafeeder_executor_pipeline():
    """THE classic fluid idiom (reference book tests): paddle.batch over
    a dataset reader -> DataFeeder.feed -> Executor.run, training a
    regressor on uci_housing until the loss drops."""
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import framework

    main, startup = framework.Program(), framework.Program()
    main.random_seed = startup.random_seed = 31
    with framework.program_guard(main, startup):
        with framework.unique_name_guard():
            x = fluid.layers.data("x", shape=[13], dtype="float32")
            y = fluid.layers.data("y", shape=[1], dtype="float32")
            pred = fluid.layers.fc(x, size=1)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(pred, y))
            fluid.optimizer.SGDOptimizer(1e-3).minimize(loss)

            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            feeder = fluid.DataFeeder(feed_list=[x, y],
                                      place=fluid.CPUPlace())
            reader = paddle.batch(
                paddle.dataset.uci_housing.train(), batch_size=16)
            first = last = None
            for epoch in range(3):
                for batch in reader():
                    out = exe.run(main, feed=feeder.feed(batch),
                                  fetch_list=[loss])
                    val = float(np.asarray(out[0]).reshape(-1)[0])
                    if first is None:
                        first = val
                    last = val
            assert np.isfinite(last) and last < first, (first, last)
