"""FLAGS_prng_impl: the PRNG bit-generator behind dropout / random-init
keys (core/rng.py). The reference seeds per-device curand state
(dropout_op.cu, uniform_random_op.cc); the TPU-native design threads
counter-based stateless keys, and this flag picks the key impl —
"auto" resolves to XLA's hardware RngBitGenerator on TPU (threefry's
~1.2G serial VPU draws/step on BERT-base b256 idle the MXU) and to
threefry2x32 on CPU so seeded CPU streams stay byte-stable."""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework
from paddle_tpu.core.rng import make_key, resolved_impl
from paddle_tpu.utils.flags import get_flag, set_flags


@pytest.fixture
def _impl_flag():
    old = get_flag("FLAGS_prng_impl")
    yield
    set_flags({"FLAGS_prng_impl": old})


def test_auto_resolves_threefry_on_cpu(_impl_flag):
    set_flags({"FLAGS_prng_impl": "auto"})
    import jax

    want = "rbg" if jax.default_backend() == "tpu" else "threefry2x32"
    assert resolved_impl() == want


def test_explicit_impl_wins(_impl_flag):
    set_flags({"FLAGS_prng_impl": "rbg"})
    assert resolved_impl() == "rbg"
    set_flags({"FLAGS_prng_impl": "threefry2x32"})
    assert resolved_impl() == "threefry2x32"


@pytest.mark.parametrize("impl", ["threefry2x32", "rbg"])
def test_typed_keys_work_with_random_consumers(_impl_flag, impl):
    import jax

    set_flags({"FLAGS_prng_impl": impl})
    k = make_key(7)
    k2 = jax.random.fold_in(k, 3)
    b = np.asarray(jax.random.bernoulli(k2, 0.7, (64, 64)))
    u = np.asarray(jax.random.uniform(k2, (8,)))
    n = np.asarray(jax.random.normal(k2, (8,)))
    assert 0.4 < b.mean() < 0.95
    assert np.isfinite(u).all() and np.isfinite(n).all()
    # same seed -> same stream (counter-based determinism per impl)
    b2 = np.asarray(jax.random.bernoulli(
        jax.random.fold_in(make_key(7), 3), 0.7, (64, 64)))
    np.testing.assert_array_equal(b, b2)


def _dropout_losses(steps=3):
    main, startup = framework.Program(), framework.Program()
    main.random_seed = startup.random_seed = 7
    with framework.program_guard(main, startup):
        with framework.unique_name_guard():
            x = fluid.layers.data("x", shape=[32], dtype="float32")
            h = fluid.layers.fc(x, size=32)
            h = fluid.layers.dropout(h, dropout_prob=0.3)
            loss = fluid.layers.mean(h)
            fluid.optimizer.SGDOptimizer(0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    feed = {"x": np.ones((4, 32), np.float32)}
    return [float(np.asarray(
        exe.run(main, feed=feed, fetch_list=[loss])[0]).ravel()[0])
        for _ in range(steps)]


@pytest.mark.parametrize("impl", ["threefry2x32", "rbg"])
def test_train_step_deterministic_under_both_impls(_impl_flag, impl):
    """The full static-graph path (seeded init + per-step dropout keys)
    stays run-to-run deterministic whichever bit generator is picked."""
    set_flags({"FLAGS_prng_impl": impl})
    a = _dropout_losses()
    b = _dropout_losses()
    assert a == b
    assert np.isfinite(a).all()
