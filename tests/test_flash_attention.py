"""Golden tests for the Pallas flash-attention kernel (interpret mode on
the CPU test mesh) against the naive XLA reference — forward and grads.

Mirrors the reference's OpTest check_output/check_grad discipline
(`python/paddle/fluid/tests/unittests/op_test.py:948,1236`)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.pallas import flash_attention, reference_attention


def _rand_qkv(rng, B, H, Sq, Sk, D, dtype="float32"):
    q = rng.standard_normal((B, H, Sq, D)).astype(dtype)
    k = rng.standard_normal((B, H, Sk, D)).astype(dtype)
    v = rng.standard_normal((B, H, Sk, D)).astype(dtype)
    return jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)


@pytest.mark.parametrize("causal", [False, True])
def test_forward_matches_reference(causal):
    rng = np.random.default_rng(0)
    q, k, v = _rand_qkv(rng, 2, 2, 256, 256, 64)
    out = flash_attention(q, k, v, causal=causal)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_forward_key_bias_padding_mask():
    rng = np.random.default_rng(1)
    B, Sk = 2, 256
    q, k, v = _rand_qkv(rng, B, 2, 128, Sk, 64)
    mask = np.ones((B, Sk), np.float32)
    mask[0, 200:] = 0.0
    mask[1, 64:] = 0.0
    bias = jnp.asarray((mask - 1.0) * 1e4)
    out = flash_attention(q, k, v, key_bias=bias)
    ref = reference_attention(q, k, v, key_bias=bias)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_unaligned_seq_lens_padded():
    rng = np.random.default_rng(2)
    q, k, v = _rand_qkv(rng, 1, 2, 100, 100, 64)
    out = flash_attention(q, k, v, block_q=64, block_k=64)
    ref = reference_attention(q, k, v)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_grads_match_reference(causal):
    rng = np.random.default_rng(3)
    q, k, v = _rand_qkv(rng, 1, 2, 128, 128, 64)
    w = jnp.asarray(rng.standard_normal((1, 2, 128, 64)).astype("float32"))

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal) * w)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=causal) * w)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(gf, gr, atol=3e-4, rtol=3e-4,
                                   err_msg="d%s mismatch" % name)


def test_grads_with_bias_nondiff():
    rng = np.random.default_rng(4)
    q, k, v = _rand_qkv(rng, 1, 1, 128, 128, 64)
    mask = np.ones((1, 128), np.float32)
    mask[0, 96:] = 0.0
    bias = jnp.asarray((mask - 1.0) * 1e4)
    w = jnp.asarray(rng.standard_normal((1, 1, 128, 64)).astype("float32"))

    g = jax.grad(lambda q: jnp.sum(
        flash_attention(q, k, v, key_bias=bias) * w))(q)
    gr = jax.grad(lambda q: jnp.sum(
        reference_attention(q, k, v, key_bias=bias) * w))(q)
    np.testing.assert_allclose(g, gr, atol=3e-4, rtol=3e-4)


def test_bfloat16_close():
    rng = np.random.default_rng(5)
    q, k, v = _rand_qkv(rng, 1, 2, 128, 128, 64)
    q, k, v = (t.astype(jnp.bfloat16) for t in (q, k, v))
    out = flash_attention(q, k, v)
    assert out.dtype == jnp.bfloat16
    ref = reference_attention(q.astype(jnp.float32), k.astype(jnp.float32),
                              v.astype(jnp.float32))
    np.testing.assert_allclose(out.astype(np.float32), ref,
                               atol=3e-2, rtol=3e-2)
