"""Golden tests for the Pallas flash-attention kernel (interpret mode on
the CPU test mesh) against the naive XLA reference — forward and grads.

Mirrors the reference's OpTest check_output/check_grad discipline
(`python/paddle/fluid/tests/unittests/op_test.py:948,1236`)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.pallas import flash_attention, reference_attention


def _rand_qkv(rng, B, H, Sq, Sk, D, dtype="float32"):
    q = rng.standard_normal((B, H, Sq, D)).astype(dtype)
    k = rng.standard_normal((B, H, Sk, D)).astype(dtype)
    v = rng.standard_normal((B, H, Sk, D)).astype(dtype)
    return jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)


@pytest.mark.parametrize("causal", [False, True])
def test_forward_matches_reference(causal):
    rng = np.random.default_rng(0)
    q, k, v = _rand_qkv(rng, 2, 2, 256, 256, 64)
    out = flash_attention(q, k, v, causal=causal)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_forward_key_bias_padding_mask():
    rng = np.random.default_rng(1)
    B, Sk = 2, 256
    q, k, v = _rand_qkv(rng, B, 2, 128, Sk, 64)
    mask = np.ones((B, Sk), np.float32)
    mask[0, 200:] = 0.0
    mask[1, 64:] = 0.0
    bias = jnp.asarray((mask - 1.0) * 1e4)
    out = flash_attention(q, k, v, key_bias=bias)
    ref = reference_attention(q, k, v, key_bias=bias)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_unaligned_seq_lens_padded():
    rng = np.random.default_rng(2)
    q, k, v = _rand_qkv(rng, 1, 2, 100, 100, 64)
    out = flash_attention(q, k, v, block_q=64, block_k=64)
    ref = reference_attention(q, k, v)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_grads_match_reference(causal):
    rng = np.random.default_rng(3)
    q, k, v = _rand_qkv(rng, 1, 2, 128, 128, 64)
    w = jnp.asarray(rng.standard_normal((1, 2, 128, 64)).astype("float32"))

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal) * w)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=causal) * w)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(gf, gr, atol=3e-4, rtol=3e-4,
                                   err_msg="d%s mismatch" % name)


def test_grads_with_bias_nondiff():
    rng = np.random.default_rng(4)
    q, k, v = _rand_qkv(rng, 1, 1, 128, 128, 64)
    mask = np.ones((1, 128), np.float32)
    mask[0, 96:] = 0.0
    bias = jnp.asarray((mask - 1.0) * 1e4)
    w = jnp.asarray(rng.standard_normal((1, 1, 128, 64)).astype("float32"))

    g = jax.grad(lambda q: jnp.sum(
        flash_attention(q, k, v, key_bias=bias) * w))(q)
    gr = jax.grad(lambda q: jnp.sum(
        reference_attention(q, k, v, key_bias=bias) * w))(q)
    np.testing.assert_allclose(g, gr, atol=3e-4, rtol=3e-4)


def test_bfloat16_close():
    rng = np.random.default_rng(5)
    q, k, v = _rand_qkv(rng, 1, 2, 128, 128, 64)
    q, k, v = (t.astype(jnp.bfloat16) for t in (q, k, v))
    out = flash_attention(q, k, v)
    assert out.dtype == jnp.bfloat16
    ref = reference_attention(q.astype(jnp.float32), k.astype(jnp.float32),
                              v.astype(jnp.float32))
    np.testing.assert_allclose(out.astype(np.float32), ref,
                               atol=3e-2, rtol=3e-2)


# ---------------------------------------------------------------------------
# In-kernel dropout (VERDICT r4 #3a): mask is a counter-based hash of
# GLOBAL (row, col, head, seed) coordinates — reproducible on the host,
# so fwd AND grads are checked EXACTLY against a reference computed with
# the identical mask.
# ---------------------------------------------------------------------------

def _host_dropout_mask(seed, BH, S, Sk, p):
    """Numpy replica of flash_attention._dropout_mask over the full
    [BH, S, Sk] lattice (blocking-independent by construction)."""
    r = np.arange(S, dtype=np.uint32)[None, :, None]
    c = np.arange(Sk, dtype=np.uint32)[None, None, :]
    b = np.arange(BH, dtype=np.uint32)[:, None, None]
    with np.errstate(over="ignore"):
        x = (r * np.uint32(0x9E3779B1)) ^ (c * np.uint32(0x85EBCA77))
        x = x ^ (b * np.uint32(0xC2B2AE3D)) ^ np.uint32(seed)
        x = x ^ (x >> np.uint32(16))
        x = x * np.uint32(0x85EBCA6B)
        x = x ^ (x >> np.uint32(13))
        x = x * np.uint32(0xC2B2AE35)
        x = x ^ (x >> np.uint32(16))
    thresh = np.uint32(min(int(p * 4294967296.0), 0xFFFFFFFF))
    return np.where(x >= thresh, 1.0 / (1.0 - p), 0.0).astype(np.float32)


def _masked_reference(q, k, v, mask_bhsk, sm_scale=None):
    """dropout(softmax(s)) @ v with an explicit [B*H, Sq, Sk] mask."""
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    if sm_scale is None:
        sm_scale = 1.0 / np.sqrt(D)
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    p = jax.nn.softmax(s, axis=-1)
    z = p * mask_bhsk.reshape(B, H, Sq, Sk)
    return jnp.einsum("bhqk,bhkd->bhqd", z,
                      v.astype(jnp.float32)).astype(q.dtype)


def test_dropout_forward_exact_vs_host_mask():
    rng = np.random.default_rng(7)
    B, H, S, D, p, seed = 2, 2, 256, 64, 0.3, 12345
    q, k, v = _rand_qkv(rng, B, H, S, S, D)
    out = flash_attention(q, k, v, dropout_p=p,
                          dropout_seed=jnp.int32(seed))
    mask = _host_dropout_mask(seed, B * H, S, S, p)
    ref = _masked_reference(q, k, v, mask)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_dropout_blocking_independent_and_deterministic():
    rng = np.random.default_rng(8)
    q, k, v = _rand_qkv(rng, 1, 2, 256, 256, 64)
    seed = jnp.int32(99)
    a = flash_attention(q, k, v, dropout_p=0.2, dropout_seed=seed)
    b = flash_attention(q, k, v, dropout_p=0.2, dropout_seed=seed,
                        block_q=64, block_k=64)
    np.testing.assert_allclose(a, b, atol=2e-5, rtol=2e-5)
    c = flash_attention(q, k, v, dropout_p=0.2,
                        dropout_seed=jnp.int32(100))
    assert not np.allclose(a, c)


def test_dropout_grads_exact_vs_host_mask():
    rng = np.random.default_rng(9)
    B, H, S, D, p, seed = 1, 2, 128, 64, 0.25, 4242
    q, k, v = _rand_qkv(rng, B, H, S, S, D)
    w = jnp.asarray(rng.standard_normal((B, H, S, D)).astype("float32"))
    mask = _host_dropout_mask(seed, B * H, S, S, p)

    g_flash = jax.grad(lambda q, k, v: jnp.sum(flash_attention(
        q, k, v, dropout_p=p, dropout_seed=jnp.int32(seed)) * w),
        argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(lambda q, k, v: jnp.sum(
        _masked_reference(q, k, v, mask) * w),
        argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(gf, gr, atol=3e-4, rtol=3e-4,
                                   err_msg="d%s mismatch" % name)


def test_dropout_rate_and_keyed_bias_interaction():
    rng = np.random.default_rng(10)
    B, H, S, D, p = 1, 2, 256, 64, 0.4
    q, k, v = _rand_qkv(rng, B, H, S, S, D)
    mask = _host_dropout_mask(777, B * H, S, S, p)
    drop_frac = float((mask == 0.0).mean())
    assert abs(drop_frac - p) < 0.02  # hash uniformity sanity

    # padding bias composes with dropout (padded keys stay dead)
    pad = np.ones((B, S), np.float32)
    pad[0, 200:] = 0.0
    bias = jnp.asarray((pad - 1.0) * 1e4)
    out = flash_attention(q, k, v, key_bias=bias, dropout_p=p,
                          dropout_seed=jnp.int32(777))
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D)
    s = s + bias[:, None, None, :]
    z = jax.nn.softmax(s, axis=-1) * mask.reshape(B, H, S, S)
    ref = jnp.einsum("bhqk,bhkd->bhqd", z, v)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_dropout_zero_p_matches_plain():
    rng = np.random.default_rng(11)
    q, k, v = _rand_qkv(rng, 1, 1, 128, 128, 64)
    a = flash_attention(q, k, v)
    b = flash_attention(q, k, v, dropout_p=0.0)
    np.testing.assert_allclose(a, b)
    with pytest.raises(ValueError):
        flash_attention(q, k, v, dropout_p=0.5)  # seed required


# -- decode shapes (serving): q_len=1 and ragged batches --------------------

def test_decode_q_len_1_matches_reference():
    """The serving decode shape: ONE query row against a long cached
    context (q block pads 1 -> 8 internally; the kernel must not read
    garbage from the padded rows)."""
    rng = np.random.default_rng(12)
    q, k, v = _rand_qkv(rng, 2, 2, 1, 256, 64)
    out = flash_attention(q, k, v)
    ref = reference_attention(q, k, v)
    assert out.shape == (2, 2, 1, 64)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_decode_ragged_batch_via_key_bias():
    """A ragged decode batch: every row q_len=1 but each sequence has
    a different live context length, expressed as the additive key
    padding bias (the pre-paging serving idiom) — rows must match the
    per-sequence dense truth, dead keys contribute nothing."""
    rng = np.random.default_rng(13)
    B, H, Sk, D = 3, 2, 192, 64
    q, k, v = _rand_qkv(rng, B, H, 1, Sk, D)
    lens = [192, 7, 64]
    mask = np.zeros((B, Sk), np.float32)
    for b, n in enumerate(lens):
        mask[b, :n] = 1.0
    bias = jnp.asarray((mask - 1.0) * 1e4)
    out = np.asarray(flash_attention(q, k, v, key_bias=bias))
    for b, n in enumerate(lens):
        ref = reference_attention(q[b:b + 1], k[b:b + 1, :, :n],
                                  v[b:b + 1, :, :n])
        np.testing.assert_allclose(out[b], np.asarray(ref)[0],
                                   atol=2e-5, rtol=2e-5)


def test_decode_q_len_1_unaligned_context():
    """q_len=1 with a context that is not a multiple of the k block
    (the auto-pad path must mask the padded tail keys)."""
    rng = np.random.default_rng(14)
    q, k, v = _rand_qkv(rng, 1, 2, 1, 100, 64)
    out = flash_attention(q, k, v, block_k=64)
    ref = reference_attention(q, k, v)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)
