"""contrib extras: complex tensor API, memory_usage, decoupled weight
decay, distributed reader (reference: `python/paddle/incubate/complex/`,
`contrib/memory_usage_calc.py`, `contrib/extend_optimizer/`,
`contrib/reader/distributed_reader.py`)."""
import os

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework, contrib
from paddle_tpu.incubate import complex as cpx


def test_complex_ops():
    a = cpx.ComplexVariable(np.ones((2, 2), "float32"),
                            np.eye(2, dtype="float32"))
    b = cpx.matmul(a, a)
    e = (np.ones((2, 2)) + 1j * np.eye(2)) @ \
        (np.ones((2, 2)) + 1j * np.eye(2))
    np.testing.assert_allclose(b.numpy(), e, rtol=1e-5)
    assert cpx.kron(a, a).shape == (4, 4)
    np.testing.assert_allclose(cpx.trace(a).numpy(),
                               np.trace(np.ones((2, 2)) + 1j * np.eye(2)),
                               rtol=1e-5)
    s = cpx.elementwise_add(a, a)
    np.testing.assert_allclose(s.real, 2 * np.ones((2, 2)), rtol=1e-6)
    t = cpx.transpose(cpx.reshape(a, [4, 1]), [1, 0])
    assert t.shape == (1, 4)
    d = cpx.elementwise_div(a, a)
    np.testing.assert_allclose(d.numpy(), np.ones((2, 2)), rtol=1e-5)


def test_memory_usage():
    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup):
        with framework.unique_name_guard():
            x = fluid.layers.data("x", shape=[64], dtype="float32")
            fluid.layers.fc(x, 128)
    lo, hi = contrib.memory_usage(main, batch_size=32)
    assert 0 < lo < hi


def test_decoupled_weight_decay_trains():
    AdamW = contrib.extend_with_decoupled_weight_decay(
        fluid.optimizer.AdamOptimizer)
    r = np.random.RandomState(0)
    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup):
        with framework.unique_name_guard():
            x = fluid.layers.data("x", shape=[8], dtype="float32")
            h = fluid.layers.fc(x, 4, name="fcw")
            loss = fluid.layers.mean(fluid.layers.square(h))
            opt = AdamW(weight_decay=0.1, learning_rate=1e-3)
            opt.minimize(loss)
            exe = fluid.Executor()
            exe.run(startup)
            from paddle_tpu.core.scope import global_scope
            w0 = np.asarray(global_scope().find_var("fcw.w_0")).copy()
            for _ in range(3):
                exe.run(main, feed={"x": r.randn(16, 8).astype("float32")},
                        fetch_list=[loss])
            w1 = np.asarray(global_scope().find_var("fcw.w_0"))
    # decay + loss gradient must shrink the weight norm
    assert np.linalg.norm(w1) < np.linalg.norm(w0)


def test_distributed_batch_reader():
    os.environ["PADDLE_TRAINER_ID"] = "1"
    os.environ["PADDLE_TRAINERS_NUM"] = "2"
    try:
        from paddle_tpu.fluid.contrib.reader import (
            distributed_batch_reader)
        r = distributed_batch_reader(lambda: iter(range(10)))
        assert list(r()) == [1, 3, 5, 7, 9]
    finally:
        os.environ["PADDLE_TRAINER_ID"] = "0"
        os.environ["PADDLE_TRAINERS_NUM"] = "1"


def test_contrib_training_decoder_and_beam_search():
    from paddle_tpu.fluid.contrib.decoder import (
        InitState, StateCell, TrainingDecoder, BeamSearchDecoder)

    r = np.random.RandomState(0)
    b, t, d = 2, 4, 8
    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup):
        with framework.unique_name_guard():
            seq = fluid.layers.data("seq", shape=[t, d], dtype="float32")
            boot = fluid.layers.data("boot", shape=[d], dtype="float32")
            init = InitState(init=boot)
            cell = StateCell(inputs={"x": None},
                             states={"h": init}, out_state="h")

            @cell.state_updater
            def updater(c):
                x = c.get_input("x")
                h = c.get_state("h")
                c.set_state("h", fluid.layers.tanh(
                    fluid.layers.elementwise_add(x, h)))

            dec = TrainingDecoder(cell)
            with dec.block():
                out = dec.decode(
                    seq, lambda c, x_t: (c.compute_state({"x": x_t})
                                         or c.out_state()))
            exe = fluid.Executor()
            exe.run(startup)
            got = exe.run(main, feed={
                "seq": r.randn(b, t, d).astype("float32"),
                "boot": np.zeros((b, d), "float32")},
                fetch_list=[out])
    assert np.asarray(got[0]).shape == (b, t, d)
    assert np.all(np.isfinite(np.asarray(got[0])))
