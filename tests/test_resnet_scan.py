"""ResNet scan_stages: each bottleneck stage's identical tail blocks as
one layers.Scan with stacked conv/BN params and per-iteration BN
running-stat slice updates (scan.iteration() + gather/scatter). Exact
forward parity vs the unrolled stage under shared weights; training
moves every stacked slice; BN stats update per row."""
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # two ~1min compiles; excluded from tier-1

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework
from paddle_tpu.core.scope import global_scope
from paddle_tpu.models import resnet as R

CLASSES, IMG = 10, 32


def _build(scan, is_test, seed=6, lr=3e-3):
    main, st = framework.Program(), framework.Program()
    main.random_seed = st.random_seed = seed
    with framework.program_guard(main, st):
        with framework.unique_name_guard():
            img = fluid.layers.data("image", shape=[3, IMG, IMG],
                                    dtype="float32")
            label = fluid.layers.data("label", shape=[1], dtype="int64")
            logits = R.resnet(img, class_dim=CLASSES, depth=50,
                              is_test=is_test, scan_stages=scan)
            loss = fluid.layers.mean(
                fluid.layers.loss.softmax_with_cross_entropy(
                    logits, label))
            if not is_test:
                fluid.optimizer.MomentumOptimizer(
                    lr, momentum=0.9).minimize(loss)
    return main, st, loss


def _feed(B=4):
    r = np.random.RandomState(0)
    return {"image": r.randn(B, 3, IMG, IMG).astype("float32"),
            "label": r.randint(0, CLASSES, (B, 1)).astype("int64")}


_SUFFIX_CH = {"2a": 1, "2b": 1, "2c": 4}


def _stack_unrolled(vals, counts=(3, 4, 6, 3)):
    """Assemble the scan path's stacked arrays from unrolled block
    params: res{s}_{b}_branch{suf}_* -> res{s}_scan{suf}_*[b-1]."""
    out = {}
    for stage, count in enumerate(counts):
        s = stage + 2
        if count < 2:
            continue
        for suf in ("2a", "2b", "2c"):
            for kind in ("weights", "bn_scale", "bn_offset", "bn_mean",
                         "bn_var"):
                key = "res%d_scan%s_%s" % (s, suf, kind)
                out[key] = np.stack([
                    vals["res%d_%d_branch%s_%s" % (s, b, suf, kind)]
                    for b in range(1, count)])
    return out


def test_resnet_scan_forward_parity():
    feed = _feed()
    main_u, st_u, loss_u = _build(scan=False, is_test=True)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(st_u)
    ref = float(np.asarray(exe.run(main_u, feed=feed,
                                   fetch_list=[loss_u])[0]).ravel()[0])
    vals = {}
    for name in global_scope().local_var_names():
        v = global_scope().find_var(name)
        if v is not None and hasattr(v, "shape"):
            vals[name] = np.asarray(v).copy()

    main_s, st_s, loss_s = _build(scan=True, is_test=True)
    exe2 = fluid.Executor(fluid.CPUPlace())
    exe2.run(st_s)
    import jax.numpy as jnp

    stacked = _stack_unrolled(vals)
    for name, v in {**vals, **stacked}.items():
        if global_scope().find_var(name) is not None:
            global_scope().set_var(name, jnp.asarray(v))
    got = float(np.asarray(exe2.run(main_s, feed=feed,
                                    fetch_list=[loss_s])[0]).ravel()[0])
    np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-5)


def test_resnet_scan_trains_and_updates_stats():
    main, st, loss = _build(scan=True, is_test=False)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(st)
    feed = _feed()
    ls = [float(np.asarray(exe.run(main, feed=feed,
                                   fetch_list=[loss])[0]).ravel()[0])
          for _ in range(5)]
    assert np.isfinite(ls).all()
    assert ls[-1] < ls[0], ls
    # every row (= every scanned block) of the BN running stats moved
    m = np.asarray(global_scope().find_var("res2_scan2b_bn_mean"))
    assert (np.abs(m).sum(axis=1) > 0).all(), m
    # and every stacked conv slice received gradient
    w = np.asarray(global_scope().find_var("res3_scan2b_weights"))
    main2, st2, _ = _build(scan=True, is_test=False, seed=6)
    # fresh init of the same seed for comparison
    import paddle_tpu.core.scope as sm

    old = sm._global_scope
    sm._global_scope = sm.Scope()
    try:
        exe2 = fluid.Executor(fluid.CPUPlace())
        exe2.run(st2)
        w0 = np.asarray(sm._global_scope.find_var("res3_scan2b_weights"))
    finally:
        sm._global_scope = old
    delta = np.abs(w - w0).reshape(w.shape[0], -1).max(axis=1)
    assert (delta > 0).all(), delta


def test_scan_stages_rejects_basic_blocks():
    with pytest.raises(ValueError, match="bottleneck"):
        main, st = framework.Program(), framework.Program()
        with framework.program_guard(main, st):
            with framework.unique_name_guard():
                img = fluid.layers.data("image", shape=[3, IMG, IMG],
                                        dtype="float32")
                R.resnet(img, class_dim=CLASSES, depth=18,
                         scan_stages=True)
