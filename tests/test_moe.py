"""Expert-parallel MoE (parallel/moe.py): dp x ep sharded forward must
equal the unsharded single-device computation exactly (same params,
same routing incl. capacity drops), and the sharded train step must
learn. 8 virtual CPU devices from conftest."""
import numpy as np

import jax
import jax.numpy as jnp

from paddle_tpu.parallel.moe import (MoEConfig, init_moe_params,
                                     make_moe_train_step, moe_ffn,
                                     shard_moe_params)


def test_moe_sharded_matches_unsharded():
    cfg = MoEConfig(d_model=16, d_ff=32, n_experts=4,
                    capacity_factor=1.25, dp=2, ep=4)
    mesh = cfg.mesh()
    params = init_moe_params(cfg, seed=1)
    rng = np.random.RandomState(0)
    x = rng.randn(4, 8, cfg.d_model).astype("float32")

    ref_out, ref_aux = moe_ffn(params, jnp.asarray(x), cfg)

    from jax.sharding import NamedSharding, PartitionSpec as P

    sharded_params = shard_moe_params(params, cfg, mesh)
    xs = jax.device_put(jnp.asarray(x),
                        NamedSharding(mesh, P("dp", None, None)))

    def fwd(p, v):
        return moe_ffn(p, v, cfg, mesh=mesh)

    out, aux = jax.jit(fwd)(sharded_params, xs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(float(aux), float(ref_aux), rtol=1e-5)


def test_moe_capacity_drops_tokens():
    # tiny capacity: only C tokens per expert survive; the rest output 0
    cfg = MoEConfig(d_model=8, d_ff=16, n_experts=2,
                    capacity_factor=0.25, dp=1, ep=1)
    params = init_moe_params(cfg, seed=2)
    x = jnp.asarray(np.random.RandomState(1).randn(2, 8, 8), "float32")
    out, _ = moe_ffn(params, x, cfg)
    # capacity = ceil(16 * 0.25 / 2) = 2 per expert -> at most 4 tokens
    # of 16 produce nonzero outputs
    nonzero_rows = np.count_nonzero(
        np.abs(np.asarray(out)).reshape(16, 8).sum(axis=1) > 1e-9)
    assert nonzero_rows <= 4


def test_moe_train_step_learns_on_ep_mesh():
    cfg = MoEConfig(d_model=16, d_ff=32, n_experts=4,
                    capacity_factor=2.0, dp=2, ep=4)
    mesh = cfg.mesh()
    params = shard_moe_params(init_moe_params(cfg, seed=3), cfg, mesh)
    step = make_moe_train_step(cfg, mesh)
    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.randn(4, 8, cfg.d_model), "float32")
    w_true = rng.randn(cfg.d_model, cfg.d_model).astype("float32") * 0.3
    y = jnp.asarray(np.asarray(x) @ w_true + np.asarray(x))
    losses = []
    for _ in range(40):
        params, loss = step(params, x, y, jnp.float32(0.2))
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])
    # expert weights stay sharded over 'ep'
    spec = params["w1"].sharding.spec
    assert spec[0] == "ep"
