"""Golden tests for the ragged paged attention kernel (Pallas
interpreter on the CPU test mesh) and its pure-JAX reference, against
dense `reference_attention` semantics on mixed-length batches —
including q_len=1 decode rows, GQA head groups, page-boundary
crossings and inactive (q_len=0) batch slots."""
import math

import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.pallas import (ragged_paged_attention,
                                   ragged_paged_attention_reference,
                                   reference_attention)

PAGE = 8


def _paged_setup(rng, seqs, Q, Hq, Hkv, D, npages, num_pages=None):
    """Build a paged cache holding `seqs` = [(ctx_len, q_len), ...]:
    per-seq contiguous K/V of ctx_len tokens scattered into randomly
    ordered pages, plus the dense copies for the golden check."""
    S = len(seqs)
    P = num_pages or (S * npages + 3)
    k_pages = rng.standard_normal((P, PAGE, Hkv, D)).astype(np.float32)
    v_pages = rng.standard_normal((P, PAGE, Hkv, D)).astype(np.float32)
    tables = np.zeros((S, npages), np.int32)
    perm = rng.permutation(P - 1) + 1  # page 0 stays a pad target
    dense_k = np.zeros((S, npages * PAGE, Hkv, D), np.float32)
    dense_v = np.zeros_like(dense_k)
    next_free = 0
    for s, (ctx, _) in enumerate(seqs):
        used = -(-ctx // PAGE)
        for j in range(npages):
            if j < used:
                tables[s, j] = perm[next_free]
                next_free += 1
        dense_k[s] = k_pages[tables[s]].reshape(-1, Hkv, D)
        dense_v[s] = v_pages[tables[s]].reshape(-1, Hkv, D)
    q = rng.standard_normal((S, Q, Hq, D)).astype(np.float32)
    ctx_lens = np.array([c for c, _ in seqs], np.int32)
    q_lens = np.array([q_ for _, q_ in seqs], np.int32)
    return (jnp.asarray(q), jnp.asarray(k_pages), jnp.asarray(v_pages),
            jnp.asarray(tables), jnp.asarray(ctx_lens),
            jnp.asarray(q_lens), dense_k, dense_v)


def _dense_golden(q, dense_k, dense_v, ctx_lens, q_lens):
    """Per-sequence dense causal attention over the real context via
    the flash module's golden `reference_attention`."""
    S, Q, Hq, D = q.shape
    Hkv = dense_k.shape[2]
    G = Hq // Hkv
    out = np.zeros((S, Q, Hq, D), np.float32)
    for s in range(S):
        ctx, ql = int(ctx_lens[s]), int(q_lens[s])
        if ql == 0:
            continue
        k = np.repeat(dense_k[s, :ctx], G, axis=1)  # [ctx, Hq, D]
        v = np.repeat(dense_v[s, :ctx], G, axis=1)
        qs = np.asarray(q)[s, :ql]                  # [ql, Hq, D]
        # absolute positions: the causal mask of a [ctx, ctx] problem
        # restricted to the last ql query rows
        full_q = np.zeros((ctx, Hq, D), np.float32)
        full_q[ctx - ql:] = qs
        o = reference_attention(
            jnp.asarray(full_q.transpose(1, 0, 2)[None]),
            jnp.asarray(k.transpose(1, 0, 2)[None]),
            jnp.asarray(v.transpose(1, 0, 2)[None]),
            causal=True, sm_scale=1.0 / math.sqrt(D))
        out[s, :ql] = np.asarray(o)[0].transpose(1, 0, 2)[ctx - ql:]
    return out


@pytest.mark.parametrize("impl", ["reference", "kernel"])
def test_mixed_prefill_decode_matches_dense(impl):
    """One call over a batch mixing a long prefill, a mid prefill, a
    q_len=1 decode and a page-boundary-straddling decode."""
    rng = np.random.default_rng(0)
    seqs = [(24, 24), (13, 13), (17, 1), (8, 1)]  # (ctx, q_len)
    q, kp, vp, tbl, ctx, ql, dk, dv = _paged_setup(
        rng, seqs, Q=24, Hq=2, Hkv=2, D=16, npages=4)
    out = ragged_paged_attention(q, kp, vp, tbl, ctx, ql, impl=impl)
    golden = _dense_golden(q, dk, dv, ctx, ql)
    valid = np.zeros(out.shape, bool)
    for s, (c, n) in enumerate(seqs):
        valid[s, :n] = True
    np.testing.assert_allclose(np.asarray(out)[valid], golden[valid],
                               atol=2e-5, rtol=2e-5)
    # rows past q_lens are defined zeros (padded bucket slots)
    assert not np.asarray(out)[~valid].any()


@pytest.mark.parametrize("impl", ["reference", "kernel"])
def test_decode_only_bucket(impl):
    """Pure decode (Q=1) at ragged context lengths, including a
    context that exactly fills its last page."""
    rng = np.random.default_rng(1)
    seqs = [(PAGE * 3, 1), (5, 1), (PAGE, 1), (PAGE + 1, 1)]
    q, kp, vp, tbl, ctx, ql, dk, dv = _paged_setup(
        rng, seqs, Q=1, Hq=4, Hkv=4, D=8, npages=4)
    out = ragged_paged_attention(q, kp, vp, tbl, ctx, ql, impl=impl)
    golden = _dense_golden(q, dk, dv, ctx, ql)
    np.testing.assert_allclose(np.asarray(out), golden,
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("impl", ["reference", "kernel"])
def test_gqa_grouped_heads(impl):
    """Hq=4 query heads over Hkv=2 kv heads: head h reads kv head
    h // 2 (verified against the repeated-kv dense golden)."""
    rng = np.random.default_rng(2)
    seqs = [(10, 3), (20, 1)]
    q, kp, vp, tbl, ctx, ql, dk, dv = _paged_setup(
        rng, seqs, Q=3, Hq=4, Hkv=2, D=16, npages=3)
    out = ragged_paged_attention(q, kp, vp, tbl, ctx, ql, impl=impl)
    golden = _dense_golden(q, dk, dv, ctx, ql)
    valid = np.zeros(out.shape, bool)
    valid[0, :3] = True
    valid[1, :1] = True
    np.testing.assert_allclose(np.asarray(out)[valid], golden[valid],
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("impl", ["reference", "kernel"])
def test_inactive_slot_returns_zeros(impl):
    """q_lens == 0 (an inactive bucket slot): zero output, no NaN —
    the contract the engine's padded decode buckets rely on."""
    rng = np.random.default_rng(3)
    seqs = [(12, 1), (0, 0)]
    q, kp, vp, tbl, ctx, ql, dk, dv = _paged_setup(
        rng, seqs, Q=1, Hq=2, Hkv=2, D=8, npages=2)
    out = np.asarray(ragged_paged_attention(q, kp, vp, tbl, ctx, ql,
                                            impl=impl))
    assert np.isfinite(out).all()
    assert not out[1].any()
    golden = _dense_golden(q, dk, dv, ctx, ql)
    np.testing.assert_allclose(out[0], golden[0], atol=2e-5, rtol=2e-5)


def test_kernel_matches_reference_exactly_shaped():
    """Kernel vs pure-JAX reference at identical inputs (fp32
    tolerance; the two implement one contract)."""
    rng = np.random.default_rng(4)
    seqs = [(30, 7), (3, 2), (16, 1)]
    q, kp, vp, tbl, ctx, ql, _, _ = _paged_setup(
        rng, seqs, Q=7, Hq=2, Hkv=1, D=32, npages=4)
    a = ragged_paged_attention(q, kp, vp, tbl, ctx, ql, impl="kernel")
    b = ragged_paged_attention(q, kp, vp, tbl, ctx, ql,
                               impl="reference")
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               atol=2e-5, rtol=2e-5)


def test_arg_validation():
    rng = np.random.default_rng(5)
    q, kp, vp, tbl, ctx, ql, _, _ = _paged_setup(
        rng, [(8, 1)], Q=1, Hq=3, Hkv=2, D=8, npages=2)
    with pytest.raises(ValueError, match="multiple"):
        ragged_paged_attention(q, kp, vp, tbl, ctx, ql)
    q2 = jnp.zeros((1, 1, 2, 8), jnp.float32)
    with pytest.raises(ValueError, match="context_lens"):
        ragged_paged_attention(q2, kp, vp, tbl,
                               jnp.zeros((2,), jnp.int32), None)
    with pytest.raises(ValueError, match="impl"):
        ragged_paged_attention(q2, kp, vp, tbl, ctx, impl="nope")
