"""Dataset API + DataLoader tests (reference test model:
test_dataset.py / test_multiprocess_dataloader_* in
python/paddle/fluid/tests/unittests/)."""
import os

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework


def _write_multislot(tmp_path, n_lines=32, dim=4):
    p = os.path.join(str(tmp_path), "data.txt")
    with open(p, "w") as f:
        for i in range(n_lines):
            feats = " ".join("%f" % (i + k * 0.1) for k in range(dim))
            f.write("%d %s 1 %d\n" % (dim, feats, i % 10))
    return p


def test_queue_dataset_feeds_executor(tmp_path):
    path = _write_multislot(tmp_path)
    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup):
        with framework.unique_name_guard():
            x = fluid.layers.data("x", shape=[4], dtype="float32")
            y = fluid.layers.data("y", shape=[1], dtype="int64")
            h = fluid.layers.fc(x, 16, act="relu")
            pred = fluid.layers.fc(h, 10)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(pred, y))
            fluid.optimizer.SGDOptimizer(learning_rate=0.01).minimize(loss)

            ds = fluid.DatasetFactory().create_dataset("QueueDataset")
            ds.set_batch_size(8)
            ds.set_thread(2)
            ds.set_filelist([path])
            ds.set_use_var([x, y])

            exe = fluid.Executor()
            exe.run(startup)
            out = exe.train_from_dataset(main, ds, fetch_list=[loss.name])
            assert out is not None
            assert np.isfinite(float(np.asarray(out[0])))


def test_inmemory_dataset_shuffle_and_batches(tmp_path):
    path = _write_multislot(tmp_path, n_lines=20)
    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup):
        with framework.unique_name_guard():
            x = fluid.layers.data("x", shape=[4], dtype="float32")
            y = fluid.layers.data("y", shape=[1], dtype="int64")
    ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
    ds.set_batch_size(4)
    ds.set_filelist([path])
    ds.set_use_var([x, y])
    ds.load_into_memory()
    assert ds.get_memory_data_size() == 20
    plain = [b["y"].ravel().tolist() for b in ds._iter_batches()]
    ds.local_shuffle()
    shuffled = [b["y"].ravel().tolist() for b in ds._iter_batches()]
    flat = sorted(sum(plain, []))
    assert flat == sorted(sum(shuffled, []))
    assert plain != shuffled  # shuffled order differs
    for b in ds._iter_batches():
        assert b["x"].shape == (4, 4)
        assert b["y"].shape[0] == 4


def test_dataset_ragged_slot_pads_and_keeps_lod(tmp_path):
    p = os.path.join(str(tmp_path), "ragged.txt")
    with open(p, "w") as f:
        f.write("1 7 1 0.0\n2 8 9 1 1.0\n3 1 2 3 1 2.0\n1 4 1 3.0\n")
    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup):
        with framework.unique_name_guard():
            ids = fluid.layers.data("ids", shape=[1], dtype="int64",
                                    lod_level=1)
            lab = fluid.layers.data("lab", shape=[1], dtype="float32")
    ds = fluid.DatasetFactory().create_dataset("QueueDataset")
    ds.set_batch_size(4)
    ds.set_filelist([p])
    ds.set_use_var([ids, lab])
    batches = list(ds._iter_batches())
    assert len(batches) == 1
    b = batches[0]
    assert b["ids"].shape == (4, 4)  # padded to bucket width 4
    assert b["ids.lod"].tolist() == [0, 1, 3, 6, 7]
    np.testing.assert_array_equal(b["ids"][2], [1, 2, 3, 0])
    assert b["ids"][0, 1] == 0  # padding


def test_dataset_lod_slot_uniform_batch_still_emits_lod(tmp_path):
    # schema must be keyed on the declared lod_level, not per-batch data:
    # a coincidentally-uniform batch of a sequence slot keeps its .lod
    p = os.path.join(str(tmp_path), "uniform.txt")
    with open(p, "w") as f:
        f.write("2 7 8 1 0.0\n2 9 10 1 1.0\n")
    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup):
        with framework.unique_name_guard():
            ids = fluid.layers.data("ids", shape=[1], dtype="int64",
                                    lod_level=1)
            lab = fluid.layers.data("lab", shape=[1], dtype="float32")
    ds = fluid.DatasetFactory().create_dataset("QueueDataset")
    ds.set_batch_size(2)
    ds.set_filelist([p])
    ds.set_use_var([ids, lab])
    (b,) = list(ds._iter_batches())
    assert "ids.lod" in b
    assert b["ids.lod"].tolist() == [0, 2, 4]
    assert b["ids"].shape == (2, 2)


def test_dataset_pipe_command(tmp_path):
    # pipe_command preprocesses each file before MultiSlot parsing
    p = os.path.join(str(tmp_path), "raw.txt")
    with open(p, "w") as f:
        f.write("5,0\n6,1\n7,2\n8,0\n")
    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup):
        with framework.unique_name_guard():
            x = fluid.layers.data("xs", shape=[1], dtype="float32")
            y = fluid.layers.data("ys", shape=[1], dtype="int64")
    ds = fluid.DatasetFactory().create_dataset("QueueDataset")
    ds.set_batch_size(4)
    ds.set_filelist([p])
    ds.set_use_var([x, y])
    ds.set_pipe_command(
        "awk -F, '{print \"1 \" $1 \" 1 \" $2}'")
    (b,) = list(ds._iter_batches())
    np.testing.assert_array_equal(b["xs"].ravel(), [5, 6, 7, 8])
    np.testing.assert_array_equal(b["ys"].ravel(), [0, 1, 2, 0])
    piped = list(ds._piped_files)
    assert all(os.path.exists(f) for f in piped)
    ds._cleanup_piped()
    assert not any(os.path.exists(f) for f in piped)


def test_generator_loader_propagates_reader_error():
    loader = fluid.DataLoader.from_generator(feed_list=["a"], capacity=2)

    def gen():
        yield [np.zeros((2,), "float32")]
        raise ValueError("corrupt record")

    loader.set_batch_generator(gen)
    it = iter(loader)
    next(it)
    with pytest.raises(RuntimeError, match="generator raised"):
        list(it)


class _SquareDataset:
    """Picklable map-style dataset for multiprocess workers."""

    def __len__(self):
        return 37

    def __getitem__(self, i):
        x = np.full((3,), i, dtype="float32")
        return x, np.int64(i * i)


def test_dataloader_multiprocess_matches_single_process():
    ds = _SquareDataset()
    single = list(fluid.DataLoader(ds, batch_size=5, num_workers=0))
    multi = list(fluid.DataLoader(ds, batch_size=5, num_workers=3))
    assert len(single) == len(multi) == 8
    for (xs, ys), (xm, ym) in zip(single, multi):
        np.testing.assert_array_equal(xs, xm)
        np.testing.assert_array_equal(ys, ym)


def test_dataloader_worker_error_surfaces():
    class Bad(_SquareDataset):
        def __getitem__(self, i):
            if i == 11:
                raise ValueError("boom")
            return super().__getitem__(i)

    with pytest.raises(RuntimeError, match="boom"):
        list(fluid.DataLoader(Bad(), batch_size=4, num_workers=2))


def test_batch_sampler():
    bs = fluid.BatchSampler(dataset=_SquareDataset(), batch_size=10,
                            drop_last=True)
    batches = list(bs)
    assert len(batches) == 3 == len(bs)
    assert all(len(b) == 10 for b in batches)


def test_generator_loader_prefetch():
    loader = fluid.DataLoader.from_generator(feed_list=["a", "b"],
                                             capacity=4)

    def gen():
        for i in range(6):
            yield [np.full((2, 2), i, "float32"),
                   np.full((2,), -i, "float32")]

    loader.set_batch_generator(gen)
    got = list(loader)
    assert len(got) == 6
    assert set(got[0]) == {"a", "b"}
    assert got[3]["a"][0, 0] == 3


def test_data_generator_authored_file_trains(tmp_path):
    """incubate.data_generator (VERDICT r3 missing #6): a
    MultiSlotDataGenerator-authored file feeds train_from_dataset
    through the native MultiSlot parser (data_feed.cc)."""
    import io

    from paddle_tpu.fluid.incubate.data_generator import \
        MultiSlotDataGenerator

    class MyGen(MultiSlotDataGenerator):
        def generate_sample(self, line):
            def local_iter():
                i = int(line.strip())
                feats = [float(i + k * 0.1) for k in range(4)]
                yield [("x", feats), ("y", [i % 10])]

            return local_iter

    raw = os.path.join(str(tmp_path), "raw.txt")
    with open(raw, "w") as f:
        for i in range(32):
            f.write("%d\n" % i)
    out_path = os.path.join(str(tmp_path), "slots.txt")
    gen = MyGen()
    gen.generate_file(raw, out_path)
    # slot line format: "4 <f> <f> <f> <f> 1 <label>"
    first = open(out_path).readline().split()
    assert first[0] == "4" and first[5] == "1"
    assert gen._proto_info == [("x", "float"), ("y", "uint64")]

    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup):
        with framework.unique_name_guard():
            x = fluid.layers.data("x", shape=[4], dtype="float32")
            y = fluid.layers.data("y", shape=[1], dtype="int64")
            h = fluid.layers.fc(x, 16, act="relu")
            pred = fluid.layers.fc(h, 10)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(pred, y))
            fluid.optimizer.SGDOptimizer(0.01).minimize(loss)

            ds = fluid.DatasetFactory().create_dataset("QueueDataset")
            ds.set_batch_size(8)
            ds.set_thread(1)
            ds.set_filelist([out_path])
            ds.set_use_var([x, y])

            exe = fluid.Executor()
            exe.run(startup)
            out = exe.train_from_dataset(main, ds,
                                         fetch_list=[loss.name])
            assert np.isfinite(float(np.asarray(out[0]).reshape(-1)[0]))


def test_multi_slot_string_data_generator_stdin(tmp_path):
    import io

    from paddle_tpu.fluid.incubate.data_generator import \
        MultiSlotStringDataGenerator

    class SGen(MultiSlotStringDataGenerator):
        def generate_sample(self, line):
            def local_iter():
                toks = line.split()
                yield [("words", toks), ("label", [toks[0]])]

            return local_iter

    g = SGen()
    out = io.StringIO()
    g.run_from_stdin(stdin=io.StringIO("7 8 9\n4 5\n"), out=out)
    lines = out.getvalue().strip().splitlines()
    assert lines[0] == "3 7 8 9 1 7"
    assert lines[1] == "2 4 5 1 4"


def test_data_generator_schema_mismatch_raises():
    from paddle_tpu.fluid.incubate.data_generator import \
        MultiSlotDataGenerator

    g = MultiSlotDataGenerator()
    g._gen_str([("a", [1]), ("b", [2])])
    with pytest.raises(ValueError, match="not match"):
        g._gen_str([("a", [1]), ("c", [2])])
    with pytest.raises(ValueError, match="inconsistent"):
        g._gen_str([("a", [1])])
