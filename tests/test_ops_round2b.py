"""Golden + grad tests for the round-2b ops batch: interpolation family,
RNN unit ops (dynamic_lstm/gru semantics), vision extras, and the small
math/loss additions — OpTest pattern per SURVEY.md §4.1."""
import numpy as np
import pytest

from op_test import OpTest


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


# -- interpolation ----------------------------------------------------------

def _np_linear_resize_axis(x, axis, out, align_corners, align_mode):
    in_size = x.shape[axis]
    i = np.arange(out, dtype="float64")
    if align_corners:
        src = i * (in_size - 1.0) / max(out - 1.0, 1.0)
    elif align_mode == 1:
        src = i * in_size / out
    else:
        src = (i + 0.5) * in_size / out - 0.5
    src = np.clip(src, 0, in_size - 1)
    i0 = np.floor(src).astype(int)
    i1 = np.minimum(i0 + 1, in_size - 1)
    w1 = src - i0
    g0 = np.take(x, i0, axis=axis)
    g1 = np.take(x, i1, axis=axis)
    shape = [1] * x.ndim
    shape[axis] = out
    return g0 * (1 - w1).reshape(shape) + g1 * w1.reshape(shape)


class TestBilinearInterp(OpTest):
    def test(self):
        r = np.random.RandomState(0)
        x = r.randn(2, 3, 5, 7).astype("float32")
        for ac, am in [(True, 1), (False, 0), (False, 1)]:
            self.op_type = "bilinear_interp"
            self.inputs = {"X": x}
            self.attrs = {"out_h": 9, "out_w": 4, "align_corners": ac,
                          "align_mode": am}
            e = _np_linear_resize_axis(x.astype("float64"), 2, 9, ac, am)
            e = _np_linear_resize_axis(e, 3, 4, ac, am)
            self.outputs = {"Out": e.astype("float32")}
            self.check_output()
        self.check_grad(["X"], "Out")


class TestNearestInterp(OpTest):
    def test(self):
        r = np.random.RandomState(1)
        x = r.randn(2, 2, 4, 4).astype("float32")
        self.op_type = "nearest_interp"
        self.inputs = {"X": x}
        self.attrs = {"out_h": 8, "out_w": 6, "align_corners": False}
        idh = np.floor(np.arange(8) * 4 / 8).astype(int)
        idw = np.floor(np.arange(6) * 4 / 6).astype(int)
        self.outputs = {"Out": x[:, :, idh][:, :, :, idw]}
        self.check_output()


class TestTrilinearInterp(OpTest):
    def test(self):
        r = np.random.RandomState(2)
        x = r.randn(1, 2, 3, 4, 5).astype("float32")
        self.op_type = "trilinear_interp"
        self.inputs = {"X": x}
        self.attrs = {"out_d": 6, "out_h": 2, "out_w": 7,
                      "align_corners": True}
        e = x.astype("float64")
        for ax, o in ((2, 6), (3, 2), (4, 7)):
            e = _np_linear_resize_axis(e, ax, o, True, 1)
        self.outputs = {"Out": e.astype("float32")}
        self.check_output()
        self.check_grad(["X"], "Out")


class TestBicubicUpscaleExact(OpTest):
    def test(self):
        # identity resize must reproduce the input exactly
        r = np.random.RandomState(3)
        x = r.randn(1, 1, 5, 5).astype("float32")
        self.op_type = "bicubic_interp"
        self.inputs = {"X": x}
        self.attrs = {"out_h": 5, "out_w": 5, "align_corners": True}
        self.outputs = {"Out": x}
        self.check_output()
        self.attrs = {"out_h": 10, "out_w": 10, "align_corners": False}
        out = self._run_forward()["Out"][0]
        assert out.shape == (1, 1, 10, 10)
        self.check_grad_shapes_only = True


class TestLinearInterp(OpTest):
    def test(self):
        r = np.random.RandomState(4)
        x = r.randn(2, 3, 6).astype("float32")
        self.op_type = "linear_interp"
        self.inputs = {"X": x}
        self.attrs = {"out_w": 11, "align_corners": True}
        e = _np_linear_resize_axis(x.astype("float64"), 2, 11, True, 1)
        self.outputs = {"Out": e.astype("float32")}
        self.check_output()


# -- rnn units --------------------------------------------------------------

class TestLstmUnit(OpTest):
    def test(self):
        r = np.random.RandomState(5)
        b, d = 4, 6
        x = r.randn(b, 4 * d).astype("float32")
        c_prev = r.randn(b, d).astype("float32")
        self.op_type = "lstm_unit"
        self.inputs = {"X": x, "C_prev": c_prev}
        self.attrs = {"forget_bias": 0.5}
        i = _sigmoid(x[:, :d])
        f = _sigmoid(x[:, d:2 * d] + 0.5)
        o = _sigmoid(x[:, 2 * d:3 * d])
        g = np.tanh(x[:, 3 * d:])
        c = f * c_prev + i * g
        self.outputs = {"C": c, "H": o * np.tanh(c)}
        self.check_output()
        self.check_grad(["X", "C_prev"], "H")


def _np_dynamic_lstm(x, w, bias, b, t, d, use_peep):
    """Reference lstm_kernel.h recurrence: gates [cand, i, f, o]."""
    ck_i = bias[4 * d:5 * d] if use_peep else np.zeros(d)
    ck_f = bias[5 * d:6 * d] if use_peep else np.zeros(d)
    ck_o = bias[6 * d:7 * d] if use_peep else np.zeros(d)
    h = np.zeros((b, d))
    c = np.zeros((b, d))
    hs, cs = [], []
    for step in range(t):
        gates = x[:, step] + bias[None, :4 * d] + h @ w
        cand = np.tanh(gates[:, :d])
        i = _sigmoid(gates[:, d:2 * d] + c * ck_i)
        f = _sigmoid(gates[:, 2 * d:3 * d] + c * ck_f)
        c = cand * i + c * f
        o = _sigmoid(gates[:, 3 * d:] + c * ck_o)
        h = o * np.tanh(c)
        hs.append(h)
        cs.append(c)
    return np.stack(hs, 1), np.stack(cs, 1)


class TestDynamicLstm(OpTest):
    def test(self):
        r = np.random.RandomState(6)
        b, t, d = 2, 3, 3
        x = r.randn(b, t, 4 * d).astype("float32")
        w = (r.randn(d, 4 * d) * 0.1).astype("float32")
        bias = (r.randn(7 * d) * 0.1).astype("float32")
        self.op_type = "lstm"
        self.inputs = {"Input": x, "Weight": w, "Bias": bias}
        self.attrs = {"use_peepholes": True}
        hs, cs = _np_dynamic_lstm(x.astype("float64"), w.astype("float64"),
                                  bias.astype("float64"), b, t, d, True)
        self.outputs = {"Hidden": hs.astype("float32"),
                        "Cell": cs.astype("float32")}
        self.check_output(atol=1e-4)
        self.check_grad(["Input", "Weight"], "Hidden")


class TestDynamicGru(OpTest):
    def test(self):
        r = np.random.RandomState(7)
        b, t, d = 2, 3, 2
        x = r.randn(b, t, 3 * d).astype("float32")
        w = (r.randn(d, 3 * d) * 0.2).astype("float32")
        self.op_type = "gru"
        self.inputs = {"Input": x, "Weight": w}
        self.attrs = {"origin_mode": False}
        h = np.zeros((b, d))
        hs = []
        for step in range(t):
            xg = x[:, step].astype("float64")
            ur = _sigmoid(xg[:, :2 * d] + h @ w[:, :2 * d])
            u, rr = ur[:, :d], ur[:, d:]
            cand = np.tanh(xg[:, 2 * d:] + (rr * h) @ w[:, 2 * d:])
            h = (1 - u) * h + u * cand
            hs.append(h)
        self.outputs = {"Hidden": np.stack(hs, 1).astype("float32")}
        self.check_output(atol=1e-4, no_check_set=(
            "BatchGate", "BatchResetHiddenPrev", "BatchHidden"))
        self.check_grad(["Input", "Weight"], "Hidden")


class TestGruUnit(OpTest):
    def test(self):
        r = np.random.RandomState(8)
        b, d = 3, 4
        xg = r.randn(b, 3 * d).astype("float32")
        h_prev = r.randn(b, d).astype("float32")
        w = (r.randn(d, 3 * d) * 0.2).astype("float32")
        self.op_type = "gru_unit"
        self.inputs = {"Input": xg, "HiddenPrev": h_prev, "Weight": w}
        self.attrs = {"origin_mode": True}
        ur = _sigmoid(xg[:, :2 * d] + h_prev @ w[:, :2 * d])
        u, rr = ur[:, :d], ur[:, d:]
        cand = np.tanh(xg[:, 2 * d:] + (rr * h_prev) @ w[:, 2 * d:])
        h = u * h_prev + (1 - u) * cand
        self.outputs = {"Hidden": h}
        self.check_output(atol=1e-4, no_check_set=("Gate",
                                                   "ResetHiddenPrev"))
        self.check_grad(["Input", "HiddenPrev", "Weight"], "Hidden")


class TestCudnnLstmShapes(OpTest):
    def test(self):
        r = np.random.RandomState(9)
        t, b, d, h, layers = 5, 2, 3, 4, 2
        x = r.randn(t, b, d).astype("float32")
        n_dir = 2
        sz = 0
        d_cur = d
        for _ in range(layers):
            sz += n_dir * (4 * h * d_cur + 4 * h * h + 8 * h)
            d_cur = h * n_dir
        w = (r.randn(sz) * 0.1).astype("float32")
        self.op_type = "cudnn_lstm"
        self.inputs = {"Input": x, "W": w}
        self.attrs = {"hidden_size": h, "num_layers": layers,
                      "is_bidirec": True}
        outs = self._run_forward()
        assert np.asarray(outs["Out"][0]).shape == (t, b, 2 * h)
        assert np.asarray(outs["last_h"][0]).shape == (4, b, h)
        assert np.all(np.isfinite(np.asarray(outs["Out"][0])))
        self.check_grad(["Input"], "Out", max_relative_error=0.01)


class TestLstmp(OpTest):
    def test(self):
        r = np.random.RandomState(10)
        b, t, d, p = 1, 2, 3, 2
        x = r.randn(b, t, 4 * d).astype("float32")
        w = (r.randn(p, 4 * d) * 0.1).astype("float32")
        wp = (r.randn(d, p) * 0.1).astype("float32")
        bias = (r.randn(4 * d) * 0.1).astype("float32")
        self.op_type = "lstmp"
        self.inputs = {"Input": x, "Weight": w, "ProjWeight": wp,
                       "Bias": bias}
        self.attrs = {"use_peepholes": False}
        outs = self._run_forward()
        assert np.asarray(outs["Projection"][0]).shape == (b, t, p)
        assert np.asarray(outs["Cell"][0]).shape == (b, t, d)
        self.check_grad(["Input", "Weight", "ProjWeight"], "Projection")


# -- vision extras ----------------------------------------------------------

class TestUnpoolRoundTrip(OpTest):
    def test(self):
        r = np.random.RandomState(11)
        x = r.randn(2, 3, 8, 8).astype("float32")
        from paddle_tpu import ops as ops_lib
        import jax.numpy as jnp
        pooled = ops_lib.run_op(
            "max_pool2d_with_index", {"X": [jnp.asarray(x)]},
            {"ksize": [2, 2], "strides": [2, 2], "paddings": [0, 0]})
        out, mask = pooled["Out"][0], pooled["Mask"][0]
        self.op_type = "unpool"
        self.inputs = {"X": np.asarray(out), "Indices": np.asarray(mask)}
        self.attrs = {"unpooled_height": 8, "unpooled_width": 8}
        res = self._run_forward()["Out"][0]
        res = np.asarray(res)
        # every pooled max value must land back at its argmax position
        assert res.shape == x.shape
        assert np.isclose(res.max(), x.max())
        assert np.count_nonzero(res) == 2 * 3 * 4 * 4


class TestMaxPool3DWithIndex(OpTest):
    def test(self):
        r = np.random.RandomState(12)
        x = r.randn(1, 2, 4, 4, 4).astype("float32")
        self.op_type = "max_pool3d_with_index"
        self.inputs = {"X": x}
        self.attrs = {"ksize": [2, 2, 2], "strides": [2, 2, 2],
                      "paddings": [0, 0, 0]}
        e = x.reshape(1, 2, 2, 2, 2, 2, 2, 2).transpose(
            0, 1, 2, 4, 6, 3, 5, 7).reshape(1, 2, 2, 2, 2, 8).max(-1)
        self.outputs = {"Out": e}
        self.check_output(no_check_set=("Mask",))


class TestDepthwiseConv2DTranspose(OpTest):
    def test(self):
        r = np.random.RandomState(13)
        x = r.randn(1, 3, 5, 5).astype("float32")
        w = r.randn(3, 1, 3, 3).astype("float32")
        self.op_type = "depthwise_conv2d_transpose"
        self.inputs = {"Input": x, "Filter": w}
        self.attrs = {"strides": [2, 2], "paddings": [1, 1],
                      "groups": 3}
        # golden: per-channel scipy-style transposed conv
        e = np.zeros((1, 3, 11, 11), "float64")
        for c in range(3):
            for i in range(5):
                for j in range(5):
                    e[0, c, i * 2:i * 2 + 3, j * 2:j * 2 + 3] += \
                        x[0, c, i, j] * w[c, 0]
        e = e[:, :, 1:-1, 1:-1]
        self.outputs = {"Output": e.astype("float32")}
        self.check_output(atol=1e-4)
        self.check_grad(["Input", "Filter"], "Output")


class TestConvShift(OpTest):
    def test(self):
        r = np.random.RandomState(14)
        b, n, m = 2, 7, 3
        x = r.randn(b, n).astype("float32")
        y = r.randn(b, m).astype("float32")
        self.op_type = "conv_shift"
        self.inputs = {"X": x, "Y": y}
        e = np.zeros((b, n))
        for bb in range(b):
            for j in range(n):
                for k in range(m):
                    e[bb, j] += x[bb, (j + k - m // 2) % n] * y[bb, k]
        self.outputs = {"Out": e.astype("float32")}
        self.check_output()
        self.check_grad(["X", "Y"], "Out")


class TestDeformableConvIdentityOffsets(OpTest):
    def test(self):
        """Zero offsets + unit mask must equal a plain convolution."""
        r = np.random.RandomState(15)
        x = r.randn(1, 2, 4, 4).astype("float32")
        w = r.randn(3, 2, 3, 3).astype("float32")
        off = np.zeros((1, 2 * 9, 4, 4), "float32")
        mask = np.ones((1, 9, 4, 4), "float32")
        self.op_type = "deformable_conv"
        self.inputs = {"Input": x, "Offset": off, "Mask": mask,
                       "Filter": w}
        self.attrs = {"strides": [1, 1], "paddings": [1, 1],
                      "dilations": [1, 1], "groups": 1,
                      "deformable_groups": 1}
        import jax.numpy as jnp
        from paddle_tpu import ops as ops_lib
        ref = ops_lib.run_op(
            "conv2d", {"Input": [jnp.asarray(x)], "Filter": [jnp.asarray(w)]},
            {"strides": [1, 1], "paddings": [1, 1], "dilations": [1, 1],
             "groups": 1})["Output"][0]
        self.outputs = {"Output": np.asarray(ref)}
        self.check_output(atol=1e-4)
        self.check_grad(["Input", "Filter"], "Output",
                        max_relative_error=0.05)


class TestPrRoiPoolConstant(OpTest):
    def test(self):
        """On a constant feature map every PrRoI bin must equal the
        constant (the integral is exact)."""
        x = np.full((1, 2, 8, 8), 3.5, "float32")
        rois = np.array([[1.0, 1.0, 6.0, 6.0]], "float32")
        self.op_type = "prroi_pool"
        self.inputs = {"X": x, "ROIs": rois}
        self.attrs = {"pooled_height": 2, "pooled_width": 2,
                      "spatial_scale": 1.0}
        self.outputs = {"Out": np.full((1, 2, 2, 2), 3.5, "float32")}
        self.check_output(atol=1e-4)
        # batched: second image is a different constant; BatchRoINums
        # routes each ROI to its image (reference prroi_pool_op.h:282)
        x2 = np.concatenate([x, np.full((1, 2, 8, 8), -1.25, "float32")])
        rois2 = np.array([[1.0, 1.0, 6.0, 6.0],
                          [1.0, 1.0, 6.0, 6.0]], "float32")
        self.inputs = {"X": x2, "ROIs": rois2,
                       "BatchRoINums": np.array([1, 1], "int64")}
        self.outputs = {"Out": np.stack(
            [np.full((2, 2, 2), 3.5, "float32"),
             np.full((2, 2, 2), -1.25, "float32")])}
        self.check_output(atol=1e-4)


class TestPsRoiPool(OpTest):
    def test(self):
        r = np.random.RandomState(16)
        oc, ph, pw = 2, 2, 2
        x = r.randn(1, oc * ph * pw, 8, 8).astype("float32")
        rois = np.array([[0.0, 0.0, 7.0, 7.0]], "float32")
        self.op_type = "psroi_pool"
        self.inputs = {"X": x, "ROIs": rois}
        self.attrs = {"pooled_height": ph, "pooled_width": pw,
                      "output_channels": oc, "spatial_scale": 1.0}
        out = np.asarray(self._run_forward()["Out"][0])
        assert out.shape == (1, oc, ph, pw)
        xs = x.reshape(oc, ph, pw, 8, 8)
        # bin (0,0) of channel k pools xs[k,0,0][:4,:4]
        np.testing.assert_allclose(out[0, 1, 0, 0],
                                   xs[1, 0, 0][:4, :4].mean(),
                                   rtol=1e-4)


@pytest.mark.slow
class TestBilateralSlice(OpTest):
    def test(self):
        r = np.random.RandomState(17)
        n, ci, h, w = 1, 2, 4, 4
        co, gd, gh, gw = 1, 2, 2, 2
        x = r.rand(n, ci, h, w).astype("float32")
        grid = r.randn(n, co * (ci + 1), gd, gh, gw).astype("float32")
        # keep guide*gd away from half-integers: the trilinear hat has a
        # kink there and central differences would straddle it
        guide = ((r.randint(0, gd, (n, h, w))
                  + r.uniform(0.15, 0.35, (n, h, w))) / gd).astype("float32")
        self.op_type = "bilateral_slice"
        self.inputs = {"X": x, "Grid": grid, "Guide": guide}
        self.attrs = {"has_offset": True}
        out = np.asarray(self._run_forward()["Out"][0])
        assert out.shape == (n, co, h, w)
        assert np.all(np.isfinite(out))
        self.check_grad(["X", "Grid", "Guide"], "Out",
                        max_relative_error=0.05)


# -- small math/loss additions ----------------------------------------------

class TestMinus(OpTest):
    def test(self):
        r = np.random.RandomState(18)
        x, y = r.randn(3, 4).astype("float32"), r.randn(3, 4).astype("float32")
        self.op_type = "minus"
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": x - y}
        self.check_output()
        self.check_grad(["X", "Y"], "Out")


class TestL1Norm(OpTest):
    def test(self):
        r = np.random.RandomState(19)
        x = (np.where(r.rand(5, 3) < 0.5, -1.0, 1.0)
             * r.uniform(0.5, 1.5, (5, 3))).astype("float32")
        self.op_type = "l1_norm"
        self.inputs = {"X": x}
        self.outputs = {"Out": np.abs(x).sum()}
        self.check_output()
        self.check_grad(["X"], "Out")


class TestFrobeniusNorm(OpTest):
    def test(self):
        r = np.random.RandomState(20)
        x = r.randn(4, 5).astype("float32")
        self.op_type = "frobenius_norm"
        self.inputs = {"X": x}
        self.attrs = {"dim": [0, 1], "keep_dim": False}
        self.outputs = {"Out": np.sqrt((x * x).sum())}
        self.check_output()
        self.check_grad(["X"], "Out")


class TestDist(OpTest):
    def test(self):
        r = np.random.RandomState(21)
        x = r.randn(3, 4).astype("float32")
        y = r.randn(3, 4).astype("float32")
        self.op_type = "dist"
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"p": 2.0}
        self.outputs = {"Out": np.linalg.norm((x - y).ravel(), 2)}
        self.check_output()
        self.check_grad(["X", "Y"], "Out")


class TestBceLoss(OpTest):
    def test(self):
        r = np.random.RandomState(22)
        x = r.uniform(0.05, 0.95, (6, 3)).astype("float32")
        label = r.randint(0, 2, (6, 3)).astype("float32")
        self.op_type = "bce_loss"
        self.inputs = {"X": x, "Label": label}
        self.outputs = {"Out": -(label * np.log(x)
                                 + (1 - label) * np.log(1 - x))}
        self.check_output(atol=1e-4)
        self.check_grad(["X"], "Out")


class TestNllLoss(OpTest):
    def test(self):
        r = np.random.RandomState(23)
        x = np.log(r.dirichlet(np.ones(5), 8)).astype("float32")
        label = r.randint(0, 5, (8,)).astype("int64")
        self.op_type = "nll_loss"
        self.inputs = {"X": x, "Label": label}
        self.attrs = {"reduction": "mean"}
        e = -x[np.arange(8), label].mean()
        self.outputs = {"Out": np.float32(e)}
        self.check_output(atol=1e-5, no_check_set=("Total_weight",))
        self.check_grad(["X"], "Out")
