"""Profiler chrome-trace export + per-op summary (reference:
tools/timeline.py:32, profiler.proto) and fleet 2.0 meta-optimizer
composition (reference: fleet/base/strategy_compiler.py)."""
import json
import os

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework, profiler


def test_chrome_trace_export(tmp_path):
    profiler.reset_profiler()
    with profiler.profiler(sorted_key="total",
                           profile_path=str(tmp_path)):
        with profiler.RecordEvent("step"):
            with profiler.RecordEvent("forward"):
                np.dot(np.ones((64, 64)), np.ones((64, 64)))
            with profiler.RecordEvent("backward"):
                pass
    trace = os.path.join(str(tmp_path), "paddle_tpu_trace.json")
    assert os.path.exists(trace)
    data = json.load(open(trace))
    names = [e["name"] for e in data["traceEvents"]]
    assert "step" in names and "forward" in names
    for e in data["traceEvents"]:
        if e["ph"] == "M":   # metadata (process_name) records
            continue
        assert e["ph"] == "X" and "ts" in e and "dur" in e

    rows = profiler.profiler_summary_rows()
    byname = {r[0]: r for r in rows}
    assert byname["step"][1] == 1  # calls
    assert byname["step"][2] >= byname["forward"][2]  # total ms ordering


def test_meta_optimizer_composition():
    from paddle_tpu import fleet as fleet_mod
    from paddle_tpu.fleet.meta_optimizers import compose

    st = fleet_mod.DistributedStrategy()
    st.recompute = True
    st.recompute_configs = {"checkpoints": ["x"]}
    st.gradient_merge = True
    st.gradient_merge_configs = {"k_steps": 2, "avg": True}
    st.amp = True
    st.amp_configs = {"use_dynamic_loss_scaling": False}
    opt, applied = compose(st, fluid.optimizer.AdamOptimizer(1e-3))
    assert applied == ["recompute", "gradient_merge", "amp"], applied
    # composition order: amp outermost, then gradient_merge, recompute
    inner1 = opt._optimizer if hasattr(opt, "_optimizer") else \
        opt.inner_optimizer
    assert type(opt).__name__ == "OptimizerWithMixedPrecision"


def test_meta_optimizer_lamb_swap():
    from paddle_tpu import fleet as fleet_mod
    from paddle_tpu.fleet.meta_optimizers import compose

    st = fleet_mod.DistributedStrategy()
    st.lamb = True
    base = fluid.optimizer.AdamOptimizer(2e-3, beta1=0.8)
    opt, applied = compose(st, base)
    assert applied == ["lamb"]
    assert type(opt).__name__ == "LambOptimizer"
    assert opt._beta1 == 0.8
    assert opt._learning_rate == 2e-3
