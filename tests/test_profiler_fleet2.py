"""Profiler chrome-trace export + per-op summary (reference:
tools/timeline.py:32, profiler.proto) and fleet 2.0 meta-optimizer
composition (reference: fleet/base/strategy_compiler.py)."""
import json
import os

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework, profiler


def test_chrome_trace_export(tmp_path):
    profiler.reset_profiler()
    with profiler.profiler(sorted_key="total",
                           profile_path=str(tmp_path)):
        with profiler.RecordEvent("step"):
            with profiler.RecordEvent("forward"):
                np.dot(np.ones((64, 64)), np.ones((64, 64)))
            with profiler.RecordEvent("backward"):
                pass
    trace = os.path.join(str(tmp_path), "paddle_tpu_trace.json")
    assert os.path.exists(trace)
    data = json.load(open(trace))
    names = [e["name"] for e in data["traceEvents"]]
    assert "step" in names and "forward" in names
    for e in data["traceEvents"]:
        if e["ph"] == "M":   # metadata (process_name) records
            continue
        assert e["ph"] == "X" and "ts" in e and "dur" in e

    rows = profiler.profiler_summary_rows()
    byname = {r[0]: r for r in rows}
    assert byname["step"][1] == 1  # calls
    assert byname["step"][2] >= byname["forward"][2]  # total ms ordering


def test_meta_optimizer_composition():
    from paddle_tpu import fleet as fleet_mod
    from paddle_tpu.fleet.meta_optimizers import compose

    st = fleet_mod.DistributedStrategy()
    st.recompute = True
    st.recompute_configs = {"checkpoints": ["x"]}
    st.gradient_merge = True
    st.gradient_merge_configs = {"k_steps": 2, "avg": True}
    st.amp = True
    st.amp_configs = {"use_dynamic_loss_scaling": False}
    opt, applied = compose(st, fluid.optimizer.AdamOptimizer(1e-3))
    assert applied == ["recompute", "gradient_merge", "amp"], applied
    # composition order: amp outermost, then gradient_merge, recompute
    inner1 = opt._optimizer if hasattr(opt, "_optimizer") else \
        opt.inner_optimizer
    assert type(opt).__name__ == "OptimizerWithMixedPrecision"


def test_meta_optimizer_lamb_swap():
    from paddle_tpu import fleet as fleet_mod
    from paddle_tpu.fleet.meta_optimizers import compose

    st = fleet_mod.DistributedStrategy()
    st.lamb = True
    base = fluid.optimizer.AdamOptimizer(2e-3, beta1=0.8)
    opt, applied = compose(st, base)
    assert applied == ["lamb"]
    assert type(opt).__name__ == "LambOptimizer"
    assert opt._beta1 == 0.8
    assert opt._learning_rate == 2e-3


def test_strategy_conflict_resolution():
    """StrategyCompiler zeroes conflicting knobs loudly (VERDICT r2
    weak #7; reference: each meta-optimizer's _disable_strategy)."""
    import warnings

    import paddle_tpu.fluid as fluid
    from paddle_tpu.fleet import DistributedStrategy
    from paddle_tpu.fleet.meta_optimizers import (compose,
                                                  resolve_conflicts)

    st = DistributedStrategy()
    st.localsgd = True
    st.dgc = True
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        disabled = resolve_conflicts(st)
    assert disabled == ["dgc"] and st.dgc is False and st.localsgd
    assert any("dgc disabled" in str(x.message) for x in w)

    st2 = DistributedStrategy()
    st2.pipeline = True
    st2.pipeline_configs = {"micro_batch": 2}
    st2.recompute = True
    st2.recompute_configs = {"checkpoints": ["x"]}
    opt, applied = compose(st2, fluid.optimizer.SGDOptimizer(0.1))
    assert "pipeline" in applied and "recompute" not in applied
    assert st2.recompute is False


def test_strategy_prototxt_roundtrip(tmp_path):
    from paddle_tpu.fleet import DistributedStrategy

    st = DistributedStrategy()
    st.amp = True
    st.gradient_merge = True
    st.gradient_merge_configs = {"k_steps": 4, "avg": False}
    p = str(tmp_path / "strategy.prototxt")
    st.save_to_prototxt(p)
    text = open(p).read()
    assert "amp: True" in text and "gradient_merge_configs {" in text

    st2 = DistributedStrategy().load_from_prototxt(p)
    assert st2.amp is True and st2.gradient_merge is True
    assert st2.gradient_merge_configs == {"k_steps": 4, "avg": False}
    assert st2.pipeline is False
