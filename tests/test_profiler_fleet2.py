"""Profiler chrome-trace export + per-op summary (reference:
tools/timeline.py:32, profiler.proto) and fleet 2.0 meta-optimizer
composition (reference: fleet/base/strategy_compiler.py)."""
import json
import os

import numpy as np
import pytest

pytestmark = pytest.mark.dist

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework, profiler


@pytest.mark.slow
def test_chrome_trace_export(tmp_path):
    profiler.reset_profiler()
    with profiler.profiler(sorted_key="total",
                           profile_path=str(tmp_path)):
        with profiler.RecordEvent("step"):
            with profiler.RecordEvent("forward"):
                np.dot(np.ones((64, 64)), np.ones((64, 64)))
            with profiler.RecordEvent("backward"):
                pass
    trace = os.path.join(str(tmp_path), "paddle_tpu_trace.json")
    assert os.path.exists(trace)
    data = json.load(open(trace))
    names = [e["name"] for e in data["traceEvents"]]
    assert "step" in names and "forward" in names
    for e in data["traceEvents"]:
        if e["ph"] == "M":   # metadata (process_name) records
            continue
        assert e["ph"] == "X" and "ts" in e and "dur" in e

    rows = profiler.profiler_summary_rows()
    byname = {r[0]: r for r in rows}
    assert byname["step"][1] == 1  # calls
    assert byname["step"][2] >= byname["forward"][2]  # total ms ordering


def test_meta_optimizer_composition():
    from paddle_tpu import fleet as fleet_mod
    from paddle_tpu.fleet.meta_optimizers import compose

    st = fleet_mod.DistributedStrategy()
    st.recompute = True
    st.recompute_configs = {"checkpoints": ["x"]}
    st.gradient_merge = True
    st.gradient_merge_configs = {"k_steps": 2, "avg": True}
    st.amp = True
    st.amp_configs = {"use_dynamic_loss_scaling": False}
    opt, applied = compose(st, fluid.optimizer.AdamOptimizer(1e-3))
    assert applied == ["recompute", "gradient_merge", "amp"], applied
    # composition order: amp outermost, then gradient_merge, recompute
    inner1 = opt._optimizer if hasattr(opt, "_optimizer") else \
        opt.inner_optimizer
    assert type(opt).__name__ == "OptimizerWithMixedPrecision"


def test_meta_optimizer_lamb_swap():
    from paddle_tpu import fleet as fleet_mod
    from paddle_tpu.fleet.meta_optimizers import compose

    st = fleet_mod.DistributedStrategy()
    st.lamb = True
    base = fluid.optimizer.AdamOptimizer(2e-3, beta1=0.8)
    opt, applied = compose(st, base)
    assert applied == ["lamb"]
    assert type(opt).__name__ == "LambOptimizer"
    assert opt._beta1 == 0.8
    assert opt._learning_rate == 2e-3


def test_strategy_conflict_resolution():
    """StrategyCompiler zeroes conflicting knobs loudly (VERDICT r2
    weak #7; reference: each meta-optimizer's _disable_strategy)."""
    import warnings

    import paddle_tpu.fluid as fluid
    from paddle_tpu.fleet import DistributedStrategy
    from paddle_tpu.fleet.meta_optimizers import (compose,
                                                  resolve_conflicts)

    st = DistributedStrategy()
    st.localsgd = True
    st.dgc = True
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        disabled = resolve_conflicts(st)
    assert disabled == ["dgc"] and st.dgc is False and st.localsgd
    assert any("dgc disabled" in str(x.message) for x in w)

    st2 = DistributedStrategy()
    st2.pipeline = True
    st2.pipeline_configs = {"micro_batch": 2}
    st2.recompute = True
    st2.recompute_configs = {"checkpoints": ["x"]}
    opt, applied = compose(st2, fluid.optimizer.SGDOptimizer(0.1))
    assert "pipeline" in applied and "recompute" not in applied
    assert st2.recompute is False


def test_strategy_prototxt_roundtrip(tmp_path):
    from paddle_tpu.fleet import DistributedStrategy

    st = DistributedStrategy()
    st.amp = True
    st.gradient_merge = True
    st.gradient_merge_configs = {"k_steps": 4, "avg": False}
    p = str(tmp_path / "strategy.prototxt")
    st.save_to_prototxt(p)
    text = open(p).read()
    # REAL protobuf text format (VERDICT r3 weak #3): lowercase bools,
    # not Python reprs
    assert "amp: true" in text and "gradient_merge_configs {" in text
    assert "avg: false" in text
    assert "True" not in text

    st2 = DistributedStrategy().load_from_prototxt(p)
    assert st2.amp is True and st2.gradient_merge is True
    assert st2.gradient_merge_configs == {"k_steps": 4, "avg": False}
    assert st2.pipeline is False


def test_strategy_reads_reference_style_prototxt(tmp_path):
    """A prototxt written by the reference's protobuf-backed strategy
    (distributed_strategy.proto field set, proto text rules: lowercase
    bools, quoted strings, repeated fields as repeated lines) parses."""
    from paddle_tpu.fleet import DistributedStrategy

    p = str(tmp_path / "ref.prototxt")
    with open(p, "w") as f:
        f.write(
            "amp: true\n"
            "recompute: true\n"
            "recompute_configs {\n"
            '  checkpoints: "fc_0.tmp_0"\n'
            '  checkpoints: "fc_1.tmp_0"\n'
            "}\n"
            "localsgd: false\n"
            "nccl_comm_num: 2\n"
        )
    st = DistributedStrategy().load_from_prototxt(p)
    assert st.amp is True and st.recompute is True
    assert st.localsgd is False and st.nccl_comm_num == 2
    assert st.recompute_configs["checkpoints"] == [
        "fc_0.tmp_0", "fc_1.tmp_0"]


def test_strategy_prototxt_legacy_repr_still_reads(tmp_path):
    """Round-3 files wrote Python reprs (True, 'str'); keep reading."""
    from paddle_tpu.fleet import DistributedStrategy

    p = str(tmp_path / "legacy.prototxt")
    with open(p, "w") as f:
        f.write("amp: True\nnccl_comm_num: 3\n")
    st = DistributedStrategy().load_from_prototxt(p)
    assert st.amp is True and st.nccl_comm_num == 3


def test_strategy_prototxt_parses_with_protobuf(tmp_path):
    """Our writer's output must be accepted by protobuf's own
    text_format parser for a message with the same field shapes."""
    from google.protobuf import descriptor_pb2, descriptor_pool
    from google.protobuf import message_factory, text_format

    from paddle_tpu.fleet import DistributedStrategy

    st = DistributedStrategy()
    st.amp = True
    st.recompute = True
    st.recompute_configs = {"checkpoints": ["a", "b"]}
    p = str(tmp_path / "st.prototxt")
    st.save_to_prototxt(p)
    # keep only the fields the probe message declares: the writer dumps
    # every knob; the proto-validity property is per-line
    wanted, inside = [], False
    for ln in open(p).read().splitlines():
        if ln.startswith("recompute_configs {"):
            inside = True
            wanted.append(ln)
        elif inside:
            wanted.append(ln)
            if ln.strip() == "}":
                inside = False
        elif ln.startswith(("amp:", "recompute:")):
            wanted.append(ln)

    fd = descriptor_pb2.FileDescriptorProto()
    fd.name = "probe.proto"
    fd.package = "probe"
    msg = fd.message_type.add()
    msg.name = "RC"
    f = msg.field.add()
    f.name = "checkpoints"
    f.number = 1
    f.type = f.TYPE_STRING
    f.label = f.LABEL_REPEATED
    top = fd.message_type.add()
    top.name = "Strategy"
    for i, nm in enumerate(("amp", "recompute"), start=1):
        f = top.field.add()
        f.name = nm
        f.number = i
        f.type = f.TYPE_BOOL
        f.label = f.LABEL_OPTIONAL
    f = top.field.add()
    f.name = "recompute_configs"
    f.number = 3
    f.type = f.TYPE_MESSAGE
    f.type_name = ".probe.RC"
    f.label = f.LABEL_OPTIONAL
    pool = descriptor_pool.DescriptorPool()
    pool.Add(fd)
    cls = message_factory.GetMessageClass(
        pool.FindMessageTypeByName("probe.Strategy"))
    parsed = text_format.Parse("\n".join(wanted), cls())
    assert parsed.amp is True and parsed.recompute is True
    assert list(parsed.recompute_configs.checkpoints) == ["a", "b"]


def test_fleet_metrics_aggregate_two_ranks():
    """fleet.metrics helpers aggregate across trainers via the host
    collective tier (reference: fleet_util.py:186/:1268 MPI allreduce
    semantics); 2 ranks in threads, rank 0 hosts the store."""
    import threading

    import numpy as np

    from paddle_tpu.distributed.host_collectives import \
        HostCollectiveGroup
    from paddle_tpu.fleet import metrics

    results = {}

    def worker(rank, port_holder, barrier):
        if rank == 0:
            g = HostCollectiveGroup(0, 2, "127.0.0.1:0")
            port_holder["port"] = g._client._ep.rsplit(":", 1)[1] \
                if hasattr(g._client, "_ep") else g._server.port
            barrier.set()
        else:
            barrier.wait(10)
            g = HostCollectiveGroup(
                1, 2, "127.0.0.1:%s" % port_holder["port"])
        # local stats: rank0 has 3 correct of 5; rank1 has 2 of 5
        correct = np.asarray([3.0 + rank * -1.0])
        total = np.asarray([5.0])
        results[(rank, "acc")] = metrics.acc(correct, total, util=g)
        results[(rank, "sum")] = float(
            metrics.sum(np.asarray([float(rank + 1)]), util=g))
        # auc buckets: rank-split halves of one global distribution
        pos = np.asarray([0.0, 1.0 + rank, 2.0])
        neg = np.asarray([2.0, 1.0, 0.0 + rank])
        results[(rank, "auc")] = metrics.auc(pos, neg, util=g)
        results[(rank, "mae")] = metrics.mae(
            np.asarray([2.0]), np.asarray([5.0]), util=g)
        g.shutdown() if rank else None

    holder, ev = {}, threading.Event()
    t0 = threading.Thread(target=worker, args=(0, holder, ev))
    t1 = threading.Thread(target=worker, args=(1, holder, ev))
    t0.start()
    t1.start()
    t0.join(30)
    t1.join(30)
    assert results[(0, "acc")] == results[(1, "acc")] == 0.5  # 5/10
    assert results[(0, "sum")] == results[(1, "sum")] == 3.0  # 1+2
    assert results[(0, "auc")] == results[(1, "auc")]
    assert 0.0 <= results[(0, "auc")] <= 1.0
    assert results[(0, "mae")] == results[(1, "mae")] == 0.4  # 4/10


def test_strategy_prototxt_single_checkpoint_stays_list(tmp_path):
    """A repeated field with ONE occurrence must parse back to a list
    (code-review r4: a str checkpoint would be iterated per-char by
    RecomputeOptimizer), and unset fields keep their defaults."""
    from paddle_tpu.fleet import DistributedStrategy

    st = DistributedStrategy()
    st.recompute = True
    st.recompute_configs = {"checkpoints": ["fc_0.tmp_0"]}
    p = str(tmp_path / "one.prototxt")
    st.save_to_prototxt(p)
    st2 = DistributedStrategy().load_from_prototxt(p)
    assert st2.recompute_configs["checkpoints"] == ["fc_0.tmp_0"]
    # default round trip: empty checkpoints key survives via defaults
    p2 = str(tmp_path / "default.prototxt")
    DistributedStrategy().save_to_prototxt(p2)
    st3 = DistributedStrategy().load_from_prototxt(p2)
    assert st3.recompute_configs == {"checkpoints": []}
    # backslash-before-n in a string value survives the round trip
    st4 = DistributedStrategy()
    st4.amp_configs = {"custom": "dir\\name"}
    p3 = str(tmp_path / "esc.prototxt")
    st4.save_to_prototxt(p3)
    st5 = DistributedStrategy().load_from_prototxt(p3)
    assert st5.amp_configs["custom"] == "dir\\name"


def test_strategy_prototxt_legacy_list_not_double_wrapped(tmp_path):
    """Round-2/3 legacy files wrote lists as Python reprs; loading must
    not wrap them again (code-review r4: [['a']] broke recompute)."""
    from paddle_tpu.fleet import DistributedStrategy

    p = str(tmp_path / "legacy_list.prototxt")
    with open(p, "w") as f:
        f.write("recompute: True\n"
                "recompute_configs {\n"
                "  checkpoints: ['layer_1.out']\n"
                "}\n")
    st = DistributedStrategy().load_from_prototxt(p)
    assert st.recompute_configs["checkpoints"] == ["layer_1.out"]
