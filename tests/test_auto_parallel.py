"""DistributedStrategy.auto — the dp x tp GSPMD auto-parallel search.

Reference: `framework/distributed_strategy.proto:401` reserves the knob
(fleet 2.0 WIP, unimplemented there). This build implements it:
`parallel/auto_parallel.py` enumerates mesh factorizations, scores each
candidate with XLA's memory/cost analyses, and compiles the winner with
GSPMD in/out shardings (no collective-op rewrite)."""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu import fleet
from paddle_tpu.parallel import auto_parallel as ap


def _build_mlp(hidden=64, in_dim=32, batch=None):
    x = fluid.data(name="x", shape=[batch or -1, in_dim], dtype="float32")
    y = fluid.data(name="y", shape=[batch or -1, 1], dtype="float32")
    h = fluid.layers.fc(input=x, size=hidden, act="tanh")
    pred = fluid.layers.fc(input=h, size=1, act=None)
    loss = fluid.layers.reduce_mean(fluid.layers.square(pred - y))
    return x, y, loss


def _train(strategy, steps=8, batch=16, seed=7):
    rng = np.random.RandomState(0)
    xs = rng.randn(steps, batch, 32).astype(np.float32)
    w = rng.randn(32, 1).astype(np.float32)
    ys = np.tanh(xs @ w)

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        main.random_seed = startup.random_seed = seed
        _, _, loss = _build_mlp()
        opt = fluid.optimizer.SGD(learning_rate=0.1)
        if strategy is not None:
            fleet.init()
            opt = fleet.distributed_optimizer(opt, strategy)
        opt.minimize(loss)
    return _run(main, startup, xs, ys, steps, loss.name)


def _run(main, startup, xs, ys, steps, loss_name):
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    losses = []
    for i in range(steps):
        out, = exe.run(main, feed={"x": xs[i], "y": ys[i]},
                       fetch_list=[loss_name])
        losses.append(float(np.asarray(out).reshape(-1)[0]))
    return losses, main


def test_auto_strategy_trains_and_matches_single_device():
    st = fleet.DistributedStrategy()
    st.auto = True
    auto_losses, main = _train(st)
    ref_losses, _ = _train(None)
    assert auto_losses[-1] < auto_losses[0], auto_losses
    np.testing.assert_allclose(auto_losses, ref_losses, rtol=2e-4,
                               atol=2e-5)
    plan = getattr(main, "_auto_plan", None)
    assert plan is not None
    # small model, 8 devices: pure DP must win the search
    assert plan.dp == 8 and plan.tp == 1, plan.describe()
    assert plan.report, "search must record its candidates"


def test_auto_with_memory_budget_forces_tp():
    """A large fc weight + a per-device memory budget that pure DP
    cannot meet makes the search pick tp > 1 — and training still
    matches the unsharded run."""
    st = fleet.DistributedStrategy()
    st.auto = True
    # weight 512x1024 fp32 = 2 MB replicated; budget 1.5 MB/device
    # forces the trailing-axis split. min_shard_bytes lowered so the
    # test-sized weight qualifies.
    st.auto_configs = {"mem_budget_mb": 1.5, "min_shard_bytes": 1 << 18}

    def build_and_train(strategy):
        rng = np.random.RandomState(1)
        steps, batch = 6, 16
        xs = rng.randn(steps, batch, 512).astype(np.float32)
        ys = rng.randn(steps, batch, 1).astype(np.float32)
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            main.random_seed = startup.random_seed = 11
            x = fluid.data(name="x", shape=[-1, 512], dtype="float32")
            y = fluid.data(name="y", shape=[-1, 1], dtype="float32")
            h = fluid.layers.fc(input=x, size=1024, act="tanh")
            pred = fluid.layers.fc(input=h, size=1, act=None)
            loss = fluid.layers.reduce_mean(
                fluid.layers.square(pred - y))
            opt = fluid.optimizer.SGD(learning_rate=0.05)
            if strategy is not None:
                fleet.init()
                opt = fleet.distributed_optimizer(opt, strategy)
            opt.minimize(loss)
        return _run(main, startup, xs, ys, steps, loss.name)

    auto_losses, main = build_and_train(st)
    ref_losses, _ = build_and_train(None)
    plan = main._auto_plan
    assert plan.tp > 1, plan.describe()
    split = [n for n, s in plan.state_specs.items()
             if any(ax is not None for ax in s)]
    assert split, "the big fc weight must be tp-split"
    np.testing.assert_allclose(auto_losses, ref_losses, rtol=2e-4,
                               atol=2e-5)


def test_build_specs_rejects_indivisible_batch():
    feed = {"x": np.zeros((6, 4), np.float32)}
    assert ap.build_specs(feed, {}, set(), dp=4, tp=1) is None
    got = ap.build_specs(feed, {}, set(), dp=2, tp=1)
    assert got is not None
    fspecs, _ = got
    assert fspecs["x"] == __import__("jax").sharding.PartitionSpec("dp")


def test_factorizations_order_prefers_dp():
    assert ap._factorizations(8)[0] == (8, 1)
    assert (1, 8) in ap._factorizations(8)


def test_unsatisfiable_budget_raises_not_silently_overruns():
    """When no candidate fits mem_budget_mb the search must fail loudly
    — never hand back an over-budget plan that OOMs at runtime."""
    st = fleet.DistributedStrategy()
    st.auto = True
    # 104 bytes/device: unsatisfiable even for this tiny model
    st.auto_configs = {"mem_budget_mb": 0.0001}

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data(name="x", shape=[-1, 32], dtype="float32")
        y = fluid.data(name="y", shape=[-1, 1], dtype="float32")
        pred = fluid.layers.fc(input=x, size=1, act=None)
        loss = fluid.layers.reduce_mean(fluid.layers.square(pred - y))
        opt = fluid.optimizer.SGD(learning_rate=0.1)
        fleet.init()
        opt = fleet.distributed_optimizer(opt, st)
        opt.minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xs = np.zeros((8, 32), np.float32)
    ys = np.zeros((8, 1), np.float32)
    with pytest.raises(RuntimeError, match="no feasible plan"):
        exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss.name])


def test_nranks_beyond_devices_is_clamped():
    """auto_configs nranks larger than the host's device count must not
    crash the search with a reshape error."""
    st = fleet.DistributedStrategy()
    st.auto = True
    st.auto_configs = {"nranks": 64}
    auto_losses, main = _train(st)
    assert main._auto_plan.dp * main._auto_plan.tp <= 8
    assert auto_losses[-1] < auto_losses[0]
