"""Zero-downtime elasticity (distributed/preemption + Executor.
live_resize + serving.Engine.drain): preemption notices (SIGTERM /
RPC / fault-injected) consumed at step boundaries, the ElasticWorld
group-agreed live seam, the device-tier in-place mesh resize whose
post-seam trajectory is BIT-IDENTICAL to an elastic cold restart
restored from the same snapshot (ZeRO-1 / AMP-O2 / vocab-sharded
embedding state), dygraph fp32 masters sharding over the mesh, the
serving drain/migrate protocol, the degrade-to-cohort-restart
breadcrumbs, and the supervised 4 -> 3 acceptance runs (live seam +
fault-during-recovery degrade)."""
import json
import os
import signal
import subprocess as _sp
import sys

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.core.scope import Scope
from paddle_tpu.distributed import faults
from paddle_tpu.distributed import preemption as pre
from paddle_tpu.fluid import checkpoint as ckpt
from paddle_tpu.fluid import framework
from paddle_tpu.utils.flags import get_flag, set_flags

_DIR = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_DIR)


@pytest.fixture(autouse=True)
def _clean_preempt_state(monkeypatch):
    """Notices and the launch-rank pin are process-global by design
    (one process == one rank in production); tests must not leak them
    into each other."""
    pre.clear_notice()
    monkeypatch.delenv("PADDLE_LAUNCH_RANK", raising=False)
    yield
    pre.clear_notice()
    faults.reset()


@pytest.fixture
def _restore_flags():
    keys = ("FLAGS_tpu_sharded_weight_update", "FLAGS_tpu_comm_bucket_mb",
            "FLAGS_tpu_sparse_embedding", "FLAGS_tpu_telemetry_dir")
    old = {k: get_flag(k) for k in keys}
    yield
    set_flags(old)


# -- notice delivery ---------------------------------------------------------

def test_deliver_notice_first_wins():
    n1 = pre.deliver_notice(grace_s=7.5, source="rpc", rank=3)
    # a racing second notice must not shorten or extend the armed window
    n2 = pre.deliver_notice(grace_s=99.0, source="sigterm")
    assert n2 is n1
    got = pre.pending_notice()
    assert got is n1 and got.grace_s == 7.5 and got.source == "rpc"
    assert got.rank == 3
    assert 0.0 <= got.remaining_s() <= 7.5
    assert got.as_dict()["source"] == "rpc"
    pre.clear_notice()
    assert pre.pending_notice() is None


def test_default_grace_env(monkeypatch):
    monkeypatch.setenv("PADDLE_PREEMPT_GRACE_S", "12.5")
    assert pre.default_grace_s() == 12.5
    monkeypatch.setenv("PADDLE_PREEMPT_GRACE_S", "nonsense")
    assert pre.default_grace_s() == 30.0


def test_sigterm_is_a_notice_not_a_death():
    """The FIRST SIGTERM arms a pending notice and the process keeps
    running — the grace window belongs to the step loop, not to the
    signal handler."""
    assert pre.install_sigterm(grace_s=11.0)
    os.kill(os.getpid(), signal.SIGTERM)
    n = pre.pending_notice()
    assert n is not None, "SIGTERM must deliver a notice, not kill"
    assert n.source == "sigterm" and n.grace_s == 11.0
    # idempotent re-install
    assert pre.install_sigterm()


def test_preempt_fault_kind_warns_without_disrupting_the_op():
    """faults.py `preempt`: deterministic notice injection at rank R /
    event K — unlike `kill` the op itself proceeds untouched."""
    with faults.inject("preempt", side="client", point="send",
                       method="hc_put_part", at=2, grace_s=3.0):
        faults.on_message("client", "send", "hc_put_part")  # 1: miss
        assert pre.pending_notice() is None
        faults.on_message("client", "send", "hc_put_part")  # 2: fire
        n = pre.pending_notice()
        assert n is not None and n.source == "fault"
        assert n.grace_s == 3.0
        # `at=` fires exactly once; and the op was never disrupted
        pre.clear_notice()
        faults.on_message("client", "send", "hc_put_part")
        assert pre.pending_notice() is None
    specs = faults.parse_spec(
        "preempt:side=client,point=send,at=14,grace_s=2.5")
    assert specs[0].kind == "preempt" and specs[0].grace_s == 2.5


def test_preempt_marker_roundtrip(tmp_path, _restore_flags):
    set_flags({"FLAGS_tpu_telemetry_dir": str(tmp_path)})
    path = pre.write_preempt_marker(2, step=9, grace_s=30.0,
                                    source="fault",
                                    extra={"group_rank": 1})
    assert path and os.path.basename(path) == "preempted.rank2.json"
    (tmp_path / "preempted.rank7.json").write_text("{torn")  # skipped
    (tmp_path / "preempted.rank0.json").write_text(
        json.dumps({"rank": 0, "ts": 1.0}))
    marks = pre.read_preempt_markers(str(tmp_path))
    assert [m["rank"] for m in marks] == [0, 2]
    assert marks[1]["step"] == 9 and marks[1]["group_rank"] == 1
    # the launch supervisor's view: the same markers name the shrink
    from paddle_tpu.distributed import launch as launch_mod

    assert launch_mod._preempt_marker_ranks(str(tmp_path)) == [0, 2]
    assert pre.read_preempt_markers(str(tmp_path / "missing")) == []


# -- ElasticWorld seam protocol (fake group: single-process units) ----------

class _FakeGroup:
    def __init__(self, rank, world, fail_barrier=False):
        self.rank, self.world = rank, world
        self.barriers = 0
        self.left = self.shut = False
        self.fail_barrier = fail_barrier

    def barrier(self):
        self.barriers += 1
        if self.fail_barrier:
            raise RuntimeError("rank 2 heartbeat stale")

    def all_reduce(self, arr, op="sum"):
        return arr

    def peek(self, key):
        return None

    def leave(self):
        self.left = True

    def shutdown(self):
        self.shut = True


def test_elastic_world_sync_agrees_on_doomed_set():
    ew = pre.ElasticWorld(_FakeGroup(1, 3), ["h:1", "h:2", "h:3"])
    assert ew.sync() == []
    pre.deliver_notice(grace_s=5.0, source="rpc", rank=1)
    assert ew.sync() == [1]
    assert ew.rank == 1 and ew.world == 3
    with pytest.raises(ValueError, match="endpoints"):
        pre.ElasticWorld(_FakeGroup(0, 3), ["h:1"])


def test_elastic_world_doomed_seam(tmp_path, _restore_flags):
    """The doomed rank's half: marker first, snapshot, barrier, clean
    leave, role report — never a survivor rebuild."""
    set_flags({"FLAGS_tpu_telemetry_dir": str(tmp_path)})
    g = _FakeGroup(1, 3)
    ew = pre.ElasticWorld(g, ["h:1", "h:2", "h:3"])
    pre.deliver_notice(grace_s=9.0, source="fault", rank=1)
    snaps = []
    report = ew.resize([1], snapshot=snaps.append, step=7)
    assert report["role"] == "doomed"
    assert report["old_world"] == 3 and report["new_world"] == 2
    assert snaps == [[1]]
    assert g.barriers == 1 and g.left and not g.shut
    assert pre.pending_notice() is None  # consumed
    marks = pre.read_preempt_markers(str(tmp_path))
    assert len(marks) == 1 and marks[0]["rank"] == 1
    assert marks[0]["step"] == 7 and marks[0]["group_rank"] == 1


def test_elastic_world_resize_validation():
    ew = pre.ElasticWorld(_FakeGroup(0, 2), ["h:1", "h:2"])
    with pytest.raises(ValueError, match="empty"):
        ew.resize([])
    with pytest.raises(pre.LiveResizeError, match="all 2 ranks"):
        ew.resize([0, 1])


def test_elastic_world_seam_failure_degrades_loudly(tmp_path,
                                                   _restore_flags):
    """A fault inside the seam (here: the agreement barrier) raises
    LiveResizeError — the runner's cue to exit DEGRADE_RC — and the
    doomed rank's marker survives it, so the cohort restart still
    drops the right rank."""
    set_flags({"FLAGS_tpu_telemetry_dir": str(tmp_path)})
    g = _FakeGroup(1, 4, fail_barrier=True)
    ew = pre.ElasticWorld(g, ["h:%d" % i for i in range(4)])
    with pytest.raises(pre.LiveResizeError, match="degrade"):
        ew.resize([1], step=4)
    assert pre.DEGRADE_RC == 98
    marks = pre.read_preempt_markers(str(tmp_path))
    assert [m["rank"] for m in marks] == [1]


def test_launch_rank_pins_across_resizes(monkeypatch):
    """Preempt markers speak the SUPERVISOR's tid space: after a first
    seam moved this process to contiguous rank 1, a second notice must
    still be attributed to the original launch rank."""
    monkeypatch.setenv("PADDLE_LAUNCH_RANK", "2")
    ew = pre.ElasticWorld(_FakeGroup(1, 3), ["h:1", "h:2", "h:3"],
                          generation=1)
    assert ew.launch_rank == 2 and ew.rank == 1


def test_survivor_rank_reassignment():
    from paddle_tpu.reader.resharding import survivor_rank

    assert survivor_rank(0, [1]) == 0
    assert survivor_rank(3, [1]) == 2
    assert survivor_rank(1, [1]) == -1
    assert survivor_rank(5, [0, 3]) == 3
    # matches the launch supervisor's contiguous reassignment rule
    doomed = [1, 4]
    world = 6
    expect = {o: n for n, o in enumerate(
        r for r in range(world) if r not in doomed)}
    for r in range(world):
        assert survivor_rank(r, doomed) == expect.get(r, -1)


# -- device tier: Executor.live_resize in-place bit-identity ----------------
#
# The tentpole acceptance: train sharded on 4 devices, snapshot, resize
# the SAME program/scope/executor in place to N', keep training — the
# post-seam losses must be BIT-IDENTICAL to a cold N'-device program
# restored from the snapshot (the PR 6/PR 8 elastic-restart ground
# truth). N'=3 exercises genuinely different flat padding (31 -> 33).

def _shrink_batch():
    r = np.random.RandomState(0)
    return (r.rand(24, 16).astype("float32"),
            r.randint(0, 4, (24, 1)).astype("int64"))


def _build_dp(ndev, zero1, amp=False, bucket_mb=0.0):
    import jax
    from jax.sharding import Mesh

    set_flags({"FLAGS_tpu_sharded_weight_update": zero1,
               "FLAGS_tpu_comm_bucket_mb": bucket_mb})
    main, startup = fluid.Program(), fluid.Program()
    with framework.unique_name_guard(), \
            fluid.program_guard(main, startup):
        main.random_seed = startup.random_seed = 77
        img = fluid.layers.data(name="img", shape=[16],
                                dtype="float32")
        label = fluid.layers.data(name="label", shape=[1],
                                  dtype="int64")
        h = fluid.layers.fc(input=img, size=31, act="relu")
        logits = fluid.layers.fc(input=h, size=4)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        opt = fluid.optimizer.AdamOptimizer(learning_rate=0.01)
        if amp:
            from paddle_tpu.fluid.contrib import mixed_precision

            opt = mixed_precision.decorate(opt)
        opt.minimize(loss)
        fluid.CompiledProgram(main).with_data_parallel(
            loss_name=loss.name)
        main._mesh = Mesh(np.array(jax.devices()[:ndev]), ("dp",))
    return main, startup, loss.name


def _steps(exe, prog, loss_name, scope, n):
    x, y = _shrink_batch()
    return [float(np.asarray(exe.run(
        prog, feed={"img": x, "label": y}, fetch_list=[loss_name],
        scope=scope)[0]).mean()) for _ in range(n)]


@pytest.mark.parametrize("amp", [False, True], ids=["zero1", "amp_o2"])
@pytest.mark.parametrize("new_ndev", [3, 2])
def test_live_resize_bit_identical_to_cold_restart(tmp_path,
                                                   _restore_flags,
                                                   amp, new_ndev):
    bucket_mb = 0.0 if amp else 0.25
    root = str(tmp_path / "seam")
    prog, st, ln = _build_dp(4, True, amp=amp, bucket_mb=bucket_mb)
    scope = Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(st, scope=scope)
    _steps(exe, prog, ln, scope, 2)
    ckpt.save_checkpoint(exe, root,
                         ckpt.TrainStatus(epoch_no=0, step_no=1),
                         main_program=prog, scope=scope)

    report = exe.live_resize(prog, ndev=new_ndev, scope=scope)
    assert report["old_world"] == 4
    assert report["new_world"] == new_ndev
    assert report["n_state"] > 0, \
        "sharded moments/masters must re-shard through the seam"
    assert report["n_evicted"] >= 1, "old-mesh executables must evict"
    post = _steps(exe, prog, ln, scope, 3)

    # cold restart reference: fresh N'-device program restored from
    # the pre-seam checkpoint (the PR 6 elastic path)
    p2, st2, ln2 = _build_dp(new_ndev, True, amp=amp,
                             bucket_mb=bucket_mb)
    sc2 = Scope()
    exe2 = fluid.Executor(fluid.CPUPlace())
    exe2.run(st2, scope=sc2)
    assert ckpt.load_checkpoint(exe2, root, main_program=p2,
                                scope=sc2) is not None
    ref = _steps(exe2, p2, ln2, sc2, 3)
    np.testing.assert_array_equal(
        np.asarray(post), np.asarray(ref),
        err_msg="live 4->%d seam not bit-identical to cold restart"
        % new_ndev)
    # the plan re-planned in place for N'
    plan = getattr(prog, "_shard_plan", None)
    if new_ndev > 1:
        assert plan is not None and plan.ndev == new_ndev
        if new_ndev == 3:
            assert any(info.numel == 31 and info.padded == 33
                       for info in plan.sharded_state.values())


def test_live_resize_requires_mesh_or_ndev(_restore_flags):
    prog, st, _ = _build_dp(4, True)
    exe = fluid.Executor(fluid.CPUPlace())
    with pytest.raises(ValueError, match="mesh= or ndev="):
        exe.live_resize(prog)


# -- device tier: vocab-sharded embedding state through the seam ------------

VOCAB, DIM = 37, 8


def _build_sparse():
    framework.default_main_program().random_seed = 7
    framework.default_startup_program().random_seed = 7
    ids = fluid.layers.data(name="ids", shape=[1], dtype="int64")
    dense = fluid.layers.data(name="dense", shape=[4],
                              dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    emb = fluid.layers.embedding(
        ids, size=[VOCAB, DIM], is_sparse=True, padding_idx=0,
        param_attr=fluid.ParamAttr(name="emb_w"))
    h = fluid.layers.concat([emb, dense], axis=1)
    h = fluid.layers.fc(input=h, size=16, act="relu")
    logits = fluid.layers.fc(input=h, size=2)
    loss = fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(logits, label))
    fluid.optimizer.AdagradOptimizer(learning_rate=0.1).minimize(loss)
    return loss


def _sparse_feed():
    r = np.random.RandomState(0)
    b = 48  # divisible by 4 and 3; covers most of the 37-row vocab
    return {"ids": r.randint(0, VOCAB, (b, 1)).astype("int64"),
            "dense": r.rand(b, 4).astype("float32"),
            "label": r.randint(0, 2, (b, 1)).astype("int64")}


def test_live_resize_embedding_tables_reshard_in_place(_restore_flags):
    """The PR 15 row-sharded tables (and their per-row moments) ride
    the same seam: unshard to logical (padded rows stripped), swap the
    mesh, re-plan at N' row padding — bit-identical to a cold N'
    engine seeded from the same logical snapshot."""
    import jax
    from jax.sharding import Mesh

    from paddle_tpu.core import scope as scope_mod
    from paddle_tpu.parallel.sharded_update import unshard_scope_value

    feed = _sparse_feed()
    set_flags({"FLAGS_tpu_sparse_embedding": True,
               "FLAGS_tpu_comm_bucket_mb": 0.0})
    with framework.unique_name_guard():
        loss = _build_sparse()
        prog = fluid.default_main_program()
        fluid.CompiledProgram(prog).with_data_parallel(
            loss_name=loss.name)
        prog._mesh = Mesh(np.array(jax.devices()[:4]), ("dp",))
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        for _ in range(2):
            exe.run(prog, feed=feed, fetch_list=[loss])
        # logical snapshot for the reference BEFORE the seam
        sc = scope_mod._global_scope
        snap = {n: np.asarray(unshard_scope_value(
            prog, n, sc.find_var(n))).copy()
            for n in sorted(sc.local_var_names())
            if sc.find_var(n) is not None}
        assert getattr(prog, "_sparse_plan", None) is not None
        assert prog._sparse_plan.tables["emb_w"].info.padded_rows == 40

        rep = exe.live_resize(prog, ndev=3)
        assert rep["new_world"] == 3
        post = [float(exe.run(prog, feed=feed,
                              fetch_list=[loss])[0].mean())
                for _ in range(3)]
        # re-planned row padding: 37 -> 39 at N'=3 (was 40 at 4)
        assert prog._sparse_plan.tables["emb_w"].info.padded_rows == 39

    # cold N'=3 reference from the logical snapshot
    framework.switch_main_program(framework.Program())
    framework.switch_startup_program(framework.Program())
    scope_mod._global_scope = scope_mod.Scope()
    with framework.unique_name_guard():
        loss = _build_sparse()
        p3 = fluid.default_main_program()
        fluid.CompiledProgram(p3).with_data_parallel(
            loss_name=loss.name)
        p3._mesh = Mesh(np.array(jax.devices()[:3]), ("dp",))
        exe3 = fluid.Executor(fluid.CPUPlace())
        exe3.run(fluid.default_startup_program())
        sc = scope_mod._global_scope
        for n, v in snap.items():
            if sc.find_var(n) is not None:
                sc.set_var(n, v.copy())
        ref = [float(exe3.run(p3, feed=feed,
                              fetch_list=[loss])[0].mean())
               for _ in range(3)]
    assert post == ref, "embedding live seam not bit-identical"


# -- dygraph: fp32 masters shard over the mesh ------------------------------

def test_eager_master_weights_shard_over_mesh(_restore_flags):
    """EagerMasterWeightOptimizer masters take the same P(ici) dim-0
    layout as the eager accumulators (divisibility-gated): memory off
    every replica, update partitioned by XLA — trajectory equal to the
    replicated masters (one transient bf16-ulp loss wobble allowed:
    the PR 4 CPU-fusion caveat; the MASTERS themselves must match
    exactly)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from paddle_tpu.fluid import optimizer as O
    from paddle_tpu.fluid.dygraph import Linear
    from paddle_tpu.hapi.model import Model
    from paddle_tpu.parallel import env as penv

    def train(mesh):
        set_flags({"FLAGS_tpu_sharded_weight_update": True})
        penv.set_global_mesh(mesh)
        try:
            r = np.random.RandomState(3)
            x = r.rand(64, 16).astype("float32")
            y = r.randint(0, 4, (64, 1)).astype("int64")
            net = Linear(16, 4)
            m = Model(net)
            m.prepare(
                O.SGDOptimizer(learning_rate=0.5,
                               parameter_list=net.parameters()),
                loss_function=lambda pred, label: fluid.layers.mean(
                    fluid.layers.softmax_with_cross_entropy(pred,
                                                            label)),
                amp_level="O2")
            rs = np.random.RandomState(5)  # identical init both runs
            for p in net.parameters():
                p._assign_raw(jnp.asarray(
                    rs.rand(*p.shape).astype("float32")
                ).astype(jnp.bfloat16))
            losses = [float(m.train_batch([x], [y])[0][0])
                      for _ in range(6)]
            masters = [np.asarray(m._optimizer._masters[p.name],
                                  np.float32).copy()
                       for p in net.parameters()]
            shards = [m._optimizer._masters[p.name].sharding
                      for p in net.parameters()]
            return losses, masters, shards
        finally:
            penv.set_global_mesh(None)

    mesh = Mesh(np.array(jax.devices()[:4]), ("ici",))
    l_sh, m_sh, shards = train(mesh)
    l_rep, m_rep, _ = train(None)
    # (16, 4) weight and (4,) bias both divide by 4: sharded dim 0
    assert all(not s.is_fully_replicated for s in shards), shards
    np.testing.assert_allclose(l_sh, l_rep, rtol=1e-5)
    for a, b in zip(m_sh, m_rep):
        np.testing.assert_array_equal(a, b)
    # divisibility gate: an indivisible dim 0 stays replicated
    from paddle_tpu.parallel.sharded_update import \
        eager_accumulator_sharding

    penv.set_global_mesh(mesh)
    try:
        set_flags({"FLAGS_tpu_sharded_weight_update": True})
        assert eager_accumulator_sharding((16, 4)) is not None
        assert eager_accumulator_sharding((31, 4)) is None
        set_flags({"FLAGS_tpu_sharded_weight_update": False})
        assert eager_accumulator_sharding((16, 4)) is None
    finally:
        penv.set_global_mesh(None)


# -- serving: drain on preemption notice ------------------------------------

from paddle_tpu import observability as obs  # noqa: E402
from paddle_tpu import serving  # noqa: E402

_MODEL_CFG = serving.TinyLMConfig(vocab=48, embed=24, layers=2,
                                  heads=2, kv_heads=2, head_dim=8,
                                  ffn=48, max_seq=48)
_MODEL = None
_PARAMS = None


def _engine(**over):
    global _MODEL, _PARAMS
    if _MODEL is None:
        _MODEL = serving.TinyDecoderLM(_MODEL_CFG)
        _PARAMS = _MODEL.init_params(seed=3)
    cfg = dict(num_pages=96, page_size=4, max_seqs=6)
    cfg.update(over)
    return serving.Engine(_MODEL, params=_PARAMS,
                          config=serving.EngineConfig(**cfg))


@pytest.fixture
def _fresh_registry():
    obs.reset_registry()
    yield
    obs.reset_registry()


def test_drain_completes_in_flight_within_grace(_fresh_registry):
    """A generous grace window: every in-flight request finishes on
    THIS engine (token streams untouched), nothing migrates, and
    admission stays closed for the doomed engine's remaining life."""
    r = np.random.RandomState(0)
    prompts = [r.randint(0, 48, size=n).astype(np.int32)
               for n in (5, 9, 3)]
    refs = []
    for p in prompts:
        e = _engine()
        q = e.submit(p, max_new_tokens=6)
        e.run_until_idle()
        refs.append(list(q.output_tokens))

    eng = _engine()
    reqs = [eng.submit(p, max_new_tokens=6) for p in prompts]
    eng.step()  # mid-flight when the notice lands
    rep = eng.drain(grace_s=60.0)
    assert rep["completed"] == 3 and rep["migrated"] == []
    assert [list(q.output_tokens) for q in reqs] == refs
    assert all(q.state == serving.RequestState.FINISHED for q in reqs)
    with pytest.raises(RuntimeError, match="drain"):
        eng.submit(prompts[0], max_new_tokens=2)
    snap = obs.registry().snapshot()["counters"]
    assert snap["event.serving_drain"] == 1


def test_drain_migrates_unfinished_and_adopt_is_bit_identical(
        _fresh_registry):
    """Grace too short to finish: the drain exports continuation
    manifests (prompt + already-generated tokens, remaining budget)
    and cancels locally; a survivor engine adopt()s them and the
    stitched streams equal the uninterrupted reference EXACTLY —
    migrate-by-re-prefill under greedy decoding is lossless."""
    r = np.random.RandomState(1)
    prompts = [r.randint(0, 48, size=n).astype(np.int32)
               for n in (7, 4, 11)]
    maxnew = [10, 8, 12]
    refs = []
    for p, m in zip(prompts, maxnew):
        e = _engine()
        q = e.submit(p, max_new_tokens=m)
        e.run_until_idle()
        refs.append(list(q.output_tokens))

    eng = _engine()
    reqs = [eng.submit(p, max_new_tokens=m)
            for p, m in zip(prompts, maxnew)]
    for _ in range(4):
        eng.step()  # partial progress, then the notice
    rep = eng.drain(grace_s=0.0)
    assert rep["completed"] + len(rep["migrated"]) == len(reqs)
    assert rep["migrated"], "grace 0 must migrate the unfinished"
    for q in reqs:
        assert q.state in (serving.RequestState.FINISHED,
                           serving.RequestState.CANCELLED)

    survivor = _engine()
    adopted = survivor.adopt(rep["migrated"])
    survivor.run_until_idle()
    for entry, cont in zip(rep["migrated"], adopted):
        # stitch: tokens the doomed engine already emitted + the
        # survivor's continuation == the uninterrupted stream
        orig = next(q for q, p in zip(reqs, prompts)
                    if entry["prompt"] == [int(t) for t in p]
                    + [int(t) for t in q.output_tokens])
        i = reqs.index(orig)
        assert entry["already_emitted"] == len(orig.output_tokens)
        stitched = list(orig.output_tokens) + list(cont.output_tokens)
        assert stitched == refs[i], \
            "migrated stream differs from uninterrupted reference"
    snap = obs.registry().snapshot()["counters"]
    assert snap["event.serving_drain"] == 1


# -- telemetry contracts ----------------------------------------------------

def test_new_event_shapes_validate_against_schema():
    from paddle_tpu.observability import schema as tschema

    sch = tschema.load_schema()
    env = {"kind": "event", "rank": 0, "step": 4, "ts": 1.0}
    ok = [
        dict(env, event="preempt_notice", grace_s=30.0,
             source="sigterm"),
        dict(env, event="live_resize", old_world=4, new_world=3,
             coordination_s=0.4, mode="live", status="ok",
             generation=1, notice_s=0.01, snapshot_s=0.1,
             rebuild_s=0.3),
        dict(env, event="live_resize", old_world=4, new_world=3,
             coordination_s=4.0, mode="live", status="degraded",
             error="RpcRemoteError('...')"),
        dict(env, event="serving_drain", completed=3, migrated=2,
             grace_s=30.0, dur_ms=12.5),
        dict(env, event="elastic_transition", old_world=4, new_world=3,
             mode="live", coordination_s=0.4),
        dict(env, event="elastic_transition", old_world=4, new_world=3,
             mode="restart", degraded_from_live=True, recovery_s=2.0),
    ]
    for rec in ok:
        assert tschema.validate_record(rec, sch) == [], rec
    bad = [
        dict(env, event="preempt_notice", source="rpc"),   # no grace_s
        dict(env, event="live_resize", old_world=4,
             new_world=3),                         # no coordination_s
        dict(env, event="serving_drain", completed=1),     # no migrated
    ]
    for rec in bad:
        assert tschema.validate_record(rec, sch), rec


def test_perf_analysis_elastic_reports_live_seams(tmp_path):
    """--elastic picks worker-emitted live seams out of the per-rank
    telemetry streams (deduped across survivors) alongside the
    supervisor's restart transitions."""
    tdir = tmp_path / "logs" / "telemetry"
    tdir.mkdir(parents=True)
    seam = {"kind": "event", "event": "live_resize", "rank": 0,
            "step": 6, "ts": 2.0, "old_world": 4, "new_world": 3,
            "mode": "live", "status": "ok", "generation": 1,
            "notice_s": 0.01, "snapshot_s": 0.05, "rebuild_s": 0.4,
            "coordination_s": 0.46}
    trans = dict(seam, event="elastic_transition")
    for rank in (0, 2):
        with open(str(tdir / ("telemetry.rank%d.jsonl" % rank)),
                  "w") as f:
            f.write(json.dumps(dict(seam, rank=rank)) + "\n")
            f.write(json.dumps(dict(trans, rank=rank)) + "\n")
    proc = _sp.run(
        [sys.executable, os.path.join(_REPO, "tools",
                                      "perf_analysis.py"),
         "--elastic", "--log-dir", str(tmp_path / "logs")],
        stdout=_sp.PIPE, stderr=_sp.STDOUT, text=True, timeout=120,
        cwd=_REPO)
    assert proc.returncode == 0, proc.stdout
    assert "live seam: world 4 -> 3 (ok)" in proc.stdout, proc.stdout
    assert proc.stdout.count("live seam:") == 1, \
        "survivor duplicates must dedup"
    assert "notice 0.010s" in proc.stdout
    assert "rebuild 0.400s" in proc.stdout


# -- supervised acceptance: live 4 -> 3, and degrade-to-restart -------------

def _launch_env():
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("PALLAS_AXON", "AXON_", "TPU_"))}
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env.pop("PADDLE_FAULTS", None)
    return env


def _loss_map(text):
    out = {}
    for ln in text.splitlines():
        if ln.startswith("LOSS"):
            out[int(ln.split()[1])] = float(ln.split()[2])
    return out


@pytest.mark.slow
@pytest.mark.faults
@pytest.mark.dist
def test_supervised_live_resize_4_to_3_bit_identical(tmp_path):
    """Acceptance: rank 1 of a supervised 4-rank cohort receives a
    fault-injected preemption notice mid-step-4; the cohort executes
    the LIVE seam — checkpoint-on-signal, doomed rank exits 0 inside
    its grace window, survivors rebuild in place and keep training at
    world 3 — with NO supervisor restart, and the post-seam losses are
    BIT-IDENTICAL to an uninterrupted 3-rank run restored from the
    seam snapshot. The seam's coordination wall time must beat the
    PR 9 restart baseline (process teardown + respawn + rendezvous:
    multiple seconds) by construction — asserted < 5s."""
    import shutil as _shutil

    runner = os.path.join(_DIR, "live_resize_runner.py")
    root = str(tmp_path / "ckpt")
    log_dir = str(tmp_path / "logs")
    hosts = ",".join("127.0.0.1:%d" % p
                     for p in (6851, 6853, 6855, 6857))
    # rank 1's 14th hc_put_part send = step 4's allreduce (1 startup
    # agreement + 3 per step: allreduce, lockstep barrier, sync)
    proc = _sp.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--hosts", hosts, "--log_dir", log_dir,
         "--max_restarts", "1", "--min_ranks", "3",
         runner, root, "8", "2", "1", "14"],
        env=_launch_env(), cwd=_REPO, stdout=_sp.PIPE,
        stderr=_sp.STDOUT, text=True, timeout=600)
    assert proc.returncode == 0, proc.stdout
    # zero downtime: the supervisor never saw a failure, no restart
    assert "restart 1/" not in proc.stdout, proc.stdout
    assert "elastic shrink" not in proc.stdout, proc.stdout

    log0 = open(os.path.join(log_dir, "workerlog.0")).read()
    log1 = open(os.path.join(log_dir, "workerlog.1")).read()
    assert "RESIZED step=4 world=3 rank=0" in log0, log0
    assert "PREEMPTED rank=1 step=4" in log1, log1
    got = _loss_map(log0)
    assert sorted(got) == list(range(8)), log0

    # uninterrupted 3-rank reference restored from the SEAM snapshot
    # (the checkpoint-on-signal save at step 4)
    ref_root = str(tmp_path / "ref_ckpt")
    os.makedirs(ref_root)
    for name in os.listdir(root):
        d = os.path.join(root, name)
        if not os.path.isdir(d):
            continue
        try:
            if ckpt.read_status(d).step_no <= 4:
                _shutil.copytree(d, os.path.join(ref_root, name))
        except OSError:
            continue
    ref_logs = str(tmp_path / "ref_logs")
    ref_hosts = ",".join("127.0.0.1:%d" % p
                         for p in (6861, 6863, 6865))
    ref = _sp.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--hosts", ref_hosts, "--log_dir", ref_logs,
         runner, ref_root, "8", "2"],
        env=_launch_env(), cwd=_REPO, stdout=_sp.PIPE,
        stderr=_sp.STDOUT, text=True, timeout=600)
    assert ref.returncode == 0, ref.stdout
    ref_log0 = open(os.path.join(ref_logs, "workerlog.0")).read()
    assert "RESUME 5 world=3 rank=0" in ref_log0, ref_log0
    ref_losses = _loss_map(ref_log0)
    assert sorted(ref_losses) == [5, 6, 7], ref_log0
    for step in (5, 6, 7):
        assert got[step] == ref_losses[step], (
            "step %d not bit-identical: live %.17g vs 3-rank ref "
            "%.17g" % (step, got[step], ref_losses[step]))

    # the seam is observable: worker-emitted live_resize, schema-valid,
    # with sub-restart coordination time; perf_analysis renders it
    from paddle_tpu.observability import schema as tschema

    sch = tschema.load_schema()
    seams = []
    tdir = os.path.join(log_dir, "telemetry")
    for fname in sorted(os.listdir(tdir)):
        if not fname.startswith("telemetry.rank"):
            continue
        for line in open(os.path.join(tdir, fname)):
            if not line.strip():
                continue
            rec = json.loads(line)
            if rec.get("event") == "live_resize":
                assert tschema.validate_record(rec, sch) == [], rec
                seams.append(rec)
    assert len(seams) == 3, seams  # one per survivor
    for s in seams:
        assert s["old_world"] == 4 and s["new_world"] == 3
        assert s["status"] == "ok" and s["generation"] == 1
        assert 0.0 < s["coordination_s"] < 5.0, s
    pa = _sp.run(
        [sys.executable, os.path.join(_REPO, "tools",
                                      "perf_analysis.py"),
         "--elastic", "--log-dir", log_dir],
        env=_launch_env(), cwd=_REPO, stdout=_sp.PIPE,
        stderr=_sp.STDOUT, text=True, timeout=240)
    assert pa.returncode == 0, pa.stdout
    assert "live seam: world 4 -> 3 (ok)" in pa.stdout, pa.stdout


@pytest.mark.slow
@pytest.mark.faults
@pytest.mark.dist
def test_supervised_live_seam_fault_degrades_to_cohort_restart(
        tmp_path):
    """Fault DURING recovery: a second machine dies silently (kill
    exit_code=0 — no crash rc, no marker) inside the seam's agreement
    barrier. The survivors' rebuild fails FAST on the stale heartbeat
    (never a hang), every survivor exits DEGRADE_RC, and the
    supervisor falls back to the PR 9 cohort restart — shrinking by
    the preempt MARKER (the doomed rank exited 0 too) and stamping the
    transition degraded_from_live."""
    runner = os.path.join(_DIR, "live_resize_runner.py")
    root = str(tmp_path / "ckpt")
    log_dir = str(tmp_path / "logs")
    hosts = ",".join("127.0.0.1:%d" % p
                     for p in (6871, 6873, 6875, 6877))
    # preempt rank 1 at step 4 (event 14); rank 2's 17th send is its
    # SEAM barrier contribution (16 = startup + 5 steps x 3) — it dies
    # there, silently
    proc = _sp.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--hosts", hosts, "--log_dir", log_dir,
         "--max_restarts", "1", "--min_ranks", "3",
         runner, root, "8", "2", "1", "14", "2", "17"],
        env=_launch_env(), cwd=_REPO, stdout=_sp.PIPE,
        stderr=_sp.STDOUT, text=True, timeout=600)
    assert proc.returncode == 0, proc.stdout
    assert "live-resize degrade" in proc.stdout, proc.stdout
    assert "preempt marker(s) for rank(s) [1]" in proc.stdout
    assert "elastic shrink 4 -> 3" in proc.stdout, proc.stdout

    log0 = open(os.path.join(log_dir, "workerlog.0")).read()
    assert "DEGRADE step=4" in log0, log0
    # the restarted 3-rank cohort resumed from the seam snapshot and
    # finished the job
    got = _loss_map(log0)
    assert sorted(got) == list(range(8)), log0

    sup = os.path.join(log_dir, "telemetry",
                       "telemetry.supervisor.jsonl")
    evs = [json.loads(ln) for ln in open(sup) if ln.strip()]
    evs = [r for r in evs if r.get("event") == "elastic_transition"]
    assert len(evs) == 1, evs
    ev = evs[0]
    assert ev["old_world"] == 4 and ev["new_world"] == 3
    assert ev["mode"] == "restart"
    assert ev["degraded_from_live"] is True
    assert ev["preempted_ranks"] == [1]
    assert ev["failed_ranks"] == [1]
    from paddle_tpu.observability import schema as tschema

    assert tschema.validate_record(ev, tschema.load_schema()) == []
    # perf_analysis shows BOTH halves of the story: the degraded live
    # seam (from the postmortem bundle) and the restart it fell back to
    pa = _sp.run(
        [sys.executable, os.path.join(_REPO, "tools",
                                      "perf_analysis.py"),
         "--elastic", "--log-dir", log_dir],
        env=_launch_env(), cwd=_REPO, stdout=_sp.PIPE,
        stderr=_sp.STDOUT, text=True, timeout=240)
    assert pa.returncode == 0, pa.stdout
    assert "degraded from live seam" in pa.stdout, pa.stdout
    assert "(degraded)" in pa.stdout, pa.stdout
